"""TPU-native batched BLS signature-set verification.

This is the device half of the north-star seam: the reference's
``verify_signature_sets`` (``/root/reference/crypto/bls/src/impls/blst.rs:36-119``)
re-designed as one fixed-shape, branch-free JAX program:

    per set i (batch lane i):
      agg_pk_i = sum of the set's pubkeys          (masked Jacobian sum)
      sig subgroup check: psi(sig) == [x] sig      (64-bit scan)
      r_i agg_pk_i, r_i sig_i                      (64-bit random scalars)
    sig_acc = sum_i r_i sig_i                      (scan reduction)
    ok = FE( prod_i ML(r_i agg_pk_i, H(m_i)) * ML(-g1, sig_acc) ) == 1
         AND all subgroup checks

The batch dimension is the data-parallel axis the reference spreads over
rayon cores (``block_signature_verifier.rs:374-382``); here it is the
device batch axis, shardable over chips via ``jax.sharding`` (see
``__graft_entry__.dryrun_multichip`` for the dp x tp mesh layout).

Shapes (B sets, K max pubkeys/set):
  pk_xy  int32[B, K, 2, 32]   pk_mask bool[B, K]
  sig_xy int32[B, 2, 2, 32]   (x, y) each Fp2
  msg_xy int32[B, 2, 2, 32]   H(m) on G2 (hash-to-curve)
  rand   int32[B, 64]          MSB-first nonzero 64-bit scalars
  set_mask bool[B]             False = padding lane (must not affect result)

Host-side padding/bucketing, randomness, and the reference's edge
semantics (empty batch / empty set / infinity signature => False) live in
:class:`TpuBackend` below.
"""

from __future__ import annotations

import secrets
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from ...utils import (
    fault_injection,
    flight_recorder,
    metrics,
    pipeline_profiler,
    tracing,
    transfer_ledger,
)
from ..params import DST, G1_X, G1_Y, P, R, X
from ..cpu.pairing import PSI_CX, PSI_CY
from ..cpu.hash_to_curve import hash_to_g2
from . import curve, fp, fp2, msm as msm_mod, pairing, tower
from .pairing import X_ABS

# psi constants (public, derived from xi; see cpu/pairing.py:22-27).
_PSI_CX = (PSI_CX.c0.n, PSI_CX.c1.n)
_PSI_CY = (PSI_CY.c0.n, PSI_CY.c1.n)

# -g1 generator, embedded as constants.
_NEG_G1 = (G1_X, (P - G1_Y) % P)


def _psi_jacobian(pt):
    """Untwist-Frobenius-twist endomorphism in Jacobian coords:
    (X, Y, Z) -> (conj(X) CX, conj(Y) CY, conj(Z))."""
    x, y, z = pt
    return (
        fp2.mul(fp2.conjugate(x), fp2.const(*_PSI_CX)),
        fp2.mul(fp2.conjugate(y), fp2.const(*_PSI_CY)),
        fp2.conjugate(z),
    )


def g2_in_subgroup(pt):
    """Scott's membership test for G2 on BLS12-381: Q in G2 iff
    psi(Q) == [x]Q (eigenvalue x of psi on the r-torsion; verified against
    the full [r]Q == O check in tests). Infinity passes."""
    xq = curve.scalar_mul_const(fp2, pt, X_ABS)
    xq = curve.neg(fp2, xq)  # x < 0
    return curve.eq(fp2, _psi_jacobian(pt), xq) | curve.is_infinity(fp2, pt)


def _bits64(r):
    """int32[..., 2] (hi, lo) -> MSB-first bits int32[..., 64]."""
    hi, lo = r[..., 0], r[..., 1]
    shifts = jnp.arange(31, -1, -1, dtype=jnp.int32)
    hb = (hi[..., None] >> shifts) & 1
    lb = (lo[..., None] >> shifts) & 1
    return jnp.concatenate([hb, lb], axis=-1)


_XBITS64 = np.array(
    [(X_ABS >> (63 - i)) & 1 for i in range(64)], np.int32
)
assert X_ABS.bit_length() == 64


def _verify_core(pk_xy, pk_mask, sig_xy, msg_aff, rand_bits, set_mask):
    """Shared verification core; ``msg_aff = (x, y, inf)`` are the hashed
    messages in G2 affine, one per lane."""
    B = pk_xy.shape[0]

    # --- aggregate pubkeys per set (masked sum over the K axis) ---------
    pk_pts = curve.from_affine(
        fp, pk_xy[..., 0, :], pk_xy[..., 1, :], ~pk_mask
    )
    agg_pk = curve.sum_points(fp, pk_pts, axis=1)  # [B] G1 Jacobian

    # --- signatures: subgroup check + random scaling --------------------
    # The subgroup check's [x]Q and the randomizer's [r]Q run through ONE
    # emitted double-and-add body: stack the points [2B] with per-lane
    # bit rows (constant |x| bits for the first half) — compile-size
    # lever (one scan body instead of two).
    sig_pts = curve.from_affine(fp2, sig_xy[..., 0, :, :], sig_xy[..., 1, :, :])
    bits = _bits64(rand_bits) if rand_bits.shape[-1] == 2 else rand_bits
    xbits = jnp.broadcast_to(jnp.asarray(_XBITS64), (B, 64))
    both = curve.scalar_mul_bits(
        fp2,
        tuple(jnp.concatenate([c, c], axis=0) for c in sig_pts),
        jnp.concatenate([xbits, bits], axis=0),
    )
    xq = tuple(c[:B] for c in both)                      # [|x|]Q
    r_sig = tuple(c[B:] for c in both)                   # [r]Q
    xq = curve.neg(fp2, xq)                              # x < 0
    sub_ok = (
        curve.eq(fp2, _psi_jacobian(sig_pts), xq)
        | curve.is_infinity(fp2, sig_pts)
        | ~set_mask
    )
    subgroup_ok = jnp.all(sub_ok)

    r_pk = curve.scalar_mul_bits(fp, agg_pk, bits)       # [B] G1

    # padding lanes must not contribute to the signature accumulator
    inf2 = curve.infinity(fp2)
    r_sig = curve.select(
        fp2, set_mask, r_sig,
        tuple(jnp.broadcast_to(c, o.shape) for c, o in zip(inf2, r_sig)),
    )
    sig_acc = curve.sum_points(fp2, r_sig, axis=0)       # single G2

    # --- assemble the multi-pairing: B lanes + the accumulator lane -----
    pk_x, pk_y, pk_inf = curve.to_affine(fp, r_pk)
    # padding lanes: force G1 point to infinity => Miller value 1
    pk_inf = pk_inf | ~set_mask

    g1_x = jnp.concatenate([pk_x, fp.const(_NEG_G1[0])[None]], axis=0)
    g1_y = jnp.concatenate([pk_y, fp.const(_NEG_G1[1])[None]], axis=0)
    g1_inf = jnp.concatenate([pk_inf, jnp.zeros((1,), bool)], axis=0)

    msg_x, msg_y, msg_inf = msg_aff
    acc_x, acc_y, acc_inf = curve.to_affine(fp2, sig_acc)
    g2_x = jnp.concatenate([msg_x, acc_x[None]], axis=0)
    g2_y = jnp.concatenate([msg_y, acc_y[None]], axis=0)
    g2_inf = jnp.concatenate([msg_inf, acc_inf[None]], axis=0)

    pairing_ok = pairing.multi_pairing_is_one(
        (g1_x, g1_y, g1_inf), (g2_x, g2_y, g2_inf)
    )

    # a real lane whose aggregate pubkey degenerated to infinity (e.g. sum
    # of pubkeys cancels) must fail rather than silently contribute 1
    agg_inf_bad = jnp.any(curve.is_infinity(fp, agg_pk) & set_mask)

    return pairing_ok & subgroup_ok & ~agg_inf_bad


def _fp_gt(a_digits, b_digits):
    """Strict canonical digits [..., NL] -> a > b (big-endian lexicographic:
    the most significant differing limb decides)."""
    diff = a_digits != b_digits
    gt = a_digits > b_digits
    idx = jnp.arange(fp.NL, dtype=jnp.int32)
    msd = jnp.max(jnp.where(diff, idx + 1, 0), axis=-1)  # 0 == all equal
    pick = jnp.take_along_axis(
        gt, jnp.maximum(msd - 1, 0)[..., None], axis=-1
    )[..., 0]
    return (msd > 0) & pick


def _decompress_pre(sig_x):
    """g(x) = x^3 + 4(1+u) — the radicand awaiting a sqrt ladder."""
    b2 = jnp.broadcast_to(fp2.const(4, 4), sig_x.shape).astype(jnp.int32)
    return fp2.add(fp2.mul(fp2.sq(sig_x), sig_x), b2)


def _decompress_post(sign_larger, y, ok):
    """Sign selection by the compressed flag's lexicographic-larger rule;
    ``y, ok`` are the sqrt outputs for ``_decompress_pre``'s radicand."""
    yc = fp2.canonical(y)
    neg_y = fp2.neg(y)
    negc = fp2.canonical(neg_y)
    c1_gt = _fp_gt(yc[..., 1, :], negc[..., 1, :])
    c1_eq = jnp.all(yc[..., 1, :] == negc[..., 1, :], axis=-1)
    c0_gt = _fp_gt(yc[..., 0, :], negc[..., 0, :])
    y_is_larger = c1_gt | (c1_eq & c0_gt)
    y_final = fp2.select(y_is_larger == sign_larger, y, neg_y)
    return y_final, ok


def decompress_g2(sig_x, sign_larger):
    """Device G2 decompression (the ~10 ms/signature host cost the gossip
    pipeline used to pay in pure Python): y = sqrt(x^3 + 4(1+u)), sign
    chosen by the compressed flag's lexicographic-larger rule.

    sig_x: fp2 [..., 2, NL]; sign_larger: bool [...]. -> (y, ok) where
    ``ok`` is False for x not on the curve."""
    from . import htc

    y, ok = htc.sqrt(_decompress_pre(sig_x))
    return _decompress_post(sign_larger, y, ok)


def verify_batch_raw_fn(
    pk_xy, pk_mask, sig_x, sig_larger, msg_u, msg_idx, rand_bits, set_mask
):
    """THE flagship program: raw compressed signatures + raw
    hash_to_field outputs in, verdict out. The host does byte wrangling
    only; decompression, hashing-to-curve, aggregation, subgroup checks
    and the multi-pairing all run on device.

    The signature-decompression square root and the 4M SSWU candidate
    square roots share ONE ladder (stacked [B + 4M] batch) — the two
    f2pow scans are the largest repeated body in the program."""
    from . import htc

    B = sig_x.shape[0]
    M = msg_u.shape[0]

    gx_sig = _decompress_pre(sig_x)                    # [B, 2, NL]
    x1, x2, g = htc.sswu_pre(msg_u)                    # g [M, 2, 2, 2, NL]
    stacked = jnp.concatenate(
        [gx_sig, g.reshape(4 * M, 2, fp.NL)], axis=0
    )
    roots, root_ok = htc.sqrt(stacked)                 # ONE shared ladder
    y, sig_ok = _decompress_post(
        sig_larger, roots[:B], root_ok[:B]
    )
    sig_xy = jnp.stack([sig_x, y], axis=1)  # [B, 2(x|y), 2, NL]

    msg_pts = htc.map_to_g2_post(
        msg_u,
        x1,
        x2,
        roots[B:].reshape(M, 2, 2, 2, fp.NL),
        root_ok[B:].reshape(M, 2, 2),
    )
    mx, my, minf = curve.to_affine(fp2, msg_pts)
    msg_aff = (
        jnp.take(mx, msg_idx, axis=0),
        jnp.take(my, msg_idx, axis=0),
        jnp.take(minf, msg_idx, axis=0),
    )
    core = _verify_core(pk_xy, pk_mask, sig_xy, msg_aff, rand_bits, set_mask)
    return core & jnp.all(sig_ok | ~set_mask)


def verify_batch_fn(pk_xy, pk_mask, sig_xy, msg_xy, rand_bits, set_mask):
    """One-shot device program over pre-hashed message points. Returns a
    scalar bool: True iff every real lane's set verifies."""
    B = pk_xy.shape[0]
    msg_aff = (msg_xy[:, 0], msg_xy[:, 1], jnp.zeros((B,), bool))
    return _verify_core(pk_xy, pk_mask, sig_xy, msg_aff, rand_bits, set_mask)


def verify_batch_hashed_fn(pk_xy, pk_mask, sig_xy, msg_u, msg_idx, rand_bits, set_mask):
    """END-TO-END device program: raw hash_to_field outputs in, verdict
    out. ``msg_u`` int32[M, 2, 2, NL] holds the unique messages' field
    elements; ``msg_idx`` int32[B] maps each lane to its message — dedup
    mirrors the reference's per-distinct-AttestationData hashing, but the
    hashing itself is the batched device map (see ``device/htc.py``)."""
    from . import htc

    msg_pts = htc.map_to_g2(msg_u)                       # [M] Jacobian
    mx, my, minf = curve.to_affine(fp2, msg_pts)
    msg_aff = (
        jnp.take(mx, msg_idx, axis=0),
        jnp.take(my, msg_idx, axis=0),
        jnp.take(minf, msg_idx, axis=0),
    )
    return _verify_core(pk_xy, pk_mask, sig_xy, msg_aff, rand_bits, set_mask)


verify_batch = jax.jit(verify_batch_fn)
verify_batch_hashed = jax.jit(verify_batch_hashed_fn)
verify_batch_raw = jax.jit(verify_batch_raw_fn)


# ---------------------------------------------------------------------------
# Staged pipeline: the same program as verify_batch_raw_fn split into three
# independently-jitted stages. Identical results; intermediate arrays stay
# on device. Motivation is COMPILE time (VERDICT r4 item #1): XLA's cost is
# superlinear-ish in program size, so three ~30k-HLO-line programs compile
# in roughly half the wall-clock of one ~90k-line program, cache
# independently in the persistent compile cache, and let a shape bump in
# one stage (e.g. more unique messages M) recompile only that stage.
# ---------------------------------------------------------------------------

def _stage1_fn(sig_x, sig_larger, msg_u):
    """Decompression + hash-to-curve (all square roots in one ladder)."""
    from . import htc

    B = sig_x.shape[0]
    M = msg_u.shape[0]
    gx_sig = _decompress_pre(sig_x)
    x1, x2, g = htc.sswu_pre(msg_u)
    stacked = jnp.concatenate([gx_sig, g.reshape(4 * M, 2, fp.NL)], axis=0)
    roots, root_ok = htc.sqrt(stacked)
    y, sig_ok = _decompress_post(sig_larger, roots[:B], root_ok[:B])
    sig_xy = jnp.stack([sig_x, y], axis=1)
    msg_pts = htc.map_to_g2_post(
        msg_u,
        x1,
        x2,
        roots[B:].reshape(M, 2, 2, 2, fp.NL),
        root_ok[B:].reshape(M, 2, 2),
    )
    mx, my, minf = curve.to_affine(fp2, msg_pts)
    return sig_xy, mx, my, minf, sig_ok


def _stage2_fn(pk_xy, pk_mask, sig_xy, rand_bits, set_mask):
    """Aggregation + subgroup checks + random scaling -> affine pairing
    inputs for the G1 side and the G2 signature accumulator."""
    B = pk_xy.shape[0]
    pk_pts = curve.from_affine(fp, pk_xy[..., 0, :], pk_xy[..., 1, :], ~pk_mask)
    agg_pk = curve.sum_points(fp, pk_pts, axis=1)

    sig_pts = curve.from_affine(fp2, sig_xy[..., 0, :, :], sig_xy[..., 1, :, :])
    bits = _bits64(rand_bits) if rand_bits.shape[-1] == 2 else rand_bits
    xbits = jnp.broadcast_to(jnp.asarray(_XBITS64), (B, 64))
    both = curve.scalar_mul_bits(
        fp2,
        tuple(jnp.concatenate([c, c], axis=0) for c in sig_pts),
        jnp.concatenate([xbits, bits], axis=0),
    )
    xq = curve.neg(fp2, tuple(c[:B] for c in both))
    r_sig = tuple(c[B:] for c in both)
    sub_ok = (
        curve.eq(fp2, _psi_jacobian(sig_pts), xq)
        | curve.is_infinity(fp2, sig_pts)
        | ~set_mask
    )
    subgroup_ok = jnp.all(sub_ok)

    r_pk = curve.scalar_mul_bits(fp, agg_pk, bits)
    inf2 = curve.infinity(fp2)
    r_sig = curve.select(
        fp2, set_mask, r_sig,
        tuple(jnp.broadcast_to(c, o.shape) for c, o in zip(inf2, r_sig)),
    )
    sig_acc = curve.sum_points(fp2, r_sig, axis=0)

    pk_x, pk_y, pk_inf = curve.to_affine(fp, r_pk)
    pk_inf = pk_inf | ~set_mask
    acc_x, acc_y, acc_inf = curve.to_affine(fp2, sig_acc)
    agg_inf_bad = jnp.any(curve.is_infinity(fp, agg_pk) & set_mask)
    return pk_x, pk_y, pk_inf, acc_x, acc_y, acc_inf, subgroup_ok & ~agg_inf_bad


def _stage3_fn(pk_x, pk_y, pk_inf, msg_aff_x, msg_aff_y, msg_aff_inf,
               acc_x, acc_y, acc_inf):
    """The multi-pairing decision over B+1 lanes."""
    g1_x = jnp.concatenate([pk_x, fp.const(_NEG_G1[0])[None]], axis=0)
    g1_y = jnp.concatenate([pk_y, fp.const(_NEG_G1[1])[None]], axis=0)
    g1_inf = jnp.concatenate([pk_inf, jnp.zeros((1,), bool)], axis=0)
    g2_x = jnp.concatenate([msg_aff_x, acc_x[None]], axis=0)
    g2_y = jnp.concatenate([msg_aff_y, acc_y[None]], axis=0)
    g2_inf = jnp.concatenate([msg_aff_inf, acc_inf[None]], axis=0)
    return pairing.multi_pairing_is_one(
        (g1_x, g1_y, g1_inf), (g2_x, g2_y, g2_inf)
    )


def _gather_fn(table, agg, pk_idx):
    """Device-side pubkey gather (ISSUE 10): the static packer ships a
    ``(B, K)`` int32 index plane and this stage materializes the
    ``[B, K, 2, NL]`` limb planes from the device-resident key table —
    the pack's dominant operand (87–94% of H2D bytes at committee
    rungs, COST_MODEL.md) never crosses the host-device boundary again.
    Indices below ``table.shape[0]`` address the validator mirror;
    indices at/above it address the small aggregate-sum region ``agg``
    (cached epoch-stable committee sums, key_table.py) — two clipped
    takes and a select, so the regions stay separate device arrays and
    an aggregate insert never copies the big table.

    Runs as its own staged program ("gather", through ``_run_stage``)
    ahead of stage 2 rather than fused into stage 1's ~31k-HLO body:
    the table argument keys the compile on the table CAPACITY rung
    (key_table.CAPACITY_LADDER), and a table-growth recompile of this
    one-op program is sub-second while a stage-1 variant would re-pay a
    multi-minute XLA compile per capacity step. The gathered output
    feeds the UNCHANGED stage-2 program, so every warm stage-1/2/3 rung
    stays warm across table growth. Masked lanes gather row 0 — a REAL
    key's coordinates, unlike the raw packer's zero-filled padding rows
    — which is safe only because stage 2's ``from_affine(..., ~pk_mask)``
    forces masked lanes to infinity regardless of coordinates; nothing
    may come to rely on masked gather lanes holding invalid points."""
    B, K = pk_idx.shape
    flat_idx = pk_idx.reshape(-1)
    base = table.shape[0]
    from_val = jnp.take(table, jnp.clip(flat_idx, 0, base - 1), axis=0)
    from_agg = jnp.take(
        agg, jnp.clip(flat_idx - base, 0, agg.shape[0] - 1), axis=0
    )
    rows = jnp.where((flat_idx < base)[:, None, None], from_val, from_agg)
    return rows.reshape(B, K, *table.shape[1:])


_stage1 = jax.jit(_stage1_fn)
_stage2 = jax.jit(_stage2_fn)
_stage3 = jax.jit(_stage3_fn)
_gather = jax.jit(_gather_fn)
# MSM family (ISSUE 16): small independent programs keyed on their own
# N rung — they never disturb the warm stage-1/2/3 shapes.
_msm = jax.jit(msm_mod.msm_g1_fn)
_g2sum = jax.jit(msm_mod.sum_g2_fn)


# ---------------------------------------------------------------------------
# Hot-path telemetry (reference: beacon_chain/src/metrics.rs label-vector
# families). Per-stage wall time is measured dispatch-to-sync
# (block_until_ready): attribution needs the sync boundary, at the cost of
# host dispatch no longer running ahead of the device between stages —
# three extra host-device round trips per batch, microseconds against
# stage bodies that run for hundreds of milliseconds of device work.
# ---------------------------------------------------------------------------

_STAGE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)
_STAGE_SECONDS = metrics.histogram_vec(
    "bls_device_stage_seconds",
    "staged device BLS verifier: per-stage wall time, dispatch to device "
    "sync (first observation per shape includes jit compile)",
    ("stage", "fp_impl"),
    buckets=_STAGE_BUCKETS,
)
_VERIFY_SECONDS = metrics.histogram_vec(
    "bls_device_verify_seconds",
    "end-to-end verify_signature_sets wall time (pack + all stages)",
    ("path", "fp_impl"),
    buckets=_STAGE_BUCKETS,
)
# bls_device_pack_seconds became a phase-labeled family owned by the
# data-movement ledger (utils/transfer_ledger.py, ISSUE 8): the raw
# packer observes decode/limb_split/pad/hash/device_put + total; the
# non-instrumented packers observe total only via this handle.
_PACK_TOTAL = transfer_ledger.PACK_SECONDS.with_labels("total")
_RECOMPILES = metrics.counter_vec(
    "bls_device_recompiles_total",
    "fresh (shape, dtype, fp_impl) argument signatures per staged program "
    "— each one costs an XLA compile, assuming callers follow the "
    "fp.set_impl contract (fp.py): switch impls only through "
    "device.reset_compiled_state()",
    ("stage",),
)
_LANES = metrics.counter_vec(
    "bls_device_batch_lanes_total",
    "batch geometry: requested vs padded lane counts per dimension "
    "(B sets, K pubkey slots, M unique messages)",
    ("dim", "kind"),
)
_PAD_WASTE = metrics.gauge(
    "bls_device_padding_waste_ratio",
    "1 - live lanes / padded lanes (B*K*M) for the most recent packed "
    "batch — the SAME formula as verification_scheduler_padding_waste_"
    "ratio (verification_service/planner.py; formula equality pinned "
    "by test). Values differ under a planned multi-sub-batch flush: "
    "this gauge holds the LAST packed batch, the scheduler gauge the "
    "whole plan",
)
_OUTCOMES = metrics.counter_vec(
    "bls_device_verify_outcomes_total",
    "verify_signature_sets verdicts (rejected = host pre-screen)",
    ("outcome",),
)

_seen_stage_shapes: set = set()
_seen_lock = threading.Lock()


def reset_recompile_tracking() -> None:
    """Forget seen argument signatures. Callers should not pair this
    with ``jax.clear_caches()`` by hand anymore — use
    ``device.reset_compiled_state()`` (crypto/device/__init__.py), which
    also invalidates the compile service's warm-shape registry: XLA will
    recompile every program, and the recompile counter should see the
    next dispatches as fresh rather than silently absorbing the cost."""
    with _seen_lock:
        _seen_stage_shapes.clear()


def _active_compile_service():
    """The process-global CompileService when one is attached and
    running (compile_service/service.py) — the warm-shape router the
    packers pad against. Lazy import: the service package is jax-free,
    but this module must not depend on it at import time."""
    from ...compile_service import service as _csvc

    return _csvc.get_active_service()


def _run_stage(stage: str, fn, *args):
    """One staged dispatch: recompile accounting keyed on the argument
    (shape, dtype, fp_impl, mesh shard) signature — a jitted program
    compiled for one chip is a FRESH compile on another (ISSUE 11) —
    span + labeled wall-time histogram closed at the device sync
    boundary. Returns ``(out, elapsed_s, fresh)`` so the caller can
    journal per-stage attribution."""
    from . import mesh as _mesh_mod

    # chaos seam (ISSUE 13): an armed `staged_dispatch` fault point
    # raises (or stalls) here — inside the sharded dispatch scope, so
    # the scheduler's failover/watchdog/probation machinery sees it
    # exactly where a real chip failure would surface
    fault_injection.fire("staged_dispatch")
    impl = fp.get_impl()
    shard = _mesh_mod.current_shard() or 0
    key = (
        stage,
        impl,
        # upper-layer engine seams (ISSUE 16): a fused fp2 kernel or a
        # restructured line-eval step is a different traced program, so
        # switching either makes the next dispatch a fresh compile
        fp2.get_impl(),
        pairing.get_line_impl(),
        shard,
        tuple((tuple(a.shape), str(a.dtype)) for a in args),
    )
    # shard attr: tools/trace_report.py groups device-stage spans into
    # per-shard chrome lanes (ISSUE 12)
    with tracing.span(f"bls.{stage}", fp_impl=impl, shard=shard):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))
        elapsed = time.perf_counter() - t0
        _STAGE_SECONDS.with_labels(stage, impl).observe(elapsed)
    # seen only after a SUCCESSFUL dispatch: a failed first compile must
    # not consume the signature's fresh tick (the retry pays the compile)
    with _seen_lock:
        fresh = key not in _seen_stage_shapes
        if fresh:
            _seen_stage_shapes.add(key)
    if fresh:
        _RECOMPILES.with_labels(stage).inc()
    # pipeline profiler (ISSUE 12): this dispatch-to-sync wall is a
    # device BUSY interval on its shard; the gap since the shard's
    # previous sync is a bubble, attributed to pack/plan/compile/
    # queue_empty/other. A fresh dispatch's wall includes the XLA
    # compile, so it is also recorded as compile activity.
    pipeline_profiler.note_stage_wall(
        stage, shard, t0, t0 + elapsed, fresh=fresh
    )
    return out, elapsed, fresh


def stage_latency_summary(impl: str | None = None) -> dict:
    """Rows of {fp_impl, p50_s, p99_s, mean_s, count} read from the
    ``bls_device_stage_seconds`` family — the one reader bench.py and
    tools/trace_report.py share. With ``impl`` the rows are keyed by
    stage; with ``impl=None`` every engine is reported, keyed
    ``stage:fp_impl`` so one engine cannot shadow another. Quantiles are
    histogram-bucket upper bounds (None = beyond the top bucket); count
    says how many dispatches (compiles included) each row aggregates.

    Also reports the END-TO-END ``bls_device_verify_seconds`` rows
    (keyed ``verify:<path>``): the verdict-latency SLO layer
    (docs/TRAFFIC_REPLAY.md) attributes a deadline miss by holding the
    scheduler's submit-to-verdict tail against this device-side
    pack+dispatch tail — if the device p99 explains the miss, the fix
    is batch shape/compile warmth, not queueing."""
    import math

    def _finite(q):
        return q if math.isfinite(q) else None  # keep the JSON strict

    def _row(child, child_impl):
        total, sum_, _cum = child.snapshot()
        if not total:
            return None
        return {
            "fp_impl": child_impl,
            "p50_s": _finite(child.quantile(0.5)),
            "p99_s": _finite(child.quantile(0.99)),
            "mean_s": round(sum_ / total, 4),
            "count": total,
        }

    out = {}
    for (stage, child_impl), child in sorted(_STAGE_SECONDS.children().items()):
        if impl is not None and child_impl != impl:
            continue
        row = _row(child, child_impl)
        if row:
            out[stage if impl is not None else f"{stage}:{child_impl}"] = row
    for (path, child_impl), child in sorted(_VERIFY_SECONDS.children().items()):
        if impl is not None and child_impl != impl:
            continue
        row = _row(child, child_impl)
        if row:
            key = (
                f"verify:{path}"
                if impl is not None
                else f"verify:{path}:{child_impl}"
            )
            out[key] = row
    # host-pack phase attribution (data-movement ledger, ISSUE 8): the
    # pack family is phase-labeled, engine-independent — the rows ride
    # along keyed pack:<phase> so bench/trace readers see where host
    # pack time goes next to the device stage split
    for (phase,), child in sorted(
        transfer_ledger.PACK_SECONDS.children().items()
    ):
        row = _row(child, "-")
        if row:
            row.pop("fp_impl", None)
            out[f"pack:{phase}"] = row
    # device idle-gap attribution (pipeline profiler, ISSUE 12): the
    # bubble rows ride along keyed bubble:<cause> so bench/trace
    # readers see where device idle went next to the stage and pack
    # splits (sum_s/count/mean_s — counters, not histograms: no
    # quantiles to report)
    for cause, row in pipeline_profiler.bubble_rows().items():
        out[f"bubble:{cause}"] = row
    return out


def verify_batch_raw_staged(
    pk_xy, pk_mask, sig_x, sig_larger, msg_u, msg_idx, rand_bits, set_mask
):
    """Staged equivalent of ``verify_batch_raw`` (same inputs, same
    verdict): three device dispatches, intermediates stay on device.
    Each call journals one ``bls_stage_verify`` flight-recorder event
    (batch geometry, fp_impl, per-stage dispatch-to-sync seconds,
    verdict, recompile flag); a False verdict triggers
    ``dump_on_failure`` so the surrounding context is preserved."""
    return _staged_verify(
        pk_xy, pk_mask, sig_x, sig_larger, msg_u, msg_idx, rand_bits,
        set_mask,
    )


def verify_batch_raw_staged_gather(
    table, agg, pk_idx, pk_mask, sig_x, sig_larger, msg_u, msg_idx,
    rand_bits, set_mask,
):
    """Gathered variant of :func:`verify_batch_raw_staged` (ISSUE 10):
    the pubkey planes arrive as a ``(B, K)`` index plane into the
    device-resident key ``table`` (+ aggregate region ``agg``) and are
    materialized by the "gather" staged program; stages 1–3 are
    byte-identical to the raw path, so the verdict is too. The
    ``bls_stage_verify`` journal row carries the extra
    ``gather_s``/``gathered`` attribution."""
    try:
        pk_xy, sg, fg = _run_stage("gather", _gather, table, agg, pk_idx)
    except BaseException:
        # mirror the staged raise contract: the pack's ledger row lands
        transfer_ledger.commit_verify(None, d2h_bytes=0)
        raise
    return _staged_verify(
        pk_xy, pk_mask, sig_x, sig_larger, msg_u, msg_idx, rand_bits,
        set_mask, gather_record=(sg, fg),
    )


def _staged_verify(
    pk_xy, pk_mask, sig_x, sig_larger, msg_u, msg_idx, rand_bits, set_mask,
    gather_record=None,
):
    try:
        (sig_xy, mx, my, minf, sig_ok), s1, f1 = _run_stage(
            "stage1", _stage1, sig_x, sig_larger, msg_u
        )
        outs, s2, f2 = _run_stage(
            "stage2", _stage2, pk_xy, pk_mask, sig_xy, rand_bits, set_mask
        )
        pk_x, pk_y, pk_inf, acc_x, acc_y, acc_inf, flags_ok = outs
        msg_aff_x = jnp.take(mx, msg_idx, axis=0)
        msg_aff_y = jnp.take(my, msg_idx, axis=0)
        msg_aff_inf = jnp.take(minf, msg_idx, axis=0)
        pair_ok, s3, f3 = _run_stage(
            "stage3", _stage3,
            pk_x, pk_y, pk_inf, msg_aff_x, msg_aff_y, msg_aff_inf,
            acc_x, acc_y, acc_inf,
        )
    except BaseException:
        # the pack's bytes already shipped and were counted: its ledger
        # row must land (verdict null, nothing read back) — one journal
        # row per pack, raise or not, and never a stale staged row
        transfer_ledger.commit_verify(None, d2h_bytes=0)
        raise
    out = pair_ok & flags_ok & jnp.all(sig_ok | ~set_mask)
    # every stage output is already synced, so the verdict read is free
    verdict = bool(out)
    geometry = {
        "b": int(pk_xy.shape[0]),
        "k": int(pk_xy.shape[1]),
        "m": int(msg_u.shape[0]),
        "fp_impl": fp.get_impl(),
    }
    gather_fields = {}
    recompiled = bool(f1 or f2 or f3)
    if gather_record is not None:
        sg, fg = gather_record
        gather_fields = {"gathered": True, "gather_s": round(sg, 6)}
        recompiled = recompiled or bool(fg)
    flight_recorder.record(
        "bls_stage_verify",
        stage1_s=round(s1, 6), stage2_s=round(s2, 6), stage3_s=round(s3, 6),
        recompiled=recompiled, verdict=verdict, **gather_fields, **geometry,
    )
    # the data-movement row this thread's pack staged (transfer_ledger):
    # the verdict read is the only device→host transfer of a staged
    # verify — intermediates stay on device by design
    transfer_ledger.commit_verify(verdict, d2h_bytes=int(out.nbytes))
    if not verdict:
        flight_recorder.dump_on_failure("stage_verify_failure", **geometry)
    return out


# ---------------------------------------------------------------------------
# MSM-family staged programs (ISSUE 16)
# ---------------------------------------------------------------------------

def run_msm_g1(pt_xy, pt_inf, scalars):
    """Dispatch the windowed G1 MSM staged program (device arrays in,
    device arrays out). Keyed like every staged program — recompile
    accounting, stage histogram (stage label "msm"), profiler span."""
    out, _s, _f = _run_stage("msm", _msm, pt_xy, pt_inf, scalars)
    return out


def run_g2_sum(pt_xy, pt_inf):
    """Dispatch the masked G2 point-sum staged program (the aggregate
    half of the MSM family; same "msm" stage label)."""
    out, _s, _f = _run_stage("msm", _g2sum, pt_xy, pt_inf)
    return out


def device_msm_g1(points, scalars, pad_n: int | None = None):
    """Host helper: cpu G1Point list + u64 scalars -> their device MSM
    as a cpu G1Point. N pads to the bucket ladder so repeated calls
    reuse warm MSM-rung programs; padding lanes are infinity with zero
    scalars (no contribution, complete group law)."""
    pts = list(points)
    sc = list(scalars)
    assert len(pts) == len(sc)
    N = pad_n or _round_up(max(len(pts), 1))
    xy = np.zeros((N, 2, fp.NL), np.int32)
    inf = np.ones((N,), bool)
    sw = np.zeros((N, 2), np.int32)
    if pts:
        pxy, pinf = curve.pack_g1(pts)
        xy[: len(pts)] = pxy
        inf[: len(pts)] = pinf
    for i, s in enumerate(sc):
        # u64 -> two's-complement int32 words (numpy rejects narrowing
        # casts of out-of-range Python ints; a view reinterprets safely)
        sw[i] = np.array(
            [(s >> 32) & 0xFFFFFFFF, s & 0xFFFFFFFF], np.uint32
        ).view(np.int32)
    # data-movement attribution (ISSUE 17 satellite: msm can't run
    # dark): live lanes count as point/scalar bytes, pad lanes as
    # padding — the labels sum to the exact device_put nbytes, the
    # transfer ledger's invariant
    live = len(pts)
    live_b = live * (xy.nbytes // N + inf.nbytes // N) + live * (sw.nbytes // N)
    transfer_ledger.note_op_bytes(
        {
            "pubkeys": live * (xy.nbytes // N + inf.nbytes // N),
            "aux": live * (sw.nbytes // N),
            "padding": xy.nbytes + inf.nbytes + sw.nbytes - live_b,
        },
        kind="msm",
    )
    oxy, oinf = run_msm_g1(
        jnp.asarray(xy), jnp.asarray(inf), jnp.asarray(sw)
    )
    return curve.unpack_g1(np.asarray(oxy)[None], np.asarray(oinf)[None])[0]


def device_sum_g2(points, pad_n: int | None = None):
    """Host helper: cpu G2Point list -> their device point sum as a cpu
    G2Point (operation_pool's aggregation path). Padding lanes are
    infinity; an empty list returns infinity."""
    pts = list(points)
    N = pad_n or _round_up(max(len(pts), 1))
    xy = np.zeros((N, 2, 2, fp.NL), np.int32)
    inf = np.ones((N,), bool)
    if pts:
        pxy, pinf = curve.pack_g2(pts)
        xy[: len(pts)] = pxy
        inf[: len(pts)] = pinf
    # G2 points ride the signatures operand (they ARE signature points
    # — the op pool's aggregation inputs); pad lanes as padding
    live_b = len(pts) * (xy.nbytes // N + inf.nbytes // N)
    transfer_ledger.note_op_bytes(
        {
            "signatures": live_b,
            "padding": xy.nbytes + inf.nbytes - live_b,
        },
        kind="msm",
    )
    oxy, oinf = run_g2_sum(jnp.asarray(xy), jnp.asarray(inf))
    return curve.unpack_g2(np.asarray(oxy)[None], np.asarray(oinf)[None])[0]


# ---------------------------------------------------------------------------
# Host backend: padding, bucketing, randomness, reference edge semantics
# ---------------------------------------------------------------------------

# 48/96/192 are intermediate rungs for the flush planner's bin-packed
# sub-batches (verification_service/planner.py): observed traffic
# shapes a pure power-of-two ladder padded up to 64/128/256. The
# scheduler mirrors this tuple as BUCKET_LADDER (jax-free); the two are
# pinned equal by tests/test_verification_scheduler.py.
def _round_up(
    n: int,
    choices=(1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256, 512, 1024),
) -> int:
    for c in choices:
        if n <= c:
            return c
    return ((n + 1023) // 1024) * 1024


def _rand_scalar_words() -> tuple[int, int]:
    while True:
        r = secrets.randbits(64)
        if r:
            return (r >> 32) & 0xFFFFFFFF, r & 0xFFFFFFFF


def _pack_common(sets, B: int, K: int):
    """Shared per-set packing: pubkeys, signatures, randomness, mask —
    used by both message-point and hashed packers."""
    pk_xy = np.zeros((B, K, 2, fp.NL), np.int32)
    pk_mask = np.zeros((B, K), bool)
    sig_xy = np.zeros((B, 2, 2, fp.NL), np.int32)
    rand = np.zeros((B, 2), np.int32)
    set_mask = np.zeros((B,), bool)
    for i, (sig, pks, _msg) in enumerate(sets):
        xy, _ = curve.pack_g1(pks)
        pk_xy[i, : len(pks)] = xy
        pk_mask[i, : len(pks)] = True
        sxy, _ = curve.pack_g2([sig])
        sig_xy[i] = sxy[0]
        hi, lo = _rand_scalar_words()
        rand[i] = (np.int32(np.uint32(hi)), np.int32(np.uint32(lo)))
        set_mask[i] = True
    # Padding lanes get a valid placeholder signature point (the real G2
    # generator) so the subgroup check vectorizes uniformly; their
    # contribution is masked out by set_mask.
    if B > len(sets):
        from ..cpu.curve import g2_generator

        gxy, _ = curve.pack_g2([g2_generator()])
        sig_xy[len(sets):] = gxy[0]
    return pk_xy, pk_mask, sig_xy, rand, set_mask


def _pad_sig_lanes(sig_x, n_live: int) -> None:
    """Padding lanes get the G2 generator's x (a valid curve x) so the
    device decompression stays uniform; their result is masked out by
    ``set_mask``. ONE definition for both halves of the static/dynamic
    packer split — the two packers must stay byte-identical in every
    non-pubkey plane."""
    if sig_x.shape[0] <= n_live:
        return
    from ..cpu.curve import g2_generator

    g = g2_generator()
    sig_x[n_live:, 0] = fp.int_to_limbs(g.x.c0.n)
    sig_x[n_live:, 1] = fp.int_to_limbs(g.x.c1.n)


def _pack_message_planes(sets, B: int, pad_m: int | None):
    """Shared message half of the raw/indexed packers: dedup + padded
    per-lane index plane + hash_to_field u-values. Returns
    ``(msg_u, msg_idx, m_req)``."""
    msgs, idx = _dedup_messages([m for _, _, m in sets], pad_m)
    m_req = int(idx.max()) + 1 if len(idx) else 1  # distinct live messages
    msg_idx = np.zeros((B,), np.int32)
    msg_idx[: len(sets)] = idx
    from . import htc

    msg_u = htc.messages_to_u(msgs, DST)
    return msg_u, msg_idx, m_req


def _dedup_messages(messages, pad_m: int | None):
    """-> (unique-message list padded to M, per-item index array)."""
    uniq: dict[bytes, int] = {}
    idx = np.zeros((len(messages),), np.int32)
    for i, m in enumerate(messages):
        idx[i] = uniq.setdefault(bytes(m), len(uniq))
    M = pad_m or _round_up(len(uniq))
    assert len(uniq) <= M, (
        f"pad_m={M} smaller than {len(uniq)} distinct messages"
    )
    msgs = sorted(uniq, key=uniq.get) + [b""] * (M - len(uniq))
    return msgs, idx


def pack_signature_sets(sets, pad_b: int | None = None, pad_k: int | None = None):
    """Host-side batch assembly: (sig_point, [pk_points], message) triples ->
    the fixed-shape device arrays of :func:`verify_batch_fn`. Sets must be
    pre-screened (non-empty, non-infinity signature). Shapes are padded to
    bucket sizes to bound jit recompiles."""
    sets = list(sets)
    B = pad_b or _round_up(len(sets))
    K = pad_k or _round_up(max(len(pks) for _, pks, _ in sets))
    pk_xy, pk_mask, sig_xy, rand, set_mask = _pack_common(sets, B, K)

    msg_xy = np.zeros((B, 2, 2, fp.NL), np.int32)
    msg_cache: dict[bytes, np.ndarray] = {}
    for i, (_sig, _pks, msg) in enumerate(sets):
        hxy = msg_cache.get(msg)
        if hxy is None:
            hxy = curve.pack_g2([hash_to_g2(msg, DST)])[0][0]
            msg_cache[msg] = hxy
        msg_xy[i] = hxy
    if B > len(sets):
        # same placeholder as the padding signature lanes
        msg_xy[len(sets):] = sig_xy[len(sets)]

    return (
        jnp.asarray(pk_xy),
        jnp.asarray(pk_mask),
        jnp.asarray(sig_xy),
        jnp.asarray(msg_xy),
        jnp.asarray(rand),
        jnp.asarray(set_mask),
    )


def pack_signature_sets_hashed(
    sets, pad_b: int | None = None, pad_k: int | None = None,
    pad_m: int | None = None,
):
    """End-to-end packing: like :func:`pack_signature_sets` but messages
    stay raw — the host computes only hash_to_field u-values (native
    SHA-256); the curve mapping runs on device inside
    :func:`verify_batch_hashed_fn`. This removes the 285 ms/message
    pure-Python ``hash_to_g2`` from the hot path (VERDICT weakness #2)."""
    from . import htc

    sets = list(sets)
    B = pad_b or _round_up(len(sets))
    K = pad_k or _round_up(max(len(pks) for _, pks, _ in sets))
    pk_xy, pk_mask, sig_xy, rand, set_mask = _pack_common(sets, B, K)

    msgs, idx = _dedup_messages([m for _, _, m in sets], pad_m)
    msg_idx = np.zeros((B,), np.int32)
    msg_idx[: len(sets)] = idx
    msg_u = htc.messages_to_u(msgs, DST)

    return (
        jnp.asarray(pk_xy),
        jnp.asarray(pk_mask),
        jnp.asarray(sig_xy),
        jnp.asarray(msg_u),
        jnp.asarray(msg_idx),
        jnp.asarray(rand),
        jnp.asarray(set_mask),
    )


def pack_signature_sets_raw(
    sets, pad_b: int | None = None, pad_k: int | None = None,
    pad_m: int | None = None,
):
    """Fully-raw packing for :func:`verify_batch_raw_fn`: ``sets`` are
    ``(Signature-object, [pk_points], message)`` triples. Signatures stay
    COMPRESSED — only byte parsing happens here; no host sqrt.

    DYNAMIC half of the static/dynamic packer split (ISSUE 10): this
    packer ships full G1 limb planes and serves out-of-table keys (VC
    tests, library callers, pre-admission gossip); sets whose pubkeys
    all resolve to device key-table indices go through
    :func:`pack_signature_sets_indexed` instead and ship a ``(B, K)``
    index plane (docs/DEVICE_CRYPTO.md).

    Instrumented as the data-movement ledger's measured pack (ISSUE 8):
    phases ``decode`` (signature byte parsing + randomness),
    ``limb_split`` (int→limb conversion + array fill), ``pad``
    (allocation + padding-lane fill), ``hash`` (message hash_to_field),
    ``device_put`` (host→device transfer) land in
    ``bls_device_pack_seconds{phase}``; per-operand byte splits and the
    packed pubkey rows feed ``utils/transfer_ledger.note_pack``."""
    t_start = time.perf_counter()
    sets = list(sets)
    B = pad_b or _round_up(len(sets))
    K = pad_k or _round_up(max(len(pks) for _, pks, _ in sets))

    pk_xy = np.zeros((B, K, 2, fp.NL), np.int32)
    pk_mask = np.zeros((B, K), bool)
    sig_x = np.zeros((B, 2, fp.NL), np.int32)
    sig_larger = np.zeros((B,), bool)
    rand = np.zeros((B, 2), np.int32)
    set_mask = np.zeros((B,), bool)
    t_pad = time.perf_counter() - t_start

    from .. import bls as _bls

    # with the ledger off, the packer must not pay for it either: no
    # per-pubkey blob copies, no device sync (note_pack would drop them)
    ledger_on = transfer_ledger.enabled()
    t_decode = t_limb = 0.0
    pk_blobs: list = []
    pk_slots = 0
    for i, (sig, pks, _msg) in enumerate(sets):
        t0 = time.perf_counter()
        x0, x1, larger = _bls.parse_compressed_g2_x(sig.serialize())
        hi, lo = _rand_scalar_words()
        t1 = time.perf_counter()
        t_decode += t1 - t0
        xy, _ = curve.pack_g1(pks)
        pk_xy[i, : len(pks)] = xy
        pk_mask[i, : len(pks)] = True
        sig_x[i, 0] = fp.int_to_limbs(x0)
        sig_x[i, 1] = fp.int_to_limbs(x1)
        sig_larger[i] = larger
        rand[i] = (np.int32(np.uint32(hi)), np.int32(np.uint32(lo)))
        set_mask[i] = True
        t_limb += time.perf_counter() - t1
        pk_slots += len(pks)
        if ledger_on:
            for j in range(len(pks)):
                pk_blobs.append(xy[j].tobytes())
    t0 = time.perf_counter()
    _pad_sig_lanes(sig_x, len(sets))
    t_pad += time.perf_counter() - t0

    t0 = time.perf_counter()
    msg_u, msg_idx, m_req = _pack_message_planes(sets, B, pad_m)
    t_hash = time.perf_counter() - t0

    t0 = time.perf_counter()
    fault_injection.fire("device_put")  # chaos seam (ISSUE 13)
    args = (
        jnp.asarray(pk_xy),
        jnp.asarray(pk_mask),
        jnp.asarray(sig_x),
        jnp.asarray(sig_larger),
        jnp.asarray(msg_u),
        jnp.asarray(msg_idx),
        jnp.asarray(rand),
        jnp.asarray(set_mask),
    )
    if ledger_on:
        # async backends (real TPU) return from asarray while the DMA
        # is in flight: block so the phase measures the TRANSFER, not
        # the enqueue — otherwise the effective-H2D-bandwidth evidence
        # is inflated exactly on the device it is meant to size. Gated:
        # with the ledger off the hot path keeps its transfer/dispatch
        # overlap and pays nothing, and the device_put semantics change
        # is DOCUMENTED in the family help (enqueue-only when disabled
        # on async backends)
        jax.block_until_ready(args)
    t_dput = time.perf_counter() - t0

    phases = {
        "decode": t_decode, "limb_split": t_limb, "pad": t_pad,
        "hash": t_hash, "device_put": t_dput,
    }
    total_s = time.perf_counter() - t_start
    # the pack histogram is always-on (it predates the ledger); only the
    # byte accounting below is behind the ledger knob
    transfer_ledger.observe_pack_phases(phases, total_s)
    transfer_ledger.note_pack(
        n_sets=len(sets), b=B, k=K, m=int(msg_u.shape[0]),
        pk_slots=pk_slots, m_req=m_req,
        phases=phases,
        total_s=total_s,
        operand_nbytes={
            "pubkeys": pk_xy.nbytes + pk_mask.nbytes,
            "signatures": sig_x.nbytes + sig_larger.nbytes,
            "messages": msg_u.nbytes + msg_idx.nbytes,
            "aux": rand.nbytes + set_mask.nbytes,
        },
        pubkey_blobs=pk_blobs,
    )
    # pipeline profiler (ISSUE 12): the whole pack is host activity —
    # a device gap overlapping it attributes to cause `pack`
    pipeline_profiler.note_pack_wall(t_start, t_start + total_s)
    return args


def pack_signature_sets_indexed(
    sets, indices, pad_b: int | None = None, pad_k: int | None = None,
    pad_m: int | None = None,
):
    """STATIC half of the raw packer split (ISSUE 10): for sets whose
    pubkeys all resolved to device key-table indices
    (``key_table.DeviceKeyTable.resolve_sets``), ship a ``(B, K)`` int32
    index plane + mask instead of the ``(B, K, 2, NL)`` G1 limb planes —
    ~5 bytes per pubkey slot instead of 257. ``indices`` is the per-set
    index list (aggregate-collapsed sets carry one index). Everything
    else (signature decode, randomness, message hashing) matches
    :func:`pack_signature_sets_raw`, and the ledger row is labeled
    ``indexed`` so byte accounting stays honest."""
    t_start = time.perf_counter()
    sets = list(sets)
    indices = list(indices)
    if len(indices) != len(sets):
        # a REAL raise, not an assert: under python -O a silent zip
        # truncation would leave trailing sets masked out — an
        # unverified signature accepted by a True batch verdict
        raise ValueError(
            f"indices must match sets one-to-one "
            f"({len(indices)} vs {len(sets)})"
        )
    B = pad_b or _round_up(len(sets))
    K = pad_k or _round_up(max((len(ix) for ix in indices), default=1))

    pk_idx = np.zeros((B, K), np.int32)
    pk_mask = np.zeros((B, K), bool)
    sig_x = np.zeros((B, 2, fp.NL), np.int32)
    sig_larger = np.zeros((B,), bool)
    rand = np.zeros((B, 2), np.int32)
    set_mask = np.zeros((B,), bool)
    t_pad = time.perf_counter() - t_start

    from .. import bls as _bls

    ledger_on = transfer_ledger.enabled()
    t_decode = t_fill = 0.0
    pk_slots = 0
    for i, ((sig, _pks, _msg), ix) in enumerate(zip(sets, indices)):
        t0 = time.perf_counter()
        x0, x1, larger = _bls.parse_compressed_g2_x(sig.serialize())
        hi, lo = _rand_scalar_words()
        t1 = time.perf_counter()
        t_decode += t1 - t0
        pk_idx[i, : len(ix)] = ix
        pk_mask[i, : len(ix)] = True
        sig_x[i, 0] = fp.int_to_limbs(x0)
        sig_x[i, 1] = fp.int_to_limbs(x1)
        sig_larger[i] = larger
        rand[i] = (np.int32(np.uint32(hi)), np.int32(np.uint32(lo)))
        set_mask[i] = True
        t_fill += time.perf_counter() - t1
        pk_slots += len(ix)
    t0 = time.perf_counter()
    _pad_sig_lanes(sig_x, len(sets))
    t_pad += time.perf_counter() - t0

    t0 = time.perf_counter()
    msg_u, msg_idx, m_req = _pack_message_planes(sets, B, pad_m)
    t_hash = time.perf_counter() - t0

    t0 = time.perf_counter()
    fault_injection.fire("device_put")  # chaos seam (ISSUE 13)
    args = (
        jnp.asarray(pk_idx),
        jnp.asarray(pk_mask),
        jnp.asarray(sig_x),
        jnp.asarray(sig_larger),
        jnp.asarray(msg_u),
        jnp.asarray(msg_idx),
        jnp.asarray(rand),
        jnp.asarray(set_mask),
    )
    if ledger_on:
        # same sync rationale as the raw packer: measure the TRANSFER
        jax.block_until_ready(args)
    t_dput = time.perf_counter() - t0

    phases = {
        "decode": t_decode, "limb_split": t_fill, "pad": t_pad,
        "hash": t_hash, "device_put": t_dput,
    }
    total_s = time.perf_counter() - t_start
    transfer_ledger.observe_pack_phases(phases, total_s)
    transfer_ledger.note_pack(
        n_sets=len(sets), b=B, k=K, m=int(msg_u.shape[0]),
        pk_slots=pk_slots, m_req=m_req,
        phases=phases,
        total_s=total_s,
        operand_nbytes={
            # the index plane IS the pubkey operand now
            "pubkeys": pk_idx.nbytes + pk_mask.nbytes,
            "signatures": sig_x.nbytes + sig_larger.nbytes,
            "messages": msg_u.nbytes + msg_idx.nbytes,
            "aux": rand.nbytes + set_mask.nbytes,
        },
        pubkey_blobs=(),  # nothing G1-shaped crossed the boundary
        indexed=True,
    )
    # pipeline profiler (ISSUE 12): same pack-activity contract as the
    # raw packer — the static half's wall is host time too
    pipeline_profiler.note_pack_wall(t_start, t_start + total_s)
    return args


def _active_key_table():
    """The process-global device key table when one is attached with
    resident rows (crypto/device/key_table.py). Lazy import mirrors
    ``_active_compile_service``."""
    from . import key_table as _kt

    return _kt.get_active_table()


class TpuBackend:
    """Runtime backend ``"tpu"`` (see crypto/backend.py). Presents the same
    protocol as the CPU oracle backend; internally packs fixed-shape
    batches and calls the jitted device program (compile cache keyed on
    padded (B, K, M) bucket shape).

    Pubkey subgroup checks are NOT repeated here: every ``PublicKey``
    enters the system through ``deserialize`` (KeyValidate — infinity +
    subgroup), mirroring the reference's decompress-once
    ``ValidatorPubkeyCache`` admission (``validator_pubkey_cache.rs:79``);
    the device program still rejects an aggregate that degenerates to
    infinity."""

    name = "tpu"

    # -- batch verification (the hot path) -------------------------------

    def verify_signature_sets(self, sets) -> bool:
        """``sets``: (Signature-object | G2Point, [pk_points], message).
        Signature objects keep their compressed bytes and are decompressed
        ON DEVICE (verify_batch_raw); bare points (oracle tests) fall back
        to the pre-decompressed program."""
        from .. import bls as _bls

        sets = list(sets)
        if not sets:
            _OUTCOMES.with_labels("rejected").inc()
            return False
        raw_mode = all(isinstance(s, _bls.Signature) for s, _, _ in sets)
        for sig, pks, _msg in sets:
            if not pks or sig.is_infinity():
                _OUTCOMES.with_labels("rejected").inc()
                return False
            if any(pk.is_infinity() for pk in pks):
                _OUTCOMES.with_labels("rejected").inc()
                return False
        path = "raw_staged" if raw_mode else "hashed"
        impl = fp.get_impl()
        # static/dynamic packer decision (ISSUE 10): when a device key
        # table is attached and EVERY pubkey of this batch resolves to a
        # resident index (identity-pinned to the host cache), the pack
        # ships a (B, K) index plane and the pubkey planes materialize
        # by device gather. Any out-of-table key (VC tests, library
        # callers, pre-admission gossip) falls the whole batch back to
        # the raw limb plane — the flush planner splits mixed flushes
        # into static/dynamic sub-batches upstream so one raw set does
        # not degrade a warm static batch.
        resolved = table_dev = agg_dev = None
        n_collapsed = 0
        table = _active_key_table()
        if raw_mode:
            if table is not None:
                res = table.resolve_sets(sets)
                if res is not None:
                    resolved, table_dev, agg_dev, n_collapsed = res
                    path = "raw_gather"
        elif table is not None:
            # hashed mode (bare points) can never gather: keep the hit
            # ratio's denominator honest about the fallback
            table.count_raw(len(sets))
        # requested geometry, computed ONCE for warm-shape routing and
        # the padding accounting (the packer's own dedup still runs — it
        # needs the index mapping, not just the count). The static path
        # pays the COLLAPSED K axis (a cached aggregate sum is one slot).
        if resolved is not None:
            k_req = max(len(ix) for ix in resolved)
            pk_slots = sum(len(ix) for ix in resolved)
        else:
            k_req = max(len(pks) for _, pks, _ in sets)
            pk_slots = None
        m_req = len({bytes(m) for _, _, m in sets})
        # warm-shape routing (compile_service): when a service is
        # attached and a warm rung covers this batch, pad UP to it so
        # the dispatch hits an already-compiled staged program instead
        # of paying a fresh XLA compile on the caller's thread
        pad_b = pad_k = pad_m = None
        svc = _active_compile_service() if raw_mode else None
        warm_epoch = None
        # the dp shard this dispatch runs on (ISSUE 11): the scheduler's
        # sharded sub-batch scope sets it thread-locally; 0 without a
        # mesh. Routing, recompile accounting and the organic-warmth
        # mark are all PER SHARD — one chip's warmth is not another's.
        from . import mesh as _mesh_mod

        shard = _mesh_mod.current_shard() or 0
        if svc is not None:
            # epoch BEFORE dispatch: if reset_compiled_state() lands while
            # we verify, the organic mark below must be rejected as stale
            warm_epoch = svc.registry.epoch
            # NOTE on collapse vs routing: aggregate collapse only
            # SHRINKS k_req, and warm coverage is >=-monotone in K
            # (planner.best_covering_rung filters K >= k_req), so the
            # collapsed request routes at least as warm as the
            # uncollapsed geometry decide_flush approved — collapse can
            # never turn a warm-approved flush into a cold stall
            rung = svc.pads_for(len(sets), k_req, m_req, device=shard)
            if rung is not None:
                pad_b, pad_k, pad_m = rung
        if resolved is not None:
            # the shipping-path accounting the health hit-ratio reads —
            # committed by the dispatcher, in one place, once the batch
            # is definitely taking the indexed path
            table.count_shipped(len(sets) - n_collapsed, n_collapsed)
        t_serve0 = time.perf_counter()  # rung-cost feed (ISSUE 14)
        with tracing.span(
            "bls.verify_signature_sets", path=path, n_sets=len(sets)
        ) as sp, _VERIFY_SECONDS.with_labels(path, impl).time():
            with tracing.span("bls.pack"):
                if resolved is not None:
                    # static packer: index plane only (the pubkey limbs
                    # are already device-resident)
                    args = pack_signature_sets_indexed(
                        sets, resolved,
                        pad_b=pad_b, pad_k=pad_k, pad_m=pad_m,
                    )
                elif raw_mode:
                    # the raw packer observes its own phase-labeled pack
                    # times (incl. total) into the data-movement ledger
                    args = pack_signature_sets_raw(
                        sets, pad_b=pad_b, pad_k=pad_k, pad_m=pad_m
                    )
                else:
                    with _PACK_TOTAL.time():
                        args = pack_signature_sets_hashed(sets)
            self._record_geometry(
                sets, args, k_req=k_req, m_req=m_req, pk_slots=pk_slots
            )
            if resolved is not None:
                out = bool(
                    verify_batch_raw_staged_gather(table_dev, agg_dev, *args)
                )
            elif raw_mode:
                out = bool(verify_batch_raw_staged(*args))
            else:
                out = bool(verify_batch_hashed(*args))
            sp.set(verdict=out)
        if raw_mode and svc is not None:
            # organic warmth: whatever rung this batch landed on is
            # compiled now ON THIS SHARD (whatever the verdict) —
            # routable without the AOT worker. OUTSIDE the timed window:
            # the first mark per rung writes the manifest to disk.
            svc.note_rung_verified(
                int(args[0].shape[0]),    # B (pk_xy)
                int(args[0].shape[1]),    # K
                int(args[4].shape[0]),    # M (msg_u)
                epoch=warm_epoch,
                device=shard,
                # the rung-cost feed (ISSUE 14): full serving wall
                # (pack + staged dispatch) per live set — the capacity
                # estimator's fallback cost input
                seconds=time.perf_counter() - t_serve0,
                n_sets=len(sets),
            )
        _OUTCOMES.with_labels("ok" if out else "fail").inc()
        return out

    @staticmethod
    def _record_geometry(
        sets, packed_args, k_req: int | None = None, m_req: int | None = None,
        pk_slots: int | None = None,
    ) -> None:
        """Batch-geometry accounting: requested vs padded B/K/M lanes and
        the padding-waste fraction of the pubkey plane (the device pays
        for padded lanes; the caller only needed the requested ones).
        ``k_req``/``m_req`` take the caller's already-computed request
        geometry so the message set is not hashed twice per batch;
        ``pk_slots`` overrides the live slot count for the indexed path,
        where an aggregate-collapsed committee set occupies ONE lane."""
        pk_xy = packed_args[0]  # raw: [B,K,2,NL]; indexed: idx plane [B,K]
        b_pad, k_pad = int(pk_xy.shape[0]), int(pk_xy.shape[1])
        # raw/hashed packers put msg_u [M, 2, 2, NL] at index 4/3
        m_pad = int(packed_args[4 if len(packed_args) == 8 else 3].shape[0])
        b_req = len(sets)
        if k_req is None:
            k_req = max(len(pks) for _, pks, _ in sets)
        if m_req is None:
            m_req = len({bytes(m) for _, _, m in sets})
        for dim, req, pad in (
            ("b", b_req, b_pad), ("k", k_req, k_pad), ("m", m_req, m_pad)
        ):
            _LANES.with_labels(dim, "requested").inc(req)
            _LANES.with_labels(dim, "padded").inc(pad)
        real_slots = (
            pk_slots
            if pk_slots is not None
            else sum(len(pks) for _, pks, _ in sets)
        )
        # ONE waste definition across the stack (lazy import: the
        # planner module is jax-free, but this module must not pull the
        # verification_service package in at import time)
        from ...verification_service import planner as _planner

        _PAD_WASTE.set(
            _planner.padding_waste_ratio(
                _planner.live_lanes(real_slots, m_req),
                _planner.padded_lanes(b_pad, k_pad, m_pad),
            )
        )

    # -- single-set entry points (same device program, B=1 semantics) ----

    def verify(self, pk, message, sig) -> bool:
        if pk.is_infinity():
            return False
        return self._verify_one(sig, [pk], message, aggregate=False)

    def fast_aggregate_verify(self, pks, message, sig) -> bool:
        pks = list(pks)
        if not pks:
            return False
        # Aggregation happens on device (masked sum); an aggregate that
        # degenerates to infinity fails inside the device program.
        return self._verify_one(sig, pks, message, aggregate=True)

    def aggregate_verify(self, pks, messages, sig) -> bool:
        """One signature over per-pubkey messages: prod e(pk_i, H(m_i)) *
        e(-g1, sig) == 1 with a subgroup-checked signature. Message
        hashing runs on device (htc.map_to_g2)."""
        from . import htc

        pks, messages = list(pks), list(messages)
        if not pks or len(pks) != len(messages):
            return False
        if any(pk.is_infinity() for pk in pks):
            return False
        n = len(pks)
        Bn = _round_up(n)
        pk_xy = np.zeros((Bn, 2, fp.NL), np.int32)
        pk_inf = np.ones((Bn,), bool)
        xy, _ = curve.pack_g1(pks)
        pk_xy[:n] = xy
        pk_inf[:n] = False
        msgs, idx = _dedup_messages(messages, None)
        msg_idx = np.zeros((Bn,), np.int32)
        msg_idx[:n] = idx
        msg_u = htc.messages_to_u(msgs, DST)

        sxy, s_inf = curve.pack_g2([sig])
        if s_inf[0]:
            return False
        return bool(
            _aggregate_verify_device(
                jnp.asarray(pk_xy),
                jnp.asarray(pk_inf),
                jnp.asarray(msg_u),
                jnp.asarray(msg_idx),
                jnp.asarray(sxy[0]),
            )
        )

    def _verify_one(self, sig, pks, message, aggregate: bool) -> bool:
        if sig.is_infinity():
            return False
        return self.verify_signature_sets([(sig, pks, message)])


@jax.jit
def _aggregate_verify_device(pk_xy, pk_inf, msg_u, msg_idx, sig_xy):
    from . import htc

    sig_pt = curve.from_affine(fp2, sig_xy[0], sig_xy[1])
    sub_ok = g2_in_subgroup(sig_pt)

    msg_pts = htc.map_to_g2(msg_u)
    mx, my, minf = curve.to_affine(fp2, msg_pts)

    g1_x = jnp.concatenate([pk_xy[:, 0], fp.const(_NEG_G1[0])[None]], axis=0)
    g1_y = jnp.concatenate([pk_xy[:, 1], fp.const(_NEG_G1[1])[None]], axis=0)
    g1_inf = jnp.concatenate([pk_inf, jnp.zeros((1,), bool)], axis=0)
    sx, sy, sinf = curve.to_affine(fp2, sig_pt)
    g2_x = jnp.concatenate([jnp.take(mx, msg_idx, axis=0), sx[None]], axis=0)
    g2_y = jnp.concatenate([jnp.take(my, msg_idx, axis=0), sy[None]], axis=0)
    # a padding pk lane is already infinity on the G1 side; message side
    # needs no mask
    g2_inf = jnp.concatenate(
        [jnp.take(minf, msg_idx, axis=0), sinf[None]], axis=0
    )

    return pairing.multi_pairing_is_one(
        (g1_x, g1_y, g1_inf), (g2_x, g2_y, g2_inf)
    ) & sub_ok
