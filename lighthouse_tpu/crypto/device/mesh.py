"""Served data-parallel device mesh for the staged BLS verifier
(ISSUE 11, ROADMAP item 1).

``DP_SCALING.json`` certifies the dp-sharded ``verify_batch_raw_fn`` at
B=256 on a virtual mesh and ``MULTICHIP_r05.json`` passes at
n_devices=8 — but those are *dryruns*: the node itself was
single-device, and one chip at bench shapes tops out orders of
magnitude short of BASELINE.json's ≥50k sets/s target. This module is
the serving half: a process-global :class:`DeviceMesh` that the flush
planner, the scheduler, the compile service and the key table all
consult to spread *independent sub-batches* across chips (data-parallel
over signature sets — the same axis the reference spreads over rayon
cores, ``block_signature_verifier.rs:374-382``, and the axis the
committee batch-verification cost model says compounds with batching,
PAPERS.md arxiv 2302.00418).

Design choice — **shards are whole sub-batches, not sharded arrays**:
the flush planner already emits kind-homogeneous, independently
dispatchable sub-batches (ISSUE 6), so the dp axis is a *second packing
axis* ((dp_shard × rung) plans) rather than a ``jax.sharding`` spec.
Each shard's sub-batch packs, ships and verifies on its own device via
a thread-local dispatch context (:func:`dispatch_to` wraps the pack +
staged dispatch in ``jax.default_device``); no collective ever runs, so
**losing a chip degrades to fewer shards instead of killing the node**:
the planner just drops that shard-axis entry, and an in-flight
sub-batch on the lost device re-resolves on a failover shard with
verdict identity preserved (the re-resolution IS a full re-verify).

Health is first-class: per-chip sets/s over a rolling window, failure
counts, lost/healthy state and per-chip ``device_memory_bytes`` feed
the ``bls_device_shard_*`` families and the ``/lighthouse/health``
``mesh`` block; shard transitions journal ``shard_lost`` events.

Mesh discovery order (the client builder owns the lifecycle):
``ClientConfig.dp_devices`` > env ``LIGHTHOUSE_TPU_DP_DEVICES`` > all
local devices of the active backend. A virtual mesh on a single-host
box comes from ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
(set BEFORE jax initializes — the recipe tests/conftest.py and
``__graft_entry__.dryrun_multichip`` already use).

jax-free at import (the scheduler, planner and tools import this
module on boxes that must not initialize a backend); jax is imported
lazily, and a mesh built with injected placeholder devices
(``DeviceMesh(devices=[None, None])``) never touches jax at all — the
shape the jax-free scheduler/planner tests drive.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ...utils import flight_recorder, metrics

_ENV_ENABLED = "LIGHTHOUSE_TPU_DP_MESH"
_ENV_DEVICES = "LIGHTHOUSE_TPU_DP_DEVICES"

# rolling per-chip throughput window (seconds): short enough that a
# stalled chip's sets/s visibly decays on the health page, long enough
# to smooth flush burstiness
_RATE_WINDOW_S = 60.0


def env_enabled() -> bool:
    return os.environ.get(_ENV_ENABLED, "1") not in ("", "0")


def env_devices():
    """The operator's dp width knob: a positive integer, the string
    ``all``/``auto`` (discover every local device), or None when
    unset/malformed — the client builder then defaults to a 1-wide mesh
    (per-chip health without multi-chip compile load; widening the axis
    is an explicit operator decision)."""
    raw = os.environ.get(_ENV_DEVICES, "").strip().lower()
    if raw in ("all", "auto"):
        return "all"
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n > 0 else None


# ---------------------------------------------------------------------------
# Telemetry (documented in docs/OBSERVABILITY.md + docs/MULTICHIP.md,
# linted by tests/test_zgate4_metrics_lint.py)
# ---------------------------------------------------------------------------

_SHARD_SETS = metrics.counter_vec(
    "bls_device_shard_sets_total",
    "signature sets verified per mesh shard (data-parallel device "
    "index) — the per-chip half of the aggregate sets/s story",
    ("shard",),
)
_SHARD_SECONDS = metrics.histogram_vec(
    "bls_device_shard_verify_seconds",
    "per-shard dispatch wall time of one sharded sub-batch verify "
    "(pack + staged dispatch on that shard's device)",
    ("shard",),
)
_SHARD_FAILURES = metrics.counter_vec(
    "bls_device_shard_failures_total",
    "dispatch failures per mesh shard (exceptions raised by a sharded "
    "verify; a failure whose failover re-verify succeeds marks the "
    "shard lost — see the shard_lost journal kind)",
    ("shard",),
)
_SHARD_HEALTH = metrics.gauge_vec(
    "bls_device_shard_health",
    "1 = shard healthy (planner packs onto it), 0 = lost (dropped "
    "from the shard axis; the node keeps serving on the rest)",
    ("shard",),
)
_SHARD_MEMORY = metrics.gauge_vec(
    "bls_device_shard_memory_bytes",
    "device bytes in use per mesh shard (allocator stats where the "
    "backend reports them, else live-buffer sum attributed by device)",
    ("shard",),
)


class _ShardState:
    __slots__ = (
        "healthy", "failures", "sets_total", "dispatches",
        "last_dispatch_t", "window", "lost_error",
    )

    def __init__(self):
        self.healthy = True
        self.failures = 0
        self.sets_total = 0
        self.dispatches = 0
        self.last_dispatch_t: Optional[float] = None
        self.window: deque = deque()  # (t, n_sets)
        self.lost_error: Optional[str] = None


class DeviceMesh:
    """The served dp mesh (see module docstring). ``devices`` injects an
    explicit device list (jax Device objects, or ``None`` placeholders
    for jax-free tests); ``n_devices`` bounds discovery. Discovery —
    the only jax-touching path — happens in the constructor, so a mesh
    that exists is a mesh whose devices existed at build time."""

    def __init__(
        self,
        n_devices: Optional[int] = None,
        devices: Optional[Sequence] = None,
    ):
        if devices is None:
            import jax

            devices = list(jax.devices())
            if not devices:
                raise RuntimeError("no devices visible to jax")
            if n_devices is not None:
                if n_devices > len(devices):
                    raise RuntimeError(
                        f"dp_devices={n_devices} but only {len(devices)} "
                        f"devices visible (virtual mesh: set XLA_FLAGS="
                        f"--xla_force_host_platform_device_count=N before "
                        f"jax initializes)"
                    )
                devices = devices[:n_devices]
        self.devices = list(devices)
        if not self.devices:
            raise RuntimeError("DeviceMesh needs at least one device")
        self._lock = threading.Lock()
        self._t0 = time.monotonic()  # rate denominator floor (young mesh)
        self._shards: Dict[int, _ShardState] = {
            i: _ShardState() for i in range(len(self.devices))
        }
        for i in self._shards:
            _SHARD_HEALTH.with_labels(str(i)).set(1)

    # -- topology ---------------------------------------------------------

    def __len__(self) -> int:
        return len(self.devices)

    def all_shards(self) -> List[int]:
        return sorted(self._shards)

    def healthy_shards(self) -> List[int]:
        with self._lock:
            return sorted(i for i, s in self._shards.items() if s.healthy)

    def is_healthy(self, shard: int) -> bool:
        with self._lock:
            st = self._shards.get(shard)
            return st is not None and st.healthy

    def primary_shard(self) -> Optional[int]:
        """The default dispatch target when no shard context is set:
        the lowest healthy shard (None when every chip is lost — the
        caller then dispatches on the process default device and/or the
        CPU fallback; the node still answers)."""
        healthy = self.healthy_shards()
        return healthy[0] if healthy else None

    def failover_shard(self, failed: int) -> Optional[int]:
        """Where an in-flight sub-batch re-resolves after ``failed``
        raised: the lowest healthy shard that is not the failed one."""
        for i in self.healthy_shards():
            if i != failed:
                return i
        return None

    def device_for(self, shard: int):
        """The device object behind a shard id (None for placeholder
        devices — the dispatch context then skips ``default_device``)."""
        try:
            return self.devices[shard]
        except (IndexError, TypeError):
            return None

    # -- dispatch accounting ----------------------------------------------

    def note_dispatch(self, shard: int, n_sets: int, seconds: float) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._shards.get(shard)
            if st is None:
                return
            st.sets_total += int(n_sets)
            st.dispatches += 1
            st.last_dispatch_t = now
            st.window.append((now, int(n_sets)))
            while st.window and now - st.window[0][0] > _RATE_WINDOW_S:
                st.window.popleft()
        _SHARD_SETS.with_labels(str(shard)).inc(int(n_sets))
        _SHARD_SECONDS.with_labels(str(shard)).observe(float(seconds))

    def note_failure(self, shard: int, error: BaseException,
                     lost: bool = True) -> bool:
        """One dispatch on ``shard`` raised. ``lost=True`` (a failover
        re-verify of the same work succeeded, so the work was fine and
        the chip is the problem) drops the shard from the axis; returns
        True exactly on the healthy→lost transition (the caller's cue
        that a ``shard_lost`` event was journaled)."""
        transition = False
        with self._lock:
            st = self._shards.get(shard)
            if st is None:
                return False
            st.failures += 1
            failures = st.failures
            if lost and st.healthy:
                st.healthy = False
                st.lost_error = repr(error)[:200]
                transition = True
        _SHARD_FAILURES.with_labels(str(shard)).inc()
        if transition:
            _SHARD_HEALTH.with_labels(str(shard)).set(0)
            flight_recorder.record(
                "shard_lost",
                shard=shard,
                failures=failures,
                healthy_remaining=len(self.healthy_shards()),
                error=repr(error)[:200],
            )
            from ...utils import logging as tlog

            tlog.log(
                "warn", "mesh shard lost — degrading to fewer dp shards",
                shard=shard, error=repr(error)[:120],
            )
        return transition

    def restore_shard(self, shard: int) -> None:
        """Operator action (or test hook): put a repaired chip back on
        the shard axis."""
        with self._lock:
            st = self._shards.get(shard)
            if st is None:
                return
            st.healthy = True
            st.lost_error = None
        _SHARD_HEALTH.with_labels(str(shard)).set(1)

    # -- introspection ----------------------------------------------------

    def _rate(self, st: _ShardState, now: float) -> float:
        """Sets/s over the ROLLING window: the denominator is the
        window length (capped by the mesh's age while it is younger
        than one window) — dividing by the span since the window's own
        first sample would let one burst after an idle gap read as
        thousands of sets/s on the health page."""
        live = [(t, n) for (t, n) in st.window if now - t <= _RATE_WINDOW_S]
        if not live:
            return 0.0
        span = min(_RATE_WINDOW_S, max(1.0, now - self._t0))
        return sum(n for _t, n in live) / span

    def memory_by_shard(self) -> Dict[int, Optional[int]]:
        """Per-chip device bytes in use (allocator stats where the
        platform reports them; None where it does not — null-safe, and
        never the trigger of a backend init: placeholder devices report
        None)."""
        out: Dict[int, Optional[int]] = {}
        for i, dev in enumerate(self.devices):
            val = None
            try:
                stats = dev.memory_stats() if dev is not None else None
                if stats and "bytes_in_use" in stats:
                    val = int(stats["bytes_in_use"])
            except Exception:
                val = None
            out[i] = val
            if val is not None:
                _SHARD_MEMORY.with_labels(str(i)).set(val)
        return out

    def status(self) -> dict:
        """The /lighthouse/health ``mesh`` block: topology, per-chip
        health/throughput/memory, and the aggregate sets/s the dp axis
        is currently delivering."""
        now = time.monotonic()
        mem = self.memory_by_shard()
        # per-chip bubble ratio (pipeline profiler, ISSUE 12): the
        # idle/(busy+idle) share of this chip's staged dispatch timeline
        # — None before its first dispatch. Lazy import keeps the mesh's
        # import surface minimal (both modules are jax-free).
        from ...utils import pipeline_profiler

        with self._lock:
            chips = []
            agg_rate = 0.0
            for i in sorted(self._shards):
                st = self._shards[i]
                rate = self._rate(st, now)
                if st.healthy:
                    agg_rate += rate
                dev = self.devices[i] if i < len(self.devices) else None
                chips.append({
                    "shard": i,
                    "device": str(dev) if dev is not None else None,
                    "platform": getattr(dev, "platform", None),
                    "healthy": st.healthy,
                    "failures": st.failures,
                    "sets_total": st.sets_total,
                    "dispatches": st.dispatches,
                    "sets_per_sec": round(rate, 2),
                    "device_memory_bytes": mem.get(i),
                    "bubble_ratio": pipeline_profiler.shard_bubble_ratio(i),
                    "lost_error": st.lost_error,
                })
            healthy = [i for i, s in self._shards.items() if s.healthy]
        return {
            "n_devices": len(self.devices),
            "healthy_shards": sorted(healthy),
            "lost_shards": sorted(set(self._shards) - set(healthy)),
            "aggregate_sets_per_sec": round(agg_rate, 2),
            "rate_window_s": _RATE_WINDOW_S,
            "chips": chips,
        }


# ---------------------------------------------------------------------------
# Thread-local dispatch context (the seam the scheduler wraps around a
# sharded sub-batch so the packers + staged dispatch land on that
# shard's device without plumbing a handle through every call)
# ---------------------------------------------------------------------------

_tls = threading.local()


def current_shard() -> Optional[int]:
    """The shard this thread is dispatching for (None outside any
    :func:`dispatch_to` scope — dispatch then targets the mesh's
    primary shard, or the process default device without a mesh)."""
    return getattr(_tls, "shard", None)


class dispatch_to:
    """Context manager scoping this thread's dispatches to ``shard``'s
    device: sets the thread-local shard AND (when the mesh's device
    object is real) makes it jax's default device, so ``jnp.asarray``
    in the packers and the jitted staged dispatch both land there.
    Placeholder devices (jax-free tests) set only the thread-local."""

    def __init__(self, shard: Optional[int]):
        self.shard = shard
        self._prev = None
        self._dev_cm = None

    def __enter__(self):
        self._prev = getattr(_tls, "shard", None)
        # device context FIRST: if default_device's enter raises (stale
        # device object, backend teardown) the thread-local must stay
        # untouched — a leaked shard would pin every later unscoped
        # dispatch on this long-lived thread to the wrong chip
        if self.shard is not None:
            mesh = get_active_mesh()
            dev = mesh.device_for(self.shard) if mesh is not None else None
            if dev is not None:
                import jax

                self._dev_cm = jax.default_device(dev)
                self._dev_cm.__enter__()
        _tls.shard = self.shard
        return self

    def __exit__(self, *exc):
        try:
            if self._dev_cm is not None:
                self._dev_cm.__exit__(*exc)
        finally:
            self._dev_cm = None
            _tls.shard = self._prev
        return False


# ---------------------------------------------------------------------------
# Process-global mesh (the seam the scheduler, compile service, key
# table and TpuBackend reach; the client builder owns the lifecycle)
# ---------------------------------------------------------------------------

_mesh_lock = threading.Lock()
_mesh: Optional[DeviceMesh] = None


def set_mesh(mesh: Optional[DeviceMesh]) -> None:
    global _mesh
    with _mesh_lock:
        _mesh = mesh


def clear_mesh(mesh: Optional[DeviceMesh] = None) -> None:
    """Detach the global mesh (only if it still IS ``mesh`` when one is
    given — a racing rebuild must not lose its fresh mesh)."""
    global _mesh
    with _mesh_lock:
        if mesh is None or _mesh is mesh:
            _mesh = None


def get_active_mesh() -> Optional[DeviceMesh]:
    """The attached mesh; None when nothing is attached (single-device
    behavior everywhere)."""
    return _mesh
