"""Served data-parallel device mesh for the staged BLS verifier
(ISSUE 11, ROADMAP item 1).

``DP_SCALING.json`` certifies the dp-sharded ``verify_batch_raw_fn`` at
B=256 on a virtual mesh and ``MULTICHIP_r05.json`` passes at
n_devices=8 — but those are *dryruns*: the node itself was
single-device, and one chip at bench shapes tops out orders of
magnitude short of BASELINE.json's ≥50k sets/s target. This module is
the serving half: a process-global :class:`DeviceMesh` that the flush
planner, the scheduler, the compile service and the key table all
consult to spread *independent sub-batches* across chips (data-parallel
over signature sets — the same axis the reference spreads over rayon
cores, ``block_signature_verifier.rs:374-382``, and the axis the
committee batch-verification cost model says compounds with batching,
PAPERS.md arxiv 2302.00418).

Design choice — **shards are whole sub-batches, not sharded arrays**:
the flush planner already emits kind-homogeneous, independently
dispatchable sub-batches (ISSUE 6), so the dp axis is a *second packing
axis* ((dp_shard × rung) plans) rather than a ``jax.sharding`` spec.
Each shard's sub-batch packs, ships and verifies on its own device via
a thread-local dispatch context (:func:`dispatch_to` wraps the pack +
staged dispatch in ``jax.default_device``); no collective ever runs, so
**losing a chip degrades to fewer shards instead of killing the node**:
the planner just drops that shard-axis entry, and an in-flight
sub-batch on the lost device re-resolves on a failover shard with
verdict identity preserved (the re-resolution IS a full re-verify).

Health is first-class: per-chip sets/s over a rolling window, failure
counts, lost/healthy state and per-chip ``device_memory_bytes`` feed
the ``bls_device_shard_*`` families and the ``/lighthouse/health``
``mesh`` block; shard transitions journal ``shard_lost`` events.

Self-healing (ISSUE 13): a lost shard is not gone forever — it enters
**probation**: a background recovery worker (:meth:`DeviceMesh.start_
recovery`, the client builder owns the lifecycle) probes it on a
capped exponential backoff with jitter (the ``utils/monitoring.py``
retry shape: ``base * 2**(attempt-1)`` capped, ``* U[0.5, 1.0]`` so a
fleet never probes in lockstep). One probe = canary verify on the chip
(a tiny device computation, or an injected ``probe_fn`` — the replay
driver probes through the real verify seam) → best-effort re-warm of
the compile plan's rungs on that device (warm rungs are no-ops: the
executables survived the loss, so the certified recovery pays ZERO
fresh staged compiles) → key-table replica re-sync (a failure here
fails the probe — a shard must never re-admit with a stale replica) →
re-admission to the planner's shard axis. Every transition journals
(``shard_probation`` per entry/failed probe with the next backoff,
``shard_recovered`` on re-admission) and the ``mesh`` health block
carries probation state + recovery counters. The reference's peer
manager scores, bans AND un-bans; this is that loop for chips.

Mesh discovery order (the client builder owns the lifecycle):
``ClientConfig.dp_devices`` > env ``LIGHTHOUSE_TPU_DP_DEVICES`` > all
local devices of the active backend. A virtual mesh on a single-host
box comes from ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
(set BEFORE jax initializes — the recipe tests/conftest.py and
``__graft_entry__.dryrun_multichip`` already use).

jax-free at import (the scheduler, planner and tools import this
module on boxes that must not initialize a backend); jax is imported
lazily, and a mesh built with injected placeholder devices
(``DeviceMesh(devices=[None, None])``) never touches jax at all — the
shape the jax-free scheduler/planner tests drive.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ...utils import flight_recorder, metrics

_ENV_ENABLED = "LIGHTHOUSE_TPU_DP_MESH"
_ENV_DEVICES = "LIGHTHOUSE_TPU_DP_DEVICES"
_ENV_RECOVERY = "LIGHTHOUSE_TPU_MESH_RECOVERY"
_ENV_PROBE_BASE = "LIGHTHOUSE_TPU_MESH_PROBE_BASE_S"
_ENV_PROBE_MAX = "LIGHTHOUSE_TPU_MESH_PROBE_MAX_S"

DEFAULT_PROBE_BASE_S = 1.0
DEFAULT_PROBE_MAX_S = 30.0

# rolling per-chip throughput window (seconds): short enough that a
# stalled chip's sets/s visibly decays on the health page, long enough
# to smooth flush burstiness
_RATE_WINDOW_S = 60.0


def env_enabled() -> bool:
    return os.environ.get(_ENV_ENABLED, "1") not in ("", "0")


def recovery_env_enabled() -> bool:
    """Kill switch for the self-healing worker (ISSUE 13): default on —
    a node that can recover a chip should; 0 pins the pre-recovery
    one-way degradation."""
    return os.environ.get(_ENV_RECOVERY, "1") not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def env_devices():
    """The operator's dp width knob: a positive integer, the string
    ``all``/``auto`` (discover every local device), or None when
    unset/malformed — the client builder then defaults to a 1-wide mesh
    (per-chip health without multi-chip compile load; widening the axis
    is an explicit operator decision)."""
    raw = os.environ.get(_ENV_DEVICES, "").strip().lower()
    if raw in ("all", "auto"):
        return "all"
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n > 0 else None


# ---------------------------------------------------------------------------
# Telemetry (documented in docs/OBSERVABILITY.md + docs/MULTICHIP.md,
# linted by tests/test_zgate4_metrics_lint.py)
# ---------------------------------------------------------------------------

_SHARD_SETS = metrics.counter_vec(
    "bls_device_shard_sets_total",
    "signature sets verified per mesh shard (data-parallel device "
    "index) — the per-chip half of the aggregate sets/s story",
    ("shard",),
)
_SHARD_SECONDS = metrics.histogram_vec(
    "bls_device_shard_verify_seconds",
    "per-shard dispatch wall time of one sharded sub-batch verify "
    "(pack + staged dispatch on that shard's device)",
    ("shard",),
)
_SHARD_FAILURES = metrics.counter_vec(
    "bls_device_shard_failures_total",
    "dispatch failures per mesh shard (exceptions raised by a sharded "
    "verify; a failure whose failover re-verify succeeds marks the "
    "shard lost — see the shard_lost journal kind)",
    ("shard",),
)
_SHARD_HEALTH = metrics.gauge_vec(
    "bls_device_shard_health",
    "1 = shard healthy (planner packs onto it), 0 = lost (dropped "
    "from the shard axis; the node keeps serving on the rest)",
    ("shard",),
)
_SHARD_MEMORY = metrics.gauge_vec(
    "bls_device_shard_memory_bytes",
    "device bytes in use per mesh shard (allocator stats where the "
    "backend reports them, else live-buffer sum attributed by device)",
    ("shard",),
)
_SHARD_PROBATION = metrics.gauge_vec(
    "bls_device_shard_probation",
    "1 = shard is in probation (lost from the axis, the recovery "
    "worker is probing it on backoff), 0 = not (healthy, or lost with "
    "recovery disabled)",
    ("shard",),
)
_SHARD_PROBES = metrics.counter_vec(
    "bls_device_shard_probes_total",
    "recovery probes run against a probation shard, by outcome (ok = "
    "canary + re-warm + key-table re-sync all passed and the shard "
    "was re-admitted; error = the probe failed and the next one backs "
    "off further)",
    ("shard", "outcome"),
)
_SHARD_RECOVERIES = metrics.counter_vec(
    "bls_device_shard_recoveries_total",
    "probation shards re-admitted to the planner's shard axis by the "
    "recovery worker (see the shard_recovered journal kind)",
    ("shard",),
)


class _ShardState:
    __slots__ = (
        "healthy", "failures", "sets_total", "dispatches",
        "last_dispatch_t", "window", "lost_error",
        "probation", "probe_attempts", "next_probe_t", "lost_at",
        "recovered_total",
    )

    def __init__(self):
        self.healthy = True
        self.failures = 0
        self.sets_total = 0
        self.dispatches = 0
        self.last_dispatch_t: Optional[float] = None
        self.window: deque = deque()  # (t, n_sets)
        self.lost_error: Optional[str] = None
        # probation/recovery (ISSUE 13): set on the healthy->lost
        # transition, cleared on re-admission (or operator restore)
        self.probation = False
        self.probe_attempts = 0
        self.next_probe_t: Optional[float] = None
        self.lost_at: Optional[float] = None
        self.recovered_total = 0


class DeviceMesh:
    """The served dp mesh (see module docstring). ``devices`` injects an
    explicit device list (jax Device objects, or ``None`` placeholders
    for jax-free tests); ``n_devices`` bounds discovery. Discovery —
    the only jax-touching path — happens in the constructor, so a mesh
    that exists is a mesh whose devices existed at build time."""

    def __init__(
        self,
        n_devices: Optional[int] = None,
        devices: Optional[Sequence] = None,
        probe_fn=None,
        probe_base_s: Optional[float] = None,
        probe_max_s: Optional[float] = None,
    ):
        if devices is None:
            import jax

            devices = list(jax.devices())
            if not devices:
                raise RuntimeError("no devices visible to jax")
            if n_devices is not None:
                if n_devices > len(devices):
                    raise RuntimeError(
                        f"dp_devices={n_devices} but only {len(devices)} "
                        f"devices visible (virtual mesh: set XLA_FLAGS="
                        f"--xla_force_host_platform_device_count=N before "
                        f"jax initializes)"
                    )
                devices = devices[:n_devices]
        self.devices = list(devices)
        if not self.devices:
            raise RuntimeError("DeviceMesh needs at least one device")
        self._lock = threading.Lock()
        self._t0 = time.monotonic()  # rate denominator floor (young mesh)
        self._shards: Dict[int, _ShardState] = {
            i: _ShardState() for i in range(len(self.devices))
        }
        for i in self._shards:
            _SHARD_HEALTH.with_labels(str(i)).set(1)
        # recovery worker (ISSUE 13): idle until start_recovery(); the
        # probe callable is injectable so chaos tooling and jax-free
        # tests can probe through the real verify seam
        self._probe_fn = probe_fn
        self._probe_base_s = (
            float(probe_base_s)
            if probe_base_s is not None
            else _env_float(_ENV_PROBE_BASE, DEFAULT_PROBE_BASE_S)
        )
        self._probe_max_s = (
            float(probe_max_s)
            if probe_max_s is not None
            else _env_float(_ENV_PROBE_MAX, DEFAULT_PROBE_MAX_S)
        )
        self._rec_cv = threading.Condition()
        self._rec_stop = False
        self._rec_thread: Optional[threading.Thread] = None
        self._recoveries_total = 0

    # -- topology ---------------------------------------------------------

    def __len__(self) -> int:
        return len(self.devices)

    def all_shards(self) -> List[int]:
        return sorted(self._shards)

    def healthy_shards(self) -> List[int]:
        with self._lock:
            return sorted(i for i, s in self._shards.items() if s.healthy)

    def is_healthy(self, shard: int) -> bool:
        with self._lock:
            st = self._shards.get(shard)
            return st is not None and st.healthy

    def is_probing(self, shard: int) -> bool:
        """True while ``shard`` is in probation — lost from the axis
        but under active recovery. The compile service treats a
        probing shard's rungs as live work (the re-warm half of a
        probe), unlike a plainly lost shard's."""
        with self._lock:
            st = self._shards.get(shard)
            return st is not None and st.probation

    def probing_shards(self) -> List[int]:
        with self._lock:
            return sorted(
                i for i, s in self._shards.items() if s.probation
            )

    def primary_shard(self) -> Optional[int]:
        """The default dispatch target when no shard context is set:
        the lowest healthy shard (None when every chip is lost — the
        caller then dispatches on the process default device and/or the
        CPU fallback; the node still answers)."""
        healthy = self.healthy_shards()
        return healthy[0] if healthy else None

    def failover_shard(self, failed: int) -> Optional[int]:
        """Where an in-flight sub-batch re-resolves after ``failed``
        raised: the lowest healthy shard that is not the failed one."""
        for i in self.healthy_shards():
            if i != failed:
                return i
        return None

    def device_for(self, shard: int):
        """The device object behind a shard id (None for placeholder
        devices — the dispatch context then skips ``default_device``)."""
        try:
            return self.devices[shard]
        except (IndexError, TypeError):
            return None

    # -- dispatch accounting ----------------------------------------------

    def note_dispatch(self, shard: int, n_sets: int, seconds: float) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._shards.get(shard)
            if st is None:
                return
            st.sets_total += int(n_sets)
            st.dispatches += 1
            st.last_dispatch_t = now
            st.window.append((now, int(n_sets)))
            while st.window and now - st.window[0][0] > _RATE_WINDOW_S:
                st.window.popleft()
        _SHARD_SETS.with_labels(str(shard)).inc(int(n_sets))
        _SHARD_SECONDS.with_labels(str(shard)).observe(float(seconds))

    def note_failure(self, shard: int, error: BaseException,
                     lost: bool = True) -> bool:
        """One dispatch on ``shard`` raised. ``lost=True`` (a failover
        re-verify of the same work succeeded, so the work was fine and
        the chip is the problem) drops the shard from the axis; returns
        True exactly on the healthy→lost transition (the caller's cue
        that a ``shard_lost`` event was journaled)."""
        transition = False
        with self._lock:
            st = self._shards.get(shard)
            if st is None:
                return False
            st.failures += 1
            failures = st.failures
            if lost and st.healthy:
                st.healthy = False
                st.lost_error = repr(error)[:200]
                transition = True
        _SHARD_FAILURES.with_labels(str(shard)).inc()
        if transition:
            _SHARD_HEALTH.with_labels(str(shard)).set(0)
            flight_recorder.record(
                "shard_lost",
                shard=shard,
                failures=failures,
                healthy_remaining=len(self.healthy_shards()),
                error=repr(error)[:200],
            )
            from ...utils import logging as tlog

            tlog.log(
                "warn", "mesh shard lost — degrading to fewer dp shards",
                shard=shard, error=repr(error)[:120],
            )
            # a lost chip enters probation immediately (the state is
            # set whether or not a recovery worker runs: the worker
            # reads it, tooling and the health page report it)
            self._enter_probation(shard, error)
        return transition

    def restore_shard(self, shard: int) -> None:
        """Operator action (or test hook): put a repaired chip back on
        the shard axis. Also the recovery worker's re-admission commit
        — probation state clears with the restore."""
        with self._lock:
            st = self._shards.get(shard)
            if st is None:
                return
            st.healthy = True
            st.lost_error = None
            st.probation = False
            st.probe_attempts = 0
            st.next_probe_t = None
        _SHARD_HEALTH.with_labels(str(shard)).set(1)
        _SHARD_PROBATION.with_labels(str(shard)).set(0)

    # -- probation / recovery (ISSUE 13) ----------------------------------

    def _backoff(self, attempt: int) -> float:
        """Capped exponential backoff with jitter — the
        ``utils/monitoring.py`` retry shape: ``base * 2**(attempt-1)``
        capped at the max, times ``U[0.5, 1.0]`` so a fleet of nodes
        losing chips to one shared cause never probes in lockstep."""
        backoff = min(
            self._probe_max_s,
            self._probe_base_s * (2.0 ** max(0, attempt - 1)),
        )
        return backoff * random.uniform(0.5, 1.0)

    def _enter_probation(self, shard: int, error: BaseException) -> None:
        delay = self._backoff(1)
        now = time.monotonic()
        with self._lock:
            st = self._shards.get(shard)
            if st is None or st.probation:
                return
            st.probation = True
            st.probe_attempts = 0
            st.lost_at = now
            st.next_probe_t = now + delay
        _SHARD_PROBATION.with_labels(str(shard)).set(1)
        flight_recorder.record(
            "shard_probation",
            shard=shard,
            attempt=0,
            next_probe_s=round(delay, 3),
            error=repr(error)[:200],
        )
        with self._rec_cv:
            self._rec_cv.notify_all()

    def start_recovery(
        self,
        probe_fn=None,
        base_backoff_s: Optional[float] = None,
        max_backoff_s: Optional[float] = None,
    ) -> "DeviceMesh":
        """Start the background recovery worker (idempotent). The
        worker probes probation shards on their backoff schedule; one
        passing probe (canary + re-warm + key-table re-sync) re-admits
        the shard to the planner's axis. Parameters override the ctor/
        env config — chaos tooling shortens the backoff and injects a
        probe through the real verify seam."""
        with self._rec_cv:
            if probe_fn is not None:
                self._probe_fn = probe_fn
            if base_backoff_s is not None:
                self._probe_base_s = float(base_backoff_s)
            if max_backoff_s is not None:
                self._probe_max_s = float(max_backoff_s)
            if self._rec_thread is not None and self._rec_thread.is_alive():
                return self
            self._rec_stop = False
            self._rec_thread = threading.Thread(
                target=self._recovery_loop, name="mesh-recovery",
                daemon=True,
            )
            self._rec_thread.start()
        return self

    def stop_recovery(self, timeout: float = 10.0) -> None:
        """Stop the recovery worker. A probe in flight gets ``timeout``
        to finish; past that the (daemon) thread is abandoned — the
        identity check in the loop makes a later ``start_recovery``
        safe, and ``Client.stop()`` during an active probe never
        wedges on it (pinned by test)."""
        with self._rec_cv:
            self._rec_stop = True
            self._rec_cv.notify_all()
        t = self._rec_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        self._rec_thread = None

    def recovery_running(self) -> bool:
        t = self._rec_thread
        return t is not None and t.is_alive() and not self._rec_stop

    def _due_probes(self):
        """(due shard list, seconds until the earliest pending probe or
        None) — called under no lock; takes the state lock itself."""
        now = time.monotonic()
        due: List[int] = []
        nxt: Optional[float] = None
        with self._lock:
            for i, st in self._shards.items():
                if not st.probation or st.next_probe_t is None:
                    continue
                if st.next_probe_t <= now:
                    due.append(i)
                elif nxt is None or st.next_probe_t < nxt:
                    nxt = st.next_probe_t
        wait = None if nxt is None else max(0.01, nxt - now)
        return sorted(due), wait

    def _recovery_loop(self) -> None:
        # identity check: stop_recovery gives up joining after its
        # timeout (a probe cannot be cancelled) and a later
        # start_recovery spawns a fresh worker — a superseded thread
        # must exit instead of double-probing
        me = threading.current_thread()
        while True:
            with self._rec_cv:
                if self._rec_stop or self._rec_thread is not me:
                    return
                due, wait = self._due_probes()
                if not due:
                    self._rec_cv.wait(wait)
                    continue
            for shard in due:
                with self._rec_cv:
                    if self._rec_stop or self._rec_thread is not me:
                        return
                self._probe_shard(shard)

    def _default_canary(self, shard: int) -> bool:
        """A tiny device computation on the probed chip — proves the
        chip executes programs again. Placeholder devices (jax-free
        meshes) pass trivially: there is no hardware to probe, and the
        injected ``probe_fn`` is the scheduling-layer seam."""
        if self.device_for(shard) is None:
            return True
        import jax
        import jax.numpy as jnp

        x = jnp.arange(8, dtype=jnp.int32)  # lands on the dispatch_to device
        return int(jax.block_until_ready(x.sum())) == 28

    def _rewarm_shard(self, shard: int) -> int:
        """Best-effort: re-queue the compile plan's rungs for this
        device. Rungs whose executables survived the loss are warm in
        the registry and the worker skips them instantly — the
        certified recovery pays ZERO fresh staged compiles; genuinely
        cold rungs compile in the background and the per-shard routing
        sheds around them meanwhile (a cold shard never stalls a
        flush). Returns the number of rungs already warm."""
        try:
            from ...compile_service import service as _csvc

            svc = _csvc.get_active_service()
            if svc is None:
                return 0
            warm = len(svc.warm_rungs_active(device=shard))
            for rung in svc.plan:
                svc.request(*rung, device=shard)
            return warm
        except Exception:
            return 0

    def _resync_key_table(self, shard: int) -> None:
        """Re-sync the device key table before re-admission (raises on
        failure — a shard must never re-join with a replica behind the
        host cache). The table mirrors every sync onto EVERY replica,
        so one full catch-up sync covers whatever deltas failed while
        the chip was down."""
        try:
            from . import key_table as _kt

            tbl = _kt.get_table()
        except Exception:
            return
        if tbl is None:
            return
        tbl.sync(reason="recovery")

    def _probe_shard(self, shard: int) -> None:
        t0 = time.monotonic()
        err: Optional[BaseException] = None
        ok = False
        warm_rungs = 0
        try:
            # the probe runs inside the shard's dispatch scope so an
            # injected probe_fn exercises the REAL per-shard seam (the
            # canary lands on the probed chip, and chaos wrappers keyed
            # on current_shard() see the probe)
            with dispatch_to(shard):
                probe = self._probe_fn or self._default_canary
                ok = bool(probe(shard))
            if ok:
                warm_rungs = self._rewarm_shard(shard)
                self._resync_key_table(shard)
        except BaseException as e:  # noqa: BLE001 — a probe must never kill the worker
            err, ok = e, False
        if ok:
            with self._lock:
                st = self._shards.get(shard)
                if st is None or not st.probation:
                    return  # operator restored (or shard vanished) meanwhile
                probes = st.probe_attempts + 1
                down_s = t0 - (st.lost_at or t0)
                st.recovered_total += 1
                self._recoveries_total += 1
            _SHARD_PROBES.with_labels(str(shard), "ok").inc()
            _SHARD_RECOVERIES.with_labels(str(shard)).inc()
            self.restore_shard(shard)
            flight_recorder.record(
                "shard_recovered",
                shard=shard,
                probes=probes,
                down_s=round(down_s, 3),
                warm_rungs=warm_rungs,
                healthy_total=len(self.healthy_shards()),
            )
            from ...utils import logging as tlog

            tlog.log(
                "warn", "mesh shard recovered — re-admitted to the dp axis",
                shard=shard, probes=probes, down_s=round(down_s, 3),
            )
        else:
            with self._lock:
                st = self._shards.get(shard)
                if st is None or not st.probation:
                    return
                st.probe_attempts += 1
                attempt = st.probe_attempts
                delay = self._backoff(attempt + 1)
                st.next_probe_t = time.monotonic() + delay
            _SHARD_PROBES.with_labels(str(shard), "error").inc()
            flight_recorder.record(
                "shard_probation",
                shard=shard,
                attempt=attempt,
                next_probe_s=round(delay, 3),
                error=None if err is None else repr(err)[:200],
            )

    # -- introspection ----------------------------------------------------

    def _rate(self, st: _ShardState, now: float) -> float:
        """Sets/s over the ROLLING window: the denominator is the
        window length (capped by the mesh's age while it is younger
        than one window) — dividing by the span since the window's own
        first sample would let one burst after an idle gap read as
        thousands of sets/s on the health page."""
        live = [(t, n) for (t, n) in st.window if now - t <= _RATE_WINDOW_S]
        if not live:
            return 0.0
        span = min(_RATE_WINDOW_S, max(1.0, now - self._t0))
        return sum(n for _t, n in live) / span

    def memory_by_shard(self) -> Dict[int, Optional[int]]:
        """Per-chip device bytes in use (allocator stats where the
        platform reports them; None where it does not — null-safe, and
        never the trigger of a backend init: placeholder devices report
        None)."""
        out: Dict[int, Optional[int]] = {}
        for i, dev in enumerate(self.devices):
            val = None
            try:
                stats = dev.memory_stats() if dev is not None else None
                if stats and "bytes_in_use" in stats:
                    val = int(stats["bytes_in_use"])
            except Exception:
                val = None
            out[i] = val
            if val is not None:
                _SHARD_MEMORY.with_labels(str(i)).set(val)
        return out

    def status(self) -> dict:
        """The /lighthouse/health ``mesh`` block: topology, per-chip
        health/throughput/memory, and the aggregate sets/s the dp axis
        is currently delivering."""
        now = time.monotonic()
        mem = self.memory_by_shard()
        # per-chip bubble ratio (pipeline profiler, ISSUE 12): the
        # idle/(busy+idle) share of this chip's staged dispatch timeline
        # — None before its first dispatch. Lazy import keeps the mesh's
        # import surface minimal (both modules are jax-free).
        from ...utils import pipeline_profiler

        mono_now = time.monotonic()
        with self._lock:
            chips = []
            agg_rate = 0.0
            probation = []
            recoveries = self._recoveries_total
            for i in sorted(self._shards):
                st = self._shards[i]
                rate = self._rate(st, now)
                if st.healthy:
                    agg_rate += rate
                if st.probation:
                    probation.append(i)
                dev = self.devices[i] if i < len(self.devices) else None
                chips.append({
                    "shard": i,
                    "device": str(dev) if dev is not None else None,
                    "platform": getattr(dev, "platform", None),
                    "healthy": st.healthy,
                    "failures": st.failures,
                    "sets_total": st.sets_total,
                    "dispatches": st.dispatches,
                    "sets_per_sec": round(rate, 2),
                    "device_memory_bytes": mem.get(i),
                    "bubble_ratio": pipeline_profiler.shard_bubble_ratio(i),
                    "lost_error": st.lost_error,
                    # probation/recovery (ISSUE 13)
                    "probation": st.probation,
                    "probe_attempts": st.probe_attempts,
                    "next_probe_in_s": (
                        round(max(0.0, st.next_probe_t - mono_now), 3)
                        if st.probation and st.next_probe_t is not None
                        else None
                    ),
                    "recovered_total": st.recovered_total,
                })
            healthy = [i for i, s in self._shards.items() if s.healthy]
        return {
            "n_devices": len(self.devices),
            "healthy_shards": sorted(healthy),
            "lost_shards": sorted(set(self._shards) - set(healthy)),
            "probation_shards": probation,
            "recoveries_total": recoveries,
            "recovery_running": self.recovery_running(),
            "probe_base_s": self._probe_base_s,
            "probe_max_s": self._probe_max_s,
            "aggregate_sets_per_sec": round(agg_rate, 2),
            "rate_window_s": _RATE_WINDOW_S,
            "chips": chips,
        }


# ---------------------------------------------------------------------------
# Thread-local dispatch context (the seam the scheduler wraps around a
# sharded sub-batch so the packers + staged dispatch land on that
# shard's device without plumbing a handle through every call)
# ---------------------------------------------------------------------------

_tls = threading.local()


def current_shard() -> Optional[int]:
    """The shard this thread is dispatching for (None outside any
    :func:`dispatch_to` scope — dispatch then targets the mesh's
    primary shard, or the process default device without a mesh)."""
    return getattr(_tls, "shard", None)


class dispatch_to:
    """Context manager scoping this thread's dispatches to ``shard``'s
    device: sets the thread-local shard AND (when the mesh's device
    object is real) makes it jax's default device, so ``jnp.asarray``
    in the packers and the jitted staged dispatch both land there.
    Placeholder devices (jax-free tests) set only the thread-local."""

    def __init__(self, shard: Optional[int]):
        self.shard = shard
        self._prev = None
        self._dev_cm = None

    def __enter__(self):
        self._prev = getattr(_tls, "shard", None)
        # device context FIRST: if default_device's enter raises (stale
        # device object, backend teardown) the thread-local must stay
        # untouched — a leaked shard would pin every later unscoped
        # dispatch on this long-lived thread to the wrong chip
        if self.shard is not None:
            mesh = get_active_mesh()
            dev = mesh.device_for(self.shard) if mesh is not None else None
            if dev is not None:
                import jax

                self._dev_cm = jax.default_device(dev)
                self._dev_cm.__enter__()
        _tls.shard = self.shard
        return self

    def __exit__(self, *exc):
        try:
            if self._dev_cm is not None:
                self._dev_cm.__exit__(*exc)
        finally:
            self._dev_cm = None
            _tls.shard = self._prev
        return False


# ---------------------------------------------------------------------------
# Process-global mesh (the seam the scheduler, compile service, key
# table and TpuBackend reach; the client builder owns the lifecycle)
# ---------------------------------------------------------------------------

_mesh_lock = threading.Lock()
_mesh: Optional[DeviceMesh] = None


def set_mesh(mesh: Optional[DeviceMesh]) -> None:
    global _mesh
    with _mesh_lock:
        _mesh = mesh


def clear_mesh(mesh: Optional[DeviceMesh] = None) -> None:
    """Detach the global mesh (only if it still IS ``mesh`` when one is
    given — a racing rebuild must not lose its fresh mesh)."""
    global _mesh
    with _mesh_lock:
        if mesh is None or _mesh is mesh:
            _mesh = None


def get_active_mesh() -> Optional[DeviceMesh]:
    """The attached mesh; None when nothing is attached (single-device
    behavior everywhere)."""
    return _mesh


def healthy_shard_count() -> int:
    """Healthy shards the attached mesh is serving on right now — the
    shard-count feed for the capacity/headroom estimator (ISSUE 14,
    ``utils/timeseries.py``): read live, not from the dp gauge, which
    only updates at flush time and would lag a chip loss. 0 when no
    mesh is attached (the estimator treats that as single-device)."""
    mesh = _mesh
    if mesh is None:
        return 0
    try:
        return len(mesh.healthy_shards())
    except Exception:
        return 0
