"""L0 — cryptographic primitives (reference: ``/root/reference/crypto/``).

Subpackages:
  cpu/      pure-Python BLS12-381 (oracle + host fallback backend)
  device/   JAX/Pallas TPU stack (limb fields, pairings, batched verify)
  bls.py    public wrapper types + backend seam (crypto/bls generic layer)
  backend.py runtime backend registry (cpu / fake / tpu)
  hashing.py SHA-256 helpers (eth2_hashing equivalent)
"""

from . import params  # noqa: F401
