"""BLS12-381 domain parameters.

These are the public curve constants of BLS12-381 as standardised for the
Ethereum consensus layer (min_pk ciphersuite
``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_``), mirroring what the
reference links via the ``blst``/``milagro`` backends
(``/root/reference/crypto/bls/src/impls/blst.rs:9-14``).

The 3-isogeny constants used by hash-to-G2 (RFC 9380 §8.8.2) are *derived*
in-repo by ``tools/derive_iso3.py`` (Vélu's formulas over Fp2) and committed
in ``iso3_g2.py`` — see that tool for the derivation and the checks pinning
it to the standard map.
"""

# Base field prime.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS parameter x (negative). |X| has 64 bits; X = -2^63 - 2^62 - 2^60 - 2^57 - 2^48 - 2^16.
X = -0xD201000000010000

# Curve equations: G1/E1: y^2 = x^3 + 4 over Fp; G2/E2: y^2 = x^3 + 4(u+1) over Fp2.
B1 = 4
B2 = (4, 4)  # 4 * (1 + u)

# Cofactors.
H1 = 0x396C8C005555E1568C00AAAB0000AAAB
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

# Standard generators (validated in tests: on-curve, r-torsion, and the
# interop keypair vectors from
# /root/reference/common/eth2_interop_keypairs/specs/keygen_10_validators.yaml
# certify G1 generator + serialization bit-exactly).
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_X0 = 0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8
G2_X1 = 0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E
G2_Y0 = 0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801
G2_Y1 = 0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE

# Ciphersuite domain-separation tag (reference: crypto/bls/src/impls/blst.rs:14).
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# SSWU parameters for the 3-isogenous curve E2': y^2 = x^3 + A'x + B'
# (RFC 9380 §8.8.2): A' = 240*u, B' = 1012*(1+u), Z = -(2+u).
ISO3_A = (0, 240)
ISO3_B = (1012, 1012)
ISO3_Z = (P - 2, P - 1)

SECRET_KEY_BYTES = 32
PUBLIC_KEY_BYTES = 48
SIGNATURE_BYTES = 96
