"""Hash-to-curve for G2: BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380 §8.8.2).

Pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fp2, m=2, L=64) ->
simplified SWU on the 3-isogenous curve E2' -> derived 3-isogeny map
(``lighthouse_tpu/crypto/iso3_g2.py``) -> psi-based clear_cofactor
(Budroni-Pintore, RFC 9380 App. G.3 — bit-equivalent to h_eff
multiplication).

This is what the reference's blst backend executes natively when verifying
or signing over a message root (``/root/reference/crypto/bls/src/impls/
blst.rs:14`` pins the same DST).
"""

from __future__ import annotations

import hashlib

from .. import iso3_g2
from ..params import ISO3_A, ISO3_B, ISO3_Z, P, X
from .curve import G2Point
from .fields import Fq2
from .pairing import psi, psi2

_A = Fq2.from_ints(*ISO3_A)
_B = Fq2.from_ints(*ISO3_B)
_Z = Fq2.from_ints(*ISO3_Z)

_X_NUM = [Fq2.from_ints(*c) for c in iso3_g2.X_NUM]
_X_DEN = [Fq2.from_ints(*c) for c in iso3_g2.X_DEN]
_Y_NUM = [Fq2.from_ints(*c) for c in iso3_g2.Y_NUM]
_Y_DEN = [Fq2.from_ints(*c) for c in iso3_g2.Y_DEN]


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 with H = SHA-256 (b=32, r=64)."""
    b_in_bytes = 32
    r_in_bytes = 64
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds exceeded")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(r_in_bytes)
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    prev = b1
    for i in range(2, ell + 1):
        prev = hashlib.sha256(
            bytes(a ^ b for a, b in zip(b0, prev)) + bytes([i]) + dst_prime
        ).digest()
        out.append(prev)
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, dst: bytes, count: int) -> list[Fq2]:
    """RFC 9380 §5.2 with m=2, L=64."""
    length = 64
    uniform = expand_message_xmd(msg, dst, count * 2 * length)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            off = length * (j + i * 2)
            coeffs.append(int.from_bytes(uniform[off : off + length], "big") % P)
        out.append(Fq2.from_ints(*coeffs))
    return out


def map_to_curve_sswu(u: Fq2) -> tuple[Fq2, Fq2]:
    """Simplified SWU on E2' (RFC 9380 §6.6.2), returning an E2' point."""
    zu2 = _Z * u.square()
    tv1 = zu2.square() + zu2
    if tv1.is_zero():
        x1 = _B * (_Z * _A).inverse()
    else:
        x1 = (-_B) * _A.inverse() * (Fq2.one() + tv1.inverse())
    gx1 = (x1.square() + _A) * x1 + _B
    y = gx1.sqrt()
    if y is not None:
        x = x1
    else:
        x2 = zu2 * x1
        gx2 = (x2.square() + _A) * x2 + _B
        x, y = x2, gx2.sqrt()
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


def _horner(coeffs: list[Fq2], x: Fq2) -> Fq2:
    acc = Fq2.zero()
    for c in reversed(coeffs):
        acc = acc * x + c
    return acc


def iso3_map(x: Fq2, y: Fq2) -> G2Point:
    """Apply the 3-isogeny E2' -> E2."""
    x_out = _horner(_X_NUM, x) * _horner(_X_DEN, x).inverse()
    y_out = y * _horner(_Y_NUM, x) * _horner(_Y_DEN, x).inverse()
    return G2Point(x_out, y_out)


def clear_cofactor(p: G2Point) -> G2Point:
    """Budroni-Pintore: [X^2-X-1]P + [X-1]psi(P) + psi^2([2]P), equivalent to
    multiplication by the standard h_eff (RFC 9380 App. G.3)."""
    xp = p.mul(X)  # X is negative; AffinePoint.mul handles sign
    x2p = xp.mul(X)
    part1 = x2p - xp - p
    part2 = psi(xp - p)
    part3 = psi2(p.double())
    return part1 + part2 + part3


def hash_to_g2(msg: bytes, dst: bytes) -> G2Point:
    u0, u1 = hash_to_field_fq2(msg, dst, 2)
    q0 = iso3_map(*map_to_curve_sswu(u0))
    q1 = iso3_map(*map_to_curve_sswu(u1))
    return clear_cofactor(q0 + q1)
