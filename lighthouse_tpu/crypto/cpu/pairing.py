"""Optimal ate pairing on BLS12-381 (oracle implementation).

Deliberately simple rather than fast: G2 points are untwisted into
E(Fq12) and the Miller loop uses affine line functions, so every formula
is the textbook one. The device stack re-implements the pairing with
projective formulas and sparse multiplications; it is tested for equality
against this module. Reference behaviour being reproduced: the multi-pairing
inside ``blst``'s ``verify_multiple_aggregate_signatures``
(``/root/reference/crypto/bls/src/impls/blst.rs:114-118``).
"""

from __future__ import annotations

from ..params import P, R, X
from .curve import G1Point, G2Point
from .fields import XI, Fq, Fq2, Fq12

# w^2 = v, w^6 = xi. Untwist: (x', y') on E2/Fq2 -> (x'/w^2, y'/w^3) on E/Fq12.
_W2_INV = Fq12.w().pow(2).inverse()
_W3_INV = Fq12.w().pow(3).inverse()

# psi = twist . frobenius . untwist collapses to coordinate-wise Fq2 maps:
#   psi(x, y) = (conj(x) * PSI_CX, conj(y) * PSI_CY)
# with PSI_CX = xi^-((p-1)/3), PSI_CY = xi^-((p-1)/2).
PSI_CX = XI.pow((P - 1) // 3).inverse()
PSI_CY = XI.pow((P - 1) // 2).inverse()


def psi(q: G2Point) -> G2Point:
    """Untwist-Frobenius-twist endomorphism on E2 (used for fast cofactor
    clearing and subgroup checks, RFC 9380 App. G.3 / Budroni-Pintore)."""
    if q.is_infinity():
        return q
    return G2Point(q.x.conjugate() * PSI_CX, q.y.conjugate() * PSI_CY)


def psi2(q: G2Point) -> G2Point:
    return psi(psi(q))


def _untwist(q: G2Point) -> tuple[Fq12, Fq12]:
    x = Fq12.from_fq2(q.x) * _W2_INV
    y = Fq12.from_fq2(q.y) * _W3_INV
    return x, y


def _embed_g1(p: G1Point) -> tuple[Fq12, Fq12]:
    return Fq12.from_fq(p.x), Fq12.from_fq(p.y)


def _line(t_xy, q_xy, at_xy) -> Fq12:
    """Evaluate the line through points T and Q (affine, in E(Fq12)) at the
    point ``at``. Handles T == Q (tangent) and T == -Q (vertical)."""
    (x1, y1), (x2, y2), (xt, yt) = t_xy, q_xy, at_xy
    if x1 != x2:
        m = (y2 - y1) * (x2 - x1).inverse()
        return m * (xt - x1) - (yt - y1)
    if y1 == y2:
        three = Fq12.from_fq(Fq(3))
        two = Fq12.from_fq(Fq(2))
        m = three * x1.square() * (two * y1).inverse()
        return m * (xt - x1) - (yt - y1)
    return xt - x1


def _add_affine(a_xy, b_xy):
    """Affine addition in E(Fq12); points are (x, y) tuples, no infinity."""
    (x1, y1), (x2, y2) = a_xy, b_xy
    if x1 == x2 and y1 == y2:
        three = Fq12.from_fq(Fq(3))
        two = Fq12.from_fq(Fq(2))
        m = three * x1.square() * (two * y1).inverse()
    else:
        m = (y2 - y1) * (x2 - x1).inverse()
    x3 = m.square() - x1 - x2
    y3 = m * (x1 - x3) - y1
    return (x3, y3)


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    """f_{|X|,Q}(P), conjugated for the negative BLS parameter."""
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()
    q12 = _untwist(q)
    p12 = _embed_g1(p)
    f = Fq12.one()
    t = q12
    for bit in bin(-X)[3:]:  # skip MSB
        f = f.square() * _line(t, t, p12)
        t = _add_affine(t, t)
        if bit == "1":
            f = f * _line(t, q12, p12)
            t = _add_affine(t, q12)
    # X < 0: f_{-|X|} = conj(f_{|X|}) in the final-exp quotient group.
    return f.conjugate()


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p^12-1)/r). Easy part via Frobenius/conjugation, hard part as a
    plain exponentiation (oracle-grade; the device path uses the x-chain)."""
    # Easy: f^(p^6-1) then ^(p^2+1).
    f = f.conjugate() * f.inverse()
    f = f.frobenius_n(2) * f
    # Hard: ^( (p^4 - p^2 + 1) / r ).
    h = (P**4 - P**2 + 1) // R
    return f.pow(h)


def pairing(p: G1Point, q: G2Point) -> Fq12:
    return final_exponentiation(miller_loop(p, q))


def multi_pairing(pairs) -> Fq12:
    """prod_i e(P_i, Q_i) with a single shared final exponentiation."""
    f = Fq12.one()
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f)
