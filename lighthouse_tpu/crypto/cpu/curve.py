"""BLS12-381 curve groups G1/E1(Fq) and G2/E2(Fq2): affine point arithmetic,
subgroup checks and the ZCash-style compressed serialization the consensus
layer standardised (48-byte G1 pubkeys, 96-byte G2 signatures — reference
wire behaviour: ``/root/reference/crypto/bls/src/generic_public_key.rs:22-27``
and ``generic_signature.rs``).
"""

from __future__ import annotations

from typing import Generic, TypeVar

from ..params import (
    B1,
    B2,
    G1_X,
    G1_Y,
    G2_X0,
    G2_X1,
    G2_Y0,
    G2_Y1,
    P,
    R,
)
from .fields import Fq, Fq2

F = TypeVar("F")


class AffinePoint(Generic[F]):
    """Affine short-Weierstrass point y^2 = x^3 + b, with the point at
    infinity encoded by ``inf=True``. Field-generic: works over Fq and Fq2."""

    __slots__ = ("x", "y", "inf")

    def __init__(self, x, y, inf: bool = False):
        self.x = x
        self.y = y
        self.inf = inf

    # -- group law -----------------------------------------------------------

    def is_infinity(self) -> bool:
        return self.inf

    def __eq__(self, o) -> bool:
        if not isinstance(o, AffinePoint):
            return NotImplemented
        if self.inf or o.inf:
            return self.inf and o.inf
        return self.x == o.x and self.y == o.y

    def __hash__(self):
        return hash((type(self).__name__, self.inf, None if self.inf else (self.x, self.y)))

    def __neg__(self):
        if self.inf:
            return self
        return type(self)(self.x, -self.y)

    def double(self):
        if self.inf or self.y.is_zero():
            return type(self).infinity()
        # lambda = 3x^2 / 2y  (a = 0)
        x2 = self.x.square()
        lam = (x2 + x2 + x2) * (self.y + self.y).inverse()
        x3 = lam.square() - self.x - self.x
        y3 = lam * (self.x - x3) - self.y
        return type(self)(x3, y3)

    def __add__(self, o):
        if self.inf:
            return o
        if o.inf:
            return self
        if self.x == o.x:
            if self.y == o.y:
                return self.double()
            return type(self).infinity()
        lam = (o.y - self.y) * (o.x - self.x).inverse()
        x3 = lam.square() - self.x - o.x
        y3 = lam * (self.x - x3) - self.y
        return type(self)(x3, y3)

    def __sub__(self, o):
        return self + (-o)

    def mul(self, k: int):
        """Scalar multiplication (double-and-add, MSB-first)."""
        if k < 0:
            return (-self).mul(-k)
        acc = type(self).infinity()
        if k == 0 or self.inf:
            return acc
        for bit in bin(k)[2:]:
            acc = acc.double()
            if bit == "1":
                acc = acc + self
        return acc

    def in_subgroup(self) -> bool:
        return self.mul(R).is_infinity()

    # -- subclass hooks ------------------------------------------------------

    @classmethod
    def infinity(cls):
        raise NotImplementedError

    def is_on_curve(self) -> bool:
        raise NotImplementedError


class G1Point(AffinePoint):
    @classmethod
    def infinity(cls) -> "G1Point":
        return cls(Fq(0), Fq(0), inf=True)

    def is_on_curve(self) -> bool:
        if self.inf:
            return True
        return self.y.square() == self.x.square() * self.x + Fq(B1)

    # ZCash compressed encoding: 48 bytes big-endian x with flag bits in the
    # top 3 bits of byte 0: 0x80 compressed, 0x40 infinity, 0x20 y is the
    # lexicographically larger root.
    def compress(self) -> bytes:
        if self.inf:
            return bytes([0xC0] + [0] * 47)
        flags = 0x80
        if self.y.n * 2 > P:
            flags |= 0x20
        raw = self.x.n.to_bytes(48, "big")
        return bytes([raw[0] | flags]) + raw[1:]

    @classmethod
    def decompress(cls, data: bytes) -> "G1Point":
        if len(data) != 48:
            raise ValueError("G1 compressed point must be 48 bytes")
        flags = data[0] >> 5
        if not flags & 0x4:
            raise ValueError("uncompressed G1 encoding not supported")
        x_int = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
        if flags & 0x2:  # infinity
            if x_int != 0 or flags & 0x1:
                raise ValueError("malformed infinity encoding")
            return cls.infinity()
        if x_int >= P:
            raise ValueError("x out of range")
        x = Fq(x_int)
        y = (x.square() * x + Fq(B1)).sqrt()
        if y is None:
            raise ValueError("x not on curve")
        greater = y.n * 2 > P
        if bool(flags & 0x1) != greater:
            y = -y
        return cls(x, y)


class G2Point(AffinePoint):
    @classmethod
    def infinity(cls) -> "G2Point":
        return cls(Fq2.zero(), Fq2.zero(), inf=True)

    def is_on_curve(self) -> bool:
        if self.inf:
            return True
        return self.y.square() == self.x.square() * self.x + Fq2.from_ints(*B2)

    def psi(self) -> "G2Point":
        from .pairing import psi  # local import to avoid cycle

        return psi(self)

    def compress(self) -> bytes:
        if self.inf:
            return bytes([0xC0] + [0] * 95)
        flags = 0x80
        # Lexicographic order on (c1, c0).
        if (self.y.c1.n, self.y.c0.n) > (((P - self.y.c1.n) % P), ((P - self.y.c0.n) % P)):
            flags |= 0x20
        raw = self.x.c1.n.to_bytes(48, "big") + self.x.c0.n.to_bytes(48, "big")
        return bytes([raw[0] | flags]) + raw[1:]

    @classmethod
    def decompress(cls, data: bytes) -> "G2Point":
        if len(data) != 96:
            raise ValueError("G2 compressed point must be 96 bytes")
        flags = data[0] >> 5
        if not flags & 0x4:
            raise ValueError("uncompressed G2 encoding not supported")
        x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:], "big")
        if flags & 0x2:  # infinity
            if x0 != 0 or x1 != 0 or flags & 0x1:
                raise ValueError("malformed infinity encoding")
            return cls.infinity()
        if x0 >= P or x1 >= P:
            raise ValueError("x out of range")
        x = Fq2.from_ints(x0, x1)
        y = (x.square() * x + Fq2.from_ints(*B2)).sqrt()
        if y is None:
            raise ValueError("x not on curve")
        neg = -y
        greater = (y.c1.n, y.c0.n) > (neg.c1.n, neg.c0.n)
        if bool(flags & 0x1) != greater:
            y = neg
        return cls(x, y)


def g1_generator() -> G1Point:
    return G1Point(Fq(G1_X), Fq(G1_Y))


def g2_generator() -> G2Point:
    return G2Point(Fq2.from_ints(G2_X0, G2_X1), Fq2.from_ints(G2_Y0, G2_Y1))
