"""BLS12-381 extension-field tower over Python integers.

Tower: Fq2 = Fq[u]/(u^2+1); Fq6 = Fq2[v]/(v^3 - xi), xi = 1+u;
Fq12 = Fq6[w]/(w^2 - v).

Plain (non-Montgomery) arithmetic over Python ints — this is the host
oracle. The DEVICE stack (``lighthouse_tpu.crypto.device``) uses 12-bit
limb arithmetic with fold-table reduction (explicitly NOT Montgomery —
see ``device/fp.py``); the NATIVE C backend (``_native/bls12381.c``)
uses Montgomery 6x64 CIOS. Both are tested for bit-equality against
this module.
"""

from __future__ import annotations

from ..params import P


class Fq:
    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    def __add__(self, o: "Fq") -> "Fq":
        return Fq(self.n + o.n)

    def __sub__(self, o: "Fq") -> "Fq":
        return Fq(self.n - o.n)

    def __mul__(self, o: "Fq") -> "Fq":
        return Fq(self.n * o.n)

    def __neg__(self) -> "Fq":
        return Fq(-self.n)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq) and self.n == o.n

    def __hash__(self):
        return hash(("Fq", self.n))

    def __repr__(self):
        return f"Fq(0x{self.n:x})"

    def is_zero(self) -> bool:
        return self.n == 0

    def square(self) -> "Fq":
        return Fq(self.n * self.n)

    def inverse(self) -> "Fq":
        if self.n == 0:
            raise ZeroDivisionError("Fq inverse of zero")
        return Fq(pow(self.n, P - 2, P))

    def pow(self, e: int) -> "Fq":
        return Fq(pow(self.n, e, P))

    def is_square(self) -> bool:
        return self.n == 0 or pow(self.n, (P - 1) // 2, P) == 1

    def sqrt(self) -> "Fq | None":
        # p == 3 (mod 4): candidate root is x^((p+1)/4).
        c = pow(self.n, (P + 1) // 4, P)
        if c * c % P != self.n:
            return None
        return Fq(c)

    def sgn0(self) -> int:
        return self.n & 1

    @staticmethod
    def zero() -> "Fq":
        return Fq(0)

    @staticmethod
    def one() -> "Fq":
        return Fq(1)


class Fq2:
    """c0 + c1*u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq, c1: Fq):
        self.c0 = c0
        self.c1 = c1

    @staticmethod
    def from_ints(c0: int, c1: int) -> "Fq2":
        return Fq2(Fq(c0), Fq(c1))

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        # (a0 + a1 u)(b0 + b1 u) = a0b0 - a1b1 + (a0b1 + a1b0) u
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        return Fq2(a0 * b0 - a1 * b1, a0 * b1 + a1 * b0)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash(("Fq2", self.c0.n, self.c1.n))

    def __repr__(self):
        return f"Fq2(0x{self.c0.n:x}, 0x{self.c1.n:x})"

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def square(self) -> "Fq2":
        # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
        a0, a1 = self.c0, self.c1
        t = a0 * a1
        return Fq2((a0 + a1) * (a0 - a1), t + t)

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def scale(self, k: Fq) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def inverse(self) -> "Fq2":
        # (a - bu) / (a^2 + b^2)
        d = (self.c0.square() + self.c1.square()).inverse()
        return Fq2(self.c0 * d, -(self.c1 * d))

    def pow(self, e: int) -> "Fq2":
        result = Fq2.one()
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def is_square(self) -> bool:
        # norm = a^2 + b^2 must be a square in Fq (x^((p^2-1)/2) = norm^((p-1)/2)).
        return (self.c0.square() + self.c1.square()).is_square()

    def sqrt(self) -> "Fq2 | None":
        """Square root via the p == 3 (mod 4) extension-field algorithm."""
        if self.is_zero():
            return self
        a1 = self.pow((P - 3) // 4)
        x0 = a1 * self
        alpha = a1 * x0
        if alpha == Fq2(Fq(P - 1), Fq(0)):
            # sqrt = u * x0
            out = Fq2(-x0.c1, x0.c0)
        else:
            b = (Fq2.one() + alpha).pow((P - 1) // 2)
            out = b * x0
        if out.square() == self:
            return out
        return None

    def sgn0(self) -> int:
        # RFC 9380 §4.1 sgn0 for m=2.
        s0 = self.c0.n & 1
        z0 = self.c0.n == 0
        s1 = self.c1.n & 1
        return s0 | (int(z0) & s1)

    @staticmethod
    def zero() -> "Fq2":
        return Fq2(Fq(0), Fq(0))

    @staticmethod
    def one() -> "Fq2":
        return Fq2(Fq(1), Fq(0))


# Non-residue used for the sextic extension: xi = 1 + u.
XI = Fq2.from_ints(1, 1)


class Fq6:
    """c0 + c1*v + c2*v^2 over Fq2 with v^3 = xi."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a0 * b1 + a1 * b0
        t2 = a0 * b2 + a1 * b1 + a2 * b0
        t3 = a1 * b2 + a2 * b1
        t4 = a2 * b2
        return Fq6(t0 + t3 * XI, t1 + t4 * XI, t2)

    def __eq__(self, o) -> bool:
        return (
            isinstance(o, Fq6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __hash__(self):
        return hash(("Fq6", self.c0, self.c1, self.c2))

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def square(self) -> "Fq6":
        return self * self

    def scale(self, k: Fq2) -> "Fq6":
        return Fq6(self.c0 * k, self.c1 * k, self.c2 * k)

    def mul_by_v(self) -> "Fq6":
        """Multiply by v (used by Fq12 arithmetic)."""
        return Fq6(self.c2 * XI, self.c0, self.c1)

    def inverse(self) -> "Fq6":
        c0, c1, c2 = self.c0, self.c1, self.c2
        t0 = c0.square() - c1 * c2 * XI
        t1 = c2.square() * XI - c0 * c1
        t2 = c1.square() - c0 * c2
        d = (c0 * t0 + (c2 * t1 + c1 * t2) * XI).inverse()
        return Fq6(t0 * d, t1 * d, t2 * d)

    @staticmethod
    def zero() -> "Fq6":
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one() -> "Fq6":
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def from_fq2(a: Fq2) -> "Fq6":
        return Fq6(a, Fq2.zero(), Fq2.zero())


# Frobenius constants, computed once at import (derivable public values).
#   gamma6_1 = xi^((p-1)/3), gamma6_2 = xi^(2(p-1)/3)  (Fq6 Frobenius)
#   gamma12  = xi^((p-1)/6)                            (Fq12 Frobenius)
GAMMA6_1 = XI.pow((P - 1) // 3)
GAMMA6_2 = XI.pow(2 * (P - 1) // 3)
GAMMA12 = XI.pow((P - 1) // 6)


class Fq12:
    """c0 + c1*w over Fq6 with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0 = c0
        self.c1 = c1

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o: "Fq12") -> "Fq12":
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        return Fq12(t0 + t1.mul_by_v(), a0 * b1 + a1 * b0)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash(("Fq12", self.c0, self.c1))

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def square(self) -> "Fq12":
        return self * self

    def conjugate(self) -> "Fq12":
        """The p^6 Frobenius: negates the w component. For unitary elements
        (Miller-loop outputs after the easy final-exp part) this is the
        inverse."""
        return Fq12(self.c0, -self.c1)

    def inverse(self) -> "Fq12":
        a, b = self.c0, self.c1
        d = (a.square() - b.square().mul_by_v()).inverse()
        return Fq12(a * d, -(b * d))

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inverse().pow(-e)
        result = Fq12.one()
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def frobenius(self) -> "Fq12":
        """x -> x^p."""
        a, b = self.c0, self.c1
        fa = Fq6(a.c0.conjugate(), a.c1.conjugate() * GAMMA6_1, a.c2.conjugate() * GAMMA6_2)
        fb = Fq6(b.c0.conjugate(), b.c1.conjugate() * GAMMA6_1, b.c2.conjugate() * GAMMA6_2)
        return Fq12(fa, fb.scale(GAMMA12))

    def frobenius_n(self, n: int) -> "Fq12":
        out = self
        for _ in range(n):
            out = out.frobenius()
        return out

    @staticmethod
    def zero() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.zero())

    @staticmethod
    def one() -> "Fq12":
        return Fq12(Fq6.one(), Fq6.zero())

    @staticmethod
    def from_fq2(a: Fq2) -> "Fq12":
        return Fq12(Fq6.from_fq2(a), Fq6.zero())

    @staticmethod
    def from_fq(a: Fq) -> "Fq12":
        return Fq12.from_fq2(Fq2(a, Fq(0)))

    @staticmethod
    def w() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.one())
