"""min_pk BLS signatures over the pure-Python stack (scheme layer).

Implements the eth2 ciphersuite ``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_``
with the exact batch-verification semantics of the reference's blst backend
(``/root/reference/crypto/bls/src/impls/blst.rs:36-119``):

* empty batch => False
* per-set 64-bit nonzero random scalar (random linear combination)
* signature subgroup-checked; "empty" signature => False
* a set with no signing keys => False
* one multi-pairing over all sets decides the batch
"""

from __future__ import annotations

import secrets
from typing import Callable, Iterable, Sequence

from ..params import DST, R
from .curve import G1Point, G2Point, g1_generator
from .fields import Fq12
from .hash_to_curve import hash_to_g2
from .pairing import multi_pairing


def sk_to_pk(sk: int) -> G1Point:
    return g1_generator().mul(sk % R)


def sign(sk: int, message: bytes, dst: bytes = DST) -> G2Point:
    return hash_to_g2(message, dst).mul(sk % R)


def verify(pk: G1Point, message: bytes, sig: G2Point, dst: bytes = DST) -> bool:
    """Single-signature verification: e(pk, H(m)) == e(g1, sig)."""
    if pk.is_infinity() or not pk.in_subgroup():
        return False
    if not sig.is_on_curve() or not sig.in_subgroup():
        return False
    h = hash_to_g2(message, dst)
    return multi_pairing([(pk, h), (-g1_generator(), sig)]) == Fq12.one()


def aggregate(sigs: Sequence[G2Point]) -> G2Point:
    acc = G2Point.infinity()
    for s in sigs:
        acc = acc + s
    return acc


def aggregate_pubkeys(pks: Sequence[G1Point]) -> G1Point:
    acc = G1Point.infinity()
    for p in pks:
        acc = acc + p
    return acc


def fast_aggregate_verify(
    pks: Sequence[G1Point], message: bytes, sig: G2Point, dst: bytes = DST
) -> bool:
    """All pubkeys signed the same message (reference:
    generic_aggregate_signature.rs fast_aggregate_verify; empty pubkeys =>
    False per the generic wrapper)."""
    if not pks:
        return False
    return verify(aggregate_pubkeys(pks), message, sig, dst)


def aggregate_verify(
    pks: Sequence[G1Point], messages: Sequence[bytes], sig: G2Point, dst: bytes = DST
) -> bool:
    """Each pubkey signed its own message."""
    if not pks or len(pks) != len(messages):
        return False
    if any(pk.is_infinity() or not pk.in_subgroup() for pk in pks):
        return False
    if not sig.is_on_curve() or not sig.in_subgroup():
        return False
    pairs = [(pk, hash_to_g2(msg, dst)) for pk, msg in zip(pks, messages)]
    pairs.append((-g1_generator(), sig))
    return multi_pairing(pairs) == Fq12.one()


def _default_rand() -> int:
    # 64-bit nonzero scalar, as in blst.rs:47-67 (RAND_BITS = 64).
    while True:
        r = secrets.randbits(64)
        if r != 0:
            return r


def verify_signature_sets(
    sets: Iterable[tuple[G2Point, Sequence[G1Point], bytes]],
    dst: bytes = DST,
    rand_fn: Callable[[], int] = _default_rand,
) -> bool:
    """Batch verification by random linear combination.

    ``sets`` yields (signature_point, signing_keys, message). Checks:
      prod_i e(r_i * agg_pk_i, H(m_i)) * e(-g1, sum_i r_i * sig_i) == 1
    """
    sets = list(sets)
    if not sets:
        return False

    pairs = []
    sig_acc = G2Point.infinity()
    for sig, pks, msg in sets:
        # "Empty"/infinity signatures fail the batch outright (blst.rs:77-83).
        if sig.is_infinity():
            return False
        if not sig.is_on_curve() or not sig.in_subgroup():
            return False
        if not pks:
            return False
        # Individual pubkeys are expected to be deserialization-checked
        # (subgroup, non-infinity) as in the reference; re-reject infinity
        # cheaply as defense in depth.
        if any(pk.is_infinity() for pk in pks):
            return False
        r = rand_fn()
        agg_pk = aggregate_pubkeys(pks)
        pairs.append((agg_pk.mul(r), hash_to_g2(msg, dst)))
        sig_acc = sig_acc + sig.mul(r)
    pairs.append((-g1_generator(), sig_acc))
    return multi_pairing(pairs) == Fq12.one()
