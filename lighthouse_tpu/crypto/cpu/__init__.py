"""Pure-Python CPU backend for BLS12-381.

This is the structural analogue of the reference's ``milagro`` backend
(``/root/reference/crypto/bls/src/impls/milagro.rs``): a from-scratch,
dependency-free implementation of the full signature scheme in the host
language. It serves two roles:

1. the ``cpu`` entry of the runtime-selectable backend seam
   (``lighthouse_tpu.crypto.backend``), used for host-side point
   decompression and as a correctness fallback; and
2. the oracle that certifies the JAX/TPU device stack — every device
   kernel is tested for bit-equality against this module.

Not constant-time; the consensus client only ever verifies public data on
this path (signing keys for the validator client use the same math but the
VC threat model matches the reference's, which also does not claim
side-channel hardening for its pure-Rust backend).
"""

from .fields import Fq, Fq2, Fq6, Fq12  # noqa: F401
from .curve import G1Point, G2Point, g1_generator, g2_generator  # noqa: F401
