"""CLI / process runtime (reference: the ``lighthouse`` binary —
``lighthouse/src/main.rs:34,339-343`` dispatching ``bn|vc|am|db``, with
``lighthouse/environment`` owning runtime + shutdown; the north-star
``--bls-backend tpu`` flag lands exactly here, per SURVEY.md §2.7/§5).

    python -m lighthouse_tpu bn --preset minimal --interop-validators 64
    python -m lighthouse_tpu vc --beacon-node http://127.0.0.1:5052 ...
    python -m lighthouse_tpu am wallet create|validator create ...
    python -m lighthouse_tpu db inspect --datadir ...
"""

from __future__ import annotations

import argparse
import getpass
import json
import signal
import sys
import threading


def _add_global_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--preset", choices=["mainnet", "minimal"], default="mainnet",
        help="compile-time preset analogue (EthSpec selection)",
    )
    p.add_argument(
        "--bls-backend",
        choices=["cpu", "cpu-native", "fake", "tpu"],
        default="cpu",
        help="BLS execution backend (the TPU batch verifier is 'tpu')",
    )
    p.add_argument("--datadir", default=None)


def build_parser() -> argparse.ArgumentParser:
    top = argparse.ArgumentParser(prog="lighthouse_tpu")
    sub = top.add_subparsers(dest="command", required=True)

    bn = sub.add_parser("bn", help="run a beacon node")
    _add_global_flags(bn)
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--http-host", default="127.0.0.1")
    bn.add_argument("--interop-validators", type=int, default=None,
                    help="quick-start genesis with N deterministic validators")
    bn.add_argument("--genesis-time", type=int, default=None)
    bn.add_argument("--workers", type=int, default=2)
    bn.add_argument("--listen-port", type=int, default=None,
                    help="p2p listen port (0 = free port; omit = no p2p)")
    bn.add_argument("--boot-nodes", nargs="*", default=[],
                    help="host:port addresses to dial at startup")
    bn.add_argument("--monitoring-endpoint", default=None,
                    help="POST process/beacon health to this URL every minute")

    vc = sub.add_parser("vc", help="run a validator client")
    _add_global_flags(vc)
    vc.add_argument("--beacon-node", action="append", required=True,
                    help="beacon node URL (repeatable for fallback)")
    vc.add_argument("--keystore", action="append", default=[],
                    help="EIP-2335 keystore path (repeatable)")
    vc.add_argument("--interop-keys", type=str, default=None,
                    help="range like 0:8 of deterministic interop keys")
    vc.add_argument("--graffiti-file", default=None,
                    help="per-validator graffiti mapping, reread each proposal")

    am = sub.add_parser("am", help="account manager")
    _add_global_flags(am)
    am_sub = am.add_subparsers(dest="am_command", required=True)
    w = am_sub.add_parser("wallet-create")
    w.add_argument("--name", required=True)
    w.add_argument("--out", required=True)
    w.add_argument("--kdf-work", type=int, default=None,
                   help="scrypt work factor override (tests/low-memory)")
    v = am_sub.add_parser("validator-create")
    v.add_argument("--wallet", required=True)
    v.add_argument("--out-dir", required=True)
    v.add_argument("--count", type=int, default=1)
    v.add_argument("--kdf-work", type=int, default=None)
    d = am_sub.add_parser(
        "validator-deposits",
        help="build DepositData (launchpad deposit_data.json) from keystores",
    )
    d.add_argument("--validator-dir", required=True)
    d.add_argument("--out", required=True)
    d.add_argument("--amount-gwei", type=int, default=32 * 10**9)
    d.add_argument("--password", default=None, help="keystore password (else prompt)")
    d.add_argument("--spec", choices=["mainnet", "minimal"], default="mainnet")
    x = am_sub.add_parser(
        "validator-exit", help="sign (and optionally publish) a voluntary exit"
    )
    x.add_argument("--keystore", required=True)
    x.add_argument("--validator-index", type=int, required=True)
    x.add_argument("--epoch", type=int, required=True)
    x.add_argument("--genesis-validators-root", required=True, help="0x-hex root")
    x.add_argument("--out", required=True)
    x.add_argument("--password", default=None)
    x.add_argument("--spec", choices=["mainnet", "minimal"], default="mainnet")
    x.add_argument("--beacon-url", default=None, help="POST the exit to this BN")

    bnode = sub.add_parser("boot-node", help="standalone peer-exchange bootstrap server")
    bnode.add_argument("--port", type=int, default=9000)

    lcli = sub.add_parser("lcli", help="dev/ops tools (reference lcli)")
    lcli_sub = lcli.add_subparsers(dest="lcli_command", required=True)
    ss = lcli_sub.add_parser("skip-slots", help="advance a state N slots")
    ss.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    ss.add_argument("--state", required=True, help="SSZ state file (fork byte prefixed)")
    ss.add_argument("--slots", type=int, required=True)
    ss.add_argument("--out", required=True)
    pr = lcli_sub.add_parser("pretty-ssz", help="decode an SSZ object to JSON")
    pr.add_argument("--preset", choices=["mainnet", "minimal"], default="mainnet")
    pr.add_argument("--type", required=True, dest="type_name")
    pr.add_argument("--file", required=True)
    ig = lcli_sub.add_parser("interop-genesis", help="write an interop genesis state")
    ig.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    ig.add_argument("--validators", type=int, default=64)
    ig.add_argument("--genesis-time", type=int, default=0)
    ig.add_argument("--out", required=True)
    tb = lcli_sub.add_parser("transition-blocks", help="apply SSZ blocks to a state")
    tb.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    tb.add_argument("--state", required=True)
    tb.add_argument("--blocks", nargs="+", required=True)
    tb.add_argument("--out", required=True)

    rr = lcli_sub.add_parser("state-root", help="hash_tree_root of an SSZ state")
    rr.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    rr.add_argument("--state", required=True)
    br = lcli_sub.add_parser("block-root", help="hash_tree_root of an SSZ signed block")
    br.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    br.add_argument("--block", required=True)
    nt = lcli_sub.add_parser(
        "new-testnet", help="write a testnet directory (config + genesis)"
    )
    nt.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    nt.add_argument("--validators", type=int, default=64)
    nt.add_argument("--genesis-time", type=int, default=0)
    nt.add_argument("--out-dir", dest="out_dir", required=True)
    eg = lcli_sub.add_parser(
        "eth1-genesis",
        help="genesis from (mock) eth1 deposit-contract logs",
    )
    eg.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    eg.add_argument("--validators", type=int, default=64)
    eg.add_argument("--genesis-time", type=int, default=0)
    eg.add_argument("--out", required=True)
    bk = lcli_sub.add_parser(
        "generate-bootnode-record",
        help="write a bootnode identity + address record (ENR analogue)",
    )
    bk.add_argument("--seed", default=None, help="deterministic identity seed")
    bk.add_argument("--host", default="127.0.0.1")
    bk.add_argument("--port", type=int, default=9000)
    bk.add_argument("--out", required=True)

    db = sub.add_parser("db", help="database manager")
    _add_global_flags(db)
    db_sub = db.add_subparsers(dest="db_command", required=True)
    inspect = db_sub.add_parser("inspect")
    inspect.add_argument("--datadir", default=None)
    ver = db_sub.add_parser("version", help="print the on-disk schema version")
    ver.add_argument("--datadir", required=True)
    mig = db_sub.add_parser("migrate", help="migrate the store to the latest schema")
    mig.add_argument("--datadir", required=True)
    pru = db_sub.add_parser(
        "prune", help="drop redundant pre-split hot snapshots + compact"
    )
    pru.add_argument("--datadir", required=True)

    return top


def run_bn(args) -> int:
    from .client import ClientBuilder, ClientConfig
    from .types.chain_spec import minimal_spec
    from .utils import metrics

    listen_port = args.listen_port
    if args.boot_nodes and listen_port is None:
        # boot nodes imply p2p: dialing without a listener would silently
        # no-op in the builder
        listen_port = 0
        print("--boot-nodes given without --listen-port: listening on a free port")
    cfg = ClientConfig(
        preset_base=args.preset,
        datadir=args.datadir,
        http_host=args.http_host,
        http_port=args.http_port,
        bls_backend=args.bls_backend,
        n_workers=args.workers,
        listen_port=listen_port,
        boot_nodes=tuple(args.boot_nodes),
        monitoring_endpoint=args.monitoring_endpoint,
    )
    spec = minimal_spec() if args.preset == "minimal" else None
    builder = ClientBuilder(cfg, spec)
    if args.interop_validators:
        import time as _time

        builder.with_interop_genesis(
            args.interop_validators,
            genesis_time=args.genesis_time or int(_time.time()),
        )
    client = builder.build().start()
    print(
        f"beacon node up: http://{args.http_host}:{client.api.port} "
        f"(backend={args.bls_backend}, preset={args.preset})",
        flush=True,
    )
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    client.stop()
    return 0


def run_vc(args) -> int:
    from .eth2_client import BeaconNodeClient
    from .types.chain_spec import minimal_spec, mainnet_spec
    from .types.containers import types_for
    from .types.preset import PRESETS
    from .utils.slot_clock import SystemTimeSlotClock
    from .validator_client import BeaconNodeFallback, ValidatorClient, ValidatorStore

    preset = PRESETS[args.preset]
    spec = minimal_spec() if args.preset == "minimal" else mainnet_spec()
    t = types_for(preset)
    clients = [BeaconNodeClient(u, t) for u in args.beacon_node]
    nodes = BeaconNodeFallback(clients)
    genesis = nodes.call("genesis")
    gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
    store = ValidatorStore(spec, preset, t, genesis_validators_root=gvr)
    if args.interop_keys:
        from .state_transition import interop_secret_key

        lo, hi = (int(x) for x in args.interop_keys.split(":"))
        for i in range(lo, hi):
            store.add_secret_key(interop_secret_key(i))
    for path in args.keystore:
        with open(path) as f:
            ks = json.load(f)
        store.add_keystore(ks, getpass.getpass(f"password for {path}: "))
    clock = SystemTimeSlotClock(int(genesis["genesis_time"]), spec.seconds_per_slot)
    graffiti_file = None
    if args.graffiti_file:
        from .validator_client.graffiti import GraffitiFile

        graffiti_file = GraffitiFile(args.graffiti_file)
    vc = ValidatorClient(store, nodes, t, preset, clock, graffiti_file=graffiti_file)
    print(f"validator client up: {len(store.pubkeys())} keys", flush=True)
    signal.signal(signal.SIGINT, lambda *a: vc.stop())
    signal.signal(signal.SIGTERM, lambda *a: vc.stop())
    vc.run_forever()
    return 0


def run_am(args) -> int:
    from .keys import Wallet, save

    if args.am_command == "wallet-create":
        password = getpass.getpass("wallet password: ")
        w = Wallet.create(args.name, password, kdf_work=args.kdf_work)
        with open(args.out, "w") as f:
            json.dump(w.json, f, indent=2)
        print(f"wallet written to {args.out}")
        return 0
    if args.am_command == "validator-create":
        import os

        with open(args.wallet) as f:
            wobj = json.load(f)
        w = Wallet(wobj)
        wallet_pw = getpass.getpass("wallet password: ")
        ks_pw = getpass.getpass("keystore password: ")
        os.makedirs(args.out_dir, exist_ok=True)
        for _ in range(args.count):
            signing, withdrawal = w.next_validator(wallet_pw, ks_pw, kdf_work=args.kdf_work)
            stem = signing["pubkey"][:12]
            save(signing, f"{args.out_dir}/keystore-{stem}.json")
            save(withdrawal, f"{args.out_dir}/withdrawal-{stem}.json")
            print(f"validator 0x{signing['pubkey'][:16]}… written")
        with open(args.wallet, "w") as f:
            json.dump(w.json, f, indent=2)
        return 0
    if args.am_command == "validator-deposits":
        return _am_validator_deposits(args)
    if args.am_command == "validator-exit":
        return _am_validator_exit(args)
    return 1


def _am_spec(name: str):
    from .types.chain_spec import mainnet_spec, minimal_spec

    return minimal_spec() if name == "minimal" else mainnet_spec()


def _am_validator_deposits(args) -> int:
    """DepositData per keystore in --validator-dir (reference
    ``account_manager`` deposit creation; EF launchpad deposit_data.json
    shape: signed DepositMessage under DOMAIN_DEPOSIT with a zeroed
    genesis_validators_root)."""
    import getpass
    import glob
    import os

    from .crypto import bls
    from .keys.keystore import decrypt, load
    from .ssz import hash_tree_root
    from .types.chain_spec import DOMAIN_DEPOSIT
    from .types.containers import types_for
    from .types.domains import compute_domain, compute_signing_root
    from .types.preset import PRESETS

    spec = _am_spec(args.spec)
    t = types_for(PRESETS[args.spec])
    password = args.password or getpass.getpass("keystore password: ")
    out = []
    paths = sorted(glob.glob(os.path.join(args.validator_dir, "keystore-*.json")))
    if not paths:
        print("no keystore-*.json files found", file=sys.stderr)
        return 1
    for path in paths:
        ks = load(path)
        sk = bls.SecretKey.deserialize(decrypt(ks, password))
        pubkey = sk.public_key().serialize()
        # BLS withdrawal credentials: 0x00 || sha256(pubkey)[1:]
        import hashlib as _hashlib

        cred = b"\x00" + _hashlib.sha256(pubkey).digest()[1:]
        msg = t.DepositMessage(
            pubkey=pubkey, withdrawal_credentials=cred, amount=args.amount_gwei
        )
        domain = compute_domain(
            spec, DOMAIN_DEPOSIT, spec.genesis_fork_version, bytes(32)
        )
        root = compute_signing_root(t.DepositMessage, msg, domain)
        sig = sk.sign(root).serialize()
        dd = t.DepositData(
            pubkey=pubkey, withdrawal_credentials=cred,
            amount=args.amount_gwei, signature=sig,
        )
        out.append(
            {
                "pubkey": pubkey.hex(),
                "withdrawal_credentials": cred.hex(),
                "amount": args.amount_gwei,
                "signature": sig.hex(),
                "deposit_message_root": root.hex(),
                "deposit_data_root": hash_tree_root(t.DepositData, dd).hex(),
                "fork_version": spec.genesis_fork_version.hex(),
            }
        )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {len(out)} deposit(s) to {args.out}")
    return 0


def _am_validator_exit(args) -> int:
    """Sign a VoluntaryExit offline (reference ``account_manager`` exit):
    domain from DOMAIN_VOLUNTARY_EXIT at --epoch against the supplied
    genesis validators root; optional publish to --beacon-url."""
    import getpass

    from .crypto import bls
    from .keys.keystore import decrypt, load
    from .types.chain_spec import DOMAIN_VOLUNTARY_EXIT
    from .types.containers import types_for
    from .types.domains import compute_domain, compute_signing_root
    from .types.preset import PRESETS

    spec = _am_spec(args.spec)
    t = types_for(PRESETS[args.spec])
    password = args.password or getpass.getpass("keystore password: ")
    sk = bls.SecretKey.deserialize(decrypt(load(args.keystore), password))
    gvr = bytes.fromhex(args.genesis_validators_root.removeprefix("0x"))
    exit_msg = t.VoluntaryExit(epoch=args.epoch, validator_index=args.validator_index)
    domain = compute_domain(
        spec, DOMAIN_VOLUNTARY_EXIT, spec.fork_version_at_epoch(args.epoch), gvr
    )
    root = compute_signing_root(t.VoluntaryExit, exit_msg, domain)
    signed = t.SignedVoluntaryExit(
        message=exit_msg, signature=sk.sign(root).serialize()
    )
    from .ssz.json import to_json

    doc = to_json(t.SignedVoluntaryExit, signed)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"wrote signed exit for validator {args.validator_index} to {args.out}")
    if args.beacon_url:
        import urllib.request

        req = urllib.request.Request(
            args.beacon_url.rstrip("/") + "/eth/v1/beacon/pool/voluntary_exits",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            print(f"published: HTTP {r.status}")
    return 0


def run_boot_node(args) -> int:
    """Chain-less peer-exchange hub (reference ``boot_node``: a
    standalone discv5 server; here the transport's peer-exchange protocol
    plays the discovery role)."""
    import json as _json
    import threading as _threading

    from .network.service import PROTO_PEER_EXCHANGE, PROTO_PING, PROTO_STATUS
    from .network.transport import Transport

    t = Transport(port=args.port)

    def on_request(peer, protocol, payload):
        if protocol == PROTO_PEER_EXCHANGE:
            peers = [
                [p.addr[0], p.remote_listen_port]
                for p in t.peers_snapshot()
                if p.remote_listen_port
            ]
            return _json.dumps(peers).encode()
        if protocol == PROTO_STATUS:
            try:
                theirs = _json.loads(payload)
                peer.remote_listen_port = theirs.get("listen_port")
            except ValueError:
                pass
            return _json.dumps({"boot_node": True, "head_slot": 0}).encode()
        if protocol == PROTO_PING:
            return b"pong"
        return b""

    t.on_request = on_request
    print(f"boot node up on port {t.port}", flush=True)
    stop = _threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    t.close()
    return 0


def run_lcli(args) -> int:
    if args.lcli_command == "generate-bootnode-record":
        import json as _json

        from .network import noise as _noise

        ident = (
            _noise.Identity.from_seed(args.seed.encode())
            if args.seed else _noise.Identity()
        )
        rec = {
            "node_id": ident.node_id,
            "static_pubkey": "0x" + ident.public.hex(),
            "host": args.host,
            "port": args.port,
        }
        with open(args.out, "w") as f:
            _json.dump(rec, f, indent=1)
        print(f"bootnode record {ident.node_id[:16]}... -> {args.out}")
        return 0
    from .ssz.json import to_json
    from .state_transition import interop_genesis_state, per_slot_processing, process_block
    from .state_transition.epoch import fork_of
    from .types.chain_spec import mainnet_spec, minimal_spec
    from .types.containers import types_for
    from .types.preset import PRESETS

    preset = PRESETS[args.preset]
    spec = minimal_spec() if args.preset == "minimal" else mainnet_spec()
    from .types.containers import FORK_IDS as ids, FORK_NAMES as forks

    t = types_for(preset)

    def read_state(path):
        raw = open(path, "rb").read()
        return t.state[forks[raw[0]]].decode(raw[1:])

    def write_state(path, st):
        with open(path, "wb") as f:
            f.write(bytes([ids[fork_of(st)]]) + type(st).encode(st))

    if args.lcli_command == "interop-genesis":
        st = interop_genesis_state(
            preset, spec, args.validators, genesis_time=args.genesis_time
        )
        write_state(args.out, st)
        print(f"wrote genesis state ({len(st.validators)} validators) to {args.out}")
        return 0
    if args.lcli_command == "skip-slots":
        st = read_state(args.state)
        for _ in range(args.slots):
            st = per_slot_processing(preset, spec, st)
        write_state(args.out, st)
        print(f"advanced to slot {st.slot}")
        return 0
    if args.lcli_command == "transition-blocks":
        st = read_state(args.state)
        import struct as _struct

        for path in args.blocks:
            raw = open(path, "rb").read()
            # fork of a block follows ITS slot (may be past a fork
            # boundary the state has not crossed yet): slot is the first
            # u64 of the message, at fixed offset 4 (signature offset) + 0
            slot = _struct.unpack_from("<Q", raw, 4)[0]
            fork = spec.fork_name_at_epoch(slot // preset.SLOTS_PER_EPOCH)
            sb = t.signed_block[fork].decode(raw)
            while st.slot < sb.message.slot:
                st = per_slot_processing(preset, spec, st)
            process_block(preset, spec, st, sb, fork_of(st), signature_strategy="none")
        write_state(args.out, st)
        print(f"applied {len(args.blocks)} block(s); state at slot {st.slot}")
        return 0
    if args.lcli_command == "pretty-ssz":
        raw = open(args.file, "rb").read()
        tpe = getattr(t, args.type_name, None)
        if tpe is None:
            print(f"unknown type {args.type_name}", file=sys.stderr)
            return 1
        obj = tpe.decode(raw)
        print(json.dumps(to_json(tpe, obj), indent=2))
        return 0
    if args.lcli_command == "state-root":
        st = read_state(args.state)
        from .ssz import hash_tree_root as _htr

        print("0x" + _htr(st).hex())
        return 0
    if args.lcli_command == "block-root":
        import struct as _struct

        from .ssz import hash_tree_root as _htr

        raw = open(args.block, "rb").read()
        # fork auto-detection from the block slot (same scheme as
        # transition-blocks): SignedBeaconBlock = offset(4) + sig(96) +
        # message, whose first field is the u64 slot
        (slot,) = _struct.unpack_from("<Q", raw, 100)
        fork = spec.fork_name_at_epoch(slot // preset.SLOTS_PER_EPOCH)
        sb = t.signed_block[fork].decode(raw)
        print("0x" + _htr(type(sb.message), sb.message).hex())
        return 0
    if args.lcli_command == "eth1-genesis":
        # reference lcli eth1-genesis: build genesis from deposit-contract
        # logs; here the deposits are built locally with signed
        # DepositData (the real-chain variant needs an eth1 RPC)
        import hashlib as _hashlib

        from .ssz.sha256 import hash32_concat as _h32
        from .state_transition.genesis import (
            initialize_beacon_state_from_eth1,
            interop_secret_key,
        )
        from .types.chain_spec import DOMAIN_DEPOSIT
        from .types.domains import compute_domain, compute_signing_root

        deposits = []
        domain = compute_domain(
            spec, DOMAIN_DEPOSIT, spec.genesis_fork_version, bytes(32)
        )
        for i in range(args.validators):
            sk = interop_secret_key(i)
            pubkey = sk.public_key().serialize()
            cred = b"\x00" + _hashlib.sha256(pubkey).digest()[1:]
            msg = t.DepositMessage(
                pubkey=pubkey, withdrawal_credentials=cred,
                amount=preset.MAX_EFFECTIVE_BALANCE,
            )
            root = compute_signing_root(t.DepositMessage, msg, domain)
            dd = t.DepositData(
                pubkey=pubkey, withdrawal_credentials=cred,
                amount=preset.MAX_EFFECTIVE_BALANCE,
                signature=sk.sign(root).serialize(),
            )
            deposits.append(t.Deposit(data=dd))
        # deterministic mock eth1 block hash (same rule the mock endpoint
        # uses); initialize_* recomputes the incremental deposit root
        # itself from `deposits`
        eth1_hash = _h32((1).to_bytes(32, "little"), b"eth1".ljust(32, b"\x00"))
        st = initialize_beacon_state_from_eth1(
            preset, spec, eth1_hash, args.genesis_time or 1, deposits
        )
        write_state(args.out, st)
        print(
            f"wrote eth1 genesis ({len(st.validators)} validators, "
            f"deposit_root 0x{bytes(st.eth1_data.deposit_root).hex()[:16]}...) "
            f"to {args.out}"
        )
        return 0
    if args.lcli_command == "new-testnet":
        import os as _os

        import yaml as _yaml

        _os.makedirs(args.out_dir, exist_ok=True)
        st = interop_genesis_state(
            preset, spec, args.validators, genesis_time=args.genesis_time
        )
        write_state(f"{args.out_dir}/genesis.ssz", st)
        cfg = {
            "PRESET_BASE": args.preset,
            "MIN_GENESIS_TIME": int(args.genesis_time),
            "GENESIS_FORK_VERSION": "0x" + spec.genesis_fork_version.hex(),
            "SECONDS_PER_SLOT": int(spec.seconds_per_slot),
            "GENESIS_VALIDATORS_ROOT": "0x"
            + bytes(st.genesis_validators_root).hex(),
            "MIN_PER_EPOCH_CHURN_LIMIT": int(spec.min_per_epoch_churn_limit),
            "CHURN_LIMIT_QUOTIENT": int(spec.churn_limit_quotient),
            "EJECTION_BALANCE": int(spec.ejection_balance),
        }
        with open(f"{args.out_dir}/config.yaml", "w") as f:
            _yaml.safe_dump(cfg, f)
        with open(f"{args.out_dir}/boot_nodes.yaml", "w") as f:
            _yaml.safe_dump([], f)
        print(
            f"testnet dir {args.out_dir}: genesis.ssz "
            f"({args.validators} validators), config.yaml, boot_nodes.yaml"
        )
        return 0
    return 1


def run_db(args) -> int:
    from .store import Column, SqliteStore

    if args.db_command == "inspect":
        if not args.datadir:
            print("--datadir required", file=sys.stderr)
            return 1
        kv = SqliteStore(f"{args.datadir}/chain.sqlite")
        for col, name in [
            (Column.BLOCK, "blocks"),
            (Column.STATE, "hot state snapshots"),
            (Column.STATE_SUMMARY, "hot state summaries"),
            (Column.COLD_STATE, "cold restore points"),
        ]:
            print(f"{name}: {sum(1 for _ in kv.keys(col))}")
        head = kv.get(Column.METADATA, b"head")
        print(f"head: {head.hex() if head else None}")
        return 0
    if args.db_command == "version":
        kv = SqliteStore(f"{args.datadir}/chain.sqlite")
        print(f"schema version: {_db_schema_version(kv)}")
        return 0
    if args.db_command == "migrate":
        kv = SqliteStore(f"{args.datadir}/chain.sqlite")
        v = _db_schema_version(kv)
        for target, fn in sorted(_DB_MIGRATIONS.items()):
            if v < target:
                fn(kv)
                kv.put(Column.METADATA, b"schema", str(target).encode())
                print(f"migrated v{v} -> v{target}")
                v = target
        print(f"store at schema v{v} (latest {DB_SCHEMA_LATEST})")
        return 0
    if args.db_command == "prune":
        import struct as _struct

        kv = SqliteStore(f"{args.datadir}/chain.sqlite")
        raw = kv.get(Column.METADATA, b"split")
        split = _struct.unpack("<Q", raw)[0] if raw else 0
        # pre-split hot snapshots are redundant once migrated to the
        # freezer (reference database_manager prune-states); the head
        # state is safe because head slot >= split always holds
        dropped = 0
        for key in list(kv.keys(Column.STATE)):
            data = kv.get(Column.STATE, key)
            if data is None:
                continue
            # every BeaconState starts [fork_id u8][genesis_time u64]
            # [genesis_validators_root 32][slot u64]
            slot = int.from_bytes(data[1 + 8 + 32 : 1 + 8 + 32 + 8], "little")
            if slot and slot < split:
                kv.delete(Column.STATE, key)
                dropped += 1
        # pre-split summaries must go WITH their base snapshots: a kept
        # summary whose replay chain bottoms out in a deleted snapshot
        # would fail to load (StateSummary starts [slot u64])
        for key in list(kv.keys(Column.STATE_SUMMARY)):
            data = kv.get(Column.STATE_SUMMARY, key)
            if data is None:
                continue
            slot = int.from_bytes(data[:8], "little")
            if slot and slot < split:
                kv.delete(Column.STATE_SUMMARY, key)
                dropped += 1
        try:
            kv._conn.execute("VACUUM")
        except Exception:
            pass
        print(f"dropped {dropped} pre-split hot snapshots (split slot {split})")
        return 0
    return 1


DB_SCHEMA_LATEST = 1
# target version -> migration fn(kv); v1 is the current layout, so the
# table is empty — the framework (version stamp + ordered apply) mirrors
# the reference's schema_change.rs
_DB_MIGRATIONS: dict = {}


def _db_schema_version(kv) -> int:
    from .store import Column

    raw = kv.get(Column.METADATA, b"schema")
    return int(raw.decode()) if raw else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "bn":
        return run_bn(args)
    if args.command == "vc":
        return run_vc(args)
    if args.command == "am":
        return run_am(args)
    if args.command == "db":
        return run_db(args)
    if args.command == "boot-node":
        return run_boot_node(args)
    if args.command == "lcli":
        return run_lcli(args)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
