"""Columnar per-epoch processing: numpy vector passes over
:class:`~.columns.Columns`, exactly mirroring the scalar spec functions
in ``state_transition/epoch.py`` (which remain the oracle — the
differential suite in ``tests/test_epoch_columnar.py`` pins scalar ==
columnar on randomized states).

Reference analogue: ``consensus/state_processing/src/per_epoch_processing/``
(base + altair), which runs the same passes as compiled per-validator
loops; here each pass is O(1) numpy kernels over the full registry, the
same shape a jnp/device tier would consume.

Fallback discipline: every :class:`Fallback` raise happens BEFORE the
first state mutation (all preconditions are pure reads), so the caller
can always rerun the scalar path from scratch.
"""

from __future__ import annotations

import numpy as np

from ...types.chain_spec import ChainSpec, FAR_FUTURE_EPOCH
from ...types.preset import Preset
from .columns import (
    FF_U64,
    FINALITY_DELAY_LIMIT,
    SCORE_LIMIT,
    Columns,
    Fallback,
)

_GENESIS_EPOCH = 0
_BASE_REWARDS_PER_EPOCH = 4


def _flag_mask(participation: np.ndarray, flag_index: int) -> np.ndarray:
    return (participation >> np.uint8(flag_index)) & np.uint8(1) != 0


def process_epoch_columnar(preset: Preset, spec: ChainSpec, state) -> None:
    """Full process_epoch over columnar views. Raises :class:`Fallback`
    (state untouched) when preconditions fail; otherwise leaves the state
    bit-identical to the scalar ``process_epoch``."""
    from .. import epoch as sc  # scalar module: shared cheap passes + helpers
    from ..helpers import get_current_epoch, get_previous_epoch

    fork = sc.fork_of(state)
    cols = Columns.from_state(state)
    n = cols.n
    cur = get_current_epoch(preset, state)
    prev = get_previous_epoch(preset, state)

    active_prev = cols.active_mask(prev)
    active_cur = cols.active_mask(cur)
    total = cols.total_active_balance(preset, cur)
    eligible = active_prev | (
        cols.slashed & (np.uint64(prev + 1) < cols.wd)
    )

    if fork == "phase0":
        pre = _phase0_precompute(preset, state, cols, prev, cur)
        scores = None
        prev_part = cur_part = None
    else:
        try:
            prev_part = np.fromiter(
                state.previous_epoch_participation, np.uint8, count=n
            )
            cur_part = np.fromiter(
                state.current_epoch_participation, np.uint8, count=n
            )
            scores = np.fromiter(state.inactivity_scores, np.int64, count=n)
        except (OverflowError, ValueError) as e:
            raise Fallback(str(e)) from e
        # score growth this epoch is bounded by +bias; check the post bound
        if n and int(scores.max()) + spec.inactivity_score_bias >= SCORE_LIMIT:
            raise Fallback("inactivity scores exceed exact-int64 bounds")
        pre = None

    # ---- remaining pure precondition checks (Fallback contract: nothing
    # below may raise Fallback once the first mutation lands) -------------
    # Post-justification finality delay can only be <= the pre-state value
    # (finalized_epoch is monotone within the pass), so the pre-state
    # bound is conservative.
    if prev - state.finalized_checkpoint.epoch >= FINALITY_DELAY_LIMIT:
        raise Fallback("finality delay exceeds exact-int64 bounds")
    if cur != _GENESIS_EPOCH:
        if fork == "phase0":
            _check_phase0_reward_bounds(preset, cols, pre, total)
        else:
            _check_altair_reward_bounds(preset, cols, active_prev, prev_part, total)

    # ---- justification & finalization (mutates checkpoints/bits) ---------
    if cur > _GENESIS_EPOCH + 1:
        if fork == "phase0":
            prev_bal = cols.sum_effective(
                preset, pre["target_att"] & ~cols.slashed
            )
            cur_bal = cols.sum_effective(
                preset, pre["target_att_cur"] & ~cols.slashed
            )
        else:
            unslashed_prev_tgt = (
                active_prev & ~cols.slashed & _flag_mask(prev_part, sc.TIMELY_TARGET_FLAG_INDEX)
            )
            unslashed_cur_tgt = (
                active_cur & ~cols.slashed & _flag_mask(cur_part, sc.TIMELY_TARGET_FLAG_INDEX)
            )
            prev_bal = cols.sum_effective(preset, unslashed_prev_tgt)
            cur_bal = cols.sum_effective(preset, unslashed_cur_tgt)
        sc._weigh_justification_and_finalization(preset, state, prev_bal, cur_bal)

    # finality delay / leak read the JUST-UPDATED finalized checkpoint,
    # matching the scalar pass order (bound pre-checked above).
    finality_delay = prev - state.finalized_checkpoint.epoch
    in_leak = finality_delay > preset.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    # ---- rewards & penalties --------------------------------------------
    if fork == "phase0":
        if cur != _GENESIS_EPOCH:
            rewards, penalties = _phase0_deltas(
                preset, cols, pre, total, eligible, in_leak, finality_delay
            )
            cols.balances = np.maximum(cols.balances + rewards - penalties, 0)
    else:
        if cur != _GENESIS_EPOCH:
            unslashed_prev_tgt = (
                active_prev & ~cols.slashed & _flag_mask(prev_part, sc.TIMELY_TARGET_FLAG_INDEX)
            )
            # inactivity updates first (rewards read the updated scores)
            scores = _inactivity_updates(
                spec, scores, eligible, unslashed_prev_tgt, in_leak
            )
            state.inactivity_scores = scores.tolist()
            rewards, penalties = _altair_deltas(
                preset, spec, cols, fork, total, eligible, active_prev,
                prev_part, unslashed_prev_tgt, scores, in_leak,
            )
            cols.balances = np.maximum(cols.balances + rewards - penalties, 0)

    # ---- registry / slashings / effective balances -----------------------
    _registry_updates(preset, spec, state, cols, cur, active_cur)
    _process_slashings(preset, state, cols, fork, cur, total)
    sc.process_eth1_data_reset(preset, state)
    _effective_balance_updates(preset, cols)
    cols.write_balances(state)
    sc.process_slashings_reset(preset, state)
    sc.process_randao_mixes_reset(preset, state)
    sc.process_historical_roots_update(preset, state)
    if fork == "phase0":
        state.previous_epoch_attestations = state.current_epoch_attestations
        state.current_epoch_attestations = []
    else:
        state.previous_epoch_participation = state.current_epoch_participation
        state.current_epoch_participation = [0] * n
        sc.process_sync_committee_updates(preset, state)


def altair_reward_components(preset: Preset, spec: ChainSpec, state) -> dict:
    """Per-validator PREVIOUS-epoch attestation reward components for the
    Beacon API rewards endpoint (reference ``http_api`` attestation
    rewards; computed with the same columnar kernels as the live epoch
    transition). Pure: works on an internal copy.

    Returns arrays (len = validator count): ``source``/``target``/``head``
    (signed: reward if participating, -penalty if not), ``inactivity``
    (<= 0), plus ``eligible`` (bool) and ``ideal`` — a map of effective
    balance -> ideal (full-participation) source/target/head rewards."""
    import copy as _copy

    from .. import epoch as sc
    from ..helpers import get_current_epoch, get_previous_epoch, integer_squareroot

    st = _copy.deepcopy(state)
    cols = Columns.from_state(st)
    n = cols.n
    cur = get_current_epoch(preset, st)
    prev = get_previous_epoch(preset, st)
    active_prev = cols.active_mask(prev)
    active_cur = cols.active_mask(cur)
    total = cols.total_active_balance(preset, cur)
    eligible = active_prev | (cols.slashed & (np.uint64(prev + 1) < cols.wd))
    prev_part = np.fromiter(st.previous_epoch_participation, np.uint8, count=n)
    cur_part = np.fromiter(st.current_epoch_participation, np.uint8, count=n)
    scores = np.fromiter(st.inactivity_scores, np.int64, count=n)

    # replicate the pass order on the copy: justification first (the leak
    # flag reads the updated finalized checkpoint), then score updates
    if cur > _GENESIS_EPOCH + 1:
        unslashed_prev_tgt = (
            active_prev & ~cols.slashed & _flag_mask(prev_part, sc.TIMELY_TARGET_FLAG_INDEX)
        )
        unslashed_cur_tgt = (
            active_cur & ~cols.slashed & _flag_mask(cur_part, sc.TIMELY_TARGET_FLAG_INDEX)
        )
        sc._weigh_justification_and_finalization(
            preset, st,
            cols.sum_effective(preset, unslashed_prev_tgt),
            cols.sum_effective(preset, unslashed_cur_tgt),
        )
    finality_delay = prev - st.finalized_checkpoint.epoch
    in_leak = finality_delay > preset.MIN_EPOCHS_TO_INACTIVITY_PENALTY
    unslashed_prev_tgt = (
        active_prev & ~cols.slashed & _flag_mask(prev_part, sc.TIMELY_TARGET_FLAG_INDEX)
    )
    scores = _inactivity_updates(spec, scores, eligible, unslashed_prev_tgt, in_leak)

    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    base_per_increment = inc * preset.BASE_REWARD_FACTOR // integer_squareroot(total)
    base = (cols.eff // inc) * base_per_increment
    active_increments = total // inc
    out = {"eligible": eligible, "ideal": {}}
    names = {0: "source", 1: "target", 2: "head"}
    distinct_eff = sorted({int(e) for e in cols.eff[eligible]}) if n else []
    for eff in distinct_eff:
        out["ideal"][eff] = {}
    for flag_index, weight in enumerate(sc.PARTICIPATION_FLAG_WEIGHTS):
        unslashed = active_prev & ~cols.slashed & _flag_mask(prev_part, flag_index)
        ui = cols.sum_effective(preset, unslashed) // inc
        comp = np.zeros(n, np.int64)
        if not in_leak:
            numerator = base * (weight * ui)
            comp[unslashed] = numerator[unslashed] // (
                active_increments * sc.WEIGHT_DENOMINATOR
            )
        if flag_index != sc.TIMELY_HEAD_FLAG_INDEX:
            miss = eligible & ~unslashed
            comp[miss] = -((base[miss] * weight) // sc.WEIGHT_DENOMINATOR)
        out[names[flag_index]] = comp
        for eff in distinct_eff:
            b = eff // inc * base_per_increment
            out["ideal"][eff][names[flag_index]] = (
                0 if in_leak else b * weight * ui // (active_increments * sc.WEIGHT_DENOMINATOR)
            )
    quotient = (
        preset.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
        if sc.fork_of(st) == "altair"
        else preset.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    )
    inact = np.zeros(n, np.int64)
    miss_tgt = eligible & ~unslashed_prev_tgt
    inact[miss_tgt] = -(
        (cols.eff[miss_tgt] * scores[miss_tgt]) // (spec.inactivity_score_bias * quotient)
    )
    out["inactivity"] = inact
    return out


# ---------------------------------------------------------------------------
# pure pre-mutation bound checks (Fallback may only come from these)
# ---------------------------------------------------------------------------

def _check_altair_reward_bounds(
    preset: Preset, cols: Columns, active_prev: np.ndarray,
    prev_part: np.ndarray, total: int,
) -> None:
    from .. import epoch as sc
    from ..helpers import integer_squareroot

    if not cols.n:
        return
    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    base_per_increment = inc * preset.BASE_REWARD_FACTOR // integer_squareroot(total)
    base_max = int(cols.eff.max()) // inc * base_per_increment  # base monotone in eff
    for flag_index, weight in enumerate(sc.PARTICIPATION_FLAG_WEIGHTS):
        unslashed = active_prev & ~cols.slashed & _flag_mask(prev_part, flag_index)
        ui = cols.sum_effective(preset, unslashed) // inc
        if base_max * weight * max(ui, 1) >= (1 << 62):
            raise Fallback("altair reward product exceeds int64")


def _check_phase0_reward_bounds(
    preset: Preset, cols: Columns, pre: dict, total: int
) -> None:
    from ..helpers import integer_squareroot

    if not cols.n:
        return
    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    base_max = (
        int(cols.eff.max())
        * preset.BASE_REWARD_FACTOR
        // integer_squareroot(total)
        // _BASE_REWARDS_PER_EPOCH
    )
    for name in ("source_att", "target_att", "head_att"):
        ai = cols.sum_effective(preset, pre[name] & ~cols.slashed) // inc
        if base_max * max(ai, 1) >= (1 << 62):
            raise Fallback("phase0 reward product exceeds int64")


# ---------------------------------------------------------------------------
# altair passes
# ---------------------------------------------------------------------------

def _inactivity_updates(
    spec: ChainSpec,
    scores: np.ndarray,
    eligible: np.ndarray,
    unslashed_prev_tgt: np.ndarray,
    in_leak: bool,
) -> np.ndarray:
    out = scores.copy()
    hit = eligible & unslashed_prev_tgt
    miss = eligible & ~unslashed_prev_tgt
    out[hit] -= np.minimum(1, out[hit])
    out[miss] += spec.inactivity_score_bias
    if not in_leak:
        out[eligible] -= np.minimum(
            spec.inactivity_score_recovery_rate, out[eligible]
        )
    return out


def _altair_deltas(
    preset: Preset,
    spec: ChainSpec,
    cols: Columns,
    fork: str,
    total: int,
    eligible: np.ndarray,
    active_prev: np.ndarray,
    prev_part: np.ndarray,
    unslashed_prev_tgt: np.ndarray,
    scores: np.ndarray,
    in_leak: bool,
):
    from .. import epoch as sc
    from ..helpers import integer_squareroot

    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    base_per_increment = inc * preset.BASE_REWARD_FACTOR // integer_squareroot(total)
    base = (cols.eff // inc) * base_per_increment
    active_increments = total // inc
    rewards = np.zeros(cols.n, np.int64)
    penalties = np.zeros(cols.n, np.int64)

    # int64-exactness: base <= (eff//inc)*inc*64/sqrt(total) <= 64*sqrt(total)
    # * (eff_max/total)... bounded directly instead:
    base_max = int(base.max()) if cols.n else 0

    for flag_index, weight in enumerate(sc.PARTICIPATION_FLAG_WEIGHTS):
        unslashed = active_prev & ~cols.slashed & _flag_mask(prev_part, flag_index)
        unslashed_increments = cols.sum_effective(preset, unslashed) // inc
        # pre-checked by _check_altair_reward_bounds; corruption-proof crash
        # is preferable to a post-mutation Fallback here
        assert base_max * weight * max(unslashed_increments, 1) < (1 << 62)
        if not in_leak:
            numerator = base * (weight * unslashed_increments)
            rewards[unslashed] += numerator[unslashed] // (
                active_increments * sc.WEIGHT_DENOMINATOR
            )
        if flag_index != sc.TIMELY_HEAD_FLAG_INDEX:
            miss = eligible & ~unslashed
            penalties[miss] += (base[miss] * weight) // sc.WEIGHT_DENOMINATOR

    quotient = (
        preset.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
        if fork == "altair"
        else preset.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    )
    miss_tgt = eligible & ~unslashed_prev_tgt
    # eff < 2^36 and scores < 2^25 (guarded) => product < 2^61
    penalty_numerator = cols.eff[miss_tgt] * scores[miss_tgt]
    penalties[miss_tgt] += penalty_numerator // (
        spec.inactivity_score_bias * quotient
    )
    return rewards, penalties


# ---------------------------------------------------------------------------
# phase0 passes
# ---------------------------------------------------------------------------

def _phase0_precompute(preset: Preset, state, cols: Columns, prev: int, cur: int):
    """Pure precomputation of attester masks from pending attestations.

    Builds, per matching category, a bool[n] attester mask, plus the
    per-validator best (lowest inclusion-delay, earliest in list order)
    source attestation's delay and proposer. One CommitteeCache per epoch
    (the scalar path's per-attestation cache rebuild is the main reason
    it cannot scale)."""
    from ..epoch import _matching_attestations
    from ..helpers import (
        CommitteeCache,
        get_block_root,
        get_block_root_at_slot,
    )

    n = cols.n
    out = {
        "source_att": np.zeros(n, bool),
        "target_att": np.zeros(n, bool),
        "head_att": np.zeros(n, bool),
        "target_att_cur": np.zeros(n, bool),
    }

    caches: dict[int, CommitteeCache] = {}

    def attesters(a, epoch):
        cache = caches.get(epoch)
        if cache is None:
            cache = caches[epoch] = CommitteeCache(preset, state, epoch)
        committee = cache.committee(int(a.data.slot), int(a.data.index))
        bits = np.fromiter(a.aggregation_bits, bool, count=len(a.aggregation_bits))
        if len(bits) != len(committee):
            raise Fallback("aggregation bits length != committee size")
        return committee[bits]

    # current-epoch target attesters (justification only)
    cur_target_root = get_block_root(preset, state, cur)
    for a in _matching_attestations(preset, state, cur):
        if bytes(a.data.target.root) == bytes(cur_target_root):
            out["target_att_cur"][attesters(a, cur)] = True

    prev_target_root = get_block_root(preset, state, prev)
    atts = list(_matching_attestations(preset, state, prev))
    if len(atts) >= 1 << 20:
        raise Fallback("too many pending attestations for keyed min trick")

    # per-validator best source attestation: min over (delay, list position)
    best_key = np.full(n, np.iinfo(np.int64).max, np.int64)
    att_proposer = np.zeros(max(len(atts), 1), np.int64)
    att_delay = np.zeros(max(len(atts), 1), np.int64)
    for pos, a in enumerate(atts):
        who = attesters(a, prev)
        out["source_att"][who] = True
        delay = int(a.inclusion_delay)
        if not 1 <= delay < (1 << 20):
            # <1 would divide by zero; huge values would overflow the
            # int64 keyed-min trick below — scalar big-ints handle both
            raise Fallback("inclusion delay outside keyed-min range")
        att_proposer[pos] = int(a.proposer_index)
        att_delay[pos] = delay
        np.minimum.at(best_key, who, delay * (1 << 20) + pos)
        is_target = bytes(a.data.target.root) == bytes(prev_target_root)
        if is_target:
            out["target_att"][who] = True
            if bytes(a.data.beacon_block_root) == bytes(
                get_block_root_at_slot(preset, state, int(a.data.slot))
            ):
                out["head_att"][who] = True

    out["best_att_pos"] = best_key % (1 << 20)
    out["att_proposer"] = att_proposer
    out["att_delay"] = att_delay
    return out


def _phase0_deltas(
    preset: Preset,
    cols: Columns,
    pre: dict,
    total: int,
    eligible: np.ndarray,
    in_leak: bool,
    finality_delay: int,
):
    from ..helpers import integer_squareroot

    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    base = (
        cols.eff * preset.BASE_REWARD_FACTOR
        // integer_squareroot(total)
        // _BASE_REWARDS_PER_EPOCH
    )
    base_max = int(base.max()) if cols.n else 0
    rewards = np.zeros(cols.n, np.int64)
    penalties = np.zeros(cols.n, np.int64)

    for name in ("source_att", "target_att", "head_att"):
        unslashed = pre[name] & ~cols.slashed
        attesting_balance = cols.sum_effective(preset, unslashed)
        attesting_increments = attesting_balance // inc
        # pre-checked by _check_phase0_reward_bounds
        assert base_max * max(attesting_increments, 1) < (1 << 62)
        hit = eligible & unslashed
        if in_leak:
            rewards[hit] += base[hit]
        else:
            rewards[hit] += (base[hit] * attesting_increments) // (total // inc)
        miss = eligible & ~unslashed
        penalties[miss] += base[miss]

    # inclusion delay: unslashed source attesters reward themselves (scaled
    # by 1/delay) and the including block's proposer.
    src = pre["source_att"] & ~cols.slashed
    idx = np.nonzero(src)[0]
    if len(idx):
        pos = pre["best_att_pos"][idx]
        proposer_reward = base[idx] // preset.PROPOSER_REWARD_QUOTIENT
        np.add.at(rewards, pre["att_proposer"][pos], proposer_reward)
        max_attester = base[idx] - proposer_reward
        rewards[idx] += max_attester // pre["att_delay"][pos]

    if in_leak:
        penalties[eligible] += (
            _BASE_REWARDS_PER_EPOCH * base[eligible]
            - base[eligible] // preset.PROPOSER_REWARD_QUOTIENT
        )
        tgt_unslashed = pre["target_att"] & ~cols.slashed
        miss = eligible & ~tgt_unslashed
        # eff < 2^36, delay < 2^24 (guarded) => product < 2^60
        penalties[miss] += (
            cols.eff[miss] * finality_delay // preset.INACTIVITY_PENALTY_QUOTIENT
        )
    return rewards, penalties


# ---------------------------------------------------------------------------
# shared tail passes (registry / slashings / effective balances)
# ---------------------------------------------------------------------------

def _registry_updates(
    preset: Preset, spec: ChainSpec, state, cols: Columns, cur: int,
    active_cur: np.ndarray,
) -> None:
    from ..helpers import compute_activation_exit_epoch

    # activation-queue eligibility marking
    newly_eligible = (cols.act_elig == FF_U64) & (
        cols.eff == preset.MAX_EFFECTIVE_BALANCE
    )
    for i in np.nonzero(newly_eligible)[0]:
        cols.vals[i].activation_eligibility_epoch = cur + 1
    cols.act_elig[newly_eligible] = np.uint64(cur + 1)

    churn_limit = max(
        spec.min_per_epoch_churn_limit,
        int(active_cur.sum()) // spec.churn_limit_quotient,
    )

    # ejections (sequential exit-queue assignment over the few hits,
    # replicating initiate_validator_exit's fresh max/count per call)
    eject = active_cur & (cols.eff <= spec.ejection_balance) & (cols.exit == FF_U64)
    eject_idx = np.nonzero(eject)[0]
    if len(eject_idx):
        exited = cols.exit != FF_U64
        exit_queue_epoch = compute_activation_exit_epoch(preset, cur)
        if exited.any():
            exit_queue_epoch = max(exit_queue_epoch, int(cols.exit[exited].max()))
        churn = int((cols.exit == np.uint64(exit_queue_epoch)).sum())
        delay = spec.min_validator_withdrawability_delay
        for i in eject_idx:
            if churn >= churn_limit:
                exit_queue_epoch += 1
                churn = 0
            v = cols.vals[i]
            v.exit_epoch = exit_queue_epoch
            v.withdrawable_epoch = exit_queue_epoch + delay
            cols.exit[i] = np.uint64(exit_queue_epoch)
            cols.wd[i] = np.uint64(exit_queue_epoch + delay)
            churn += 1

    # activation queue: ordered by (eligibility epoch, index), churn-limited
    cand = (cols.act_elig <= np.uint64(state.finalized_checkpoint.epoch)) & (
        cols.act == FF_U64
    )
    ci = np.nonzero(cand)[0]
    if len(ci):
        order = np.lexsort((ci, cols.act_elig[ci]))
        activation_epoch = compute_activation_exit_epoch(preset, cur)
        for i in ci[order][:churn_limit]:
            cols.vals[i].activation_epoch = activation_epoch
            cols.act[i] = np.uint64(activation_epoch)


def _process_slashings(
    preset: Preset, state, cols: Columns, fork: str, cur: int, total: int
) -> None:
    mult = {
        "phase0": preset.PROPORTIONAL_SLASHING_MULTIPLIER,
        "altair": preset.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
        "bellatrix": preset.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
    }[fork]
    adjusted = min(sum(state.slashings) * mult, total)
    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    mask = cols.slashed & (
        np.uint64(cur + preset.EPOCHS_PER_SLASHINGS_VECTOR // 2) == cols.wd
    )
    if mask.any():
        # (eff//inc) * adjusted can brush 2^64 at the guard bounds, and the
        # hit set is tiny (slashed validators at their mid-withdrawability
        # epoch) — compute these few penalties with exact python ints.
        penalty = np.fromiter(
            (
                int(e) // inc * adjusted // total * inc
                for e in cols.eff[mask]
            ),
            np.int64,
            count=int(mask.sum()),
        )
        cols.balances[mask] = np.maximum(cols.balances[mask] - penalty, 0)


def _effective_balance_updates(preset: Preset, cols: Columns) -> None:
    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    hysteresis = inc // preset.HYSTERESIS_QUOTIENT
    down = hysteresis * preset.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis * preset.HYSTERESIS_UPWARD_MULTIPLIER
    mask = (cols.balances + down < cols.eff) | (cols.eff + up < cols.balances)
    if mask.any():
        new_eff = np.minimum(
            cols.balances - cols.balances % inc, preset.MAX_EFFECTIVE_BALANCE
        )
        for i in np.nonzero(mask)[0]:
            cols.vals[i].effective_balance = int(new_eff[i])
        cols.eff[mask] = new_eff[mask]
