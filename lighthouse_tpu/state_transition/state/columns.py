"""Structure-of-arrays view of the validator registry + balances.

One extraction pass over ``state.validators`` yields int64/uint64/bool
columns; every epoch-processing pass then runs as numpy vector ops. All
arithmetic here is exact integer math: the extraction asserts value
bounds under which every downstream product provably fits int64, and
raises :class:`Fallback` otherwise so the caller can take the scalar
(python big-int) path instead. Mirrors the layout of the reference's
``BeaconState`` validator vectors (``consensus/types/src/beacon_state.rs``)
rather than its per-validator struct iteration.
"""

from __future__ import annotations

import numpy as np

from ...types.chain_spec import FAR_FUTURE_EPOCH

FF_U64 = np.uint64(FAR_FUTURE_EPOCH)

# Bounds under which every product computed by the columnar passes fits
# int64 (see the per-pass derivations in epoch.py). Real networks sit
# orders of magnitude below all of them.
EFF_BALANCE_LIMIT = 1 << 36      # max effective balance (gwei); mainnet max 32e9 < 2^35
BALANCE_LIMIT = 1 << 62          # max balance (gwei)
SCORE_LIMIT = 1 << 25            # max inactivity score (eff * score < 2^61)
TOTAL_BALANCE_LIMIT = 1 << 58    # max total active balance (adjusted * (eff//inc) < 2^63)
FINALITY_DELAY_LIMIT = 1 << 24   # max finality delay (eff * delay < 2^60)


class Fallback(Exception):
    """Columnar preconditions not met — caller must use the scalar path.

    Raised only from pure (non-mutating) precondition checks, so the
    state is guaranteed untouched when it propagates.
    """


class Columns:
    """Columnar registry view. Mutating passes keep the arrays and the
    underlying validator objects in sync (arrays are authoritative
    mid-epoch; objects are written through immediately for the sparse
    fields and wholesale for balances at the end)."""

    __slots__ = (
        "n", "vals", "eff", "slashed", "act_elig", "act", "exit", "wd", "balances",
    )

    @classmethod
    def from_state(cls, state) -> "Columns":
        vals = state.validators
        n = len(vals)
        c = cls()
        c.n = n
        c.vals = vals
        try:
            c.eff = np.fromiter(
                (v.effective_balance for v in vals), np.int64, count=n
            )
            c.balances = np.fromiter(state.balances, np.int64, count=n)
        except OverflowError as e:  # value >= 2^63: scalar big-int territory
            raise Fallback(str(e)) from e
        c.slashed = np.fromiter((bool(v.slashed) for v in vals), bool, count=n)
        c.act_elig = np.fromiter(
            (v.activation_eligibility_epoch for v in vals), np.uint64, count=n
        )
        c.act = np.fromiter((v.activation_epoch for v in vals), np.uint64, count=n)
        c.exit = np.fromiter((v.exit_epoch for v in vals), np.uint64, count=n)
        c.wd = np.fromiter(
            (v.withdrawable_epoch for v in vals), np.uint64, count=n
        )
        if n and (
            int(c.eff.max()) >= EFF_BALANCE_LIMIT
            or int(c.balances.max()) >= BALANCE_LIMIT
        ):
            raise Fallback("balance columns exceed exact-int64 bounds")
        return c

    def active_mask(self, epoch: int) -> np.ndarray:
        e = np.uint64(epoch)
        return (self.act <= e) & (e < self.exit)

    def total_active_balance(self, preset, epoch: int) -> int:
        """Spec get_total_active_balance (floored at one increment)."""
        total = int(self.eff[self.active_mask(epoch)].sum())
        total = max(preset.EFFECTIVE_BALANCE_INCREMENT, total)
        if total >= TOTAL_BALANCE_LIMIT:
            raise Fallback("total active balance exceeds exact-int64 bounds")
        return total

    def sum_effective(self, preset, mask: np.ndarray) -> int:
        """Spec get_total_balance over a mask (floored at one increment)."""
        return max(
            preset.EFFECTIVE_BALANCE_INCREMENT, int(self.eff[mask].sum())
        )

    def write_balances(self, state) -> None:
        state.balances = self.balances.tolist()
