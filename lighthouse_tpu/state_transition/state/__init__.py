"""Columnar (structure-of-arrays) state views for batched epoch
processing — the numpy tier of the per-epoch pipeline promised in
``state_transition/epoch.py``.

The reference's per-epoch processing is compiled Rust over struct-of-
validator arrays (``consensus/state_processing/src/per_epoch_processing/``);
a TPU-native framework holds the per-validator columns as flat arrays so
every pass is a handful of vector ops over the full validator set instead
of a million-iteration interpreter loop. These views are also the layout
a future device (jnp) tier consumes unchanged.
"""

from .columns import Columns, Fallback
from .epoch import process_epoch_columnar

__all__ = ["Columns", "Fallback", "process_epoch_columnar"]
