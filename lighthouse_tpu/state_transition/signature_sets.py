"""SignatureSet constructors: every signed object in the system -> the
(signature, pubkeys, signing_root) triple the batch verifier consumes.

This is the re-design of the reference's
``consensus/state_processing/src/per_block_processing/signature_sets.rs``
(set constructors) + ``block_signature_verifier.rs`` (the accumulator):
the accumulator here is a plain list whose one consumer is
``bls.verify_signature_sets`` — on the ``tpu`` backend that means ONE
fixed-shape device batch for the whole block (vs the reference's
rayon-chunked CPU loop, ``block_signature_verifier.rs:374-382``).

Deposits are deliberately excluded (spec: deposit signatures are checked
individually with the genesis domain and may legitimately be invalid —
reference ``block_signature_verifier.rs:116-117``).
"""

from __future__ import annotations

from typing import Callable

from ..crypto import bls
from .. import ssz
from ..ssz import hash_tree_root
from ..types import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    ChainSpec,
    compute_signing_root,
    get_domain,
    types_for,
)
from ..types.preset import Preset
from .helpers import get_attesting_indices, get_beacon_proposer_index

PubkeyResolver = Callable[[int], "bls.PublicKey | None"]


class SignatureSetError(ValueError):
    pass


def _pk(resolver: PubkeyResolver, index: int) -> bls.PublicKey:
    pk = resolver(index)
    if pk is None:
        raise SignatureSetError(f"unknown validator index {index}")
    return pk


def _sig(raw: bytes) -> bls.Signature:
    return bls.Signature.deserialize(raw)


def block_proposal_set(
    preset: Preset, spec: ChainSpec, state, signed_block, resolver: PubkeyResolver,
    block_root: bytes | None = None,
) -> bls.SignatureSet:
    block = signed_block.message
    epoch = block.slot // preset.SLOTS_PER_EPOCH
    domain = get_domain(spec, state, DOMAIN_BEACON_PROPOSER, epoch)
    if block_root is None:
        block_root = hash_tree_root(type(block), block)
    root = compute_signing_root(None, block_root, domain)
    return bls.SignatureSet.single_pubkey(
        _sig(signed_block.signature), _pk(resolver, block.proposer_index), root,
        signing_index=block.proposer_index,
    )


def randao_set(
    preset: Preset, spec: ChainSpec, state, block, resolver: PubkeyResolver
) -> bls.SignatureSet:
    epoch = block.slot // preset.SLOTS_PER_EPOCH
    domain = get_domain(spec, state, DOMAIN_RANDAO, epoch)
    root = compute_signing_root(ssz.Uint64, epoch, domain)
    return bls.SignatureSet.single_pubkey(
        _sig(block.body.randao_reveal), _pk(resolver, block.proposer_index), root,
        signing_index=block.proposer_index,
    )


def proposer_slashing_sets(
    preset: Preset, spec: ChainSpec, state, slashing, resolver: PubkeyResolver
) -> list[bls.SignatureSet]:
    out = []
    for signed_header in (slashing.signed_header_1, slashing.signed_header_2):
        header = signed_header.message
        epoch = header.slot // preset.SLOTS_PER_EPOCH
        domain = get_domain(spec, state, DOMAIN_BEACON_PROPOSER, epoch)
        root = compute_signing_root(type(header), header, domain)
        out.append(
            bls.SignatureSet.single_pubkey(
                _sig(signed_header.signature),
                _pk(resolver, header.proposer_index),
                root,
                signing_index=header.proposer_index,
            )
        )
    return out


def indexed_attestation_set(
    preset: Preset, spec: ChainSpec, state, indexed, resolver: PubkeyResolver
) -> bls.SignatureSet:
    t = types_for(preset)
    domain = get_domain(spec, state, DOMAIN_BEACON_ATTESTER, indexed.data.target.epoch)
    root = compute_signing_root(t.AttestationData, indexed.data, domain)
    indices = [int(i) for i in indexed.attesting_indices]
    pks = [_pk(resolver, i) for i in indices]
    return bls.SignatureSet.multiple_pubkeys(
        _sig(indexed.signature), pks, root, signing_indices=indices
    )


def attestation_set(
    preset: Preset, spec: ChainSpec, state, attestation, resolver: PubkeyResolver
) -> bls.SignatureSet:
    from .helpers import get_indexed_attestation

    indexed = get_indexed_attestation(preset, state, attestation)
    return indexed_attestation_set(preset, spec, state, indexed, resolver)


def attester_slashing_sets(
    preset: Preset, spec: ChainSpec, state, slashing, resolver: PubkeyResolver
) -> list[bls.SignatureSet]:
    return [
        indexed_attestation_set(preset, spec, state, slashing.attestation_1, resolver),
        indexed_attestation_set(preset, spec, state, slashing.attestation_2, resolver),
    ]


def exit_set(
    preset: Preset, spec: ChainSpec, state, signed_exit, resolver: PubkeyResolver
) -> bls.SignatureSet:
    t = types_for(preset)
    exit_msg = signed_exit.message
    domain = get_domain(spec, state, DOMAIN_VOLUNTARY_EXIT, exit_msg.epoch)
    root = compute_signing_root(t.VoluntaryExit, exit_msg, domain)
    return bls.SignatureSet.single_pubkey(
        _sig(signed_exit.signature), _pk(resolver, exit_msg.validator_index), root,
        signing_index=exit_msg.validator_index,
    )


def sync_aggregate_set(
    preset: Preset, spec: ChainSpec, state, block_slot: int, sync_aggregate,
    resolver_by_pubkey_bytes,
) -> "bls.SignatureSet | None":
    """Sync committee signs the previous slot's block root. Returns None if
    no bits are set AND the signature is the infinity point (valid empty
    aggregate, spec eth2_fast_aggregate_verify G2_POINT_AT_INFINITY rule)."""
    from .helpers import get_block_root_at_slot

    t = types_for(preset)
    bits = sync_aggregate.sync_committee_bits
    participant_pubkeys = [
        pk_bytes
        for pk_bytes, bit in zip(state.current_sync_committee.pubkeys, bits)
        if bit
    ]
    sig = _sig(sync_aggregate.sync_committee_signature)
    if not participant_pubkeys:
        if sig.serialize() == bls.INFINITY_SIGNATURE:
            return None
        raise SignatureSetError("empty sync aggregate with non-infinity signature")
    prev_slot = max(block_slot, 1) - 1
    domain = get_domain(
        spec, state, DOMAIN_SYNC_COMMITTEE, prev_slot // preset.SLOTS_PER_EPOCH
    )
    root = compute_signing_root(
        None, get_block_root_at_slot(preset, state, prev_slot), domain
    )
    pks = [resolver_by_pubkey_bytes(b) for b in participant_pubkeys]
    if any(p is None for p in pks):
        raise SignatureSetError("unknown sync-committee pubkey")
    return bls.SignatureSet.multiple_pubkeys(sig, pks, root)


def aggregate_and_proof_sets(
    preset: Preset, spec: ChainSpec, state, signed_agg, resolver: PubkeyResolver
) -> list[bls.SignatureSet]:
    """The three sets of a gossip aggregate (reference:
    ``attestation_verification/batch.rs:77-107``): selection proof,
    aggregator signature, aggregate attestation signature."""
    t = types_for(preset)
    msg = signed_agg.message
    att = msg.aggregate
    epoch = att.data.slot // preset.SLOTS_PER_EPOCH

    sel_domain = get_domain(spec, state, DOMAIN_SELECTION_PROOF, epoch)
    sel_root = compute_signing_root(ssz.Uint64, att.data.slot, sel_domain)
    selection = bls.SignatureSet.single_pubkey(
        _sig(msg.selection_proof), _pk(resolver, msg.aggregator_index), sel_root,
        signing_index=msg.aggregator_index,
    )

    agg_domain = get_domain(spec, state, DOMAIN_AGGREGATE_AND_PROOF, epoch)
    agg_root = compute_signing_root(t.AggregateAndProof, msg, agg_domain)
    aggregator = bls.SignatureSet.single_pubkey(
        _sig(signed_agg.signature), _pk(resolver, msg.aggregator_index), agg_root,
        signing_index=msg.aggregator_index,
    )

    attestation = attestation_set(preset, spec, state, att, resolver)
    return [selection, aggregator, attestation]


def deposit_signature_is_valid(preset: Preset, spec: ChainSpec, deposit_data) -> bool:
    """Deposits verify individually against the GENESIS fork version and an
    empty genesis_validators_root (spec is_valid_deposit_signature); invalid
    signatures skip the deposit rather than fail the block."""
    from ..types import compute_domain, DOMAIN_DEPOSIT

    t = types_for(preset)
    try:
        pk = bls.PublicKey.deserialize(deposit_data.pubkey)
        sig = bls.Signature.deserialize(deposit_data.signature)
        domain = compute_domain(
            spec, DOMAIN_DEPOSIT, spec.genesis_fork_version, bytes(32)
        )
        msg = t.DepositMessage(
            pubkey=deposit_data.pubkey,
            withdrawal_credentials=deposit_data.withdrawal_credentials,
            amount=deposit_data.amount,
        )
        root = compute_signing_root(t.DepositMessage, msg, domain)
        # verify() may ALSO raise BlsError now: decompression is lazy, so
        # an off-curve x surfaces here, and must skip the deposit, not
        # fail the block (spec is_valid_deposit_signature semantics)
        return sig.verify(pk, root)
    except bls.BlsError:
        return False


class BlockSignatureAccumulator:
    """Collects every signature set of a signed block, then verifies them
    as ONE batch (the ``VerifyBulk`` strategy of the reference's
    ``BlockSignatureVerifier``, ``block_signature_verifier.rs:66-132``)."""

    def __init__(self, preset: Preset, spec: ChainSpec, state, resolver: PubkeyResolver,
                 resolver_by_pubkey_bytes=None):
        self.preset = preset
        self.spec = spec
        self.state = state
        self.resolver = resolver
        self.resolver_by_pubkey_bytes = resolver_by_pubkey_bytes
        self.sets: list[bls.SignatureSet] = []

    def include_all(self, signed_block, block_root: bytes | None = None) -> None:
        self.include_block_proposal(signed_block, block_root)
        block = signed_block.message
        self.include_randao_reveal(block)
        self.include_operations(signed_block)

    def include_operations(self, signed_block) -> None:
        """Every body operation's sets: slashings, attestations, exits,
        sync aggregate (reference include_* methods,
        ``block_signature_verifier.rs:135-340``)."""
        block = signed_block.message
        body = block.body
        for ps in body.proposer_slashings:
            self.sets.extend(
                proposer_slashing_sets(self.preset, self.spec, self.state, ps, self.resolver)
            )
        for asl in body.attester_slashings:
            self.sets.extend(
                attester_slashing_sets(self.preset, self.spec, self.state, asl, self.resolver)
            )
        for att in body.attestations:
            self.sets.append(
                attestation_set(self.preset, self.spec, self.state, att, self.resolver)
            )
        for ex in body.voluntary_exits:
            self.sets.append(
                exit_set(self.preset, self.spec, self.state, ex, self.resolver)
            )
        if hasattr(body, "sync_aggregate"):
            s = sync_aggregate_set(
                self.preset,
                self.spec,
                self.state,
                block.slot,
                body.sync_aggregate,
                self.resolver_by_pubkey_bytes,
            )
            if s is not None:
                self.sets.append(s)

    def include_block_proposal(self, signed_block, block_root=None) -> None:
        self.sets.append(
            block_proposal_set(
                self.preset, self.spec, self.state, signed_block, self.resolver, block_root
            )
        )

    def include_randao_reveal(self, block) -> None:
        self.sets.append(
            randao_set(self.preset, self.spec, self.state, block, self.resolver)
        )

    def verify(self) -> bool:
        return bls.verify_signature_sets(self.sets)
