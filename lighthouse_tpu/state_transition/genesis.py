"""Genesis state construction (reference: ``beacon_node/genesis`` +
``consensus/state_processing/src/genesis.rs``): from deposits, plus the
deterministic interop genesis used by every multi-node test rig
(``common/eth2_interop_keypairs`` — sk_i = int_le(sha256(i_le32)) mod r).
"""

from __future__ import annotations

import hashlib

from ..crypto import bls
from ..crypto.params import R as CURVE_ORDER
from ..ssz import hash_tree_root
from ..types.chain_spec import ChainSpec, FAR_FUTURE_EPOCH
from ..types.containers import types_for
from ..types.preset import Preset
from .block import apply_deposit
from .epoch import get_next_sync_committee
from .helpers import get_active_validator_indices

GENESIS_EPOCH = 0
BLS_WITHDRAWAL_PREFIX = b"\x00"


def interop_secret_key(index: int) -> bls.SecretKey:
    """Deterministic insecure interop key (eth2.0-pm mocked-start rule)."""
    pre = index.to_bytes(8, "little") + bytes(24)
    k = int.from_bytes(hashlib.sha256(pre).digest(), "little") % CURVE_ORDER
    return bls.SecretKey(k)


def _genesis_core(preset: Preset, spec: ChainSpec, fork_name: str, t):
    state = t.state[fork_name]()
    body = t.block_body[fork_name]()
    state.latest_block_header = t.BeaconBlockHeader(
        body_root=hash_tree_root(body)
    )
    if fork_name == "phase0":
        version = spec.genesis_fork_version
        prev = spec.genesis_fork_version
    elif fork_name == "altair":
        version, prev = spec.altair_fork_version, spec.genesis_fork_version
    else:
        version, prev = spec.bellatrix_fork_version, spec.altair_fork_version
    state.fork = t.Fork(
        previous_version=prev, current_version=version, epoch=GENESIS_EPOCH
    )
    return state


def initialize_beacon_state_from_eth1(
    preset: Preset,
    spec: ChainSpec,
    eth1_block_hash: bytes,
    eth1_timestamp: int,
    deposits,
    fork_name: str = "phase0",
):
    """Spec initialize_beacon_state_from_eth1 (with the per-fork genesis
    variants the reference supports for testnets)."""
    from .merkle import compute_merkle_root

    t = types_for(preset)
    state = _genesis_core(preset, spec, fork_name, t)
    state.genesis_time = eth1_timestamp + spec.genesis_delay
    state.eth1_data = t.Eth1Data(
        deposit_count=len(deposits), block_hash=eth1_block_hash
    )
    state.randao_mixes = [eth1_block_hash] * preset.EPOCHS_PER_HISTORICAL_VECTOR

    # process deposits with an incrementally-updated deposit root
    leaves = [hash_tree_root(t.DepositData, d.data) for d in deposits]
    for i, deposit in enumerate(deposits):
        sub = compute_merkle_root(leaves[: i + 1], preset.DEPOSIT_CONTRACT_TREE_DEPTH)
        from ..ssz.sha256 import hash32_concat

        state.eth1_data.deposit_root = hash32_concat(
            sub, (i + 1).to_bytes(32, "little")
        )
        state.eth1_deposit_index = i
        # bypass the merkle proof (computed root IS the proof target)
        apply_deposit(preset, spec, state, deposit.data, fork_name)
        state.eth1_deposit_index = i + 1

    # activations
    for v in state.validators:
        if v.effective_balance == preset.MAX_EFFECTIVE_BALANCE:
            v.activation_eligibility_epoch = GENESIS_EPOCH
            v.activation_epoch = GENESIS_EPOCH
    validators_tpe = dict(t.state[fork_name].fields)["validators"]
    state.genesis_validators_root = hash_tree_root(validators_tpe, state.validators)
    if fork_name in ("altair", "bellatrix"):
        sync = get_next_sync_committee(preset, state)
        state.current_sync_committee = sync
        state.next_sync_committee = get_next_sync_committee(preset, state)
    return state


def is_valid_genesis_state(preset: Preset, spec: ChainSpec, state) -> bool:
    if state.genesis_time < spec.min_genesis_time:
        return False
    return (
        len(get_active_validator_indices(state, GENESIS_EPOCH))
        >= spec.min_genesis_active_validator_count
    )


def interop_genesis_state(
    preset: Preset,
    spec: ChainSpec,
    validator_count: int,
    genesis_time: int = 0,
    fork_name: str = "phase0",
    eth1_block_hash: bytes = b"\x42" * 32,
):
    """Quick-start genesis: deterministic interop validators, all at max
    effective balance and active from epoch 0 (the reference's interop
    genesis used by ``BeaconChainHarness`` and the simulator)."""
    t = types_for(preset)
    state = _genesis_core(preset, spec, fork_name, t)
    state.genesis_time = genesis_time
    state.randao_mixes = [eth1_block_hash] * preset.EPOCHS_PER_HISTORICAL_VECTOR
    state.eth1_data = t.Eth1Data(
        deposit_count=validator_count, block_hash=eth1_block_hash
    )
    state.eth1_deposit_index = validator_count

    validators = []
    balances = []
    for i in range(validator_count):
        sk = interop_secret_key(i)
        pk = sk.public_key().serialize()
        wc = BLS_WITHDRAWAL_PREFIX + hashlib.sha256(pk).digest()[1:]
        validators.append(
            t.Validator(
                pubkey=pk,
                withdrawal_credentials=wc,
                effective_balance=preset.MAX_EFFECTIVE_BALANCE,
                slashed=False,
                activation_eligibility_epoch=GENESIS_EPOCH,
                activation_epoch=GENESIS_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        )
        balances.append(preset.MAX_EFFECTIVE_BALANCE)
    state.validators = validators
    state.balances = balances
    if fork_name in ("altair", "bellatrix"):
        state.previous_epoch_participation = [0] * validator_count
        state.current_epoch_participation = [0] * validator_count
        state.inactivity_scores = [0] * validator_count

    validators_tpe = dict(t.state[fork_name].fields)["validators"]
    state.genesis_validators_root = hash_tree_root(validators_tpe, state.validators)
    if fork_name in ("altair", "bellatrix"):
        sync = get_next_sync_committee(preset, state)
        state.current_sync_committee = sync
        state.next_sync_committee = get_next_sync_committee(preset, state)
    return state
