"""Spec state-transition function (reference layer:
``consensus/state_processing``, SURVEY.md §2.3): slot/epoch/block
processing, shuffling/committees, signature-set accumulation (the feeder
of the TPU BLS backend), genesis, and fork upgrades.
"""

from .block import (
    BlockProcessingError,
    process_block,
    state_pubkey_resolver,
)
from .epoch import fork_of, process_epoch
from .genesis import (
    initialize_beacon_state_from_eth1,
    interop_genesis_state,
    interop_secret_key,
    is_valid_genesis_state,
)
from .merkle import compute_merkle_root, is_valid_merkle_branch
from .replay import replay_blocks, store_replayer
from .mutators import initiate_validator_exit, slash_validator
from .shuffle import compute_shuffled_index, shuffle_list, unshuffle_list
from .signature_sets import BlockSignatureAccumulator
from .slot import partial_state_advance, per_slot_processing, process_slot, state_transition
from .upgrade import maybe_upgrade_state, upgrade_to_altair, upgrade_to_bellatrix
from .helpers import (
    CommitteeCache,
    compute_activation_exit_epoch,
    compute_committee,
    compute_epoch_at_slot,
    compute_proposer_index,
    compute_start_slot_at_epoch,
    get_active_validator_indices,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_current_epoch,
    get_previous_epoch,
    get_randao_mix,
    get_seed,
    get_total_active_balance,
    get_total_balance,
    get_validator_churn_limit,
    integer_squareroot,
    is_active_validator,
    is_eligible_for_activation,
    is_eligible_for_activation_queue,
    is_slashable_attestation_data,
    is_slashable_validator,
    is_valid_indexed_attestation_structure,
    get_indexed_attestation,
    get_attesting_indices,
)

__all__ = [k for k in dir() if not k.startswith("_")]
