"""Block replay (reference: ``consensus/state_processing/src/block_replayer.rs``):
re-apply a chain of already-verified blocks to a base state, advancing
through empty slots, without re-verifying signatures.

Used by the store to rebuild summary states from snapshots and by
checkpoint-sync backfill.
"""

from __future__ import annotations

import copy

from ..types.chain_spec import ChainSpec
from ..types.preset import Preset
from .block import process_block
from .epoch import fork_of
from .slot import per_slot_processing


def replay_blocks(
    preset: Preset,
    spec: ChainSpec,
    base_state,
    blocks,
    target_slot: int,
    copy_state: bool = True,
):
    """Apply ``blocks`` (ascending slots, all > base_state.slot) and then
    advance empty slots to ``target_slot``. Signature verification is
    skipped — replay is only ever fed blocks that were verified on import
    (reference BlockReplayer uses NoVerification)."""
    state = copy.deepcopy(base_state) if copy_state else base_state
    for signed in blocks:
        while state.slot < signed.message.slot:
            state = per_slot_processing(preset, spec, state)
        process_block(
            preset, spec, state, signed, fork_of(state), signature_strategy="none"
        )
    while state.slot < target_slot:
        state = per_slot_processing(preset, spec, state)
    return state


def store_replayer(preset: Preset, spec: ChainSpec):
    """Adapter with the HotColdDB replayer signature."""

    def _replay(base_state, blocks, target_slot):
        return replay_blocks(preset, spec, base_state, blocks, target_slot)

    return _replay
