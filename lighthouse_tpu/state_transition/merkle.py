"""Merkle branch verification (reference: ``consensus/merkle_proof``)."""

from __future__ import annotations

from ..ssz.sha256 import hash32_concat


def is_valid_merkle_branch(
    leaf: bytes, branch, depth: int, index: int, root: bytes
) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = hash32_concat(branch[i], value)
        else:
            value = hash32_concat(value, branch[i])
    return value == root


def compute_merkle_root(leaves, depth: int) -> bytes:
    """Root of a depth-``depth`` tree over ``leaves`` (zero-padded)."""
    from ..ssz.sha256 import ZERO_HASHES

    layer = list(leaves)
    for d in range(depth):
        if len(layer) % 2:
            layer.append(ZERO_HASHES[d])
        layer = [
            hash32_concat(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)
        ] or [ZERO_HASHES[d + 1]]
    return layer[0] if layer else ZERO_HASHES[depth]
