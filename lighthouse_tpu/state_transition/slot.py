"""Per-slot processing + state advance (reference:
``consensus/state_processing/src/per_slot_processing.rs`` and
``state_advance.rs``)."""

from __future__ import annotations

from ..ssz import hash_tree_root
from ..ssz.cache import cached_state_root
from ..types.chain_spec import ChainSpec
from ..types.preset import Preset
from .epoch import process_epoch
from .upgrade import maybe_upgrade_state


def process_slot(preset: Preset, state) -> None:
    """Cache the previous state/block roots (spec process_slot)."""
    prev_state_root = cached_state_root(state)
    state.state_roots[state.slot % preset.SLOTS_PER_HISTORICAL_ROOT] = prev_state_root
    if state.latest_block_header.state_root == bytes(32):
        state.latest_block_header.state_root = prev_state_root
    prev_block_root = hash_tree_root(state.latest_block_header)
    state.block_roots[state.slot % preset.SLOTS_PER_HISTORICAL_ROOT] = prev_block_root


def per_slot_processing(preset: Preset, spec: ChainSpec, state):
    """Advance the state by one slot (epoch processing at boundaries,
    fork upgrade when the new epoch crosses a fork). Returns the state
    (same object, mutated) — possibly REPLACED by its upgraded variant."""
    process_slot(preset, state)
    if (state.slot + 1) % preset.SLOTS_PER_EPOCH == 0:
        process_epoch(preset, spec, state)
    state.slot += 1
    return maybe_upgrade_state(preset, spec, state)


def state_transition(
    preset: Preset, spec: ChainSpec, state, signed_block,
    signature_strategy: str = "individual", validate_result: bool = True,
):
    """The spec's top-level ``state_transition``: advance slots, apply the
    block, and (validate_result) require the block's claimed state root to
    match (reference ``per_block_processing`` callers + spec
    ``state_transition``). Returns the (possibly fork-upgraded) state."""
    from .block import BlockProcessingError, process_block
    from .epoch import fork_of

    block = signed_block.message
    while state.slot < block.slot:
        state = per_slot_processing(preset, spec, state)
    process_block(
        preset, spec, state, signed_block, fork_of(state),
        signature_strategy=signature_strategy,
    )
    if validate_result:
        got = cached_state_root(state)
        if got != bytes(block.state_root):
            raise BlockProcessingError(
                f"state root mismatch: block claims "
                f"{bytes(block.state_root).hex()[:12]}, got {got.hex()[:12]}"
            )
    return state


def partial_state_advance(preset: Preset, spec: ChainSpec, state, target_slot: int):
    """Advance to ``target_slot`` (reference ``partial_state_advance``:
    used before signature verification of future-slot objects)."""
    if target_slot < state.slot:
        raise ValueError("cannot advance backwards")
    while state.slot < target_slot:
        state = per_slot_processing(preset, spec, state)
    return state
