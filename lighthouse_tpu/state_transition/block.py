"""Per-block processing for phase0/altair/bellatrix (spec
``process_block``; reference:
``consensus/state_processing/src/per_block_processing.rs:91`` and the
``per_block_processing/`` modules).

Signature strategy mirrors the reference's ``BlockSignatureStrategy``
(``per_block_processing.rs:45-56``):

* ``"none"``       — trust everything (used after bulk verification)
* ``"individual"`` — verify each set as it is built
* ``"bulk"``       — accumulate every set, verify as ONE batch first
  (the TPU-native path: one device launch per block), then process with
  ``"none"``.
"""

from __future__ import annotations

from ..crypto import bls
from ..ssz import hash_tree_root
from ..types.chain_spec import ChainSpec, FAR_FUTURE_EPOCH
from ..types.containers import types_for
from ..types.preset import Preset
from . import signature_sets as sigsets
from .helpers import (
    compute_epoch_at_slot,
    get_attesting_indices,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
    get_current_epoch,
    get_indexed_attestation,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    get_total_balance,
    increase_balance,
    decrease_balance,
    integer_squareroot,
    is_active_validator,
    is_slashable_attestation_data,
    is_slashable_validator,
    is_valid_indexed_attestation_structure,
    get_block_root,
    get_block_root_at_slot,
)
from .merkle import is_valid_merkle_branch
from .mutators import (
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    add_flag,
    has_flag,
    initiate_validator_exit,
    slash_validator,
)

GENESIS_EPOCH = 0


class BlockProcessingError(ValueError):
    pass


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise BlockProcessingError(msg)


def state_pubkey_resolver(state):
    """index -> PublicKey via the state registry (deserialization-checked,
    memoized — the in-process stand-in for the beacon chain's persistent
    ValidatorPubkeyCache, ``validator_pubkey_cache.rs:20``)."""
    cache: dict[int, bls.PublicKey] = {}

    def resolve(i: int):
        if i in cache:
            return cache[i]
        if i >= len(state.validators):
            return None
        pk = bls.PublicKey.deserialize(state.validators[i].pubkey)
        cache[i] = pk
        return pk

    return resolve


def state_pubkey_bytes_resolver(state):
    cache: dict[bytes, bls.PublicKey] = {}

    def resolve(b: bytes):
        if b not in cache:
            cache[b] = bls.PublicKey.deserialize(b)
        return cache[b]

    return resolve


def _verify_set(s: bls.SignatureSet, what: str) -> None:
    _require(bls.verify_signature_sets([s]), f"invalid signature: {what}")


# ---------------------------------------------------------------------------
# process_block
# ---------------------------------------------------------------------------

def process_block(
    preset: Preset,
    spec: ChainSpec,
    state,
    signed_block,
    fork: str,
    signature_strategy: str = "individual",
    execution_engine=None,
) -> None:
    block = signed_block.message
    resolver = state_pubkey_resolver(state)
    by_bytes = state_pubkey_bytes_resolver(state)

    if signature_strategy == "bulk":
        acc = sigsets.BlockSignatureAccumulator(
            preset, spec, state, resolver, by_bytes
        )
        acc.include_all(signed_block)
        _require(acc.verify(), "bulk signature verification failed")
        signature_strategy = "none"
    elif signature_strategy == "individual":
        _verify_set(
            sigsets.block_proposal_set(preset, spec, state, signed_block, resolver),
            "block proposal",
        )

    verify = signature_strategy == "individual"

    process_block_header(preset, state, block)
    if fork == "bellatrix" and is_execution_enabled(preset, state, block.body):
        process_execution_payload(
            preset, spec, state, block.body.execution_payload, execution_engine
        )
    process_randao(preset, spec, state, block, verify, resolver)
    process_eth1_data(preset, state, block.body)
    process_operations(preset, spec, state, block.body, fork, verify, resolver)
    if fork in ("altair", "bellatrix"):
        process_sync_aggregate(
            preset, spec, state, block.slot, block.body.sync_aggregate, verify, by_bytes
        )


def process_block_header(preset: Preset, state, block) -> None:
    t = types_for(preset)
    _require(block.slot == state.slot, "block slot != state slot")
    _require(
        block.slot > state.latest_block_header.slot, "block not newer than header"
    )
    _require(
        block.proposer_index == get_beacon_proposer_index(preset, state),
        "wrong proposer index",
    )
    _require(
        block.parent_root == hash_tree_root(state.latest_block_header),
        "parent root mismatch",
    )
    state.latest_block_header = t.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=bytes(32),
        body_root=hash_tree_root(block.body),
    )
    _require(
        not state.validators[block.proposer_index].slashed, "proposer slashed"
    )


def process_randao(
    preset: Preset, spec: ChainSpec, state, block, verify: bool, resolver
) -> None:
    from ..ssz.sha256 import hash_bytes

    epoch = get_current_epoch(preset, state)
    if verify:
        _verify_set(
            sigsets.randao_set(preset, spec, state, block, resolver), "randao reveal"
        )
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(preset, state, epoch), hash_bytes(block.body.randao_reveal)
        )
    )
    state.randao_mixes[epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR] = mix


def process_eth1_data(preset: Preset, state, body) -> None:
    state.eth1_data_votes = list(state.eth1_data_votes) + [body.eth1_data]
    if (
        state.eth1_data_votes.count(body.eth1_data) * 2
        > preset.EPOCHS_PER_ETH1_VOTING_PERIOD * preset.SLOTS_PER_EPOCH
    ):
        state.eth1_data = body.eth1_data


def process_operations(
    preset: Preset, spec: ChainSpec, state, body, fork: str, verify: bool, resolver
) -> None:
    expected_deposits = min(
        preset.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    _require(
        len(body.deposits) == expected_deposits,
        f"expected {expected_deposits} deposits, block has {len(body.deposits)}",
    )
    for ps in body.proposer_slashings:
        process_proposer_slashing(preset, spec, state, ps, fork, verify, resolver)
    for asl in body.attester_slashings:
        process_attester_slashing(preset, spec, state, asl, fork, verify, resolver)
    for att in body.attestations:
        process_attestation(preset, spec, state, att, fork, verify, resolver)
    for dep in body.deposits:
        process_deposit(preset, spec, state, dep, fork)
    for ex in body.voluntary_exits:
        process_voluntary_exit(preset, spec, state, ex, verify, resolver)


def process_proposer_slashing(
    preset: Preset, spec: ChainSpec, state, slashing, fork: str, verify: bool, resolver
) -> None:
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    _require(h1.slot == h2.slot, "proposer slashing: slots differ")
    _require(
        h1.proposer_index == h2.proposer_index, "proposer slashing: proposers differ"
    )
    _require(h1 != h2, "proposer slashing: identical headers")
    _require(
        h1.proposer_index < len(state.validators), "proposer slashing: bad index"
    )
    v = state.validators[h1.proposer_index]
    _require(
        is_slashable_validator(v, get_current_epoch(preset, state)),
        "proposer slashing: not slashable",
    )
    if verify:
        for s in sigsets.proposer_slashing_sets(preset, spec, state, slashing, resolver):
            _verify_set(s, "proposer slashing header")
    slash_validator(preset, spec, state, fork, h1.proposer_index)


def process_attester_slashing(
    preset: Preset, spec: ChainSpec, state, slashing, fork: str, verify: bool, resolver
) -> None:
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    _require(
        is_slashable_attestation_data(a1.data, a2.data),
        "attester slashing: data not slashable",
    )
    for a in (a1, a2):
        _require(
            is_valid_indexed_attestation_structure(preset, a),
            "attester slashing: malformed indexed attestation",
        )
        if verify:
            _verify_set(
                sigsets.indexed_attestation_set(preset, spec, state, a, resolver),
                "attester slashing attestation",
            )
    slashed_any = False
    current = get_current_epoch(preset, state)
    for index in sorted(
        set(a1.attesting_indices) & set(a2.attesting_indices)
    ):
        if is_slashable_validator(state.validators[index], current):
            slash_validator(preset, spec, state, fork, index)
            slashed_any = True
    _require(slashed_any, "attester slashing: no one slashed")


def process_attestation(
    preset: Preset, spec: ChainSpec, state, attestation, fork: str, verify: bool, resolver
) -> None:
    data = attestation.data
    current = get_current_epoch(preset, state)
    previous = get_previous_epoch(preset, state)
    _require(data.target.epoch in (previous, current), "attestation: bad target epoch")
    _require(
        data.target.epoch == compute_epoch_at_slot(preset, data.slot),
        "attestation: target/slot mismatch",
    )
    _require(
        data.slot + preset.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot,
        "attestation: too early",
    )
    # One-epoch inclusion window, shared by every pre-Deneb fork (Deneb
    # removes the upper bound; none of our forks reach it).
    _require(
        state.slot <= data.slot + preset.SLOTS_PER_EPOCH,
        "attestation: too late",
    )
    _require(
        data.index < get_committee_count_per_slot(preset, state, data.target.epoch),
        "attestation: bad committee index",
    )
    committee = get_beacon_committee(preset, state, data.slot, data.index)
    _require(
        len(attestation.aggregation_bits) == len(committee),
        "attestation: bits/committee length mismatch",
    )

    indexed = get_indexed_attestation(preset, state, attestation)
    _require(
        is_valid_indexed_attestation_structure(preset, indexed),
        "attestation: malformed indexed attestation",
    )
    if verify:
        _verify_set(
            sigsets.indexed_attestation_set(preset, spec, state, indexed, resolver),
            "attestation",
        )

    if fork == "phase0":
        t = types_for(preset)
        pending = t.PendingAttestation(
            aggregation_bits=attestation.aggregation_bits,
            data=data,
            inclusion_delay=state.slot - data.slot,
            proposer_index=get_beacon_proposer_index(preset, state),
        )
        if data.target.epoch == current:
            _require(
                data.source == state.current_justified_checkpoint,
                "attestation: wrong current source",
            )
            state.current_epoch_attestations = list(
                state.current_epoch_attestations
            ) + [pending]
        else:
            _require(
                data.source == state.previous_justified_checkpoint,
                "attestation: wrong previous source",
            )
            state.previous_epoch_attestations = list(
                state.previous_epoch_attestations
            ) + [pending]
        return

    # altair+: participation flags + proposer reward
    participation_flags = get_attestation_participation_flags(
        preset, state, data, state.slot - data.slot
    )
    if data.target.epoch == current:
        epoch_participation = state.current_epoch_participation
    else:
        epoch_participation = state.previous_epoch_participation
    total_active = get_total_active_balance(preset, state)
    base_reward_per_increment = (
        preset.EFFECTIVE_BALANCE_INCREMENT
        * preset.BASE_REWARD_FACTOR
        // integer_squareroot(total_active)
    )
    proposer_reward_numerator = 0
    for index in get_attesting_indices(
        preset, state, data, attestation.aggregation_bits
    ):
        eff = state.validators[index].effective_balance
        base_reward = (
            eff // preset.EFFECTIVE_BALANCE_INCREMENT * base_reward_per_increment
        )
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index in participation_flags and not has_flag(
                epoch_participation[index], flag_index
            ):
                epoch_participation[index] = add_flag(
                    epoch_participation[index], flag_index
                )
                proposer_reward_numerator += base_reward * PARTICIPATION_FLAG_WEIGHTS[flag_index]
    proposer_reward_denominator = (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
        * WEIGHT_DENOMINATOR
        // PROPOSER_WEIGHT
    )
    increase_balance(
        state,
        get_beacon_proposer_index(preset, state),
        proposer_reward_numerator // proposer_reward_denominator,
    )


def get_attestation_participation_flags(
    preset: Preset, state, data, inclusion_delay: int
) -> list[int]:
    """Spec get_attestation_participation_flag_indices."""
    justified = (
        state.current_justified_checkpoint
        if data.target.epoch == get_current_epoch(preset, state)
        else state.previous_justified_checkpoint
    )
    is_matching_source = data.source == justified
    _require(is_matching_source, "attestation: source mismatch")
    is_matching_target = data.target.root == get_block_root(
        preset, state, data.target.epoch
    )
    is_matching_head = (
        is_matching_target
        and data.beacon_block_root == get_block_root_at_slot(preset, state, data.slot)
    )
    flags = []
    if is_matching_source and inclusion_delay <= integer_squareroot(
        preset.SLOTS_PER_EPOCH
    ):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= preset.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == preset.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def process_deposit(preset: Preset, spec: ChainSpec, state, deposit, fork: str) -> None:
    t = types_for(preset)
    leaf = hash_tree_root(t.DepositData, deposit.data)
    _require(
        is_valid_merkle_branch(
            leaf,
            deposit.proof,
            preset.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            state.eth1_deposit_index,
            state.eth1_data.deposit_root,
        ),
        "deposit: bad merkle proof",
    )
    state.eth1_deposit_index += 1
    apply_deposit(preset, spec, state, deposit.data, fork)


def apply_deposit(preset: Preset, spec: ChainSpec, state, data, fork: str) -> None:
    pubkeys = [v.pubkey for v in state.validators]
    if data.pubkey not in pubkeys:
        if not sigsets.deposit_signature_is_valid(preset, spec, data):
            return  # invalid deposit signatures are skipped, not fatal
        t = types_for(preset)
        amount = data.amount
        eff = min(
            amount - amount % preset.EFFECTIVE_BALANCE_INCREMENT,
            preset.MAX_EFFECTIVE_BALANCE,
        )
        state.validators = list(state.validators) + [
            t.Validator(
                pubkey=data.pubkey,
                withdrawal_credentials=data.withdrawal_credentials,
                effective_balance=eff,
                slashed=False,
                activation_eligibility_epoch=FAR_FUTURE_EPOCH,
                activation_epoch=FAR_FUTURE_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        ]
        state.balances = list(state.balances) + [amount]
        if fork in ("altair", "bellatrix"):
            state.previous_epoch_participation = list(
                state.previous_epoch_participation
            ) + [0]
            state.current_epoch_participation = list(
                state.current_epoch_participation
            ) + [0]
            state.inactivity_scores = list(state.inactivity_scores) + [0]
    else:
        index = pubkeys.index(data.pubkey)
        increase_balance(state, index, data.amount)


def process_voluntary_exit(
    preset: Preset, spec: ChainSpec, state, signed_exit, verify: bool, resolver
) -> None:
    exit_msg = signed_exit.message
    _require(
        exit_msg.validator_index < len(state.validators), "exit: bad index"
    )
    v = state.validators[exit_msg.validator_index]
    current = get_current_epoch(preset, state)
    _require(is_active_validator(v, current), "exit: not active")
    _require(v.exit_epoch == FAR_FUTURE_EPOCH, "exit: already exiting")
    _require(current >= exit_msg.epoch, "exit: epoch in future")
    _require(
        current >= v.activation_epoch + spec.shard_committee_period,
        "exit: too young",
    )
    if verify:
        _verify_set(
            sigsets.exit_set(preset, spec, state, signed_exit, resolver),
            "voluntary exit",
        )
    initiate_validator_exit(preset, spec, state, exit_msg.validator_index)


def sync_aggregate_rewards(preset: Preset, state) -> tuple[int, int]:
    """Spec sync-aggregate reward pair: (participant_reward,
    proposer_reward per included bit) — shared by process_sync_aggregate
    and the Beacon API block-rewards route."""
    total_active_increments = (
        get_total_active_balance(preset, state) // preset.EFFECTIVE_BALANCE_INCREMENT
    )
    total_base_rewards = (
        preset.EFFECTIVE_BALANCE_INCREMENT
        * preset.BASE_REWARD_FACTOR
        // integer_squareroot(get_total_active_balance(preset, state))
        * total_active_increments
    )
    max_participant_rewards = (
        total_base_rewards
        * SYNC_REWARD_WEIGHT
        // WEIGHT_DENOMINATOR
        // preset.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // preset.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    return participant_reward, proposer_reward


def process_sync_aggregate(
    preset: Preset, spec: ChainSpec, state, slot: int, sync_aggregate, verify: bool,
    by_bytes,
) -> None:
    if verify:
        s = sigsets.sync_aggregate_set(
            preset, spec, state, slot, sync_aggregate, by_bytes
        )
        if s is not None:
            _verify_set(s, "sync aggregate")

    participant_reward, proposer_reward = sync_aggregate_rewards(preset, state)

    pubkey_to_index = {v.pubkey: i for i, v in enumerate(state.validators)}
    proposer = get_beacon_proposer_index(preset, state)
    for pk_bytes, bit in zip(
        state.current_sync_committee.pubkeys, sync_aggregate.sync_committee_bits
    ):
        index = pubkey_to_index[pk_bytes]
        if bit:
            increase_balance(state, index, participant_reward)
            increase_balance(state, proposer, proposer_reward)
        else:
            decrease_balance(state, index, participant_reward)


# ---------------------------------------------------------------------------
# Execution payload (bellatrix)
# ---------------------------------------------------------------------------

def is_merge_transition_complete(preset: Preset, state) -> bool:
    t = types_for(preset)
    return state.latest_execution_payload_header != t.ExecutionPayloadHeader()


def is_execution_enabled(preset: Preset, state, body) -> bool:
    t = types_for(preset)
    return (
        is_merge_transition_complete(preset, state)
        or body.execution_payload != t.ExecutionPayload()
    )


def process_execution_payload(
    preset: Preset, spec: ChainSpec, state, payload, execution_engine=None
) -> None:
    t = types_for(preset)
    if is_merge_transition_complete(preset, state):
        _require(
            payload.parent_hash == state.latest_execution_payload_header.block_hash,
            "payload: parent hash mismatch",
        )
    _require(
        payload.prev_randao
        == get_randao_mix(preset, state, get_current_epoch(preset, state)),
        "payload: prev_randao mismatch",
    )
    _require(
        payload.timestamp == compute_timestamp_at_slot(preset, spec, state, state.slot),
        "payload: bad timestamp",
    )
    if execution_engine is not None:
        _require(
            execution_engine.notify_new_payload(payload), "payload: EL rejected"
        )
    state.latest_execution_payload_header = t.ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(
            t.ExecutionPayload.fields[-1][1], payload.transactions
        ),
    )


def compute_timestamp_at_slot(preset: Preset, spec: ChainSpec, state, slot: int) -> int:
    return state.genesis_time + (slot - 0) * spec.seconds_per_slot
