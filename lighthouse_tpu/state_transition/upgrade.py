"""Fork upgrades phase0 -> altair -> bellatrix (spec upgrade functions;
reference: ``consensus/state_processing/src/upgrade/``)."""

from __future__ import annotations

from ..types.chain_spec import ChainSpec
from ..types.containers import types_for
from ..types.preset import Preset
from .helpers import get_current_epoch, get_attesting_indices
from .mutators import add_flag
from .block import get_attestation_participation_flags, BlockProcessingError


def maybe_upgrade_state(preset: Preset, spec: ChainSpec, state):
    """At an epoch boundary, replace the state with its next-fork variant
    when the new epoch crosses a scheduled fork."""
    if state.slot % preset.SLOTS_PER_EPOCH != 0:
        return state
    epoch = get_current_epoch(preset, state)
    from .epoch import fork_of

    fork = fork_of(state)
    if (
        fork == "phase0"
        and spec.altair_fork_epoch is not None
        and epoch == spec.altair_fork_epoch
    ):
        state = upgrade_to_altair(preset, spec, state)
        fork = "altair"
    if (
        fork == "altair"
        and spec.bellatrix_fork_epoch is not None
        and epoch == spec.bellatrix_fork_epoch
    ):
        state = upgrade_to_bellatrix(preset, spec, state)
    return state


def _translate_participation(preset: Preset, state, pending_attestations) -> None:
    """Replay phase0 pending attestations into altair participation flags
    (spec translate_participation)."""
    for a in pending_attestations:
        try:
            flags = get_attestation_participation_flags(
                preset, state, a.data, a.inclusion_delay
            )
        except BlockProcessingError:
            continue
        for index in get_attesting_indices(
            preset, state, a.data, a.aggregation_bits
        ):
            for f in flags:
                state.previous_epoch_participation[index] = add_flag(
                    state.previous_epoch_participation[index], f
                )


def upgrade_to_altair(preset: Preset, spec: ChainSpec, pre):
    from .epoch import get_next_sync_committee

    t = types_for(preset)
    epoch = get_current_epoch(preset, pre)
    n = len(pre.validators)
    post = t.state["altair"](
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=t.Fork(
            previous_version=pre.fork.current_version,
            current_version=spec.altair_fork_version,
            epoch=epoch,
        ),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=[0] * n,
        current_epoch_participation=[0] * n,
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=[0] * n,
    )
    _translate_participation(preset, post, pre.previous_epoch_attestations)
    sync = get_next_sync_committee(preset, post)
    post.current_sync_committee = sync
    post.next_sync_committee = get_next_sync_committee(preset, post)
    return post


def upgrade_to_bellatrix(preset: Preset, spec: ChainSpec, pre):
    t = types_for(preset)
    epoch = get_current_epoch(preset, pre)
    post = t.state["bellatrix"](
        **{
            name: getattr(pre, name)
            for name, _ in t.state["altair"].fields
            if name != "fork"
        },
        fork=t.Fork(
            previous_version=pre.fork.current_version,
            current_version=spec.bellatrix_fork_version,
            epoch=epoch,
        ),
        latest_execution_payload_header=t.ExecutionPayloadHeader(),
    )
    return post
