"""State mutators shared by block and epoch processing (spec
``initiate_validator_exit`` / ``slash_validator``; reference:
``consensus/state_processing/src/common/``)."""

from __future__ import annotations

from ..types.chain_spec import ChainSpec, FAR_FUTURE_EPOCH
from ..types.preset import Preset
from .helpers import (
    compute_activation_exit_epoch,
    decrease_balance,
    get_beacon_proposer_index,
    get_current_epoch,
    get_validator_churn_limit,
    increase_balance,
)

# Altair participation flag indices / weights (public spec constants).
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = (
    TIMELY_SOURCE_WEIGHT,
    TIMELY_TARGET_WEIGHT,
    TIMELY_HEAD_WEIGHT,
)


def add_flag(flags: int, index: int) -> int:
    return flags | (1 << index)


def has_flag(flags: int, index: int) -> bool:
    return bool(flags & (1 << index))


def initiate_validator_exit(preset: Preset, spec: ChainSpec, state, index: int) -> None:
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_epochs = [
        w.exit_epoch for w in state.validators if w.exit_epoch != FAR_FUTURE_EPOCH
    ]
    exit_queue_epoch = max(
        exit_epochs
        + [compute_activation_exit_epoch(preset, get_current_epoch(preset, state))]
    )
    churn = sum(1 for w in state.validators if w.exit_epoch == exit_queue_epoch)
    if churn >= get_validator_churn_limit(preset, spec, state):
        exit_queue_epoch += 1
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = exit_queue_epoch + spec.min_validator_withdrawability_delay


def slash_validator(
    preset: Preset,
    spec: ChainSpec,
    state,
    fork: str,
    slashed_index: int,
    whistleblower_index: int | None = None,
) -> None:
    epoch = get_current_epoch(preset, state)
    initiate_validator_exit(preset, spec, state, slashed_index)
    v = state.validators[slashed_index]
    v.slashed = True
    v.withdrawable_epoch = max(
        v.withdrawable_epoch, epoch + preset.EPOCHS_PER_SLASHINGS_VECTOR
    )
    state.slashings[epoch % preset.EPOCHS_PER_SLASHINGS_VECTOR] += v.effective_balance
    if fork == "phase0":
        min_q = preset.MIN_SLASHING_PENALTY_QUOTIENT
    elif fork == "altair":
        min_q = preset.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    else:
        min_q = preset.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    decrease_balance(state, slashed_index, v.effective_balance // min_q)

    proposer_index = get_beacon_proposer_index(preset, state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = v.effective_balance // preset.WHISTLEBLOWER_REWARD_QUOTIENT
    if fork == "phase0":
        proposer_reward = whistleblower_reward // preset.PROPOSER_REWARD_QUOTIENT
    else:
        proposer_reward = whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index, whistleblower_reward - proposer_reward)
