"""Swap-or-not committee shuffling (spec ``compute_shuffled_index``;
reference: ``consensus/swap_or_not_shuffle``).

Two entry points:

* :func:`compute_shuffled_index` — single-index, the literal spec loop.
* :func:`shuffle_list` — whole-permutation, numpy-vectorized per round
  (one hash per 256-index block per round, then lane-parallel bit tests).
  The reference gets the same asymptotics with its ``shuffle_list``; this
  formulation keeps the whole permutation as flat arrays — the layout the
  TPU batch planner and committee caches consume directly.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(
    index: int, index_count: int, seed: bytes, rounds: int
) -> int:
    """Spec-exact single-index swap-or-not (forward direction)."""
    assert 0 <= index < index_count
    for r in range(rounds):
        pivot = int.from_bytes(_h(seed + bytes([r]))[:8], "little") % index_count
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = _h(seed + bytes([r]) + (position // 256).to_bytes(4, "little"))
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def _shuffle_rounds(n: int, seed: bytes, rounds) -> np.ndarray:
    """Apply swap-or-not rounds (an iterable of round numbers) to the full
    index vector at once."""
    idx = np.arange(n, dtype=np.int64)
    n_blocks = (n + 255) // 256
    for r in rounds:
        rb = bytes([r])
        pivot = int.from_bytes(_h(seed + rb)[:8], "little") % n
        flip = (pivot + n - idx) % n
        position = np.maximum(idx, flip)
        # one hash per 256-position block covering every `position` value
        blocks = np.frombuffer(
            b"".join(
                _h(seed + rb + blk.to_bytes(4, "little")) for blk in range(n_blocks)
            ),
            np.uint8,
        ).reshape(n_blocks, 32)
        byte = blocks[position // 256, (position % 256) // 8]
        bit = (byte >> (position % 8).astype(np.uint8)) & 1
        idx = np.where(bit.astype(bool), flip, idx)
    return idx


def shuffle_list(n: int, seed: bytes, rounds: int) -> np.ndarray:
    """``out[i] = compute_shuffled_index(i, n, seed)`` for all i at once."""
    if n == 0:
        return np.zeros(0, np.int64)
    return _shuffle_rounds(n, seed, range(rounds))


def unshuffle_list(n: int, seed: bytes, rounds: int) -> np.ndarray:
    """Inverse permutation (rounds applied in reverse order). Satisfies
    ``unshuffle[shuffle[i]] == i`` — what committee assignment actually
    needs: committee k is ``unshuffle_list(...)[k*size:(k+1)*size]``...
    i.e. the *positions whose shuffled index* lands in that slice."""
    if n == 0:
        return np.zeros(0, np.int64)
    return _shuffle_rounds(n, seed, range(rounds - 1, -1, -1))
