"""Beacon-state accessors and predicates (spec helpers; reference:
``consensus/state_processing/src/common/`` + ``consensus/types``
``BeaconState`` methods). Pure functions of (preset/spec, state) — no god
object: the state is data, helpers are free functions, which is also what
lets the epoch-processing layer vectorize over columnar views.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..types.chain_spec import ChainSpec, FAR_FUTURE_EPOCH
from ..types.preset import Preset
from .shuffle import compute_shuffled_index, shuffle_list

DOMAIN_BEACON_ATTESTER = 1


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def integer_squareroot(n: int) -> int:
    """Spec Newton iteration (floor sqrt)."""
    x = n
    y = (x + 1) // 2
    while y < x:
        x = y
        y = (x + n // x) // 2
    return x


# -- epochs / slots ---------------------------------------------------------

def compute_epoch_at_slot(preset: Preset, slot: int) -> int:
    return slot // preset.SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(preset: Preset, epoch: int) -> int:
    return epoch * preset.SLOTS_PER_EPOCH


def get_current_epoch(preset: Preset, state) -> int:
    return compute_epoch_at_slot(preset, state.slot)


def get_previous_epoch(preset: Preset, state) -> int:
    cur = get_current_epoch(preset, state)
    return cur - 1 if cur > 0 else 0


def compute_activation_exit_epoch(preset: Preset, epoch: int) -> int:
    return epoch + 1 + preset.MAX_SEED_LOOKAHEAD


# -- validator predicates ---------------------------------------------------

def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def is_slashable_validator(v, epoch: int) -> bool:
    return (not v.slashed) and v.activation_epoch <= epoch < v.withdrawable_epoch


def is_eligible_for_activation_queue(preset: Preset, v) -> bool:
    return (
        v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
        and v.effective_balance == preset.MAX_EFFECTIVE_BALANCE
    )


def is_eligible_for_activation(state, v) -> bool:
    return (
        v.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
        and v.activation_epoch == FAR_FUTURE_EPOCH
    )


def is_slashable_attestation_data(d1, d2) -> bool:
    """Double vote or surround vote (spec)."""
    from ..ssz import hash_tree_root

    double = (
        hash_tree_root(type(d1), d1) != hash_tree_root(type(d2), d2)
        and d1.target.epoch == d2.target.epoch
    )
    surround = (
        d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch
    )
    return double or surround


# -- registry / balances ----------------------------------------------------

def get_active_validator_indices(state, epoch: int) -> list[int]:
    return [
        i for i, v in enumerate(state.validators) if is_active_validator(v, epoch)
    ]


def get_total_balance(preset: Preset, state, indices) -> int:
    total = sum(state.validators[i].effective_balance for i in indices)
    return max(preset.EFFECTIVE_BALANCE_INCREMENT, total)


def get_total_active_balance(preset: Preset, state) -> int:
    return get_total_balance(
        preset, state, get_active_validator_indices(state, get_current_epoch(preset, state))
    )


def get_validator_churn_limit(preset: Preset, spec: ChainSpec, state) -> int:
    active = len(
        get_active_validator_indices(state, get_current_epoch(preset, state))
    )
    return max(spec.min_per_epoch_churn_limit, active // spec.churn_limit_quotient)


def increase_balance(state, index: int, delta: int) -> None:
    state.balances[index] += delta


def decrease_balance(state, index: int, delta: int) -> None:
    state.balances[index] = max(0, state.balances[index] - delta)


# -- randomness / roots -----------------------------------------------------

def get_randao_mix(preset: Preset, state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR]


def get_seed(preset: Preset, state, epoch: int, domain_type: int) -> bytes:
    mix = get_randao_mix(
        preset,
        state,
        epoch + preset.EPOCHS_PER_HISTORICAL_VECTOR - preset.MIN_SEED_LOOKAHEAD - 1,
    )
    return _h(domain_type.to_bytes(4, "little") + epoch.to_bytes(8, "little") + mix)


def get_block_root_at_slot(preset: Preset, state, slot: int) -> bytes:
    if not slot < state.slot <= slot + preset.SLOTS_PER_HISTORICAL_ROOT:
        raise ValueError(f"slot {slot} out of block-root range at {state.slot}")
    return state.block_roots[slot % preset.SLOTS_PER_HISTORICAL_ROOT]


def get_block_root(preset: Preset, state, epoch: int) -> bytes:
    return get_block_root_at_slot(
        preset, state, compute_start_slot_at_epoch(preset, epoch)
    )


# -- committees -------------------------------------------------------------

def get_committee_count_per_slot(preset: Preset, state, epoch: int) -> int:
    active = len(get_active_validator_indices(state, epoch))
    return max(
        1,
        min(
            preset.MAX_COMMITTEES_PER_SLOT,
            active // preset.SLOTS_PER_EPOCH // preset.TARGET_COMMITTEE_SIZE,
        ),
    )


def compute_committee(
    preset: Preset, indices, seed: bytes, index: int, count: int
) -> list[int]:
    start = len(indices) * index // count
    end = len(indices) * (index + 1) // count
    perm = shuffle_list(len(indices), seed, preset.SHUFFLE_ROUND_COUNT)
    return [indices[perm[i]] for i in range(start, end)]


class CommitteeCache:
    """Per-epoch committee assignment, computed once from the shuffled
    permutation (the analogue of the reference's ``committee_cache.rs``):
    flat numpy arrays, sliced per (slot, committee)."""

    def __init__(self, preset: Preset, state, epoch: int):
        self.preset = preset
        self.epoch = epoch
        self.active = get_active_validator_indices(state, epoch)
        seed = get_seed(preset, state, epoch, DOMAIN_BEACON_ATTESTER)
        self.seed = seed
        n = len(self.active)
        perm = shuffle_list(n, seed, preset.SHUFFLE_ROUND_COUNT)
        self.shuffled = np.asarray(self.active, np.int64)[perm] if n else perm
        self.committees_per_slot = get_committee_count_per_slot(preset, state, epoch)

    def committee(self, slot: int, index: int) -> np.ndarray:
        P = self.preset
        n = len(self.active)
        count = self.committees_per_slot * P.SLOTS_PER_EPOCH
        which = (slot % P.SLOTS_PER_EPOCH) * self.committees_per_slot + index
        start = n * which // count
        end = n * (which + 1) // count
        return self.shuffled[start:end]


def get_beacon_committee(preset: Preset, state, slot: int, index: int):
    epoch = compute_epoch_at_slot(preset, slot)
    return CommitteeCache(preset, state, epoch).committee(slot, index)


# -- proposer selection -----------------------------------------------------

def compute_proposer_index(preset: Preset, state, indices, seed: bytes) -> int:
    assert indices
    MAX_RANDOM_BYTE = 255
    i = 0
    total = len(indices)
    while True:
        shuffled = compute_shuffled_index(
            i % total, total, seed, preset.SHUFFLE_ROUND_COUNT
        )
        candidate = indices[shuffled]
        random_byte = _h(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eff = state.validators[candidate].effective_balance
        if eff * MAX_RANDOM_BYTE >= preset.MAX_EFFECTIVE_BALANCE * random_byte:
            return candidate
        i += 1


def get_beacon_proposer_index(preset: Preset, state) -> int:
    return proposer_index_at_slot(preset, state, state.slot)


def proposer_index_at_slot(preset: Preset, state, slot: int) -> int:
    """Proposer for any slot of the state's current epoch — usable for
    whole-epoch duty queries without advancing the state per slot."""
    epoch = compute_epoch_at_slot(preset, slot)
    seed = _h(
        get_seed(preset, state, epoch, 0)  # DOMAIN_BEACON_PROPOSER
        + int(slot).to_bytes(8, "little")
    )
    indices = get_active_validator_indices(state, epoch)
    return compute_proposer_index(preset, state, indices, seed)


# -- attestations -----------------------------------------------------------

def get_attesting_indices(preset: Preset, state, data, aggregation_bits) -> list[int]:
    committee = get_beacon_committee(preset, state, data.slot, data.index)
    if len(aggregation_bits) != len(committee):
        raise ValueError("aggregation bits length != committee size")
    return sorted(int(v) for v, b in zip(committee, aggregation_bits) if b)


def get_indexed_attestation(preset: Preset, state, attestation):
    from ..types.containers import types_for

    t = types_for(preset)
    return t.IndexedAttestation(
        attesting_indices=get_attesting_indices(
            preset, state, attestation.data, attestation.aggregation_bits
        ),
        data=attestation.data,
        signature=attestation.signature,
    )


def is_valid_indexed_attestation_structure(preset: Preset, indexed) -> bool:
    """Structural half of the check (signature half goes through the BLS
    backend via signature_sets)."""
    idx = indexed.attesting_indices
    return bool(idx) and list(idx) == sorted(set(idx))


def latest_block_header_root(state, state_root_hint: bytes | None = None) -> bytes:
    """Block root implied by ``state.latest_block_header``. The in-flight
    header's state_root is zero until the next process_slot fills it;
    hashing it raw would give a root no other node computes, so fill it
    (with ``state_root_hint`` when the caller already knows the state
    root, else by hashing the state)."""
    import copy as _copy

    from ..ssz import hash_tree_root

    header = state.latest_block_header
    if bytes(header.state_root) == bytes(32):
        header = _copy.copy(header)
        header.state_root = (
            state_root_hint if state_root_hint is not None else hash_tree_root(state)
        )
    return hash_tree_root(header)
