"""Per-epoch processing, phase0 and altair/bellatrix variants (spec
``process_epoch``; reference: ``consensus/state_processing/src/
per_epoch_processing/`` base + altair modules).

Two tiers share this module's orchestration: the scalar spec loops below
(the readable oracle, and the big-int fallback) and the columnar numpy
passes over the state views in ``state/`` (the default — vector ops over
the whole registry, the layout a device tier consumes). ``process_epoch``
dispatches; ``tests/test_epoch_columnar.py`` pins the two bit-identical.
"""

from __future__ import annotations

from ..ssz import hash_tree_root
from ..types.chain_spec import ChainSpec, FAR_FUTURE_EPOCH
from ..types.containers import types_for
from ..types.preset import Preset
from .helpers import (
    compute_activation_exit_epoch,
    decrease_balance,
    get_active_validator_indices,
    get_attesting_indices,
    get_block_root,
    get_block_root_at_slot,
    get_current_epoch,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    get_total_balance,
    get_validator_churn_limit,
    increase_balance,
    integer_squareroot,
    is_active_validator,
    is_eligible_for_activation,
    is_eligible_for_activation_queue,
)
from .mutators import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    has_flag,
    initiate_validator_exit,
)

BASE_REWARDS_PER_EPOCH = 4
GENESIS_EPOCH = 0


def fork_of(state) -> str:
    if hasattr(state, "latest_execution_payload_header"):
        return "bellatrix"
    if hasattr(state, "current_epoch_participation"):
        return "altair"
    return "phase0"


def process_epoch(preset: Preset, spec: ChainSpec, state) -> None:
    """Dispatch: columnar (numpy state views, ``state/epoch.py``) by
    default, scalar spec loops on guard fallback or when
    ``LIGHTHOUSE_TPU_EPOCH=scalar`` pins the oracle path."""
    import os

    mode = os.environ.get("LIGHTHOUSE_TPU_EPOCH", "auto")
    if mode != "scalar":
        from .state import Fallback, process_epoch_columnar

        try:
            process_epoch_columnar(preset, spec, state)
            return
        except Fallback:
            if mode == "columnar":
                raise
            # guards fire before any mutation: scalar rerun is safe

    process_epoch_scalar(preset, spec, state)


def process_epoch_scalar(preset: Preset, spec: ChainSpec, state) -> None:
    fork = fork_of(state)
    if fork == "phase0":
        process_justification_and_finalization_phase0(preset, state)
        process_rewards_and_penalties_phase0(preset, spec, state)
    else:
        process_justification_and_finalization_altair(preset, state)
        process_inactivity_updates(preset, spec, state)
        process_rewards_and_penalties_altair(preset, spec, state)
    process_registry_updates(preset, spec, state)
    process_slashings(preset, state, fork)
    process_eth1_data_reset(preset, state)
    process_effective_balance_updates(preset, state)
    process_slashings_reset(preset, state)
    process_randao_mixes_reset(preset, state)
    process_historical_roots_update(preset, state)
    if fork == "phase0":
        state.previous_epoch_attestations = state.current_epoch_attestations
        state.current_epoch_attestations = []
    else:
        state.previous_epoch_participation = state.current_epoch_participation
        state.current_epoch_participation = [0] * len(state.validators)
        process_sync_committee_updates(preset, state)


# ---------------------------------------------------------------------------
# phase0: pending-attestation accounting
# ---------------------------------------------------------------------------

def _matching_attestations(preset: Preset, state, epoch: int):
    current = get_current_epoch(preset, state)
    assert epoch in (current, get_previous_epoch(preset, state))
    return (
        state.current_epoch_attestations
        if epoch == current
        else state.previous_epoch_attestations
    )


def _matching_target_attestations(preset: Preset, state, epoch: int):
    root = get_block_root(preset, state, epoch)
    return [
        a for a in _matching_attestations(preset, state, epoch)
        if a.data.target.root == root
    ]


def _matching_head_attestations(preset: Preset, state, epoch: int):
    return [
        a
        for a in _matching_target_attestations(preset, state, epoch)
        if a.data.beacon_block_root
        == get_block_root_at_slot(preset, state, a.data.slot)
    ]


def _unslashed_attesting_indices(preset: Preset, state, attestations):
    out = set()
    for a in attestations:
        out |= set(
            get_attesting_indices(preset, state, a.data, a.aggregation_bits)
        )
    return sorted(i for i in out if not state.validators[i].slashed)


def _attesting_balance(preset: Preset, state, attestations) -> int:
    return get_total_balance(
        preset, state, _unslashed_attesting_indices(preset, state, attestations)
    )


def process_justification_and_finalization_phase0(preset: Preset, state) -> None:
    if get_current_epoch(preset, state) <= GENESIS_EPOCH + 1:
        return
    previous = get_previous_epoch(preset, state)
    current = get_current_epoch(preset, state)
    prev_bal = _attesting_balance(
        preset, state, _matching_target_attestations(preset, state, previous)
    )
    cur_bal = _attesting_balance(
        preset, state, _matching_target_attestations(preset, state, current)
    )
    _weigh_justification_and_finalization(preset, state, prev_bal, cur_bal)


def _weigh_justification_and_finalization(
    preset: Preset, state, prev_target_balance: int, cur_target_balance: int
) -> None:
    t = types_for(preset)
    previous = get_previous_epoch(preset, state)
    current = get_current_epoch(preset, state)
    old_previous_justified = state.previous_justified_checkpoint
    old_current_justified = state.current_justified_checkpoint
    total = get_total_active_balance(preset, state)

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = list(state.justification_bits)
    bits = [False] + bits[: preset.JUSTIFICATION_BITS_LENGTH - 1]
    if prev_target_balance * 3 >= total * 2:
        state.current_justified_checkpoint = t.Checkpoint(
            epoch=previous, root=get_block_root(preset, state, previous)
        )
        bits[1] = True
    if cur_target_balance * 3 >= total * 2:
        state.current_justified_checkpoint = t.Checkpoint(
            epoch=current, root=get_block_root(preset, state, current)
        )
        bits[0] = True
    state.justification_bits = bits

    # finalization rules
    if all(bits[1:4]) and old_previous_justified.epoch + 3 == current:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[1:3]) and old_previous_justified.epoch + 2 == current:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[0:3]) and old_current_justified.epoch + 2 == current:
        state.finalized_checkpoint = old_current_justified
    if all(bits[0:2]) and old_current_justified.epoch + 1 == current:
        state.finalized_checkpoint = old_current_justified


def _base_reward_phase0(preset: Preset, state, total_balance: int, index: int) -> int:
    eff = state.validators[index].effective_balance
    return (
        eff
        * preset.BASE_REWARD_FACTOR
        // integer_squareroot(total_balance)
        // BASE_REWARDS_PER_EPOCH
    )


def _is_in_inactivity_leak(preset: Preset, state) -> bool:
    return _finality_delay(preset, state) > preset.MIN_EPOCHS_TO_INACTIVITY_PENALTY


def _finality_delay(preset: Preset, state) -> int:
    return get_previous_epoch(preset, state) - state.finalized_checkpoint.epoch


def _eligible_indices(preset: Preset, state) -> list[int]:
    previous = get_previous_epoch(preset, state)
    return [
        i
        for i, v in enumerate(state.validators)
        if is_active_validator(v, previous)
        or (v.slashed and previous + 1 < v.withdrawable_epoch)
    ]


def process_rewards_and_penalties_phase0(
    preset: Preset, spec: ChainSpec, state
) -> None:
    if get_current_epoch(preset, state) == GENESIS_EPOCH:
        return
    rewards, penalties = get_attestation_deltas(preset, state)
    for i in range(len(state.validators)):
        increase_balance(state, i, rewards[i])
        decrease_balance(state, i, penalties[i])


def get_attestation_deltas(preset: Preset, state):
    """Spec get_attestation_deltas (source/target/head + inclusion delay +
    inactivity)."""
    total = get_total_active_balance(preset, state)
    previous = get_previous_epoch(preset, state)
    rewards = [0] * len(state.validators)
    penalties = [0] * len(state.validators)
    eligible = _eligible_indices(preset, state)

    matching_source = _matching_attestations(preset, state, previous)
    matching_target = _matching_target_attestations(preset, state, previous)
    matching_head = _matching_head_attestations(preset, state, previous)

    in_leak = _is_in_inactivity_leak(preset, state)
    increment = preset.EFFECTIVE_BALANCE_INCREMENT

    for attestations, _name in (
        (matching_source, "source"),
        (matching_target, "target"),
        (matching_head, "head"),
    ):
        unslashed = set(_unslashed_attesting_indices(preset, state, attestations))
        attesting_balance = get_total_balance(preset, state, unslashed)
        for i in eligible:
            base = _base_reward_phase0(preset, state, total, i)
            if i in unslashed:
                if in_leak:
                    rewards[i] += base
                else:
                    reward_numerator = base * (attesting_balance // increment)
                    rewards[i] += reward_numerator // (total // increment)
            else:
                penalties[i] += base

    # inclusion delay (source attesters only)
    source_unslashed = set(
        _unslashed_attesting_indices(preset, state, matching_source)
    )
    for i in source_unslashed:
        best = None
        for a in matching_source:
            if i in get_attesting_indices(preset, state, a.data, a.aggregation_bits):
                if best is None or a.inclusion_delay < best.inclusion_delay:
                    best = a
        base = _base_reward_phase0(preset, state, total, i)
        proposer_reward = base // preset.PROPOSER_REWARD_QUOTIENT
        rewards[best.proposer_index] += proposer_reward
        max_attester_reward = base - proposer_reward
        rewards[i] += max_attester_reward // best.inclusion_delay

    # inactivity penalty
    if in_leak:
        target_unslashed = set(
            _unslashed_attesting_indices(preset, state, matching_target)
        )
        delay = _finality_delay(preset, state)
        for i in eligible:
            base = _base_reward_phase0(preset, state, total, i)
            penalties[i] += BASE_REWARDS_PER_EPOCH * base - (
                base // preset.PROPOSER_REWARD_QUOTIENT
            )
            if i not in target_unslashed:
                eff = state.validators[i].effective_balance
                penalties[i] += eff * delay // preset.INACTIVITY_PENALTY_QUOTIENT
    return rewards, penalties


# ---------------------------------------------------------------------------
# altair: participation-flag accounting
# ---------------------------------------------------------------------------

def get_unslashed_participating_indices(
    preset: Preset, state, flag_index: int, epoch: int
) -> set[int]:
    current = get_current_epoch(preset, state)
    assert epoch in (current, get_previous_epoch(preset, state))
    participation = (
        state.current_epoch_participation
        if epoch == current
        else state.previous_epoch_participation
    )
    return {
        i
        for i in get_active_validator_indices(state, epoch)
        if has_flag(participation[i], flag_index)
        and not state.validators[i].slashed
    }


def process_justification_and_finalization_altair(preset: Preset, state) -> None:
    if get_current_epoch(preset, state) <= GENESIS_EPOCH + 1:
        return
    prev_idx = get_unslashed_participating_indices(
        preset, state, TIMELY_TARGET_FLAG_INDEX, get_previous_epoch(preset, state)
    )
    cur_idx = get_unslashed_participating_indices(
        preset, state, TIMELY_TARGET_FLAG_INDEX, get_current_epoch(preset, state)
    )
    _weigh_justification_and_finalization(
        preset,
        state,
        get_total_balance(preset, state, prev_idx),
        get_total_balance(preset, state, cur_idx),
    )


def process_inactivity_updates(preset: Preset, spec: ChainSpec, state) -> None:
    if get_current_epoch(preset, state) == GENESIS_EPOCH:
        return
    prev_target = get_unslashed_participating_indices(
        preset, state, TIMELY_TARGET_FLAG_INDEX, get_previous_epoch(preset, state)
    )
    in_leak = _is_in_inactivity_leak(preset, state)
    for i in _eligible_indices(preset, state):
        if i in prev_target:
            state.inactivity_scores[i] -= min(1, state.inactivity_scores[i])
        else:
            state.inactivity_scores[i] += spec.inactivity_score_bias
        if not in_leak:
            state.inactivity_scores[i] -= min(
                spec.inactivity_score_recovery_rate, state.inactivity_scores[i]
            )


def _base_reward_altair(preset: Preset, state, total: int, index: int) -> int:
    increment = preset.EFFECTIVE_BALANCE_INCREMENT
    base_per_increment = (
        increment * preset.BASE_REWARD_FACTOR // integer_squareroot(total)
    )
    return (
        state.validators[index].effective_balance // increment * base_per_increment
    )


def process_rewards_and_penalties_altair(
    preset: Preset, spec: ChainSpec, state
) -> None:
    if get_current_epoch(preset, state) == GENESIS_EPOCH:
        return
    fork = fork_of(state)
    total = get_total_active_balance(preset, state)
    previous = get_previous_epoch(preset, state)
    increment = preset.EFFECTIVE_BALANCE_INCREMENT
    in_leak = _is_in_inactivity_leak(preset, state)
    eligible = _eligible_indices(preset, state)

    rewards = [0] * len(state.validators)
    penalties = [0] * len(state.validators)

    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        unslashed = get_unslashed_participating_indices(
            preset, state, flag_index, previous
        )
        unslashed_balance = get_total_balance(preset, state, unslashed)
        unslashed_increments = unslashed_balance // increment
        active_increments = total // increment
        for i in eligible:
            base = _base_reward_altair(preset, state, total, i)
            if i in unslashed:
                if not in_leak:
                    numerator = base * weight * unslashed_increments
                    rewards[i] += numerator // (active_increments * WEIGHT_DENOMINATOR)
            elif flag_index != TIMELY_HEAD_FLAG_INDEX:
                penalties[i] += base * weight // WEIGHT_DENOMINATOR

    # inactivity penalties (always applied, scaled by score)
    quotient = (
        preset.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
        if fork == "altair"
        else preset.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    )
    prev_target = get_unslashed_participating_indices(
        preset, state, TIMELY_TARGET_FLAG_INDEX, previous
    )
    for i in eligible:
        if i not in prev_target:
            penalty_numerator = (
                state.validators[i].effective_balance * state.inactivity_scores[i]
            )
            penalties[i] += penalty_numerator // (
                spec.inactivity_score_bias * quotient
            )

    for i in range(len(state.validators)):
        increase_balance(state, i, rewards[i])
        decrease_balance(state, i, penalties[i])


# ---------------------------------------------------------------------------
# shared tail phases
# ---------------------------------------------------------------------------

def process_registry_updates(preset: Preset, spec: ChainSpec, state) -> None:
    current = get_current_epoch(preset, state)
    for i, v in enumerate(state.validators):
        if is_eligible_for_activation_queue(preset, v):
            v.activation_eligibility_epoch = current + 1
        if is_active_validator(v, current) and v.effective_balance <= spec.ejection_balance:
            initiate_validator_exit(preset, spec, state, i)

    queue = sorted(
        (
            i
            for i, v in enumerate(state.validators)
            if is_eligible_for_activation(state, v)
        ),
        key=lambda i: (state.validators[i].activation_eligibility_epoch, i),
    )
    for i in queue[: get_validator_churn_limit(preset, spec, state)]:
        state.validators[i].activation_epoch = compute_activation_exit_epoch(
            preset, current
        )


def process_slashings(preset: Preset, state, fork: str) -> None:
    epoch = get_current_epoch(preset, state)
    total_balance = get_total_active_balance(preset, state)
    mult = {
        "phase0": preset.PROPORTIONAL_SLASHING_MULTIPLIER,
        "altair": preset.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
        "bellatrix": preset.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
    }[fork]
    adjusted = min(sum(state.slashings) * mult, total_balance)
    increment = preset.EFFECTIVE_BALANCE_INCREMENT
    for i, v in enumerate(state.validators):
        if (
            v.slashed
            and epoch + preset.EPOCHS_PER_SLASHINGS_VECTOR // 2 == v.withdrawable_epoch
        ):
            penalty_numerator = v.effective_balance // increment * adjusted
            decrease_balance(state, i, penalty_numerator // total_balance * increment)


def process_eth1_data_reset(preset: Preset, state) -> None:
    next_epoch = get_current_epoch(preset, state) + 1
    if next_epoch % preset.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(preset: Preset, state) -> None:
    increment = preset.EFFECTIVE_BALANCE_INCREMENT
    hysteresis_increment = increment // preset.HYSTERESIS_QUOTIENT
    down = hysteresis_increment * preset.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis_increment * preset.HYSTERESIS_UPWARD_MULTIPLIER
    for i, v in enumerate(state.validators):
        balance = state.balances[i]
        if (
            balance + down < v.effective_balance
            or v.effective_balance + up < balance
        ):
            v.effective_balance = min(
                balance - balance % increment, preset.MAX_EFFECTIVE_BALANCE
            )


def process_slashings_reset(preset: Preset, state) -> None:
    next_epoch = get_current_epoch(preset, state) + 1
    state.slashings[next_epoch % preset.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(preset: Preset, state) -> None:
    current = get_current_epoch(preset, state)
    next_epoch = current + 1
    state.randao_mixes[next_epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR] = (
        get_randao_mix(preset, state, current)
    )


def process_historical_roots_update(preset: Preset, state) -> None:
    next_epoch = get_current_epoch(preset, state) + 1
    period = preset.SLOTS_PER_HISTORICAL_ROOT // preset.SLOTS_PER_EPOCH
    if next_epoch % period == 0:
        t = types_for(preset)
        batch = t.HistoricalBatch(
            block_roots=list(state.block_roots), state_roots=list(state.state_roots)
        )
        state.historical_roots = list(state.historical_roots) + [
            hash_tree_root(batch)
        ]


def process_sync_committee_updates(preset: Preset, state) -> None:
    next_epoch = get_current_epoch(preset, state) + 1
    if next_epoch % preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(preset, state)


# ---------------------------------------------------------------------------
# sync committee selection
# ---------------------------------------------------------------------------

def get_next_sync_committee_indices(preset: Preset, state) -> list[int]:
    """Spec balance-weighted sampling over the shuffled active set.

    The whole permutation is materialized once with the vectorized
    ``shuffle_list`` (identical output to per-index
    ``compute_shuffled_index`` — pinned by tests), so the rejection loop
    costs one SHA-256 per 32 candidates instead of ~SHUFFLE_ROUND_COUNT
    hashes per candidate — the difference between milliseconds and
    minutes at mainnet validator counts."""
    import hashlib

    from .helpers import get_seed
    from .shuffle import shuffle_list

    DOMAIN_SYNC_COMMITTEE = 7
    epoch = get_current_epoch(preset, state) + 1
    active = get_active_validator_indices(state, epoch)
    count = len(active)
    seed = get_seed(preset, state, epoch, DOMAIN_SYNC_COMMITTEE)
    perm = shuffle_list(count, seed, preset.SHUFFLE_ROUND_COUNT)
    indices = []
    i = 0
    block = b""
    while len(indices) < preset.SYNC_COMMITTEE_SIZE:
        if i % 32 == 0:
            block = hashlib.sha256(
                seed + (i // 32).to_bytes(8, "little")
            ).digest()
        candidate = active[int(perm[i % count])]
        random_byte = block[i % 32]
        eff = state.validators[candidate].effective_balance
        if eff * 255 >= preset.MAX_EFFECTIVE_BALANCE * random_byte:
            indices.append(candidate)
        i += 1
    return indices


def get_next_sync_committee(preset: Preset, state):
    from ..crypto import bls

    t = types_for(preset)
    indices = get_next_sync_committee_indices(preset, state)
    pubkeys = [state.validators[i].pubkey for i in indices]
    # aggregate pubkey = sum of the G1 points
    pts = [bls.PublicKey.deserialize(b).point for b in pubkeys]
    acc = pts[0]
    for p in pts[1:]:
        acc = acc + p
    aggregate = bls.PublicKey(acc).serialize()
    return t.SyncCommittee(pubkeys=list(pubkeys), aggregate_pubkey=aggregate)
