"""Proto-array: the flat-array LMD-GHOST fork-choice DAG (reference:
``consensus/proto_array/src/proto_array.rs`` + ``proto_array_fork_choice.rs``).

Design: nodes live in insertion order (parents before children), so weight
propagation is ONE reverse sweep and best-descendant maintenance is local
to (child, parent) pairs — no recursion, no tree walk. Vote deltas are
computed from the latest-message table against old/new balances
(``proto_array_fork_choice.rs`` ``compute_deltas``). The score sweep is
numpy-vectorized where the data allows (delta scatter), with the
sequential parent propagation kept explicit — the structure is a
prefix-scan over a ragged tree, which is also the shape a future device
port would use (segmented scan).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional


class ExecutionStatus(Enum):
    """Execution-layer verdict for the node's payload (reference:
    ``proto_array/src/proto_array.rs`` ``ExecutionStatus``)."""

    IRRELEVANT = "irrelevant"  # pre-merge
    OPTIMISTIC = "optimistic"  # sent to EL, verdict pending
    VALID = "valid"
    INVALID = "invalid"


@dataclass
class ProtoNode:
    slot: int
    root: bytes
    parent: Optional[int]  # index into the array
    justified_checkpoint: tuple[int, bytes]
    finalized_checkpoint: tuple[int, bytes]
    execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


@dataclass
class VoteTracker:
    current_root: bytes = bytes(32)
    next_root: bytes = bytes(32)
    next_epoch: int = 0


class ProtoArrayError(ValueError):
    pass


class ProtoArrayForkChoice:
    def __init__(
        self,
        finalized_slot: int,
        finalized_root: bytes,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ):
        self.nodes: list[ProtoNode] = []
        self.index: dict[bytes, int] = {}
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        self.votes: dict[int, VoteTracker] = {}
        self.balances: list[int] = []
        self.proposer_boost_root: bytes = bytes(32)
        self.equivocating_indices: set[int] = set()
        self.on_block(
            finalized_slot,
            finalized_root,
            None,
            justified_checkpoint,
            finalized_checkpoint,
            execution_status,
        )

    # -- DAG growth ------------------------------------------------------

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: Optional[bytes],
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ) -> None:
        if root in self.index:
            return
        parent = self.index.get(parent_root) if parent_root is not None else None
        if parent is None and parent_root is not None and self.nodes:
            raise ProtoArrayError(f"unknown parent {parent_root.hex()}")
        node = ProtoNode(
            slot=slot,
            root=root,
            parent=parent,
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            execution_status=execution_status,
        )
        self.index[root] = len(self.nodes)
        self.nodes.append(node)
        if parent is not None:
            self._maybe_update_best_child(parent, len(self.nodes) - 1)

    # -- votes -----------------------------------------------------------

    def process_attestation(
        self, validator_index: int, block_root: bytes, target_epoch: int
    ) -> None:
        if validator_index in self.equivocating_indices:
            return
        vote = self.votes.setdefault(validator_index, VoteTracker())
        # A default tracker (no vote yet) must accept a genesis-epoch vote:
        # `target_epoch > next_epoch` alone rejects epoch 0 forever.
        is_default = vote.next_root == self._NO_VOTE and vote.next_epoch == 0
        if is_default or target_epoch > vote.next_epoch:
            vote.next_root = block_root
            vote.next_epoch = target_epoch

    def process_equivocation(self, validator_index: int) -> None:
        """A slashed (equivocating) validator's vote is removed forever
        (reference: fork_choice on_attester_slashing)."""
        self.equivocating_indices.add(validator_index)

    # -- head ------------------------------------------------------------

    def find_head(
        self,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        justified_state_balances: list[int],
        proposer_boost_root: bytes = bytes(32),
        proposer_boost_amount: int = 0,
    ) -> bytes:
        deltas = self._compute_deltas(justified_state_balances)
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        self._apply_score_changes(
            deltas, proposer_boost_root, proposer_boost_amount
        )
        self.balances = list(justified_state_balances)

        just_index = self.index.get(justified_checkpoint[1])
        if just_index is None:
            raise ProtoArrayError("justified root not in proto-array")
        node = self.nodes[just_index]
        best = node.best_descendant if node.best_descendant is not None else just_index
        head = self.nodes[best]
        if not self._node_is_viable_for_head(head):
            # fall back: the justified node itself (matches reference error
            # semantics loosely; a fully non-viable tree is a chain bug)
            raise ProtoArrayError("best node is not viable for head")
        return head.root

    _NO_VOTE = bytes(32)  # sentinel: distinct from any real (hash) root

    def _compute_deltas(self, new_balances: list[int]) -> list[int]:
        deltas = [0] * len(self.nodes)
        for vindex, vote in self.votes.items():
            if vindex in self.equivocating_indices:
                # remove any standing weight, never add
                old_bal = self.balances[vindex] if vindex < len(self.balances) else 0
                if vote.current_root != self._NO_VOTE and old_bal > 0:
                    if vote.current_root in self.index:
                        deltas[self.index[vote.current_root]] -= old_bal
                vote.current_root = self._NO_VOTE
                continue
            old_bal = self.balances[vindex] if vindex < len(self.balances) else 0
            new_bal = new_balances[vindex] if vindex < len(new_balances) else 0
            if vote.current_root != vote.next_root or old_bal != new_bal:
                if vote.current_root != self._NO_VOTE and vote.current_root in self.index:
                    deltas[self.index[vote.current_root]] -= old_bal
                if vote.next_root != self._NO_VOTE and vote.next_root in self.index:
                    deltas[self.index[vote.next_root]] += new_bal
                # Advance unconditionally (reference compute_deltas): if the
                # advance were gated on `next_root in self.index`, a vote whose
                # target was pruned would re-subtract old_bal from the surviving
                # old node on every find_head, driving its weight negative.
                vote.current_root = vote.next_root
        return deltas

    def _apply_score_changes(
        self, deltas: list[int], boost_root: bytes, boost_amount: int
    ) -> None:
        # proposer boost: remove previous boost, add new one (as deltas)
        if self.proposer_boost_root != bytes(32) and self._boost_amount:
            if self.proposer_boost_root in self.index:
                deltas[self.index[self.proposer_boost_root]] -= self._boost_amount
        if boost_root != bytes(32) and boost_amount:
            if boost_root in self.index:
                deltas[self.index[boost_root]] += boost_amount
        self.proposer_boost_root = boost_root
        self._boost_amount = boost_amount

        # reverse sweep: children before parents (insertion order property)
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            node.weight += deltas[i]
            if node.weight < 0:
                raise ProtoArrayError("negative node weight")
            if node.parent is not None:
                deltas[node.parent] += deltas[i]
        # second sweep: refresh best children bottom-up
        for i in range(len(self.nodes) - 1, 0, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child(node.parent, i)

    _boost_amount: int = 0

    # -- viability + best-child maintenance ------------------------------

    def _checkpoints_match(self, node: ProtoNode) -> bool:
        correct_justified = (
            self.justified_checkpoint[0] == 0
            or node.justified_checkpoint == self.justified_checkpoint
        )
        correct_finalized = (
            self.finalized_checkpoint[0] == 0
            or node.finalized_checkpoint == self.finalized_checkpoint
        )
        return correct_justified and correct_finalized

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        return (
            node.execution_status != ExecutionStatus.INVALID
            and self._checkpoints_match(node)
        )

    def _node_leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child(self, parent_i: int, child_i: int) -> None:
        parent = self.nodes[parent_i]
        child = self.nodes[child_i]
        child_leads = self._node_leads_to_viable_head(child)
        child_best = (
            child.best_descendant if child.best_descendant is not None else child_i
        )
        if parent.best_child is None:
            if child_leads:
                parent.best_child = child_i
                parent.best_descendant = child_best
            return
        if parent.best_child == child_i:
            if not child_leads:
                # find replacement among other children
                self._re_elect_best_child(parent_i)
            else:
                parent.best_descendant = child_best
            return
        current_best = self.nodes[parent.best_child]
        current_leads = self._node_leads_to_viable_head(current_best)
        if child_leads and not current_leads:
            parent.best_child = child_i
            parent.best_descendant = child_best
        elif child_leads and (
            child.weight > current_best.weight
            or (
                child.weight == current_best.weight
                and child.root > current_best.root  # tie-break: higher root
            )
        ):
            parent.best_child = child_i
            parent.best_descendant = child_best
        elif not current_leads and not child_leads:
            parent.best_child = None
            parent.best_descendant = None

    def _re_elect_best_child(self, parent_i: int) -> None:
        parent = self.nodes[parent_i]
        parent.best_child = None
        parent.best_descendant = None
        for i in range(parent_i + 1, len(self.nodes)):
            if self.nodes[i].parent == parent_i:
                self._maybe_update_best_child(parent_i, i)

    # -- execution status updates ---------------------------------------

    def on_execution_status(self, root: bytes, status: ExecutionStatus) -> None:
        """EL verdicts propagate: VALID validates ancestors, INVALID
        invalidates descendants (reference
        ``proto_array.rs`` propagate_execution_payload_*)."""
        if root not in self.index:
            return
        i = self.index[root]
        if status == ExecutionStatus.VALID:
            j: Optional[int] = i
            while j is not None:
                n = self.nodes[j]
                if n.execution_status in (
                    ExecutionStatus.VALID,
                    ExecutionStatus.IRRELEVANT,
                ):
                    break
                n.execution_status = ExecutionStatus.VALID
                j = n.parent
        elif status == ExecutionStatus.INVALID:
            invalid = {i}
            self.nodes[i].execution_status = ExecutionStatus.INVALID
            for j in range(i + 1, len(self.nodes)):
                if self.nodes[j].parent in invalid:
                    self.nodes[j].execution_status = ExecutionStatus.INVALID
                    invalid.add(j)
            for j in range(len(self.nodes) - 1, 0, -1):
                n = self.nodes[j]
                if n.parent is not None:
                    self._maybe_update_best_child(n.parent, j)

    # -- pruning ---------------------------------------------------------

    def prune(self, finalized_root: bytes) -> None:
        """Drop everything not descending from the finalized root."""
        if finalized_root not in self.index:
            raise ProtoArrayError("finalized root not in proto-array")
        fin_i = self.index[finalized_root]
        if fin_i == 0:
            return
        keep = {fin_i}
        for i in range(fin_i + 1, len(self.nodes)):
            if self.nodes[i].parent in keep:
                keep.add(i)
        order = sorted(keep)
        remap = {old: new for new, old in enumerate(order)}
        new_nodes = []
        for old in order:
            n = self.nodes[old]
            n.parent = remap.get(n.parent) if n.parent in remap else None
            n.best_child = remap.get(n.best_child)
            n.best_descendant = remap.get(n.best_descendant)
            new_nodes.append(n)
        self.nodes = new_nodes
        self.index = {n.root: i for i, n in enumerate(self.nodes)}

    # -- queries ---------------------------------------------------------

    def contains(self, root: bytes) -> bool:
        return root in self.index

    def get_block_slot(self, root: bytes) -> int:
        return self.nodes[self.index[root]].slot

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        if ancestor_root not in self.index or descendant_root not in self.index:
            return False
        a = self.index[ancestor_root]
        j: Optional[int] = self.index[descendant_root]
        while j is not None and j >= a:
            if j == a:
                return True
            j = self.nodes[j].parent
        return False

    def ancestor_at_slot(self, root: bytes, slot: int) -> Optional[bytes]:
        if root not in self.index:
            return None
        j: Optional[int] = self.index[root]
        while j is not None:
            n = self.nodes[j]
            if n.slot <= slot:
                return n.root
            j = n.parent
        return None
