"""Fork-choice persistence: the full ForkChoice (store checkpoints +
queued attestations + proto-array nodes + vote trackers) round-trips
through one opaque blob in the hot DB's FORK_CHOICE column.

Reference: the beacon chain persists fork choice on shutdown and at
finalization and resumes from it (``beacon_chain.rs:400-440``,
``proto_array/src/proto_array_fork_choice.rs`` ``as_bytes/from_bytes``
SSZ containers). The blob here is versioned JSON with hex-encoded roots —
same durability contract, introspectable in a debugger.
"""

from __future__ import annotations

import json

from ..types.chain_spec import ChainSpec
from ..types.preset import Preset
from .fork_choice import ForkChoice, QueuedAttestation
from .proto_array import ExecutionStatus, ProtoNode, VoteTracker

_VERSION = 1


def _hx(b: bytes) -> str:
    return bytes(b).hex()


def _un(s: str) -> bytes:
    return bytes.fromhex(s)


def _cp(cp: tuple[int, bytes]) -> list:
    return [int(cp[0]), _hx(cp[1])]


def _uncp(v) -> tuple[int, bytes]:
    return (int(v[0]), _un(v[1]))


def fork_choice_to_bytes(fc: ForkChoice) -> bytes:
    """Caller must own ``fc`` exclusively (the chain serializes via
    ``BeaconChain.fork_choice_bytes`` under the chain lock) — concurrent
    mutation tears the nodes/votes iteration."""
    st = fc.store
    doc = {
        "version": _VERSION,
        "store": {
            "current_slot": st.current_slot,
            "justified": _cp(st.justified_checkpoint),
            "finalized": _cp(st.finalized_checkpoint),
            "best_justified": _cp(st.best_justified_checkpoint),
            "justified_balances": list(map(int, st.justified_balances)),
            "proposer_boost_root": _hx(st.proposer_boost_root),
            "equivocating_indices": sorted(st.equivocating_indices),
        },
        "queued_attestations": [
            [qa.slot, list(qa.validator_indices), _hx(qa.block_root), qa.target_epoch]
            for qa in fc.queued_attestations
        ],
        "proto": {
            "nodes": [
                [
                    n.slot,
                    _hx(n.root),
                    n.parent,
                    _cp(n.justified_checkpoint),
                    _cp(n.finalized_checkpoint),
                    n.execution_status.value,
                    int(n.weight),
                    n.best_child,
                    n.best_descendant,
                ]
                for n in fc.proto.nodes
            ],
            "votes": {
                str(v): [_hx(t.current_root), _hx(t.next_root), t.next_epoch]
                for v, t in fc.proto.votes.items()
            },
            "balances": list(map(int, fc.proto.balances)),
            "justified": _cp(fc.proto.justified_checkpoint),
            "finalized": _cp(fc.proto.finalized_checkpoint),
            "proposer_boost_root": _hx(fc.proto.proposer_boost_root),
            "equivocating_indices": sorted(fc.proto.equivocating_indices),
        },
    }
    return json.dumps(doc, separators=(",", ":")).encode()


def fork_choice_from_bytes(
    preset: Preset, spec: ChainSpec, data: bytes
) -> ForkChoice:
    doc = json.loads(data.decode())
    if doc.get("version") != _VERSION:
        raise ValueError(f"unknown fork-choice blob version {doc.get('version')}")
    st = doc["store"]
    proto = doc["proto"]
    nodes = proto["nodes"]
    if not nodes:
        raise ValueError("fork-choice blob has no nodes")

    anchor = nodes[0]
    fc = ForkChoice(
        preset,
        spec,
        anchor[0],
        _un(anchor[1]),
        _uncp(proto["justified"]),
        _uncp(proto["finalized"]),
        st["justified_balances"],
    )
    # replace the single-anchor proto contents with the persisted DAG
    fc.proto.nodes = [
        ProtoNode(
            slot=n[0],
            root=_un(n[1]),
            parent=n[2],
            justified_checkpoint=_uncp(n[3]),
            finalized_checkpoint=_uncp(n[4]),
            execution_status=ExecutionStatus(n[5]),
            weight=n[6],
            best_child=n[7],
            best_descendant=n[8],
        )
        for n in nodes
    ]
    fc.proto.index = {n.root: i for i, n in enumerate(fc.proto.nodes)}
    fc.proto.votes = {
        int(v): VoteTracker(
            current_root=_un(t[0]), next_root=_un(t[1]), next_epoch=t[2]
        )
        for v, t in proto["votes"].items()
    }
    fc.proto.balances = proto["balances"]
    fc.proto.proposer_boost_root = _un(proto["proposer_boost_root"])
    fc.proto.equivocating_indices = set(proto["equivocating_indices"])

    s = fc.store
    s.current_slot = st["current_slot"]
    s.justified_checkpoint = _uncp(st["justified"])
    s.finalized_checkpoint = _uncp(st["finalized"])
    s.best_justified_checkpoint = _uncp(st["best_justified"])
    s.justified_balances = st["justified_balances"]
    s.proposer_boost_root = _un(st["proposer_boost_root"])
    s.equivocating_indices = set(st["equivocating_indices"])
    fc.queued_attestations = [
        QueuedAttestation(
            slot=q[0], validator_indices=q[1], block_root=_un(q[2]), target_epoch=q[3]
        )
        for q in doc["queued_attestations"]
    ]
    return fc
