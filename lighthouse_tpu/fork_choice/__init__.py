"""Fork choice: proto-array LMD-GHOST + the spec wrapper (reference:
``consensus/proto_array`` + ``consensus/fork_choice``, SURVEY.md §2.3)."""

from .proto_array import ProtoArrayForkChoice, ProtoNode, ExecutionStatus
from .fork_choice import ForkChoice, ForkChoiceError, ForkChoiceStore

__all__ = [
    "ExecutionStatus",
    "ForkChoice",
    "ForkChoiceError",
    "ForkChoiceStore",
    "ProtoArrayForkChoice",
    "ProtoNode",
]
