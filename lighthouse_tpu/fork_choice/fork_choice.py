"""Spec fork choice over the proto-array (reference:
``consensus/fork_choice/src/fork_choice.rs``: ``on_block`` :668,
``on_attestation`` :1083, ``get_head`` :511, ``on_attester_slashing``
:1136; store trait ``fork_choice_store.rs``).

Implements the v1.2-era rules the reference ships: LMD-GHOST votes with
FFG filtering, best-justified deferral to epoch boundaries, proposer
score boost, equivocation removal, and optimistic execution statuses.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Optional

from ..types.chain_spec import ChainSpec
from ..types.preset import Preset
from .proto_array import ExecutionStatus, ProtoArrayForkChoice


class ForkChoiceError(ValueError):
    pass


@dataclass
class ForkChoiceStore:
    """The mutable store (reference ``ForkChoiceStore`` trait): slot clock
    + checkpoints + justified balances, owned by the chain."""

    current_slot: int
    justified_checkpoint: tuple[int, bytes]
    finalized_checkpoint: tuple[int, bytes]
    best_justified_checkpoint: tuple[int, bytes]
    justified_balances: list[int] = dc_field(default_factory=list)
    proposer_boost_root: bytes = bytes(32)
    equivocating_indices: set[int] = dc_field(default_factory=set)


@dataclass
class QueuedAttestation:
    slot: int
    validator_indices: list[int]
    block_root: bytes
    target_epoch: int


class ForkChoice:
    def __init__(
        self,
        preset: Preset,
        spec: ChainSpec,
        genesis_or_anchor_slot: int,
        anchor_root: bytes,
        anchor_justified: tuple[int, bytes],
        anchor_finalized: tuple[int, bytes],
        justified_balances: list[int],
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ):
        self.preset = preset
        self.spec = spec
        self.proto = ProtoArrayForkChoice(
            genesis_or_anchor_slot,
            anchor_root,
            anchor_justified,
            anchor_finalized,
            execution_status,
        )
        self.store = ForkChoiceStore(
            current_slot=genesis_or_anchor_slot,
            justified_checkpoint=anchor_justified,
            finalized_checkpoint=anchor_finalized,
            best_justified_checkpoint=anchor_justified,
            justified_balances=list(justified_balances),
        )
        self.queued_attestations: list[QueuedAttestation] = []

    # -- clock -----------------------------------------------------------

    def on_tick(self, slot: int) -> None:
        """Per-slot tick: dequeue one-slot-delayed attestations, reset the
        proposer boost, and at epoch boundaries adopt best-justified."""
        P = self.preset
        while self.store.current_slot < slot:
            self.store.current_slot += 1
            self.store.proposer_boost_root = bytes(32)
            if self.store.current_slot % P.SLOTS_PER_EPOCH == 0:
                if (
                    self.store.best_justified_checkpoint[0]
                    > self.store.justified_checkpoint[0]
                ):
                    self.store.justified_checkpoint = (
                        self.store.best_justified_checkpoint
                    )
        self._process_queued_attestations()

    def _process_queued_attestations(self) -> None:
        remaining = []
        for qa in self.queued_attestations:
            if qa.slot < self.store.current_slot:
                for v in qa.validator_indices:
                    self.proto.process_attestation(v, qa.block_root, qa.target_epoch)
            else:
                remaining.append(qa)
        self.queued_attestations = remaining

    # -- blocks ----------------------------------------------------------

    def on_block(
        self,
        current_slot: int,
        block,
        block_root: bytes,
        state,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ) -> None:
        """Register an imported block (caller has fully verified it)."""
        self.on_tick(max(current_slot, self.store.current_slot))
        if block.slot > current_slot:
            raise ForkChoiceError("block from the future")
        if not self.proto.contains(block.parent_root):
            raise ForkChoiceError("unknown parent in fork choice")
        fin_epoch, fin_root = self.store.finalized_checkpoint
        if fin_root != bytes(32):
            fin_slot = fin_epoch * self.preset.SLOTS_PER_EPOCH
            anc = self.proto.ancestor_at_slot(block.parent_root, fin_slot)
            if anc is not None and fin_epoch > 0 and anc != fin_root:
                raise ForkChoiceError("block does not descend from finalized root")

        state_justified = (
            state.current_justified_checkpoint.epoch,
            state.current_justified_checkpoint.root,
        )
        state_finalized = (
            state.finalized_checkpoint.epoch,
            state.finalized_checkpoint.root,
        )
        if state_justified[0] > self.store.best_justified_checkpoint[0]:
            self.store.best_justified_checkpoint = state_justified
        if self._should_update_justified(block, state_justified):
            self._update_justified(state_justified, state)
        if state_finalized[0] > self.store.finalized_checkpoint[0]:
            self.store.finalized_checkpoint = state_finalized
            if state_justified[0] > self.store.justified_checkpoint[0]:
                self._update_justified(state_justified, state)

        # proposer boost for timely blocks (spec: before attestation cutoff;
        # the caller passes current_slot == block.slot only when timely)
        if block.slot == current_slot:
            self.store.proposer_boost_root = block_root

        self.proto.on_block(
            block.slot,
            block_root,
            block.parent_root,
            state_justified,
            state_finalized,
            execution_status,
        )

    def _should_update_justified(self, block, new_justified) -> bool:
        P = self.preset
        if new_justified[0] <= self.store.justified_checkpoint[0]:
            return False
        if (
            self.store.current_slot % P.SLOTS_PER_EPOCH
            < P.SAFE_SLOTS_TO_UPDATE_JUSTIFIED
        ):
            return True
        # mid-epoch: only update if new justified descends from the old one
        just_slot = self.store.justified_checkpoint[0] * P.SLOTS_PER_EPOCH
        anc = self.proto.ancestor_at_slot(new_justified[1], just_slot)
        return anc == self.store.justified_checkpoint[1]

    def _update_justified(self, checkpoint, state) -> None:
        self.store.justified_checkpoint = checkpoint
        self.store.justified_balances = [
            v.effective_balance if _active(v, checkpoint[0]) else 0
            for v in state.validators
        ]

    # -- attestations ----------------------------------------------------

    def on_attestation(
        self, current_slot: int, indexed_attestation, is_from_block: bool = False
    ) -> None:
        data = indexed_attestation.data
        P = self.preset
        target = data.target
        if not is_from_block:
            cur_epoch = current_slot // P.SLOTS_PER_EPOCH
            if target.epoch not in (cur_epoch, cur_epoch - 1):
                raise ForkChoiceError("attestation target epoch out of range")
        if target.epoch != data.slot // P.SLOTS_PER_EPOCH:
            raise ForkChoiceError("attestation target/slot mismatch")
        if not self.proto.contains(target.root):
            raise ForkChoiceError("unknown attestation target block")
        if not self.proto.contains(data.beacon_block_root):
            raise ForkChoiceError("unknown attestation head block")
        if self.proto.get_block_slot(data.beacon_block_root) > data.slot:
            raise ForkChoiceError("attestation to a future block")
        # The LMD vote must be consistent with the FFG target: the head block
        # must descend from (or be) the claimed target at the target's start
        # slot, else the attestation moves LMD weight for an impossible vote.
        target_start = target.epoch * P.SLOTS_PER_EPOCH
        if (
            self.proto.ancestor_at_slot(data.beacon_block_root, target_start)
            != target.root
        ):
            raise ForkChoiceError("LMD vote inconsistent with FFG target")
        # LMD votes take effect one slot after creation
        self.queued_attestations.append(
            QueuedAttestation(
                slot=data.slot,
                validator_indices=list(indexed_attestation.attesting_indices),
                block_root=data.beacon_block_root,
                target_epoch=target.epoch,
            )
        )
        self._process_queued_attestations()

    def on_attester_slashing(self, indexed_1, indexed_2) -> None:
        both = set(indexed_1.attesting_indices) & set(indexed_2.attesting_indices)
        for v in both:
            self.store.equivocating_indices.add(v)
            self.proto.process_equivocation(v)

    # -- head ------------------------------------------------------------

    def get_head(self) -> bytes:
        boost_amount = 0
        if (
            self.store.proposer_boost_root != bytes(32)
            and self.spec.proposer_score_boost
        ):
            total = sum(self.store.justified_balances)
            committee_weight = total // self.preset.SLOTS_PER_EPOCH
            boost_amount = committee_weight * self.spec.proposer_score_boost // 100
        return self.proto.find_head(
            self.store.justified_checkpoint,
            self.store.finalized_checkpoint,
            self.store.justified_balances,
            self.store.proposer_boost_root,
            boost_amount,
        )

    # -- execution verdicts ---------------------------------------------

    def on_valid_execution_payload(self, root: bytes) -> None:
        self.proto.on_execution_status(root, ExecutionStatus.VALID)

    def on_invalid_execution_payload(self, root: bytes) -> None:
        self.proto.on_execution_status(root, ExecutionStatus.INVALID)

    # -- pruning ---------------------------------------------------------

    def prune(self) -> None:
        fin_root = self.store.finalized_checkpoint[1]
        if fin_root != bytes(32) and self.proto.contains(fin_root):
            self.proto.prune(fin_root)


def _active(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch
