"""Duty-lookahead precompute (ISSUE 19, ROADMAP item 3).

Committee shuffles are deterministic an epoch ahead
(``state_transition/helpers.py`` — the attester seed reaches back
``MIN_SEED_LOOKAHEAD`` epochs), yet the key table's aggregate cache is
purely reactive: a committee's FIRST sighting pays hundreds of
pure-Python EC adds on a verifier thread, and only the second-plus
sighting ships the collapsed K=1 row. PR 17's
``key_table_first_sighting_hit_ratio`` measured ~0.81 on the
epoch-boundary flood — one in five committee batches paying the
host-sum worst case exactly when traffic peaks. This module closes
that window with the precomputed-key-store pattern the FPGA
verification-engine paper applies to certificates (PAPERS.md, arxiv
2112.02229), lifted to aggregate rows:

* a **builder-owned background worker** (:class:`DutyLookahead`) that
  watches the process-global slot clock (``utils/slot_clock.py`` — so
  replay-installed clocks drive it too) and, past a configurable
  trigger point inside the current epoch (default: halfway), walks the
  NEXT epoch's shuffle via a pluggable **duty source** (the client
  wires :func:`chain_duty_source` — one ``CommitteeCache`` per epoch,
  never a per-(slot, index) ``get_beacon_committee`` rebuild; the
  replay harness wires a trace-derived source);
* each committee's validator-index tuple resolves against the host
  ``ValidatorPubkeyCache`` and its aggregate-sum G1 row is computed
  OFF the hot path — the PR 16 windowed device MSM (all-one scalars)
  when a device is up, the host EC fold as the fallback, each path
  journaled — then pre-inserted through
  ``DeviceKeyTable.insert_precomputed``, which bypasses
  ``agg_min_repeats`` for lookahead-sourced tuples (the reactive
  path's admission rules are untouched) so the committee's first
  sighting already ships K=1 with zero host EC adds inside any verify
  span;
* worker lifecycle reuses PR 13's ``sync_or_schedule`` shape: one
  worker thread, capped exponential backoff with jitter on repeated
  failure (each retry IS the probation probe), clean :meth:`stop`,
  and a ``duty_lookahead`` fault-injection point so the failure paths
  are drivable on demand.

Surfaces follow the house pattern: ``duty_lookahead_*`` metric
families, ``lookahead_epoch_warmed`` / ``lookahead_insert_failed``
journal kinds, a ``duty_lookahead`` block in ``/lighthouse/health``,
and chain-time attribution of the precompute work into the slot
ledger (``note_lookahead`` — the cost lands in the quiet mid-epoch
slots that paid it, visibly OUTSIDE every verify span).

jax-free at import (the metrics lint and the replay driver import this
module on boxes that must not initialize a backend); the device sum
path imports lazily and any failure falls back to the host fold — a
broken accelerator can only ever cost the speedup, never a row.

Env knobs (read at import; :func:`configure` overrides at runtime):

    LIGHTHOUSE_TPU_DUTY_LOOKAHEAD               1|0    (default 1)
    LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_TRIGGER_FRAC  float  (default 0.5)
    LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_POLL_S        float  (default 1.0)
    LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_BACKOFF_BASE_S float (default 1.0)
    LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_BACKOFF_MAX_S float  (default 60.0)
    LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_DEVICE_SUM    1|0    (default 1)
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

from ..utils import fault_injection, flight_recorder, metrics, slot_clock
from ..utils import slot_ledger

_ENV_ENABLED = "LIGHTHOUSE_TPU_DUTY_LOOKAHEAD"
_ENV_TRIGGER = "LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_TRIGGER_FRAC"
_ENV_POLL = "LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_POLL_S"
_ENV_BACKOFF_BASE = "LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_BACKOFF_BASE_S"
_ENV_BACKOFF_MAX = "LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_BACKOFF_MAX_S"
_ENV_DEVICE_SUM = "LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_DEVICE_SUM"

DEFAULT_TRIGGER_FRAC = 0.5
DEFAULT_POLL_S = 1.0
DEFAULT_BACKOFF_BASE_S = 1.0
DEFAULT_BACKOFF_MAX_S = 60.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def env_enabled() -> bool:
    return os.environ.get(_ENV_ENABLED, "1") not in ("", "0")


def env_device_sum() -> bool:
    return os.environ.get(_ENV_DEVICE_SUM, "1") not in ("", "0")


# ---------------------------------------------------------------------------
# Telemetry (documented in docs/OBSERVABILITY.md, linted by
# tests/test_zgate4_metrics_lint.py)
# ---------------------------------------------------------------------------

_EPOCHS = metrics.counter_vec(
    "duty_lookahead_epochs_total",
    "lookahead epoch warm attempts by outcome: warmed = the next "
    "epoch's committees were walked and pre-inserted, empty = the duty "
    "source yielded no committees (warm still counts as done for the "
    "epoch), error = the attempt raised and the backoff timer armed",
    ("outcome",),
)
_COMMITTEES = metrics.counter_vec(
    "duty_lookahead_committees_total",
    "committees processed by the lookahead, by sum path: device = "
    "aggregate row produced by the windowed device MSM (all-one "
    "scalars), host = pure-Python EC fold fallback, virtual = no key "
    "table attached (replay/model mode — admission prewarmed, no row "
    "computed), failed = pubkey resolution or pre-insert declined",
    ("path",),
)
_INSERTS = metrics.counter_vec(
    "duty_lookahead_inserts_total",
    "key-table pre-insert outcomes (DeviceKeyTable.insert_precomputed "
    "return values: inserted, exists, infinity, never_cache, full, "
    "unsynced, disabled)",
    ("outcome",),
)
_WARM_SECONDS = metrics.gauge(
    "duty_lookahead_warm_seconds",
    "wall seconds the most recent epoch warm took (resolve + sum + "
    "pre-insert, all off the hot path)",
)


DutySource = Callable[[int], Iterable[Sequence[int]]]


def chain_duty_source(chain) -> DutySource:
    """Duty source over a live chain: ONE ``CommitteeCache`` built per
    queried epoch from the head state (the shuffle is a pure function
    of (state, epoch) and the attester seed reaches back
    ``MIN_SEED_LOOKAHEAD`` epochs, so the next epoch's assignment is
    already determined), yielding every (slot, index) committee's
    validator-index tuple."""

    def source(epoch: int) -> Iterable[Tuple[int, ...]]:
        from ..state_transition.helpers import CommitteeCache

        state = chain.head_state
        cache = CommitteeCache(chain.preset, state, int(epoch))
        start = int(epoch) * chain.preset.SLOTS_PER_EPOCH
        for slot in range(start, start + chain.preset.SLOTS_PER_EPOCH):
            for index in range(cache.committees_per_slot):
                committee = cache.committee(slot, index)
                if len(committee) > 1:
                    yield tuple(int(v) for v in committee)

    return source


class DutyLookahead:
    """The background precompute worker (see module docstring).

    ``duty_source(epoch)`` yields validator-index tuples for that
    epoch's committees. ``key_table`` / ``pubkey_cache`` may both be
    None (replay/model mode): the worker then only counts committees
    and fires ``on_warmed`` — the harness prewarms its sighting model
    there — without touching a device. ``on_warmed(epoch, committees)``
    is called after every successful warm."""

    def __init__(
        self,
        duty_source: DutySource,
        key_table=None,
        pubkey_cache=None,
        *,
        trigger_frac: Optional[float] = None,
        poll_s: Optional[float] = None,
        backoff_base_s: Optional[float] = None,
        backoff_max_s: Optional[float] = None,
        device_sum: Optional[bool] = None,
        on_warmed: Optional[Callable[[int, list], None]] = None,
    ):
        self.duty_source = duty_source
        self.key_table = key_table
        self.pubkey_cache = pubkey_cache
        self.trigger_frac = min(0.95, max(0.0, (
            _env_float(_ENV_TRIGGER, DEFAULT_TRIGGER_FRAC)
            if trigger_frac is None else float(trigger_frac)
        )))
        self.poll_s = max(0.05, (
            _env_float(_ENV_POLL, DEFAULT_POLL_S)
            if poll_s is None else float(poll_s)
        ))
        self.backoff_base_s = max(0.01, (
            _env_float(_ENV_BACKOFF_BASE, DEFAULT_BACKOFF_BASE_S)
            if backoff_base_s is None else float(backoff_base_s)
        ))
        self.backoff_max_s = max(self.backoff_base_s, (
            _env_float(_ENV_BACKOFF_MAX, DEFAULT_BACKOFF_MAX_S)
            if backoff_max_s is None else float(backoff_max_s)
        ))
        self.device_sum = (
            env_device_sum() if device_sum is None else bool(device_sum)
        )
        self.on_warmed = on_warmed
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._warmed_epoch: Optional[int] = None
        self._failures = 0           # consecutive warm failures
        self._backoff_until = 0.0    # monotonic deadline of the pause
        self._last_error: Optional[str] = None
        self._last_warm_s: Optional[float] = None
        self._epochs = {"warmed": 0, "empty": 0, "error": 0}
        self._committees = {"device": 0, "host": 0, "virtual": 0,
                            "failed": 0}
        self._inserts: Dict[str, int] = {}

    # -- lifecycle (PR 13's worker shape) ---------------------------------

    def start(self) -> "DutyLookahead":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            t = threading.Thread(
                target=self._run, name="duty-lookahead", daemon=True
            )
            self._thread = t
        t.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Clean stop: signal, then a bounded join — stop() during an
        in-flight warm must never wedge the client's shutdown."""
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=timeout)

    close = stop  # the Client.stop() idiom other workers expose

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                pass  # tick() accounts its own failures; never die
            self._stop.wait(self.poll_s)

    # -- trigger policy ----------------------------------------------------

    def epoch_fraction(self) -> float:
        """How far into the current epoch the process-global clock is
        (0.0 at the epoch's first slot boundary, →1.0 at its end)."""
        clock = slot_clock.get_clock()
        slot = clock.now()
        into_epoch = slot - clock.first_slot_of_epoch(clock.epoch_of(slot))
        sub_slot = 0.0
        if clock.seconds_per_slot > 0:
            sub_slot = clock.seconds_into_slot() / clock.seconds_per_slot
        return min(1.0, (into_epoch + sub_slot) / clock.slots_per_epoch)

    def tick(self) -> Optional[dict]:
        """One poll: warm the NEXT epoch once the trigger point inside
        the current epoch has passed (and the backoff pause, if a prior
        attempt failed, has expired). Idempotent per target epoch."""
        if self._stop.is_set():
            return None
        clock = slot_clock.get_clock()
        target = clock.current_epoch() + 1
        with self._lock:
            if self._warmed_epoch is not None and target <= self._warmed_epoch:
                return None
            if time.monotonic() < self._backoff_until:
                return None
        if self.epoch_fraction() < self.trigger_frac:
            return None
        return self.warm_epoch(target)

    # -- the warm ----------------------------------------------------------

    def warm_epoch(self, epoch: int) -> Optional[dict]:
        """Walk ``epoch``'s committees and pre-insert their aggregate
        rows. The synchronous core the worker thread calls — and the
        seam replays drive directly (no thread, deterministic). On
        failure: error journal + capped exponential backoff with
        jitter; each expiry's retry is the probation probe."""
        epoch = int(epoch)
        t0 = time.perf_counter()
        try:
            fault_injection.fire("duty_lookahead")
            committees = [
                tuple(int(v) for v in c)
                for c in self.duty_source(epoch)
                if len(c) > 1
            ]
            counts = {"device": 0, "host": 0, "virtual": 0, "failed": 0}
            inserts: Dict[str, int] = {}
            for idxs in committees:
                path = self._warm_one(idxs, epoch, inserts)
                counts[path] += 1
        except Exception as e:
            wall = time.perf_counter() - t0
            with self._lock:
                self._failures += 1
                fails = self._failures
                delay = min(
                    self.backoff_max_s,
                    self.backoff_base_s * (2.0 ** (fails - 1)),
                ) * random.uniform(0.5, 1.0)
                self._backoff_until = time.monotonic() + delay
                self._last_error = repr(e)[:200]
                self._epochs["error"] += 1
            _EPOCHS.with_labels("error").inc()
            flight_recorder.record(
                "lookahead_insert_failed",
                epoch=epoch,
                reason="warm_error",
                error=repr(e)[:200],
                failures=fails,
                backoff_s=round(delay, 3),
            )
            from ..utils import logging as tlog

            tlog.log(
                "warn", "duty-lookahead epoch warm failed",
                epoch=epoch, failures=fails, delay_s=round(delay, 3),
                error=repr(e)[:120],
            )
            return None
        wall = time.perf_counter() - t0
        warmed = counts["device"] + counts["host"] + counts["virtual"]
        outcome = "warmed" if committees else "empty"
        with self._lock:
            self._failures = 0
            self._backoff_until = 0.0
            self._last_warm_s = wall
            if self._warmed_epoch is None or epoch > self._warmed_epoch:
                self._warmed_epoch = epoch
            self._epochs[outcome] += 1
            for k, v in counts.items():
                self._committees[k] += v
            for k, v in inserts.items():
                self._inserts[k] = self._inserts.get(k, 0) + v
        _EPOCHS.with_labels(outcome).inc()
        for k, v in counts.items():
            if v:
                _COMMITTEES.with_labels(k).inc(v)
        for k, v in inserts.items():
            _INSERTS.with_labels(k).inc(v)
        _WARM_SECONDS.set(round(wall, 6))
        # chain-time attribution (ISSUE 17/19): the precompute cost
        # lands in the slot that PAID it — outside every verify span
        slot_ledger.note_lookahead(
            committees=warmed,
            host_sums=counts["host"],
            device_sums=counts["device"],
        )
        flight_recorder.record(
            "lookahead_epoch_warmed",
            epoch=epoch,
            committees=len(committees),
            warmed=warmed,
            device_sums=counts["device"],
            host_sums=counts["host"],
            virtual=counts["virtual"],
            failed=counts["failed"],
            wall_s=round(wall, 6),
        )
        if self.on_warmed is not None:
            try:
                self.on_warmed(epoch, committees)
            except Exception:
                pass
        return {
            "epoch": epoch,
            "committees": len(committees),
            "counts": counts,
            "inserts": dict(inserts),
            "wall_s": wall,
        }

    def _warm_one(
        self, idxs: Tuple[int, ...], epoch: int, inserts: Dict[str, int]
    ) -> str:
        """Resolve + sum + pre-insert ONE committee; returns the sum
        path ('device' | 'host' | 'virtual' | 'failed')."""
        if self.key_table is None or self.pubkey_cache is None:
            # replay/model mode: admission is prewarmed via on_warmed,
            # no row exists to compute
            return "virtual"
        try:
            points = [self.pubkey_cache.get(i).point for i in idxs]
        except Exception as e:
            self._journal_insert_failed(epoch, idxs, "unresolved", e)
            return "failed"
        point, path = self._sum_points(points)
        outcome = self.key_table.insert_precomputed(idxs, point, epoch=epoch)
        inserts[outcome] = inserts.get(outcome, 0) + 1
        if outcome in ("inserted", "exists", "infinity", "never_cache"):
            # infinity/never_cache are terminal decisions, not failures:
            # the device agg_inf_bad screen owns that edge by design
            return path
        self._journal_insert_failed(epoch, idxs, outcome, None)
        return "failed"

    def _sum_points(self, points) -> Tuple[object, str]:
        """The committee's aggregate G1 sum: device windowed MSM with
        all-one scalars when enabled (same rung ladder as the op-pool
        aggregator), host EC fold as the universal fallback."""
        if self.device_sum and len(points) > 1:
            try:
                from ..compile_service.service import MSM_RUNGS
                from ..crypto.device import bls as dbls

                pad_n = None
                for r in sorted(MSM_RUNGS):
                    if r >= len(points):
                        pad_n = r
                        break
                out = dbls.device_msm_g1(
                    points, [1] * len(points), pad_n=pad_n
                )
                return out, "device"
            except Exception:
                pass  # any device failure: the host fold serves
        agg = points[0]
        for p in points[1:]:
            agg = agg + p
        return agg, "host"

    def _journal_insert_failed(
        self, epoch: int, idxs, reason: str, error
    ) -> None:
        _COMMITTEES.with_labels("failed").inc(0)  # family present early
        flight_recorder.record(
            "lookahead_insert_failed",
            epoch=epoch,
            committee_size=len(idxs),
            reason=reason,
            error=None if error is None else repr(error)[:200],
        )

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """The ``/lighthouse/health`` ``duty_lookahead`` block."""
        with self._lock:
            backoff_s = max(0.0, self._backoff_until - time.monotonic())
            return {
                "running": self._thread is not None,
                "trigger_frac": self.trigger_frac,
                "poll_s": self.poll_s,
                "device_sum": self.device_sum,
                "warmed_epoch": self._warmed_epoch,
                "epochs": dict(self._epochs),
                "committees": dict(self._committees),
                "inserts": dict(self._inserts),
                "failures": self._failures,
                "backoff_s": round(backoff_s, 3),
                "last_error": self._last_error,
                "last_warm_s": (
                    None if self._last_warm_s is None
                    else round(self._last_warm_s, 6)
                ),
            }


# ---------------------------------------------------------------------------
# Module-level config seam (tests / replay drivers)
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_enabled = env_enabled()


def enabled() -> bool:
    return _enabled


def configure(enabled: Optional[bool] = None) -> dict:
    """Runtime override of the env default; returns the PREVIOUS values
    so callers restore with ``configure(**prev)``."""
    global _enabled
    with _cfg_lock:
        prev = {"enabled": _enabled}
        if enabled is not None:
            _enabled = bool(enabled)
    return prev
