"""Device-side G2 signature aggregation for the operation pool (ISSUE 16).

The pool's aggregation sites (greedy attestation merge on insert, sync
contribution assembly, block sync-aggregate assembly) are host-side
point-addition folds over ``AggregateSignature.add_assign``. With the
device MSM surface open (``crypto/device/msm.py``) those folds can run
as ONE masked point-sum on the accelerator: :class:`DeviceAggregator`
batches the decoded G2 points, pads to the MSM ladder's warm rungs
(``compile_service.service.MSM_RUNGS``), and dispatches
``bls.device_sum_g2`` — the same staged program the compile service
warms under the "msm" stage label.

Strictly opt-in (``ClientConfig.device_msm``, default off) and strictly
a fast path: the aggregate is the same group element either way, and
serialization compresses the canonical affine point, so the flag-on
output is BYTE-IDENTICAL to the host fold (pinned by
``tests/test_device_msm.py``). Any device failure — and any batch below
``min_batch`` — returns None and the caller's host fold serves, so a
broken accelerator can only ever cost the speedup.
"""

from __future__ import annotations

import time

from ..utils import flight_recorder, metrics, tracing

_AGG = metrics.counter_vec(
    "op_pool_device_agg_total",
    "operation-pool aggregate computations by path: ok = one device G2 "
    "point-sum served (dispatched under the bls stage label \"msm\"), "
    "fallback = the device path failed and the host add_assign fold "
    "served, small = batch below min_batch (host fold, device never "
    "tried)",
    ("outcome",),
)


class DeviceAggregator:
    """Sums decoded G2 signature points on device (see module docstring).

    ``min_batch`` keeps tiny folds (the 2-point greedy attestation
    merge) on the host by default — a device round-trip per gossip
    insert would be pure overhead; sync-committee assembly over dozens
    to hundreds of messages is where the batched sum pays.
    """

    def __init__(self, min_batch: int = 2):
        self.min_batch = max(1, int(min_batch))

    @staticmethod
    def _pad_n(n: int):
        """Smallest warm MSM rung covering ``n``; None (= the generic
        ``_round_up`` pad) when ``n`` exceeds the ladder."""
        from ..compile_service.service import MSM_RUNGS

        for r in sorted(MSM_RUNGS):
            if r >= n:
                return r
        return None

    def aggregate(self, sigs):
        """Decoded ``bls.Signature`` list -> ``bls.AggregateSignature``
        via one device point-sum, or None when the host fold should
        serve (small batch, or any device failure)."""
        from ..crypto import bls

        if len(sigs) < self.min_batch:
            _AGG.with_labels("small").inc()
            return None
        pad_n = self._pad_n(len(sigs))
        t0 = time.perf_counter()
        try:
            with tracing.span(
                "op_pool.device_agg", n_points=len(sigs), pad_n=pad_n
            ):
                pts = [s.point_or_infinity() for s in sigs]
                from ..crypto.device import bls as dbls

                out = dbls.device_sum_g2(pts, pad_n=pad_n)
        except Exception as e:
            _AGG.with_labels("fallback").inc()
            flight_recorder.record(
                "op_pool_device_agg",
                outcome="fallback",
                n_points=len(sigs),
                pad_n=pad_n,
                wall_s=round(time.perf_counter() - t0, 6),
                error=str(e)[:200],
            )
            return None
        _AGG.with_labels("ok").inc()
        flight_recorder.record(
            "op_pool_device_agg",
            outcome="ok",
            n_points=len(sigs),
            pad_n=pad_n,
            wall_s=round(time.perf_counter() - t0, 6),
        )
        if out.is_infinity():
            # the canonical infinity encoding, exactly like the host
            # fold's untouched AggregateSignature.infinity()
            return bls.AggregateSignature.infinity()
        return bls.AggregateSignature(out)
