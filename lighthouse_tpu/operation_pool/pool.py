"""Operation pool (reference: ``beacon_node/operation_pool/src/lib.rs``,
``attestation_storage.rs:128-180``, ``max_cover.rs``).

Holds gossip-learned operations for block inclusion:

* attestations, grouped by attestation data, greedily aggregated on
  insert (non-overlapping aggregation via ``signature.add_assign``), and
  selected per block by weighted max-cover over uncovered validators;
* proposer/attester slashings and voluntary exits, deduped by the
  validators they affect, slashings picked by coverage.
"""

from __future__ import annotations

import threading

from ..utils import metrics

_ATTS = metrics.gauge("op_pool_attestations", "pending attestation groups")
_EXITS = metrics.gauge("op_pool_voluntary_exits", "pending voluntary exits")
_ASLASH = metrics.gauge("op_pool_attester_slashings", "pending attester slashings")
_PSLASH = metrics.gauge("op_pool_proposer_slashings", "pending proposer slashings")
_PACKING = metrics.histogram(
    "op_pool_packing_seconds", "max-cover block packing latency"
)
from dataclasses import dataclass

from ..crypto import bls
from ..ssz import hash_tree_root
from ..types.chain_spec import FAR_FUTURE_EPOCH
from ..state_transition.helpers import (
    compute_epoch_at_slot,
    get_beacon_committee,
    get_current_epoch,
    get_previous_epoch,
    is_slashable_attestation_data,
    is_slashable_validator,
)
from .max_cover import maximum_cover


@dataclass
class _CompactAttestation:
    """One (possibly aggregated) attestation over a committee: bit mask +
    signature (reference CompactIndexedAttestation)."""

    aggregation_bits: list
    signature: bytes

    def disjoint(self, other_bits) -> bool:
        return not any(a and b for a, b in zip(self.aggregation_bits, other_bits))


class OperationPool:
    def __init__(self, preset, spec, types, device_agg=None):
        self.preset = preset
        self.spec = spec
        self.types = types
        # opt-in device G2 aggregation (ISSUE 16): a DeviceAggregator
        # routes the pool's signature point-sums through the device MSM
        # surface; None (the default) keeps every fold on the host —
        # byte-identical output either way (see device_agg.py)
        self._device_agg = device_agg
        self._lock = threading.Lock()
        # (data_root) -> (data, [CompactAttestation])
        self._attestations: dict[bytes, tuple[object, list[_CompactAttestation]]] = {}
        self._proposer_slashings: dict[int, object] = {}
        self._attester_slashings: list[object] = []
        self._voluntary_exits: dict[int, object] = {}
        # (slot, block_root) -> {committee_position: signature}
        self._sync_messages: dict[tuple[int, bytes], dict[int, bytes]] = {}
        # (slot, block_root, subcommittee) -> (bits, aggregated signature)
        self._sync_contributions: dict[tuple, tuple[list, bytes]] = {}

    def set_device_aggregator(self, device_agg) -> None:
        """Attach (or detach with None) the device aggregation path —
        the client wires this after construction so the persistence
        loader's pools pick it up too."""
        self._device_agg = device_agg

    def _aggregate(self, sigs):
        """One AggregateSignature from decoded signatures: the device
        point-sum path when attached and willing (ISSUE 16), else the
        host ``add_assign`` fold. Both serialize the same group element,
        so the choice is invisible in the pool's outputs."""
        if self._device_agg is not None:
            agg = self._device_agg.aggregate(sigs)
            if agg is not None:
                return agg
        agg = bls.AggregateSignature.infinity()
        for s in sigs:
            agg.add_assign(s)
        return agg

    # -- attestations ----------------------------------------------------

    def _update_size_gauges(self) -> None:
        # caller holds self._lock (reference: op-pool size metrics,
        # beacon_chain/src/metrics.rs OP_POOL_* families)
        _ATTS.set(sum(len(groups) for _, groups in self._attestations.values()))
        _EXITS.set(len(self._voluntary_exits))
        _ASLASH.set(len(self._attester_slashings))
        _PSLASH.set(len(self._proposer_slashings))

    def insert_attestation(self, attestation) -> None:
        """Greedy on-insert aggregation (reference
        ``attestation_storage.rs`` ``aggregate``/``insert``): merge into
        the first disjoint existing aggregate, else keep separately."""
        data_root = hash_tree_root(attestation.data)
        bits = list(attestation.aggregation_bits)
        with self._lock:
            data, groups = self._attestations.setdefault(
                data_root, (attestation.data, [])
            )
            for g in groups:
                if bits == g.aggregation_bits:
                    return  # exact duplicate
                if g.disjoint(bits):
                    merged = self._aggregate(
                        [
                            bls.Signature.deserialize(bytes(g.signature)),
                            bls.Signature.deserialize(
                                bytes(attestation.signature)
                            ),
                        ]
                    )
                    g.aggregation_bits = [
                        a or b for a, b in zip(g.aggregation_bits, bits)
                    ]
                    g.signature = merged.serialize()
                    return
            groups.append(
                _CompactAttestation(bits, bytes(attestation.signature))
            )
            self._update_size_gauges()

    def n_attestations(self) -> int:
        with self._lock:
            return sum(len(g) for _, g in self._attestations.values())

    def attestations_for_block(self, state) -> list:
        """Max-cover selection of up to MAX_ATTESTATIONS attestations
        whose data is includable in a block on ``state``: weight = sum of
        effective balances of not-yet-covered attesting validators."""
        P = self.preset
        t = self.types
        current = get_current_epoch(P, state)
        previous = get_previous_epoch(P, state)

        candidates = []
        with self._lock:
            items = [
                (data, list(groups))
                for data, groups in self._attestations.values()
            ]
        for data, groups in items:
            if data.target.epoch not in (previous, current):
                continue
            if not (
                data.slot + P.MIN_ATTESTATION_INCLUSION_DELAY
                <= state.slot
                <= data.slot + P.SLOTS_PER_EPOCH
            ):
                continue
            # FFG source must match the state's checkpoint for the epoch
            src = (
                state.current_justified_checkpoint
                if data.target.epoch == current
                else state.previous_justified_checkpoint
            )
            if (data.source.epoch, bytes(data.source.root)) != (
                src.epoch,
                bytes(src.root),
            ):
                continue
            committee = get_beacon_committee(P, state, data.slot, data.index)
            for g in groups:
                if len(g.aggregation_bits) != len(committee):
                    continue
                cover = {
                    int(v): state.validators[int(v)].effective_balance
                    for v, bit in zip(committee, g.aggregation_bits)
                    if bit
                }
                att = t.Attestation(
                    aggregation_bits=list(g.aggregation_bits),
                    data=data,
                    signature=g.signature,
                )
                candidates.append((att, cover))
        picked = maximum_cover(candidates, P.MAX_ATTESTATIONS)
        return [att for att, _ in picked]

    # -- slashings / exits ----------------------------------------------

    def insert_proposer_slashing(self, slashing) -> None:
        with self._lock:
            self._proposer_slashings.setdefault(
                slashing.signed_header_1.message.proposer_index, slashing
            )
            self._update_size_gauges()

    def insert_attester_slashing(self, slashing) -> None:
        with self._lock:
            self._attester_slashings.append(slashing)
            self._update_size_gauges()

    def insert_voluntary_exit(self, signed_exit) -> None:
        with self._lock:
            self._voluntary_exits.setdefault(
                signed_exit.message.validator_index, signed_exit
            )
            self._update_size_gauges()

    def _slashable_indices(self, slashing, state) -> dict:
        a = set(slashing.attestation_1.attesting_indices)
        b = set(slashing.attestation_2.attesting_indices)
        epoch = get_current_epoch(self.preset, state)
        if not is_slashable_attestation_data(
            slashing.attestation_1.data, slashing.attestation_2.data
        ):
            return {}
        return {
            int(i): state.validators[int(i)].effective_balance
            for i in a & b
            if int(i) < len(state.validators)
            and is_slashable_validator(state.validators[int(i)], epoch)
        }

    # -- sync committee messages (altair+) -------------------------------
    # (reference: beacon_chain's naive_sync_aggregation_pool + op pool
    # sync contributions)

    def insert_sync_committee_message(self, slot: int, block_root: bytes,
                                      committee_position: int, signature: bytes) -> None:
        with self._lock:
            key = (slot, bytes(block_root))
            self._sync_messages.setdefault(key, {})[committee_position] = bytes(signature)

    def insert_sync_contribution(self, contribution) -> None:
        """Keep the best (highest-participation) contribution per
        (slot, root, subcommittee) — reference op-pool sync contributions
        (``operation_pool/src/sync_aggregate_id.rs`` keying)."""
        bits = [bool(b) for b in contribution.aggregation_bits]
        key = (
            int(contribution.slot),
            bytes(contribution.beacon_block_root),
            int(contribution.subcommittee_index),
        )
        with self._lock:
            prev = self._sync_contributions.get(key)
            if prev is None or sum(bits) > sum(prev[0]):
                self._sync_contributions[key] = (
                    bits, bytes(contribution.signature)
                )

    def sync_contribution_for(self, slot: int, block_root: bytes,
                              subcommittee_index: int):
        """Best SyncCommitteeContribution for ONE subcommittee: the
        aggregate of collected individual messages, or a stored
        gossip-received contribution when it has more participation (a
        node subscribed to the contribution topic but not this subnet has
        only the latter). None when both are empty. (The VC aggregator's
        GET ``sync_committee_contribution`` route.)"""
        sub_size = self.preset.sync_subcommittee_size
        lo = subcommittee_index * sub_size
        key = (slot, bytes(block_root), subcommittee_index)
        with self._lock:
            msgs = self._sync_messages.get((slot, bytes(block_root))) or {}
            sub = {
                pos - lo: raw
                for pos, raw in msgs.items()
                if lo <= pos < lo + sub_size
            }
            stored = self._sync_contributions.get(key)
        bits = [False] * sub_size
        sigs = []
        for pos, raw in sorted(sub.items()):
            try:
                s = bls.Signature.deserialize(raw)
                s.point  # decompress NOW: a bad signature skips, like add_assign
            except bls.BlsError:
                continue
            sigs.append(s)
            bits[pos] = True
        if stored is not None and sum(stored[0]) > sum(bits):
            bits, sig_bytes = list(stored[0]), stored[1]
        elif any(bits):
            sig_bytes = self._aggregate(sigs).serialize()
        else:
            return None
        return self.types.SyncCommitteeContribution(
            slot=slot,
            beacon_block_root=bytes(block_root),
            subcommittee_index=subcommittee_index,
            aggregation_bits=bits,
            signature=sig_bytes,
        )

    def sync_aggregate_for_block(self, slot: int, block_root: bytes):
        """Best-effort SyncAggregate for (slot, root): stored contributions
        cover their subcommittees; individual messages fill positions no
        contribution covers. None when empty (caller uses the empty
        aggregate)."""
        key_root = bytes(block_root)
        with self._lock:
            msgs = dict(self._sync_messages.get((slot, key_root)) or {})
            contribs = {
                k[2]: v
                for k, v in self._sync_contributions.items()
                if k[0] == slot and k[1] == key_root
            }
        if not msgs and not contribs:
            return None
        size = self.preset.SYNC_COMMITTEE_SIZE
        sub_size = self.preset.sync_subcommittee_size
        sigs = []
        covered: set[int] = set()
        for subc, (bits, sig_raw) in contribs.items():
            try:
                s = bls.Signature.deserialize(sig_raw)
                s.point
            except bls.BlsError:
                continue
            sigs.append(s)
            for pos, bit in enumerate(bits):
                if bit:
                    covered.add(subc * sub_size + pos)
        for pos, raw in sorted(msgs.items()):
            if pos in covered:
                continue  # already inside a contribution's aggregate
            try:
                s = bls.Signature.deserialize(raw)
                s.point
            except bls.BlsError:
                continue  # undecodable signature: skip, never break production
            sigs.append(s)
            covered.add(pos)
        if not covered:
            return None
        bits = [p in covered for p in range(size)]
        return self.types.SyncAggregate(
            sync_committee_bits=bits,
            sync_committee_signature=self._aggregate(sigs).serialize(),
        )

    def packing_for_block(self, chain, state) -> dict:
        with _PACKING.time():
            return self._packing_for_block(chain, state)

    def _packing_for_block(self, chain, state) -> dict:
        """Everything the block body takes from the pool (reference
        ``produce_block_on_state`` op-pool calls)."""
        P = self.preset
        with self._lock:
            proposer_slashings = list(self._proposer_slashings.values())
            attester_slashings = list(self._attester_slashings)
            exits = list(self._voluntary_exits.values())

        epoch = get_current_epoch(P, state)
        proposer_slashings = [
            s
            for s in proposer_slashings
            if is_slashable_validator(
                state.validators[s.signed_header_1.message.proposer_index], epoch
            )
        ][: P.MAX_PROPOSER_SLASHINGS]

        att_candidates = [
            (s, self._slashable_indices(s, state)) for s in attester_slashings
        ]
        picked = maximum_cover(att_candidates, P.MAX_ATTESTER_SLASHINGS)
        attester_slashings = [s for s, _ in picked]

        exits_out = []
        for e in exits:
            v = state.validators[e.message.validator_index]
            # skip validators already exiting or slashed
            if v.exit_epoch != FAR_FUTURE_EPOCH or v.slashed:
                continue
            exits_out.append(e)
            if len(exits_out) >= P.MAX_VOLUNTARY_EXITS:
                break

        return {
            "attestations": self.attestations_for_block(state),
            "proposer_slashings": proposer_slashings,
            "attester_slashings": attester_slashings,
            "voluntary_exits": exits_out,
        }

    # -- maintenance -----------------------------------------------------

    def contents(self) -> dict:
        """Snapshot of the poolable operations (Beacon API pool dumps +
        persistence consumers) under the pool lock."""
        with self._lock:
            return {
                "voluntary_exits": list(self._voluntary_exits.values()),
                "attester_slashings": list(self._attester_slashings),
                "proposer_slashings": list(self._proposer_slashings.values()),
            }

    def prune(self, state) -> None:
        """Drop everything no longer includable (reference prune_all)."""
        P = self.preset
        current = get_current_epoch(P, state)
        with self._lock:
            self._attestations = {
                r: (d, g)
                for r, (d, g) in self._attestations.items()
                if d.target.epoch + 1 >= current
            }
            self._voluntary_exits = {
                v: e
                for v, e in self._voluntary_exits.items()
                if state.validators[v].exit_epoch == FAR_FUTURE_EPOCH
            }
            self._attester_slashings = [
                s
                for s in self._attester_slashings
                if any(
                    is_slashable_validator(state.validators[int(i)], current)
                    for i in set(s.attestation_1.attesting_indices)
                    & set(s.attestation_2.attesting_indices)
                    if int(i) < len(state.validators)
                )
            ]
            self._proposer_slashings = {
                v: s
                for v, s in self._proposer_slashings.items()
                if is_slashable_validator(state.validators[v], current)
            }
            self._sync_messages = {
                k: v
                for k, v in self._sync_messages.items()
                if k[0] + 2 >= state.slot  # only slot-1 is ever packed
            }
            self._sync_contributions = {
                k: v
                for k, v in self._sync_contributions.items()
                if k[0] + 2 >= state.slot
            }
