"""Operation-pool persistence (reference:
``beacon_node/operation_pool/src/persistence.rs`` — the pool is
SSZ-persisted on shutdown and restored by the client builder).

One versioned blob in the OP_POOL column: attestation data + compact
aggregation entries, slashings, exits, and the sync-committee pools.
Containers are SSZ-encoded (same wire types as gossip); the envelope is
JSON with hex payloads for debuggability.
"""

from __future__ import annotations

import json

from .pool import OperationPool, _CompactAttestation

_VERSION = 1


def _hx(b: bytes) -> str:
    return bytes(b).hex()


def _un(s: str) -> bytes:
    return bytes.fromhex(s)


def pool_to_bytes(pool: OperationPool) -> bytes:
    t = pool.types
    with pool._lock:
        doc = {
            "version": _VERSION,
            "attestations": [
                {
                    "data": _hx(t.AttestationData.encode(data)),
                    "entries": [
                        {"bits": [int(b) for b in c.aggregation_bits],
                         "sig": _hx(c.signature)}
                        for c in compacts
                    ],
                }
                for data, compacts in pool._attestations.values()
            ],
            "proposer_slashings": [
                _hx(t.ProposerSlashing.encode(s))
                for s in pool._proposer_slashings.values()
            ],
            "attester_slashings": [
                _hx(t.AttesterSlashing.encode(s)) for s in pool._attester_slashings
            ],
            "voluntary_exits": [
                _hx(t.SignedVoluntaryExit.encode(e))
                for e in pool._voluntary_exits.values()
            ],
            "sync_messages": [
                [slot, _hx(root), {str(p): _hx(sig) for p, sig in sigs.items()}]
                for (slot, root), sigs in pool._sync_messages.items()
            ],
            "sync_contributions": [
                [list(k[:1]) + [_hx(k[1])] + list(k[2:]),
                 [[int(b) for b in bits], _hx(sig)]]
                for k, (bits, sig) in pool._sync_contributions.items()
            ],
        }
    return json.dumps(doc, separators=(",", ":")).encode()


def pool_from_bytes(preset, spec, types, data: bytes) -> OperationPool:
    doc = json.loads(data.decode())
    if doc.get("version") != _VERSION:
        raise ValueError(f"unknown op-pool blob version {doc.get('version')}")
    t = types
    pool = OperationPool(preset, spec, types)
    from ..ssz import hash_tree_root

    for a in doc["attestations"]:
        att_data = t.AttestationData.decode(_un(a["data"]))
        root = hash_tree_root(t.AttestationData, att_data)
        pool._attestations[root] = (
            att_data,
            [
                _CompactAttestation(
                    aggregation_bits=[bool(b) for b in e["bits"]],
                    signature=_un(e["sig"]),
                )
                for e in a["entries"]
            ],
        )
    for s in doc["proposer_slashings"]:
        sl = t.ProposerSlashing.decode(_un(s))
        pool._proposer_slashings[int(sl.signed_header_1.message.proposer_index)] = sl
    pool._attester_slashings = [
        t.AttesterSlashing.decode(_un(s)) for s in doc["attester_slashings"]
    ]
    for e in doc["voluntary_exits"]:
        ex = t.SignedVoluntaryExit.decode(_un(e))
        pool._voluntary_exits[int(ex.message.validator_index)] = ex
    for slot, root, sigs in doc["sync_messages"]:
        pool._sync_messages[(int(slot), _un(root))] = {
            int(p): _un(sig) for p, sig in sigs.items()
        }
    for key, (bits, sig) in doc["sync_contributions"]:
        k = (int(key[0]), _un(key[1]), *[int(x) for x in key[2:]])
        pool._sync_contributions[k] = ([bool(b) for b in bits], _un(sig))
    return pool
