"""Greedy weighted maximum-coverage selection (reference:
``beacon_node/operation_pool/src/max_cover.rs:1-226``).

Each candidate exposes a cover set (dict key -> weight). The greedy
algorithm repeatedly takes the candidate with the largest *uncovered*
weight and removes its coverage from the rest — the classic (1 - 1/e)
approximation the reference uses for attestation packing.
"""

from __future__ import annotations

from typing import Callable, Iterable, TypeVar

T = TypeVar("T")


class MaxCoverItem:
    """Wraps a candidate with its current (shrinking) cover set."""

    __slots__ = ("item", "covering")

    def __init__(self, item, covering: dict):
        self.item = item
        self.covering = dict(covering)

    def score(self) -> int:
        return sum(self.covering.values())


def maximum_cover(
    items: Iterable[tuple[T, dict]], limit: int
) -> list[tuple[T, dict]]:
    """items: (candidate, {key: weight}). Returns up to ``limit``
    (candidate, covered-at-selection) pairs, highest-value first."""
    pool = [MaxCoverItem(i, c) for i, c in items if c]
    out = []
    for _ in range(limit):
        if not pool:
            break
        best = max(pool, key=MaxCoverItem.score)
        if best.score() == 0:
            break
        covered = dict(best.covering)
        pool.remove(best)
        for other in pool:
            for k in covered:
                other.covering.pop(k, None)
        out.append((best.item, covered))
    return out
