"""Operation pool: gossip-learned operations -> optimal block packings.

Reference: ``beacon_node/operation_pool`` (max-cover selection, on-insert
aggregation, reward-weighted packing).
"""

from .device_agg import DeviceAggregator
from .max_cover import MaxCoverItem, maximum_cover
from .pool import OperationPool

__all__ = [
    "DeviceAggregator",
    "MaxCoverItem",
    "OperationPool",
    "maximum_cover",
]
