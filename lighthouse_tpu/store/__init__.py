"""L3 persistence: key-value stores, the hot/cold split DB, iterators.

Reference: ``beacon_node/store`` (``hot_cold_store.rs``,
``memory_store.rs``, ``iter.rs``, ``leveldb_store.rs``).
"""

from .hot_cold import HotColdDB, StateSummary, StoreError
from .iter import block_roots_iter, state_roots_iter
from .kv import Column, KeyValueStore, MemoryStore, SqliteStore

__all__ = [
    "Column",
    "HotColdDB",
    "KeyValueStore",
    "MemoryStore",
    "SqliteStore",
    "StateSummary",
    "StoreError",
    "block_roots_iter",
    "state_roots_iter",
]
