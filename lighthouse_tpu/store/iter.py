"""Ancestor iterators over stored chains (reference:
``beacon_node/store/src/iter.rs`` ``BlockRootsIterator`` /
``StateRootsIterator`` — walk backwards from a block/state towards
genesis, crossing into the freezer when the hot chain ends).
"""

from __future__ import annotations

from typing import Iterator

from .hot_cold import HotColdDB


def block_roots_iter(db: HotColdDB, head_block_root: bytes) -> Iterator[tuple[int, bytes]]:
    """Yield (slot, block_root) walking back from ``head_block_root`` to
    genesis (block-granular: empty slots are skipped, like the reference's
    parent-chain walk)."""
    root = head_block_root
    while True:
        block = db.get_block(root)
        if block is None:
            return
        slot = block.message.slot
        yield slot, root
        if slot == 0:
            return
        parent = bytes(block.message.parent_root)
        if parent == bytes(32):
            return
        root = parent


def state_roots_iter(db: HotColdDB, head_state_root: bytes) -> Iterator[tuple[int, bytes]]:
    """Yield (slot, state_root) walking back via hot summaries/snapshots,
    then the cold per-slot index."""
    from .kv import Column
    from .hot_cold import StateSummary
    import struct

    root = head_state_root
    while True:
        raw = db.kv.get(Column.STATE_SUMMARY, root)
        if raw is not None:
            s = StateSummary.decode(raw)
            yield s.slot, root
            if s.slot == 0:
                return
            root = s.previous_state_root
            continue
        state = db._get_state_full(Column.STATE, root) or db._get_state_full(
            Column.COLD_STATE, root
        )
        if state is None:
            return
        yield state.slot, root
        if state.slot == 0:
            return
        # continue through the cold index if present, else via state_roots
        prev = db.kv.get(Column.COLD_STATE_ROOTS, struct.pack("<Q", state.slot - 1))
        if prev is None:
            prev = bytes(
                state.state_roots[(state.slot - 1) % db.preset.SLOTS_PER_HISTORICAL_ROOT]
            )
        root = prev
