"""Key-value store abstraction (reference: ``beacon_node/store``'s
``KeyValueStore`` trait + ``MemoryStore`` (``memory_store.rs:1-126``) +
``leveldb_store.rs``).

Keys are (column, key-bytes); columns mirror the reference's ``DBColumn``
prefixes. The disk backend is sqlite3 (the stdlib binding to the native C
library — filling leveldb's niche here: ordered iteration, batch atomic
writes, single-file persistence).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator, Optional


class Column:
    """DBColumn analogue (reference ``store/src/lib.rs`` DBColumn)."""

    BLOCK = "blk"
    STATE = "ste"
    STATE_SUMMARY = "ssm"
    COLD_STATE = "cst"
    COLD_BLOCK_ROOTS = "cbr"
    COLD_STATE_ROOTS = "csr"
    COLD_STATE_SLOTS = "csl"  # state root -> slot (freezer reverse index)
    COLD_PARTIAL = "cpt"      # chunked restore points (freezer.py)
    COLD_VREC = "cvr"         # interned validator records (id -> SSZ)
    COLD_VREC_INDEX = "cvi"   # validator record hash -> id
    COLD_RANDAO = "crn"       # epoch -> final randao mix
    PUBKEY_CACHE = "pkc"
    METADATA = "meta"
    FORK_CHOICE = "frk"
    OP_POOL = "opo"
    SLASHER = "sls"


class KeyValueStore:
    """Interface: get/put/delete/iteration + atomic batches."""

    def get(self, column: str, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, column: str, key: bytes, value: bytes) -> None:
        self.put_batch([(column, key, value)])

    def put_batch(self, items) -> None:
        raise NotImplementedError

    def delete(self, column: str, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, column: str) -> Iterator[bytes]:
        raise NotImplementedError

    def iter_column(self, column: str) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def approx_size(self) -> int:
        """Approximate on-disk bytes (0 when unknown) — feeds the
        store_db_size_bytes gauge (reference exposes LevelDB sizes)."""
        return 0

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    """Ephemeral store for tests/harnesses (reference memory_store.rs)."""

    def __init__(self):
        self._data: dict[str, dict[bytes, bytes]] = {}
        self._lock = threading.Lock()

    def get(self, column: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(column, {}).get(key)

    def put_batch(self, items) -> None:
        with self._lock:
            for column, key, value in items:
                self._data.setdefault(column, {})[key] = value

    def delete(self, column: str, key: bytes) -> None:
        with self._lock:
            self._data.get(column, {}).pop(key, None)

    def keys(self, column: str) -> Iterator[bytes]:
        with self._lock:
            return iter(sorted(self._data.get(column, {}).keys()))

    def iter_column(self, column: str) -> Iterator[tuple[bytes, bytes]]:
        with self._lock:
            return iter(sorted(self._data.get(column, {}).items()))

    def approx_size(self) -> int:
        with self._lock:
            return sum(
                len(k) + len(v)
                for col in self._data.values()
                for k, v in col.items()
            )


class SqliteStore(KeyValueStore):
    """Disk store over sqlite3 (native C). One table, (col, key) PK, WAL
    mode for concurrent readers. Atomic put_batch via a transaction."""

    def __init__(self, path: str):
        self._path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._conn:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                " col TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
                " PRIMARY KEY (col, key))"
            )

    def get(self, column: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE col=? AND key=?", (column, key)
            ).fetchone()
        return row[0] if row else None

    def put_batch(self, items) -> None:
        with self._lock, self._conn:
            self._conn.executemany(
                "INSERT OR REPLACE INTO kv (col, key, value) VALUES (?,?,?)",
                [(c, k, v) for c, k, v in items],
            )

    def delete(self, column: str, key: bytes) -> None:
        with self._lock, self._conn:
            self._conn.execute(
                "DELETE FROM kv WHERE col=? AND key=?", (column, key)
            )

    def keys(self, column: str) -> Iterator[bytes]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key FROM kv WHERE col=? ORDER BY key", (column,)
            ).fetchall()
        return iter(r[0] for r in rows)

    def iter_column(self, column: str) -> Iterator[tuple[bytes, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE col=? ORDER BY key", (column,)
            ).fetchall()
        return iter((r[0], r[1]) for r in rows)

    def approx_size(self) -> int:
        import os

        try:
            return os.path.getsize(self._path)
        except OSError:
            return 0

    def close(self) -> None:
        self._conn.close()
