"""Hot/cold split database (reference:
``beacon_node/store/src/hot_cold_store.rs:49-62`` — hot DB holds recent
states + all blocks; the cold "freezer" holds finalized history as sparse
restore-point states + per-slot root indexes, reconstructed by replay).

Layout here:

* blocks: ``Column.BLOCK``, key = block root, value = fork byte + SSZ.
* hot states: full SSZ snapshots every ``slots_per_snapshot`` slots
  (``Column.STATE``); other slots get a :class:`StateSummary`
  (``Column.STATE_SUMMARY``) and are rebuilt by replaying blocks from the
  nearest snapshot at or below — the reference's `load_hot_state` +
  `BlockReplayer` path (``hot_cold_store.rs`` ``load_hot_state``,
  ``state_processing/src/block_replayer.rs``).
* cold: on finalization ``migrate`` moves everything at or below the split
  slot out of the hot columns; restore-point states every
  ``slots_per_restore_point`` (``Column.COLD_STATE``) plus per-slot
  block/state-root indexes (``Column.COLD_BLOCK_ROOTS`` /
  ``COLD_STATE_ROOTS``) for forwards iteration.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from ..ssz import hash_tree_root
from ..state_transition.epoch import fork_of
from ..types.containers import FORK_IDS as _FORK_IDS, FORK_NAMES as _FORK_NAMES
from ..utils import metrics
from .kv import Column, KeyValueStore

_STATE_READS = metrics.histogram(
    "store_state_read_seconds", "get_state latency incl. any replay"
)
_STATE_REPLAYS = metrics.counter(
    "store_state_replays_total", "states rebuilt by block replay"
)
_BLOCK_READS = metrics.counter("store_block_reads_total", "get_block calls")
_MIGRATE_TIME = metrics.histogram(
    "store_migrate_seconds", "freezer migration latency"
)
_DB_SIZE = metrics.gauge(
    "store_db_size_bytes", "approximate database size (0 if unknown)"
)

_SPLIT_KEY = b"split"
_HEAD_KEY = b"head"
_GENESIS_STATE_ROOT_KEY = b"genesis_state_root"


@dataclass
class StateSummary:
    """Hot-DB record for a non-snapshot state (reference
    ``HotStateSummary``): enough to find the replay base and blocks."""

    slot: int
    latest_block_root: bytes
    previous_state_root: bytes

    def encode(self) -> bytes:
        return struct.pack("<Q", self.slot) + self.latest_block_root + self.previous_state_root

    @classmethod
    def decode(cls, data: bytes) -> "StateSummary":
        (slot,) = struct.unpack_from("<Q", data)
        return cls(slot, data[8:40], data[40:72])


class StoreError(ValueError):
    pass


class HotColdDB:
    """``types`` is the ``types_for(preset)`` namespace; ``replayer`` is
    ``(state, blocks, target_slot) -> state`` (dependency-injected so the
    store does not hard-bind the signature-verification strategy)."""

    def __init__(
        self,
        kv: KeyValueStore,
        types,
        spec,
        replayer: Callable,
        slots_per_snapshot: int = 32,
        slots_per_restore_point: int = 2048,
    ):
        self.kv = kv
        self.types = types
        self.preset = types.preset
        self.spec = spec
        self.replayer = replayer
        self.slots_per_snapshot = slots_per_snapshot
        self.slots_per_restore_point = slots_per_restore_point

    # -- split ----------------------------------------------------------

    @property
    def split_slot(self) -> int:
        raw = self.kv.get(Column.METADATA, _SPLIT_KEY)
        return struct.unpack("<Q", raw)[0] if raw else 0

    def _set_split_slot(self, slot: int) -> None:
        self.kv.put(Column.METADATA, _SPLIT_KEY, struct.pack("<Q", slot))

    # -- blocks ----------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block) -> None:
        fork = _fork_of_block(self.types, signed_block)
        data = bytes([_FORK_IDS[fork]]) + type(signed_block).encode(signed_block)
        self.kv.put(Column.BLOCK, block_root, data)

    def get_block(self, block_root: bytes):
        _BLOCK_READS.inc()
        data = self.kv.get(Column.BLOCK, block_root)
        if data is None:
            return None
        fork = _FORK_NAMES[data[0]]
        return self.types.signed_block[fork].decode(data[1:])

    def block_exists(self, block_root: bytes) -> bool:
        return self.kv.get(Column.BLOCK, block_root) is not None

    # -- hot states ------------------------------------------------------

    def put_state(self, state_root: bytes, state) -> None:
        """Snapshot or summary depending on slot alignment."""
        if state.slot % self.slots_per_snapshot == 0:
            self._put_state_full(Column.STATE, state_root, state)
        else:
            summary = StateSummary(
                slot=state.slot,
                latest_block_root=_latest_block_root(state, state_root),
                previous_state_root=bytes(
                    state.state_roots[(state.slot - 1) % self.preset.SLOTS_PER_HISTORICAL_ROOT]
                ),
            )
            self.kv.put(Column.STATE_SUMMARY, state_root, summary.encode())

    def put_state_snapshot(self, state_root: bytes, state) -> None:
        """Force a full snapshot (genesis / anchor states)."""
        self._put_state_full(Column.STATE, state_root, state)

    def _put_state_full(self, column: str, state_root: bytes, state) -> None:
        fork = fork_of(state)
        data = bytes([_FORK_IDS[fork]]) + type(state).encode(state)
        self.kv.put(column, state_root, data)

    def _get_state_full(self, column: str, state_root: bytes):
        data = self.kv.get(column, state_root)
        if data is None:
            return None
        fork = _FORK_NAMES[data[0]]
        return self.types.state[fork].decode(data[1:])

    def get_state(self, state_root: bytes):
        """Load a state: hot snapshot directly, hot summary via replay,
        frozen states via restore-point + cold-index replay."""
        with _STATE_READS.time():
            state = self._get_state_full(Column.STATE, state_root)
            if state is not None:
                return state
            raw = self.kv.get(Column.STATE_SUMMARY, state_root)
            if raw is None:
                return self._load_cold_state(state_root)
            summary = StateSummary.decode(raw)
            _STATE_REPLAYS.inc()
            return self._replay_to(summary)

    def _replay_to(self, summary: StateSummary):
        """Walk summaries back to a snapshot, collect the block chain in
        between, replay forward."""
        blocks = []
        seen_root = None
        cur = summary
        while True:
            # Empty slots share latest_block_root with their predecessor —
            # dedupe by root while walking backwards.
            if cur.latest_block_root != seen_root:
                block = self.get_block(cur.latest_block_root)
                if block is None:
                    raise StoreError(
                        f"replay: missing block {cur.latest_block_root.hex()[:12]}"
                    )
                blocks.append(block)
                seen_root = cur.latest_block_root
            base = self._get_state_full(Column.STATE, cur.previous_state_root)
            if base is None:
                base = self._get_cold_state(cur.previous_state_root)
            if base is not None:
                chain = [b for b in reversed(blocks) if b.message.slot > base.slot]
                return self.replayer(base, chain, summary.slot)
            raw = self.kv.get(Column.STATE_SUMMARY, cur.previous_state_root)
            if raw is None:
                raise StoreError(
                    f"replay: missing summary {cur.previous_state_root.hex()[:12]}"
                )
            cur = StateSummary.decode(raw)

    def _get_cold_state(self, state_root: bytes):
        """Restore-point lookup across both freezer layouts: chunked
        (COLD_PARTIAL, freezer.py) then legacy full SSZ (COLD_STATE)."""
        from . import freezer

        state = freezer.load_restore_point(
            self.kv, self.types, state_root,
            self.cold_block_root_at_slot, self._cold_state_root_at_slot,
        )
        if state is not None:
            return state
        return self._get_state_full(Column.COLD_STATE, state_root)

    def _cold_state_root_at_slot(self, slot: int) -> Optional[bytes]:
        return self.kv.get(Column.COLD_STATE_ROOTS, struct.pack("<Q", slot))

    def _load_cold_state(self, state_root: bytes):
        """Frozen state: restore point at or below + replay through the
        cold per-slot block index (reference ``hot_cold_store.rs``
        ``load_cold_state`` + state reconstruction)."""
        state = self._get_cold_state(state_root)
        if state is not None:
            return state
        raw = self.kv.get(Column.COLD_STATE_SLOTS, state_root)
        if raw is None:
            return None
        (slot,) = struct.unpack("<Q", raw)
        srp = self.slots_per_restore_point
        base = None
        base_slot = (slot // srp) * srp
        while base is None and base_slot >= 0:
            base_root = self.kv.get(
                Column.COLD_STATE_ROOTS, struct.pack("<Q", base_slot)
            )
            if base_root is not None:
                base = self._get_cold_state(base_root)
            if base is None:
                if base_slot == 0:
                    break
                base_slot -= srp
        if base is None:
            raise StoreError(f"no restore point at or below slot {slot}")
        blocks, seen = [], None
        for s in range(base.slot + 1, slot + 1):
            br = self.cold_block_root_at_slot(s)
            if br is None or br == seen:
                continue
            block = self.get_block(br)
            if block is None:
                raise StoreError(f"cold replay: missing block at slot {s}")
            if block.message.slot > base.slot:
                blocks.append(block)
            seen = br
        return self.replayer(base, blocks, slot)

    # -- cold (freezer) --------------------------------------------------

    def migrate(self, finalized_state_root: bytes, finalized_state) -> None:
        """Move finalized history below the new split into the freezer
        (reference ``beacon_chain/src/migrate.rs`` + ``hot_cold_store``
        ``migrate_database``): walk back from the finalized state, index
        roots per slot, keep restore points, drop hot entries."""
        new_split = finalized_state.slot
        old_split = self.split_slot
        if new_split <= old_split:
            return
        _timer = _MIGRATE_TIME.time()
        _timer.__enter__()

        # Per-slot root indexes for the newly-frozen range, walked from the
        # finalized state backwards via summaries/snapshots.
        root = finalized_state_root
        restore_points: list[bytes] = []
        while True:
            raw_sum = self.kv.get(Column.STATE_SUMMARY, root)
            full = self._get_state_full(Column.STATE, root)
            if raw_sum is not None:
                s = StateSummary.decode(raw_sum)
                slot, block_root, prev = s.slot, s.latest_block_root, s.previous_state_root
            elif full is not None:
                slot = full.slot
                block_root = _latest_block_root(full, root)
                prev = bytes(
                    full.state_roots[(slot - 1) % self.preset.SLOTS_PER_HISTORICAL_ROOT]
                ) if slot > 0 else None
            else:
                break  # already migrated (or anchor boundary)
            if slot < old_split:
                break
            self.kv.put_batch(
                [
                    (Column.COLD_BLOCK_ROOTS, struct.pack("<Q", slot), block_root),
                    (Column.COLD_STATE_ROOTS, struct.pack("<Q", slot), root),
                    (Column.COLD_STATE_SLOTS, root, struct.pack("<Q", slot)),
                ]
            )
            if slot % self.slots_per_restore_point == 0:
                restore_points.append(root)
            if slot == 0 or prev is None:
                break
            root = prev

        # Restore points are materialized AFTER the walk so the per-slot
        # cold index covering their vector windows is complete, and BEFORE
        # hot entries are dropped (their states load from hot summaries).
        # Stored CHUNKED (freezer.py): vectors reconstruct from the cold
        # index, validators from the interned record table. A round-trip
        # byte-compare guards bit-exactness; any mismatch (e.g. a
        # checkpoint-synced node whose window predates the cold index)
        # falls back to the legacy full snapshot.
        from . import freezer

        for rp_root in restore_points:
            full = self.get_state(rp_root)
            freezer.put_restore_point(self.kv, self.types, rp_root, full)
            loaded = freezer.load_restore_point(
                self.kv, self.types, rp_root,
                self.cold_block_root_at_slot, self._cold_state_root_at_slot,
            )
            if loaded is None or type(full).encode(full) != type(loaded).encode(loaded):
                self.kv.delete(Column.COLD_PARTIAL, rp_root)
                self._put_state_full(Column.COLD_STATE, rp_root, full)

        # The finalized state itself anchors the hot DB: keep it as a full
        # snapshot, drop frozen summaries/snapshots strictly below it.
        self._put_state_full(Column.STATE, finalized_state_root, finalized_state)
        for col in (Column.STATE, Column.STATE_SUMMARY):
            for key in list(self.kv.keys(col)):
                if key == finalized_state_root:
                    continue
                raw = self.kv.get(col, key)
                if raw is None:
                    continue
                slot = (
                    StateSummary.decode(raw).slot
                    if col == Column.STATE_SUMMARY
                    # fork byte + genesis_time (8) + genesis_validators_root (32)
                    else struct.unpack_from("<Q", raw, 41)[0]
                )
                if slot < new_split:
                    self.kv.delete(col, key)
        self._set_split_slot(new_split)
        _timer.__exit__(None, None, None)
        _DB_SIZE.set(self.kv.approx_size())

    def cold_block_root_at_slot(self, slot: int) -> Optional[bytes]:
        return self.kv.get(Column.COLD_BLOCK_ROOTS, struct.pack("<Q", slot))

    def forwards_block_roots(self, start_slot: int, end_slot: int) -> Iterator[tuple[int, bytes]]:
        """Cold-range forwards iterator (reference ``forwards_iter.rs``)."""
        for slot in range(start_slot, end_slot + 1):
            root = self.cold_block_root_at_slot(slot)
            if root is not None:
                yield slot, root

    # -- head / metadata -------------------------------------------------

    def put_head(self, block_root: bytes) -> None:
        self.kv.put(Column.METADATA, _HEAD_KEY, block_root)

    def get_head(self) -> Optional[bytes]:
        return self.kv.get(Column.METADATA, _HEAD_KEY)

    def put_genesis_state_root(self, root: bytes) -> None:
        self.kv.put(Column.METADATA, _GENESIS_STATE_ROOT_KEY, root)

    def get_genesis_state_root(self) -> Optional[bytes]:
        return self.kv.get(Column.METADATA, _GENESIS_STATE_ROOT_KEY)

    def put_blob(self, column: str, key: bytes, data: bytes) -> None:
        self.kv.put(column, key, data)

    def get_blob(self, column: str, key: bytes) -> Optional[bytes]:
        return self.kv.get(column, key)


def _latest_block_root(state, state_root_hint: bytes | None = None) -> bytes:
    from ..state_transition.helpers import latest_block_header_root

    return latest_block_header_root(state, state_root_hint)


def _fork_of_block(types, signed_block) -> str:
    for fork, cls in types.signed_block.items():
        if isinstance(signed_block, cls):
            return fork
    raise StoreError(f"unknown block type {type(signed_block).__name__}")
