"""Freezer-grade restore-point storage (VERDICT r4 item #5; reference:
``beacon_node/store/src/chunked_vector.rs`` + ``partial_beacon_state.rs``).

The naive freezer stored a FULL SSZ snapshot per restore point — at
mainnet scale that is ~15 MB each, dominated by content that is either
shared between consecutive restore points or already present in the
per-slot cold index. This layout splits a restore-point state into:

* **vector fields reconstructed from global per-slot/epoch columns** —
  ``block_roots[s % W]`` / ``state_roots[s % W]`` are exactly the
  ``COLD_BLOCK_ROOTS`` / ``COLD_STATE_ROOTS`` entries the migrate walk
  already writes (the reference's chunked_vector insight: one global
  copy per slot, not one per state); ``randao_mixes`` gets its own
  per-epoch ``COLD_RANDAO`` column (final mix of each completed epoch).
  Window entries not covered (pre-genesis fill, the in-progress current
  epoch) ride along as explicit exceptions.
* **an interned validator-record table** — each distinct Validator SSZ
  record is stored ONCE globally (``COLD_VREC``, id-keyed;
  ``COLD_VREC_INDEX`` maps record-hash -> id); a restore point stores
  u32 ids. Records change only on activation/exit/slashing/eff-balance
  steps, so consecutive restore points share almost the whole table —
  without diff chains, so loading any restore point stays O(1).
* **packed balances** — raw little-endian u64 array (the one field that
  genuinely changes every epoch for every validator).
* **the partial state** — the full state SSZ with the above fields
  emptied/zeroed, carrying every small field verbatim.

``put_restore_point`` / ``load_restore_point`` round-trip bit-exactly
(asserted by tests against hash_tree_root).
"""

from __future__ import annotations

import struct
import zlib
from typing import Optional

from ..ssz import hash_tree_root
from ..ssz.sha256 import hash_bytes
from ..state_transition.epoch import fork_of
from ..types.containers import FORK_IDS as _FORK_IDS, FORK_NAMES as _FORK_NAMES
from .kv import Column

_NEXT_VREC_KEY = b"next_vrec_id"


def _intern_validators(kv, validators) -> bytes:
    """Validator records -> packed u32 ids, interning new records."""
    raw_next = kv.get(Column.METADATA, _NEXT_VREC_KEY)
    next_id = struct.unpack("<I", raw_next)[0] if raw_next else 0
    ids = []
    new_recs = []
    for v in validators:
        enc = type(v).encode(v)
        h = hash_bytes(enc)[:16]
        known = kv.get(Column.COLD_VREC_INDEX, h)
        if known is None:
            vid = next_id
            next_id += 1
            known = struct.pack("<I", vid)
            new_recs.append((Column.COLD_VREC, known, enc))
            new_recs.append((Column.COLD_VREC_INDEX, h, known))
        ids.append(known)
    if new_recs:
        new_recs.append(
            (Column.METADATA, _NEXT_VREC_KEY, struct.pack("<I", next_id))
        )
        kv.put_batch(new_recs)
    return b"".join(ids)


def _restore_validators(kv, types, ids_blob: bytes):
    vcls = types.Validator
    out = []
    for i in range(0, len(ids_blob), 4):
        rec = kv.get(Column.COLD_VREC, ids_blob[i:i + 4])
        if rec is None:
            raise KeyError(f"missing validator record id at offset {i}")
        out.append(vcls.decode(rec))
    return out


def put_restore_point(kv, types, state_root: bytes, state) -> None:
    """Store ``state`` as a chunked restore point under ``state_root``."""
    preset = types.preset
    W = preset.SLOTS_PER_HISTORICAL_ROOT
    N = preset.EPOCHS_PER_HISTORICAL_VECTOR
    spe = preset.SLOTS_PER_EPOCH
    slot = int(state.slot)
    epoch = slot // spe

    # global per-epoch randao column: final mixes of completed epochs in
    # this state's window (idempotent; only missing keys are written)
    batch = []
    for e in range(max(0, epoch - N + 1), epoch):
        key = struct.pack("<Q", e)
        if kv.get(Column.COLD_RANDAO, key) is None:
            batch.append(
                (Column.COLD_RANDAO, key, bytes(state.randao_mixes[e % N]))
            )
    if batch:
        kv.put_batch(batch)

    # randao exceptions: indices whose epoch is pre-genesis (genesis fill)
    # or the in-progress current epoch
    exceptions = []
    for e in range(epoch - N + 1, epoch + 1):
        if e < 0 or e == epoch:
            idx = e % N
            exceptions.append(struct.pack("<I", idx) + bytes(state.randao_mixes[idx]))

    ids_blob = _intern_validators(kv, state.validators)
    balances_blob = struct.pack(f"<{len(state.balances)}Q", *state.balances)

    # partial state: big fields emptied/zeroed, then restored (callers
    # may hold the state object)
    saved = (
        state.validators, state.balances, state.block_roots,
        state.state_roots, state.randao_mixes,
    )
    zero = b"\x00" * 32
    try:
        state.validators = []
        state.balances = []
        state.block_roots = [zero] * W
        state.state_roots = [zero] * W
        state.randao_mixes = [zero] * N
        partial = type(state).encode(state)
    finally:
        (state.validators, state.balances, state.block_roots,
         state.state_roots, state.randao_mixes) = saved

    blob = b"".join(
        [
            bytes([_FORK_IDS[fork_of(state)]]),
            struct.pack("<III", len(ids_blob), len(balances_blob),
                        len(exceptions)),
            ids_blob,
            balances_blob,
            b"".join(exceptions),
            partial,
        ]
    )
    # zlib (the in-repo snappy is literal-only wire framing): the zeroed
    # vector fields inside `partial` and the genesis randao exceptions
    # early in the chain collapse to run-length tokens
    kv.put(Column.COLD_PARTIAL, state_root, zlib.compress(blob, 6))


def load_restore_point(kv, types, state_root: bytes,
                       cold_block_root_at_slot, cold_state_root_at_slot):
    """Reassemble a chunked restore point; None if absent."""
    blob = kv.get(Column.COLD_PARTIAL, state_root)
    if blob is None:
        return None
    blob = zlib.decompress(blob)
    fork = _FORK_NAMES[blob[0]]
    n_ids, n_bal, n_exc = struct.unpack_from("<III", blob, 1)
    off = 13
    ids_blob = blob[off:off + n_ids]
    off += n_ids
    balances_blob = blob[off:off + n_bal]
    off += n_bal
    exceptions = []
    for _ in range(n_exc):
        (idx,) = struct.unpack_from("<I", blob, off)
        exceptions.append((idx, blob[off + 4:off + 36]))
        off += 36
    state = types.state[fork].decode(blob[off:])

    preset = types.preset
    W = preset.SLOTS_PER_HISTORICAL_ROOT
    N = preset.EPOCHS_PER_HISTORICAL_VECTOR
    spe = preset.SLOTS_PER_EPOCH
    slot = int(state.slot)
    epoch = slot // spe

    state.validators = _restore_validators(kv, types, ids_blob)
    state.balances = list(struct.unpack(f"<{n_bal // 8}Q", balances_blob))

    block_roots = list(state.block_roots)
    state_roots = list(state.state_roots)
    for s in range(max(0, slot - W), slot):
        br = cold_block_root_at_slot(s)
        if br is not None:
            block_roots[s % W] = br
        sr = cold_state_root_at_slot(s)
        if sr is not None:
            state_roots[s % W] = sr
    state.block_roots = block_roots
    state.state_roots = state_roots

    mixes = list(state.randao_mixes)
    for e in range(max(0, epoch - N + 1), epoch):
        raw = kv.get(Column.COLD_RANDAO, struct.pack("<Q", e))
        if raw is not None:
            mixes[e % N] = raw
    for idx, val in exceptions:
        mixes[idx] = val
    state.randao_mixes = mixes
    return state
