"""Client builder: assembles a running beacon node (reference:
``beacon_node/client/src/builder.rs:56-128,676,825`` — store -> chain ->
network/processor -> HTTP API -> timers; plus ``timer`` and
``state_advance_timer``).
"""

from __future__ import annotations

import copy
import json
import threading
from dataclasses import dataclass, field
from typing import Optional

from .beacon_chain import BeaconChain, VerifiedAggregatedAttestation, VerifiedUnaggregatedAttestation
from .beacon_processor import BeaconProcessor, Work, WorkKind
from .http_api import BeaconApiServer
from .operation_pool import OperationPool
from .state_transition import store_replayer
from .store import HotColdDB, MemoryStore, SqliteStore
from .types.chain_spec import ChainSpec
from .types.containers import types_for
from .types.preset import PRESETS
from .utils.slot_clock import SlotClock, SystemTimeSlotClock


@dataclass
class ClientConfig:
    preset_base: str = "mainnet"
    datadir: Optional[str] = None  # None = in-memory store
    http_host: str = "127.0.0.1"
    http_port: int = 5052
    http_enabled: bool = True
    bls_backend: str = "cpu"  # cpu | fake | tpu — the north-star flag
    n_workers: int = 2
    slots_per_snapshot: int = 32
    # None = off; "auto" = monitor every validator; or a list of indices
    monitor_validators: object = None
    slasher: bool = False  # store-backed min-max-span slashing detection
    # None = no p2p network (library/tests); 0 = listen on a free port
    listen_port: object = None
    listen_host: str = "127.0.0.1"
    boot_nodes: tuple = ()  # "host:port" strings dialed at startup
    monitoring_endpoint: Optional[str] = None  # remote metrics push URL
    # cross-caller continuous batching for BLS verification
    # (verification_service/batcher.py); False = direct backend calls
    verification_scheduler: bool = True
    scheduler_deadline_ms: float = 25.0
    scheduler_max_batch_sets: int = 256
    scheduler_max_queue_sets: int = 2048
    # shape-aware flush planner (verification_service/planner.py):
    # kind-homogeneous bin-packed sub-batches when they beat the legacy
    # single-rung flush. None = LIGHTHOUSE_TPU_SCHED_PLANNER env
    # (default on); False pins the legacy plan.
    scheduler_plan_flushes: Optional[bool] = None
    # bulk QoS class (verification_service/batcher.py + admission.py,
    # ISSUE 15): chain-segment backfill / historical sync submit with
    # qos="bulk" — a separate bounded queue flushed only at gossip idle
    # onto the biggest warm rungs, paused by headroom-driven admission
    # control (capacity_headroom_ratio below the floor, or a gossip
    # slo_burn latch) and resumed with hysteresis. None = env knobs
    # LIGHTHOUSE_TPU_SCHED_MAX_BULK_QUEUE (default 8192) /
    # …_SCHED_BULK_FLUSH_SETS (512) / …_SCHED_BULK_LINGER_MS (100) /
    # …_SCHED_BULK_HEADROOM_FLOOR (0.10) / …_SCHED_BULK_RESUME_HEADROOM
    # (0.20).
    scheduler_bulk_max_queue_sets: Optional[int] = None
    scheduler_bulk_flush_sets: Optional[int] = None
    scheduler_bulk_linger_ms: Optional[float] = None
    scheduler_bulk_headroom_floor: Optional[float] = None
    scheduler_bulk_resume_headroom: Optional[float] = None
    # AOT warmup + warm-shape routing + persistent executable caching for
    # the staged device pipeline (compile_service/); only effective with
    # bls_backend="tpu". None cache dir = LIGHTHOUSE_TPU_COMPILE_CACHE_DIR
    # env (unset = no persistent cache); empty rungs = the default ladder.
    compile_service: bool = True
    compile_cache_dir: Optional[str] = None
    compile_rungs: tuple = ()
    # device-resident validator pubkey table (crypto/device/key_table.py,
    # ISSUE 10): uploaded once from the chain's ValidatorPubkeyCache,
    # delta-synced on deposit admission; signature sets whose keys are
    # resident ship (B, K) indices instead of G1 limb planes. Only
    # effective with bls_backend="tpu"; LIGHTHOUSE_TPU_KEY_TABLE=0
    # disables at the env level.
    device_key_table: bool = True
    # None = LIGHTHOUSE_TPU_KEY_TABLE_MAX_AGG env (default 4096); 0
    # disables the aggregate-sum region
    key_table_max_aggregates: Optional[int] = None
    # served dp mesh width (crypto/device/mesh.py, ISSUE 11): how many
    # devices the flush planner shards (dp x rung) plans across. None =
    # env LIGHTHOUSE_TPU_DP_DEVICES (integer, or "all" to discover every
    # local device; unset = 1 — per-chip health without multi-chip
    # compile load). Virtual mesh on a single-host box: set XLA_FLAGS=
    # --xla_force_host_platform_device_count=N before jax initializes.
    # Only effective with bls_backend="tpu"; LIGHTHOUSE_TPU_DP_MESH=0
    # disables the mesh entirely.
    dp_devices: Optional[int] = None
    # the watchtower (utils/watchtower.py, ISSUE 18): the background
    # evaluator walking the detector catalogue over the timeseries
    # store + slot ledger, latching incidents and writing correlated
    # forensic bundles. None = env LIGHTHOUSE_TPU_WATCHTOWER (default
    # on); evaluator cadence/bundle knobs stay env-tunable
    # (LIGHTHOUSE_TPU_WT_INTERVAL_S / _WT_COOLDOWN_S / _WT_BUNDLE /
    # _WT_BUNDLE_DIR / _WT_MAX_INCIDENTS, docs/OBSERVABILITY.md).
    watchtower: Optional[bool] = None
    # device-side operation_pool aggregation (ISSUE 16): route the
    # pool's G2 signature point-sums through the windowed-MSM surface
    # (operation_pool/device_agg.py; programs warmed on the compile
    # service's MSM ladder). OFF by default: the host fold is correct
    # and byte-identical — this only buys the batched-sum speedup. Only
    # effective with bls_backend="tpu".
    device_msm: bool = False
    # duty-lookahead precompute (ISSUE 19): a builder-owned background
    # worker that, past the trigger point inside each epoch, walks the
    # NEXT epoch's committee shuffle and pre-inserts every committee's
    # aggregate-sum G1 row into the key table's (epoch-tagged)
    # aggregate region — so a committee's FIRST sighting already ships
    # K=1 with zero host EC adds inside any verify span. None = env
    # LIGHTHOUSE_TPU_DUTY_LOOKAHEAD (default on); trigger/poll/backoff
    # knobs stay env-tunable (LIGHTHOUSE_TPU_DUTY_LOOKAHEAD_*,
    # docs/DUTY_LOOKAHEAD.md). Only effective when the device key
    # table came up — without a table there is nothing to pre-insert.
    duty_lookahead: Optional[bool] = None


class Client:
    """A built beacon node: chain + processor + API + slot timer."""

    def __init__(self, chain, processor, api, slot_clock, timer):
        self.chain = chain
        self.processor = processor
        self.api = api
        self.network = None  # attached by the builder when listening
        self.monitoring = None  # attached when a monitoring endpoint is set
        self.slot_clock = slot_clock
        self._timer = timer
        self._stop = threading.Event()

    def start(self):
        if self.api is not None:
            self.api.start()
        # capacity & saturation observability (ISSUE 14): the background
        # sampler that feeds /lighthouse/timeseries and the headroom
        # estimate in the health `capacity` block. No-op (free) when
        # LIGHTHOUSE_TPU_TIMESERIES=0.
        from .utils import timeseries, watchtower

        if timeseries.enabled():
            timeseries.start_sampler()
        # the watchtower (ISSUE 18): background detector evaluation
        # over the store the sampler just started feeding; incident
        # bundles snapshot the same (TTL-cached) health document the
        # endpoint serves. No-op when LIGHTHOUSE_TPU_WATCHTOWER=0 or
        # config.watchtower=False.
        if watchtower.enabled():
            if self.api is not None:
                watchtower.set_health_provider(self.api._health_doc)
            watchtower.start_evaluator()
        self._timer.start()
        return self

    def stop(self):
        try:
            self._stop.set()
            from .utils import timeseries, watchtower

            # evaluator before sampler: a final tick against a live
            # store beats one against a stopping one; the provider is
            # cleared so bundles never call a stopped server's cache
            watchtower.stop_evaluator()
            watchtower.set_health_provider(None)
            timeseries.stop_sampler()
            if self.api is not None:
                self.api.stop()
            monitor = getattr(self.chain, "validator_monitor", None)
            if monitor is not None:
                monitor.detach()  # stop feeding a dead client's monitor
            sched = getattr(self.chain, "verification_scheduler", None)
            if sched is not None:
                # drain BEFORE the processor joins its workers: stop()
                # resolves every queued future, and post-stop submissions
                # degrade to synchronous direct calls
                sched.stop()
            csvc = getattr(self.chain, "compile_service", None)
            if csvc is not None:
                # after the scheduler drain: in-flight flushes may still
                # route through the warm-shape registry
                from .compile_service import clear_service

                csvc.stop()
                clear_service(csvc)
            lookahead = getattr(self.chain, "duty_lookahead", None)
            if lookahead is not None:
                # before the key table closes: an in-flight warm may
                # still be pre-inserting rows into it (bounded join —
                # stop() during a warm must never wedge)
                lookahead.stop()
            ktable = getattr(self.chain, "device_key_table", None)
            if ktable is not None:
                # after the drain too: a draining flush may still pack
                # against the table. Detach only OUR table — a racing
                # rebuild must not lose its fresh one — and drop the
                # admission listener so the cache stops syncing (and
                # keeping alive) a table nothing routes to.
                from .crypto.device import key_table as _key_table

                _key_table.clear_table(ktable)
                listener = getattr(self.chain, "_key_table_listener", None)
                if listener is not None:
                    self.chain.pubkey_cache.unsubscribe(listener)
                    self.chain._key_table_listener = None
                # cancel any pending re-sync retry timer: a stopped
                # client's table must not keep syncing in the background
                ktable.close()
            mesh = getattr(self.chain, "device_mesh", None)
            if mesh is not None:
                # last: everything above may still dispatch through the
                # mesh while draining. Detach only OUR mesh — a racing
                # rebuild must not lose its fresh one. The recovery
                # worker stops FIRST with a bounded join — stop() during
                # an active probation probe must never wedge (ISSUE 13).
                from .crypto.device import mesh as _mesh_mod

                mesh.stop_recovery()
                _mesh_mod.clear_mesh(mesh)
            self.processor.shutdown()
            self.persist()
            if self.monitoring is not None:
                self.monitoring.stop()
            if self.network is not None:
                self.network.close()
        finally:
            lock = getattr(self, "_lock", None)
            if lock is not None:
                lock.release()

    def persist(self):
        """Write fork choice + op pool + slasher state to the store
        (reference shutdown persistence: ``beacon_chain.rs:400-440``,
        ``operation_pool/src/persistence.rs``)."""
        from .operation_pool.persistence import pool_to_bytes
        from .store.kv import Column

        # independent try/excepts: one failed write must not discard the
        # others, and persistence must never block shutdown
        store = self.chain.store
        try:
            store.put_blob(
                Column.FORK_CHOICE,
                b"fork_choice",
                self.chain.fork_choice_bytes(),  # chain-locked snapshot
            )
        except Exception:
            pass
        try:
            if self.chain.op_pool is not None:
                store.put_blob(
                    Column.OP_POOL, b"pool", pool_to_bytes(self.chain.op_pool)
                )
        except Exception:
            pass
        try:
            if self.chain.slasher is not None:
                self.chain.slasher.flush()
        except Exception:
            pass
        try:
            if self.network is not None:
                store.put_blob(
                    Column.METADATA,
                    b"known_peers",
                    json.dumps(self.network.discovery.addresses()).encode(),
                )
        except Exception:
            pass


class ClientBuilder:
    def __init__(self, config: ClientConfig, spec: ChainSpec | None = None):
        self.config = config
        self.preset = PRESETS[config.preset_base]
        self.spec = spec or (
            ChainSpec() if config.preset_base == "mainnet" else _minimal()
        )
        self.types = types_for(self.preset)
        self.genesis_state = None
        self.slot_clock: SlotClock | None = None

    def with_genesis_state(self, state):
        self.genesis_state = state
        return self

    def with_interop_genesis(self, validator_count: int, genesis_time: int = 0):
        from .state_transition import interop_genesis_state

        self.genesis_state = interop_genesis_state(
            self.preset, self.spec, validator_count, genesis_time=genesis_time
        )
        return self

    def with_slot_clock(self, clock: SlotClock):
        self.slot_clock = clock
        return self

    def with_checkpoint_sync(self, remote_url: str):
        """Bootstrap from a remote BN's finalized state instead of genesis
        (reference checkpoint sync, ``client/src/builder.rs:128-350``);
        history below the anchor is backfilled by the network layer."""
        from .eth2_client import BeaconNodeClient

        from .state_transition.helpers import latest_block_header_root

        remote = BeaconNodeClient(remote_url, self.types)
        state = remote.state_ssz("finalized")
        self.genesis_state = state
        # fetch the block by the root the STATE implies — "finalized" could
        # have advanced between the two requests
        anchor_root = latest_block_header_root(state)
        try:
            self._checkpoint_block = remote.block("0x" + anchor_root.hex())
        except Exception:
            self._checkpoint_block = None  # anchor block lookups 404 until synced
        return self

    def build(self) -> Client:
        cfg = self.config

        # the north-star seam: runtime backend selection
        from .crypto import backend as bls_backend

        bls_backend.set_backend(cfg.bls_backend)

        lock = None
        if cfg.datadir:
            from .utils import Lockfile

            lock = Lockfile(f"{cfg.datadir}/beacon.lock").acquire()
        try:
            return self._build_locked(cfg, lock)
        except BaseException:
            if lock is not None:
                lock.release()  # a failed build must not wedge the datadir
            raise

    def _build_locked(self, cfg, lock) -> Client:
        kv = (
            SqliteStore(f"{cfg.datadir}/chain.sqlite")
            if cfg.datadir
            else MemoryStore()
        )
        store = HotColdDB(
            kv,
            self.types,
            self.spec,
            store_replayer(self.preset, self.spec),
            slots_per_snapshot=cfg.slots_per_snapshot,
        )

        genesis = self.genesis_state
        if genesis is None:
            # resume from the store: anchor the chain at the persisted
            # HEAD's post-state (reference resume path in
            # ``client/src/builder.rs``: resume_from_db), not at genesis.
            head_root = store.get_head()
            anchor = None
            if head_root is not None:
                head_block = store.get_block(head_root)
                if head_block is not None:
                    anchor = store.get_state(bytes(head_block.message.state_root))
            if anchor is None:
                root = store.get_genesis_state_root()
                if root is None:
                    raise ValueError(
                        "no genesis state provided and none found in the store"
                    )
                anchor = store.get_state(root)
            genesis = anchor

        clock = self.slot_clock or SystemTimeSlotClock(
            genesis.genesis_time, self.spec.seconds_per_slot
        )
        chain = BeaconChain(
            self.preset, self.spec, self.types, store, genesis, slot_clock=clock
        )

        # restore persisted fork choice + op pool (reference resume:
        # beacon_chain.rs:400-440, operation_pool/src/persistence.rs)
        from .store.kv import Column

        fc_blob = store.get_blob(Column.FORK_CHOICE, b"fork_choice")
        if fc_blob is not None:
            from .fork_choice.persistence import fork_choice_from_bytes

            try:
                restored = fork_choice_from_bytes(
                    self.preset, self.spec, fc_blob
                )
            except Exception:
                restored = None  # corrupt/old blob: keep the anchor-built one
            if restored is not None:
                chain.fork_choice = restored
                # The store's HEAD advances on every recompute_head but the
                # blob is written only on finalization/shutdown: after a
                # crash the restored DAG may predate the persisted head, and
                # new blocks building on it would stall as ParentUnknown.
                # Replay the store blocks between the DAG tip and HEAD.
                # Replay failures get their OWN handler: the blob is already
                # installed, so a swallowed error here would silently keep a
                # partially-replayed DAG — log it instead.
                try:
                    _replay_fork_choice_gap(chain, store)
                except Exception as e:
                    from .utils import logging as tlog

                    tlog.log(
                        "warn", "fork-choice crash-gap replay failed",
                        error=repr(e)[:120],
                    )

        pool_blob = store.get_blob(Column.OP_POOL, b"pool")
        if pool_blob is not None:
            from .operation_pool.persistence import pool_from_bytes

            try:
                chain.op_pool = pool_from_bytes(
                    self.preset, self.spec, self.types, pool_blob
                )
            except Exception:
                chain.op_pool = OperationPool(self.preset, self.spec, self.types)
        else:
            chain.op_pool = OperationPool(self.preset, self.spec, self.types)

        if cfg.bls_backend == "tpu" and cfg.device_msm:
            # device-side pool aggregation (ISSUE 16): attach AFTER
            # construction so the persistence-restored pool gets it too;
            # also opt the compile service's AOT walk into warming the
            # MSM ladder so the first real aggregate pays no compile
            from .compile_service.service import set_msm_warm_enabled
            from .operation_pool import DeviceAggregator

            chain.op_pool.set_device_aggregator(DeviceAggregator())
            set_msm_warm_enabled(True)

        if cfg.slasher:
            from .slasher import Slasher

            # found slashings are drained into the op pool by the slot
            # timer (reference: slasher/service/src/service.rs)
            chain.slasher = Slasher(
                self.types,
                slots_per_epoch=self.preset.SLOTS_PER_EPOCH,
                store=kv,
            )
        if cfg.monitor_validators is not None:
            from .beacon_chain import ValidatorMonitor

            monitor = ValidatorMonitor(auto=cfg.monitor_validators == "auto")
            if isinstance(cfg.monitor_validators, (list, tuple, set)):
                for i in cfg.monitor_validators:
                    monitor.add_validator(int(i))
            chain.validator_monitor = monitor.attach()
        # checkpoint sync: store the anchor block so lookups resolve and
        # backfill has a starting parent
        cp_block = getattr(self, "_checkpoint_block", None)
        if cp_block is not None:
            from .ssz import hash_tree_root as _htr

            store.put_block(_htr(cp_block.message), cp_block)

        mesh = None
        if cfg.bls_backend == "tpu":
            from .crypto.device import mesh as mesh_mod

            if mesh_mod.env_enabled():
                try:
                    # mesh FIRST: the key table replicates per mesh
                    # shard and the compile service walks the mesh
                    # ladder — both read the seam at their own startup
                    want = cfg.dp_devices
                    if want is None:
                        env_n = mesh_mod.env_devices()
                        want = None if env_n == "all" else (env_n or 1)
                    mesh = mesh_mod.DeviceMesh(n_devices=want)
                    mesh_mod.set_mesh(mesh)
                    if mesh_mod.recovery_env_enabled():
                        # self-healing (ISSUE 13): lost chips enter
                        # probation and a background probe re-admits
                        # them (canary -> re-warm -> key-table re-sync)
                        mesh.start_recovery()
                except Exception as e:
                    from .utils import logging as tlog

                    tlog.log(
                        "warn", "device mesh unavailable",
                        error=repr(e)[:120],
                    )
                    mesh = None
        chain.device_mesh = mesh

        ktable = None
        if cfg.bls_backend == "tpu" and cfg.device_key_table:
            from .crypto.device import key_table as _key_table

            if _key_table.env_enabled():
                try:
                    # one upload at startup mirrors the loaded cache
                    # (restart-from-store included); import_new_pubkeys
                    # admissions delta-sync through the subscription
                    ktable = _key_table.DeviceKeyTable(
                        chain.pubkey_cache,
                        max_aggregates=cfg.key_table_max_aggregates,
                    )
                    ktable.sync(reason="startup")
                    _key_table.set_table(ktable)
                    # sync_or_schedule (ISSUE 13): a failed delta
                    # schedules a full-sync retry with backoff instead
                    # of degrading batches to raw packs forever
                    listener = (
                        lambda _cache, _t=ktable:
                        _t.sync_or_schedule(reason="delta")
                    )
                    chain.pubkey_cache.subscribe(listener)
                    # stop() must be able to detach it, or admissions
                    # would keep a dead client's table alive + syncing
                    chain._key_table_listener = listener
                except Exception as e:
                    from .utils import logging as tlog

                    tlog.log(
                        "warn", "device key table unavailable",
                        error=repr(e)[:120],
                    )
                    ktable = None
        chain.device_key_table = ktable

        lookahead = None
        if ktable is not None:
            # duty-lookahead precompute (ISSUE 19): only with a live key
            # table — the worker exists to pre-insert aggregate rows
            from . import duty_lookahead as _lookahead

            want = (
                _lookahead.enabled()
                if cfg.duty_lookahead is None else cfg.duty_lookahead
            )
            if want:
                lookahead = _lookahead.DutyLookahead(
                    _lookahead.chain_duty_source(chain),
                    key_table=ktable,
                    pubkey_cache=chain.pubkey_cache,
                ).start()
        chain.duty_lookahead = lookahead

        csvc = None
        if cfg.bls_backend == "tpu" and cfg.compile_service:
            from .compile_service import CompileService, set_service
            from .compile_service.service import env_enabled

            if env_enabled():
                # AOT-warm the staged bucket ladder off the hot path and
                # route cold-bucket traffic around XLA compiles; also
                # wires the persistent executable cache into the node so
                # a restart warm-starts from disk
                csvc = CompileService(
                    rungs=cfg.compile_rungs or None,
                    cache_dir=cfg.compile_cache_dir,
                ).start()
                set_service(csvc)  # the seam TpuBackend pads against
        chain.compile_service = csvc

        if cfg.verification_scheduler:
            # the continuous-batching layer: gossip verifiers submit
            # through chain.verification_scheduler and their signature
            # sets fuse into shared device batches across callers
            from .verification_service import VerificationScheduler

            bulk_admission = None
            if (
                cfg.scheduler_bulk_headroom_floor is not None
                or cfg.scheduler_bulk_resume_headroom is not None
            ):
                # explicit admission thresholds: build the controller
                # here; unset = the scheduler's own (env-tunable) one
                from .verification_service import BulkAdmissionController

                bulk_admission = BulkAdmissionController(
                    floor=cfg.scheduler_bulk_headroom_floor,
                    resume_headroom=cfg.scheduler_bulk_resume_headroom,
                )
            chain.verification_scheduler = VerificationScheduler(
                deadline_ms=cfg.scheduler_deadline_ms,
                max_batch_sets=cfg.scheduler_max_batch_sets,
                max_queue_sets=cfg.scheduler_max_queue_sets,
                compile_service=csvc,
                plan_flushes=cfg.scheduler_plan_flushes,
                bulk_max_queue_sets=cfg.scheduler_bulk_max_queue_sets,
                bulk_flush_sets=cfg.scheduler_bulk_flush_sets,
                bulk_linger_ms=cfg.scheduler_bulk_linger_ms,
                bulk_admission=bulk_admission,
            ).start()

        processor = _build_processor(chain, cfg.n_workers)

        network = None
        if cfg.listen_port is not None:
            from .network.service import NetworkService

            network = NetworkService(
                chain, processor, host=cfg.listen_host, port=int(cfg.listen_port)
            )
            known = store.get_blob(Column.METADATA, b"known_peers")
            if known is not None:
                try:
                    network.discovery.import_addresses(json.loads(known))
                except Exception:
                    pass
            for addr in cfg.boot_nodes:
                try:
                    host, port = addr.rsplit(":", 1)
                    network.connect(host, int(port))
                except (ValueError, OSError):
                    pass
        # the watchtower config seam (ISSUE 18): an explicit
        # cfg.watchtower overrides the LIGHTHOUSE_TPU_WATCHTOWER env
        # default; None leaves the env knob in charge
        if cfg.watchtower is not None:
            from .utils import watchtower as _watchtower

            _watchtower.configure(enabled=cfg.watchtower)
        api = (
            BeaconApiServer(chain, cfg.http_host, cfg.http_port)
            if cfg.http_enabled
            else None
        )
        stop = threading.Event()
        timer = threading.Thread(
            target=_slot_timer, args=(chain, clock, stop), daemon=True
        )
        client = Client(chain, processor, api, clock, timer)
        client.network = network
        if cfg.monitoring_endpoint:
            from .utils.monitoring import MonitoringService

            client.monitoring = MonitoringService(
                chain, cfg.monitoring_endpoint
            ).start()
        client._stop = stop
        client._lock = lock
        return client


def _replay_fork_choice_gap(chain, store) -> None:
    """Walk back from the store's persisted HEAD to the first block the
    restored fork choice knows, then replay the gap (oldest first) into
    it so the resumed node can extend its own pre-crash head."""
    head_root = store.get_head()
    proto = chain.fork_choice.proto
    if head_root is None or proto.contains(head_root):
        return
    gap = []
    root = head_root
    while root is not None and not proto.contains(root):
        block = store.get_block(root)
        if block is None:
            return  # chain of unknown ancestry: keep the blob's DAG as-is
        gap.append((root, block))
        parent = bytes(block.message.parent_root)
        root = parent if any(parent) else None
    if root is None:
        return  # walked past genesis without meeting the DAG
    for blk_root, block in reversed(gap):
        state = store.get_state(bytes(block.message.state_root))
        if state is None:
            return
        chain.fork_choice.on_block(
            int(block.message.slot), block.message, blk_root, state
        )


def _build_processor(chain, n_workers: int) -> BeaconProcessor:
    """Wire the gossip work kinds to the chain's batch verifiers
    (reference ``worker/gossip_methods.rs`` entry points)."""

    def on_attestation_batch(items):
        results = chain.batch_verify_unaggregated_attestations_for_gossip(items)
        for r in results:
            if isinstance(r, VerifiedUnaggregatedAttestation):
                chain.apply_attestation_to_fork_choice(r)
                if chain.op_pool is not None:
                    chain.op_pool.insert_attestation(r.attestation)
                if chain.slasher is not None:
                    chain.slasher.accept_attestation(r.indexed)
        return results

    def on_aggregate_batch(items):
        results = chain.batch_verify_aggregated_attestations_for_gossip(items)
        for r in results:
            if isinstance(r, VerifiedAggregatedAttestation):
                chain.apply_attestation_to_fork_choice(r)
                if chain.op_pool is not None:
                    chain.op_pool.insert_attestation(r.signed_aggregate.message.aggregate)
                if chain.slasher is not None:
                    chain.slasher.accept_attestation(r.indexed)
        return results

    def on_block(item):
        # the slasher must see the header BEFORE gossip verification: an
        # equivocating second block is rejected there as RepeatProposal,
        # which is exactly the event that yields a ProposerSlashing
        if chain.slasher is not None:
            msg = item.message
            from .ssz import hash_tree_root as _htr

            header = chain.types.SignedBeaconBlockHeader(
                message=chain.types.BeaconBlockHeader(
                    slot=msg.slot,
                    proposer_index=msg.proposer_index,
                    parent_root=msg.parent_root,
                    state_root=msg.state_root,
                    body_root=_htr(msg.body),
                ),
                signature=item.signature,
            )
            found = chain.slasher.check_block_header(header)
            if found is not None and chain.op_pool is not None:
                chain.op_pool.insert_proposer_slashing(found)
        gossip = chain.verify_block_for_gossip(item)
        return chain.process_block(gossip)

    def on_chain_segment(item):
        return chain.process_chain_segment(item)

    def on_sync_message_batch(items):
        from .beacon_chain.sync_committee_verification import (
            VerifiedSyncCommitteeMessage,
            batch_verify_sync_committee_messages,
        )

        results = batch_verify_sync_committee_messages(chain, items)
        if chain.op_pool is not None:
            for v in results:
                if isinstance(v, VerifiedSyncCommitteeMessage):
                    m = v.message
                    for pos in v.positions:
                        chain.op_pool.insert_sync_committee_message(
                            int(m.slot),
                            bytes(m.beacon_block_root),
                            pos,
                            bytes(m.signature),
                        )
        return results

    def on_sync_contribution(item):
        from .beacon_chain import verify_sync_contribution

        v = verify_sync_contribution(chain, item)
        if chain.op_pool is not None:
            chain.op_pool.insert_sync_contribution(item.message.contribution)
        return v

    processor = BeaconProcessor(
        {
            WorkKind.GOSSIP_ATTESTATION: on_attestation_batch,
            WorkKind.GOSSIP_AGGREGATE: on_aggregate_batch,
            WorkKind.GOSSIP_SYNC_MESSAGE: on_sync_message_batch,
            WorkKind.GOSSIP_SYNC_CONTRIBUTION: on_sync_contribution,
            WorkKind.GOSSIP_BLOCK: on_block,
            WorkKind.CHAIN_SEGMENT: on_chain_segment,
        },
        n_workers=n_workers,
    )
    # the /lighthouse/health surface reads queue depths off the chain
    chain.beacon_processor = processor
    return processor


def _slot_timer(chain, clock, stop: threading.Event) -> None:
    """Per-slot tick (reference ``timer/src/lib.rs``): advance fork
    choice's clock and re-evaluate the head each slot, until stopped."""
    last = -1
    last_pruned_epoch = [0]
    while not stop.is_set():
        slot = clock.now()
        if slot != last:
            try:
                chain.on_tick(slot)
            except Exception:
                pass
            if chain.slasher is not None:
                # periodic batch processing + evidence → op pool, and
                # pruning on finalization advance (reference:
                # slasher/service/src/service.rs)
                try:
                    chain.slasher.process_queued()
                    net = getattr(chain, "network", None)
                    while chain.slasher.found_attester_slashings:
                        s = chain.slasher.found_attester_slashings.pop(0)
                        if chain.op_pool is not None:
                            chain.op_pool.insert_attester_slashing(s)
                        # equivocators lose fork-choice weight immediately,
                        # same as evidence submitted via the API pool route
                        chain.on_attester_slashing(s)
                        if net is not None:
                            net.publish_attester_slashing(s)
                    while chain.slasher.found_proposer_slashings:
                        s = chain.slasher.found_proposer_slashings.pop(0)
                        if chain.op_pool is not None:
                            chain.op_pool.insert_proposer_slashing(s)
                        if net is not None:
                            net.publish_proposer_slashing(s)
                    fin = chain.fork_choice.store.finalized_checkpoint[0]
                    if fin > last_pruned_epoch[0]:
                        chain.slasher.prune(fin)
                        last_pruned_epoch[0] = fin
                except Exception:
                    pass
            last = slot
        # state-advance timer (reference state_advance_timer.rs:93-231):
        # in the last quarter of the slot, pre-advance the head state to
        # the next slot so the boundary spike is paid off-path
        remaining = clock.duration_to_next_slot()
        seconds_per_slot = getattr(clock, "seconds_per_slot", 12)
        if remaining < seconds_per_slot / 4:
            try:
                chain.advance_head_state_to(slot + 1)
            except Exception:
                pass
        stop.wait(min(1.0, max(0.05, remaining)))


def _minimal() -> ChainSpec:
    from .types.chain_spec import minimal_spec

    return minimal_spec()
