"""Light-client update production (reference:
``beacon_node/beacon_chain``'s light-client server duties over
``consensus/types/src/light_client_*.rs``: serve Bootstrap /
FinalityUpdate / OptimisticUpdate objects proving sync-committee and
finality membership out of the head state)."""

from __future__ import annotations

from ..ssz import hash_tree_root
from ..ssz.proof import compute_merkle_proof
from ..state_transition.helpers import latest_block_header_root


FINALIZED_ROOT_INDEX = 105
NEXT_SYNC_COMMITTEE_INDEX = 55
CURRENT_SYNC_COMMITTEE_INDEX = 54


def _header_for(chain, state):
    """BeaconBlockHeader of the state's latest block, state_root filled."""
    import copy

    header = copy.copy(state.latest_block_header)
    if bytes(header.state_root) == bytes(32):
        header.state_root = hash_tree_root(state)
    return header


def produce_bootstrap(chain, state):
    """LightClientBootstrap for a (finalized) state."""
    t = chain.types
    leaf, branch, gi = compute_merkle_proof(state, ["current_sync_committee"])
    assert gi == CURRENT_SYNC_COMMITTEE_INDEX, gi
    return t.LightClientBootstrap(
        header=_header_for(chain, state),
        current_sync_committee=state.current_sync_committee,
        current_sync_committee_branch=branch,
    )


def produce_finality_update(chain):
    """LightClientFinalityUpdate at the current head."""
    t = chain.types
    state = chain.head_state
    # the branch proves head_state.finalized_checkpoint — the header MUST
    # be that same checkpoint's block (fork choice's store can be ahead)
    fin_root = bytes(state.finalized_checkpoint.root)
    if fin_root == bytes(32):
        return None  # no real finality yet: nothing provable to serve
    fin_block = chain.store.get_block(fin_root)
    if fin_block is None:
        return None
    leaf, branch, gi = compute_merkle_proof(
        state, ["finalized_checkpoint", "root"]
    )
    assert gi == FINALIZED_ROOT_INDEX, gi
    fin_msg = fin_block.message
    finalized_header = t.BeaconBlockHeader(
        slot=fin_msg.slot,
        proposer_index=fin_msg.proposer_index,
        parent_root=bytes(fin_msg.parent_root),
        state_root=bytes(fin_msg.state_root),
        body_root=hash_tree_root(fin_msg.body),
    )
    agg = None
    if chain.op_pool is not None:
        agg = chain.op_pool.sync_aggregate_for_block(
            state.slot, chain.head_block_root
        )
    if agg is None:
        from ..crypto.bls import INFINITY_SIGNATURE

        agg = t.SyncAggregate(sync_committee_signature=INFINITY_SIGNATURE)
    return t.LightClientFinalityUpdate(
        attested_header=_header_for(chain, state),
        finalized_header=finalized_header,
        finality_branch=branch,
        sync_aggregate=agg,
        signature_slot=state.slot + 1,
    )


def produce_optimistic_update(chain):
    t = chain.types
    state = chain.head_state
    agg = None
    if chain.op_pool is not None:
        agg = chain.op_pool.sync_aggregate_for_block(
            state.slot, chain.head_block_root
        )
    if agg is None:
        from ..crypto.bls import INFINITY_SIGNATURE

        agg = t.SyncAggregate(sync_committee_signature=INFINITY_SIGNATURE)
    return t.LightClientOptimisticUpdate(
        attested_header=_header_for(chain, state),
        sync_aggregate=agg,
        signature_slot=state.slot + 1,
    )
