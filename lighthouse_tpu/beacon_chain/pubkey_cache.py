"""Validator pubkey cache (reference:
``beacon_node/beacon_chain/src/validator_pubkey_cache.rs:20-136``).

Decompression + subgroup checks happen ONCE, at validator-registry
admission; every subsequent signature build is an O(1) index lookup of the
already-validated point. This is the structural prerequisite for the TPU
batch path: sets are packed from decompressed points without touching the
per-block deserialization cost the round-1 code paid.

Persisted to the store (compressed bytes keyed by index) and reloaded at
startup, like the reference (``validator_pubkey_cache.rs:49,79``).
"""

from __future__ import annotations

import struct
from typing import Optional

from ..crypto import bls
from ..store.kv import Column


class PubkeyCacheError(ValueError):
    pass


class ValidatorPubkeyCache:
    def __init__(self, store=None):
        self.pubkeys: list[bls.PublicKey] = []
        self.indices: dict[bytes, int] = {}  # compressed bytes -> index
        self.store = store
        # admission listeners (ISSUE 10): the device-resident pubkey
        # table subscribes so deposits delta-sync host→device without
        # the cache importing the device stack
        self._listeners: list = []
        if store is not None:
            self._load()

    def subscribe(self, fn) -> None:
        """Call ``fn(cache)`` after every successful admission batch.
        Listener failures are contained (logged, never raised): a device
        mirror that cannot sync degrades that mirror — new indices fall
        back to the raw pack path — and must not fail block import."""
        self._listeners.append(fn)

    def unsubscribe(self, fn) -> None:
        """Remove a listener (no-op when absent): a stopped client must
        detach its device mirror or admissions would keep syncing — and
        keeping alive — a table nothing routes to anymore."""
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _load(self) -> None:
        rows = sorted(
            self.store.kv.iter_column(Column.PUBKEY_CACHE),
            key=lambda kv: struct.unpack("<Q", kv[0])[0],
        )
        for key, raw in rows:
            (idx,) = struct.unpack("<Q", key)
            if idx != len(self.pubkeys):
                raise PubkeyCacheError(f"pubkey cache gap at index {idx}")
            pk = bls.PublicKey.deserialize(raw)  # re-validated on load
            self.indices[raw] = idx
            self.pubkeys.append(pk)

    def import_new_pubkeys(self, state) -> None:
        """Admit validators beyond the current length. Raises on an invalid
        (non-subgroup / infinity) pubkey — such a validator cannot exist in
        a valid state (deposits are checked on the way in)."""
        n = len(self.pubkeys)
        if len(state.validators) <= n:
            return
        batch = []
        for idx in range(n, len(state.validators)):
            raw = bytes(state.validators[idx].pubkey)
            pk = bls.PublicKey.deserialize(raw)
            self.indices[raw] = idx
            self.pubkeys.append(pk)
            batch.append((Column.PUBKEY_CACHE, struct.pack("<Q", idx), raw))
        if self.store is not None and batch:
            self.store.kv.put_batch(batch)
        if batch:
            for fn in list(self._listeners):
                try:
                    fn(self)
                except Exception as e:
                    from ..utils import logging as tlog

                    tlog.log(
                        "warn", "pubkey-cache admission listener failed",
                        error=repr(e)[:120],
                    )

    def get(self, validator_index: int) -> bls.PublicKey:
        try:
            return self.pubkeys[validator_index]
        except IndexError:
            raise PubkeyCacheError(
                f"validator index {validator_index} beyond pubkey cache "
                f"({len(self.pubkeys)})"
            ) from None

    def get_index(self, pubkey_bytes: bytes) -> Optional[int]:
        return self.indices.get(bytes(pubkey_bytes))

    def resolver(self):
        """PubkeyResolver for the signature-set constructors."""
        return self.get

    def __len__(self) -> int:
        return len(self.pubkeys)
