"""Fork-boundary revert (reference:
``beacon_node/beacon_chain/src/fork_revert.rs:19-30`` —
``revert_to_fork_boundary``: when the head is stuck on a pre-fork branch
whose blocks were produced without the fork applied, reset the head to
the last block before the fork boundary so the chain can re-sync onto
the right branch)."""

from __future__ import annotations

from ..store.iter import block_roots_iter


def revert_to_fork_boundary(chain, fork_epoch: int) -> bytes:
    """Re-anchor ``chain`` at the latest stored block strictly before the
    fork boundary slot. Returns the new head root. Blocks above the
    boundary remain in the store but leave fork choice (they are re-run
    through import if they were actually valid)."""
    boundary_slot = fork_epoch * chain.preset.SLOTS_PER_EPOCH
    target = None
    for slot, root in block_roots_iter(chain.store, chain.head_block_root):
        if slot < boundary_slot:
            target = (slot, root)
            break
    if target is None:
        raise ValueError("no pre-fork block found to revert to")
    slot, root = target
    block = chain.store.get_block(root)
    state = chain.store.get_state(bytes(block.message.state_root))
    if state is None:
        raise ValueError("pre-fork state unavailable for revert")

    # re-anchor fork choice at the boundary block
    from ..fork_choice.fork_choice import ForkChoice

    chain.fork_choice = ForkChoice(
        chain.preset,
        chain.spec,
        state.slot,
        root,
        (state.current_justified_checkpoint.epoch, root),
        (state.finalized_checkpoint.epoch, root),
        [v.effective_balance for v in state.validators],
    )
    chain.set_head(root, state)
    chain._last_finalized_epoch = state.finalized_checkpoint.epoch
    chain.snapshot_cache.insert(root, state)
    chain.store.put_head(root)
    return root
