"""Production caches: the latency tier that lets the node meet slot
deadlines under load (reference ``beacon_node/beacon_chain/src/
{early_attester_cache,beacon_proposer_cache,attester_cache,
block_times_cache}.rs`` + ``state_advance_timer.rs:93-231``).

All are small, lock-guarded, and advisory: every consumer keeps a
state-backed fallback path, so a miss is a slowdown, never an error.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional


@dataclass
class EarlyAttesterItem:
    epoch: int
    beacon_block_root: bytes
    source: tuple[int, bytes]
    target_root: bytes


class EarlyAttesterCache:
    """Attestation template for the most recently imported head-candidate
    block: serves ``produce_unaggregated_attestation`` without touching
    any state (reference ``beacon_chain.rs:1496-1512``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._item: Optional[EarlyAttesterItem] = None

    def add(self, epoch: int, block_root: bytes, source: tuple[int, bytes],
            target_root: bytes) -> None:
        with self._lock:
            self._item = EarlyAttesterItem(epoch, block_root, source, target_root)

    def try_attest(self, epoch: int, head_root: bytes) -> Optional[EarlyAttesterItem]:
        """The cached template, iff it is for this epoch and this head."""
        with self._lock:
            item = self._item
        if (
            item is not None
            and item.epoch == epoch
            and item.beacon_block_root == head_root
        ):
            return item
        return None


class BeaconProposerCache:
    """(epoch, decision_root) -> proposer index per slot of the epoch
    (reference ``beacon_proposer_cache.rs``; the decision root pins the
    shuffling so a reorg cannot serve stale duties)."""

    def __init__(self, cap: int = 16):
        self._lock = threading.Lock()
        self._cap = cap
        self._map: OrderedDict[tuple[int, bytes], list[int]] = OrderedDict()

    def get(self, epoch: int, decision_root: bytes) -> Optional[list[int]]:
        with self._lock:
            v = self._map.get((epoch, decision_root))
            if v is None:
                return None
            self._map.move_to_end((epoch, decision_root))
            return list(v)  # callers may mutate their copy freely

    def insert(self, epoch: int, decision_root: bytes, proposers: list[int]) -> None:
        with self._lock:
            self._map[(epoch, decision_root)] = list(proposers)
            while len(self._map) > self._cap:
                self._map.popitem(last=False)


@dataclass
class AttesterDutyInfo:
    source: tuple[int, bytes]
    target_root: bytes


class AttesterCache:
    """(epoch, head_root) -> FFG info for attestation production — the
    cross-epoch-boundary fallback that otherwise costs a full state copy
    + epoch advance per request (reference ``attester_cache.rs``)."""

    def __init__(self, cap: int = 16):
        self._lock = threading.Lock()
        self._cap = cap
        self._map: OrderedDict[tuple[int, bytes], AttesterDutyInfo] = OrderedDict()

    def get(self, epoch: int, head_root: bytes) -> Optional[AttesterDutyInfo]:
        with self._lock:
            v = self._map.get((epoch, head_root))
            if v is not None:
                self._map.move_to_end((epoch, head_root))
            return v

    def insert(self, epoch: int, head_root: bytes, info: AttesterDutyInfo) -> None:
        with self._lock:
            self._map[(epoch, head_root)] = info
            while len(self._map) > self._cap:
                self._map.popitem(last=False)


class BlockTimesCache:
    """Per-block observed/imported/became-head timestamps for delay
    metrics and the validator monitor (reference
    ``block_times_cache.rs``). Bounded FIFO."""

    def __init__(self, cap: int = 64):
        self._lock = threading.Lock()
        self._cap = cap
        self._map: OrderedDict[bytes, dict] = OrderedDict()

    def _entry(self, root: bytes) -> dict:
        e = self._map.get(root)
        if e is None:
            e = self._map[root] = {}
            while len(self._map) > self._cap:
                self._map.popitem(last=False)
        return e

    def set_observed(self, root: bytes, ts: float | None = None) -> None:
        with self._lock:
            self._entry(root).setdefault("observed", ts or time.time())

    def set_imported(self, root: bytes, ts: float | None = None) -> None:
        with self._lock:
            self._entry(root).setdefault("imported", ts or time.time())

    def set_became_head(self, root: bytes, ts: float | None = None) -> None:
        with self._lock:
            self._entry(root).setdefault("became_head", ts or time.time())

    def delays(self, root: bytes) -> dict:
        """{observed_to_imported, imported_to_head, observed_to_head}
        (seconds; only the spans whose endpoints were both recorded)."""
        with self._lock:
            e = dict(self._map.get(root, {}))
        out = {}
        if "observed" in e and "imported" in e:
            out["observed_to_imported"] = e["imported"] - e["observed"]
        if "imported" in e and "became_head" in e:
            out["imported_to_head"] = e["became_head"] - e["imported"]
        if "observed" in e and "became_head" in e:
            out["observed_to_head"] = e["became_head"] - e["observed"]
        return out
