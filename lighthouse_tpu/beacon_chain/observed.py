"""Duplicate-detection caches (reference:
``beacon_node/beacon_chain/src/observed_attesters.rs`` (1,002 LoC),
``observed_aggregates.rs``, ``observed_block_producers.rs``,
``observed_operations.rs``).

These guard the gossip pipelines: an item seen once is not re-verified or
re-propagated. All prune on finalization advance.
"""

from __future__ import annotations

from typing import Iterable


class ObservedAttesters:
    """(validator, target-epoch) pairs for unaggregated attestations —
    one vote per epoch per validator may be gossiped."""

    def __init__(self):
        self._by_epoch: dict[int, set[int]] = {}

    def observe(self, validator_index: int, epoch: int) -> bool:
        """Record; True if it was already present."""
        seen = self._by_epoch.setdefault(epoch, set())
        if validator_index in seen:
            return True
        seen.add(validator_index)
        return False

    def is_known(self, validator_index: int, epoch: int) -> bool:
        return validator_index in self._by_epoch.get(epoch, ())

    def prune(self, finalized_epoch: int) -> None:
        for e in [e for e in self._by_epoch if e < finalized_epoch]:
            del self._by_epoch[e]


class ObservedAggregators(ObservedAttesters):
    """(aggregator, target-epoch) — one aggregate per epoch per aggregator."""


class ObservedAggregates:
    """Roots of aggregate attestations already fully processed (keyed by
    hash-tree-root of the attestation, per slot)."""

    def __init__(self):
        self._by_slot: dict[int, set[bytes]] = {}

    def observe(self, att_root: bytes, slot: int) -> bool:
        seen = self._by_slot.setdefault(slot, set())
        if att_root in seen:
            return True
        seen.add(att_root)
        return False

    def is_known(self, att_root: bytes, slot: int) -> bool:
        return att_root in self._by_slot.get(slot, ())

    def prune(self, finalized_slot: int) -> None:
        for s in [s for s in self._by_slot if s < finalized_slot]:
            del self._by_slot[s]


class ObservedBlockProducers:
    """(proposer, slot) pairs — equivocation guard on gossip blocks."""

    def __init__(self):
        self._by_slot: dict[int, set[int]] = {}

    def observe(self, proposer_index: int, slot: int) -> bool:
        seen = self._by_slot.setdefault(slot, set())
        if proposer_index in seen:
            return True
        seen.add(proposer_index)
        return False

    def is_known(self, proposer_index: int, slot: int) -> bool:
        return proposer_index in self._by_slot.get(slot, ())

    def prune(self, finalized_slot: int) -> None:
        for s in [s for s in self._by_slot if s <= finalized_slot]:
            del self._by_slot[s]


class ObservedOperations:
    """Dedup for gossiped slashings/exits (reference
    ``observed_operations.rs``): proposer slashings by proposer index,
    exits by validator index, attester slashings by attesting-index
    coverage (a slashing adding no new indices is redundant)."""

    def __init__(self):
        self.proposer_slashings: set[int] = set()
        self.exits: set[int] = set()
        self.attester_slashed: set[int] = set()

    def observe_proposer_slashing(self, proposer_index: int) -> bool:
        if proposer_index in self.proposer_slashings:
            return True
        self.proposer_slashings.add(proposer_index)
        return False

    def observe_exit(self, validator_index: int) -> bool:
        if validator_index in self.exits:
            return True
        self.exits.add(validator_index)
        return False

    def observe_attester_slashing(self, slashable_indices: Iterable[int]) -> bool:
        new = set(slashable_indices) - self.attester_slashed
        if not new:
            return True
        self.attester_slashed |= new
        return False
