"""Gossip attestation verification, single and batched (reference:
``beacon_node/beacon_chain/src/attestation_verification.rs`` and
``attestation_verification/batch.rs:31-222``).

The batch paths are the TPU feeder: N structural-verified attestations
become one backend ``verify_signature_sets`` call (1 set per unaggregated
attestation; 3 per aggregate — ``batch.rs:77-107,182-196``). On a batch
failure, items are re-verified individually so per-item results are
identical to the non-batched path (``batch.rs:1-11,115-119``).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..crypto.bls import BlsError
from ..ssz import hash_tree_root
from ..state_transition.helpers import compute_epoch_at_slot
from ..state_transition.signature_sets import (
    aggregate_and_proof_sets,
    indexed_attestation_set,
)
from ..utils import flight_recorder, metrics, tracing
from ..verification_service import backend_verify, backend_verify_each

ATTESTATION_PROPAGATION_SLOT_RANGE = 32
TARGET_AGGREGATORS_PER_COMMITTEE = 16

_BATCH_SETUP = metrics.histogram_vec(
    "attestation_batch_setup_seconds",
    "structural checks + set building for a gossip attestation batch",
    ("kind",),
)
_BATCH_SIG = metrics.histogram_vec(
    "attestation_batch_signature_seconds",
    "backend batch signature verification for a gossip attestation batch",
    ("kind",),
)
_VERIFY_SECONDS = metrics.histogram_vec(
    "attestation_verification_seconds",
    "full gossip-to-verdict wall time (mode=batch: one sample per "
    "N-item batch; mode=single: one per item)",
    ("kind", "mode"),
)
_OUTCOMES = metrics.counter_vec(
    "attestation_verification_outcomes_total",
    "per-item gossip attestation verdicts (outcome = ok or the error kind)",
    ("kind", "outcome"),
)


def _att_data(kind: str, item):
    """The AttestationData of a gossip item, whichever wrapper it wears."""
    return item.data if kind == "unaggregated" else item.message.aggregate.data


def _record_rejection(kind: str, e: "AttestationError", item) -> None:
    """Journal one ``attestation_rejected`` event: reason + slot/root (+
    the validator/aggregator index when the raise site knew it)."""
    try:
        data = _att_data(kind, item)
        where = {
            "slot": int(data.slot),
            "committee_index": int(data.index),
            "root": bytes(data.beacon_block_root),
        }
    except Exception:  # malformed item: the reason is still worth keeping
        where = {}
    flight_recorder.record(
        "attestation_rejected", kind=kind, reason=e.kind, **e.ctx, **where
    )


def _count_outcomes(kind: str, results, items) -> None:
    for r, item in zip(results, items):
        if isinstance(r, AttestationError):
            _OUTCOMES.with_labels(kind, r.kind).inc()
            _record_rejection(kind, r, item)
        else:
            _OUTCOMES.with_labels(kind, "ok").inc()


def _observed(kind: str):
    """Single-item paths: same latency family + outcome accounting as the
    batch paths, so dashboards see one verdict stream per kind."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(chain, item, current_slot):
            with tracing.span("attestation.verify", kind=kind), \
                    _VERIFY_SECONDS.with_labels(kind, "single").time():
                try:
                    out = fn(chain, item, current_slot)
                except AttestationError as e:
                    _OUTCOMES.with_labels(kind, e.kind).inc()
                    _record_rejection(kind, e, item)
                    raise
                _OUTCOMES.with_labels(kind, "ok").inc()
                return out
        return wrapper
    return deco


class AttestationError(ValueError):
    """Structural/gossip-rule rejection; ``kind`` mirrors the reference's
    error enum so batch fallback can report per-item outcomes. ``ctx``
    carries whatever identifying context the raise site had (validator
    index, aggregator index) for the flight-recorder journal."""

    def __init__(self, kind: str, detail: str = "", **ctx):
        super().__init__(f"{kind}{': ' + detail if detail else ''}")
        self.kind = kind
        self.ctx = ctx


@dataclass
class VerifiedUnaggregatedAttestation:
    attestation: object
    indexed: object
    validator_index: int
    committee_index: int


@dataclass
class VerifiedAggregatedAttestation:
    signed_aggregate: object
    indexed: object
    aggregator_index: int


def _committee_for(chain, data):
    epoch = compute_epoch_at_slot(chain.preset, data.slot)
    cache = chain.shuffling_cache.get(chain, epoch, data.target.root)
    count = cache.committees_per_slot
    if data.index >= count:
        raise AttestationError("BadCommitteeIndex", f"{data.index} >= {count}")
    return cache.committee(data.slot, data.index)


def _structural_unaggregated(chain, att, current_slot: int):
    """Everything except the signature; returns (indexed, validator_index)."""
    data = att.data
    if data.target.epoch != compute_epoch_at_slot(chain.preset, data.slot):
        raise AttestationError("BadTargetEpoch")
    if not (
        data.slot <= current_slot <= data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
    ):
        raise AttestationError(
            "OutsideSlotRange", f"slot {data.slot} vs current {current_slot}"
        )
    bits = list(att.aggregation_bits)
    if sum(bits) != 1:
        raise AttestationError("NotExactlyOneBit")
    if not chain.fork_choice.proto.contains(bytes(data.beacon_block_root)):
        raise AttestationError("UnknownHeadBlock", data.beacon_block_root.hex()[:12])
    if not chain.fork_choice.proto.contains(bytes(data.target.root)):
        raise AttestationError("UnknownTargetRoot")
    committee = _committee_for(chain, data)
    if len(bits) != len(committee):
        raise AttestationError(
            "BitsCommitteeMismatch", f"{len(bits)} != {len(committee)}"
        )
    validator_index = int(committee[bits.index(True)])
    if chain.observed_attesters.is_known(validator_index, data.target.epoch):
        raise AttestationError(
            "PriorAttestationKnown", str(validator_index),
            validator_index=validator_index,
        )
    t = chain.types
    indexed = t.IndexedAttestation(
        attesting_indices=[validator_index], data=data, signature=att.signature
    )
    return indexed, validator_index


@_observed("unaggregated")
def verify_unaggregated_attestation(chain, att, current_slot: int):
    """Single-item gossip path (reference
    ``IndexedUnaggregatedAttestation::verify``)."""
    # Lock discipline (reference: RwLock-guarded caches around a lock-free
    # signature check): structural checks + set building read shared chain
    # state under the chain lock, the BLS call runs WITHOUT it, and the
    # observed-cache commit re-takes it — observe() returning True then
    # catches any racing duplicate.
    with chain._chain_lock:
        indexed, validator_index = _structural_unaggregated(
            chain, att, current_slot
        )
        try:
            s = indexed_attestation_set(
                chain.preset, chain.spec, chain.head_state, indexed,
                chain.pubkey_cache.resolver(),
            )
        except BlsError:
            raise AttestationError("InvalidSignature")
    try:
        ok = backend_verify(chain, [s], "unaggregated")
    except BlsError:  # malformed signature bytes = invalid, never a crash
        ok = False
    if not ok:
        raise AttestationError("InvalidSignature")
    with chain._chain_lock:
        if chain.observed_attesters.observe(validator_index, att.data.target.epoch):
            raise AttestationError(
                "PriorAttestationKnown", validator_index=validator_index
            )
    return VerifiedUnaggregatedAttestation(att, indexed, validator_index, att.data.index)


def batch_verify_unaggregated_attestations(chain, attestations, current_slot: int):
    """One backend call for the whole batch; identical per-item results to
    the single path (reference ``batch.rs:139-222``). Returns a list of
    ``VerifiedUnaggregatedAttestation | AttestationError`` per input.

    The heavy BLS batch runs outside the chain lock so worker threads
    verify concurrently; setup and the observed-cache commit take it."""
    results: list[object] = [None] * len(attestations)
    pending = []  # (pos, att, indexed, validator_index, set)
    with tracing.span(
        "attestation.batch_verify", kind="unaggregated",
        n=len(attestations),
    ), _VERIFY_SECONDS.with_labels("unaggregated", "batch").time():
        with chain._chain_lock, tracing.span("attestation.setup"), \
                _BATCH_SETUP.with_labels("unaggregated").time():
            for pos, att in enumerate(attestations):
                try:
                    indexed, vindex = _structural_unaggregated(chain, att, current_slot)
                    s = indexed_attestation_set(
                        chain.preset, chain.spec, chain.head_state, indexed,
                        chain.pubkey_cache.resolver(),
                    )
                    pending.append((pos, att, indexed, vindex, s))
                except AttestationError as e:
                    results[pos] = e
                except BlsError:
                    results[pos] = AttestationError("InvalidSignature")
        with tracing.span("attestation.signature", n_sets=len(pending)), \
                _BATCH_SIG.with_labels("unaggregated").time():
            # backend_verify routes through the chain's verification
            # scheduler when one is attached (cross-caller fused device
            # batches, verification_service/batcher.py); verdicts are
            # identical to the direct call either way.
            batch_ok = bool(pending) and backend_verify(
                chain, [p[4] for p in pending], "unaggregated"
            )
            # per-item fallback (reference batch.rs:115-119) — still
            # unlocked; submitted together so the retries fuse too
            if batch_ok:
                item_ok = {p[0]: True for p in pending}
            else:
                each = backend_verify_each(
                    chain, [[p[4]] for p in pending], "unaggregated"
                )
                item_ok = {p[0]: ok for p, ok in zip(pending, each)}
        with chain._chain_lock:
            for pos, att, indexed, vindex, s in pending:
                if item_ok[pos]:
                    # observe() returning True = duplicate within this batch or
                    # a racing thread (the pre-batch is_known check ran before
                    # any item was observed); reject it exactly as the
                    # sequential path would.
                    if chain.observed_attesters.observe(vindex, att.data.target.epoch):
                        results[pos] = AttestationError(
                            "PriorAttestationKnown", validator_index=vindex
                        )
                    else:
                        results[pos] = VerifiedUnaggregatedAttestation(
                            att, indexed, vindex, att.data.index
                        )
                else:
                    results[pos] = AttestationError(
                        "InvalidSignature", validator_index=vindex
                    )
    _count_outcomes("unaggregated", results, attestations)
    return results


def _is_aggregator(committee_len: int, selection_proof: bytes) -> bool:
    """Spec ``is_aggregator``: SHA-256 of the proof mod the per-committee
    aggregator modulus."""
    modulo = max(1, committee_len // TARGET_AGGREGATORS_PER_COMMITTEE)
    h = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(h[:8], "little") % modulo == 0


def _structural_aggregated(chain, signed_agg, current_slot: int):
    msg = signed_agg.message
    att = msg.aggregate
    data = att.data
    if data.target.epoch != compute_epoch_at_slot(chain.preset, data.slot):
        raise AttestationError("BadTargetEpoch")
    if not (
        data.slot <= current_slot <= data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
    ):
        raise AttestationError("OutsideSlotRange")
    att_root = hash_tree_root(att)
    if chain.observed_aggregates.is_known(att_root, data.slot):
        raise AttestationError("AttestationAlreadyKnown")
    if chain.observed_aggregators.is_known(msg.aggregator_index, data.target.epoch):
        raise AttestationError(
            "AggregatorAlreadyKnown", aggregator_index=int(msg.aggregator_index)
        )
    if not chain.fork_choice.proto.contains(bytes(data.beacon_block_root)):
        raise AttestationError("UnknownHeadBlock")
    if not chain.fork_choice.proto.contains(bytes(data.target.root)):
        raise AttestationError("UnknownTargetRoot")
    committee = _committee_for(chain, data)
    bits = list(att.aggregation_bits)
    if len(bits) != len(committee):
        raise AttestationError("BitsCommitteeMismatch")
    if not any(bits):
        raise AttestationError("EmptyAggregationBits")
    if msg.aggregator_index not in [int(i) for i in committee]:
        raise AttestationError("AggregatorNotInCommittee")
    if not _is_aggregator(len(committee), bytes(msg.selection_proof)):
        raise AttestationError("InvalidSelectionProof")
    attesting = [int(v) for v, b in zip(committee, bits) if b]
    t = chain.types
    indexed = t.IndexedAttestation(
        attesting_indices=sorted(attesting), data=data, signature=att.signature
    )
    return indexed, att_root


@_observed("aggregate")
def verify_aggregated_attestation(chain, signed_agg, current_slot: int):
    """Single aggregate: 3 signature sets (reference ``batch.rs:77-107``).
    Same lock discipline as the unaggregated path: BLS runs unlocked."""
    with chain._chain_lock:
        indexed, att_root = _structural_aggregated(chain, signed_agg, current_slot)
        try:
            sets = aggregate_and_proof_sets(
                chain.preset, chain.spec, chain.head_state, signed_agg,
                chain.pubkey_cache.resolver(),
            )
        except BlsError:
            raise AttestationError("InvalidSignature")
    try:
        ok = backend_verify(chain, sets, "aggregate")
    except BlsError:
        ok = False
    if not ok:
        raise AttestationError("InvalidSignature")
    msg = signed_agg.message
    with chain._chain_lock:
        # Root first, and only observe the aggregator for an actually-new
        # aggregate — a rejected duplicate root must not burn the
        # aggregator for the whole epoch (the reference checks
        # observed_aggregates before recording the aggregator).
        if chain.observed_aggregates.observe(att_root, msg.aggregate.data.slot):
            raise AttestationError("AttestationAlreadyKnown")
        if chain.observed_aggregators.observe(
            msg.aggregator_index, msg.aggregate.data.target.epoch
        ):
            raise AttestationError("AggregatorAlreadyKnown")
    return VerifiedAggregatedAttestation(signed_agg, indexed, msg.aggregator_index)


def batch_verify_aggregated_attestations(chain, signed_aggs, current_slot: int):
    """3N sets in one backend call, per-item fallback on failure
    (reference ``batch.rs:31-134``). BLS runs outside the chain lock."""
    results: list[object] = [None] * len(signed_aggs)
    pending = []
    with tracing.span(
        "attestation.batch_verify", kind="aggregate", n=len(signed_aggs),
    ), _VERIFY_SECONDS.with_labels("aggregate", "batch").time():
        _batch_verify_aggregated_inner(
            chain, signed_aggs, current_slot, results, pending
        )
    _count_outcomes("aggregate", results, signed_aggs)
    return results


def _batch_verify_aggregated_inner(
    chain, signed_aggs, current_slot, results, pending
):
    with chain._chain_lock, tracing.span("attestation.setup"), \
            _BATCH_SETUP.with_labels("aggregate").time():
        for pos, sa in enumerate(signed_aggs):
            try:
                indexed, att_root = _structural_aggregated(chain, sa, current_slot)
                sets = aggregate_and_proof_sets(
                    chain.preset, chain.spec, chain.head_state, sa,
                    chain.pubkey_cache.resolver(),
                )
                pending.append((pos, sa, indexed, att_root, sets))
            except AttestationError as e:
                results[pos] = e
            except BlsError:
                results[pos] = AttestationError("InvalidSignature")
    with tracing.span("attestation.signature", n_sets=3 * len(pending)), \
            _BATCH_SIG.with_labels("aggregate").time():
        all_sets = [s for p in pending for s in p[4]]
        batch_ok = bool(pending) and backend_verify(
            chain, all_sets, "aggregate"
        )
        if batch_ok:
            item_ok = {p[0]: True for p in pending}
        else:
            each = backend_verify_each(
                chain, [p[4] for p in pending], "aggregate"
            )
            item_ok = {p[0]: ok for p, ok in zip(pending, each)}
    with chain._chain_lock:
        for pos, sa, indexed, att_root, sets in pending:
            if item_ok[pos]:
                msg = sa.message
                # intra-batch (or cross-thread) duplicates: observe()
                # returns True when the root/aggregator is already
                # recorded. Root first; a duplicate root must not burn
                # the aggregator for the epoch.
                if chain.observed_aggregates.observe(
                    att_root, msg.aggregate.data.slot
                ):
                    results[pos] = AttestationError("AttestationAlreadyKnown")
                elif chain.observed_aggregators.observe(
                    msg.aggregator_index, msg.aggregate.data.target.epoch
                ):
                    results[pos] = AttestationError(
                        "AggregatorAlreadyKnown",
                        aggregator_index=int(msg.aggregator_index),
                    )
                else:
                    results[pos] = VerifiedAggregatedAttestation(
                        sa, indexed, msg.aggregator_index
                    )
            else:
                results[pos] = AttestationError(
                    "InvalidSignature",
                    aggregator_index=int(sa.message.aggregator_index),
                )
    return results
