"""Validator monitor (reference:
``beacon_node/beacon_chain/src/validator_monitor.rs:112-165`` — tracks
registered validators' attestation inclusion/latency and block proposals,
exported through the metrics registry and an API summary).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..utils import metrics

_MONITORED = metrics.gauge(
    "validator_monitor_validators", "number of monitored validators"
)
_ATT_HITS = metrics.counter(
    "validator_monitor_attestation_in_block_total",
    "monitored validators' attestations observed in imported blocks",
)
_PROPOSALS = metrics.counter(
    "validator_monitor_block_proposals_total",
    "monitored validators' imported block proposals",
)
_DELAY = metrics.histogram(
    "validator_monitor_inclusion_delay_slots",
    "attestation inclusion delay for monitored validators",
    buckets=(1, 2, 3, 4, 8, 16, 32),
)


@dataclass
class ValidatorRecord:
    index: int
    attestations_included: int = 0
    blocks_proposed: int = 0
    last_attestation_slot: int | None = None
    last_inclusion_delay: int | None = None
    missed_epochs: set = field(default_factory=set)


class ValidatorMonitor:
    """Register indices (or ``auto`` to watch everyone) and feed imported
    blocks through ``process_block``; summaries come out of ``summary()``
    and the process metrics registry."""

    def __init__(self, auto: bool = False):
        self.auto = auto
        self._records: dict[int, ValidatorRecord] = {}
        self._lock = threading.Lock()

    def add_validator(self, index: int) -> None:
        with self._lock:
            self._records.setdefault(index, ValidatorRecord(index))
            _MONITORED.set(len(self._records))

    def _record(self, index: int) -> ValidatorRecord | None:
        rec = self._records.get(index)
        if rec is None and self.auto:
            rec = self._records[index] = ValidatorRecord(index)
            _MONITORED.set(len(self._records))
        return rec

    # -- feed -------------------------------------------------------------

    def process_block(self, chain, signed_block, state) -> None:
        """Called after import with the block's post-state: credits the
        proposer and every monitored attester in the block's attestations
        (the reference hooks the same import path)."""
        block = signed_block.message
        with self._lock:
            rec = self._record(block.proposer_index)
            if rec is not None:
                rec.blocks_proposed += 1
                _PROPOSALS.inc()
            from ..state_transition import get_attesting_indices

            seen: set = set()  # overlapping aggregates must not double-count
            for att in block.body.attestations:
                try:
                    indices = get_attesting_indices(
                        chain.preset, state, att.data, att.aggregation_bits
                    )
                except Exception:
                    continue
                delay = block.slot - att.data.slot
                for vi in indices:
                    key = (int(vi), att.data.slot, att.data.index)
                    if key in seen:
                        continue
                    seen.add(key)
                    rec = self._record(int(vi))
                    if rec is None:
                        continue
                    rec.attestations_included += 1
                    rec.last_attestation_slot = att.data.slot
                    rec.last_inclusion_delay = delay
                    _ATT_HITS.inc()
                    _DELAY.observe(delay)

    def note_missed_epoch(self, index: int, epoch: int) -> None:
        with self._lock:
            rec = self._records.get(index)
            if rec is not None:
                rec.missed_epochs.add(epoch)

    # -- read -------------------------------------------------------------

    def summary(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "index": r.index,
                    "attestations_included": r.attestations_included,
                    "blocks_proposed": r.blocks_proposed,
                    "last_attestation_slot": r.last_attestation_slot,
                    "last_inclusion_delay": r.last_inclusion_delay,
                    "missed_epochs": sorted(r.missed_epochs),
                }
                for r in self._records.values()
            ]
