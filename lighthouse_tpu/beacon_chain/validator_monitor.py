"""Validator monitor (reference:
``beacon_node/beacon_chain/src/validator_monitor.rs:112-165`` — tracks
registered validators' attestation inclusion/latency and block proposals,
exported through the metrics registry and an API summary).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..utils import flight_recorder, logging, metrics

_MONITORED = metrics.gauge(
    "validator_monitor_validators", "number of monitored validators"
)
_FAILURES = metrics.counter_vec(
    "validator_monitor_failures_total",
    "monitored validators' rejected gossip objects, by kind and reason "
    "(fed by the flight-recorder rejection events)",
    ("kind", "reason"),
)
# a rejection storm against a monitored validator is one page, not a
# log line per event (the journal + counter keep the full count)
_FAIL_LATCH = logging.TimeLatch(10.0)
_ATT_HITS = metrics.counter(
    "validator_monitor_attestation_in_block_total",
    "monitored validators' attestations observed in imported blocks",
)
_PROPOSALS = metrics.counter(
    "validator_monitor_block_proposals_total",
    "monitored validators' imported block proposals",
)
_DELAY = metrics.histogram(
    "validator_monitor_inclusion_delay_slots",
    "attestation inclusion delay for monitored validators",
    buckets=(1, 2, 3, 4, 8, 16, 32),
)


@dataclass
class ValidatorRecord:
    index: int
    attestations_included: int = 0
    blocks_proposed: int = 0
    attestations_failed: int = 0
    blocks_failed: int = 0
    last_failure_reason: str | None = None
    last_attestation_slot: int | None = None
    last_inclusion_delay: int | None = None
    missed_epochs: set = field(default_factory=set)


class ValidatorMonitor:
    """Register indices (or ``auto`` to watch everyone) and feed imported
    blocks through ``process_block``; summaries come out of ``summary()``
    and the process metrics registry."""

    def __init__(self, auto: bool = False):
        self.auto = auto
        self._records: dict[int, ValidatorRecord] = {}
        self._lock = threading.Lock()
        self._attached = False

    # -- flight-recorder wiring -------------------------------------------

    def attach(self) -> "ValidatorMonitor":
        """Subscribe to the flight-recorder journal: ``attestation_rejected``
        and ``block_rejected`` events for monitored validators become
        ``validator_monitor_failures_total{kind, reason}`` ticks, per-record
        failure counts, and a warn log — a monitored validator failing to
        land work is an operator page, not just an anonymous counter."""
        if not self._attached:
            flight_recorder.subscribe(self._on_event)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            flight_recorder.unsubscribe(self._on_event)
            self._attached = False

    def _on_event(self, ev: dict) -> None:
        kind = ev.get("kind")
        fields = ev.get("fields") or {}
        if kind == "attestation_rejected":
            index = fields.get("validator_index", fields.get("aggregator_index"))
            failure = "attestation"
        elif kind == "block_rejected":
            index = fields.get("proposer_index")
            failure = "block"
        else:
            return
        if index is None:
            return  # rejection happened before an index was known
        reason = fields.get("reason", "unknown")
        with self._lock:
            # observe-only, even in auto mode: a rejection can carry an
            # ATTACKER-SUPPLIED index (e.g. a bogus proposer_index on a
            # junk gossip block) — only indices already registered (or
            # auto-registered from VALIDATED imports) may grow state
            rec = self._records.get(int(index))
            if rec is None:
                return  # not monitored
            if failure == "attestation":
                rec.attestations_failed += 1
            else:
                rec.blocks_failed += 1
            rec.last_failure_reason = reason
        _FAILURES.with_labels(failure, reason).inc()
        logging.rate_limited(
            _FAIL_LATCH, "warn", f"monitored validator {failure} rejected",
            validator_index=int(index), reason=reason,
            slot=fields.get("slot"),
        )

    def add_validator(self, index: int) -> None:
        with self._lock:
            self._records.setdefault(index, ValidatorRecord(index))
            _MONITORED.set(len(self._records))

    def _record(self, index: int) -> ValidatorRecord | None:
        rec = self._records.get(index)
        if rec is None and self.auto:
            rec = self._records[index] = ValidatorRecord(index)
            _MONITORED.set(len(self._records))
        return rec

    # -- feed -------------------------------------------------------------

    def process_block(self, chain, signed_block, state) -> None:
        """Called after import with the block's post-state: credits the
        proposer and every monitored attester in the block's attestations
        (the reference hooks the same import path)."""
        block = signed_block.message
        with self._lock:
            rec = self._record(block.proposer_index)
            if rec is not None:
                rec.blocks_proposed += 1
                _PROPOSALS.inc()
            from ..state_transition import get_attesting_indices

            seen: set = set()  # overlapping aggregates must not double-count
            for att in block.body.attestations:
                try:
                    indices = get_attesting_indices(
                        chain.preset, state, att.data, att.aggregation_bits
                    )
                except Exception:
                    continue
                delay = block.slot - att.data.slot
                for vi in indices:
                    key = (int(vi), att.data.slot, att.data.index)
                    if key in seen:
                        continue
                    seen.add(key)
                    rec = self._record(int(vi))
                    if rec is None:
                        continue
                    rec.attestations_included += 1
                    rec.last_attestation_slot = att.data.slot
                    rec.last_inclusion_delay = delay
                    _ATT_HITS.inc()
                    _DELAY.observe(delay)

    def note_missed_epoch(self, index: int, epoch: int) -> None:
        with self._lock:
            rec = self._records.get(index)
            if rec is not None:
                rec.missed_epochs.add(epoch)

    # -- read -------------------------------------------------------------

    def summary(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "index": r.index,
                    "attestations_included": r.attestations_included,
                    "blocks_proposed": r.blocks_proposed,
                    "attestations_failed": r.attestations_failed,
                    "blocks_failed": r.blocks_failed,
                    "last_failure_reason": r.last_failure_reason,
                    "last_attestation_slot": r.last_attestation_slot,
                    "last_inclusion_delay": r.last_inclusion_delay,
                    "missed_epochs": sorted(r.missed_epochs),
                }
                for r in self._records.values()
            ]
