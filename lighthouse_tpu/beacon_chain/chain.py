"""The BeaconChain runtime (reference:
``beacon_node/beacon_chain/src/beacon_chain.rs`` — the god object wiring
store, fork choice, caches, and verification pipelines; ``process_block``
at :2495, ``produce_block_on_state`` :3364, ``process_chain_segment``
:2340, head recompute ``canonical_head.rs:449``).

This is the consumer that feeds the TPU BLS backend its real workload:
block imports batch every block signature through
``SignatureVerifiedBlock``; gossip attestations batch through
``attestation_verification``.
"""

from __future__ import annotations

import copy
import threading
from collections import OrderedDict

from ..fork_choice.fork_choice import ForkChoice
from ..fork_choice.proto_array import ExecutionStatus
from ..ssz import hash_tree_root
from ..ssz.cache import CachedRootComputer
from ..state_transition import (
    CommitteeCache,
    get_indexed_attestation,
    partial_state_advance,
    process_block as st_process_block,
    get_beacon_proposer_index,
)
from ..state_transition.epoch import fork_of
from ..state_transition.helpers import compute_epoch_at_slot
from ..utils import metrics
from ..utils.slot_clock import SlotClock
from .attestation_verification import (
    batch_verify_aggregated_attestations,
    batch_verify_unaggregated_attestations,
    verify_aggregated_attestation,
    verify_unaggregated_attestation,
)
from .block_verification import (
    BlockError,
    ExecutionPendingBlock,
    GossipVerifiedBlock,
    SignatureVerifiedBlock,
)
from .observed import (
    ObservedAggregates,
    ObservedAggregators,
    ObservedAttesters,
    ObservedBlockProducers,
    ObservedOperations,
)
from .pubkey_cache import ValidatorPubkeyCache

_BLOCK_PROCESSING = metrics.histogram(
    "block_processing_seconds", "full block import wall time"
)
_HEAD_RECOMPUTE = metrics.counter("head_recompute_total", "get_head invocations")
_BLOCK_OBSERVED_TO_HEAD = metrics.histogram(
    "beacon_block_observed_to_head_seconds",
    "gossip-observed to set-as-head delay (block_times_cache)",
)


class SnapshotCache:
    """Post-states of recent blocks by block root (reference
    ``snapshot_cache.rs``, DEFAULT_SNAPSHOT_CACHE_SIZE=4)."""

    def __init__(self, cap: int = 4):
        self.cap = cap
        self._map: OrderedDict[bytes, object] = OrderedDict()
        # get() is a mutating read (move_to_end) on a plain OrderedDict and
        # is reached from HTTP/timer threads outside the chain lock.
        self._lock = threading.Lock()

    def insert(self, block_root: bytes, state) -> None:
        with self._lock:
            self._map[block_root] = state
            self._map.move_to_end(block_root)
            while len(self._map) > self.cap:
                self._map.popitem(last=False)

    def get(self, block_root: bytes):
        with self._lock:
            state = self._map.get(block_root)
            if state is not None:
                self._map.move_to_end(block_root)
            return state


class ShufflingCache:
    """Committee caches keyed by (epoch, target root) (reference
    ``shuffling_cache.rs``)."""

    def __init__(self, cap: int = 16):
        self.cap = cap
        self._map: OrderedDict[tuple, CommitteeCache] = OrderedDict()
        # Double-checked locking: the lock guards only dict access; a
        # cold-miss committee build (deepcopy + epoch of state advance,
        # potentially seconds) runs UNLOCKED so an HTTP duties request
        # can never stall a worker that holds the chain lock and blocks
        # here. The price is an occasional duplicate build.
        self._lock = threading.Lock()

    def get(self, chain, epoch: int, target_root: bytes) -> CommitteeCache:
        key = (epoch, bytes(target_root))
        with self._lock:
            hit = self._map.get(key)
            if hit is not None:
                self._map.move_to_end(key)
                return hit
        # The shuffling must come from a state on the TARGET's chain — the
        # head may be on a competing fork with a different RANDAO seed.
        # Advance the target block's post-state to the epoch if needed.
        try:
            state = chain.state_at_block_root(bytes(target_root))
        except Exception:
            state = chain.head_state  # pre-genesis targets / missing state
        target_epoch_slot = epoch * chain.preset.SLOTS_PER_EPOCH
        if state.slot < target_epoch_slot:
            state = partial_state_advance(
                chain.preset, chain.spec, copy.deepcopy(state),
                target_epoch_slot,
            )
        cache = CommitteeCache(chain.preset, state, epoch)
        with self._lock:
            existing = self._map.get(key)
            if existing is not None:
                return existing
            self._map[key] = cache
            while len(self._map) > self.cap:
                self._map.popitem(last=False)
        return cache


class BeaconChain:
    def __init__(self, preset, spec, types, store, genesis_state, slot_clock=None):
        self.preset = preset
        self.spec = spec
        self.types = types
        self.store = store
        self.slot_clock = slot_clock or SlotClock(
            genesis_state.genesis_time, spec.seconds_per_slot
        )

        self.genesis_state_root = hash_tree_root(genesis_state)
        genesis_block_root = _anchor_block_root(genesis_state)
        self.genesis_block_root = genesis_block_root

        self.fork_choice = ForkChoice(
            preset,
            spec,
            genesis_state.slot,
            genesis_block_root,
            (
                genesis_state.current_justified_checkpoint.epoch,
                genesis_block_root,
            ),
            (genesis_state.finalized_checkpoint.epoch, genesis_block_root),
            [v.effective_balance for v in genesis_state.validators],
        )

        self.pubkey_cache = ValidatorPubkeyCache(store)
        self.pubkey_cache.import_new_pubkeys(genesis_state)
        self.observed_attesters = ObservedAttesters()
        self.observed_aggregators = ObservedAggregators()
        self.observed_aggregates = ObservedAggregates()
        self.observed_block_producers = ObservedBlockProducers()
        self.observed_operations = ObservedOperations()
        self.snapshot_cache = SnapshotCache()
        self.shuffling_cache = ShufflingCache()
        self.root_computer = CachedRootComputer()
        from .caches import (
            AttesterCache,
            BeaconProposerCache,
            BlockTimesCache,
            EarlyAttesterCache,
        )

        self.early_attester_cache = EarlyAttesterCache()
        self.beacon_proposer_cache = BeaconProposerCache()
        self.attester_cache = AttesterCache()
        self.block_times_cache = BlockTimesCache()
        # state pre-advanced to the next slot by the state-advance timer:
        # (head_block_root, state) — see advance_head_state_to()
        self._advanced: tuple[bytes, object] | None = None
        self.op_pool = None  # attached by the client builder when present
        self.slasher = None  # attached by the client builder when enabled
        self.validator_monitor = None  # attached when monitoring is on
        # cross-caller batching scheduler (verification_service/batcher.py),
        # attached by the client builder; None = direct backend calls
        self.verification_scheduler = None

        # (root, state) swapped as ONE tuple so unlocked readers (HTTP
        # routes, duty production) always see a consistent pair; exposed
        # via the head_block_root / head_state properties.
        self._head = (genesis_block_root, genesis_state)
        self._last_finalized_epoch = genesis_state.finalized_checkpoint.epoch
        # Serializes every chain-mutating path (block import, attestation
        # verification bookkeeping, head recompute). The BeaconProcessor
        # runs multiple worker threads plus the slot-timer and HTTP
        # threads; the fork-choice proto-array, observed_* caches, and
        # snapshot/shuffling caches are plain dicts with no internal
        # locking — the reference guards the equivalents with RwLocks
        # (canonical_head.rs). Reentrant: process_chain_segment →
        # _import_block → recompute_head all take it.
        self._chain_lock = threading.RLock()

        # Materialize the anchor block implied by the state's header (an
        # interop/spec genesis has an empty body); lets block_id lookups
        # resolve "head" from slot 0. A checkpoint-sync anchor whose real
        # body is unknown simply skips this (root would not match).
        fork = fork_of(genesis_state)
        header = genesis_state.latest_block_header
        anchor = types.block[fork](
            slot=header.slot,
            proposer_index=header.proposer_index,
            parent_root=bytes(header.parent_root),
            state_root=(
                self.genesis_state_root
                if bytes(header.state_root) == bytes(32)
                else bytes(header.state_root)
            ),
            body=types.block_body[fork](),
        )
        if hash_tree_root(anchor) == genesis_block_root:
            store.put_block(
                genesis_block_root, types.signed_block[fork](message=anchor)
            )
        self.snapshot_cache.insert(genesis_block_root, genesis_state)
        store.put_state_snapshot(self.genesis_state_root, genesis_state)
        # The anchor may be a resumed HEAD, not genesis: never clobber an
        # existing genesis-root record.
        if store.get_genesis_state_root() is None:
            store.put_genesis_state_root(self.genesis_state_root)
        store.put_head(genesis_block_root)

    # -- clock / lookup ---------------------------------------------------

    @property
    def head_block_root(self) -> bytes:
        return self._head[0]

    @property
    def head_state(self):
        return self._head[1]

    def head_info(self):
        """Consistent (head_block_root, head_state) pair for readers."""
        return self._head

    def set_head(self, root: bytes, state) -> None:
        """Atomic head replacement (fork_revert, checkpoint resume)."""
        with self._chain_lock:
            self._head = (root, state)

    def slot(self) -> int:
        return self.slot_clock.now()

    def epoch(self) -> int:
        return compute_epoch_at_slot(self.preset, self.slot())

    def state_at_block_root(self, block_root: bytes):
        """Post-state of a block: snapshot cache, then store."""
        state = self.snapshot_cache.get(block_root)
        if state is not None:
            return state
        block = self.store.get_block(block_root)
        if block is None:
            raise BlockError("ParentUnknown", block_root.hex()[:12])
        state = self.store.get_state(bytes(block.message.state_root))
        if state is None:
            raise BlockError("MissingParentState", block_root.hex()[:12])
        return state

    def pubkey_resolver_by_bytes(self):
        cache = self.pubkey_cache

        def _resolve(raw: bytes):
            idx = cache.get_index(bytes(raw))
            return cache.get(idx) if idx is not None else None

        return _resolve

    # -- block pipeline ---------------------------------------------------

    def verify_block_for_gossip(self, signed_block) -> GossipVerifiedBlock:
        # Mutates observed_block_producers and reads fork-choice/snapshot
        # state; gossip blocks arrive ~1/slot, so holding the lock across
        # the single proposal-signature check costs nothing.
        with self._chain_lock:
            gossip = GossipVerifiedBlock.new(self, signed_block)
        self.block_times_cache.set_observed(gossip.block_root)
        return gossip

    def process_block(self, block, execution_status=ExecutionStatus.IRRELEVANT):
        """Import a block through the full pipeline. Accepts a raw
        SignedBeaconBlock, a GossipVerifiedBlock, or a
        SignatureVerifiedBlock; returns the block root."""
        with self._chain_lock, _BLOCK_PROCESSING.time():
            if isinstance(block, GossipVerifiedBlock):
                sv = SignatureVerifiedBlock.from_gossip(block, self)
            elif isinstance(block, SignatureVerifiedBlock):
                sv = block
            else:
                sv = SignatureVerifiedBlock.new(self, block)
            return self._import_block(sv, execution_status)

    def _import_block(self, sv: SignatureVerifiedBlock, execution_status):
        signed_block = sv.signed_block
        block = signed_block.message
        state = sv.state  # advanced to block.slot, pre-block

        st_process_block(
            self.preset, self.spec, state, signed_block, fork_of(state),
            signature_strategy="none",
        )
        post_root = self.root_computer.hash_tree_root(state)
        if post_root != bytes(block.state_root):
            raise BlockError(
                "StateRootMismatch",
                f"{post_root.hex()[:12]} != {bytes(block.state_root).hex()[:12]}",
            )

        # fork choice: block + its attestations + slashings
        self.fork_choice.on_block(
            self.slot(), block, sv.block_root, state, execution_status
        )
        for att in block.body.attestations:
            try:
                indexed = get_indexed_attestation(self.preset, state, att)
                self.fork_choice.on_attestation(
                    self.slot(), indexed, is_from_block=True
                )
            except Exception:
                pass  # fork-choice-irrelevant (e.g. old target); state transition accepted it
        for slashing in block.body.attester_slashings:
            self.fork_choice.on_attester_slashing(
                slashing.attestation_1, slashing.attestation_2
            )

        self.pubkey_cache.import_new_pubkeys(state)
        if self.validator_monitor is not None:
            self.validator_monitor.process_block(self, signed_block, state)
        self.store.put_block(sv.block_root, signed_block)
        self.store.put_state(post_root, state)
        self.snapshot_cache.insert(sv.block_root, state)

        # early-attester template: attesting to THIS block this epoch
        # needs no state access (reference beacon_chain.rs:1496-1512)
        epoch = compute_epoch_at_slot(self.preset, block.slot)
        epoch_start = epoch * self.preset.SLOTS_PER_EPOCH
        target_root = (
            sv.block_root
            if block.slot == epoch_start
            else bytes(
                state.block_roots[epoch_start % self.preset.SLOTS_PER_HISTORICAL_ROOT]
            )
        )
        self.early_attester_cache.add(
            epoch,
            sv.block_root,
            (
                state.current_justified_checkpoint.epoch,
                bytes(state.current_justified_checkpoint.root),
            ),
            target_root,
        )
        self.block_times_cache.set_imported(sv.block_root)

        self.recompute_head()
        return sv.block_root

    def process_chain_segment(self, blocks) -> list[bytes]:
        """Sync-time import: EVERY signature of the whole segment verified
        in ONE backend batch before any block is imported (reference
        ``process_chain_segment`` ``beacon_chain.rs:2340`` +
        ``signature_verify_chain_segment`` ``block_verification.rs:525``
        — the widest batch the device sees)."""
        blocks = list(blocks)
        if not blocks:
            return []
        # The segment-wide BLS batch (the slowest single operation in the
        # system) runs on deep-copied state OUTSIDE the chain lock; only
        # the per-block imports lock, so gossip verification and ticks can
        # interleave with a long sync segment.
        verified = self.signature_verify_chain_segment(blocks)
        out = []
        for sv in verified:
            with self._chain_lock:
                out.append(self._import_block(sv, ExecutionStatus.IRRELEVANT))
        return out

    def signature_verify_chain_segment(self, blocks) -> list[SignatureVerifiedBlock]:
        """Accumulate signature sets across all blocks of a contiguous
        segment, verify once, and return per-block SignatureVerifiedBlock
        evidence (with each block's advanced pre-state)."""
        from ..crypto import bls
        from ..ssz import hash_tree_root as htr
        from ..state_transition import BlockSignatureAccumulator

        parent_root = bytes(blocks[0].message.parent_root)
        state = copy.deepcopy(self.state_at_block_root(parent_root))
        all_sets = []
        out = []
        for sb in blocks:
            state = partial_state_advance(
                self.preset, self.spec, state, sb.message.slot
            )
            block_root = htr(sb.message)
            acc = BlockSignatureAccumulator(
                self.preset, self.spec, state, self.pubkey_cache.resolver(),
                resolver_by_pubkey_bytes=self.pubkey_resolver_by_bytes(),
            )
            acc.include_all(sb, block_root=block_root)
            all_sets.extend(acc.sets)
            out.append(
                SignatureVerifiedBlock(sb, block_root, copy.deepcopy(state))
            )
            # apply so the next block's sets build on the right state
            st_process_block(
                self.preset, self.spec, state, sb, fork_of(state),
                signature_strategy="none",
            )
        # segment import is deadline-INSENSITIVE (the syncing caller is
        # self-paced on the whole range): the bulk QoS class (ISSUE 15),
        # which flushes at gossip idle onto the big warm rungs under
        # admission control — a saturating range sync can no longer ride
        # the synchronous verify_now bypass head-on against gossip's
        # deadline class. Gossip block import keeps the bypass
        # (block_verification.py): a proposal on the wire IS
        # latency-critical.
        from ..verification_service import backend_verify_bulk

        if not backend_verify_bulk(self, all_sets, kind="chain_segment"):
            raise BlockError("InvalidSignature", "chain segment batch")
        return out

    # -- attestation pipeline ---------------------------------------------

    # The verify functions take the chain lock internally at the right
    # granularity (setup + commit locked, the BLS call unlocked) so the
    # heavy signature work of concurrent workers is not serialized.

    def verify_unaggregated_attestation_for_gossip(self, att):
        return verify_unaggregated_attestation(self, att, self.slot())

    def batch_verify_unaggregated_attestations_for_gossip(self, atts):
        return batch_verify_unaggregated_attestations(self, atts, self.slot())

    def verify_aggregated_attestation_for_gossip(self, signed_agg):
        return verify_aggregated_attestation(self, signed_agg, self.slot())

    def batch_verify_aggregated_attestations_for_gossip(self, signed_aggs):
        return batch_verify_aggregated_attestations(
            self, signed_aggs, self.slot()
        )

    def apply_attestation_to_fork_choice(self, verified) -> None:
        with self._chain_lock:
            self.fork_choice.on_attestation(self.slot(), verified.indexed)

    def on_tick(self, slot: int) -> None:
        """Slot-timer entry: advance fork choice's clock and re-evaluate
        the head, all under the chain lock (the timer runs on its own
        thread)."""
        with self._chain_lock:
            self.fork_choice.on_tick(slot)
            self._recompute_head_locked()

    def on_attester_slashing(self, slashing) -> None:
        """Record an attester slashing's equivocation evidence in fork
        choice (HTTP-pool and gossip paths; locked — mutates proto-array
        state)."""
        with self._chain_lock:
            self.fork_choice.on_attester_slashing(
                slashing.attestation_1, slashing.attestation_2
            )

    # -- head / finalization ----------------------------------------------

    def recompute_head(self) -> bytes:
        with self._chain_lock:
            return self._recompute_head_locked()

    def _recompute_head_locked(self) -> bytes:
        _HEAD_RECOMPUTE.inc()
        head_root = self.fork_choice.get_head()
        if head_root != self.head_block_root:
            state = self.snapshot_cache.get(head_root)
            if state is None:
                head_block = self.store.get_block(head_root)
                state = self.store.get_state(bytes(head_block.message.state_root))
            self._head = (head_root, state)  # atomic pair swap
            self.store.put_head(head_root)
            self.block_times_cache.set_became_head(head_root)
            # the pre-advanced state belongs to the previous head; entries
            # are keyed by root so a stale one is merely unused, but drop
            # it so the timer re-advances for the new head promptly
            if self._advanced is not None and self._advanced[0] != head_root:
                self._advanced = None
            delays = self.block_times_cache.delays(head_root)
            if "observed_to_head" in delays:
                _BLOCK_OBSERVED_TO_HEAD.observe(delays["observed_to_head"])
        # Finalization is advanced by fork_choice.on_block, so compare
        # against the chain's own last-seen epoch, not a before/after of
        # the fork-choice store within this call.
        new_finalized = self.fork_choice.store.finalized_checkpoint
        if new_finalized[0] > self._last_finalized_epoch:
            self._last_finalized_epoch = new_finalized[0]
            self._on_finalization(new_finalized)
        return head_root

    def _on_finalization(self, finalized_checkpoint) -> None:
        """Prune memory caches + migrate the store split (reference
        ``migrate.rs`` + per-cache prune calls)."""
        epoch, root = finalized_checkpoint
        fin_slot = epoch * self.preset.SLOTS_PER_EPOCH
        self.observed_attesters.prune(epoch)
        self.observed_aggregators.prune(epoch)
        self.observed_aggregates.prune(fin_slot)
        self.observed_block_producers.prune(fin_slot)
        obs_sync = getattr(self, "observed_sync_items", None)
        if obs_sync is not None:
            obs_sync.prune(fin_slot)
        self.fork_choice.prune()
        block = self.store.get_block(root)
        if block is not None:
            state = self.store.get_state(bytes(block.message.state_root))
            if state is not None:
                self.store.migrate(bytes(block.message.state_root), state)
        # Persist fork choice now, not only at shutdown: the store's HEAD
        # advances on every recompute_head, so a crash between shutdowns
        # would otherwise restore an old DAG that lacks the persisted head
        # and stall on ParentUnknown (reference persists on finalization
        # too, ``beacon_chain.rs:400-440``). Already under the chain RLock.
        try:
            from ..store.kv import Column

            self.store.put_blob(
                Column.FORK_CHOICE, b"fork_choice", self.fork_choice_bytes()
            )
        except Exception:
            pass  # persistence must never break finalization handling

    # -- production --------------------------------------------------------

    def produce_block_on_state(self, slot: int, randao_reveal: bytes, graffiti: bytes = bytes(32)):
        """Unsigned block proposal on the canonical head (reference
        ``produce_block_on_state`` ``beacon_chain.rs:3364``); op-pool
        selection when a pool is attached."""
        state = copy.deepcopy(self.head_state)
        state = partial_state_advance(self.preset, self.spec, state, slot)
        proposer = get_beacon_proposer_index(self.preset, state)
        fork = fork_of(state)
        t = self.types

        body_kwargs = dict(
            randao_reveal=randao_reveal,
            eth1_data=state.eth1_data,
            graffiti=graffiti,
        )
        if self.op_pool is not None:
            packing = self.op_pool.packing_for_block(self, state)
            body_kwargs.update(packing)
        if fork in ("altair", "bellatrix") and "sync_aggregate" not in body_kwargs:
            from ..crypto.bls import INFINITY_SIGNATURE

            agg = None
            if self.op_pool is not None and slot >= 1:
                agg = self.op_pool.sync_aggregate_for_block(
                    slot - 1, self.head_block_root
                )
            body_kwargs["sync_aggregate"] = agg or t.SyncAggregate(
                sync_committee_signature=INFINITY_SIGNATURE
            )
        body = t.block_body[fork](**body_kwargs)
        block = t.block[fork](
            slot=slot,
            proposer_index=proposer,
            parent_root=self.head_block_root,
            state_root=bytes(32),
            body=body,
        )
        trial = copy.deepcopy(state)
        st_process_block(
            self.preset, self.spec, trial,
            t.signed_block[fork](message=block), fork, signature_strategy="none",
        )
        block.state_root = hash_tree_root(trial)
        return block, proposer

    def produce_unaggregated_attestation(self, slot: int, committee_index: int):
        """AttestationData for a duty (reference
        ``produce_unaggregated_attestation`` ``beacon_chain.rs:1496``).

        Fast paths, in order: the early-attester template (filled at
        block import — zero state access), then the attester cache
        (cross-epoch FFG info), then the state-advance-timer's
        pre-advanced state, then a fresh copy+advance (which refills the
        attester cache)."""
        t = self.types
        epoch = compute_epoch_at_slot(self.preset, slot)
        head_root, head_state = self.head_info()  # consistent pair

        item = self.early_attester_cache.try_attest(epoch, head_root)
        if item is not None:
            return t.AttestationData(
                slot=slot,
                index=committee_index,
                beacon_block_root=item.beacon_block_root,
                source=t.Checkpoint(epoch=item.source[0], root=item.source[1]),
                target=t.Checkpoint(epoch=epoch, root=item.target_root),
            )

        state = head_state
        if compute_epoch_at_slot(self.preset, state.slot) < epoch:
            # epoch boundary between head and duty slot: the justified
            # checkpoint changes at the boundary
            info = self.attester_cache.get(epoch, head_root)
            if info is not None:
                return t.AttestationData(
                    slot=slot,
                    index=committee_index,
                    beacon_block_root=head_root,
                    source=t.Checkpoint(epoch=info.source[0], root=info.source[1]),
                    target=t.Checkpoint(epoch=epoch, root=info.target_root),
                )
            advanced = self._advanced
            if (
                advanced is not None
                and advanced[0] == head_root
                and compute_epoch_at_slot(self.preset, advanced[1].slot) >= epoch
            ):
                state = advanced[1]  # read-only use
            else:
                state = partial_state_advance(
                    self.preset, self.spec, copy.deepcopy(state), slot
                )
        target_slot = epoch * self.preset.SLOTS_PER_EPOCH
        if state.slot > target_slot:
            hist = state.block_roots[
                target_slot % self.preset.SLOTS_PER_HISTORICAL_ROOT
            ]
            target_root = bytes(hist)
        else:
            target_root = head_root
        from .caches import AttesterDutyInfo

        self.attester_cache.insert(
            epoch,
            head_root,
            AttesterDutyInfo(
                source=(
                    state.current_justified_checkpoint.epoch,
                    bytes(state.current_justified_checkpoint.root),
                ),
                target_root=target_root,
            ),
        )
        return t.AttestationData(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint,
            target=t.Checkpoint(epoch=epoch, root=target_root),
        )

    def fork_choice_bytes(self) -> bytes:
        """Serialize fork choice under the chain lock — concurrent
        on_block/on_attestation mutation otherwise tears the snapshot
        (found by tests/test_concurrency_stress.py: 'dictionary changed
        size during iteration')."""
        from ..fork_choice.persistence import fork_choice_to_bytes

        with self._chain_lock:
            return fork_choice_to_bytes(self.fork_choice)

    def advance_head_state_to(self, slot: int) -> bool:
        """State-advance timer body (reference
        ``state_advance_timer.rs:93-231``): near the end of a slot,
        pre-advance a COPY of the head state to the next slot so block
        verification and attestation production at the slot boundary skip
        the per-slot (and at boundaries, per-epoch) processing spike.
        Returns True when an advance was performed. The copy + advance run
        OUTSIDE the chain lock (the whole point is not to stall gossip and
        import during the boundary spike); the result is published only if
        the head did not move meanwhile."""
        head_root, head_state = self.head_info()
        advanced = self._advanced
        if advanced is not None and advanced[0] == head_root and (
            advanced[1].slot >= slot
        ):
            return False
        if head_state.slot >= slot:
            return False
        state = partial_state_advance(
            self.preset, self.spec, copy.deepcopy(head_state), slot
        )
        with self._chain_lock:
            if self._head[0] != head_root:
                return False  # advanced a stale head: discard
            self._advanced = (head_root, state)
        return True

    def advanced_state_for(self, parent_root: bytes, slot: int):
        """The pre-advanced state when it matches (root, <=slot); None
        otherwise. Callers must deepcopy before mutating."""
        advanced = self._advanced
        if (
            advanced is not None
            and advanced[0] == parent_root
            and advanced[1].slot <= slot
        ):
            return advanced[1]
        return None

    def proposers_for_epoch(self, epoch: int) -> list[int]:
        """Proposer index for every slot of ``epoch``, cached on
        (epoch, head root) (reference ``beacon_proposer_cache.rs``)."""
        head_root, head_state = self.head_info()  # consistent pair
        cached = self.beacon_proposer_cache.get(epoch, head_root)
        if cached is not None:
            return cached
        from ..state_transition.helpers import proposer_index_at_slot

        P = self.preset
        start = epoch * P.SLOTS_PER_EPOCH
        state = head_state
        if state.slot < start:
            state = self.advanced_state_for(head_root, start)
            if state is None or compute_epoch_at_slot(P, state.slot) < epoch:
                state = partial_state_advance(
                    P, self.spec, copy.deepcopy(head_state), start
                )
        proposers = [
            proposer_index_at_slot(P, state, s)
            for s in range(start, start + P.SLOTS_PER_EPOCH)
        ]
        self.beacon_proposer_cache.insert(epoch, head_root, proposers)
        return proposers


def _anchor_block_root(state) -> bytes:
    """Root of the anchor (genesis) block implied by a state whose
    latest_block_header.state_root may be unfilled."""
    from ..state_transition.helpers import latest_block_header_root

    return latest_block_header_root(state)
