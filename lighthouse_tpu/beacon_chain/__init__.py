"""L4 core runtime: the BeaconChain and its verification pipelines.

Reference: ``beacon_node/beacon_chain`` (SURVEY.md §2.4).
"""

from .attestation_verification import (
    AttestationError,
    VerifiedAggregatedAttestation,
    VerifiedUnaggregatedAttestation,
    batch_verify_aggregated_attestations,
    batch_verify_unaggregated_attestations,
)
from .block_verification import (
    BlockError,
    ExecutionPendingBlock,
    GossipVerifiedBlock,
    SignatureVerifiedBlock,
)
from .chain import BeaconChain, ShufflingCache, SnapshotCache
from .fork_revert import revert_to_fork_boundary
from .observed import (
    ObservedAggregates,
    ObservedAggregators,
    ObservedAttesters,
    ObservedBlockProducers,
    ObservedOperations,
)
from .pubkey_cache import ValidatorPubkeyCache
from .sync_committee_verification import (
    batch_verify_sync_committee_messages,
    SyncCommitteeError,
    VerifiedSyncCommitteeMessage,
    VerifiedSyncContribution,
    verify_sync_committee_message,
    verify_sync_contribution,
)
from .validator_monitor import ValidatorMonitor

__all__ = [
    "AttestationError",
    "BeaconChain",
    "BlockError",
    "revert_to_fork_boundary",
    "ExecutionPendingBlock",
    "GossipVerifiedBlock",
    "ObservedAggregates",
    "ObservedAggregators",
    "ObservedAttesters",
    "ObservedBlockProducers",
    "ObservedOperations",
    "ShufflingCache",
    "SignatureVerifiedBlock",
    "SnapshotCache",
    "SyncCommitteeError",
    "batch_verify_sync_committee_messages",
    "VerifiedSyncCommitteeMessage",
    "VerifiedSyncContribution",
    "verify_sync_committee_message",
    "verify_sync_contribution",
    "ValidatorMonitor",
    "ValidatorPubkeyCache",
    "VerifiedAggregatedAttestation",
    "VerifiedUnaggregatedAttestation",
    "ValidatorPubkeyCache",
]
