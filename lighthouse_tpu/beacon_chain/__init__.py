"""L4 core runtime: the BeaconChain and its verification pipelines.

Reference: ``beacon_node/beacon_chain`` (SURVEY.md §2.4).
"""

from .attestation_verification import (
    AttestationError,
    VerifiedAggregatedAttestation,
    VerifiedUnaggregatedAttestation,
    batch_verify_aggregated_attestations,
    batch_verify_unaggregated_attestations,
)
from .block_verification import (
    BlockError,
    ExecutionPendingBlock,
    GossipVerifiedBlock,
    SignatureVerifiedBlock,
)
from .chain import BeaconChain, ShufflingCache, SnapshotCache
from .fork_revert import revert_to_fork_boundary
from .observed import (
    ObservedAggregates,
    ObservedAggregators,
    ObservedAttesters,
    ObservedBlockProducers,
    ObservedOperations,
)
from .pubkey_cache import ValidatorPubkeyCache
from .validator_monitor import ValidatorMonitor

__all__ = [
    "AttestationError",
    "BeaconChain",
    "BlockError",
    "revert_to_fork_boundary",
    "ExecutionPendingBlock",
    "GossipVerifiedBlock",
    "ObservedAggregates",
    "ObservedAggregators",
    "ObservedAttesters",
    "ObservedBlockProducers",
    "ObservedOperations",
    "ShufflingCache",
    "SignatureVerifiedBlock",
    "SnapshotCache",
    "ValidatorMonitor",
    "ValidatorPubkeyCache",
    "VerifiedAggregatedAttestation",
    "VerifiedUnaggregatedAttestation",
    "ValidatorPubkeyCache",
]
