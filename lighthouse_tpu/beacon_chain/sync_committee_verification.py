"""Gossip verification of sync-committee messages and contributions
(reference: ``beacon_chain/src/sync_committee_verification.rs`` —
``verify_sync_committee_message`` :561 and ``verify_sync_signed_
contribution_and_proof`` :252-267).

Both verifiers follow the attestation pipeline's shape: structural checks
and dedup bookkeeping under the chain lock, the BLS batch as one
``verify_signature_sets`` call (a contribution costs three sets, exactly
like an aggregate attestation — selection proof, aggregator signature,
aggregated message signature).
"""

from __future__ import annotations

import hashlib
import threading

from ..crypto import bls
from ..ssz import hash_tree_root
from ..types.chain_spec import (
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
)
from ..types.domains import compute_signing_root, get_domain
from ..utils import flight_recorder
from ..verification_service import backend_verify, backend_verify_each

TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16


class SyncCommitteeError(ValueError):
    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


class VerifiedSyncCommitteeMessage:
    __slots__ = ("message", "positions")

    def __init__(self, message, positions):
        self.message = message
        self.positions = positions  # positions within the FULL committee


class VerifiedSyncContribution:
    __slots__ = ("signed", "participant_indices")

    def __init__(self, signed, participant_indices):
        self.signed = signed
        self.participant_indices = participant_indices


class ObservedSyncItems:
    """Dedup caches for sync gossip, pruned by slot (reference
    ``observed_attesters``-style seen-caches for sync messages)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._messages: set[tuple] = set()        # (slot, validator_index)
        self._aggregators: set[tuple] = set()     # (slot, subcommittee, vi)
        self._contributions: set[tuple] = set()   # (slot, root, subc, bits)

    def observe(self, table: str, key: tuple) -> bool:
        with self._lock:
            s = getattr(self, f"_{table}")
            if key in s:
                return True
            s.add(key)
            return False

    def is_known(self, table: str, key: tuple) -> bool:
        with self._lock:
            return key in getattr(self, f"_{table}")

    def prune(self, min_slot: int) -> None:
        with self._lock:
            for name in ("_messages", "_aggregators", "_contributions"):
                s = getattr(self, name)
                setattr(self, name, {k for k in s if k[0] >= min_slot})


def _observed(chain) -> ObservedSyncItems:
    obs = getattr(chain, "observed_sync_items", None)
    if obs is None:
        obs = chain.observed_sync_items = ObservedSyncItems()
    return obs


def sync_committee_pubkeys(chain, slot: int):
    """Full sync-committee pubkey list for ``slot``'s period, or None when
    the head state cannot know it (reference committee rotation rule)."""
    P = chain.preset
    state = chain.head_state
    if not hasattr(state, "current_sync_committee"):
        return None  # pre-altair
    period = (slot // P.SLOTS_PER_EPOCH) // P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    head_period = (
        state.slot // P.SLOTS_PER_EPOCH
    ) // P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    if period == head_period:
        return [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    if period == head_period + 1:
        return [bytes(pk) for pk in state.next_sync_committee.pubkeys]
    return None


def is_sync_committee_aggregator(preset, selection_proof: bytes) -> bool:
    """Spec ``is_sync_committee_aggregator``."""
    modulo = max(
        1,
        preset.sync_subcommittee_size
        // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    h = hashlib.sha256(bytes(selection_proof)).digest()
    return int.from_bytes(h[:8], "little") % modulo == 0


def _slot_window_ok(chain, slot: int) -> bool:
    # Sync messages are only useful for the current slot; allow one slot
    # of clock disparity either way (reference MAXIMUM_GOSSIP_CLOCK_
    # DISPARITY applied to the one-slot propagation window).
    current = chain.slot()
    return slot <= current + 1 and slot + 1 >= current


def _prepare_sync_message(chain, msg):
    """Structural checks + signature-set assembly for one message; MUST be
    called under the chain lock. Returns (positions, SignatureSet)."""
    slot = int(msg.slot)
    vi = int(msg.validator_index)
    if not _slot_window_ok(chain, slot):
        raise SyncCommitteeError("OutsideSlotWindow", f"slot {slot}")
    state = chain.head_state
    if not 0 <= vi < len(state.validators):
        raise SyncCommitteeError("UnknownValidator", str(vi))
    committee = sync_committee_pubkeys(chain, slot)
    if committee is None:
        raise SyncCommitteeError("UnknownSyncCommittee")
    pk_raw = bytes(state.validators[vi].pubkey)
    positions = [i for i, c in enumerate(committee) if c == pk_raw]
    if not positions:
        raise SyncCommitteeError("NotInSyncCommittee", str(vi))
    if _observed(chain).is_known("messages", (slot, vi)):
        raise SyncCommitteeError("PriorMessageKnown", str(vi))
    epoch = slot // chain.preset.SLOTS_PER_EPOCH
    domain = get_domain(chain.spec, state, DOMAIN_SYNC_COMMITTEE, epoch)
    signing_root = compute_signing_root(
        None, bytes(msg.beacon_block_root), domain
    )
    from .pubkey_cache import PubkeyCacheError

    try:
        pk = chain.pubkey_cache.get(vi)  # a bls.PublicKey wrapper
    except PubkeyCacheError:
        raise SyncCommitteeError("UnknownValidator", str(vi))
    try:
        sig = bls.Signature.deserialize(bytes(msg.signature))
    except bls.BlsError:
        raise SyncCommitteeError("InvalidSignature")
    return positions, bls.SignatureSet.single_pubkey(sig, pk, signing_root)


def batch_verify_sync_committee_messages(chain, messages):
    """ONE backend call for a whole gossip batch, per-item fallback on
    failure — the sync analogue of ``batch_verify_unaggregated_
    attestations`` (reference processes sync messages through the same
    batch machinery, ``sync_committee_verification.rs:561`` fed by the
    beacon processor). Returns VerifiedSyncCommitteeMessage |
    SyncCommitteeError per input; BLS runs outside the chain lock."""
    results: list[object] = [None] * len(messages)
    pending = []  # (pos, msg, positions, set)
    with chain._chain_lock:
        for pos, m in enumerate(messages):
            try:
                positions, s = _prepare_sync_message(chain, m)
                pending.append((pos, m, positions, s))
            except SyncCommitteeError as e:
                results[pos] = e
    try:
        # scheduler-aware (verification_service/batcher.py): sync batches
        # fuse with concurrent attestation traffic into shared device
        # batches when the chain carries a scheduler
        batch_ok = bool(pending) and backend_verify(
            chain, [p[3] for p in pending], "sync_message"
        )
    except bls.BlsError:
        batch_ok = False
    if batch_ok:
        item_ok = {p[0]: True for p in pending}
    else:
        item_ok = {}
        try:
            each = backend_verify_each(
                chain, [[p[3]] for p in pending], "sync_message"
            )
        except bls.BlsError:
            each = [False] * len(pending)
        for p, ok in zip(pending, each):
            item_ok[p[0]] = ok
    with chain._chain_lock:
        for pos, m, positions, _s in pending:
            if not item_ok[pos]:
                results[pos] = SyncCommitteeError("InvalidSignature")
            elif _observed(chain).observe(
                "messages", (int(m.slot), int(m.validator_index))
            ):
                results[pos] = SyncCommitteeError(
                    "PriorMessageKnown", str(int(m.validator_index))
                )
            else:
                results[pos] = VerifiedSyncCommitteeMessage(m, positions)
    for pos, r in enumerate(results):
        if isinstance(r, SyncCommitteeError):
            m = messages[pos]
            flight_recorder.record(
                "sync_rejected", kind="message", reason=r.kind,
                slot=int(m.slot), validator_index=int(m.validator_index),
                root=bytes(m.beacon_block_root),
            )
    return results


def verify_sync_committee_message(chain, msg) -> VerifiedSyncCommitteeMessage:
    """Single sync-committee message from gossip/API; returns positions in
    the full committee (a pubkey may hold several slots)."""
    out = batch_verify_sync_committee_messages(chain, [msg])[0]
    if isinstance(out, SyncCommitteeError):
        raise out
    return out


def verify_sync_contribution(chain, signed) -> VerifiedSyncContribution:
    """SignedContributionAndProof from gossip/API — three signature sets
    in one backend call (reference ``:252-267``). Rejections are
    journaled as ``sync_rejected`` events with slot/aggregator context."""
    try:
        return _verify_sync_contribution_inner(chain, signed)
    except SyncCommitteeError as e:
        c = signed.message.contribution
        flight_recorder.record(
            "sync_rejected", kind="contribution", reason=e.kind,
            slot=int(c.slot), subcommittee_index=int(c.subcommittee_index),
            aggregator_index=int(signed.message.aggregator_index),
            root=bytes(c.beacon_block_root),
        )
        raise


def _verify_sync_contribution_inner(chain, signed) -> VerifiedSyncContribution:
    msg = signed.message
    contribution = msg.contribution
    slot = int(contribution.slot)
    subc = int(contribution.subcommittee_index)
    P = chain.preset
    if not _slot_window_ok(chain, slot):
        raise SyncCommitteeError("OutsideSlotWindow", f"slot {slot}")
    if subc >= P.SYNC_COMMITTEE_SUBNET_COUNT:
        raise SyncCommitteeError("InvalidSubcommittee", str(subc))
    bits = [bool(b) for b in contribution.aggregation_bits]
    if not any(bits):
        raise SyncCommitteeError("EmptyAggregationBits")
    ai = int(msg.aggregator_index)

    with chain._chain_lock:
        state = chain.head_state
        if not 0 <= ai < len(state.validators):
            raise SyncCommitteeError("UnknownValidator", str(ai))
        committee = sync_committee_pubkeys(chain, slot)
        if committee is None:
            raise SyncCommitteeError("UnknownSyncCommittee")
        sub_size = P.sync_subcommittee_size
        sub_pks = committee[subc * sub_size : (subc + 1) * sub_size]
        agg_pk_raw = bytes(state.validators[ai].pubkey)
        if agg_pk_raw not in sub_pks:
            raise SyncCommitteeError("AggregatorNotInSubcommittee", str(ai))
        if not is_sync_committee_aggregator(P, bytes(msg.selection_proof)):
            raise SyncCommitteeError("InvalidSelectionProof")
        obs = _observed(chain)
        bits_key = tuple(bits)
        root = bytes(contribution.beacon_block_root)
        if obs.is_known("aggregators", (slot, subc, ai)):
            raise SyncCommitteeError("AggregatorAlreadyKnown", str(ai))
        if obs.is_known("contributions", (slot, root, subc, bits_key)):
            raise SyncCommitteeError("ContributionAlreadyKnown")

        epoch = slot // P.SLOTS_PER_EPOCH
        t = chain.types
        resolver = chain.pubkey_resolver_by_bytes()

        # set 1: selection proof over SyncAggregatorSelectionData
        sel_domain = get_domain(
            chain.spec, state, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch
        )
        sel_data = t.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subc
        )
        sel_root = compute_signing_root(
            t.SyncAggregatorSelectionData, sel_data, sel_domain
        )
        # set 2: aggregator's signature over the ContributionAndProof
        cap_domain = get_domain(
            chain.spec, state, DOMAIN_CONTRIBUTION_AND_PROOF, epoch
        )
        cap_root = compute_signing_root(t.ContributionAndProof, msg, cap_domain)
        # set 3: the aggregated message signature from the participants
        sc_domain = get_domain(chain.spec, state, DOMAIN_SYNC_COMMITTEE, epoch)
        sc_root = compute_signing_root(None, root, sc_domain)
        participant_pks = []
        participant_indices = []
        for pos, bit in enumerate(bits):
            if not bit:
                continue
            pk_point = resolver(sub_pks[pos])
            if pk_point is None:
                raise SyncCommitteeError("UnknownParticipantPubkey")
            participant_pks.append(pk_point)
            participant_indices.append(subc * sub_size + pos)
        from .pubkey_cache import PubkeyCacheError

        try:
            agg_pk = chain.pubkey_cache.get(ai)
            sel_sig = bls.Signature.deserialize(bytes(msg.selection_proof))
            cap_sig = bls.Signature.deserialize(bytes(signed.signature))
            con_sig = bls.Signature.deserialize(bytes(contribution.signature))
        except (bls.BlsError, PubkeyCacheError) as e:
            raise SyncCommitteeError("InvalidSignature", str(e))
        # pubkey_cache / resolver hand back bls.PublicKey wrappers already
        sets = [
            bls.SignatureSet.single_pubkey(sel_sig, agg_pk, sel_root),
            bls.SignatureSet.single_pubkey(cap_sig, agg_pk, cap_root),
            bls.SignatureSet.multiple_pubkeys(
                con_sig, participant_pks, sc_root
            ),
        ]
    try:
        ok = backend_verify(chain, sets, "sync_contribution")
    except bls.BlsError:
        ok = False
    if not ok:
        raise SyncCommitteeError("InvalidSignature")
    with chain._chain_lock:
        obs = _observed(chain)
        if obs.observe("contributions", (slot, root, subc, bits_key)):
            raise SyncCommitteeError("ContributionAlreadyKnown")
        if obs.observe("aggregators", (slot, subc, ai)):
            raise SyncCommitteeError("AggregatorAlreadyKnown", str(ai))
    return VerifiedSyncContribution(signed, participant_indices)
