"""Block import pipeline types (reference:
``beacon_node/beacon_chain/src/block_verification.rs:21-44,590-660``):

    gossip bytes -> GossipVerifiedBlock  (cheap checks + ONE proposal sig)
                 -> SignatureVerifiedBlock (ALL block sigs, one batch)
                 -> ExecutionPendingBlock  (payload sent to the EL)
                 -> imported (fork choice + store)

Each stage owns the evidence of the previous one; ``BeaconChain.process_block``
drives the chain of custody.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field as dc_field

from ..ssz import hash_tree_root
from ..state_transition import (
    BlockSignatureAccumulator,
    partial_state_advance,
    get_beacon_proposer_index,
)
from ..state_transition.epoch import fork_of
from ..state_transition.signature_sets import block_proposal_set
from ..utils import flight_recorder, metrics, tracing
from ..verification_service import backend_verify_now

_STAGE_SECONDS = metrics.histogram_vec(
    "beacon_block_verification_seconds",
    "block import pipeline: per-stage wall time (gossip = structure + "
    "proposer + proposal signature; signature = full-block batch)",
    ("stage",),
)
_OUTCOMES = metrics.counter_vec(
    "beacon_block_verification_outcomes_total",
    "block verification verdicts per stage (outcome = ok or BlockError kind)",
    ("stage", "outcome"),
)


class BlockError(ValueError):
    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}{': ' + detail if detail else ''}")
        self.kind = kind
        self.detail = detail


def _record_rejection(stage: str, e: BlockError, signed_block, block_root=None):
    """Journal one ``block_rejected`` event with the forensic context a
    counter tick loses: stage, reason, slot, proposer and root."""
    if not flight_recorder.enabled():
        # the root below may need a full SSZ hash: never pay it (bursts
        # of duplicate-gossip rejections) when nothing is recording
        return
    block = signed_block.message
    if block_root is None:
        block_root = hash_tree_root(block)
    flight_recorder.record(
        "block_rejected",
        stage=stage, reason=e.kind, detail=e.detail,
        slot=int(block.slot), proposer_index=int(block.proposer_index),
        root=bytes(block_root),
    )


@dataclass
class GossipVerifiedBlock:
    """Propagation-safe: structure + proposer + proposal signature checked
    against an advanced parent state (reference ``block_verification.rs:590``)."""

    signed_block: object
    block_root: bytes
    state: object  # parent state advanced to block.slot (pre-block)

    @classmethod
    def new(cls, chain, signed_block):
        with tracing.span(
            "block.gossip_verify", slot=int(signed_block.message.slot)
        ), _STAGE_SECONDS.with_labels("gossip").time():
            try:
                out = cls._new_inner(chain, signed_block)
            except BlockError as e:
                _OUTCOMES.with_labels("gossip", e.kind).inc()
                _record_rejection(
                    "gossip", e, signed_block,
                    getattr(e, "block_root", None),
                )
                raise
            _OUTCOMES.with_labels("gossip", "ok").inc()
            return out

    @classmethod
    def _new_inner(cls, chain, signed_block):
        block_root = hash_tree_root(signed_block.message)
        try:
            return cls._new_checked(chain, signed_block, block_root)
        except BlockError as e:
            # forensics reuses THIS Merkleization: a flood of junk gossip
            # blocks must not pay a second full SSZ hash per rejection
            e.block_root = block_root
            raise

    @classmethod
    def _new_checked(cls, chain, signed_block, block_root):
        block = signed_block.message
        current_slot = chain.slot()

        if block.slot > current_slot:
            raise BlockError("FutureSlot", f"{block.slot} > {current_slot}")
        fin_epoch, _ = chain.fork_choice.store.finalized_checkpoint
        if block.slot <= fin_epoch * chain.preset.SLOTS_PER_EPOCH:
            raise BlockError("WouldRevertFinalizedSlot")
        if chain.fork_choice.proto.contains(block_root):
            raise BlockError("BlockIsAlreadyKnown")
        if chain.observed_block_producers.is_known(block.proposer_index, block.slot):
            raise BlockError("RepeatProposal")
        parent_root = bytes(block.parent_root)
        if not chain.fork_choice.proto.contains(parent_root):
            raise BlockError("ParentUnknown", parent_root.hex()[:12])

        state = (
            chain.advanced_state_for(parent_root, block.slot)
            or chain.state_at_block_root(parent_root)
        )
        state = partial_state_advance(chain.preset, chain.spec, copy.deepcopy(state), block.slot)
        expected = get_beacon_proposer_index(chain.preset, state)
        if expected != block.proposer_index:
            raise BlockError(
                "IncorrectBlockProposer", f"{block.proposer_index} != {expected}"
            )
        s = block_proposal_set(
            chain.preset, chain.spec, state, signed_block,
            chain.pubkey_cache.resolver(), block_root=block_root,
        )
        # block verification is latency-critical (a late block loses the
        # slot): the scheduler's SYNCHRONOUS bypass, never the fusing queue
        if not backend_verify_now(chain, [s], kind="block"):
            raise BlockError("ProposalSignatureInvalid")
        chain.observed_block_producers.observe(block.proposer_index, block.slot)
        return cls(signed_block, block_root, state)


@dataclass
class SignatureVerifiedBlock:
    """Every signature in the block verified as ONE batch — the
    north-star consumer (reference ``block_verification.rs:599`` +
    ``block_signature_verifier.rs:120-132``)."""

    signed_block: object
    block_root: bytes
    state: object
    proposal_already_verified: bool = False

    @classmethod
    def from_gossip(cls, gossip: GossipVerifiedBlock, chain):
        return cls._verify(
            chain, gossip.signed_block, gossip.block_root, gossip.state,
            skip_proposal=True,
        )

    @classmethod
    def new(cls, chain, signed_block, block_root=None):
        block = signed_block.message
        if block_root is None:
            block_root = hash_tree_root(block)
        parent_root = bytes(block.parent_root)
        if not chain.fork_choice.proto.contains(parent_root):
            raise BlockError("ParentUnknown", parent_root.hex()[:12])
        state = (
            chain.advanced_state_for(parent_root, block.slot)
            or chain.state_at_block_root(parent_root)
        )
        state = partial_state_advance(
            chain.preset, chain.spec, copy.deepcopy(state), block.slot
        )
        return cls._verify(chain, signed_block, block_root, state, skip_proposal=False)

    @classmethod
    def _verify(cls, chain, signed_block, block_root, state, skip_proposal):
        from ..crypto.bls import BlsError

        with tracing.span(
            "block.signature_verify", slot=int(signed_block.message.slot),
            skip_proposal=skip_proposal,
        ), _STAGE_SECONDS.with_labels("signature").time():
            try:
                acc = BlockSignatureAccumulator(
                    chain.preset, chain.spec, state, chain.pubkey_cache.resolver(),
                    resolver_by_pubkey_bytes=chain.pubkey_resolver_by_bytes(),
                )
                if skip_proposal:
                    acc.include_randao_reveal(signed_block.message)
                    acc.include_operations(signed_block)
                else:
                    acc.include_all(signed_block, block_root=block_root)
                # same bypass as the proposal check: the full-block batch
                # must not wait on the gossip fusing deadline
                ok = backend_verify_now(chain, acc.sets, kind="block")
            except BlsError:  # malformed signature bytes in the block body
                ok = False
        _OUTCOMES.with_labels(
            "signature", "ok" if ok else "InvalidSignature"
        ).inc()
        if not ok:
            e = BlockError("InvalidSignature")
            _record_rejection("signature", e, signed_block, block_root)
            # a full-block signature batch failing is the verify-failure
            # the forensics layer exists for: snapshot the journal (the
            # staged device event with per-stage latencies is in it)
            flight_recorder.dump_on_failure(
                "block_signature_invalid",
                slot=int(signed_block.message.slot), root=bytes(block_root),
            )
            raise e
        return cls(signed_block, block_root, state, skip_proposal)


@dataclass
class ExecutionPendingBlock:
    """Consensus-verified; payload handed to the execution layer whose
    verdict is joined at import (reference ``block_verification.rs:621``)."""

    signed_block: object
    block_root: bytes
    state: object
    payload_verification_handle: object = dc_field(default=None)
