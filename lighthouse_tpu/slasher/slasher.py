"""Min-max-span slashing detection (reference: ``slasher/src/array.rs``
— the published min-max surround-detection scheme over a 2D
(validator x epoch) distance array; ``slasher/src/lib.rs:33-48`` status
enum; queues in ``attestation_queue.rs``).

Data layout is the vectorized (numpy) analogue of the reference's
chunked LMDB arrays: per validator,

* ``min_span[e]`` = min over recorded attestations with ``source > e`` of
  ``target - e`` — a new attestation (s, t) **surrounds** an existing one
  iff ``min_span[s] < t - s`` (some vote sits strictly inside it);
* ``max_span[e]`` = max over recorded attestations with ``source < e`` of
  ``target - e`` — a new attestation is **surrounded by** an existing one
  iff ``max_span[s] > t - s``.

Span updates touch a contiguous epoch range and are applied with numpy
slice min/max — one vector op per attestation instead of a Python loop
over epochs.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Optional

import numpy as np

from ..ssz import hash_tree_root

_NO_SPAN = np.iinfo(np.int64).max


from ..utils import metrics

_BATCH_TIME = metrics.histogram(
    "slasher_batch_seconds", "queued-attestation batch processing latency"
)
_SLASHINGS = metrics.counter(
    "slasher_slashings_found_total", "attester/proposer slashings detected"
)


def _b64(v: int) -> bytes:
    return int(v).to_bytes(8, "big")


def _u64(b: bytes) -> int:
    return int.from_bytes(b, "big")


class AttesterSlashingStatus(enum.Enum):
    NOT_SLASHABLE = "not_slashable"
    DOUBLE_VOTE = "double_vote"
    SURROUNDS_EXISTING = "surrounds_existing"
    SURROUNDED_BY_EXISTING = "surrounded_by_existing"


class Slasher:
    """``on_slashing`` receives (status, indexed_attestation_new,
    indexed_attestation_old) — e.g. the op pool's insert_attester_slashing
    wrapped by the service."""

    def __init__(
        self,
        types,
        history_length: int = 4096,
        on_slashing: Optional[Callable] = None,
        slots_per_epoch: int = 32,
        store=None,
    ):
        """``store``: optional :class:`KeyValueStore`; when given, span
        arrays + evidence persist under ``Column.SLASHER`` and reload on
        construction (reference: the LMDB database behind
        ``slasher/src/database/lmdb_impl.rs:1-203``). Writes are batched
        per ``process_queued``/``check_block_header`` call."""
        self.t = types
        self.history = history_length
        self.slots_per_epoch = slots_per_epoch
        self.on_slashing = on_slashing
        self._store = store
        self._dirty_spans: set[int] = set()
        self._dirty_targets: set[tuple[int, int]] = set()
        self._dirty_blocks: set[tuple[int, int]] = set()
        self._lock = threading.Lock()
        # spans index epochs relative to this sliding base; advancing the
        # base shifts every validator's arrays (reference: the chunked
        # arrays slide with the finalized epoch)
        self._base = 0
        # per-validator span arrays [history] int64
        self._min_span: dict[int, np.ndarray] = {}
        self._max_span: dict[int, np.ndarray] = {}
        # (validator, target_epoch) -> [(data_root, indexed_attestation)]
        # (all distinct votes kept: span flags must always have evidence)
        self._by_target: dict[tuple[int, int], list[tuple[bytes, object]]] = {}
        # (validator, source_epoch) -> targets recorded (for evidence lookup)
        self._by_source: dict[tuple[int, int], list[int]] = {}
        # blocks: (proposer, slot) -> (root, signed_header)
        self._blocks: dict[tuple[int, int], tuple[bytes, object]] = {}
        self._queue: list = []
        self.found_attester_slashings: list = []
        self.found_proposer_slashings: list = []
        if store is not None:
            try:
                self._load()
            except Exception:
                # corrupt/mismatched persisted state must not brick
                # startup (same degrade-to-fresh contract as the client's
                # fork-choice and op-pool restores)
                self._base = 0
                self._min_span.clear()
                self._max_span.clear()
                self._by_target.clear()
                self._by_source.clear()
                self._blocks.clear()

    # -- ingestion (queued, like the reference's batching queues) --------

    def accept_attestation(self, indexed_attestation) -> None:
        with self._lock:
            self._queue.append(indexed_attestation)

    def process_queued(self) -> int:
        with _BATCH_TIME.time():
            return self._process_queued()

    def _process_queued(self) -> int:
        """Periodic batch processing (reference
        ``slasher/service/src/service.rs``). Returns #slashings found."""
        with self._lock:
            batch, self._queue = self._queue, []
        found = 0
        for att in batch:
            found += len(self.check_attestation(att))
        self.flush()
        return found

    # -- attestations ----------------------------------------------------

    def _spans(self, v: int) -> tuple[np.ndarray, np.ndarray]:
        mn = self._min_span.get(v)
        if mn is None:
            mn = self._min_span[v] = np.full(self.history, _NO_SPAN, np.int64)
            self._max_span[v] = np.full(self.history, -1, np.int64)
        return mn, self._max_span[v]

    def check_attestation(self, indexed) -> list:
        """Record + detect; returns [(status, evidence AttesterSlashing)].

        Evidence ordering follows spec ``is_slashable_attestation_data``:
        the SURROUNDING attestation must be ``attestation_1``."""
        data = indexed.data
        s, t = data.source.epoch, data.target.epoch
        root = hash_tree_root(data)
        out = []
        with self._lock:
            for v in (int(i) for i in indexed.attesting_indices):
                status, old = self._check_one(v, s, t, root)
                if status != AttesterSlashingStatus.NOT_SLASHABLE:
                    if status == AttesterSlashingStatus.SURROUNDS_EXISTING:
                        first, second = indexed, old  # new surrounds old
                    else:
                        first, second = old, indexed
                    slashing = self.t.AttesterSlashing(
                        attestation_1=first, attestation_2=second
                    )
                    self.found_attester_slashings.append(slashing)
                    _SLASHINGS.inc()
                    out.append((status, slashing))
                    if self.on_slashing:
                        self.on_slashing(status, indexed, old)
                self._record(v, s, t, root, indexed)
        return out

    def _check_one(self, v: int, s: int, t: int, root: bytes):
        # double vote: same target, ANY data difference
        for prev_root, prev_att in self._by_target.get((v, t), ()):
            if prev_root != root:
                return AttesterSlashingStatus.DOUBLE_VOTE, prev_att
        self._maybe_rebase(t)
        mn, mx = self._spans(v)
        si = s - self._base
        if 0 <= si < self.history:
            dist = t - s
            # min_span[s] = min(t' - s) over existing with s' > s; a value
            # below dist means some existing (s', t') sits strictly INSIDE
            # the new (s, t): the NEW SURROUNDS an existing vote.
            if mn[si] != _NO_SPAN and mn[si] < dist:
                old = self._find_inside(v, s, t)
                if old is not None:
                    return AttesterSlashingStatus.SURROUNDS_EXISTING, old
            # max_span[s] = max(t' - s) over existing with s' < s; above
            # dist means some existing encloses the new vote.
            if mx[si] > dist:
                old = self._find_enclosing(v, s, t)
                if old is not None:
                    return (
                        AttesterSlashingStatus.SURROUNDED_BY_EXISTING,
                        old,
                    )
        return AttesterSlashingStatus.NOT_SLASHABLE, None

    def _find_inside(self, v: int, s: int, t: int):
        """Existing attestation strictly inside (s, t)."""
        for (vv, tt), entries in self._by_target.items():
            if vv != v or not tt < t:
                continue
            for _, att in entries:
                if att.data.source.epoch > s:
                    return att
        return None

    def _find_enclosing(self, v: int, s: int, t: int):
        """Existing attestation strictly enclosing (s, t)."""
        for (vv, tt), entries in self._by_target.items():
            if vv != v or not tt > t:
                continue
            for _, att in entries:
                if att.data.source.epoch < s:
                    return att
        return None

    def _record(self, v: int, s: int, t: int, root: bytes, indexed) -> None:
        entries = self._by_target.setdefault((v, t), [])
        if all(r != root for r, _ in entries):
            entries.append((root, indexed))
            self._dirty_targets.add((v, t))
        self._by_source.setdefault((v, s), []).append(t)
        self._dirty_spans.add(v)
        self._maybe_rebase(t)
        mn, mx = self._spans(v)
        base = self._base
        # attestations with source > e: window epochs e in [base, s);
        # distance t - e. Vectorized slice update over indices.
        lo_i, hi_i = 0, min(max(s - base, 0), self.history)
        if hi_i > lo_i:
            e = np.arange(lo_i, hi_i) + base
            np.minimum(mn[lo_i:hi_i], t - e, out=mn[lo_i:hi_i])
        # attestations with source < e: epochs e in (s, t]
        lo_i = min(max(s + 1 - base, 0), self.history)
        hi_i = min(max(t + 1 - base, 0), self.history)
        if hi_i > lo_i:
            e = np.arange(lo_i, hi_i) + base
            np.maximum(mx[lo_i:hi_i], t - e, out=mx[lo_i:hi_i])

    def _maybe_rebase(self, epoch: int) -> None:
        """Slide the span window so ``epoch`` is addressable; history that
        falls off the left edge is forgotten (it is older than the
        weak-subjectivity horizon anyway)."""
        if epoch - self._base < self.history:
            return
        new_base = epoch - self.history // 2
        shift = new_base - self._base
        for v in self._min_span:
            mn, mx = self._min_span[v], self._max_span[v]
            mn[:-shift] = mn[shift:] if shift < self.history else _NO_SPAN
            mn[-shift:] = _NO_SPAN
            mx[:-shift] = mx[shift:] if shift < self.history else -1
            mx[-shift:] = -1
        self._base = new_base
        self._dirty_spans.update(self._min_span)  # the shift touched all

    # -- blocks ----------------------------------------------------------

    def check_block_header(self, signed_header) -> Optional[object]:
        """Double-proposal detection -> ProposerSlashing evidence."""
        msg = signed_header.message
        key = (msg.proposer_index, msg.slot)
        root = hash_tree_root(msg)
        slashing = None
        with self._lock:
            prev = self._blocks.get(key)
            if prev is None:
                self._blocks[key] = (root, signed_header)
                self._dirty_blocks.add(key)
            elif prev[0] != root:
                slashing = self.t.ProposerSlashing(
                    signed_header_1=prev[1], signed_header_2=signed_header
                )
                self.found_proposer_slashings.append(slashing)
                _SLASHINGS.inc()
        if slashing is not None and self.on_slashing:
            self.on_slashing("double_proposal", signed_header, prev[1])
        self.flush()
        return slashing

    # -- maintenance -----------------------------------------------------

    def prune(self, finalized_epoch: int) -> None:
        with self._lock:
            self._by_target = {
                k: v for k, v in self._by_target.items() if k[1] >= finalized_epoch
            }
            self._by_source = {
                k: v for k, v in self._by_source.items() if k[1] >= finalized_epoch
            }
            self._blocks = {
                k: v
                for k, v in self._blocks.items()
                if k[1] >= finalized_epoch * self.slots_per_epoch
            }
            # dirty entries for pruned keys must not resurrect store rows
            self._dirty_targets = {
                k for k in self._dirty_targets if k in self._by_target
            }
            self._dirty_blocks = {k for k in self._dirty_blocks if k in self._blocks}
            if self._store is not None:
                from ..store.kv import Column

                drop = []
                for key in list(self._store.keys(Column.SLASHER)):
                    if key[:1] == b"a" and _u64(key[9:17]) < finalized_epoch:
                        drop.append(key)
                    elif key[:1] == b"b" and (
                        _u64(key[9:17]) < finalized_epoch * self.slots_per_epoch
                    ):
                        drop.append(key)
                for key in drop:
                    self._store.delete(Column.SLASHER, key)

    # -- persistence (reference: slasher/src/database/lmdb_impl.rs) ------

    def flush(self) -> None:
        """Write dirty spans/evidence/blocks to the store in one batch."""
        if self._store is None:
            return
        import json

        from ..store.kv import Column

        with self._lock:
            if not (self._dirty_spans or self._dirty_targets or self._dirty_blocks):
                return
            items = [
                (
                    Column.SLASHER,
                    b"meta",
                    json.dumps(
                        {
                            "version": 1,
                            "base": self._base,
                            "history": self.history,
                            "slots_per_epoch": self.slots_per_epoch,
                        }
                    ).encode(),
                )
            ]
            for v in self._dirty_spans:
                mn, mx = self._spans(v)
                items.append(
                    (Column.SLASHER, b"s" + _b64(v), mn.tobytes() + mx.tobytes())
                )
            for v, t in self._dirty_targets:
                entries = self._by_target.get((v, t), [])
                items.append(
                    (
                        Column.SLASHER,
                        b"a" + _b64(v) + _b64(t),
                        json.dumps(
                            [
                                [
                                    r.hex(),
                                    self.t.IndexedAttestation.encode(att).hex(),
                                ]
                                for r, att in entries
                            ]
                        ).encode(),
                    )
                )
            for p, slot in self._dirty_blocks:
                entry = self._blocks.get((p, slot))
                if entry is None:  # pruned between marking and flush
                    continue
                root, header = entry
                items.append(
                    (
                        Column.SLASHER,
                        b"b" + _b64(p) + _b64(slot),
                        json.dumps(
                            [root.hex(), self.t.SignedBeaconBlockHeader.encode(header).hex()]
                        ).encode(),
                    )
                )
            self._store.put_batch(items)
            self._dirty_spans.clear()
            self._dirty_targets.clear()
            self._dirty_blocks.clear()

    def _load(self) -> None:
        """Restore spans + evidence from the store (init-time; lock not
        yet shared). ``_by_source`` is derived from the evidence."""
        import json

        from ..store.kv import Column

        meta = self._store.get(Column.SLASHER, b"meta")
        if meta is None:
            return
        doc = json.loads(meta.decode())
        if doc.get("history") != self.history:
            raise ValueError(
                f"slasher history mismatch: store {doc.get('history')}, "
                f"configured {self.history}"
            )
        self._base = int(doc["base"])
        for key, value in self._store.iter_column(Column.SLASHER):
            tag = key[:1]
            if tag == b"s":
                v = _u64(key[1:9])
                arr = np.frombuffer(value, np.int64).copy()
                self._min_span[v] = arr[: self.history]
                self._max_span[v] = arr[self.history :]
            elif tag == b"a":
                v, t = _u64(key[1:9]), _u64(key[9:17])
                entries = [
                    (
                        bytes.fromhex(r),
                        self.t.IndexedAttestation.decode(bytes.fromhex(att)),
                    )
                    for r, att in json.loads(value.decode())
                ]
                self._by_target[(v, t)] = entries
                for _, att in entries:
                    self._by_source.setdefault(
                        (v, int(att.data.source.epoch)), []
                    ).append(t)
            elif tag == b"b":
                p, slot = _u64(key[1:9]), _u64(key[9:17])
                r, header = json.loads(value.decode())
                self._blocks[(p, slot)] = (
                    bytes.fromhex(r),
                    self.t.SignedBeaconBlockHeader.decode(bytes.fromhex(header)),
                )
