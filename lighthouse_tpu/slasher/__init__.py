"""L4d: slasher — double-vote + min-max surround detection.

Reference: ``slasher/`` (``src/lib.rs:20-48`` AttesterSlashingStatus,
``src/array.rs`` chunked min/max span arrays over (validator, epoch),
``attestation_queue.rs``/``block_queue.rs`` batching, feeding found
slashings into the op pool via ``slasher/service``).
"""

from .slasher import AttesterSlashingStatus, Slasher

__all__ = ["AttesterSlashingStatus", "Slasher"]
