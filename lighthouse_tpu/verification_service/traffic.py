"""Arrival-trace model for the traffic-replay harness (ISSUE 7).

"Heavy traffic from millions of users" needs an ARRIVAL model, not just
a steady-state throughput number (ROADMAP item 5): every bench so far
measures one fixed shape at saturation, which says nothing about what a
submitter experiences under bursty, epoch-boundary-shaped load. This
module is the jax-free substrate of that model — shared by the replay
driver (``tools/traffic_replay.py``), the bench ``replay_leg``, and the
determinism tests:

* a **versioned JSONL trace format** (:data:`TRACE_SCHEMA`): one header
  line, then one arrival event per line — ``t`` (seconds from trace
  start), ``kind`` (caller kind), ``n_sets``/``pubkeys``/``messages``
  (submission geometry, the three axes the packers pad), ``path``
  (``submit`` for the fusing queue, ``verify_now`` for the
  latency-critical bypass);
* **synthetic mainnet-shaped generators** (:data:`GENERATORS`):
  gossip steady-state, epoch-boundary attestation flood, sync-committee
  period, bulk backfill running underneath — each fully deterministic
  under its seed (``random.Random``; no wall clock, no global RNG);
* a **lockstep simulator** (:func:`lockstep_replay`): the scheduler's
  drain/flush policy and the shape-aware planner replayed as a pure
  function of the trace — same trace + same seed ⇒ identical submission
  sequence, flush-plan shapes and set counts, byte for byte (the
  determinism gate ``tests/test_traffic_replay.py`` pins this in a
  subprocess, like ``tools/flush_plan_report.py``). The timed replay
  against a LIVE scheduler+compile-service stack lives in
  ``tools/traffic_replay.py``; this module never starts a thread.

Trace schema and generator catalogue are documented in
``docs/TRAFFIC_REPLAY.md`` (linted by ``tests/test_zgate4_metrics_lint``).
"""

from __future__ import annotations

import hashlib
import json
import random
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .planner import FlushPlanner

TRACE_VERSION = 1
TRACE_SCHEMA = f"lighthouse_tpu.traffic_trace/{TRACE_VERSION}"

_PATHS = ("submit", "verify_now")
# QoS service classes (ISSUE 15): "deadline" = gossip's latency class,
# "bulk" = the deadline-insensitive backfill/ingest class (submit-path
# only — the verify_now bypass IS the latency-critical escape hatch)
_QOS = ("deadline", "bulk")
_EVENT_DEFAULTS = {
    "pubkeys": 1, "messages": 1, "path": "submit", "qos": "deadline",
}


# ---------------------------------------------------------------------------
# Trace format (JSONL: header line + one event per line)
# ---------------------------------------------------------------------------


def _validate_event(ev: dict, lineno: int) -> dict:
    out = dict(_EVENT_DEFAULTS)
    out.update(ev)
    try:
        out["t"] = float(out["t"])
        out["kind"] = str(out["kind"])
        out["n_sets"] = int(out["n_sets"])
        out["pubkeys"] = int(out["pubkeys"])
        out["messages"] = int(out["messages"])
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"trace line {lineno}: malformed event {ev!r}: {e}")
    if out["t"] < 0 or out["n_sets"] <= 0 or out["pubkeys"] <= 0 \
            or out["messages"] <= 0:
        raise ValueError(
            f"trace line {lineno}: non-positive geometry/time in {ev!r}"
        )
    if out["path"] not in _PATHS:
        raise ValueError(
            f"trace line {lineno}: unknown path {out['path']!r} "
            f"(expected one of {_PATHS})"
        )
    if out["qos"] not in _QOS:
        raise ValueError(
            f"trace line {lineno}: unknown qos {out['qos']!r} "
            f"(expected one of {_QOS})"
        )
    if out["qos"] == "bulk" and out["path"] != "submit":
        raise ValueError(
            f"trace line {lineno}: qos=bulk is submit-only (the "
            f"verify_now bypass is the latency-critical class)"
        )
    # optional committee identity (ISSUE 17): the validator-index tuple
    # an aggregate's signers form — what the aggregate-cache collapse
    # keys on and the replay's first-sighting model consumes
    if "validators" in out:
        try:
            vals = [int(v) for v in out["validators"]]
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"trace line {lineno}: malformed validators in {ev!r}: {e}"
            )
        if any(v < 0 for v in vals):
            raise ValueError(
                f"trace line {lineno}: negative validator index in {ev!r}"
            )
        out["validators"] = vals
    return out


def trace_header(
    events: List[dict],
    name: str,
    seed: int,
    generator: str | None = None,
    params: dict | None = None,
) -> dict:
    """THE header document for a trace of ``events`` (assumed sorted) —
    one construction shared by :func:`write_trace` and the replay
    driver's generate-without-write path, so the two can never carry
    different field sets."""
    return {
        "schema": TRACE_SCHEMA,
        "name": name,
        "seed": int(seed),
        "n_events": len(events),
        "duration_s": round(events[-1]["t"], 6) if events else 0.0,
        "generator": generator,
        "params": params or {},
    }


def write_trace(
    path: str,
    events: List[dict],
    name: str,
    seed: int,
    generator: str | None = None,
    params: dict | None = None,
) -> dict:
    """Write ``events`` as a versioned JSONL trace; returns the header.
    Events are validated and written sorted by arrival time so a trace
    file is replayable as-is."""
    events = sorted(
        (_validate_event(ev, i + 2) for i, ev in enumerate(events)),
        key=lambda e: e["t"],
    )
    header = trace_header(events, name, seed, generator, params)
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for ev in events:
            f.write(json.dumps(ev, sort_keys=True) + "\n")
    return header


def read_trace(path: str) -> Tuple[dict, List[dict]]:
    """Parse a trace file; raises ``ValueError`` on a missing/unsupported
    schema version or a malformed event — a replay must never silently
    reinterpret a trace written by a different format generation."""
    with open(path) as f:
        # keep REAL file line numbers through the blank-line filter so
        # every error message points at the line the operator must edit
        lines = [
            (lineno, ln)
            for lineno, ln in enumerate((l.strip() for l in f), start=1)
            if ln
        ]
    if not lines:
        raise ValueError(f"{path}: empty trace file")
    header_lineno, header_line = lines[0]
    try:
        header = json.loads(header_line)
    except ValueError as e:
        raise ValueError(
            f"{path}: line {header_lineno}: unparseable header: {e}"
        )
    schema = header.get("schema")
    if schema != TRACE_SCHEMA:
        raise ValueError(
            f"{path}: unsupported trace schema {schema!r} "
            f"(this build reads {TRACE_SCHEMA!r})"
        )
    events = []
    for lineno, ln in lines[1:]:
        try:
            ev = json.loads(ln)
        except ValueError as e:
            raise ValueError(f"{path}: line {lineno}: unparseable: {e}")
        events.append(_validate_event(ev, lineno))
    events.sort(key=lambda e: e["t"])
    return header, events


def synthetic_sets(
    kind: str, n_sets: int, pubkeys: int, messages: int
) -> list:
    """Geometry-only signature sets for an arrival event: ``(None,
    [None]*pubkeys, message bytes)`` triples — everything the planner
    and the packers' geometry extraction read, nothing the crypto needs
    (same trick as ``tools/flush_plan_report.py``). Messages are salted
    per kind: real traffic's kinds sign different messages, so the
    fused flush's unique-message axis is the sum, not the max, of the
    per-kind counts."""
    return [
        (
            None,
            [None] * pubkeys,
            kind.encode() + (i % max(1, messages)).to_bytes(4, "big"),
        )
        for i in range(n_sets)
    ]


# ---------------------------------------------------------------------------
# Generators (deterministic under seed; rates are per-second)
# ---------------------------------------------------------------------------


def _poisson(
    rng: random.Random,
    rate: float,
    t0: float,
    t1: float,
    make: Callable[[float, random.Random], dict],
) -> List[dict]:
    """Homogeneous Poisson arrivals of one event class on [t0, t1)."""
    out: List[dict] = []
    if rate <= 0 or t1 <= t0:
        return out
    t = t0 + rng.expovariate(rate)
    while t < t1:
        out.append(make(round(t, 6), rng))
        t += rng.expovariate(rate)
    return out


def _finish(events: List[dict]) -> List[dict]:
    events.sort(key=lambda e: e["t"])
    return events


def gossip_steady(
    duration_s: float = 10.0,
    seed: int = 0,
    rate_scale: float = 1.0,
    committee: int = 8,
    unagg_rate: float = 40.0,
    agg_rate: float = 12.0,
    sync_rate: float = 6.0,
) -> List[dict]:
    """Steady-state gossip: single-pubkey attestations, committee-width
    aggregates, and sync-committee messages as independent Poisson
    streams — the baseline every other shape layers onto."""
    rng = random.Random(seed)
    evs: List[dict] = []
    evs += _poisson(
        rng, unagg_rate * rate_scale, 0.0, duration_s,
        lambda t, r: {"t": t, "kind": "unaggregated", "n_sets": 1,
                      "pubkeys": 1, "messages": 1, "path": "submit"},
    )
    evs += _poisson(
        rng, agg_rate * rate_scale, 0.0, duration_s,
        lambda t, r: {"t": t, "kind": "aggregate", "n_sets": 1,
                      "pubkeys": committee, "messages": 1, "path": "submit"},
    )
    evs += _poisson(
        rng, sync_rate * rate_scale, 0.0, duration_s,
        lambda t, r: {"t": t, "kind": "sync_message", "n_sets": 1,
                      "pubkeys": 1, "messages": 1, "path": "submit"},
    )
    return _finish(evs)


def epoch_boundary_flood(
    duration_s: float = 12.0,
    seed: int = 0,
    rate_scale: float = 1.0,
    committee: int = 8,
    slot_s: float = 2.0,
    flood_start_frac: float = 0.5,
    flood_width_s: float = 2.0,
    flood_factor: float = 8.0,
    block_sets: int = 2,
    n_committees: int = 16,
) -> List[dict]:
    """The acceptance-gate shape: gossip steady-state with an
    attestation FLOOD in the window starting at
    ``flood_start_frac * duration_s`` (the epoch boundary, where every
    validator's attestation for the old epoch and the committee
    reshuffle land together), plus one latency-critical block
    verification per slot on the ``verify_now`` bypass — the trace that
    exercises fused, planned, shed, bypass and fallback resolution
    paths at once.

    Committee realism (ISSUE 17): a real epoch has a FIXED committee
    shuffle — the same validator-index tuples recur across the epoch's
    aggregates — so flood aggregates draw their ``validators`` tuple
    from ``n_committees`` stable disjoint committees instead of being
    anonymous. Repeated tuples are exactly what the aggregate-cache
    collapse (key table, ROADMAP item 3) keys on; without them the
    first-sighting hit-ratio is structurally unmeasurable on this
    trace."""
    rng = random.Random(seed)
    evs = gossip_steady(
        duration_s=duration_s, seed=seed + 1, rate_scale=rate_scale,
        committee=committee,
    )
    f0 = flood_start_frac * duration_s
    f1 = min(duration_s, f0 + flood_width_s)
    # the flood rides ON TOP of the base rates (extra independent
    # streams), so the boundary window carries base + (factor-1)x extra
    extra = max(0.0, flood_factor - 1.0) * rate_scale
    # the epoch's committee shuffle: stable disjoint index tuples
    committees = [
        tuple(range(c * committee, (c + 1) * committee))
        for c in range(max(1, int(n_committees)))
    ]
    evs += _poisson(
        rng, 40.0 * extra, f0, f1,
        lambda t, r: {"t": t, "kind": "unaggregated", "n_sets": 1,
                      "pubkeys": 1, "messages": 1, "path": "submit"},
    )
    evs += _poisson(
        rng, 12.0 * extra, f0, f1,
        lambda t, r: {"t": t, "kind": "aggregate", "n_sets": 1,
                      "pubkeys": committee, "messages": 1, "path": "submit",
                      "validators": list(
                          committees[r.randrange(len(committees))]
                      )},
    )
    # one block per slot, early in the slot, on the synchronous bypass
    slot = 0
    while slot * slot_s < duration_s:
        t = slot * slot_s + rng.uniform(0.0, 0.3 * slot_s)
        if t < duration_s:
            evs.append({
                "t": round(t, 6), "kind": "block", "n_sets": block_sets,
                "pubkeys": 1, "messages": block_sets, "path": "verify_now",
            })
        slot += 1
    return _finish(evs)


def sync_committee_period(
    duration_s: float = 12.0,
    seed: int = 0,
    rate_scale: float = 1.0,
    slot_s: float = 2.0,
    subcommittee: int = 16,
    msg_rate: float = 30.0,
    contrib_per_slot: int = 4,
    background_rate: float = 8.0,
) -> List[dict]:
    """Sync-committee period: per slot, a burst of single-pubkey sync
    messages in the first half (the 4-second broadcast window scaled
    down) and a few subcommittee-width contributions near the slot end,
    over a thin attestation background."""
    rng = random.Random(seed)
    evs: List[dict] = []
    evs += _poisson(
        rng, background_rate * rate_scale, 0.0, duration_s,
        lambda t, r: {"t": t, "kind": "unaggregated", "n_sets": 1,
                      "pubkeys": 1, "messages": 1, "path": "submit"},
    )
    slot = 0
    while slot * slot_s < duration_s:
        s0 = slot * slot_s
        evs += _poisson(
            rng, msg_rate * rate_scale, s0, min(duration_s, s0 + slot_s / 2),
            lambda t, r: {"t": t, "kind": "sync_message", "n_sets": 1,
                          "pubkeys": 1, "messages": 1, "path": "submit"},
        )
        for _ in range(contrib_per_slot):
            t = s0 + slot_s * rng.uniform(0.7, 0.95)
            if t < duration_s:
                evs.append({
                    "t": round(t, 6), "kind": "sync_contribution",
                    "n_sets": 1, "pubkeys": subcommittee, "messages": 1,
                    "path": "submit",
                })
        slot += 1
    return _finish(evs)


def bulk_backfill(
    duration_s: float = 20.0,
    seed: int = 0,
    rate_scale: float = 1.0,
    committee: int = 8,
    batch_every_s: float = 2.5,
    batch_sets: Tuple[int, ...] = (64, 96, 128),
    gossip_rate: float = 8.0,
) -> List[dict]:
    """Chain-segment backfill running UNDERNEATH live gossip: large
    deadline-insensitive contiguous submissions every few seconds (the
    ROADMAP item-5 bulk class) while a thin unaggregated stream keeps
    arriving — the shape that shows whether bulk batches starve gossip
    tail latency."""
    rng = random.Random(seed)
    evs: List[dict] = []
    evs += _poisson(
        rng, gossip_rate * rate_scale, 0.0, duration_s,
        lambda t, r: {"t": t, "kind": "unaggregated", "n_sets": 1,
                      "pubkeys": 1, "messages": 1, "path": "submit"},
    )
    t = rng.uniform(0.0, batch_every_s)
    while t < duration_s:
        n = rng.choice(batch_sets)
        evs.append({
            "t": round(t, 6), "kind": "backfill", "n_sets": int(n),
            "pubkeys": committee, "messages": max(1, int(n) // 8),
            "path": "submit",
        })
        t += batch_every_s * rng.uniform(0.7, 1.3)
    return _finish(evs)


def saturation_ramp(
    duration_s: float = 20.0,
    seed: int = 0,
    rate_scale: float = 1.0,
    committee: int = 8,
    start_rate: float = 5.0,
    end_rate: float = 80.0,
    agg_fraction: float = 0.25,
    backfill_every_s: float = 4.0,
    backfill_sets: int = 48,
    slice_s: float = 0.5,
) -> List[dict]:
    """The capacity-certification shape (ISSUE 14): gossip arrival rate
    rising LINEARLY from ``start_rate`` to ``end_rate`` events/s over
    the trace (an inhomogeneous Poisson process, realized as
    piecewise-constant ``slice_s`` slices with the rate interpolated at
    each slice midpoint — deterministic under seed like every other
    generator), split ``agg_fraction`` committee-width aggregates /
    the rest single-pubkey attestations, over a bulk-backfill FLOOR
    (large deadline-insensitive submissions every ``backfill_every_s``).
    Somewhere along the ramp demand crosses serving capacity: the trace
    the headroom estimator is certified against (headroom must cross
    below its alert threshold and an ``slo_burn`` event must journal
    BEFORE the first deadline-miss burst — the estimator is predictive,
    not retrospective), and the missing precursor for ROADMAP item 2's
    bulk-QoS admission-control work."""
    rng = random.Random(seed)
    evs: List[dict] = []
    t0 = 0.0
    while t0 < duration_s:
        t1 = min(duration_s, t0 + slice_s)
        frac = ((t0 + t1) / 2.0) / duration_s
        rate = (start_rate + (end_rate - start_rate) * frac) * rate_scale
        evs += _poisson(
            rng, rate * (1.0 - agg_fraction), t0, t1,
            lambda t, r: {"t": t, "kind": "unaggregated", "n_sets": 1,
                          "pubkeys": 1, "messages": 1, "path": "submit"},
        )
        evs += _poisson(
            rng, rate * agg_fraction, t0, t1,
            lambda t, r: {"t": t, "kind": "aggregate", "n_sets": 1,
                          "pubkeys": committee, "messages": 1,
                          "path": "submit"},
        )
        t0 = t1
    t = rng.uniform(0.0, backfill_every_s)
    while t < duration_s:
        evs.append({
            "t": round(t, 6), "kind": "backfill",
            "n_sets": int(backfill_sets), "pubkeys": committee,
            "messages": max(1, int(backfill_sets) // 8),
            "path": "submit",
        })
        t += backfill_every_s * rng.uniform(0.8, 1.2)
    return _finish(evs)


def bulk_backfill_under_gossip(
    duration_s: float = 12.0,
    seed: int = 0,
    rate_scale: float = 1.0,
    committee: int = 8,
    bulk_start_frac: float = 0.25,
    bulk_every_s: float = 0.4,
    bulk_sets: Tuple[int, ...] = (96, 128, 192),
) -> List[dict]:
    """The ISSUE 15 acceptance shape (ROADMAP item 2 names it): FULL
    gossip steady-state — the same three Poisson streams as
    ``gossip_steady`` with the same seed derivation, so a gossip-only
    baseline run of ``gossip_steady(duration_s, seed, rate_scale,
    committee)`` carries byte-identical gossip arrivals — plus a
    SATURATING bulk stream (``qos="bulk"``, kind ``backfill``): large
    contiguous chain-segment submissions every ``bulk_every_s`` from
    ``bulk_start_frac * duration_s`` onward, offering far more sets/s
    than any deadline-class box serves. The leading bulk-free window is
    the within-trace control; the robustness contract under replay is
    that gossip's per-kind p99 and deadline-miss ratio in the bulk
    window are indistinguishable from the baseline run, bulk drains at
    gossip idle onto the big rungs, and the admission controller
    journals ``bulk_throttle`` before any gossip miss burst."""
    rng = random.Random(seed)
    # IDENTICAL gossip arrivals to gossip_steady(seed): same helper,
    # same derived seed — the isolation test depends on this equality
    evs = gossip_steady(
        duration_s=duration_s, seed=seed, rate_scale=rate_scale,
        committee=committee,
    )
    t = bulk_start_frac * duration_s
    while t < duration_s:
        n = rng.choice(bulk_sets)
        evs.append({
            # the REAL wired bulk callers' geometry (chain-segment
            # import + checkpoint backfill verify proposal signatures):
            # K=1, one DISTINCT message per set — exactly the shape the
            # bulk AOT rungs (512,1,512)/(256,1,256) serve, so a warm
            # staged replay of this trace exercises the big-rung drain
            # path instead of shedding every bulk flush to the CPU
            # fallback (a committee-carrying K=8/M=n//8 shape could
            # never route to the shipped bulk rungs)
            "t": round(t, 6), "kind": "backfill", "n_sets": int(n),
            "pubkeys": 1, "messages": int(n),
            "path": "submit", "qos": "bulk",
        })
        t += bulk_every_s * rng.uniform(0.8, 1.2)
    return _finish(evs)


# Generator catalogue: every entry documented in docs/TRAFFIC_REPLAY.md
# (linted by tests/test_zgate4_metrics_lint.py).
GENERATORS: Dict[str, Callable[..., List[dict]]] = {
    "gossip_steady": gossip_steady,
    "epoch_boundary_flood": epoch_boundary_flood,
    "sync_committee_period": sync_committee_period,
    "bulk_backfill": bulk_backfill,
    "saturation_ramp": saturation_ramp,
    "bulk_backfill_under_gossip": bulk_backfill_under_gossip,
}


# ---------------------------------------------------------------------------
# Lockstep replay: the flush policy as a pure function of the trace
# ---------------------------------------------------------------------------


class ReplaySubmission:
    """The planner-facing submission shape (``.kind`` + ``.sets``),
    shared by the lockstep simulator and the timed driver's payload
    pre-build."""

    __slots__ = ("kind", "sets")

    def __init__(self, kind: str, sets: list):
        self.kind = kind
        self.sets = sets


def lockstep_replay(
    events: List[dict],
    deadline_ms: float = 25.0,
    max_batch_sets: int = 256,
    planner: Optional[FlushPlanner] = None,
    warm_rungs: Optional[list] = None,
    shards: Optional[list] = None,
    bulk_flush_sets: int = 512,
    bulk_linger_ms: float = 100.0,
    slot_s: float = 2.0,
    slots_per_epoch: int = 32,
    agg_min_repeats: int = 2,
    lookahead: bool = False,
) -> dict:
    """Deterministic virtual replay: walk the trace in arrival order and
    apply the scheduler's EXACT drain/flush policy (deadline measured
    from the oldest pending submission; bucket-full at
    ``max_batch_sets``; whole-submission drains; shutdown drain at the
    end) with the shape-aware planner deciding every flush — no
    threads, no wall clock, no jax. ``qos="bulk"`` events (ISSUE 15)
    enqueue on the modeled bulk queue, which drains in
    ``bulk_flush_sets`` chunks ONLY while the deadline queue is idle —
    full chunks immediately at idle, partial ones after
    ``bulk_linger_ms`` — mirroring the batcher's never-preempt trigger
    priority (admission control is live-signal-driven and deliberately
    NOT modeled: headroom needs a wall clock). The returned report
    (submission sequence, per-flush plan shapes, per-kind set counts,
    and a sha256 digest over all of it) is a pure function of (trace,
    parameters): the determinism property
    ``tests/test_traffic_replay.py`` pins across processes.

    Chain-time (ISSUE 17): virtual trace time maps deterministically to
    slots (``slot = t // slot_s``), and the report carries a per-slot
    block — arrivals, sets, flushes and committee sightings per slot —
    so a flood slot is individually visible instead of smeared into the
    window average. Committee sightings model the key table's
    aggregate-cache admission on events carrying ``validators``: a
    tuple's first ``agg_min_repeats`` consults are ``first`` sightings
    (host EC sum territory), every later one a collapsed ``hit``
    (``DEFAULT_AGG_MIN_REPEATS`` in crypto/device/key_table.py). The
    model is local and pure — the lockstep simulator never touches the
    process-global slot ledger.

    Duty-lookahead (ISSUE 19): with ``lookahead=True`` the admission
    model is prewarmed the way the duty-lookahead worker prewarms the
    key table — every committee tuple's epoch shuffle is deterministic
    an epoch ahead, so each tuple counts as already-admitted (its seen
    count starts at ``agg_min_repeats``) BEFORE its first arrival.
    First sightings collapse to hits and the report's ``chain_time``
    gains a ``lookahead`` block (prewarmed tuple count, per-epoch
    breakdown). With ``lookahead=False`` (the default) the report body
    is byte-identical to earlier releases — the digest-determinism
    property is preserved."""
    planner = planner or FlushPlanner()
    deadline_s = deadline_ms / 1000.0
    bulk_linger_s = bulk_linger_ms / 1000.0
    slot_s = max(1e-9, float(slot_s))
    slots_per_epoch = max(1, int(slots_per_epoch))
    slots: Dict[int, dict] = {}
    committee_seen: Dict[tuple, int] = {}
    lookahead_epochs: Dict[int, int] = {}
    if lookahead:
        # the worker's effect, replayed pure: every committee's epoch
        # assignment is known ahead of its first signature, so each
        # distinct tuple starts at the admission threshold (the
        # pre-inserted aggregate row) — attributed to the epoch of its
        # first arrival for the report
        for ev in sorted(events, key=lambda e: e["t"]):
            vals = ev.get("validators")
            if vals and len(vals) > 1:
                key = tuple(vals)
                if key not in committee_seen:
                    committee_seen[key] = agg_min_repeats
                    e = int(ev["t"] // slot_s) // slots_per_epoch
                    lookahead_epochs[e] = lookahead_epochs.get(e, 0) + 1
    t_end = 0.0

    def slot_row(t: float) -> dict:
        s = int(t // slot_s)
        row = slots.get(s)
        if row is None:
            row = slots[s] = {
                "slot": s,
                "epoch": s // slots_per_epoch,
                "arrivals": 0,
                "sets": 0,
                "bypass_sets": 0,
                "flushes": 0,
                "flushed_sets": 0,
                "bulk_sets": 0,
                "sightings_first": 0,
                "sightings_hit": 0,
            }
        return row
    pending: deque = deque()  # (ReplaySubmission, arrival t)
    pending_sets = 0
    bulk_pending: deque = deque()  # (ReplaySubmission, arrival t)
    bulk_pending_sets = 0
    # the virtual arrival time at which the bulk queue last crossed the
    # full-chunk threshold (None while below): a full chunk's idle-time
    # drain is due from that moment, not from the oldest arrival
    bulk_full_at: Optional[float] = None
    submissions: List[list] = []
    bypasses: List[list] = []
    flushes: List[dict] = []
    set_totals: Dict[str, int] = {}
    bulk_set_total = 0

    def drain_one(
        trigger: str, qos: str = "deadline", t: float = 0.0
    ) -> None:
        nonlocal pending_sets, bulk_pending_sets, bulk_full_at
        bulk = qos == "bulk"
        queue = bulk_pending if bulk else pending
        cap = bulk_flush_sets if bulk else max_batch_sets
        subs: List[ReplaySubmission] = []
        n = 0
        while queue:
            nxt, _t = queue[0]
            if subs and n + len(nxt.sets) > cap:
                break
            sub, _t = queue.popleft()
            subs.append(sub)
            n += len(sub.sets)
        if bulk:
            bulk_pending_sets -= n
            if bulk_pending_sets < bulk_flush_sets:
                bulk_full_at = None
        else:
            pending_sets -= n
        plan = planner.plan(
            subs, warm_rungs=warm_rungs, shards=shards, qos=qos
        )
        row = slot_row(t)
        row["flushes"] += 1
        row["flushed_sets"] += n
        if bulk:
            row["bulk_sets"] += n
        flushes.append({
            "trigger": trigger,
            "qos": qos,
            "slot": row["slot"],
            "n_submissions": len(subs),
            "n_sets": n,
            "mode": plan.mode,
            "rungs": plan.rungs_label(),
            "dp_shards": plan.shards_used(),
            "live_lanes": plan.live,
            "padded_lanes": plan.padded,
            "waste": round(plan.waste(), 4),
            # per-element byte accounting (ISSUE 8): what the raw packer
            # would ship per sub-batch — tools/transfer_report.py turns
            # this into per-kind H2D attribution without jax
            "sub_batches": [
                {
                    "kinds": sb.kinds,
                    "rung": list(sb.rung),
                    "shard": sb.shard,
                    "n_sets": sb.n_sets,
                    "pk_slots": sb.pk_slots,
                    "m_req": sb.m_req,
                    "est_h2d_bytes": sb.est_h2d_bytes,
                    "est_live_h2d_bytes": sb.est_live_h2d_bytes,
                }
                for sb in plan.sub_batches
            ],
        })

    def advance_to(t_limit: float) -> None:
        """Run every drain due strictly before ``t_limit``, in virtual-
        time order: gossip deadline drains first; bulk drains only in
        the windows where the deadline queue is empty (the batcher's
        never-preempt rule — a gossip submission PARKS bulk until its
        own deadline passes)."""
        while True:
            if pending:
                td = pending[0][1] + deadline_s
                if td <= t_limit:
                    drain_one("deadline", t=td)
                    continue
                return  # gossip pending blocks bulk past t_limit
            if bulk_pending:
                if bulk_full_at is not None:
                    tb = bulk_full_at
                else:
                    tb = bulk_pending[0][1] + bulk_linger_s
                if tb <= t_limit:
                    drain_one("bulk", qos="bulk", t=tb)
                    continue
            return

    for ev in sorted(events, key=lambda e: e["t"]):
        advance_to(ev["t"])
        t_end = max(t_end, ev["t"])
        row = slot_row(ev["t"])
        row["arrivals"] += 1
        row["sets"] += ev["n_sets"]
        vals = ev.get("validators")
        if vals and len(vals) > 1:
            # the key table's admission policy, replayed pure: consult
            # j of a tuple is a hit only once j > agg_min_repeats
            key = tuple(vals)
            prior = committee_seen.get(key, 0)
            committee_seen[key] = prior + 1
            if prior >= agg_min_repeats:
                row["sightings_hit"] += 1
            else:
                row["sightings_first"] += 1
        if ev["path"] == "verify_now":
            row["bypass_sets"] += ev["n_sets"]
            bypasses.append([ev["kind"], ev["n_sets"]])
            set_totals[ev["kind"]] = (
                set_totals.get(ev["kind"], 0) + ev["n_sets"]
            )
            continue
        sets = synthetic_sets(
            ev["kind"], ev["n_sets"], ev["pubkeys"], ev["messages"]
        )
        set_totals[ev["kind"]] = set_totals.get(ev["kind"], 0) + ev["n_sets"]
        if ev.get("qos", "deadline") == "bulk":
            bulk_pending.append((ReplaySubmission(ev["kind"], sets), ev["t"]))
            bulk_pending_sets += ev["n_sets"]
            bulk_set_total += ev["n_sets"]
            submissions.append([ev["kind"], ev["n_sets"], "bulk"])
            if (
                bulk_full_at is None
                and bulk_pending_sets >= bulk_flush_sets
            ):
                bulk_full_at = ev["t"]
            continue
        pending.append((ReplaySubmission(ev["kind"], sets), ev["t"]))
        pending_sets += ev["n_sets"]
        submissions.append([ev["kind"], ev["n_sets"]])
        while pending_sets >= max_batch_sets:
            drain_one("full", t=ev["t"])
    while pending:
        drain_one("shutdown", t=t_end)
    while bulk_pending:
        drain_one("shutdown", qos="bulk", t=t_end)

    first_total = sum(r["sightings_first"] for r in slots.values())
    hit_total = sum(r["sightings_hit"] for r in slots.values())
    sighting_total = first_total + hit_total
    body = {
        "n_events": len(events),
        "deadline_ms": round(deadline_ms, 3),
        "max_batch_sets": max_batch_sets,
        "bulk_flush_sets": bulk_flush_sets,
        "bulk_linger_ms": round(bulk_linger_ms, 3),
        "submissions": submissions,
        "bypasses": bypasses,
        "flushes": flushes,
        "set_totals": dict(sorted(set_totals.items())),
        "bulk": {
            "sets_offered": bulk_set_total,
            "flushes": sum(1 for f in flushes if f["qos"] == "bulk"),
        },
        # slot-aligned view (ISSUE 17): one row per virtual slot, so a
        # flood slot is individually visible in the report and its
        # digest
        "chain_time": {
            "slot_s": round(slot_s, 6),
            "slots_per_epoch": slots_per_epoch,
            "n_slots": len(slots),
            "agg_min_repeats": agg_min_repeats,
            "committee_sightings": sighting_total,
            "first_sightings": first_total,
            "sighting_hits": hit_total,
            "first_sighting_hit_ratio": (
                round(hit_total / sighting_total, 4)
                if sighting_total else None
            ),
        },
        "slots": [slots[s] for s in sorted(slots)],
    }
    if lookahead:
        # present ONLY when on: the lookahead-off body (and digest)
        # stays byte-identical to earlier releases
        body["chain_time"]["lookahead"] = {
            "enabled": True,
            "committees": sum(lookahead_epochs.values()),
            "epochs": [
                [e, lookahead_epochs[e]] for e in sorted(lookahead_epochs)
            ],
        }
    digest = hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()
    return {"mode": "lockstep", **body, "digest": digest}
