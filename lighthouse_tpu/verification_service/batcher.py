"""Cross-caller continuous batching for BLS signature-set verification.

The cost model (docs/COST_MODEL.md) and the padding-waste gauge from the
device telemetry say the same thing: per-batch fixed overhead (host pack,
dispatch, padded lanes) amortizes only at large B, yet every gossip
caller — attestation batches, sync-committee batches, single-item API
paths — issues its own synchronous ``bls.verify_signature_sets`` call,
so device batches are capped at ONE caller's burst. This module is the
continuous-batching layer between the verifiers and the backend
("Performance of EdDSA and BLS Signatures in Committee-Based Consensus",
PAPERS.md: batch-aggregated BLS verification is the throughput lever):
concurrent producers ``submit(sets, kind)`` and a flush thread fuses
their submissions into shared batches whose padded size lands on the
same ``_round_up`` bucket ladder the device packers use, so the XLA
recompile count stays bounded across traffic shapes.

Semantics contract (the part that makes fusing safe): per-submission
verdicts are IDENTICAL to a direct per-caller ``verify_signature_sets``
call.

* A fused batch that verifies True proves every member submission would
  verify True on its own (the standard 2^-64 random-linear-combination
  soundness — the same argument the existing batch-then-fallback caller
  paths already rely on).
* A fused batch that verifies False is split-and-retried (bisection):
  halves re-verify until the poisoned submission(s) are isolated, and a
  LEAF verdict is literally the direct call ``verify(sets_of_that_
  submission)`` — byte-identical by construction. One bad attestation
  can therefore never reject another caller's block.
* An empty submission resolves False immediately (``verify_signature_
  sets([])`` is False) and never joins a fused batch, where its absence
  of sets would otherwise let a neighbour's verdict stand in for it.

Flush triggers: geometry-bucket-full (pending sets reached
``max_batch_sets``), deadline (oldest submission waited ``deadline_ms``),
explicit ``flush()``, and shutdown drain.

Backpressure: the pending queue is bounded by ``max_queue_sets``. A
submission that would overflow it is SHED to caller fallback — verified
synchronously in the caller's thread (identical verdict, no fusing) —
and journaled as a ``scheduler_shed`` flight-recorder event, so overload
degrades to exactly the pre-scheduler behavior instead of queueing
without bound.

Latency-critical callers (block verification) use :meth:`verify_now`,
a counted synchronous bypass that never waits on a deadline.

Bulk QoS class (ISSUE 15): ``submit(sets, kind, qos="bulk")`` queues
deadline-INSENSITIVE work — chain-segment backfill, historical sync,
slasher-style ingest — on a SEPARATE bounded queue that the flush
thread services only when the deadline class is idle (the gossip queue
is empty and no gossip trigger is due), draining up to
``bulk_flush_sets`` (default 512) at a time so the planner packs it
onto the largest warm rungs (B=256/512 — where DP_SCALING.json shows
the best sets/s, exactly where the committee batch-verification cost
model says batching gains are largest for this class). A bulk flush
NEVER preempts gossip: the trigger priority is shutdown > explicit >
full > deadline > bulk, and a trickle of bulk lingers
``bulk_linger_ms`` (default 100) to accumulate a big batch instead of
shredding the rung ladder. Admission is governed by
:class:`.admission.BulkAdmissionController`: when the live
``capacity_headroom_ratio`` drops below its floor or a gossip kind's
SLO burn alert latches, bulk flushing and admission PAUSE (one
``bulk_throttle`` journal event per excursion) and resume with
hysteresis (``bulk_resume``). Overflow of the bulk queue degrades the
submission to its CALLER's thread — the self-paced pre-scheduler
behavior — never to gossip's flush thread. Bulk verdicts feed the SLO
surface under their own class (path ``bulk`` / ``bulk_shed``,
``qos="bulk"``): quantiles are visible, but they can neither miss a
deadline nor dilute gossip's burn windows (slo.py). The robustness
contract: under ANY bulk load, gossip's verdict-latency SLO is
indistinguishable from the no-bulk baseline, and losing headroom sheds
bulk first, gracefully, with full observability
(``tests/test_bulk_qos.py``).

Verdict-latency SLO (ISSUE 7): every submission's end-to-end
submit→future-resolution latency is measured on EVERY resolution path —
``fused`` (single-rung flush), ``sub_batch`` (planned split), ``bisection``
(split-and-retry leaf), ``shed`` (backpressure fallback in the caller's
thread), ``bypass`` (``verify_now``), ``fallback`` (compile-service
CPU-native shed), ``empty`` (degenerate immediate False) — into
``verification_scheduler_verdict_latency_seconds{kind,path}``, so tail
numbers cannot be flattered by dropping the slow paths. A verdict that
lands after ``deadline_ms`` (measured from SUBMISSION time, regardless
of which flush trigger fired — the deadline used to be only a flush
trigger, so a flush whose device time blew the budget was invisible)
ticks ``verification_scheduler_deadline_misses_total{kind}`` and
journals a ``deadline_miss`` event. A rolling per-kind window
(:mod:`.slo`) serves p50/p99 and miss ratio to ``/lighthouse/health``'s
``slo`` block and to the traffic-replay harness
(docs/TRAFFIC_REPLAY.md).

The miss threshold is ``slo_grace * deadline_ms`` (default 2x,
``LIGHTHOUSE_TPU_SCHED_SLO_GRACE``), NOT ``deadline_ms`` itself: the
deadline is the maximum queue wait by construction — the trigger fires
exactly when the oldest submission has waited that long — so a literal
``latency > deadline`` threshold would brand the oldest member of every
deadline-triggered flush a miss on trigger-timing noise alone (trickle
traffic would read 100% miss with an instant backend). With the 2x
budget, the oldest member of a deadline flush misses exactly when the
BACKEND took longer than the deadline — the invisible case the SLO
layer exists to expose.

Flush planning (ISSUE 6): a flush is no longer padded wholesale onto
one ladder rung. The shape-aware planner (:mod:`.planner`) partitions
the fused submission list into kind-homogeneous, B-axis bin-packed
sub-batches when that reduces total padded device lanes (B*K*M), and
falls back to the legacy single-rung plan when it cannot win — or when
the split would leave a warm single rung for cold ones. Each sub-batch
gets its own backend dispatch, its own compile-service routing
decision, and its own bisection scope; submissions stay atomic, so
per-submission futures and verdict identity are untouched.

Dispatch watchdog (ISSUE 13): a sharded sub-batch dispatch can HANG —
a wedged device tunnel, a runaway injected stall — and before the
watchdog that hang wedged the flush thread (or its dp worker) forever.
With a deadline configured (``watchdog_s`` / env
``LIGHTHOUSE_TPU_SCHED_WATCHDOG_S``), each sharded dispatch runs on a
reaper-monitored thread: past the deadline the dispatch is abandoned
(daemon thread; its eventual result is discarded) and converted into
the EXISTING chip-loss failover path — the same sets re-verify on a
failover shard, a success drops the hung shard into probation
(``shard_lost`` → recovery, crypto/device/mesh.py) and verdict
identity holds because the re-verify IS the verdict. A failover that
also times out means the WORK hangs: the shard keeps its health and
:class:`WatchdogTimeout` propagates like any backend raise. The
deadline is OFF by default (0) — a cold dispatch legitimately blocks
minutes on an XLA compile, so arming it is an operator decision (set
it above the worst-case cold compile, or run a prebaked compile
cache); the ``verify_now`` bypass has its own knob
(``LIGHTHOUSE_TPU_SCHED_WATCHDOG_BYPASS_S``), also default off. Every
reap ticks ``verification_scheduler_watchdog_reaped_total{shard}``
and journals a ``watchdog_reaped`` event. The bypass additionally
gains the failover contract (ISSUE 13 satellite): a failure during a
``verify_now`` dispatch on the primary shard retries once on a
failover shard instead of propagating into the block path.

Cold-bucket protection (ISSUE 5): with a
:class:`~lighthouse_tpu.compile_service.CompileService` attached, every
flush (and every ``verify_now`` bypass) is routed first — a batch whose
padded bucket has no compiled staged program is served through the
service's counted synchronous CPU-native fallback (identical verdict,
``cold_route`` journal event) instead of blocking a gossip-hot thread
on a multi-minute XLA compile; the service compiles the rung in the
background and subsequent flushes run on device. Without a service
attached (the default, and every pre-existing test) behavior is
byte-identical to before.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

from ..crypto import bls
from ..utils import (
    flight_recorder,
    metrics,
    pipeline_profiler,
    slot_ledger,
    tracing,
    transfer_ledger,
)
from .admission import BulkAdmissionController
from .slo import SloTracker

# Mirrors crypto/device/bls._round_up's choices without importing the
# device stack (jax) here; tests/test_verification_scheduler.py pins the
# two ladders equal so they cannot drift apart. 48/96/192 are the
# intermediate rungs the flush planner (planner.py) bin-packs onto —
# observed traffic shapes (the 48-set headline flush, 96/192 backfill
# bursts) that a pure power-of-two ladder padded up to 64/128/256.
BUCKET_LADDER = (1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256, 512, 1024)


def round_up_bucket(n: int, ladder: Sequence[int] = BUCKET_LADDER) -> int:
    """Padded batch size for ``n`` fused sets — same ladder the device
    packers pad to, so a flush of any size maps onto a bounded set of
    compiled shapes."""
    for c in ladder:
        if n <= c:
            return c
    top = ladder[-1]
    return ((n + top - 1) // top) * top


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


_FUSED_BATCHES = metrics.counter_vec(
    "verification_scheduler_fused_batches_total",
    "backend batches dispatched (one per sub-batch under a planned "
    "split), labeled by the sorted caller-kind mix — mixed labels "
    "(e.g. aggregate+sync_message+unaggregated) appear on single-rung "
    "flushes; a planned split dispatches kind-homogeneous labels",
    ("kinds",),
)
_SUBMISSIONS = metrics.counter_vec(
    "verification_scheduler_submissions_total",
    "submissions resolved, by caller kind and verdict outcome",
    ("kind", "outcome"),
)
_SETS_TOTAL = metrics.counter_vec(
    "verification_scheduler_sets_total",
    "signature sets fused into shared batches, per caller kind",
    ("kind",),
)
_FLUSHES = metrics.counter_vec(
    "verification_scheduler_flushes_total",
    "batch flushes by trigger (full = bucket ceiling reached, deadline = "
    "oldest submission hit the latency budget, explicit, shutdown)",
    ("trigger",),
)
_OCCUPANCY = metrics.gauge(
    "verification_scheduler_batch_occupancy_ratio",
    "live lanes / padded lanes (B*K*M, the shared formula in "
    "verification_service/planner.py) of the most recent flush's "
    "DEVICE-dispatched sub-batches; sub-batches shed to the CPU "
    "fallback are excluded",
)
_PAD_WASTE = metrics.gauge(
    "verification_scheduler_padding_waste_ratio",
    "1 - occupancy of the most recent device-dispatched flush plan "
    "(the lanes the device pays for that no caller asked for) — the "
    "SAME formula as bls_device_padding_waste_ratio (equality pinned "
    "per geometry by test; under a planned split this gauge aggregates "
    "the whole plan while the device gauge holds its last sub-batch)",
)
_QUEUE_DEPTH = metrics.gauge(
    "verification_scheduler_queue_depth",
    "signature sets currently queued awaiting a flush",
)
_QUEUE_WAIT = metrics.histogram(
    "verification_scheduler_queue_wait_seconds",
    "submit-to-dispatch wait per DEADLINE-class submission (bounded by "
    "the deadline) — bulk submissions are excluded (ISSUE 15): a bulk "
    "wait spans linger + gossip-busy windows + throttle excursions by "
    "design and would explode this histogram's tail while gossip is "
    "perfectly healthy; bulk wait is visible in "
    "verification_scheduler_verdict_latency_seconds{path=bulk} and the "
    "bulk queue-depth gauge",
)
_BISECTIONS = metrics.counter(
    "verification_scheduler_bisections_total",
    "split-and-retry group verifications run to isolate poisoned "
    "submissions after a fused batch failed",
)
_SHED = metrics.counter_vec(
    "verification_scheduler_shed_total",
    "submissions shed to synchronous caller fallback on a full queue",
    ("kind",),
)
_BYPASS = metrics.counter_vec(
    "verification_scheduler_bypass_total",
    "synchronous verify_now calls (latency-critical callers, e.g. block "
    "verification) that skip the fusing queue",
    ("kind",),
)
_PLANS = metrics.counter_vec(
    "verification_scheduler_plans_total",
    "flush-planner decisions: planned = kind-homogeneous bin-packed "
    "sub-batches, single = the legacy one-rung flush (planner "
    "disabled, could not win, or would go cold while the single rung "
    "is warm)",
    ("mode",),
)
_PLAN_SUBBATCHES = metrics.counter_vec(
    "verification_scheduler_plan_subbatches_total",
    "sub-batches dispatched by the flush planner, labeled by the "
    "sub-batch's sorted caller-kind mix (kind-homogeneous under a "
    "planned split)",
    ("kind",),
)
_PLAN_LANES = metrics.counter_vec(
    "verification_scheduler_plan_lanes_total",
    "device lanes (B*K*M cells) of DEVICE-dispatched sub-batches: live "
    "= lanes callers asked for, padded = lanes of the rung the flush "
    "actually routed to (the shared padding-waste formula, "
    "verification_service/planner.py). Sub-batches shed to the CPU "
    "fallback are not counted — the device paid nothing for them",
    ("lane",),
)
_VERDICT_LATENCY = metrics.histogram_vec(
    "verification_scheduler_verdict_latency_seconds",
    "end-to-end submit-to-verdict latency per submission, on EVERY "
    "resolution path: fused (single-rung flush), sub_batch (planned "
    "split), bisection (split-and-retry leaf), shed (backpressure "
    "caller-thread fallback), bypass (verify_now), fallback "
    "(compile-service CPU-native shed), empty (immediate False), bulk "
    "(bulk-class idle-time flush), bulk_shed (bulk-queue overflow "
    "degraded to the caller's thread) — the submitter-experienced "
    "latency the SLO layer certifies (docs/TRAFFIC_REPLAY.md)",
    ("kind", "path"),
)
_DP_SHARDS = metrics.gauge(
    "verification_scheduler_dp_shards",
    "healthy dp mesh shards the flush planner currently packs onto "
    "(crypto/device/mesh.py; 0 = no mesh attached — single-device "
    "dispatch). Losing a chip decrements this and the node keeps "
    "serving on the rest",
)
_DP_SUBBATCHES = metrics.counter_vec(
    "verification_scheduler_dp_subbatches_total",
    "sharded sub-batches dispatched per dp shard (the shard axis of a "
    "(dp x rung) flush plan; unsharded single-device dispatches are "
    "not counted here — see verification_scheduler_plan_subbatches_"
    "total for the rung axis)",
    ("shard",),
)
_DP_SETS = metrics.counter_vec(
    "verification_scheduler_dp_sets_total",
    "signature sets dispatched per dp shard by the flush planner — "
    "with bls_device_shard_sets_total this splits the aggregate "
    "sets/s story into scheduler-side and device-side halves",
    ("shard",),
)
_WATCHDOG_REAPED = metrics.counter_vec(
    "verification_scheduler_watchdog_reaped_total",
    "sharded dispatches abandoned by the watchdog after exceeding the "
    "configured deadline (each converts into the chip-loss failover "
    "path: the same sets re-verify on a failover shard and the hung "
    "chip enters probation — see the watchdog_reaped journal kind)",
    ("shard",),
)
_ARRIVALS = metrics.counter_vec(
    "verification_scheduler_arrival_sets_total",
    "signature sets ARRIVING at the scheduler per caller kind and entry "
    "path (submit = the fusing queue, incl. submissions later shed; "
    "bypass = verify_now), counted at submission time — NOT at flush "
    "time like verification_scheduler_sets_total, whose rate saturates "
    "at serving capacity exactly when the arrival rate matters most. "
    "The capacity sampler (utils/timeseries.py) rates this family into "
    "capacity_arrival_sets_per_sec, the utilization numerator "
    "(ISSUE 14)",
    ("kind", "path"),
)
_BULK_QUEUE_DEPTH = metrics.gauge(
    "verification_scheduler_bulk_queue_depth",
    "signature sets queued in the bulk QoS class awaiting an idle-time "
    "flush (ISSUE 15) — bounded by the bulk queue knob; overflow "
    "degrades to the caller's thread, so this gauge can saturate but "
    "never grow without bound. The deadline class's queue is "
    "verification_scheduler_queue_depth",
)
_BULK_SETS = metrics.counter_vec(
    "verification_scheduler_bulk_sets_total",
    "signature sets SERVED by the bulk class per caller kind: queued "
    "drains counted at flush time, overflow sheds counted when their "
    "caller-thread verify resolves (shed bulk is still bulk service — "
    "the capacity estimator's utilization numerator must see it). With "
    "verification_scheduler_sets_total (flushed, both classes) this "
    "splits served throughput by QoS class; the capacity sampler "
    "rates it into capacity_bulk_sets_per_sec",
    ("kind",),
)
_BULK_SHED = metrics.counter_vec(
    "verification_scheduler_bulk_shed_total",
    "bulk submissions degraded to synchronous verification in their "
    "CALLER's thread on bulk-queue overflow (the documented degradation "
    "order: bulk sheds first, self-paced, never onto gossip's flush "
    "thread)",
    ("kind",),
)
_DEADLINE_MISSES = metrics.counter_vec(
    "verification_scheduler_deadline_misses_total",
    "submissions whose verdict landed after the SLO budget (slo_grace x "
    "deadline_ms, default 2x — queue-wait allowance plus equal service "
    "headroom) measured from SUBMISSION time, regardless of which flush "
    "trigger fired; each miss journals a deadline_miss flight-recorder "
    "event. The deadline alone is the flush TRIGGER; this family is "
    "what makes it an SLO",
    ("kind",),
)


def _mesh_module():
    """The device-mesh module (ISSUE 11), reached lazily so this module
    stays jax-free at import: mesh.py itself imports jax only inside
    dispatch, and a jax-free test mesh (placeholder devices) never
    touches it at all."""
    from ..crypto.device import mesh as mesh_mod

    return mesh_mod


def _active_mesh():
    try:
        return _mesh_module().get_active_mesh()
    except Exception:
        return None


class WatchdogTimeout(RuntimeError):
    """A sharded dispatch exceeded the watchdog deadline and was
    abandoned — handled exactly like a raised dispatch (failover
    decides whether the chip or the work is the problem)."""


class _Submission:
    __slots__ = ("kind", "sets", "future", "submitted_at", "qos")

    def __init__(self, kind: str, sets: List, qos: str = "deadline"):
        self.kind = kind
        self.sets = sets
        self.qos = qos
        self.future: Future = Future()
        self.submitted_at = time.monotonic()


class VerificationScheduler:
    """Thread-safe cross-caller batcher: ``submit(sets, kind) -> Future``
    fuses submissions from concurrent producers into shared
    ``verify_signature_sets`` batches (see module docstring for the
    verdict-identity contract)."""

    def __init__(
        self,
        verify_fn: Optional[Callable[[list], bool]] = None,
        deadline_ms: float | None = None,
        max_batch_sets: int | None = None,
        max_queue_sets: int | None = None,
        compile_service=None,
        plan_flushes: bool | None = None,
        flush_planner=None,
        slo_grace: float | None = None,
        watchdog_s: float | None = None,
        watchdog_bypass_s: float | None = None,
        bulk_max_queue_sets: int | None = None,
        bulk_flush_sets: int | None = None,
        bulk_linger_ms: float | None = None,
        bulk_admission: Optional[BulkAdmissionController] = None,
    ):
        self._verify = verify_fn or bls.verify_signature_sets
        # warm-shape router (compile_service/service.py); None = every
        # flush dispatches directly, cold compiles and all
        self._compile_service = compile_service
        # shape-aware flush planner (planner.py): partitions a fused
        # flush into kind-homogeneous bin-packed sub-batches when that
        # beats the legacy single-rung pad-up. plan_flushes=False (or
        # LIGHTHOUSE_TPU_SCHED_PLANNER=0) pins the legacy plan. Lazy
        # import: planner.py imports this module's ladder.
        from . import planner as _planner_mod

        self._planner = (
            flush_planner
            if flush_planner is not None
            else _planner_mod.FlushPlanner(enabled=plan_flushes)
        )
        self.deadline_s = (
            deadline_ms
            if deadline_ms is not None
            else _env_float("LIGHTHOUSE_TPU_SCHED_DEADLINE_MS", 25.0)
        ) / 1000.0
        self.max_batch_sets = int(
            max_batch_sets
            if max_batch_sets is not None
            else _env_int("LIGHTHOUSE_TPU_SCHED_MAX_BATCH", 256)
        )
        self.max_queue_sets = int(
            max_queue_sets
            if max_queue_sets is not None
            else _env_int("LIGHTHOUSE_TPU_SCHED_MAX_QUEUE", 2048)
        )
        # verdict-SLO budget multiplier (see module docstring: deadline
        # = max queue wait by construction, so the budget adds service
        # headroom; <1x would brand trigger noise a miss)
        self.slo_grace = max(
            1.0,
            slo_grace
            if slo_grace is not None
            else _env_float("LIGHTHOUSE_TPU_SCHED_SLO_GRACE", 2.0),
        )
        # dispatch watchdog deadlines (ISSUE 13; module docstring): 0 =
        # off. Off by default — a cold dispatch legitimately blocks
        # minutes on an XLA compile, so the deadline is an operator
        # decision (bypass has its own knob, also default off)
        self.watchdog_s = float(
            watchdog_s
            if watchdog_s is not None
            else _env_float("LIGHTHOUSE_TPU_SCHED_WATCHDOG_S", 0.0)
        )
        self.watchdog_bypass_s = float(
            watchdog_bypass_s
            if watchdog_bypass_s is not None
            else _env_float("LIGHTHOUSE_TPU_SCHED_WATCHDOG_BYPASS_S", 0.0)
        )
        self._watchdog_reaped = 0
        # bulk QoS class (ISSUE 15; module docstring): a second bounded
        # queue serviced only when the deadline class is idle, drained
        # in big-rung chunks, governed by the admission controller
        self.bulk_max_queue_sets = int(
            bulk_max_queue_sets
            if bulk_max_queue_sets is not None
            else _env_int("LIGHTHOUSE_TPU_SCHED_MAX_BULK_QUEUE", 8192)
        )
        self.bulk_flush_sets = max(1, int(
            bulk_flush_sets
            if bulk_flush_sets is not None
            else _env_int("LIGHTHOUSE_TPU_SCHED_BULK_FLUSH_SETS", 512)
        ))
        self.bulk_linger_s = max(0.0, (
            bulk_linger_ms
            if bulk_linger_ms is not None
            else _env_float("LIGHTHOUSE_TPU_SCHED_BULK_LINGER_MS", 100.0)
        ) / 1000.0)
        # while throttled the flush thread re-polls admission at this
        # cadence instead of parking forever (resume is time-driven:
        # the latch expiry and the headroom dial move without a wake)
        self._bulk_recheck_s = 0.25
        self._admission = (
            bulk_admission
            if bulk_admission is not None
            else BulkAdmissionController()
        )
        self._bulk_flushes = 0
        self._bulk_sets_flushed = 0
        self._bulk_shed = 0
        # throttle-transition latch for chain-time parked accounting:
        # one note per excursion, never per recheck poll
        self._bulk_parked_noted = False
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: deque[_Submission] = deque()
        self._pending_sets = 0
        self._bulk_pending: deque[_Submission] = deque()
        self._bulk_pending_sets = 0
        self._flush_requested = False
        self._stopped = True  # not accepting until start()
        self._thread: Optional[threading.Thread] = None
        # own counters for status(): the health endpoint should not have
        # to parse the exposition to describe the scheduler
        self._fused_batches = 0
        self._bisections = 0
        self._shed = 0
        self._buckets_seen: set[int] = set()
        self._last_occupancy = 0.0
        self._plans_planned = 0
        self._plans_single = 0
        self._last_plan: Optional[dict] = None
        # rolling verdict-latency window (the /lighthouse/health slo
        # block and the replay harness read THIS scheduler's window, not
        # the process-global cumulative histograms); the tracker also
        # owns the lifetime miss totals — one source of truth
        self._slo = SloTracker()
        # the admission controller's burn-latch read is THIS scheduler's
        # tracker (an injected controller may already carry its own)
        if self._admission.tracker is None:
            self._admission.tracker = self._slo

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "VerificationScheduler":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stopped = False
            self._thread = threading.Thread(
                target=self._loop, name="verification-scheduler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop accepting queued work and drain: everything already
        submitted resolves (final flush, trigger=shutdown); later
        ``submit`` calls fall back to a synchronous direct call."""
        with self._cv:
            if self._stopped and self._thread is None:
                return
            self._stopped = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stopped

    # -- submission -------------------------------------------------------

    def submit(self, sets, kind: str, qos: str = "deadline") -> Future:
        """Queue one caller's signature sets for fused verification.
        Returns a Future resolving to the same bool a direct
        ``bls.verify_signature_sets(sets)`` call would return.
        ``qos="bulk"`` routes deadline-insensitive work (chain-segment
        backfill, slasher ingest) onto the bulk class — idle-time
        big-rung flushes under admission control (module docstring) —
        with the same verdict-identity contract."""
        if qos not in ("deadline", "bulk"):
            raise ValueError(f"unknown qos class {qos!r}")
        if qos == "bulk":
            return self._submit_bulk(sets, kind)
        sub = _Submission(kind, list(sets))
        if not sub.sets:
            # matches verify_signature_sets([]) == False; must not join a
            # fused batch where it would have no sets to vote with
            self._finish(sub, False, path="empty")
            return sub.future
        # arrival accounting (ISSUE 14): counted at SUBMISSION time —
        # shed submissions included (they arrived; the queue just could
        # not hold them) — so the capacity estimator's utilization
        # numerator keeps climbing past saturation instead of reading
        # serving throughput back as demand
        _ARRIVALS.with_labels(kind, "submit").inc(len(sub.sets))
        shed = False
        with self._cv:
            if self._stopped:
                shed = True  # not running: degrade to the direct call
            elif (
                self._pending
                and self._pending_sets + len(sub.sets) > self.max_queue_sets
            ):
                # backpressure: full queue sheds to caller fallback. An
                # oversized submission on an EMPTY queue is accepted — it
                # flushes as its own batch and could never fit otherwise.
                shed = True
            if shed:
                self._shed += 1
            else:
                was_empty = not self._pending
                self._pending.append(sub)
                self._pending_sets += len(sub.sets)
                _QUEUE_DEPTH.set(self._pending_sets)
                if was_empty or self._pending_sets >= self.max_batch_sets:
                    # wake the flush thread: it must (re)arm the deadline
                    # timer for a fresh queue, or fire the bucket-full flush
                    self._cv.notify()
        if shed:
            _SHED.with_labels(kind).inc()
            flight_recorder.record(
                "scheduler_shed",
                kind=kind,
                n_sets=len(sub.sets),
                queue_sets=self._pending_sets,
                bound=self.max_queue_sets,
                running=self.running(),
            )
            self._shed_resolve(
                sub, "scheduler.shed_fallback", f"shed:{kind}", "shed"
            )
        return sub.future

    def _submit_bulk(self, sets, kind: str) -> Future:
        """Bulk-class admission (ISSUE 15): enqueue on the bounded bulk
        queue — serviced only at deadline-class idle — or, on overflow
        (or a stopped scheduler), degrade to a synchronous verify in
        the CALLER's thread: the self-paced pre-scheduler behavior,
        identical verdict, never a burden on gossip's flush thread."""
        sub = _Submission(kind, list(sets), qos="bulk")
        if not sub.sets:
            self._finish(sub, False, path="empty")
            return sub.future
        _ARRIVALS.with_labels(kind, "bulk").inc(len(sub.sets))
        # drive the throttle latch from the arrival side too, FORCED
        # past the evaluator's rate limit: the first bulk submission
        # after headroom collapses must journal the bulk_throttle
        # BEFORE any of its sets could queue — the ordering the
        # acceptance gate pins (throttle precedes the miss burst, not
        # the other way around) — and a rate-limited read would return
        # the stale pre-collapse state for arrivals landing within
        # min_interval_s of the flush loop's last evaluation. The
        # result is deliberately NOT cached: admission state is read
        # fresh off the controller's latch everywhere (a cached flag
        # written from two threads could overwrite a fresh throttle
        # with a stale admitted and let one chunk flush mid-excursion)
        self._admission.evaluate(force=True)
        shed = False
        with self._cv:
            if self._stopped:
                shed = True
            elif (
                self._bulk_pending
                and self._bulk_pending_sets + len(sub.sets)
                > self.bulk_max_queue_sets
            ):
                # overflow sheds to the caller's thread; an oversized
                # submission on an EMPTY bulk queue is accepted (same
                # live-lock rule as the deadline queue)
                shed = True
            if shed:
                self._bulk_shed += 1
            else:
                self._bulk_pending.append(sub)
                self._bulk_pending_sets += len(sub.sets)
                _BULK_QUEUE_DEPTH.set(self._bulk_pending_sets)
                # wake the flush thread: it must (re)arm the bulk
                # linger/full timer (a gossip-idle thread may be parked
                # with no deadline armed at all)
                self._cv.notify()
        if shed:
            _BULK_SHED.with_labels(kind).inc()
            flight_recorder.record(
                "scheduler_shed",
                kind=kind,
                qos="bulk",
                n_sets=len(sub.sets),
                queue_sets=self._bulk_pending_sets,
                bound=self.bulk_max_queue_sets,
                running=self.running(),
            )
            self._shed_resolve(
                sub, "scheduler.bulk_shed", f"bulk_shed:{kind}", "bulk_shed"
            )
            # shed bulk IS bulk service (verified in the caller's
            # thread, possibly on the device): counted into the served
            # family so the capacity estimator's utilization numerator
            # (timeseries.sample) sees the work — an uncounted shed
            # stream would let headroom over-read exactly while the
            # device is busiest with it
            _BULK_SETS.with_labels(kind).inc(len(sub.sets))
        return sub.future

    def _shed_resolve(
        self, sub: "_Submission", span_name: str, caller: str, path: str,
    ) -> None:
        """ONE shed rule for both QoS classes: leaf resolution in the
        CALLER's thread — verdict, outcome accounting and exception
        delivery all match the direct call the submission degraded to.
        Cold-rung protection applies to EVERY shed path: a degraded
        caller must never block minutes on an XLA compile (the
        compile-service fallback serves it instead, relabeling the
        resolution path)."""
        with tracing.span(span_name, kind=sub.kind, n_sets=len(sub.sets)):
            verify = None
            svc = self._compile_service
            if svc is not None and svc.active():
                decision = svc.decide_flush(sub.sets, caller=caller)
                if decision["action"] == "shed":
                    verify = svc.fallback_verify
                    path = "fallback"
            self._resolve_group([sub], verify, path=path)

    def verify_now(self, sets, kind: str = "block") -> bool:
        """Synchronous bypass for latency-critical callers: identical to
        a direct backend call, counted so dashboards can see how much
        traffic skips the fusing queue."""
        sets = list(sets)
        _BYPASS.with_labels(kind).inc()
        if sets:
            _ARRIVALS.with_labels(kind, "bypass").inc(len(sets))
        t0 = time.monotonic()
        path = "bypass"
        try:
            with tracing.span("scheduler.bypass", kind=kind, n_sets=len(sets)):
                # the bypass dispatches on the mesh's primary HEALTHY
                # shard (after a chip loss the block path keeps serving
                # on the survivors) — resolved FIRST so the cold-bucket
                # warm check below consults the chip that will actually
                # dispatch, not device 0's registry
                mesh = _active_mesh()
                primary = (
                    mesh.primary_shard() if mesh is not None else None
                )
                svc = self._compile_service
                if svc is not None and svc.active():
                    # even the latency-critical bypass must not stall on a
                    # cold-bucket XLA compile: shed to the service's counted
                    # synchronous fallback (identical verdict)
                    decision = svc.decide_flush(
                        sets, caller=f"verify_now:{kind}",
                        device_index=primary or 0,
                    )
                    if decision["action"] == "shed":
                        # SLO path follows the RESOLUTION, not the entry:
                        # a bypass served by the CPU fallback has the
                        # fallback's latency profile, and filing it under
                        # `bypass` would blame device dispatch for a
                        # cold-route cost (the other fallback call sites
                        # already label it this way)
                        path = "fallback"
                        with transfer_ledger.context(kind, path):
                            return svc.fallback_verify(sets)
                with transfer_ledger.context(kind, path):
                    if mesh is not None and primary is not None:
                        t_mesh = time.monotonic()
                        try:
                            out = self._dispatch_on(
                                self._verify, sets, primary,
                                self.watchdog_bypass_s,
                            )
                        except BaseException as e:  # noqa: BLE001
                            # chip-loss failover on the bypass too
                            # (ISSUE 13 satellite): one retry on a
                            # failover shard — same verdict-identity
                            # contract as sharded sub-batches — instead
                            # of propagating into the block path. A
                            # failover that raises the same way means
                            # the WORK is the problem and the raise
                            # reaches the caller (pre-mesh contract).
                            return self._failover_retry(
                                self._verify, sets, primary, e, mesh,
                                watchdog_s=self.watchdog_bypass_s,
                            )
                        mesh.note_dispatch(
                            primary, len(sets),
                            time.monotonic() - t_mesh,
                        )
                        return out
                    return self._verify(sets)
        finally:
            # the bypass IS this caller's end-to-end latency: no queue,
            # but a cold-route fallback or a slow device dispatch can
            # still blow the deadline — it must feed the same SLO
            # surface the queued paths do (a raise still observes; the
            # caller paid the wall time either way)
            self._observe_latency(
                kind, path, time.monotonic() - t0, len(sets)
            )

    def flush(self) -> None:
        """Ask the flush thread to dispatch whatever is pending now."""
        with self._cv:
            self._flush_requested = True
            self._cv.notify()

    # -- flush loop -------------------------------------------------------

    def _oldest_deadline(self) -> Optional[float]:
        if not self._pending:
            return None
        return self._pending[0].submitted_at + self.deadline_s

    def _bulk_due_locked(self, now: float) -> Optional[float]:
        """The time the bulk queue becomes eligible to flush — ``now``
        once a full big-rung chunk is pending, else the oldest bulk
        submission's linger expiry; None when the queue is empty or
        admission is paused. Called under the lock; bulk eligibility
        additionally requires the deadline class to be idle (the
        caller checks ``self._pending`` — never preempt)."""
        if not self._bulk_pending or self._admission.throttled():
            return None
        if self._bulk_pending_sets >= self.bulk_flush_sets:
            return now
        return self._bulk_pending[0].submitted_at + self.bulk_linger_s

    def _loop(self) -> None:
        while True:
            # admission DRIVEN outside the cv (it reads the capacity
            # estimator and may journal a transition); the lock-held
            # due computation reads the controller's latch directly —
            # never a cached flag (see _submit_bulk)
            if self._bulk_pending_sets or self._admission.throttled():
                self._admission.evaluate()
                # chain-time: on entering a throttle excursion, the sets
                # sitting in the bulk queue are PARKED — attributed once
                # per excursion to the slot the valve closed in
                throttled_now = self._admission.throttled()
                if throttled_now and not self._bulk_parked_noted:
                    parked = self._bulk_pending_sets
                    if parked:
                        slot_ledger.note_bulk(parked_sets=parked)
                self._bulk_parked_noted = throttled_now
            trigger = None
            bulk = False
            with self._cv:
                while True:
                    if self._stopped:
                        trigger = "shutdown"
                        break
                    if self._flush_requested:
                        trigger = "explicit"
                        break
                    if self._pending_sets >= self.max_batch_sets:
                        trigger = "full"
                        break
                    deadline = self._oldest_deadline()
                    now = time.monotonic()
                    if deadline is not None and now >= deadline:
                        trigger = "deadline"
                        break
                    # bulk services ONLY at deadline-class idle (never
                    # preempts), and only while admitted
                    bulk_due = self._bulk_due_locked(now)
                    if (
                        not self._pending
                        and bulk_due is not None
                        and now >= bulk_due
                    ):
                        trigger = "bulk"
                        bulk = True
                        break
                    waits = []
                    if deadline is not None:
                        waits.append(deadline - now)
                    if bulk_due is not None and not self._pending:
                        waits.append(bulk_due - now)
                    if self._bulk_pending and self._admission.throttled():
                        # throttled with bulk waiting: the resume signal
                        # (latch expiry, headroom recovery) moves without
                        # a notify — re-poll instead of parking forever
                        waits.append(self._bulk_recheck_s)
                    # pipeline profiler (ISSUE 12): an empty-queue wait
                    # is the `queue_empty` bubble cause — a device gap
                    # overlapping it is traffic's fault, not the
                    # pipeline's (timed only when the DEADLINE queue is
                    # empty; a deadline-armed wait has work pending —
                    # parked bulk is idle by design, not a bubble).
                    # Opened EAGERLY: a verify_now gap closing while
                    # this thread is still parked must see the wait.
                    idle_t0 = (
                        time.perf_counter() if not self._pending else None
                    )
                    if idle_t0 is not None:
                        pipeline_profiler.note_idle_begin(idle_t0)
                    self._cv.wait(min(waits) if waits else None)
                    if idle_t0 is not None:
                        pipeline_profiler.note_idle_end(
                            idle_t0, time.perf_counter()
                        )
                    if self._bulk_pending and self._admission.throttled():
                        # re-evaluate admission outside the lock before
                        # the next wait round
                        break
                if trigger is None:
                    continue  # admission recheck wake
                if bulk:
                    subs = self._drain_bulk_locked()
                else:
                    subs = self._drain_locked()
                    if trigger == "shutdown" and not subs:
                        # the shutdown drain covers BOTH classes: gossip
                        # first (priority holds to the end), then bulk
                        # in big-rung chunks until empty — admission
                        # cannot veto the drain contract (every queued
                        # future resolves)
                        subs = self._drain_bulk_locked()
                        bulk = bool(subs)
                self._flush_requested = False
                stopped = self._stopped
            if subs:
                self._flush_batch(
                    subs, trigger, qos="bulk" if bulk else "deadline"
                )
            elif stopped:
                return

    @staticmethod
    def _drain_from(queue, cap: int) -> List[_Submission]:
        """ONE drain rule for both QoS classes: take at most ``cap``
        sets off ``queue`` in whole submissions (a submission is the
        isolation unit and never splits across fused batches; the
        first submission is always taken so an oversized one cannot
        live-lock). Called under the lock."""
        subs: List[_Submission] = []
        n = 0
        while queue:
            nxt = queue[0]
            if subs and n + len(nxt.sets) > cap:
                break
            subs.append(queue.popleft())
            n += len(nxt.sets)
        return subs

    def _drain_locked(self) -> List[_Submission]:
        """One bucket's worth off the deadline queue (under the lock)."""
        subs = self._drain_from(self._pending, self.max_batch_sets)
        self._pending_sets -= sum(len(s.sets) for s in subs)
        _QUEUE_DEPTH.set(self._pending_sets)
        return subs

    def _drain_bulk_locked(self) -> List[_Submission]:
        """One big-rung chunk (``bulk_flush_sets``) off the bulk queue
        (under the lock)."""
        subs = self._drain_from(self._bulk_pending, self.bulk_flush_sets)
        self._bulk_pending_sets -= sum(len(s.sets) for s in subs)
        _BULK_QUEUE_DEPTH.set(self._bulk_pending_sets)
        return subs

    def _flush_batch(
        self, subs: List[_Submission], trigger: str, qos: str = "deadline",
    ) -> None:
        n_sets = sum(len(s.sets) for s in subs)
        kinds_mix = "+".join(sorted({s.kind for s in subs}))
        now = time.monotonic()
        for s in subs:
            if qos != "bulk":
                # bulk waits (linger + gossip-busy windows + throttle
                # excursions) are the class contract, not queue latency
                # — they'd pollute the deadline-class histogram's tail
                _QUEUE_WAIT.observe(now - s.submitted_at)
            _SETS_TOTAL.with_labels(s.kind).inc(len(s.sets))
            if qos == "bulk":
                _BULK_SETS.with_labels(s.kind).inc(len(s.sets))
        if qos == "bulk":
            self._bulk_flushes += 1
            self._bulk_sets_flushed += n_sets
            # chain-time: sets the admission governor let through, on
            # the slot the flush ran in
            slot_ledger.note_bulk(admitted_sets=n_sets)
        # pipeline profiler (ISSUE 12): one lifecycle record per flush —
        # queue-wait (the oldest submission's), plan, pack, device and
        # fallback walls accumulate from this thread and the dp workers
        # (flush_scope below), and flush_end journals ONE pipeline_flush
        # event with the critical-path split (None when disabled). A
        # bulk flush reports queue-wait 0: its wait (linger +
        # gossip-busy windows + whole throttle excursions) is the class
        # contract, and one post-excursion flush would otherwise swamp
        # the deadline-class flush_phase_seconds{queue_wait} signal —
        # the same pollution the _QUEUE_WAIT exclusion above prevents
        prec = pipeline_profiler.flush_begin(
            trigger=trigger, kinds=kinds_mix, n_submissions=len(subs),
            n_sets=n_sets, queue_wait_s=(
                0.0 if qos == "bulk" else now - subs[0].submitted_at
            ),
        )
        svc = self._compile_service
        if svc is not None and not svc.active():
            svc = None
        # the plan: one legacy-style sub-batch, or kind-homogeneous
        # bin-packed sub-batches when that wins on padded lanes
        # (planner.py). With a compile service attached the planner only
        # splits onto rungs the warm registry can serve; with a served
        # device mesh attached (ISSUE 11) plans gain the dp shard axis
        # and the warm set is PER SHARD — a cold shard sheds to the
        # fallback instead of stalling the whole flush.
        mesh = _active_mesh()
        shards = mesh.healthy_shards() if mesh is not None else None
        _DP_SHARDS.set(len(shards) if shards else 0)
        warm = None
        if svc is not None:
            try:
                if shards:
                    # per-shard view even at width 1: after a chip loss
                    # the surviving shard may not be device 0, and its
                    # OWN warmth — not the dead chip's — must drive the
                    # plan
                    warm = svc.warm_rungs_by_shard(shards)
                else:
                    warm = svc.warm_rungs_active()
            except Exception:
                warm = None
        t_plan = time.perf_counter()
        plan = self._planner.plan(
            subs, warm_rungs=warm, shards=shards, qos=qos
        )
        pipeline_profiler.note_plan_wall(
            t_plan, time.perf_counter(), record=prec
        )
        _PLANS.with_labels(plan.mode).inc()
        _FLUSHES.with_labels(trigger).inc()
        waste = plan.waste()
        if plan.mode == "planned":
            self._plans_planned += 1
        else:
            self._plans_single += 1
        self._last_plan = {
            "mode": plan.mode,
            "n_sub_batches": len(plan.sub_batches),
            "rungs": plan.rungs_label(),
            "dp_shards": plan.shards_used(),
            "padding_waste": round(waste, 4),
            "est_h2d_bytes": plan.est_h2d_bytes,
            "est_live_h2d_bytes": plan.est_live_h2d_bytes,
        }
        bisections_before = self._bisections
        all_ok = True
        dev_live = dev_padded = 0  # lanes of DEVICE-dispatched sub-batches
        results: List[Optional[dict]] = [None] * len(plan.sub_batches)
        # the dp axis is the parallelism: sub-batches on DIFFERENT
        # shards dispatch concurrently (one worker per sub-batch —
        # thread count is bounded by the plan, itself bounded by the
        # mesh width x kind split), and the flush thread joins them. A
        # single-shard (or unsharded) plan keeps the serial dispatch.
        multi_shard = len({sb.shard for sb in plan.sub_batches}) > 1
        with tracing.span(
            "scheduler.flush",
            trigger=trigger,
            qos=qos,
            kinds=kinds_mix,
            n_submissions=len(subs),
            n_sets=n_sets,
            mode=plan.mode,
            n_sub_batches=len(plan.sub_batches),
            dp_shards=len(plan.shards_used()),
        ) as sp:
            def run_one(idx: int, sb) -> None:
                # the profiler scope rides on the dispatching thread
                # (flush thread for serial plans, a per-sub-batch worker
                # for dp plans): pack/device/fallback walls fired under
                # it attribute to THIS flush's lifecycle record
                with pipeline_profiler.flush_scope(prec):
                    try:
                        results[idx] = self._dispatch_sub_batch(
                            sb, svc, mesh, plan.mode, trigger, qos
                        )
                    except BaseException as e:  # noqa: BLE001 — futures first
                        # a worker must NEVER strand its futures: whatever
                        # slipped past the dispatch path's own handling is
                        # delivered to every submission (the caller sees the
                        # raise a direct call would have surfaced)
                        for s in sb.subs:
                            self._account(s, "sub_batch")
                            _SUBMISSIONS.with_labels(s.kind, "error").inc()
                            if not s.future.done():
                                s.future.set_exception(e)

            if multi_shard:
                workers = [
                    threading.Thread(
                        target=run_one, args=(i, sb),
                        name=f"flush-shard-{sb.shard}", daemon=True,
                    )
                    for i, sb in enumerate(plan.sub_batches)
                ]
                for w in workers:
                    w.start()
                for w in workers:
                    w.join()
            else:
                for i, sb in enumerate(plan.sub_batches):
                    run_one(i, sb)
            # bookkeeping on the flush thread (the per-sb workers only
            # verify; self._* counters stay single-writer)
            for sb, rec in zip(plan.sub_batches, results):
                if rec is None:
                    all_ok = False
                    continue
                self._fused_batches += 1
                self._buckets_seen.add(sb.rung[0])
                if rec["route"] != "shed":
                    dev_live += sb.live
                    dev_padded += rec["paid"]
                all_ok = all_ok and rec["ok"]
            sp.set(verdict=all_ok)
        # one pipeline_flush journal row per flush — bisections, shed
        # sub-batches and worker crashes included (the record closed is
        # the record opened; exactly-once pinned by test)
        pipeline_profiler.flush_end(
            prec, verdict=all_ok, mode=plan.mode,
            n_sub_batches=len(plan.sub_batches),
            dp_shards=plan.shards_used(),
        )
        if dev_padded:
            # gauges describe device lanes only (consistent with
            # verification_scheduler_plan_lanes_total): an all-shed
            # flush dispatched nothing and leaves them untouched
            occupancy = dev_live / float(dev_padded)
            _OCCUPANCY.set(occupancy)
            _PAD_WASTE.set(1.0 - occupancy)
            self._last_occupancy = occupancy
        flight_recorder.record(
            "scheduler_plan",
            mode=plan.mode,
            qos=qos,
            n_submissions=len(subs),
            n_sets=n_sets,
            n_sub_batches=len(plan.sub_batches),
            static_sub_batches=sum(
                1 for sb in plan.sub_batches if getattr(sb, "static", False)
            ),
            dp_shards=plan.shards_used(),
            rungs=plan.rungs_label(),
            live_lanes=plan.live,
            padded_lanes=plan.padded,
            legacy_padded_lanes=plan.legacy_padded,
            waste=round(waste, 4),
            est_h2d_bytes=plan.est_h2d_bytes,
            est_live_h2d_bytes=plan.est_live_h2d_bytes,
            kinds=kinds_mix,
        )
        flight_recorder.record(
            "scheduler_flush",
            trigger=trigger,
            qos=qos,
            kinds=kinds_mix,
            n_submissions=len(subs),
            n_sets=n_sets,
            bucket=(
                plan.sub_batches[0].rung[0]
                if plan.mode == "single"
                else None
            ),
            mode=plan.mode,
            occupancy=round(1.0 - waste, 4),  # plan-wide (journal = plan record)
            verdict=all_ok,
            bisections=self._bisections - bisections_before,
        )

    # -- sub-batch dispatch (the dp x rung plan element) ------------------

    def _dispatch_sub_batch(
        self, sb, svc, mesh, plan_mode: str, trigger: str,
        qos: str = "deadline",
    ) -> dict:
        """Execute ONE plan element: route it (cold-bucket protection per
        element — a sub-batch whose padded rung has no compiled staged
        program on ITS shard is served through the compile service's
        counted synchronous fallback, and bisects there too), dispatch
        it on its dp shard when the plan is sharded, and resolve its
        submissions. Runs on the flush thread for serial plans and on a
        per-sub-batch worker for multi-shard plans — everything here is
        thread-safe (labeled metric families lock; ``self._*`` counters
        stay with the flush thread)."""
        verify = self._verify
        route_action = "direct"
        paid = sb.padded
        if svc is not None:
            try:
                decision = svc.decide_flush(
                    sb.sets,
                    caller=f"flush:{trigger}",
                    geometry=(sb.n_sets, sb.k_req, sb.m_req),
                    device_index=sb.shard or 0,
                )
                route_action = decision["action"]
                if route_action == "shed":
                    verify = svc.fallback_verify
                elif decision["rung"] is not None:
                    # the registry may have warmed between planning and
                    # routing: charge the rung the device will ACTUALLY
                    # pad to, not the one the plan assumed
                    rb, rk, rm = decision["rung"]
                    paid = rb * rk * rm
            except Exception:
                # a routing failure must never fail a flush: dispatch
                # direct (the pre-service behavior)
                verify = self._verify
                route_action = "direct"
        _FUSED_BATCHES.with_labels(sb.kinds).inc()
        _PLAN_SUBBATCHES.with_labels(sb.kinds).inc()
        if route_action != "shed":
            # a shed sub-batch runs on the CPU fallback: the device paid
            # no lanes for it
            _PLAN_LANES.with_labels("live").inc(sb.live)
            _PLAN_LANES.with_labels("padded").inc(paid)
        # SLO path label: the compile-service CPU fallback is its own
        # resolution path (its latency profile is nothing like a device
        # dispatch); a BULK flush resolves under its class's own label
        # (idle-time latency is the class contract, not a tail to hide
        # among gossip's); otherwise a planned split resolves via
        # sub_batch, a single-rung flush via fused
        if route_action == "shed":
            path = "fallback"
        elif qos == "bulk":
            path = "bulk"
        elif plan_mode == "planned":
            path = "sub_batch"
        else:
            path = "fused"
        sharded = mesh is not None and sb.shard is not None
        if sharded and route_action != "shed":
            # the failover wrapper scopes every call of this sub-batch's
            # resolution tree (bisection retries included) to its shard
            verify = self._sharded_verify(verify, sb.shard, mesh)
            _DP_SUBBATCHES.with_labels(str(sb.shard)).inc()
            _DP_SETS.with_labels(str(sb.shard)).inc(sb.n_sets)
        t0 = time.monotonic()
        with tracing.span(
            "scheduler.sub_batch",
            kinds=sb.kinds,
            n_sets=sb.n_sets,
            rung="x".join(str(v) for v in sb.rung),
            route=route_action,
            shard=sb.shard,
        ):
            ok = self._resolve_group(
                sb.subs, verify, fused=sb.sets, path=path
            )
        if sharded:
            flight_recorder.record(
                "shard_dispatch",
                shard=sb.shard,
                kinds=sb.kinds,
                n_sets=sb.n_sets,
                rung="x".join(str(v) for v in sb.rung),
                route=route_action,
                ok=ok,
                seconds=round(time.monotonic() - t0, 6),
            )
        return {"ok": ok, "route": route_action, "paid": paid}

    def _dispatch_on(self, verify, sets, shard, deadline_s: float):
        """One dispatch scoped to ``shard``'s device — under the
        watchdog when ``deadline_s`` > 0: the call runs on a monitored
        daemon thread (which re-enters this thread's ledger/profiler
        attribution scopes and the shard's dispatch scope, so
        byte/phase attribution is unchanged) and a dispatch that blows
        the deadline raises :class:`WatchdogTimeout` here — the caller
        converts it into the chip-loss failover path instead of
        wedging the flush thread on a hung device."""
        mesh_mod = _mesh_module()
        if not deadline_s or deadline_s <= 0:
            with mesh_mod.dispatch_to(shard):
                return verify(sets)
        ctx = transfer_ledger.current_context()
        rec = pipeline_profiler.current_flush()
        box: dict = {}
        done = threading.Event()

        def target():
            try:
                with transfer_ledger.context(*ctx), \
                        pipeline_profiler.flush_scope(rec), \
                        mesh_mod.dispatch_to(shard):
                    box["ok"] = verify(sets)
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["err"] = e
            finally:
                done.set()

        worker = threading.Thread(
            target=target, name=f"dispatch-wd-{shard}", daemon=True
        )
        worker.start()
        if not done.wait(deadline_s):
            with self._lock:
                self._watchdog_reaped += 1
            _WATCHDOG_REAPED.with_labels(str(shard)).inc()
            flight_recorder.record(
                "watchdog_reaped",
                shard=shard,
                deadline_s=deadline_s,
                n_sets=len(sets),
            )
            raise WatchdogTimeout(
                f"sharded dispatch on shard {shard} exceeded the "
                f"{deadline_s:g}s watchdog deadline"
            )
        if "err" in box:
            raise box["err"]
        return box["ok"]

    def _sharded_verify(self, verify, shard: int, mesh):
        """Wrap ``verify`` so the whole resolution tree of one sharded
        sub-batch dispatches on ``shard``'s device — and so LOSING that
        chip degrades instead of erroring: the first raise triggers one
        failover re-verify of the same sets on another healthy shard
        (or the default device when none is left). A failover that
        SUCCEEDS proves the work was fine and the chip is the problem —
        the shard is dropped from the axis (``shard_lost`` journaled,
        planner stops packing onto it) and the verdict is the
        failover's, so verdict identity holds. A failover that raises
        the same way means the WORK is the problem: the shard keeps its
        health and the exception propagates exactly as the pre-mesh
        contract demands (bisection delivers it leaf by leaf). A HUNG
        dispatch is the same story through the watchdog (ISSUE 13):
        past the deadline the dispatch raises :class:`WatchdogTimeout`
        and takes this exact failover path instead of wedging the
        flush thread."""
        state = {"failed_over": False}

        def run(sets):
            target = shard
            if state["failed_over"] or not mesh.is_healthy(shard):
                target = mesh.failover_shard(shard)
            if target is None:
                return verify(sets)  # every chip lost: default device
            t0 = time.monotonic()
            try:
                out = self._dispatch_on(
                    verify, sets, target, self.watchdog_s
                )
            except BaseException as e:  # noqa: BLE001 — failover decides
                if target != shard:
                    raise  # the failover shard itself raised: real error
                state["failed_over"] = True
                return self._failover_retry(verify, sets, shard, e, mesh)
            mesh.note_dispatch(target, len(sets), time.monotonic() - t0)
            return out

        return run

    def _failover_retry(self, verify, sets, shard: int, err, mesh,
                        watchdog_s: float | None = None):
        fb = mesh.failover_shard(shard)
        wd = self.watchdog_s if watchdog_s is None else watchdog_s
        t0 = time.monotonic()
        try:
            if fb is not None:
                out = self._dispatch_on(verify, sets, fb, wd)
            else:
                out = verify(sets)
        except BaseException:
            # the failover failed the SAME work: the work, not the chip,
            # is the problem — count the failure, keep the shard on the
            # axis, surface the exception (pre-mesh contract)
            mesh.note_failure(shard, err, lost=False)
            raise
        # failover verdict in hand: the chip is the problem — drop it
        # (note_failure journals shard_lost on the healthy->lost
        # transition) and the verdict stands
        mesh.note_failure(shard, err, lost=True)
        if fb is not None:
            mesh.note_dispatch(fb, len(sets), time.monotonic() - t0)
        return out

    # -- verdict resolution (split-and-retry isolation) -------------------

    def _resolve_group(
        self, subs: List[_Submission], verify: Optional[Callable] = None,
        fused: Optional[list] = None, path: str = "fused",
    ) -> bool:
        """Verify ``subs`` as one fused call; on False — or on a raised
        backend exception, which a larger fused shape can hit even when
        each member's own call would not — bisect so every submission
        ends at exactly the verdict (or exception) its own direct call
        produces. Only a LEAF failure is delivered to a future.
        ``verify`` overrides the backend for the WHOLE resolution tree
        (the compile service's shed fallback); ``fused`` is the caller's
        already-flattened set list (bisection sub-calls re-flatten);
        ``path`` is the SLO resolution-path label every member resolves
        under (a bisected tree relabels its members ``bisection`` — the
        retries ARE the latency the submitter experienced)."""
        if verify is None:
            verify = self._verify
        # data-movement attribution (transfer_ledger): the backend pack
        # under this call charges its bytes to this group's kind mix and
        # resolution path — a bisection retry's re-packed bytes are real
        # (the host re-shipped them) but land under path=bisection, so
        # the original flush's attribution stays exactly-once
        kinds = "+".join(sorted({s.kind for s in subs}))
        try:
            with transfer_ledger.context(kinds, path):
                ok = bool(verify(
                    fused if fused is not None
                    else [st for s in subs for st in s.sets]
                ))
        except BaseException as e:  # noqa: BLE001 — flush thread survives
            if len(subs) == 1:
                sub = subs[0]
                # this fused call WAS the direct call: the caller would
                # have seen the raise, so the future carries it (and the
                # wall time it waited still counts against the SLO)
                _SUBMISSIONS.with_labels(sub.kind, "error").inc()
                self._account(sub, path)
                if not sub.future.done():
                    sub.future.set_exception(e)
                return False
            return self._bisect(subs, verify)
        if ok:
            for s in subs:
                self._finish(s, True, path)
            return True
        if len(subs) == 1:
            # leaf: this fused call WAS the direct per-caller call
            self._finish(subs[0], False, path)
            return False
        return self._bisect(subs, verify)

    def _bisect(
        self, subs: List[_Submission], verify: Optional[Callable] = None
    ) -> bool:
        with self._lock:  # dp shard workers may bisect concurrently
            self._bisections += 1
        _BISECTIONS.inc()
        flight_recorder.record(
            "scheduler_bisection",
            n_submissions=len(subs),
            n_sets=sum(len(s.sets) for s in subs),
            kinds="+".join(sorted({s.kind for s in subs})),
        )
        mid = len(subs) // 2
        left = self._resolve_group(subs[:mid], verify, path="bisection")
        right = self._resolve_group(subs[mid:], verify, path="bisection")
        return left and right

    def _finish(self, sub: _Submission, ok: bool, path: str) -> None:
        # accounting is unconditional — the resolution tree reaches each
        # submission exactly once, and an externally-cancelled future
        # must not make the counters (or the SLO window) undercount the
        # work the scheduler actually did; only the future mutation is
        # guarded
        self._account(sub, path)
        _SUBMISSIONS.with_labels(sub.kind, "ok" if ok else "invalid").inc()
        if not sub.future.done():
            sub.future.set_result(ok)

    # -- verdict-latency SLO ----------------------------------------------

    def _account(self, sub: _Submission, path: str) -> None:
        """One submission resolved: its end-to-end latency feeds the SLO
        surface exactly once, on whatever path delivered the verdict —
        under the submission's own QoS class, so a bisected or shed bulk
        submission stays bulk-class on every leaf."""
        self._observe_latency(
            sub.kind, path, time.monotonic() - sub.submitted_at,
            len(sub.sets), qos=sub.qos,
        )

    def _observe_latency(
        self, kind: str, path: str, latency_s: float, n_sets: int,
        qos: str = "deadline",
    ) -> None:
        budget_s = self.deadline_s * self.slo_grace
        # a bulk verdict is deadline-insensitive BY CONTRACT: it cannot
        # miss (its latency is the idle-time wait the class signed up
        # for) and must not reach the burn buckets either way (slo.py)
        missed = qos == "deadline" and latency_s > budget_s
        _VERDICT_LATENCY.with_labels(kind, path).observe(latency_s)
        self._slo.observe(kind, path, latency_s, missed, qos=qos)
        # chain-time attribution (ISSUE 17): THIS is the one point every
        # resolution path funnels through (_account ← _finish, for
        # planned / bisection / shed / bulk / fallback alike), so the
        # slot's report card counts each submission exactly once
        slot_ledger.note_resolution(
            kind, path, n_sets, latency_s, missed=missed, qos=qos
        )
        if missed:
            _DEADLINE_MISSES.with_labels(kind).inc()
            flight_recorder.record(
                "deadline_miss",
                kind=kind,
                path=path,
                n_sets=n_sets,
                latency_ms=round(latency_s * 1000.0, 3),
                deadline_ms=round(self.deadline_s * 1000.0, 3),
                budget_ms=round(budget_s * 1000.0, 3),
            )

    def slo_summary(self) -> dict:
        """Rolling p50/p99 + miss ratio per kind over the tracker window
        — the ``slo`` block `/lighthouse/health` serves and the replay
        harness reports (docs/TRAFFIC_REPLAY.md)."""
        doc = self._slo.summary(deadline_ms=self.deadline_s * 1000.0)
        doc["slo_grace"] = self.slo_grace
        doc["budget_ms"] = round(
            self.deadline_s * self.slo_grace * 1000.0, 3
        )
        doc["deadline_misses_total"] = self._slo.misses_total()
        return doc

    # -- introspection ----------------------------------------------------

    def status(self) -> dict:
        """One document for /lighthouse/health: queue depth, occupancy,
        config, and the padded buckets this process has dispatched (the
        recompile-bound surface)."""
        with self._lock:
            pending_subs = len(self._pending)
            pending_sets = self._pending_sets
            bulk_subs = len(self._bulk_pending)
            bulk_sets = self._bulk_pending_sets
        mesh = _active_mesh()  # read the seam ONCE: stop() may null it
        return {
            "running": self.running(),
            "queue_submissions": pending_subs,
            "queue_sets": pending_sets,
            # the bulk QoS class (ISSUE 15): per-class queue depth,
            # flush/shed totals and the live admission/throttle state —
            # the health rows an operator reads to see WHY backfill is
            # paused while gossip is fine
            "bulk": {
                "queue_submissions": bulk_subs,
                "queue_sets": bulk_sets,
                "max_queue_sets": self.bulk_max_queue_sets,
                "flush_sets": self.bulk_flush_sets,
                "linger_ms": round(self.bulk_linger_s * 1000.0, 3),
                "flushes_total": self._bulk_flushes,
                "sets_flushed_total": self._bulk_sets_flushed,
                "shed_total": self._bulk_shed,
                "admission": self._admission.status(),
            },
            "deadline_misses_total": self._slo.misses_total(),
            "max_batch_sets": self.max_batch_sets,
            "max_queue_sets": self.max_queue_sets,
            "deadline_ms": round(self.deadline_s * 1000.0, 3),
            "fused_batches_total": self._fused_batches,
            "bisections_total": self._bisections,
            "shed_total": self._shed,
            "watchdog_s": self.watchdog_s,
            "watchdog_bypass_s": self.watchdog_bypass_s,
            "watchdog_reaped_total": self._watchdog_reaped,
            "last_batch_occupancy": round(self._last_occupancy, 4),
            "buckets_seen": sorted(self._buckets_seen),
            "compile_service_attached": self._compile_service is not None,
            "dp_shards": (
                len(mesh.healthy_shards()) if mesh is not None else 0
            ),
            "planner": {
                "enabled": self._planner.enabled,
                "overhead_lanes": self._planner.overhead_lanes,
                "plans_planned_total": self._plans_planned,
                "plans_single_total": self._plans_single,
                "last_plan": self._last_plan,
            },
        }


# ---------------------------------------------------------------------------
# Caller-side helpers: one spelling for "verify these sets, fused when a
# scheduler is attached to the chain, direct otherwise".
# ---------------------------------------------------------------------------


def scheduler_of(chain) -> Optional[VerificationScheduler]:
    sched = getattr(chain, "verification_scheduler", None)
    if sched is not None and sched.running():
        return sched
    return None


def backend_verify(chain, sets, kind: str) -> bool:
    """One batch verification for ``chain``: submitted to the attached
    scheduler (cross-caller fusing) when present, else the direct
    backend call. Verdict identical either way."""
    sched = scheduler_of(chain)
    if sched is None:
        return bls.verify_signature_sets(sets)
    return sched.submit(sets, kind).result()


def backend_verify_each(chain, list_of_sets, kind: str) -> List[bool]:
    """Per-item fallback helper: verify each element of ``list_of_sets``
    independently. With a scheduler the items are submitted together
    first so they fuse into one retry batch instead of N serial calls."""
    sched = scheduler_of(chain)
    if sched is None:
        return [bls.verify_signature_sets(s) for s in list_of_sets]
    futures = [sched.submit(s, kind) for s in list_of_sets]
    return [f.result() for f in futures]


def backend_verify_now(chain, sets, kind: str = "block") -> bool:
    """Latency-critical callers (block verification): the scheduler's
    counted synchronous bypass when attached, else the direct call."""
    sched = scheduler_of(chain)
    if sched is None:
        return bls.verify_signature_sets(sets)
    return sched.verify_now(sets, kind)


def backend_verify_bulk(chain, sets, kind: str) -> bool:
    """Deadline-insensitive callers (chain-segment backfill, historical
    sync, slasher ingest): the scheduler's BULK class when attached —
    idle-time big-rung flushes under admission control, so a saturating
    backfill can never move gossip's p99 — else the direct call. The
    caller blocks on the verdict either way (segment import is
    sequential by nature, which is exactly the self-pacing the
    degradation order relies on). Verdict identical to a direct
    ``bls.verify_signature_sets(sets)``.

    A big segment is CHUNKED into ``bulk_flush_sets``-sized
    submissions here: submissions are atomic (the isolation unit never
    splits) and the drain always takes the first submission whole, so
    one multi-thousand-set submission would flush as one batch and
    occupy the flush thread for the segment's entire verify wall —
    breaking the documented head-of-line bound (a gossip arrival waits
    at most ONE in-flight bulk chunk). All chunks are submitted before
    any result is awaited (they fuse/pipeline at gossip idle), every
    future is consumed, and the all() verdict matches the single batch
    call's."""
    sched = scheduler_of(chain)
    if sched is None:
        return bls.verify_signature_sets(sets)
    sets = list(sets)
    if not sets:
        # matches verify_signature_sets([]) == False via the
        # scheduler's empty-submission path
        return sched.submit(sets, kind, qos="bulk").result()
    chunk = max(1, int(sched.bulk_flush_sets))
    futs = [
        sched.submit(sets[i:i + chunk], kind, qos="bulk")
        for i in range(0, len(sets), chunk)
    ]
    return all([f.result() for f in futs])
