"""Verification service: the continuous-batching layer between the
gossip verifiers and the BLS backend (see ``batcher.py``). Callers
submit signature sets; the scheduler fuses submissions from many
producers into shared fixed-geometry device batches under a latency
deadline, with split-and-retry isolation so per-submission verdicts
stay identical to direct per-caller calls. At flush time the
shape-aware planner (``planner.py``) partitions the fused submissions
into kind-homogeneous, bin-packed sub-batches when that reduces padded
device lanes, falling back to the legacy single-rung flush when it
cannot win."""

from .admission import BulkAdmissionController
from .batcher import (
    BUCKET_LADDER,
    VerificationScheduler,
    backend_verify,
    backend_verify_bulk,
    backend_verify_each,
    backend_verify_now,
    round_up_bucket,
    scheduler_of,
)
from .planner import (
    FlushPlan,
    FlushPlanner,
    PlannedSubBatch,
    flush_geometry,
    live_lanes,
    padded_lanes,
    padding_waste_ratio,
    set_geometry,
)
from .slo import SloTracker

__all__ = [
    "BUCKET_LADDER",
    "BulkAdmissionController",
    "FlushPlan",
    "FlushPlanner",
    "PlannedSubBatch",
    "SloTracker",
    "VerificationScheduler",
    "backend_verify",
    "backend_verify_bulk",
    "backend_verify_each",
    "backend_verify_now",
    "flush_geometry",
    "live_lanes",
    "padded_lanes",
    "padding_waste_ratio",
    "round_up_bucket",
    "scheduler_of",
    "set_geometry",
]
