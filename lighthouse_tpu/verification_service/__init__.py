"""Verification service: the continuous-batching layer between the
gossip verifiers and the BLS backend (see ``batcher.py``). Callers
submit signature sets; the scheduler fuses submissions from many
producers into shared fixed-geometry device batches under a latency
deadline, with split-and-retry isolation so per-submission verdicts
stay identical to direct per-caller calls."""

from .batcher import (
    BUCKET_LADDER,
    VerificationScheduler,
    backend_verify,
    backend_verify_each,
    backend_verify_now,
    round_up_bucket,
    scheduler_of,
)

__all__ = [
    "BUCKET_LADDER",
    "VerificationScheduler",
    "backend_verify",
    "backend_verify_each",
    "backend_verify_now",
    "round_up_bucket",
    "scheduler_of",
]
