"""Rolling verdict-latency SLO tracker for the verification scheduler.

The Prometheus histogram family
(``verification_scheduler_verdict_latency_seconds{kind,path}``,
batcher.py) is cumulative — right for dashboards, wrong for the question
an operator asks ``/lighthouse/health``: "what are submitters
experiencing RIGHT NOW?". This module keeps a bounded per-kind window of
the most recent end-to-end submit→verdict latencies (every resolution
path: fused flush, planned sub-batch, bisection retry, backpressure
shed, ``verify_now`` bypass, compile-service fallback) and answers with
rolling p50/p99 and the deadline-miss ratio over that window — the
``slo`` block the health endpoint serves and the traffic-replay harness
(``tools/traffic_replay.py``, docs/TRAFFIC_REPLAY.md) certifies against.

**Burn-rate tracking (ISSUE 14).** A window miss RATIO says what just
happened; it does not say whether the miss BUDGET will survive the
hour. The tracker therefore also keeps per-sample timestamps and
answers the SRE-standard multi-window question: over the ``fast``
window (default 60 s) and the ``slow`` window (default 600 s), at what
multiple of the budgeted miss ratio (default 1%) are misses being
consumed?  ``burn = window_miss_ratio / budget_miss_ratio`` — burn 1.0
consumes exactly the budget over that window, burn 14 exhausts an hour
of budget in ~4 minutes. When BOTH windows burn at or above the alert
threshold (default 1.0 — "on track to exhaust"; the two-window AND
suppresses blips the slow window forgives) the tracker journals ONE
``slo_burn`` flight-recorder event per excursion (a continuing storm
re-confirms the latch without re-firing; a stretch longer than the
fast window without a CONFIRMED alert — quiet or merely sub-budget —
re-arms it), ticks
``verification_scheduler_slo_burn_events_total{kind}`` and serves the
live burn rates in ``verification_scheduler_slo_burn_rate{kind,window}``
— the standing alert primitive the capacity/headroom estimator
(``utils/timeseries.py``) and ROADMAP item 2's admission control build
on.

**Per-class tracking (ISSUE 15).** The scheduler now serves two QoS
classes (``deadline`` — gossip's latency class — and ``bulk`` —
chain-segment backfill and slasher-style ingest, docs/
VERIFICATION_SERVICE.md). A bulk verdict is deadline-INSENSITIVE by
contract: it never counts as a miss, and — the part that matters — its
samples must not enter the burn-window DENOMINATOR either, where a
saturating backfill's thousands of on-time verdicts would dilute
gossip's miss ratio and silence the very alert that is supposed to
shed the backfill. ``observe(..., qos=...)`` therefore records bulk
samples into the quantile window (operators still get bulk p50/p99)
but skips the burn buckets entirely; ``summary()`` labels each kind
with its class (STICKY-deadline: one deadline-class sample upgrades a
kind for good, so a mixed-class kind's bulk samples can never hide an
active gossip burn excursion), and ``latched_kinds()`` — the admission
controller's read — can only ever name deadline-class kinds.

**Ratio-scope fix (ISSUE 14 satellite).** ``misses_total`` /
``count_total`` are LIFETIME counters and the window numbers are
window-scoped — after long uptimes the two diverge, and a reader mixing
a lifetime numerator with a windowed denominator gets a meaningless
ratio. ``summary()`` now reports both scopes explicitly:
``window_miss_ratio`` (window misses / window count) AND
``lifetime_miss_ratio`` (lifetime misses / lifetime count), so no
consumer has to derive a ratio across scopes. Both denominators count
DEADLINE-class samples only (ISSUE 15): misses are deadline-only by
construction, so a mixed-class kind's saturating bulk stream would
otherwise dilute either ratio toward zero during a live miss storm.

Deliberately **jax-free** and scheduler-instance-scoped: a replay run or
a test reads ITS scheduler's window (``summary()``/``burn()``), not the
process-global metric registry another run already polluted. The burn
GAUGE/counter families are the usual exception — like every scheduler
metric family they are process-global, so concurrent trackers in one
process share them (tests and dashboards read the per-instance
documents for isolation).

Design constraints (same discipline as the metric families):

* ``observe()`` is O(1) amortized: one deque append + one time-bucket
  update under one lock — it sits on every future resolution,
  including the shed path that runs in a gossip caller's thread. Burn
  recomputation is miss-driven: a miss while UN-latched scans the
  bounded bucket ring (≤ one slow window of buckets — so even the
  first miss of a sub-millisecond burst is evaluated, never dropped by
  a throttle), while misses inside a live excursion just refresh the
  latch in O(1) — a sustained storm cannot turn the tracker into a
  CPU sink.
* ``summary()`` sorts only the bounded window (default 1024 samples per
  kind, ``LIGHTHOUSE_TPU_SLO_WINDOW``) — a health scrape can never walk
  unbounded history.

Env knobs: ``LIGHTHOUSE_TPU_SLO_WINDOW`` (sample window),
``LIGHTHOUSE_TPU_SLO_BUDGET_RATIO`` (budgeted miss ratio, default 0.01),
``LIGHTHOUSE_TPU_SLO_FAST_S`` / ``LIGHTHOUSE_TPU_SLO_SLOW_S`` (burn
windows, default 60/600 s), ``LIGHTHOUSE_TPU_SLO_BURN_ALERT`` (alert
threshold, default 1.0).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..utils import flight_recorder, metrics

DEFAULT_WINDOW = 1024
DEFAULT_BUDGET_MISS_RATIO = 0.01
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0
DEFAULT_BURN_ALERT = 1.0

_ENV_WINDOW = "LIGHTHOUSE_TPU_SLO_WINDOW"
_ENV_BUDGET = "LIGHTHOUSE_TPU_SLO_BUDGET_RATIO"
_ENV_FAST = "LIGHTHOUSE_TPU_SLO_FAST_S"
_ENV_SLOW = "LIGHTHOUSE_TPU_SLO_SLOW_S"
_ENV_ALERT = "LIGHTHOUSE_TPU_SLO_BURN_ALERT"

_Sample = Tuple[float, float, str, bool, str]  # (t, s, path, missed, qos)

_BURN_RATE = metrics.gauge_vec(
    "verification_scheduler_slo_burn_rate",
    "miss-budget burn rate per caller kind and window (fast/slow): "
    "window miss ratio / budgeted miss ratio. 1.0 consumes exactly the "
    "budget over that window; both windows >= the alert threshold "
    "journals an slo_burn event (the standing alert primitive, "
    "ISSUE 14). Updated on misses and on burn()/summary() reads",
    ("kind", "window"),
)
_BURN_EVENTS = metrics.counter_vec(
    "verification_scheduler_slo_burn_events_total",
    "slo_burn alerts journaled per caller kind: both burn windows "
    "crossed the alert threshold (latched — one event per excursion, "
    "not per miss)",
    ("kind",),
)


# one env-parsing convention across the observability knobs
_env_float = flight_recorder._env_float


def quantile_ms(sorted_latencies, q: float) -> float:
    """Nearest-rank quantile of an already-sorted seconds list, in
    milliseconds (0.0 for an empty window). THE quantile spelling for
    every replay/SLO report (tools/traffic_replay.py reuses it for
    dispatch-lag), so the harness and the health block can never
    disagree on rank semantics. Nearest-rank proper: index
    ``ceil(q*n) - 1`` — ``int(q*n)`` would overshoot by one exactly when
    ``q*n`` is integral, silently reporting the max as p99 at round
    window sizes."""
    if not sorted_latencies:
        return 0.0
    n = len(sorted_latencies)
    idx = min(n - 1, max(0, math.ceil(q * n) - 1))
    return round(sorted_latencies[idx] * 1000.0, 3)


class SloTracker:
    """Bounded rolling window of verdict latencies per caller kind (see
    module docstring). ``observe`` is called by the scheduler on every
    resolution; ``summary`` is the health-endpoint/replay-report read;
    ``burn`` is the miss-budget burn-rate read."""

    def __init__(
        self,
        window: int | None = None,
        budget_miss_ratio: float | None = None,
        fast_window_s: float | None = None,
        slow_window_s: float | None = None,
        burn_alert: float | None = None,
    ):
        if window is None:
            try:
                window = int(os.environ.get(_ENV_WINDOW, ""))
            except ValueError:
                window = DEFAULT_WINDOW
        self.window = max(1, int(window))
        self.budget_miss_ratio = max(1e-9, float(
            budget_miss_ratio
            if budget_miss_ratio is not None
            else _env_float(_ENV_BUDGET, DEFAULT_BUDGET_MISS_RATIO)
        ))
        self.fast_window_s = max(1e-3, float(
            fast_window_s
            if fast_window_s is not None
            else _env_float(_ENV_FAST, DEFAULT_FAST_WINDOW_S)
        ))
        self.slow_window_s = max(self.fast_window_s, float(
            slow_window_s
            if slow_window_s is not None
            else _env_float(_ENV_SLOW, DEFAULT_SLOW_WINDOW_S)
        ))
        self.burn_alert = max(1e-6, float(
            burn_alert
            if burn_alert is not None
            else _env_float(_ENV_ALERT, DEFAULT_BURN_ALERT)
        ))
        self._lock = threading.Lock()
        self._samples: Dict[str, Deque[_Sample]] = {}
        self._count_total: Dict[str, int] = {}
        # lifetime DEADLINE-class sample count per kind: the
        # lifetime_miss_ratio denominator (misses are deadline-only by
        # construction, so the all-class count would dilute a mixed
        # kind's ratio exactly like the window fix below prevents)
        self._dl_count_total: Dict[str, int] = {}
        self._misses_total: Dict[str, int] = {}
        # kind -> QoS class label (ISSUE 15), STICKY-deadline: "bulk"
        # only while every sample the kind ever carried was bulk — one
        # deadline sample upgrades it for good (a mixed-class kind's
        # deadline samples keep feeding the burn buckets, so its burn
        # doc must stay visible). The summary label + the guarantee
        # that latched_kinds() only ever names deadline-class kinds.
        self._kind_qos: Dict[str, str] = {}
        # burn accounting is TIME-bucketed, decoupled from the
        # count-bounded quantile deque: at production verdict rates
        # (hundreds/s) 1024 samples span seconds, which would silently
        # collapse both burn windows onto the same sliver of history
        # and defeat the slow window's blip forgiveness. Buckets are
        # fast_window/20 wide; the ring holds one slow window of them
        # per kind — bounded memory at ANY rate.
        self._bucket_s = max(1e-3, self.fast_window_s / 20.0)
        self._bucket_cap = int(self.slow_window_s / self._bucket_s) + 2
        # kind -> deque of [bucket_start, count, misses]
        self._burn_buckets: Dict[str, Deque[list]] = {}
        # burn-alert latches + recompute throttle, per kind: the latch
        # is the time the alert state was last CONFIRMED — a continuing
        # storm refreshes it (no re-fire); a gap longer than the fast
        # window (misses aged out, then a fresh excursion) re-arms it
        self._burn_alerted_at: Dict[str, Optional[float]] = {}
        # last latched-path re-confirmation scan per kind: while
        # latched, misses re-evaluate at bucket granularity (the
        # windows only move in bucket steps), not on every miss
        self._burn_checked_at: Dict[str, float] = {}
        self._burn_events_total: Dict[str, int] = {}

    def observe(
        self, kind: str, path: str, seconds: float, missed: bool,
        now: float | None = None, qos: str = "deadline",
    ) -> None:
        """Record one resolved submission: end-to-end latency, the
        resolution path that produced the verdict, and whether it landed
        past the deadline. ``now`` is injectable for deterministic
        burn-window tests (default ``time.monotonic()``). ``qos`` is the
        submission's service class: a non-deadline sample feeds the
        quantile window only — never the burn buckets, whose count
        denominator a saturating bulk stream would otherwise dilute
        (module docstring, ISSUE 15)."""
        if now is None:
            now = time.monotonic()
        check_burn = False
        with self._lock:
            dq = self._samples.get(kind)
            if dq is None:
                dq = self._samples[kind] = deque(maxlen=self.window)
                self._count_total[kind] = 0
                self._dl_count_total[kind] = 0
                self._misses_total[kind] = 0
            # sticky-deadline: a kind that EVER carried deadline-class
            # samples keeps its burn visibility — last-writer-wins
            # would let one bulk sample of a mixed-class kind hide an
            # ACTIVE gossip burn excursion from burn()/summary()
            if qos == "deadline" or kind not in self._kind_qos:
                self._kind_qos[kind] = qos
            dq.append((now, seconds, path, missed, qos))
            self._count_total[kind] += 1
            if qos == "deadline":
                self._dl_count_total[kind] += 1
            if missed:
                self._misses_total[kind] += 1
            if qos != "deadline":
                return
            buckets = self._burn_buckets.get(kind)
            if buckets is None:
                buckets = self._burn_buckets[kind] = deque(
                    maxlen=self._bucket_cap
                )
            start = (now // self._bucket_s) * self._bucket_s
            if not buckets or start > buckets[-1][0]:
                buckets.append([start, 0, 0])
            # an out-of-order timestamp (synthetic test time) folds into
            # the newest bucket rather than corrupting the ring order
            buckets[-1][1] += 1
            if missed:
                buckets[-1][2] += 1
                at = self._burn_alerted_at.get(kind)
                if at is not None and now - at <= self.fast_window_s:
                    # latched: re-CONFIRM at bucket granularity (the
                    # windows only move in bucket steps, so finer
                    # rechecks cannot change the answer — a storm
                    # costs one bounded scan per bucket, not per
                    # miss). The latch is NEVER refreshed without a
                    # confirming scan: a sub-budget background miss
                    # trickle would otherwise pin it alive forever
                    # and silence every later real excursion.
                    last = self._burn_checked_at.get(
                        kind, -float("inf")
                    )
                    if now - last >= self._bucket_s:
                        check_burn = True
                else:
                    # un-latched: EVERY miss evaluates (bounded bucket
                    # scan, ≤ one slow window of buckets) — a
                    # time-throttle here once let a sub-throttle burst
                    # cross both windows without ever journaling
                    check_burn = True
        if check_burn:
            self._recheck_burn(kind, now)

    # -- burn-rate tracking ------------------------------------------------

    def _window_burn_locked(
        self, kind: str, window_s: float, now: float
    ) -> dict:
        """Miss ratio + burn over the trailing ``window_s``, from the
        time-bucketed counters (bucket granularity ≈ fast/20 — a ≤5%
        edge approximation, never a rate-dependent window collapse)."""
        cutoff = now - window_s
        count = misses = 0
        for start, n, m in reversed(self._burn_buckets.get(kind) or ()):
            if start + self._bucket_s <= cutoff:
                break
            count += n
            misses += m
        ratio = (misses / count) if count else 0.0
        return {
            "window_s": window_s,
            "count": count,
            "misses": misses,
            "miss_ratio": round(ratio, 6),
            "burn": (
                round(ratio / self.budget_miss_ratio, 4) if count else None
            ),
        }

    def _burn_kind_locked(self, kind: str, now: float) -> dict:
        fast = self._window_burn_locked(kind, self.fast_window_s, now)
        slow = self._window_burn_locked(kind, self.slow_window_s, now)
        alerting = (
            fast["burn"] is not None and fast["burn"] >= self.burn_alert
            and slow["burn"] is not None and slow["burn"] >= self.burn_alert
        )
        return {
            "fast": fast,
            "slow": slow,
            "alerting": alerting,
            "events_total": self._burn_events_total.get(kind, 0),
        }

    @staticmethod
    def _publish_burn_gauges(kind: str, doc: dict) -> None:
        """Mirror one kind's computed burn into the gauge family —
        called from miss-driven rechecks AND from burn()/summary()
        reads, so a post-storm scrape decays the gauge instead of
        freezing it at the excursion's peak (an alert on the gauge
        would otherwise fire forever after full recovery)."""
        for win in ("fast", "slow"):
            burn = doc[win]["burn"]
            _BURN_RATE.with_labels(kind, win).set(
                burn if burn is not None else 0.0
            )

    def _recheck_burn(self, kind: str, now: float) -> None:
        """Recompute the two burn windows for ``kind`` and drive the
        alert latch: entering the alerting state journals ONE
        ``slo_burn`` event per EXCURSION (the standing alert). A
        continuing storm re-confirms the latch without re-firing; a
        stretch longer than the fast window without a confirmed alert
        (quiet, or background misses under budget) expires it, so the
        next excursion alerts again even if nothing read the tracker
        in between."""
        with self._lock:
            self._burn_checked_at[kind] = now
            doc = self._burn_kind_locked(kind, now)
            fire = False
            if doc["alerting"]:
                at = self._burn_alerted_at.get(kind)
                if at is None or now - at > self.fast_window_s:
                    fire = True
                    self._burn_events_total[kind] = (
                        self._burn_events_total.get(kind, 0) + 1
                    )
                    doc["events_total"] = self._burn_events_total[kind]
                self._burn_alerted_at[kind] = now
            # NOT cleared on a non-alerting recheck: re-arm is purely
            # time-based (a quiet — or merely sub-budget — stretch
            # longer than the fast window since the last CONFIRMED
            # alert). A miss ratio oscillating around the budget would
            # otherwise fire one event per re-crossing and flood the
            # journal during a sustained near-budget storm.
        self._publish_burn_gauges(kind, doc)
        if fire:
            _BURN_EVENTS.with_labels(kind).inc()
            flight_recorder.record(
                "slo_burn",
                kind=kind,
                budget_miss_ratio=self.budget_miss_ratio,
                burn_alert=self.burn_alert,
                fast_window_s=self.fast_window_s,
                fast_miss_ratio=doc["fast"]["miss_ratio"],
                fast_burn=doc["fast"]["burn"],
                slow_window_s=self.slow_window_s,
                slow_miss_ratio=doc["slow"]["miss_ratio"],
                slow_burn=doc["slow"]["burn"],
            )

    def latched_kinds(self, now: float | None = None) -> list:
        """Kinds whose burn-alert latch is live: a confirmed ``slo_burn``
        excursion within the fast window. THE standing-alert read the
        bulk admission controller polls (ISSUE 15) — bulk-class samples
        never reach the burn buckets, so any latched kind is by
        construction a deadline-class (gossip) kind."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            return sorted(
                kind
                for kind, at in self._burn_alerted_at.items()
                if at is not None and now - at <= self.fast_window_s
            )

    def burn(self, now: float | None = None) -> dict:
        """The miss-budget burn document: per kind, miss ratio and burn
        multiple over the fast and slow windows, the alert latch state
        and the per-kind alert count — plus the budget configuration.
        The latch stays miss-driven; reads refresh the burn GAUGES so
        they decay after a storm instead of freezing at its peak."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            # bulk-class kinds (ISSUE 15) never feed the burn buckets —
            # an all-zero doc for them would read as "zero burn
            # measured" rather than "not applicable", so they are
            # absent here exactly as their summary() burn block is None
            kinds = {
                kind: self._burn_kind_locked(kind, now)
                for kind in sorted(self._samples)
                if self._kind_qos.get(kind, "deadline") == "deadline"
            }
        for kind, doc in kinds.items():
            self._publish_burn_gauges(kind, doc)
        return {
            "budget_miss_ratio": self.budget_miss_ratio,
            "burn_alert": self.burn_alert,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "kinds": kinds,
        }

    # -- totals ------------------------------------------------------------

    def misses_total(self) -> int:
        """Lifetime deadline misses across every kind — THE total the
        scheduler's ``status()`` and ``slo_summary()`` both report (one
        source of truth; the per-kind split lives in ``summary()``)."""
        with self._lock:
            return sum(self._misses_total.values())

    def summary(
        self, deadline_ms: float | None = None, now: float | None = None,
    ) -> dict:
        """The ``slo`` document: per kind, rolling p50/p99/max over the
        window, the miss ratio in BOTH scopes (window-scoped and
        lifetime — never mixed, see module docstring), lifetime totals,
        a per-path breakdown (each path's own window quantiles) so a
        flattering fast path cannot hide a slow one's tail, and the
        per-kind burn-rate block."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            snap = {k: list(dq) for k, dq in self._samples.items()}
            counts = dict(self._count_total)
            dl_counts = dict(self._dl_count_total)
            misses = dict(self._misses_total)
            kind_qos = dict(self._kind_qos)
            # deadline-class kinds only (burn()'s filter): computing a
            # bulk kind's burn doc here would be lock-held work whose
            # result the "burn" key below discards anyway
            burn_kinds = {
                kind: self._burn_kind_locked(kind, now)
                for kind in sorted(self._samples)
                if kind_qos.get(kind, "deadline") == "deadline"
            }
        for kind, bdoc in burn_kinds.items():
            self._publish_burn_gauges(kind, bdoc)
        kinds = {}
        for kind in sorted(snap):
            samples = snap[kind]
            lat = sorted(s[1] for s in samples)
            # the windowed miss ratio is DEADLINE-scoped (ISSUE 15): a
            # mixed-class kind's saturating bulk stream would otherwise
            # pack the shared window with never-miss samples and read
            # near-zero during an active gossip miss storm — the exact
            # dilution the burn buckets already refuse. Quantiles stay
            # all-class (bulk visibility is the feature; the per-path
            # rows below separate the classes for mixed kinds).
            dl_count = sum(1 for s in samples if s[4] == "deadline")
            window_misses = sum(1 for s in samples if s[3])
            paths = {}
            for path in sorted({s[2] for s in samples}):
                plat = sorted(s[1] for s in samples if s[2] == path)
                paths[path] = {
                    "count": len(plat),
                    "p50_ms": quantile_ms(plat, 0.50),
                    "p99_ms": quantile_ms(plat, 0.99),
                }
            kinds[kind] = {
                # the QoS class this kind's samples carry (ISSUE 15):
                # bulk kinds report quantiles but no burn block — their
                # misses are defined away, not hidden
                "qos": kind_qos.get(kind, "deadline"),
                "count_total": counts[kind],
                "window_count": len(samples),
                "p50_ms": quantile_ms(lat, 0.50),
                "p99_ms": quantile_ms(lat, 0.99),
                "max_ms": round(lat[-1] * 1000.0, 3) if lat else 0.0,
                "misses_total": misses[kind],
                "window_misses": window_misses,
                "window_miss_ratio": (
                    round(window_misses / dl_count, 4) if dl_count else 0.0
                ),
                # explicitly lifetime-scoped (ISSUE 14 satellite): the
                # lifetime numerator over the lifetime DEADLINE-class
                # denominator (ISSUE 15) — a reader never has to divide
                # across scopes, and a mixed kind's bulk samples cannot
                # dilute it
                "lifetime_miss_ratio": (
                    round(misses[kind] / dl_counts.get(kind, 0), 6)
                    if dl_counts.get(kind) else 0.0
                ),
                "paths": paths,
                # bulk kinds carry no burn block: their samples never
                # enter the burn buckets, so the empty doc would read
                # as "zero burn measured" rather than "not applicable"
                "burn": (
                    burn_kinds.get(kind)
                    if kind_qos.get(kind, "deadline") == "deadline"
                    else None
                ),
            }
        doc = {
            "window": self.window,
            "kinds": kinds,
            "burn_config": {
                "budget_miss_ratio": self.budget_miss_ratio,
                "burn_alert": self.burn_alert,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
            },
        }
        if deadline_ms is not None:
            doc["deadline_ms"] = round(float(deadline_ms), 3)
        return doc
