"""Rolling verdict-latency SLO tracker for the verification scheduler.

The Prometheus histogram family
(``verification_scheduler_verdict_latency_seconds{kind,path}``,
batcher.py) is cumulative — right for dashboards, wrong for the question
an operator asks ``/lighthouse/health``: "what are submitters
experiencing RIGHT NOW?". This module keeps a bounded per-kind window of
the most recent end-to-end submit→verdict latencies (every resolution
path: fused flush, planned sub-batch, bisection retry, backpressure
shed, ``verify_now`` bypass, compile-service fallback) and answers with
rolling p50/p99 and the deadline-miss ratio over that window — the
``slo`` block the health endpoint serves and the traffic-replay harness
(``tools/traffic_replay.py``, docs/TRAFFIC_REPLAY.md) certifies against.

Deliberately **jax-free** and scheduler-instance-scoped: a replay run or
a test reads ITS scheduler's window, not the process-global metric
registry another run already polluted.

Design constraints (same discipline as the metric families):

* ``observe()`` is O(1): one deque append under one lock — it sits on
  every future resolution, including the shed path that runs in a
  gossip caller's thread.
* ``summary()`` sorts only the bounded window (default 1024 samples per
  kind, ``LIGHTHOUSE_TPU_SLO_WINDOW``) — a health scrape can never walk
  unbounded history.
"""

from __future__ import annotations

import math
import os
import threading
from collections import deque
from typing import Deque, Dict, Tuple

DEFAULT_WINDOW = 1024
_ENV_WINDOW = "LIGHTHOUSE_TPU_SLO_WINDOW"

# (latency_seconds, path, missed)
_Sample = Tuple[float, str, bool]


def quantile_ms(sorted_latencies, q: float) -> float:
    """Nearest-rank quantile of an already-sorted seconds list, in
    milliseconds (0.0 for an empty window). THE quantile spelling for
    every replay/SLO report (tools/traffic_replay.py reuses it for
    dispatch-lag), so the harness and the health block can never
    disagree on rank semantics. Nearest-rank proper: index
    ``ceil(q*n) - 1`` — ``int(q*n)`` would overshoot by one exactly when
    ``q*n`` is integral, silently reporting the max as p99 at round
    window sizes."""
    if not sorted_latencies:
        return 0.0
    n = len(sorted_latencies)
    idx = min(n - 1, max(0, math.ceil(q * n) - 1))
    return round(sorted_latencies[idx] * 1000.0, 3)


class SloTracker:
    """Bounded rolling window of verdict latencies per caller kind (see
    module docstring). ``observe`` is called by the scheduler on every
    resolution; ``summary`` is the health-endpoint/replay-report read."""

    def __init__(self, window: int | None = None):
        if window is None:
            try:
                window = int(os.environ.get(_ENV_WINDOW, ""))
            except ValueError:
                window = DEFAULT_WINDOW
        self.window = max(1, int(window))
        self._lock = threading.Lock()
        self._samples: Dict[str, Deque[_Sample]] = {}
        self._count_total: Dict[str, int] = {}
        self._misses_total: Dict[str, int] = {}

    def observe(
        self, kind: str, path: str, seconds: float, missed: bool
    ) -> None:
        """Record one resolved submission: end-to-end latency, the
        resolution path that produced the verdict, and whether it landed
        past the deadline."""
        with self._lock:
            dq = self._samples.get(kind)
            if dq is None:
                dq = self._samples[kind] = deque(maxlen=self.window)
                self._count_total[kind] = 0
                self._misses_total[kind] = 0
            dq.append((seconds, path, missed))
            self._count_total[kind] += 1
            if missed:
                self._misses_total[kind] += 1

    def misses_total(self) -> int:
        """Lifetime deadline misses across every kind — THE total the
        scheduler's ``status()`` and ``slo_summary()`` both report (one
        source of truth; the per-kind split lives in ``summary()``)."""
        with self._lock:
            return sum(self._misses_total.values())

    def summary(self, deadline_ms: float | None = None) -> dict:
        """The ``slo`` document: per kind, rolling p50/p99/max over the
        window, window miss ratio, lifetime totals, and a per-path
        breakdown (each path's own window quantiles), so a flattering
        fast path cannot hide a slow one's tail."""
        with self._lock:
            snap = {k: list(dq) for k, dq in self._samples.items()}
            counts = dict(self._count_total)
            misses = dict(self._misses_total)
        kinds = {}
        for kind in sorted(snap):
            samples = snap[kind]
            lat = sorted(s[0] for s in samples)
            window_misses = sum(1 for s in samples if s[2])
            paths = {}
            for path in sorted({s[1] for s in samples}):
                plat = sorted(s[0] for s in samples if s[1] == path)
                paths[path] = {
                    "count": len(plat),
                    "p50_ms": quantile_ms(plat, 0.50),
                    "p99_ms": quantile_ms(plat, 0.99),
                }
            kinds[kind] = {
                "count_total": counts[kind],
                "window_count": len(samples),
                "p50_ms": quantile_ms(lat, 0.50),
                "p99_ms": quantile_ms(lat, 0.99),
                "max_ms": round(lat[-1] * 1000.0, 3) if lat else 0.0,
                "misses_total": misses[kind],
                "window_misses": window_misses,
                "window_miss_ratio": (
                    round(window_misses / len(samples), 4) if samples else 0.0
                ),
                "paths": paths,
            }
        doc = {"window": self.window, "kinds": kinds}
        if deadline_ms is not None:
            doc["deadline_ms"] = round(float(deadline_ms), 3)
        return doc
