"""Shape-aware flush planner: bin-packed, kind-homogeneous sub-batches.

The headline bench pads 48 fused sets up to ONE (B=64, K=8, M=4) rung
and burns ``padding_waste 0.6875`` — two of every three device lanes do
nothing, a ~3x throughput loss no kernel work can recover, because the
committee batch-verification cost model (PAPERS.md, arxiv 2302.00418)
scales with *padded* lanes, not live sets. The fix is the same one
continuous-batching serving stacks use: pack heterogeneous requests into
shape-homogeneous device batches. This module is that planner, and it is
deliberately **jax-free** so the scheduler, the compile service, the
tests and ``tools/flush_plan_report.py`` can all plan without touching a
device backend.

At flush time the scheduler hands the fused submission list to
:meth:`FlushPlanner.plan`, which partitions it into one or more
sub-batches:

* **sub-bucket by kind** — attestation and sync-committee sets have
  near-fixed (K, M) geometry per caller kind, so kind-homogeneous
  sub-batches stop padding the K/M axes up to the mix's max (a
  single-pubkey gossip attestation no longer pays committee-width K);
* **split static from dynamic** (ISSUE 10) — with a device-resident
  pubkey key table attached (``crypto/device/key_table.py``),
  submissions whose every pubkey is table-resident ("static") are
  packed separately from out-of-table ones ("dynamic"): the backend's
  static/dynamic packer is all-or-nothing per batch, so without the
  split ONE pre-admission key would degrade a whole fused flush back
  to the G1 limb plane. A plan that separates the two wins even when
  its lane score does not;
* **bin-pack the B axis** — a kind group's submissions are first-fit-
  decreasing packed across ladder rungs (48 -> one 48 rung; 72 -> 64+8
  instead of 96), minimizing total padded lanes B*K*M;
* **prefer warm rungs** — with a compile-service registry attached, a
  sub-batch lands on the cheapest warm rung covering it; if the split
  would go cold while the legacy single rung is warm, the planner falls
  back to today's single-rung plan (a plan must never trade warm device
  dispatch for a CPU-fallback shed);
* **fall back when it can't win** — a plan is only used when its total
  padded lanes (plus a per-extra-dispatch overhead charge) beat the
  single-rung plan, so trickle traffic keeps fusing into one batch and
  the per-batch fixed overhead the scheduler exists to amortize
  (docs/COST_MODEL.md) is not re-fragmented.

* **shard the dp axis** (ISSUE 11) — with a served device mesh
  attached (``crypto/device/mesh.py``), plans gain a second packing
  axis: each kind group's submissions are balance-partitioned across
  the mesh's healthy shards (whole submissions only) and bin-packed
  per shard, so every shard's sub-batch is a kind-homogeneous batch
  dispatched to its own chip. A shard is never given fewer than
  ``dp_min_sets`` sets (trickle traffic must not be shredded across
  chips just because chips exist), and a lost shard simply stops
  appearing in ``shards`` — the axis degrades, the plan does not fail.
  Scoring compares the *busiest shard's* padded lanes (shards run
  concurrently; wall-clock is the max, not the sum) plus the dispatch
  overhead charge against the legacy single rung.

* **class-aware packing** (ISSUE 15) — ``plan(..., qos="bulk")`` packs
  a BULK-class flush (chain-segment backfill, slasher ingest —
  ``batcher.py``'s second service class) for throughput, not latency:
  the batcher drains bulk in big-rung chunks (``bulk_flush_sets``,
  default 512), so bulk bins naturally fill the largest ladder rungs
  (B=256/512 — where DP_SCALING.json measures the best sets/s and the
  committee cost model's batching gains peak, PAPERS.md arxiv
  2302.00418); when the exact big rung is COLD but smaller warm rungs
  cover the group's (K, M), a bulk bin RE-BINS into chunks of the
  largest covering warm rung instead of shedding hundreds of sets to
  the CPU fallback (a deadline-class flush never does this — splitting
  a latency-class flush multiplies its dispatch count on the critical
  path; bulk has no deadline, only throughput); and the dp floor rises
  to :data:`BULK_DP_MIN_SETS` so bulk never shreds below a
  big-rung-worth per shard. The deadline class's plan is byte-identical
  to pre-ISSUE-15 (pinned by ``tests/test_bulk_qos.py``).

Submissions are ATOMIC: a submission is the verdict-isolation unit
(split-and-retry bisection, batcher.py) and is never split across
sub-batches — every plan covers every submission exactly once, and the
shard axis respects the same unit (a submission lands on exactly one
shard), pinned by ``tests/test_flush_planner.py`` /
``tests/test_dp_mesh.py``.

This module also owns the ONE lane/padding-waste formula
(:func:`padded_lanes` / :func:`live_lanes` /
:func:`padding_waste_ratio`) shared by ``bls_device_padding_waste_ratio``
(crypto/device/bls.py) and ``verification_scheduler_padding_waste_ratio``
(batcher.py), so the two families can no longer disagree on what
"waste" means; their equality is pinned by test.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..utils import transfer_ledger
from .batcher import BUCKET_LADDER, round_up_bucket

Rung = Tuple[int, int, int]  # (B, K, M) padded bucket shape

# Scoring charge for every sub-batch beyond the first, in padded-lane
# units: a dispatch pays fixed overhead (host pack, dispatch, device
# sync) that the cost model prices at roughly this many B*K*M cells, so
# the planner never shreds trickle traffic into tiny batches just to
# shave a lane or two — the fusing win of the scheduler stays intact.
DEFAULT_SUBBATCH_OVERHEAD_LANES = 16
# Minimum sets a dp shard is worth waking up for: below this the
# per-dispatch fixed overhead dominates whatever parallelism buys, so a
# kind group smaller than 2x this stays on one shard (trickle keeps
# fusing; the shard axis is for the big warm rungs, DP_SCALING.json).
DEFAULT_DP_MIN_SETS = 8
# Bulk-class dp floor (ISSUE 15): a bulk flush exists to fill the big
# rungs, so a shard is only worth waking for a big-rung-worth of sets —
# below this the deadline-class floor would shred a 512-set drain into
# dispatch-overhead-dominated slivers across chips.
BULK_DP_MIN_SETS = 64
_ENV_OVERHEAD = "LIGHTHOUSE_TPU_SCHED_PLAN_OVERHEAD_LANES"
_ENV_PLANNER = "LIGHTHOUSE_TPU_SCHED_PLANNER"
_ENV_DP_MIN = "LIGHTHOUSE_TPU_SCHED_DP_MIN_SETS"


# ---------------------------------------------------------------------------
# THE lane / padding-waste formula (one definition, two metric families)
# ---------------------------------------------------------------------------


def padded_lanes(b: int, k: int, m: int) -> int:
    """Device lanes a padded (B, K, M) batch pays for: the full B*K*M
    volume — B set lanes x K pubkey slots x M message-plane slices."""
    return int(b) * int(k) * int(m)


def live_lanes(pk_slots: int, m_req: int) -> int:
    """Lanes the callers actually asked for: the real pubkey slots
    (sum of len(pks) over live sets) replicated across the m_req live
    message-plane slices. Padding on ANY axis (B, K or M) shows up as
    the gap to :func:`padded_lanes`."""
    return int(pk_slots) * max(1, int(m_req))


def padding_waste_ratio(live: int, padded: int) -> float:
    """1 - live/padded: the fraction of paid-for device lanes no caller
    asked for. 0.0 for an empty/degenerate batch (nothing was paid)."""
    if padded <= 0:
        return 0.0
    return max(0.0, 1.0 - live / float(padded))


# ---------------------------------------------------------------------------
# Geometry extraction (shared with compile_service._geometry)
# ---------------------------------------------------------------------------


def set_geometry(item) -> Tuple[int, Optional[bytes]]:
    """(pubkey count, hashable message key) of ONE signature set —
    a ``SignatureSet`` object or a ``(sig, pks, msg)`` triple. Anything
    else conservatively counts as a 1-pubkey set with an un-keyable
    message (over-reserving only risks extra padding)."""
    keys = getattr(item, "signing_keys", None)
    msg = getattr(item, "message", None)
    if keys is None and isinstance(item, (tuple, list)) and len(item) == 3:
        keys, msg = item[1], item[2]
    k = len(keys) if keys is not None else 1
    if msg is None:
        return k, None
    try:
        return k, bytes(msg)
    except (TypeError, ValueError):
        return k, None


def flush_geometry(sets) -> Tuple[int, int, int]:
    """(n_sets, max pubkeys/set, unique messages) of a flush — the three
    dims the packers pad. Un-keyable messages each count distinct."""
    n = 0
    k = 1
    msgs: Set[bytes] = set()
    distinct = 0
    for item in sets:
        n += 1
        ki, key = set_geometry(item)
        k = max(k, ki or 1)
        if key is None:
            distinct += 1
        else:
            msgs.add(key)
    return n, k, max(1, len(msgs) + distinct)


# ---------------------------------------------------------------------------
# Plan data model
# ---------------------------------------------------------------------------


class PlannedSubBatch:
    """One dispatch of the plan: whole submissions, their live geometry,
    and the padded rung the backend will land on. ``static`` marks a
    sub-batch whose every pubkey resolves to the device key table
    (ISSUE 10): the backend ships a ``(B, K)`` index plane for it, so
    its byte estimate uses the indexed operand model."""

    __slots__ = (
        "subs", "sets", "kinds", "n_sets", "k_req", "m_req",
        "pk_slots", "rung", "cold", "static", "shard", "live", "padded",
        "est_h2d_bytes", "est_live_h2d_bytes",
    )

    def __init__(self, subs: List, rung: Rung, cold: bool,
                 n_sets: int, k_req: int, m_req: int, pk_slots: int,
                 static: bool = False, shard: Optional[int] = None):
        self.subs = subs
        self.sets = [st for s in subs for st in s.sets]
        self.kinds = "+".join(sorted({s.kind for s in subs}))
        self.n_sets = n_sets
        self.k_req = k_req
        self.m_req = m_req
        self.pk_slots = pk_slots
        self.rung = rung
        self.cold = cold
        self.static = static
        # the dp shard this sub-batch dispatches on (ISSUE 11); None =
        # unsharded (primary device) — the pre-mesh behavior
        self.shard = shard
        self.live = live_lanes(pk_slots, m_req)
        self.padded = padded_lanes(*rung)
        # byte accounting (ISSUE 8): what the packer will ship
        # host→device for this element's padded rung, and the live share
        # the callers asked for — the shared analytic model pinned
        # against the packer's actual ndarray.nbytes by test. A static
        # sub-batch prices the index plane (ISSUE 10).
        self.est_h2d_bytes = transfer_ledger.operand_bytes_model(
            *rung, indexed=static
        )["total"]
        self.est_live_h2d_bytes = transfer_ledger.live_operand_bytes(
            n_sets, pk_slots, m_req, indexed=static
        )["total"]

    def waste(self) -> float:
        return padding_waste_ratio(self.live, self.padded)


class FlushPlan:
    """The planner's answer: ``mode`` is ``"planned"`` (multi- or
    better-shaped sub-batches) or ``"single"`` (today's one-rung flush,
    the fallback). Lane totals use the shared formula above."""

    __slots__ = (
        "mode", "sub_batches", "live", "padded",
        "legacy_rung", "legacy_padded", "legacy_cold",
        "est_h2d_bytes", "est_live_h2d_bytes",
    )

    def __init__(self, mode: str, sub_batches: List[PlannedSubBatch],
                 legacy_rung: Rung, legacy_cold: bool = False):
        self.mode = mode
        self.sub_batches = sub_batches
        self.live = sum(sb.live for sb in sub_batches)
        self.padded = sum(sb.padded for sb in sub_batches)
        self.legacy_rung = legacy_rung
        self.legacy_padded = padded_lanes(*legacy_rung)
        self.legacy_cold = legacy_cold
        self.est_h2d_bytes = sum(sb.est_h2d_bytes for sb in sub_batches)
        self.est_live_h2d_bytes = sum(
            sb.est_live_h2d_bytes for sb in sub_batches
        )

    def waste(self) -> float:
        return padding_waste_ratio(self.live, self.padded)

    def rungs_label(self) -> str:
        return "+".join(
            f"{b}x{k}x{m}" for (b, k, m) in (sb.rung for sb in self.sub_batches)
        )

    def shards_used(self) -> List[int]:
        """Distinct dp shards this plan dispatches on (empty when the
        plan is unsharded — the single-device behavior)."""
        return sorted({
            sb.shard for sb in self.sub_batches if sb.shard is not None
        })


# ---------------------------------------------------------------------------
# The planner
# ---------------------------------------------------------------------------


def best_covering_rung(
    warm: Iterable[Rung], n: int, k: int, m: int
) -> Optional[Rung]:
    """Cheapest rung in ``warm`` covering (n, k, m), minimizing padded
    lanes. THE covering policy: ``WarmShapeRegistry.best_covering``
    (compile_service/service.py) delegates here, so the rung the
    planner scores a sub-batch at is the rung routing actually lands
    it on."""
    cands = [r for r in warm if r[0] >= n and r[1] >= k and r[2] >= m]
    if not cands:
        return None
    return min(cands, key=lambda r: (padded_lanes(*r), r[0], r[1], r[2]))


def _largest_rung_at_most(n: int) -> int:
    best = BUCKET_LADDER[0]
    for c in BUCKET_LADDER:
        if c <= n:
            best = c
    return best


def _active_key_table():
    """The process-global device key table (ISSUE 10), reached lazily so
    this module stays jax-free: key_table.py imports no jax at import
    time, and the planner only calls its jax-free ``covers_sets``."""
    try:
        from ..crypto.device import key_table as _kt

        return _kt.get_active_table()
    except Exception:
        return None


class FlushPlanner:
    """Stateless-per-flush planner (see module docstring). ``overhead_
    lanes`` is the scoring charge per sub-batch beyond the first;
    ``enabled=False`` always returns the single-rung plan (the
    pre-planner behavior, byte-identical)."""

    def __init__(
        self,
        overhead_lanes: Optional[int] = None,
        enabled: Optional[bool] = None,
        dp_min_sets: Optional[int] = None,
    ):
        if overhead_lanes is None:
            try:
                overhead_lanes = int(os.environ.get(_ENV_OVERHEAD, ""))
            except ValueError:
                overhead_lanes = DEFAULT_SUBBATCH_OVERHEAD_LANES
        self.overhead_lanes = max(0, int(overhead_lanes))
        if dp_min_sets is None:
            try:
                dp_min_sets = int(os.environ.get(_ENV_DP_MIN, ""))
            except ValueError:
                dp_min_sets = DEFAULT_DP_MIN_SETS
        self.dp_min_sets = max(1, int(dp_min_sets))
        if enabled is None:
            enabled = os.environ.get(_ENV_PLANNER, "1") not in ("", "0")
        self.enabled = bool(enabled)

    # -- public entry -----------------------------------------------------

    def plan(
        self,
        subs: Sequence,
        warm_rungs=None,
        shards: Optional[Sequence[int]] = None,
        qos: str = "deadline",
    ) -> FlushPlan:
        """Partition ``subs`` (objects with ``.kind`` and ``.sets``) into
        sub-batches. ``warm_rungs`` is the compile-service registry's
        warm (B, K, M) set for the active engine — a flat iterable, or
        (mesh-aware, ISSUE 11) a ``{shard: [rungs]}`` dict so a COLD
        shard sheds to fallback instead of stalling a flush; None means
        no service attached (every exact rung dispatches; the packers
        pad to it). ``shards`` is the mesh's healthy shard-id list —
        more than one enables the dp packing axis; None/1 is the
        single-device behavior, byte-identical to before. ``qos`` is
        the flush's service class (ISSUE 15, module docstring): bulk
        plans fill the largest warm rungs and re-bin cold big rungs
        onto warm coverage; the deadline class is unchanged."""
        bulk = qos == "bulk"
        shard_ids = [int(s) for s in shards] if shards else []
        dp = len(shard_ids) > 1
        warm = warm_rungs
        if warm is not None and not isinstance(warm, dict):
            warm = list(warm)
        legacy_warm = self._warm_for(warm, shard_ids[0] if shard_ids else None)
        table = _active_key_table()
        subs = list(subs)
        # classify each submission ONCE; the legacy whole-flush flag and
        # the bin-packer's group keys both derive from this pass (no
        # re-walk of the identity map per bin)
        flags = [
            bool(table is not None and self._is_static([s], table))
            for s in subs
        ]
        legacy = self._make_sub_batch(
            subs, legacy_warm, table, static=bool(subs) and all(flags),
            shard=shard_ids[0] if shard_ids else None,
        )
        if not self.enabled or len(subs) == 0:
            return FlushPlan("single", [legacy], legacy.rung, legacy.cold)
        # shards are passed through even at width 1: a one-chip mesh
        # still tags every sub-batch with its shard so per-chip
        # accounting and failover behave uniformly (dp scoring below
        # only engages at width > 1)
        planned = self._kind_binpacked(
            subs, flags, warm, table, shard_ids or None, bulk=bulk
        )
        if len(planned) <= 1:
            # one bin == the legacy plan re-derived; report it as single
            # (same rung by construction: one group, one bin, whole flush)
            return FlushPlan("single", [legacy], legacy.rung, legacy.cold)
        # warm preference dominates the lane score in BOTH directions: a
        # shed pays CPU wall time, not device lanes, so comparing a cold
        # plan's padded lanes against a warm one's is apples-to-oranges.
        # A plan that sends ANY sub-batch to the CPU fallback while the
        # single warm rung could serve the whole flush on device is a
        # de-optimization; conversely an all-warm split must beat a COLD
        # single rung whatever the lane count says.
        if warm is not None:
            planned_cold = any(sb.cold for sb in planned)
            if planned_cold and not legacy.cold:
                return FlushPlan("single", [legacy], legacy.rung, legacy.cold)
            if legacy.cold and not planned_cold:
                return FlushPlan("planned", planned, legacy.rung, legacy.cold)
            if bulk and legacy.cold and any(not sb.cold for sb in planned):
                # bulk partial-warm salvage (ISSUE 15): when the single
                # rung is cold, a split that gets ANY share onto warm
                # device rungs beats shedding the whole drain to the CPU
                # fallback — the lane score below cannot see the
                # device/CPU cliff (a shed pays CPU wall, not lanes).
                # Deadline-class flushes never take this: a partial shed
                # still stalls the latency class on its slowest member.
                return FlushPlan("planned", planned, legacy.rung, legacy.cold)
        # static/dynamic separation dominates the lane score (ISSUE 10):
        # when the split isolates key-table-resident sub-batches from
        # raw ones and the single-rung flush would be MIXED (one raw set
        # degrades every static set back to the G1 limb plane), the
        # split is the point — the static share drops ~98% of its pubkey
        # bytes, worth far more than the overhead-lane charge. An
        # all-static or all-raw flush keeps the pure lane comparison.
        static_split = (
            table is not None
            and len({sb.static for sb in planned}) > 1
            and not legacy.static
        )
        if dp and len({sb.shard for sb in planned}) > 1:
            # shards run CONCURRENTLY: the wall-clock cost of a dp plan
            # is the busiest shard's padded lanes (plus its extra
            # dispatches), not the sum over shards — comparing the sum
            # against one device's single rung would charge parallelism
            # as if it were serial and the axis would never win
            per_shard_padded: Dict[Optional[int], int] = {}
            per_shard_count: Dict[Optional[int], int] = {}
            for sb in planned:
                per_shard_padded[sb.shard] = (
                    per_shard_padded.get(sb.shard, 0) + sb.padded
                )
                per_shard_count[sb.shard] = (
                    per_shard_count.get(sb.shard, 0) + 1
                )
            score = max(per_shard_padded.values()) + self.overhead_lanes * (
                max(per_shard_count.values()) - 1
            )
        else:
            score = sum(sb.padded for sb in planned) + self.overhead_lanes * (
                len(planned) - 1
            )
        if score >= legacy.padded and not static_split:
            return FlushPlan("single", [legacy], legacy.rung, legacy.cold)
        return FlushPlan("planned", planned, legacy.rung, legacy.cold)

    # -- internals --------------------------------------------------------

    def _geometry_of(self, subs: List) -> Tuple[int, int, int, int]:
        """(n_sets, k_req, m_req, live pk slots) over whole submissions."""
        n = 0
        k_req = 1
        pk_slots = 0
        msgs: Set[bytes] = set()
        distinct = 0
        for s in subs:
            for item in s.sets:
                n += 1
                ki, key = set_geometry(item)
                k_req = max(k_req, ki or 1)
                pk_slots += ki
                if key is None:
                    distinct += 1
                else:
                    msgs.add(key)
        m_req = max(1, len(msgs) + distinct)
        return n, k_req, m_req, pk_slots

    @staticmethod
    def _warm_for(warm, shard: Optional[int]):
        """The warm-rung set a sub-batch on ``shard`` routes against:
        a per-shard dict (mesh-aware registry) keys by shard — an
        unknown shard reads as COLD, never as another chip's warmth; a
        flat list applies to every shard; None means no service."""
        if isinstance(warm, dict):
            if shard is None:
                if not warm:
                    return None
                shard = sorted(warm)[0]
            return list(warm.get(shard, ()))
        return warm

    def _make_sub_batch(
        self, subs: List, warm: Optional[List[Rung]], table=None,
        static: Optional[bool] = None, shard: Optional[int] = None,
    ) -> PlannedSubBatch:
        """``static=None`` classifies here (the legacy whole-flush
        sub-batch); the bin-packer passes its group's already-known
        flag so a flush is classified once per submission, not re-walked
        per bin. ``warm`` is already shard-resolved by the caller."""
        n, k_req, m_req, pk_slots = self._geometry_of(subs)
        exact: Rung = (
            round_up_bucket(max(1, n)),
            round_up_bucket(k_req),
            round_up_bucket(m_req),
        )
        cold = False
        rung = exact
        if warm is not None:
            covering = best_covering_rung(warm, n, k_req, m_req)
            if covering is not None:
                rung = covering
            else:
                cold = True
        if static is None:
            static = bool(table is not None and self._is_static(subs, table))
        return PlannedSubBatch(
            subs, rung, cold, n, k_req, m_req, pk_slots, static=static,
            shard=shard,
        )

    @staticmethod
    def _is_static(subs: List, table) -> bool:
        """Every set of every submission resolves to the device key
        table (jax-free predicate; the backend re-verifies identity at
        pack time, so a misprediction costs padding, never
        correctness)."""
        try:
            return all(table.covers_sets(s.sets) for s in subs)
        except Exception:
            return False

    def _kind_binpacked(
        self, subs: List, flags: List[bool], warm,
        table=None, shards: Optional[List[int]] = None,
        bulk: bool = False,
    ) -> List[PlannedSubBatch]:
        """Sub-bucket by kind — and, with a device key table attached,
        by static/dynamic eligibility (``flags``, one per submission,
        classified once by ``plan``), so one out-of-table submission
        cannot degrade a whole flush back to the raw limb plane — then,
        with a dp mesh (``shards``, ISSUE 11), balance-partition each
        group across shards (whole submissions only; a shard never gets
        fewer than ``dp_min_sets`` sets), then first-fit-decreasing
        bin-pack each (group × shard)'s submissions over the B axis
        with bin capacity = the largest ladder rung <= that partition's
        set count (an oversized submission opens its own bin —
        submissions never split)."""
        groups: Dict[Tuple[str, bool], List] = {}
        for s, static in zip(subs, flags):
            groups.setdefault((s.kind, static), []).append(s)
        planned: List[PlannedSubBatch] = []
        # cross-group shard load so small groups spread over the mesh
        # instead of all landing on the first shard
        shard_load: Dict[int, int] = {s: 0 for s in (shards or ())}
        for kind, _static in sorted(groups):
            members = groups[(kind, _static)]
            n_group = sum(len(s.sets) for s in members)
            if shards:
                parts = self._dp_partition(
                    members, n_group, shards, shard_load,
                    # bulk never shreds below a big-rung-worth per
                    # shard (ISSUE 15): parallelism is for the big
                    # warm rungs, not for slivers
                    dp_min=(
                        max(self.dp_min_sets, BULK_DP_MIN_SETS)
                        if bulk else self.dp_min_sets
                    ),
                )
            else:
                parts = [(None, members)]
            for shard, part in parts:
                n_part = sum(len(s.sets) for s in part)
                cap = _largest_rung_at_most(max(1, n_part))
                shard_warm = self._warm_for(warm, shard)
                # stable FFD: big submissions first, arrival-order
                # tie-break
                order = sorted(
                    range(len(part)),
                    key=lambda i: (-len(part[i].sets), i),
                )
                bins: List[List] = []  # [submissions, set count]
                for i in order:
                    sub = part[i]
                    size = len(sub.sets)
                    placed = False
                    for b in bins:
                        if b[1] + size <= cap:
                            b[0].append(sub)
                            b[1] += size
                            placed = True
                            break
                    if not placed:
                        # a submission larger than cap still gets its
                        # own bin
                        bins.append([[sub], size])
                for members_bin, _count in bins:
                    sb = self._make_sub_batch(
                        members_bin, shard_warm, table,
                        static=_static, shard=shard,
                    )
                    if bulk and sb.cold and shard_warm:
                        # bulk fills warm rungs (ISSUE 15): a cold big
                        # rung re-bins onto warm coverage instead of
                        # shedding the drain to the CPU fallback
                        planned.extend(self._bulk_warm_rebin(
                            sb, shard_warm, table, _static, shard,
                        ))
                    else:
                        planned.append(sb)
        return planned

    def _bulk_warm_rebin(
        self, sb: PlannedSubBatch, warm: List[Rung], table,
        static: bool, shard: Optional[int],
    ) -> List[PlannedSubBatch]:
        """Bulk-class cold-rung salvage (ISSUE 15): ``sb``'s exact big
        rung has no compiled program, but smaller warm rungs may cover
        its (K, M) — re-bin the submissions into chunks of the LARGEST
        covering warm B, so a 512-set backfill drain fills two warm
        256-rungs on device instead of shedding the lot to the CPU
        fallback. The deadline class never does this: splitting a
        latency-class flush multiplies dispatches on the critical path,
        while bulk pays wall-clock it is contractually indifferent to.
        Submissions stay atomic — one larger than every covering warm
        rung keeps its own (cold) bin, and decide_flush sheds exactly
        that remainder, not the whole drain.

        Coverage is judged per CHUNK, not against the whole batch's
        m_req: each set carries one message (``_geometry_of``), so a
        chunk's unique-message count is bounded by its set count — a
        warm (256,1,256) rung serves 256-set chunks of a 512-set
        per-set-distinct-message drain (m_req=512) that could never
        cover the batch whole. A cap below :data:`BULK_DP_MIN_SETS`
        is not worth re-binning for (a big drain would shred into
        dispatch-overhead-dominated slivers): keep the cold bin."""
        cap = 0
        for r in warm:
            if r[1] < sb.k_req:
                continue
            # the rung serves chunks up to its B outright when its M
            # plane covers min(B, batch m_req); else chunks up to its
            # M (a chunk of c sets has at most c unique messages)
            cap = max(cap, (
                r[0] if r[2] >= min(sb.m_req, r[0]) else min(r[0], r[2])
            ))
        if cap < BULK_DP_MIN_SETS:
            return [sb]
        if cap >= sb.n_sets:
            # a covering rung existed after all — sb would not be cold;
            # defensive: keep the original bin
            return [sb]
        bins: List[List] = []
        order = sorted(
            range(len(sb.subs)), key=lambda i: (-len(sb.subs[i].sets), i)
        )
        for i in order:
            sub = sb.subs[i]
            size = len(sub.sets)
            placed = False
            for b in bins:
                if b[1] + size <= cap:
                    b[0].append(sub)
                    b[1] += size
                    placed = True
                    break
            if not placed:
                bins.append([[sub], size])
        if len(bins) <= 1:
            return [sb]
        return [
            self._make_sub_batch(
                members, warm, table, static=static, shard=shard
            )
            for members, _count in bins
        ]

    def _dp_partition(
        self, members: List, n_group: int, shards: List[int],
        shard_load: Dict[int, int], dp_min: Optional[int] = None,
    ) -> List[Tuple[int, List]]:
        """Partition one kind group's submissions across dp shards:
        at most ``n_group // dp_min`` shards participate (a shard
        must be worth its dispatch overhead; ``dp_min`` defaults to
        the deadline class's ``dp_min_sets`` — bulk raises it to
        :data:`BULK_DP_MIN_SETS`), chosen least-loaded
        first; big submissions greedily land on the least-loaded chosen
        shard. Deterministic (sorted, index tie-breaks) — the lockstep
        replay's byte-identical-across-processes gate covers dp plans
        too. Submissions NEVER split across shards."""
        if dp_min is None:
            dp_min = self.dp_min_sets
        k = min(len(shards), max(1, n_group // dp_min))
        if k <= 1:
            s = min(shards, key=lambda i: (shard_load[i], i))
            shard_load[s] += n_group
            return [(s, members)]
        chosen = sorted(shards, key=lambda i: (shard_load[i], i))[:k]
        buckets: Dict[int, List] = {s: [] for s in chosen}
        local: Dict[int, int] = {s: 0 for s in chosen}
        order = sorted(
            range(len(members)), key=lambda i: (-len(members[i].sets), i)
        )
        for i in order:
            sub = members[i]
            s = min(chosen, key=lambda j: (local[j], j))
            buckets[s].append(sub)
            local[s] += len(sub.sets)
        # enforce the floor AFTER the greedy pass: skewed atomic
        # submissions (one 16-set + one 2-set) can leave a shard below
        # dp_min_sets — merge it into the least-loaded other shard so
        # no dispatch is ever worth less than the floor the knob
        # documents. Terminates: every merge removes a bucket.
        while len(buckets) > 1:
            under = [s for s in buckets if local[s] < dp_min]
            if not under:
                break
            s = min(under, key=lambda j: (local[j], j))
            tgt = min(
                (t for t in buckets if t != s),
                key=lambda j: (local[j], j),
            )
            buckets[tgt].extend(buckets.pop(s))
            local[tgt] += local.pop(s)
        for s, n in local.items():
            shard_load[s] += n
        return [(s, buckets[s]) for s in sorted(buckets) if buckets[s]]
