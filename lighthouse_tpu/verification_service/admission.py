"""Headroom-driven admission control for the bulk QoS class (ISSUE 15).

The scheduler's bulk class (``batcher.py``) exists so chain-segment
backfill and slasher-style ingest can saturate the device WITHOUT
moving gossip's p99. Queue priority alone is not enough: once demand
crosses serving capacity, every bulk set the scheduler still admits is
a set the deadline class will eventually queue behind. This module is
the valve — it watches the two signals PR 14 built exactly for this
decision and pauses bulk admission while either says the node is out
of slack:

* **capacity headroom** (``utils/timeseries.py``,
  ``capacity_headroom_ratio`` = max(0, 1 − arrival/capacity)): when the
  live estimate drops below ``floor`` (default 0.10,
  ``LIGHTHOUSE_TPU_SCHED_BULK_HEADROOM_FLOOR``) the node is close
  enough to saturation that bulk must stop feeding the queue. The dial
  is PREDICTIVE — on a saturation ramp it crosses before the first
  deadline-miss burst (pinned by ``tests/test_timeseries_capacity.py``)
  — so the throttle lands before gossip pays, not after. An UNKNOWN
  headroom (sampler disabled, no cost measured yet) is treated as "no
  signal", never as "no headroom": a box without the estimator keeps
  the pre-admission-control behavior instead of banning bulk forever.
* **the SLO burn latch** (``slo.py``, ``latched_kinds()``): a confirmed
  ``slo_burn`` excursion on ANY deadline-class kind — bulk samples
  never reach the burn buckets, so any latch IS a gossip kind — pauses
  bulk immediately. This is the retrospective backstop for whatever the
  estimator did not foresee.

**Hysteresis.** Throttle state resumes only when BOTH signals clear:
the burn latch must have expired (no confirmed alert for a full fast
window) AND headroom must have recovered past ``resume_headroom``
(default 0.20, ``LIGHTHOUSE_TPU_SCHED_BULK_RESUME_HEADROOM``), not just
back above the floor — a dial oscillating around the floor must not
flap the valve once per sample.

**One journal event per excursion.** Entering the throttled state
journals ONE ``bulk_throttle`` flight-recorder event (with the reason,
the headroom reading and the latched kinds); leaving it journals ONE
``bulk_resume`` (with the excursion's duration). A continuing excursion
re-confirms silently — the journal records state TRANSITIONS, the
``verification_scheduler_bulk_throttled`` gauge records state.

**Degradation order** (docs/VERIFICATION_SERVICE.md): losing headroom
sheds bulk FIRST — bulk flushes pause while queued bulk waits; a bulk
queue overflow degrades the submission to its CALLER's thread (the
self-paced pre-scheduler behavior — never to gossip's flush thread);
gossip's deadline class is untouched throughout.

Deliberately **jax-free** (the verification_service import rule) and
dependency-injected: ``headroom_fn`` defaults to the live
``timeseries.last_estimate()`` read but tests drive the controller with
a scripted dial, so every transition is pinned deterministically
(``tests/test_bulk_qos.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..utils import flight_recorder, metrics

DEFAULT_HEADROOM_FLOOR = 0.10
DEFAULT_RESUME_HEADROOM = 0.20
# evaluate() is called on every bulk submit and every flush-loop wake;
# the signals only move at sampler cadence, so re-reads are throttled
DEFAULT_MIN_INTERVAL_S = 0.05

_ENV_FLOOR = "LIGHTHOUSE_TPU_SCHED_BULK_HEADROOM_FLOOR"
_ENV_RESUME = "LIGHTHOUSE_TPU_SCHED_BULK_RESUME_HEADROOM"

_env_float = flight_recorder._env_float

_THROTTLED = metrics.gauge(
    "verification_scheduler_bulk_throttled",
    "1 while bulk admission is paused (headroom below the floor or a "
    "gossip slo_burn latch live), 0 while bulk flows — state; the "
    "transitions are journaled as bulk_throttle/bulk_resume events and "
    "counted in verification_scheduler_bulk_throttle_events_total",
)
_THROTTLE_EVENTS = metrics.counter_vec(
    "verification_scheduler_bulk_throttle_events_total",
    "bulk-admission throttle excursions entered, by triggering reason "
    "(headroom = capacity_headroom_ratio below the floor, slo_burn = a "
    "deadline-class burn latch) — one tick per excursion, not per "
    "evaluation; resumes are the bulk_resume journal events",
    ("reason",),
)


def _live_headroom() -> Optional[float]:
    """The default headroom feed: the capacity estimator's latest
    ``headroom_ratio`` (None when the sampler is off or no cost has
    been measured — 'no signal', never 'no headroom'). Lazy import so
    this module stays cheap and jax-free at import."""
    try:
        from ..utils import timeseries

        est = timeseries.last_estimate()
        if est is None:
            return None
        return est.get("headroom_ratio")
    except Exception:
        return None


class BulkAdmissionController:
    """The bulk-admission valve (module docstring). ``evaluate()``
    returns True while bulk may flush/admit; the scheduler calls it on
    every bulk submit and every flush-loop wake. ``tracker`` is bound
    by the scheduler to ITS SloTracker when not injected."""

    def __init__(
        self,
        headroom_fn: Optional[Callable[[], Optional[float]]] = None,
        tracker=None,
        floor: float | None = None,
        resume_headroom: float | None = None,
        min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
    ):
        self.headroom_fn = headroom_fn or _live_headroom
        self.tracker = tracker
        self.floor = max(0.0, float(
            floor if floor is not None
            else _env_float(_ENV_FLOOR, DEFAULT_HEADROOM_FLOOR)
        ))
        self.resume_headroom = max(self.floor, float(
            resume_headroom if resume_headroom is not None
            else _env_float(_ENV_RESUME, DEFAULT_RESUME_HEADROOM)
        ))
        self.min_interval_s = max(0.0, float(min_interval_s))
        self._lock = threading.Lock()
        self._throttled = False
        self._reason: Optional[str] = None
        self._since: Optional[float] = None
        self._last_eval = -float("inf")
        self._last_headroom: Optional[float] = None
        self._excursions = 0
        # the process-global gauge is deliberately NOT reset here: a
        # second controller constructed in-process (a replay tool, a
        # test helper, another scheduler) must not wipe a live
        # scheduler's throttle state off /metrics — gauges register at
        # 0 and only TRANSITIONS write it

    # -- the valve ---------------------------------------------------------

    def throttled(self) -> bool:
        with self._lock:
            return self._throttled

    def evaluate(self, now: float | None = None, force: bool = False) -> bool:
        """Re-read the signals and drive the throttle latch; returns
        True when bulk is admitted. Rate-limited internally (the
        signals move at sampler cadence); transitions journal exactly
        once per excursion. ``force`` skips the rate limit — the
        scheduler forces on every bulk ARRIVAL so the first submission
        after a signal collapse journals its ``bulk_throttle`` before
        any of its sets could queue (bulk arrivals are big, self-paced
        chunks; the per-arrival re-read is cheap and the rate limit
        exists for the flush loop's tight wake cadence, not for them).
        Never raises — a broken signal read must not take the flush
        thread down, and reads as 'no signal'."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if not force and now - self._last_eval < self.min_interval_s:
                return not self._throttled
            self._last_eval = now
        try:
            headroom = self.headroom_fn()
        except Exception:
            headroom = None
        try:
            latched = (
                self.tracker.latched_kinds(now)
                if self.tracker is not None else []
            )
        except Exception:
            latched = []
        fire = resume = None
        with self._lock:
            self._last_headroom = headroom
            if not self._throttled:
                reason = None
                if latched:
                    reason = "slo_burn"
                elif headroom is not None and headroom < self.floor:
                    reason = "headroom"
                if reason is not None:
                    self._throttled = True
                    self._reason = reason
                    self._since = now
                    self._excursions += 1
                    fire = reason
            else:
                # hysteresis: BOTH signals must clear, and headroom must
                # recover past resume_headroom, not just the floor
                if not latched and (
                    headroom is None or headroom >= self.resume_headroom
                ):
                    resume = round(now - (self._since or now), 3)
                    self._throttled = False
                    self._reason = None
                    self._since = None
            admitted = not self._throttled
        if fire is not None:
            _THROTTLED.set(1)
            _THROTTLE_EVENTS.with_labels(fire).inc()
            flight_recorder.record(
                "bulk_throttle",
                reason=fire,
                headroom=headroom,
                floor=self.floor,
                resume_headroom=self.resume_headroom,
                latched_kinds=",".join(latched),
            )
        elif resume is not None:
            _THROTTLED.set(0)
            flight_recorder.record(
                "bulk_resume",
                headroom=headroom,
                resume_headroom=self.resume_headroom,
                throttled_s=resume,
            )
        return admitted

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """The admission block of the scheduler's health document."""
        with self._lock:
            return {
                "throttled": self._throttled,
                "reason": self._reason,
                "throttled_s": (
                    round(time.monotonic() - self._since, 3)
                    if self._since is not None else None
                ),
                "excursions_total": self._excursions,
                "headroom_floor": self.floor,
                "resume_headroom": self.resume_headroom,
                "last_headroom": self._last_headroom,
            }
