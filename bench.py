"""North-star benchmark: BLS signature-set verifications/sec on one chip.

Workload shape follows BASELINE.md config #3 (gossip aggregate batch):
each aggregate attestation costs three signature sets (selection proof,
aggregator signature, aggregate attestation signature over the committee —
reference ``beacon_node/beacon_chain/src/attestation_verification/batch.rs:77-107``).

END-TO-END measurement (VERDICT r1 weakness #3): every rep re-packs the
raw (compressed-signature, pubkeys, message) sets — host byte wrangling +
randomness + hash_to_field only — and runs the device program, which
DECOMPRESSES the signatures, hashes the messages to G2 and verifies, all
on device. No host big-int math in the hot path.

Robustness (round-1 BENCH died at TPU init): the TPU backend is probed in
a SUBPROCESS with a deadline first; if the probe fails or times out the
bench falls back to the CPU backend so a measurement is always printed.
Persistent compilation cache keeps the recurring driver runs cheap.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` is the ratio of the measured device throughput to the
NATIVE C CPU baseline (`_native/bls12381.c`, backend "cpu-native" — the
blst-class baseline BASELINE.md demands) measured in-process on the SAME
workload; ``vs_target`` tracks the 50k aggregate-verifications/sec goal
from BASELINE.json (one aggregate = 3 sets). The line also stamps
``backend`` ("tpu" | "cpu-fallback") and the padded bucket shapes so a
fallback run can never masquerade as the TPU metric (VERDICT r2 weak #1).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Full geometry (TPU): one gossip aggregate batch, reference mix.
N_AGG = 64
COMMITTEE = 16
N_MSGS = 8
B_PAD = 256
K_PAD = 16
M_PAD = 8
TARGET_AGG_PER_SEC = 50_000.0
INIT_TIMEOUT_S = 60      # backend init (a dead tunnel hangs forever)
PROBE_TIMEOUT_S = 420    # full warm-up compile budget


def _shrink_for_cpu_fallback() -> None:
    """The CPU fallback exists to ALWAYS print a measurement, not to be
    fast — shrink the workload so host-oracle setup + the XLA:CPU compile
    + runs fit a tight driver budget. Throughput extrapolates."""
    global N_AGG, COMMITTEE, N_MSGS, B_PAD, K_PAD, M_PAD
    N_AGG = 16
    COMMITTEE = 8
    N_MSGS = 4
    B_PAD = 64
    K_PAD = 8
    M_PAD = 4


def probe_tpu() -> bool:
    """Is the TPU backend usable within budget? The probe runs in a
    SUBPROCESS (a hung tunnel cannot wedge the bench) and performs the
    full warm-up compile of the bench program at the bench bucket shapes
    with the persistent compile cache enabled — if it completes, the main
    process's compile is either cached or proven feasible; if it times
    out or dies, the bench falls back to CPU and still prints a number."""
    # stage 1: can the backend initialize at all? (fast fail on a dead
    # relay — jax.devices() otherwise blocks indefinitely)
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=INIT_TIMEOUT_S,
            capture_output=True,
        )
        if r.returncode != 0:
            return False
    except subprocess.TimeoutExpired:
        return False

    cache_dir = os.path.join(os.path.dirname(__file__) or ".", ".jax_cache")
    code = f"""
import jax
assert jax.devices()[0].platform != "cpu"
try:
    jax.config.update("jax_compilation_cache_dir", {cache_dir!r})
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass
import numpy as np, jax.numpy as jnp
from lighthouse_tpu.crypto.device import fp
from lighthouse_tpu.crypto.device.bls import verify_batch_raw_fn
args = (
    jnp.zeros(({B_PAD}, {K_PAD}, 2, fp.NL), jnp.int32),
    jnp.zeros(({B_PAD}, {K_PAD}), bool),
    jnp.zeros(({B_PAD}, 2, fp.NL), jnp.int32),
    jnp.zeros(({B_PAD},), bool),
    jnp.zeros(({M_PAD}, 2, 2, fp.NL), jnp.int32),
    jnp.zeros(({B_PAD},), jnp.int32),
    jnp.zeros(({B_PAD}, 2), jnp.int32),
    jnp.zeros(({B_PAD},), bool),
)
jax.jit(verify_batch_raw_fn).lower(*args).compile()
print("COMPILE_OK")
"""
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=PROBE_TIMEOUT_S,
            capture_output=True,
        )
        return r.returncode == 0 and b"COMPILE_OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def build_sets():
    """Raw signature sets, reference mix: per aggregate, two single-pubkey
    sets + one committee set. Aggregate signatures are produced with the
    summed secret key (same group element as aggregating per-signer
    signatures) to keep host-oracle setup time bounded."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.crypto.params import R

    sks = [bls.SecretKey(1_000 + i) for i in range(COMMITTEE)]
    pks = [sk.public_key().point for sk in sks]
    sk_agg = bls.SecretKey(sum(1_000 + i for i in range(COMMITTEE)) % R)
    msgs = [bytes([m + 1]) * 32 for m in range(N_MSGS)]
    # signatures stay COMPRESSED (lazy Signature): the device decompresses
    single0 = {m: bls.Signature.deserialize(sks[0].sign(m).serialize()) for m in msgs}
    single1 = {m: bls.Signature.deserialize(sks[1].sign(m).serialize()) for m in msgs}
    agg = {m: bls.Signature.deserialize(sk_agg.sign(m).serialize()) for m in msgs}

    sets = []
    for i in range(N_AGG):
        m = msgs[i % N_MSGS]
        sets.append((single0[m], [pks[0]], m))
        sets.append((single1[m], [pks[1]], m))
        sets.append((agg[m], pks, m))
    return sets


def measure_native_baseline(sets) -> float | None:
    """sets/s of the native C backend on the same workload (the reference
    seam, blst.rs:36-119, measured as BASELINE.md requires). None when no
    C toolchain is available."""
    try:
        from lighthouse_tpu.crypto.native import NativeBackend

        native = NativeBackend()
    except Exception:
        return None
    assert native.verify_signature_sets(sets) is True
    reps = 2
    t0 = time.perf_counter()
    for _ in range(reps):
        native.verify_signature_sets(sets)
    dt = (time.perf_counter() - t0) / reps
    return len(sets) / dt


def main() -> None:
    use_cpu = not probe_tpu()
    if use_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        _shrink_for_cpu_fallback()

    import jax

    if use_cpu:
        # The env var alone does NOT stop the axon plugin from initializing
        # (and hanging on a dead tunnel); the config knob does.
        jax.config.update("jax_platforms", "cpu")

    try:
        cache_dir = os.path.join(os.path.dirname(__file__) or ".", ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from lighthouse_tpu.crypto.device.bls import (
        pack_signature_sets_raw,
        verify_batch_raw,
    )

    sets = build_sets()
    n_sets = len(sets)

    def run_once():
        args = pack_signature_sets_raw(
            sets, pad_b=B_PAD, pad_k=K_PAD, pad_m=M_PAD
        )
        out = verify_batch_raw(*args)
        jax.block_until_ready(out)
        return out

    ok = run_once()  # warm-up: compile
    assert bool(ok) is True, "benchmark batch must verify"

    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run_once()
    dt = (time.perf_counter() - t0) / reps

    sets_per_sec = n_sets / dt
    agg_per_sec = N_AGG / dt

    baseline = measure_native_baseline(sets)
    print(
        json.dumps(
            {
                "metric": "bls_sigset_verifications_per_sec_per_chip",
                "value": round(sets_per_sec, 2),
                "unit": "sets/s",
                "vs_baseline": (
                    round(sets_per_sec / baseline, 4) if baseline else 0.0
                ),
                "vs_target": round(agg_per_sec / TARGET_AGG_PER_SEC, 4),
                "backend": "cpu-fallback" if use_cpu else "tpu",
                "baseline_backend": "cpu-native" if baseline else "unavailable",
                "baseline_sets_per_sec": round(baseline, 2) if baseline else None,
                "shapes": {"B": B_PAD, "K": K_PAD, "M": M_PAD, "n_sets": n_sets},
            }
        )
    )


if __name__ == "__main__":
    main()
