"""North-star benchmark: BLS signature-set verifications/sec on one chip.

Workload shape follows BASELINE.md config #3 (gossip aggregate batch):
each aggregate attestation costs three signature sets (selection proof,
aggregator signature, aggregate attestation signature over the committee —
reference ``beacon_node/beacon_chain/src/attestation_verification/batch.rs:77-107``).

END-TO-END measurement (VERDICT r1 weakness #3): every rep re-packs the
raw (compressed-signature, pubkeys, message) sets — host byte wrangling +
randomness + hash_to_field only — and runs the STAGED device pipeline
(``verify_batch_raw_staged``: decompression, hash-to-curve, aggregation,
subgroup checks and the multi-pairing all on device, three jitted stages
that cache independently).

Hardening (VERDICT r4 item #8):
* median-of-5 timing on BOTH legs (device and native-C baseline) with
  spread recorded, instead of mean-of-2;
* committee-size buckets K in {16, 128, 512} measured separately
  (mainnet committees are ~128-512; K=16 alone understates padding) with
  the padding-waste fraction per bucket;
* a wall-clock budget: buckets are skipped (and marked) rather than
  blowing the driver's window — silent truncation would read as
  "covered everything".

Robustness (round-1 BENCH died at TPU init): the TPU backend is probed in
a SUBPROCESS with a deadline first; if the probe fails or times out the
bench falls back to the CPU backend so a measurement is always printed.
Persistent compilation cache keeps recurring runs cheap.

Per-impl legs (VERDICT r5 rec #2): the headline bucket is re-measured
under ``FP_IMPL=matmul_int8`` (the int8 limb-split MXU decomposition of
``fp.mul``) after the toeplitz_int32 headline, and both land in
``fp_impl_legs`` so rounds can track the contraction engines separately.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` is the ratio of the measured device throughput to the
NATIVE C CPU baseline (`_native/bls12381.c`, backend "cpu-native" — the
blst-class baseline BASELINE.md demands) measured in-process on the SAME
workload; ``vs_target`` tracks the 50k aggregate-verifications/sec goal
from BASELINE.json (one aggregate = 3 sets). The line also stamps
``backend`` ("tpu" | "cpu-fallback") and the padded bucket shapes so a
fallback run can never masquerade as the TPU metric (VERDICT r2 weak #1).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

# Headline geometry (TPU): one gossip aggregate batch, reference mix.
N_AGG = 64
COMMITTEE = 16
N_MSGS = 8
B_PAD = 256
K_PAD = 16
M_PAD = 8
# Extra committee-size buckets (mainnet: ~128-512 validators/committee).
# Per bucket: 8 aggregates x 3 sets, padded to B=32 lanes.
EXTRA_BUCKETS = [
    {"K": 128, "n_agg": 8, "B": 32, "M": 4},
    {"K": 512, "n_agg": 8, "B": 32, "M": 4},
]
TARGET_AGG_PER_SEC = 50_000.0
INIT_TIMEOUT_S = 60      # backend init (a dead tunnel hangs forever)
PROBE_TIMEOUT_S = 420    # full warm-up compile budget
BENCH_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "2400"))
REPS = 5

_T0 = time.perf_counter()


def _budget_left() -> float:
    return BENCH_BUDGET_S - (time.perf_counter() - _T0)


def _configure_jax_cache(jax) -> None:
    """Persistent compile cache for every bench process (probe subprocess
    carries its own textual copy inside its ``-c`` program)."""
    try:
        cache_dir = os.path.join(os.path.dirname(__file__) or ".", ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def _shrink_for_cpu_fallback() -> None:
    """The CPU fallback exists to ALWAYS print a measurement, not to be
    fast — shrink the workload so host-oracle setup + the XLA:CPU compile
    + runs fit a tight driver budget. Throughput extrapolates."""
    global N_AGG, COMMITTEE, N_MSGS, B_PAD, K_PAD, M_PAD, EXTRA_BUCKETS
    N_AGG = 16
    COMMITTEE = 8
    N_MSGS = 4
    B_PAD = 64
    K_PAD = 8
    M_PAD = 4
    EXTRA_BUCKETS = []


def probe_tpu() -> bool:
    """Is the TPU backend usable within budget? The probe runs in a
    SUBPROCESS (a hung tunnel cannot wedge the bench) and performs the
    full warm-up compile of the STAGED bench program at the bench bucket
    shapes with the persistent compile cache enabled — if it completes,
    the main process's compile is either cached or proven feasible; if it
    times out or dies, the bench falls back to CPU and still prints a
    number."""
    # stage 1: can the backend initialize at all? (fast fail on a dead
    # relay — jax.devices() otherwise blocks indefinitely)
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=INIT_TIMEOUT_S,
            capture_output=True,
        )
        if r.returncode != 0:
            return False
    except subprocess.TimeoutExpired:
        return False

    cache_dir = os.path.join(os.path.dirname(__file__) or ".", ".jax_cache")
    code = f"""
import jax
assert jax.devices()[0].platform != "cpu"
try:
    jax.config.update("jax_compilation_cache_dir", {cache_dir!r})
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass
import numpy as np, jax.numpy as jnp
from lighthouse_tpu.crypto.device import fp
from lighthouse_tpu.crypto.device.bls import verify_batch_raw_staged
args = (
    jnp.zeros(({B_PAD}, {K_PAD}, 2, fp.NL), jnp.int32),
    jnp.zeros(({B_PAD}, {K_PAD}), bool),
    jnp.zeros(({B_PAD}, 2, fp.NL), jnp.int32),
    jnp.zeros(({B_PAD},), bool),
    jnp.zeros(({M_PAD}, 2, 2, fp.NL), jnp.int32),
    jnp.zeros(({B_PAD},), jnp.int32),
    jnp.zeros(({B_PAD}, 2), jnp.int32),
    jnp.zeros(({B_PAD},), bool),
)
out = verify_batch_raw_staged(*args)
jax.block_until_ready(out)
print("COMPILE_OK")
"""
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=PROBE_TIMEOUT_S,
            capture_output=True,
        )
        return r.returncode == 0 and b"COMPILE_OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def build_sets(n_agg: int, committee: int, n_msgs: int):
    """Raw signature sets, reference mix: per aggregate, two single-pubkey
    sets + one committee set. Aggregate signatures are produced with the
    summed secret key (same group element as aggregating per-signer
    signatures) to keep host-oracle setup time bounded."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.crypto.params import R

    sks = [bls.SecretKey(1_000 + i) for i in range(committee)]
    pks = [sk.public_key().point for sk in sks]
    sk_agg = bls.SecretKey(sum(1_000 + i for i in range(committee)) % R)
    msgs = [bytes([m + 1]) * 32 for m in range(n_msgs)]
    # signatures stay COMPRESSED (lazy Signature): the device decompresses
    single0 = {m: bls.Signature.deserialize(sks[0].sign(m).serialize()) for m in msgs}
    single1 = {m: bls.Signature.deserialize(sks[1].sign(m).serialize()) for m in msgs}
    agg = {m: bls.Signature.deserialize(sk_agg.sign(m).serialize()) for m in msgs}

    sets = []
    for i in range(n_agg):
        m = msgs[i % n_msgs]
        sets.append((single0[m], [pks[0]], m))
        sets.append((single1[m], [pks[1]], m))
        sets.append((agg[m], pks, m))
    return sets


def _median_spread(samples: list[float]) -> tuple[float, float]:
    med = statistics.median(samples)
    spread = (max(samples) - min(samples)) / med if med else 0.0
    return med, spread


def measure_scheduler_leg(sets, B, K, M, n_callers: int = 4, reps: int = 3):
    """Fused-scheduler vs direct per-caller throughput at the headline
    geometry (ISSUE 4). Both legs run the SAME compiled staged program at
    the SAME padded shape — no new XLA compiles: the `direct` leg pays
    ``n_callers`` dispatches of a 1/n-occupied bucket (the fragmented
    traffic shape the scheduler exists to fix), the `fused` leg pays one
    full-occupancy dispatch assembled by concurrent ``submit()`` calls
    from ``n_callers`` feeder threads."""
    import threading

    import jax

    from lighthouse_tpu.crypto.device.bls import (
        pack_signature_sets_raw,
        verify_batch_raw_staged,
    )
    from lighthouse_tpu.verification_service import VerificationScheduler

    chunk = (len(sets) + n_callers - 1) // n_callers
    chunks = [sets[i: i + chunk] for i in range(0, len(sets), chunk)]

    def device_verify(s):
        args = pack_signature_sets_raw(s, pad_b=B, pad_k=K, pad_m=M)
        return bool(jax.block_until_ready(verify_batch_raw_staged(*args)))

    assert device_verify(sets) is True  # warm (shape compiled by headline)

    direct = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for c in chunks:
            assert device_verify(c)
        direct.append(time.perf_counter() - t0)

    kinds = ("unaggregated", "aggregate", "sync_message", "sync_contribution")
    sched = VerificationScheduler(
        verify_fn=device_verify,
        deadline_ms=2000.0,
        max_batch_sets=len(sets),  # bucket-full fires on the last feeder
        max_queue_sets=4 * len(sets),
    ).start()
    fused = []
    try:
        for _ in range(reps):
            futs = [None] * len(chunks)

            def feed(i):
                futs[i] = sched.submit(chunks[i], kinds[i % len(kinds)])

            threads = [
                threading.Thread(target=feed, args=(i,))
                for i in range(len(chunks))
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert all(f.result(timeout=600) for f in futs)
            fused.append(time.perf_counter() - t0)
    finally:
        sched.stop()

    d_med, d_spread = _median_spread(direct)
    f_med, f_spread = _median_spread(fused)
    n = len(sets)
    return {
        "n_callers": len(chunks),
        "sets_per_caller": chunk,
        "B": B, "K": K, "M": M, "reps": reps,
        "direct_sets_per_sec": round(n / d_med, 2),
        "direct_rep_spread": round(d_spread, 3),
        "fused_sets_per_sec": round(n / f_med, 2),
        "fused_rep_spread": round(f_spread, 3),
        "fused_vs_direct": round(d_med / f_med, 4),
    }


def measure_planner_leg(sets, B, K, M, reps: int = 3):
    """Planned multi-rung flush vs legacy single-rung flush at the
    headline shape (ISSUE 6), same warm cache. The LEGACY leg is the
    pre-planner behavior: the whole fused mix padded onto the one
    headline rung (B, K, M) — already compiled by the headline bucket,
    so it pays zero new XLA work. The PLANNED leg routes the same
    traffic through the scheduler's shape-aware planner: kind-
    homogeneous sub-batches (single-pubkey gossip sets on a K=1 rung,
    committee sets on a small-B rung) whose compiles are paid once in
    the warm-up flush; the measured reps then run at steady state —
    recompile delta recorded to prove it stays 0. Per-leg sets/s and
    padding_waste (the shared B*K*M lane formula) land in the JSON."""
    import threading

    import jax

    from lighthouse_tpu.crypto.device import bls as device_bls
    from lighthouse_tpu.utils import metrics
    from lighthouse_tpu.verification_service import (
        VerificationScheduler,
        live_lanes,
        padded_lanes,
        padding_waste_ratio,
    )

    singles = [s for s in sets if len(s[1]) == 1]
    committees = [s for s in sets if len(s[1]) > 1]
    if not singles or not committees:
        return {"skipped": "workload has no kind mix to split"}
    n = len(sets)
    live = live_lanes(
        sum(len(pks) for _, pks, _ in sets),
        len({bytes(m) for _, _, m in sets}),
    )
    # kind-faithful submissions: the two single-pubkey sets per
    # aggregate are unaggregated-style, the committee set aggregate-style
    subs = [("unaggregated", singles), ("aggregate", committees)]

    def legacy_verify(s):
        # pad-everything-to-the-headline-rung: the pre-planner flush
        args = device_bls.pack_signature_sets_raw(
            s, pad_b=B, pad_k=K, pad_m=M
        )
        return bool(
            jax.block_until_ready(device_bls.verify_batch_raw_staged(*args))
        )

    def _recompiles() -> float:
        m = metrics.get("bls_device_recompiles_total")
        return sum(c.value for c in m.children().values()) if m else 0.0

    def _gauge(name) -> float:
        m = metrics.get(name)
        return float(m.value) if m is not None else float("nan")

    def run_flush(sched) -> float:
        futs = [None] * len(subs)

        def feed(i):
            futs[i] = sched.submit(subs[i][1], subs[i][0])

        threads = [
            threading.Thread(target=feed, args=(i,))
            for i in range(len(subs))
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert all(f.result(timeout=1800) for f in futs)
        return time.perf_counter() - t0

    def measure(verify_fn, plan_on):
        sched = VerificationScheduler(
            verify_fn=verify_fn, deadline_ms=10_000.0,
            max_batch_sets=n,  # bucket-full fires on the last feeder
            max_queue_sets=4 * n, plan_flushes=plan_on,
        ).start()
        try:
            run_flush(sched)  # warm-up: the planned leg's compiles land here
            rec_before = _recompiles()
            samples = [run_flush(sched) for _ in range(reps)]
            steady_recompiles = _recompiles() - rec_before
            st = sched.status()
        finally:
            sched.stop()
        med, spread = _median_spread(samples)
        return {
            "sets_per_sec": round(n / med, 2),
            "rep_spread": round(spread, 3),
            "steady_recompiles": steady_recompiles,
            "plan": st["planner"]["last_plan"],
            "scheduler_waste_gauge": round(
                _gauge("verification_scheduler_padding_waste_ratio"), 4
            ),
            "device_waste_gauge": round(
                _gauge("bls_device_padding_waste_ratio"), 4
            ),
        }

    legacy = measure(legacy_verify, plan_on=False)
    # the legacy verify bypasses TpuBackend and pads to the HEADLINE
    # rung (B, K, M), not the scheduler plan's exact rung, so neither
    # gauge describes what it actually dispatched — report its waste
    # from the same shared formula at the rung it really padded to and
    # drop the gauge readings rather than ship contradictory numbers
    legacy["padding_waste"] = round(
        padding_waste_ratio(live, padded_lanes(B, K, M)), 4
    )
    legacy["rung"] = [B, K, M]
    del legacy["device_waste_gauge"]  # not touched by the direct packer
    del legacy["scheduler_waste_gauge"]  # reflects the plan, not the pad
    del legacy["plan"]  # ditto: the plan's exact rung was never dispatched

    planned = measure(
        device_bls.TpuBackend().verify_signature_sets, plan_on=True
    )
    planned["padding_waste"] = planned["plan"]["padding_waste"]

    return {
        "n_sets": n,
        "reps": reps,
        "legacy": legacy,
        "planned": planned,
        "planned_vs_legacy": round(
            planned["sets_per_sec"] / legacy["sets_per_sec"], 4
        ) if legacy["sets_per_sec"] else None,
    }


def measure_pipeline_leg(sets, B, K, M, reps: int = 3, n_callers: int = 4):
    """Pipeline-occupancy profile at the headline rung (ISSUE 12):
    device bubble ratio with cause attribution, flush-thread saturation
    and the overlap-potential projection — the sizing input for ROADMAP
    item 5's double-buffered pack pipeline, measured through the REAL
    scheduler at the already-warm headline shape (plan_flushes off +
    headline pad = zero new XLA compiles; the steady-recompile delta
    pins it). ``bubble_ratio`` feeds the bench_diff gate."""
    import threading

    import jax

    from lighthouse_tpu.crypto.device.bls import (
        pack_signature_sets_raw,
        verify_batch_raw_staged,
    )
    from lighthouse_tpu.utils import metrics, pipeline_profiler
    from lighthouse_tpu.verification_service import VerificationScheduler

    if not pipeline_profiler.enabled():
        return {"skipped": "pipeline profiler disabled"}

    def device_verify(s):
        args = pack_signature_sets_raw(s, pad_b=B, pad_k=K, pad_m=M)
        return bool(jax.block_until_ready(verify_batch_raw_staged(*args)))

    # -O-safe warm-up raise (the headline bucket already compiled this
    # shape; a failure here is a workload bug, not a compile)
    if device_verify(sets) is not True:
        raise RuntimeError("pipeline leg warm-up batch must verify")

    def _recompiles() -> float:
        m = metrics.get("bls_device_recompiles_total")
        return sum(c.value for c in m.children().values()) if m else 0.0

    pipeline_profiler.reset()
    rec0 = _recompiles()
    chunk = (len(sets) + n_callers - 1) // n_callers
    chunks = [sets[i: i + chunk] for i in range(0, len(sets), chunk)]
    kinds = ("unaggregated", "aggregate", "sync_message", "sync_contribution")
    sched = VerificationScheduler(
        verify_fn=device_verify,
        deadline_ms=2000.0,
        max_batch_sets=len(sets),  # bucket-full fires on the last feeder
        max_queue_sets=4 * len(sets),
        plan_flushes=False,  # keep every flush on the one warm rung
    ).start()
    try:
        for _ in range(reps):
            futs = [None] * len(chunks)

            def feed(i):
                futs[i] = sched.submit(chunks[i], kinds[i % len(kinds)])

            threads = [
                threading.Thread(target=feed, args=(i,))
                for i in range(len(chunks))
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            if not all(f.result(timeout=1800) for f in futs):
                raise RuntimeError("pipeline leg flushes must verify")
    finally:
        sched.stop()
    doc = pipeline_profiler.summary()
    shard0 = doc["shards"].get("0", {})
    ov = doc["overlap_potential"]
    return {
        "B": B, "K": K, "M": M, "n_sets": len(sets), "reps": reps,
        "flushes": doc["flushes"]["count"],
        "steady_recompiles": _recompiles() - rec0,
        "bubble_ratio": shard0.get("bubble_ratio"),
        "dominant_bubble_cause": shard0.get("dominant_cause"),
        "bubble_causes_s": shard0.get("causes"),
        "flush_thread_saturation": doc["flush_thread_saturation"],
        "flush_phases_s": {
            p: doc["flushes"][f"{p}_s"]
            for p in pipeline_profiler.FLUSH_PHASES
        },
        "flush_wall_s": doc["flushes"]["wall_s"],
        "overlap": {
            "measured_sets_per_sec": ov["measured_sets_per_sec"],
            "projected_sets_per_sec": ov["projected_sets_per_sec"],
            "projected_speedup": ov["projected_speedup"],
        },
    }


def measure_key_table_leg(sets, B, K, M, reps: int = 3):
    """Device-resident pubkey table on/off at the headline bucket
    (ISSUE 10), same repeat-validator traffic both legs: the OFF leg
    re-packs and re-ships the G1 limb planes every rep (the measured
    >0.9 re-upload shape the table exists to kill), the ON leg ships a
    (B, K) index plane and gathers device-side. Both legs dispatch the
    SAME already-warm staged rung; the ON leg's one new compile is the
    sub-second gather program, paid in its warm-up rep and pinned by
    the steady-recompile delta. Per-leg pubkeys bytes/set (the
    acceptance metric, live operand), pack seconds and sets/s land in
    the JSON; ``pubkeys_bytes_per_set`` feeds the bench_diff gate."""
    import types as _types

    import jax

    from lighthouse_tpu.crypto.device import bls as device_bls
    from lighthouse_tpu.crypto.device import key_table as key_table_mod
    from lighthouse_tpu.utils import metrics, transfer_ledger

    if not transfer_ledger.enabled():
        return {"skipped": "transfer ledger disabled"}

    n = len(sets)

    def _pubkeys_bytes():
        doc = transfer_ledger.summary()
        return doc.get("h2d_bytes_by_operand", {}).get("pubkeys", 0)

    def _pack_total_s():
        doc = transfer_ledger.summary()
        return doc.get("pack_seconds", {}).get("total", {}).get("sum_s", 0.0)

    def _recompiles() -> float:
        m = metrics.get("bls_device_recompiles_total")
        return sum(c.value for c in m.children().values()) if m else 0.0

    def _measure(run_once):
        # warm-up (compiles land here); -O-safe — an assert would strip
        # the warm-up itself and bill the first timed rep for the compile
        if run_once() is not True:
            raise RuntimeError("key-table leg warm-up batch must verify")
        rec0 = _recompiles()
        pk0, pack0 = _pubkeys_bytes(), _pack_total_s()
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run_once()
            samples.append(time.perf_counter() - t0)
        med, spread = _median_spread(samples)
        return {
            "sets_per_sec": round(n / med, 2),
            "rep_spread": round(spread, 3),
            "pubkeys_bytes_per_set": round(
                (_pubkeys_bytes() - pk0) / (reps * n), 1
            ),
            "pack_s_per_batch": round((_pack_total_s() - pack0) / reps, 4),
            "steady_recompiles": _recompiles() - rec0,
        }

    def run_off():
        args = device_bls.pack_signature_sets_raw(
            sets, pad_b=B, pad_k=K, pad_m=M
        )
        return bool(
            jax.block_until_ready(device_bls.verify_batch_raw_staged(*args))
        )

    off = _measure(run_off)

    # the table mirrors exactly this workload's distinct points (the
    # bench's stand-in for the node's ValidatorPubkeyCache; same
    # identity-map contract: the wrappers pin the very point objects
    # the sets carry)
    points, seen = [], set()
    for _sig, pks, _m in sets:
        for p in pks:
            if id(p) not in seen:
                seen.add(id(p))
                points.append(p)
    cache = _types.SimpleNamespace(
        pubkeys=[_types.SimpleNamespace(point=p) for p in points]
    )
    table = key_table_mod.DeviceKeyTable(cache)
    table.sync(reason="startup")
    key_table_mod.set_table(table)
    try:

        def run_on():
            res = table.resolve_sets(sets)
            if res is None:
                raise RuntimeError("bench sets must be table-resident")
            resolved, dev, agg, collapsed = res
            # the bench dispatches directly (no backend), so it commits
            # the shipping-path accounting the hit-ratio reads
            table.count_shipped(len(sets) - collapsed, collapsed)
            args = device_bls.pack_signature_sets_indexed(
                sets, resolved, pad_b=B, pad_k=K, pad_m=M
            )
            return bool(
                jax.block_until_ready(
                    device_bls.verify_batch_raw_staged_gather(dev, agg, *args)
                )
            )

        on = _measure(run_on)
    finally:
        key_table_mod.clear_table(table)
    st = table.status()
    on["hit_ratio"] = st["hit_ratio"]
    on["collapsed_sets"] = st["sets"]["collapsed"]
    on["aggregate_rows"] = st["aggregates_resident"]
    off_b, on_b = off["pubkeys_bytes_per_set"], on["pubkeys_bytes_per_set"]
    return {
        "B": B, "K": K, "M": M, "n_sets": n, "reps": reps,
        "off": off,
        "on": on,
        "pubkeys_bytes_per_set_reduction": (
            round(1.0 - on_b / off_b, 4) if off_b else None
        ),
        "table_validators": st["validators_resident"],
        "table_upload_bytes": st["upload_bytes"],
    }


def measure_replay_leg(
    use_cpu: bool,
    generator: str = "epoch_boundary_flood",
    seed: int = 7,
    duration_s: float = 8.0,
    time_scale: float = 0.5,
    deadline_ms: float = 50.0,
) -> dict:
    """Mainnet-shaped traffic replay (ISSUE 7): per-kind p50/p99 verdict
    latency and deadline-miss ratio under the epoch-boundary attestation
    flood, measured through the REAL scheduler stack — the arrival-model
    counterpart of every steady-state leg above, and the standing
    acceptance surface for roadmap items 1-3 (docs/TRAFFIC_REPLAY.md).
    Runs ``tools/traffic_replay.py`` in a SUBPROCESS (crash/wedge costs
    a marker, never the bench line) against the cpu-native backend —
    real crypto, no XLA compiles, so the leg measures SCHEDULING latency
    at a budget the driver can afford; the report records which backend
    actually ran (a stub fallback can never masquerade as measured
    crypto)."""
    replay = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "traffic_replay.py",
    )
    leg_timeout = min(300.0, _budget_left() - 60)
    if leg_timeout < 60:
        return {"skipped": "budget"}
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, replay,
             "--generate", generator, "--seed", str(seed),
             "--duration", str(duration_s),
             "--time-scale", str(time_scale),
             "--deadline-ms", str(deadline_ms),
             "--verify", "native", "--json"],
            capture_output=True, text=True, timeout=leg_timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"skipped": f"timeout>{leg_timeout:.0f}s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
    try:
        report = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {r.stdout[-200:]}"}
    slo = report["slo"]
    rec = {
        "generator": generator,
        "seed": seed,
        "n_events": report["n_events"],
        "n_sets": report["n_sets"],
        "time_scale": time_scale,
        "deadline_ms": slo["deadline_ms"],
        "verify_backend": report["config"]["verify_backend"],
        "wall_s": report["wall_s"],
        "arrival_fidelity": report["arrival_fidelity"],
        "dispatch_lag_p99_ms": report["dispatch_lag_ms"]["p99"],
        "deadline_misses_total": slo["deadline_misses_total"],
        "per_kind": {
            kind: {
                "count": rec["count_total"],
                "p50_ms": rec["p50_ms"],
                "p99_ms": rec["p99_ms"],
                "miss_ratio": rec["window_miss_ratio"],
                "paths": {p: v["count"] for p, v in rec["paths"].items()},
            }
            for kind, rec in slo["kinds"].items()
        },
        "plans": report["scheduler"]["planner"],
    }
    rec["data_movement"] = _replay_data_movement(
        generator, seed, duration_s, deadline_ms, time_scale
    )
    return rec


def _replay_data_movement(
    generator: str, seed: int, duration_s: float,
    deadline_ms: float, time_scale: float,
) -> dict:
    """Modeled data movement for the replay trace (ISSUE 8): a jax-free
    ``tools/transfer_report.py`` subprocess prices the SAME trace's
    flush plans with the shared byte model and models the pubkey
    re-upload ratio (same validators re-sign every epoch) — the replay
    leg runs cpu-native crypto, so measured device bytes do not exist
    here and a modeled number is reported AS a model. The lockstep
    model runs the leg's OWN flush policy: the wall-clock deadline is
    converted to trace time (deadline / time_scale), so the modeled
    flush plans are the ones the live leg actually aggregated."""
    if _budget_left() < 90:
        return {"skipped": "budget"}
    report_tool = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "transfer_report.py",
    )
    trace_deadline_ms = deadline_ms / max(time_scale, 1e-9)
    try:
        r = subprocess.run(
            [sys.executable, report_tool,
             "--generate", generator, "--seed", str(seed),
             "--duration", str(duration_s),
             "--deadline-ms", str(trace_deadline_ms), "--json"],
            capture_output=True, text=True, timeout=60,
        )
    except subprocess.TimeoutExpired:
        return {"skipped": "timeout>60s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
    try:
        rep = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {r.stdout[-200:]}"}
    n_sets = sum(rec["sets"] for rec in rep["per_kind"].values())
    return {
        "mode": rep["mode"],
        "est_h2d_bytes_total": rep["est_h2d_bytes_total"],
        "est_h2d_bytes_per_set": (
            round(rep["est_h2d_bytes_total"] / n_sets, 1) if n_sets else None
        ),
        "h2d_bytes_by_operand": rep["h2d_bytes_by_operand"],
        "padding_bytes_share": rep["padding_bytes_share"],
        "pubkey_bytes_share": rep["pubkey_bytes_share"],
        "modeled_reupload_ratio": rep["reupload_model"]["ratio"],
        "dedup_opportunity_bytes": rep["dedup_opportunity_bytes"],
        "dedup_ceiling_bytes": rep["dedup_ceiling_bytes"],
    }


def measure_capacity_leg(
    headline_sets_per_sec: float,
    generator: str = "saturation_ramp",
    seed: int = 11,
    duration_s: float = 20.0,
    deadline_ms: float = 25.0,
) -> dict:
    """Capacity/headroom estimator leg (ISSUE 14): lockstep-replay a
    ``saturation_ramp`` trace through the estimator at THIS RUN's
    measured headline cost (1 / headline sets/s) — a jax-free
    ``tools/capacity_report.py`` subprocess — and record where the ramp
    saturates, where the modeled miss onset lands, and the predictive
    lead between them. The ramp and its bulk-backfill floor are SCALED
    to the measured capacity (mid-ramp crossing; floor bursts sized to
    drain inside ~40% of the SLO budget), so the leg stays meaningful
    from the 5 sets/s XLA-emulated box to the 567 sets/s cpu-native
    one. ``headroom_ratio`` (at trace end) and ``predictive_lead_s``
    are LEARNED, not gated, by ``tools/bench_diff.py``."""
    if not headline_sets_per_sec or headline_sets_per_sec <= 0:
        return {"skipped": "no headline throughput"}
    if _budget_left() < 60:
        return {"skipped": "budget"}
    report_tool = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "capacity_report.py",
    )
    capacity = float(headline_sets_per_sec)
    cost = 1.0 / capacity
    budget_s = (deadline_ms / 1000.0) * 2.0  # default slo_grace
    # nominal ramp mean rate at scale 1 ≈ (5+80)/2 + floor; scale so
    # capacity crosses mid-ramp, and size floor bursts to ~40% budget
    rate_scale = max(0.01, capacity / 46.0)
    backfill_sets = max(1, int(capacity * budget_s * 0.4))
    try:
        r = subprocess.run(
            [sys.executable, report_tool,
             "--generate", generator, "--seed", str(seed),
             "--duration", str(duration_s),
             "--rate-scale", f"{rate_scale:.6g}",
             "--param", f"backfill_sets={backfill_sets}",
             "--cost-per-set", f"{cost:.9g}",
             "--deadline-ms", str(deadline_ms), "--json"],
            capture_output=True, text=True, timeout=60,
        )
    except subprocess.TimeoutExpired:
        return {"skipped": "timeout>60s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
    try:
        rep = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {r.stdout[-200:]}"}
    return {
        "generator": generator,
        "seed": seed,
        # False when serving ONE set already exceeds the SLO budget
        # (the 5 sets/s XLA-emulated box): misses are then structural,
        # not saturation-driven, and the predictive lead can go
        # negative — the estimator still reads demand honestly
        "budget_feasible": capacity * budget_s >= 1.0,
        "modeled_capacity_sets_per_sec": rep["model"][
            "capacity_sets_per_sec"
        ],
        "cost_s_per_set": rep["model"]["cost_s_per_set"],
        "rate_scale": round(rate_scale, 6),
        "backfill_sets": backfill_sets,
        "n_sets": rep["n_sets"],
        "saturated_at_s": rep["saturated_at_s"],
        "miss_onset_s": rep["miss_onset_s"],
        "predictive_lead_s": rep["predictive_lead_s"],
        "headroom_min": rep["headroom_min"],
        "headroom_ratio": rep["headroom_final"],
        "peak_wait_ms": rep["peak_wait_ms"],
    }


def measure_epoch_flood_leg(
    use_cpu: bool,
    seed: int = 7,
    duration_s: float = 12.0,
    time_scale: float = 0.25,
    deadline_ms: float = 50.0,
    slot_s: float = 2.0,
) -> dict:
    """Slot-aligned epoch-flood leg (ISSUE 17): replay the canonical
    ``epoch_boundary_flood`` trace with the chain-time axis on and
    score WHERE in chain time the tail lives — the per-slot p99 spread
    between the flood slots and the quiet slots, plus the committee
    first-sighting hit ratio (ROADMAP item 3's go/no-go dial: the
    flood's committee tuples repeat, so most sightings should collapse
    to cache hits). Stub-backend subprocess (seconds): the leg measures
    slot ATTRIBUTION under load, not crypto. Both headline numbers are
    LEARNED, not gated, by ``tools/bench_diff.py``."""
    replay = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "traffic_replay.py",
    )
    leg_timeout = min(120.0, _budget_left() - 60)
    if leg_timeout < 30:
        return {"skipped": "budget"}
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, replay,
             "--generate", "epoch_boundary_flood", "--seed", str(seed),
             "--duration", str(duration_s),
             "--time-scale", str(time_scale),
             "--deadline-ms", str(deadline_ms),
             "--slot-s", str(slot_s),
             "--verify", "stub:0.0005", "--json"],
            capture_output=True, text=True, timeout=leg_timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"skipped": f"timeout>{leg_timeout:.0f}s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
    try:
        report = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {r.stdout[-200:]}"}
    slots = [s for s in report.get("slots", []) if s["sets"]]
    if not slots:
        return {"error": "no slot cards in replay report"}
    # flood slots by demand, not by position: the flood window's cards
    # carry well over the median per-slot set count
    counts = sorted(s["sets"] for s in slots)
    median_sets = counts[len(counts) // 2]
    flood = [s for s in slots if s["sets"] > 2 * median_sets]
    quiet = [s for s in slots if s not in flood]
    p99s = [s["p99_ms"] for s in slots if s["p99_ms"] is not None]
    ct = report.get("chain_time", {})
    return {
        "generator": "epoch_boundary_flood",
        "seed": seed,
        "slot_s": slot_s,
        "time_scale": time_scale,
        "n_slots": len(slots),
        "flood_slots": sorted(s["slot"] for s in flood),
        "flood_sets": sum(s["sets"] for s in flood),
        "quiet_sets": sum(s["sets"] for s in quiet),
        "flood_p99_ms": (
            round(max(s["p99_ms"] for s in flood), 3) if flood else None
        ),
        "quiet_p99_ms": (
            round(
                sorted(s["p99_ms"] for s in quiet)[len(quiet) // 2], 3
            ) if quiet else None
        ),
        "p99_spread_ms": (
            round(max(p99s) - min(p99s), 3) if len(p99s) > 1 else 0.0
        ),
        "misses_in_flood_slots": sum(s["misses"] for s in flood),
        "misses_total": sum(s["misses"] for s in slots),
        "committee_sightings": ct.get("committee_sightings"),
        "first_sighting_hit_ratio": ct.get("first_sighting_hit_ratio"),
    }


def measure_lookahead_leg(
    use_cpu: bool,
    seed: int = 7,
    duration_s: float = 12.0,
    time_scale: float = 0.25,
    deadline_ms: float = 50.0,
    slot_s: float = 2.0,
) -> dict:
    """Duty-lookahead leg (ISSUE 19): the canonical epoch-boundary
    flood replayed twice — reactive-only vs ``--lookahead`` (the
    duty-lookahead warm pre-seeding each epoch's committees before
    their first signature). Scores the first-sighting hit ratio pair
    (acceptance: ~0.8 off, 1.0 on with ZERO first sightings), the
    flood-slot p99 on each side, and the warm's attribution (committees
    warmed, host vs device sums — the replay is stub-backend, so sums
    are virtual and host_sums must stay 0 inside verify spans either
    way). Two stub subprocesses (seconds); headline numbers LEARNED by
    ``tools/bench_diff.py``."""
    replay = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "traffic_replay.py",
    )
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"

    def _run(lookahead: bool) -> dict:
        leg_timeout = min(120.0, _budget_left() - 60)
        if leg_timeout < 30:
            return {"skipped": "budget"}
        cmd = [sys.executable, replay,
               "--generate", "epoch_boundary_flood", "--seed", str(seed),
               "--duration", str(duration_s),
               "--time-scale", str(time_scale),
               "--deadline-ms", str(deadline_ms),
               "--slot-s", str(slot_s),
               "--verify", "stub:0.0005", "--json"]
        if lookahead:
            cmd.append("--lookahead")
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=leg_timeout,
                env=env,
            )
        except subprocess.TimeoutExpired:
            return {"skipped": f"timeout>{leg_timeout:.0f}s"}
        if r.returncode != 0:
            return {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
        try:
            return json.loads(r.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return {"error": f"unparseable output: {r.stdout[-200:]}"}

    def _side(report: dict) -> dict:
        if "skipped" in report or "error" in report:
            return report
        ct = report.get("chain_time", {})
        slots = [s for s in report.get("slots", []) if s["sets"]]
        counts = sorted(s["sets"] for s in slots)
        median_sets = counts[len(counts) // 2] if counts else 0
        flood = [s for s in slots if s["sets"] > 2 * median_sets]
        lifetime = ct.get("lifetime", {})
        side = {
            "first_sighting_hit_ratio": ct.get("first_sighting_hit_ratio"),
            "first_sightings": ct.get("first_sightings"),
            "sighting_hits": ct.get("sighting_hits"),
            "flood_p99_ms": (
                round(max(s["p99_ms"] for s in flood), 3)
                if flood and all(
                    s["p99_ms"] is not None for s in flood
                ) else None
            ),
            "verdicts": report.get("verdicts"),
            "lookahead_host_sums": lifetime.get("lookahead_host_sums", 0),
        }
        la = ct.get("lookahead")
        if la:
            side["epochs_warmed"] = la.get("epochs_warmed")
            side["committees_warmed"] = la.get("committees")
            side["prewarmed"] = la.get("prewarmed")
        return side

    off = _side(_run(lookahead=False))
    on = _side(_run(lookahead=True))
    out = {
        "generator": "epoch_boundary_flood",
        "seed": seed,
        "slot_s": slot_s,
        "time_scale": time_scale,
        "off": off,
        "on": on,
    }
    r_off = off.get("first_sighting_hit_ratio")
    r_on = on.get("first_sighting_hit_ratio")
    if r_off is not None and r_on is not None:
        out["hit_ratio_gain"] = round(r_on - r_off, 4)
        # the acceptance pair at a glance: on-side reaches unity with
        # zero first sightings, and neither side pays host EC sums in
        # verify spans (warm sums are attributed off-path)
        out["on_reaches_unity"] = bool(
            r_on >= 1.0 and on.get("first_sightings") == 0
        )
        out["verdicts_identical"] = bool(
            off.get("verdicts") == on.get("verdicts")
        )
    return out


def measure_chaos_leg(
    use_cpu: bool,
    generator: str = "gossip_steady",
    seed: int = 5,
    duration_s: float = 6.0,
    time_scale: float = 0.5,
    deadline_ms: float = 100.0,
) -> dict:
    """Self-healing under chaos (ISSUE 13): a gossip-steady replay on a
    2-shard mesh with one INJECTED shard loss and an in-replay
    recovery — kill → probation (backoff probes through the same
    verify seam) → re-admission — recording the SLO miss ratio during
    degradation, the time-to-recover, and post-recovery sets/s (the
    dp axis must come back, not just survive). Stub backend in a
    SUBPROCESS: the leg certifies the RECOVERY machinery's latency
    economics, which live entirely in the scheduling layer (the
    staged-device half of degradation is `tests/test_zgate8_multichip`;
    the chaos gate is `tests/test_zgate9_chaos`). bench_diff gates
    `time_to_recover_s` — a recovery that slows >20% is a regression
    in the node's capacity restoration, exactly what the committee
    cost model assumes never leaks (PAPERS.md arxiv 2302.00418)."""
    replay = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "traffic_replay.py",
    )
    leg_timeout = min(240.0, _budget_left() - 60)
    if leg_timeout < 60:
        return {"skipped": "budget"}
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, replay,
             "--generate", generator, "--seed", str(seed),
             "--duration", str(duration_s),
             "--time-scale", str(time_scale),
             "--deadline-ms", str(deadline_ms),
             "--dp", "2", "--kill-shard", "1", "--kill-after", "3",
             "--revive-shard", "1", "--revive-after", "10",
             "--probe-base-s", "0.1",
             "--verify", "stub:0.001", "--json"],
            capture_output=True, text=True, timeout=leg_timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"skipped": f"timeout>{leg_timeout:.0f}s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
    try:
        report = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {r.stdout[-200:]}"}
    rec = report.get("recovery") or {}
    mesh = report.get("mesh") or {}
    if not rec.get("recovered"):
        # a chaos leg that never exercised recovery must be LOUD: the
        # gated time_to_recover_s is absent and bench_diff reports the
        # skipped gate instead of silently passing
        return {
            "error": "injected shard loss did not recover in-replay",
            "recovery": rec,
        }
    return {
        "generator": generator,
        "seed": seed,
        "time_scale": time_scale,
        "deadline_ms": deadline_ms,
        "verify_backend": report["config"]["verify_backend"],
        "n_events": report["n_events"],
        "n_sets": report["n_sets"],
        "wall_s": report["wall_s"],
        "verdicts": report["verdicts"],
        "time_to_recover_s": rec["time_to_recover_s"],
        "probes": rec["probes"],
        "flushes_served_degraded": rec["flushes_served_degraded"],
        "sets_served_degraded": rec["sets_served_degraded"],
        "slo_miss_ratio_degraded": rec["slo_miss_ratio_degraded"],
        "post_recovery_sets_per_sec": rec.get("post_recovery_sets_per_sec"),
        "recoveries_total": mesh.get("recoveries_total"),
        "healthy_shards_final": mesh.get("healthy_shards"),
        "deadline_misses_total": report["slo"]["deadline_misses_total"],
    }


def measure_bulk_leg(
    use_cpu: bool,
    seed: int = 9,
    duration_s: float = 4.0,
    time_scale: float = 0.5,
    deadline_ms: float = 60.0,
) -> dict:
    """Bulk QoS class isolation (ISSUE 15): replay the
    ``bulk_backfill_under_gossip`` composite vs its gossip-only
    baseline — BYTE-IDENTICAL gossip arrivals by construction
    (docs/TRAFFIC_REPLAY.md) — through a live scheduler with a stub
    backend, each in a subprocess. Records gossip's worst-kind p99 and
    miss ratio in both runs (``gossip_p99_under_bulk_ms`` is GATED by
    ``tools/bench_diff.py`` — a growing number means the bulk class
    started moving gossip's tail, the exact failure mode the class
    exists to prevent) plus the bulk side's served throughput, sheds
    and throttle excursions. Chunk size is pinned small for the stub
    backend (one 512-set chunk's wall would rival the deadline here —
    the documented head-of-line knob)."""
    replay = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "traffic_replay.py",
    )
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    env["LIGHTHOUSE_TPU_SCHED_BULK_FLUSH_SETS"] = "64"
    env["LIGHTHOUSE_TPU_SCHED_BULK_LINGER_MS"] = "10"
    reports = {}
    for label, gen in (
        ("baseline", "gossip_steady"),
        ("bulk", "bulk_backfill_under_gossip"),
    ):
        leg_timeout = min(120.0, _budget_left() - 60)
        if leg_timeout < 45:
            return {"skipped": "budget"}
        try:
            r = subprocess.run(
                [sys.executable, replay,
                 "--generate", gen, "--seed", str(seed),
                 "--duration", str(duration_s),
                 "--time-scale", str(time_scale),
                 "--deadline-ms", str(deadline_ms),
                 "--workers", "96",
                 "--verify", "stub:0.0002", "--json"],
                capture_output=True, text=True, timeout=leg_timeout,
                env=env,
            )
        except subprocess.TimeoutExpired:
            return {"skipped": f"timeout>{leg_timeout:.0f}s"}
        if r.returncode != 0:
            return {"error": f"{label}: rc={r.returncode}: {r.stderr[-200:]}"}
        try:
            reports[label] = json.loads(r.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return {"error": f"{label}: unparseable: {r.stdout[-200:]}"}

    def worst_gossip(rep):
        p99 = miss = 0.0
        for kind in ("unaggregated", "aggregate", "sync_message"):
            rec = rep["slo"]["kinds"].get(kind)
            if rec:
                p99 = max(p99, rec["p99_ms"])
                miss = max(miss, rec["window_miss_ratio"])
        return p99, miss

    p99_0, miss_0 = worst_gossip(reports["baseline"])
    p99_1, miss_1 = worst_gossip(reports["bulk"])
    bulk_st = reports["bulk"]["scheduler"]["bulk"]
    wall = reports["bulk"]["wall_s"]
    return {
        "generator": "bulk_backfill_under_gossip",
        "seed": seed,
        "time_scale": time_scale,
        "deadline_ms": deadline_ms,
        "verify_backend": reports["bulk"]["config"]["verify_backend"],
        "gossip_p99_baseline_ms": p99_0,
        "gossip_p99_under_bulk_ms": p99_1,
        "gossip_p99_ratio": (
            round(p99_1 / p99_0, 4) if p99_0 else None
        ),
        "gossip_miss_ratio_baseline": miss_0,
        "gossip_miss_ratio_under_bulk": miss_1,
        "bulk_sets_flushed": bulk_st["sets_flushed_total"],
        "bulk_sets_per_sec": (
            round(bulk_st["sets_flushed_total"] / wall, 2) if wall else None
        ),
        "bulk_flushes": bulk_st["flushes_total"],
        "bulk_shed_total": bulk_st["shed_total"],
        "throttle_excursions": bulk_st["admission"]["excursions_total"],
        "verdicts": reports["bulk"]["verdicts"],
    }


def measure_watchtower_leg(
    use_cpu: bool,
    seed: int = 7,
    duration_s: float = 14.0,
    rate_scale: float = 2.2,
    deadline_ms: float = 250.0,
) -> dict:
    """Anomaly-watchtower economics (ISSUE 18): the acceptance
    ``saturation_ramp`` replay twice — watchtower OFF then ON (isolated
    sampler + evaluator armed around the replay) — recording (a) the
    evaluator's throughput overhead as the sets/s delta, flagged
    against the documented <1% budget, and (b) the DETECTION LEAD on
    the ON run: how many seconds the ``headroom_floor`` page opened
    before the first deadline-miss burst. Both ride the trajectory
    LEARNED (stub-backend wall-clock instruments, not SLOs); the hard
    acceptance lives in ``tests/test_watchtower.py``. A negative lead
    here means the watchtower has become a postmortem tool — exactly
    what the predictive headroom dial (COST_MODEL.md) exists to
    prevent."""
    replay = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "traffic_replay.py",
    )
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    base_args = [
        sys.executable, replay,
        "--generate", "saturation_ramp", "--seed", str(seed),
        "--duration", str(duration_s), "--rate-scale", str(rate_scale),
        "--deadline-ms", str(deadline_ms), "--workers", "256",
        "--verify", "stub:0.005", "--json",
    ]
    reports = {}
    for label, extra in (("off", []), ("on", ["--watchtower"])):
        leg_timeout = min(120.0, _budget_left() - 60)
        if leg_timeout < 45:
            return {"skipped": "budget"}
        try:
            r = subprocess.run(
                base_args + extra, capture_output=True, text=True,
                timeout=leg_timeout, env=env,
            )
        except subprocess.TimeoutExpired:
            return {"skipped": f"timeout>{leg_timeout:.0f}s"}
        if r.returncode != 0:
            return {"error": f"{label}: rc={r.returncode}: {r.stderr[-200:]}"}
        try:
            reports[label] = json.loads(r.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return {"error": f"{label}: unparseable: {r.stdout[-200:]}"}

    def rate(rep):
        return rep["n_sets"] / rep["wall_s"] if rep["wall_s"] else 0.0

    rate_off, rate_on = rate(reports["off"]), rate(reports["on"])
    overhead = (rate_off - rate_on) / rate_off if rate_off else None
    wt = reports["on"].get("watchtower") or {}
    lead = wt.get("lead") or {}
    incidents = wt.get("incidents") or []
    return {
        "generator": "saturation_ramp",
        "seed": seed,
        "rate_scale": rate_scale,
        "deadline_ms": deadline_ms,
        "verify_backend": reports["on"]["config"]["verify_backend"],
        "n_sets": reports["on"]["n_sets"],
        "sets_per_sec_off": round(rate_off, 2),
        "sets_per_sec_on": round(rate_on, 2),
        "overhead_ratio": round(overhead, 4) if overhead is not None else None,
        "overhead_under_1pct": (
            overhead is not None and overhead < 0.01
        ),
        "n_incidents": lead.get("n_incidents"),
        "first_incident_detector": lead.get("first_incident_detector"),
        "first_incident_t": lead.get("first_incident_t"),
        "first_miss_burst_t": lead.get("first_miss_burst_t"),
        "lead_time_s": lead.get("lead_time_s"),
        "incident_detectors": sorted(
            {i.get("detector") for i in incidents if i.get("detector")}
        ),
    }


def measure_dp_leg(
    n_sets: int = 16, reps: int = 3, messages: int = 2
) -> dict:
    """Served multi-chip data-parallel verify, 1 vs 2 devices
    (ISSUE 11): the SAME single-pubkey gossip mix driven through the
    real scheduler+planner+TpuBackend stack on a virtual mesh, per-chip
    and aggregate sets/s recorded. Each width runs in a SUBPROCESS with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=2`` (the flag
    must precede jax init) and ``JAX_PLATFORMS=cpu`` — the virtual-mesh
    recipe DP_SCALING.json already certifies for the raw program, now
    measured through the served path. Honest caveat recorded in the
    record: on this box every virtual device shares the same physical
    cores, so the 2-device aggregate does NOT beat 1-device wall-clock
    here — the leg certifies the served sharding machinery (plan
    shapes, per-chip dispatch, zero steady recompiles per shard) and
    the per-chip numbers; the aggregate win is the real-chip story
    (COST_MODEL.md per-chip scaling)."""
    legs = {}
    for n_dev in (1, 2):
        leg_timeout = min(1500.0, _budget_left() - 120)
        if leg_timeout < 400:
            legs[f"dp{n_dev}"] = {"skipped": "budget"}
            continue
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        xla = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla:
            env["XLA_FLAGS"] = (
                xla + " --xla_force_host_platform_device_count=2"
            ).strip()
        try:
            r = subprocess.run(
                [sys.executable, __file__, "--dp-leg",
                 str(n_dev), str(n_sets), str(reps), str(messages)],
                capture_output=True, text=True, timeout=leg_timeout,
                env=env,
            )
        except subprocess.TimeoutExpired:
            legs[f"dp{n_dev}"] = {"skipped": f"timeout>{leg_timeout:.0f}s"}
            continue
        if r.returncode != 0:
            legs[f"dp{n_dev}"] = {
                "error": f"rc={r.returncode}: {r.stderr[-200:]}"
            }
            continue
        try:
            legs[f"dp{n_dev}"] = json.loads(r.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            legs[f"dp{n_dev}"] = {"error": f"unparseable: {r.stdout[-200:]}"}
    rec = {
        "n_sets": n_sets,
        "reps": reps,
        "caveat": (
            "virtual CPU mesh: all devices share one host's cores, so "
            "aggregate wall-clock does not scale here; the leg "
            "certifies served dp sharding + per-chip accounting"
        ),
        **legs,
    }
    one = legs.get("dp1", {}).get("sets_per_sec")
    two = legs.get("dp2", {}).get("sets_per_sec")
    if one and two:
        rec["aggregate_speedup"] = round(two / one, 4)
    return rec


def _dp_leg_main(argv) -> None:
    """Subprocess body for the dp leg: build an n_devices mesh, drive
    the scheduler's (dp x rung) plan with real staged device verifies,
    and print per-chip + aggregate sets/s as one JSON line."""
    import threading

    n_dev, n_sets, reps, messages = (int(v) for v in argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    _configure_jax_cache(jax)

    from lighthouse_tpu.crypto.device import mesh as mesh_mod
    from lighthouse_tpu.crypto.device.bls import TpuBackend
    from lighthouse_tpu.utils import metrics
    from lighthouse_tpu.verification_service import VerificationScheduler

    mesh = mesh_mod.DeviceMesh(n_devices=n_dev)
    mesh_mod.set_mesh(mesh)

    # single-pubkey gossip mix over a few messages: the kind the dp
    # axis splits first (K=1 rungs are the cheapest XLA:CPU compiles,
    # keeping the leg affordable; the plan shapes generalize)
    from lighthouse_tpu.crypto import bls

    sk = bls.SecretKey(4242)
    pk = sk.public_key().point
    msgs = [bytes([m + 1]) * 32 for m in range(messages)]
    sigs = {m: bls.Signature.deserialize(sk.sign(m).serialize()) for m in msgs}
    sets = [(sigs[msgs[i % messages]], [pk], msgs[i % messages])
            for i in range(n_sets)]

    from lighthouse_tpu.verification_service.planner import FlushPlanner

    backend = TpuBackend()
    sched = VerificationScheduler(
        verify_fn=backend.verify_signature_sets,
        deadline_ms=60_000.0,
        max_batch_sets=n_sets,  # bucket-full fires on the last feeder
        max_queue_sets=4 * n_sets,
        # threshold scaled to the leg's workload so the flush always
        # splits across the full mesh width (the default dp_min_sets=8
        # is a production trickle guard, not a bench knob)
        flush_planner=FlushPlanner(
            dp_min_sets=max(1, n_sets // (2 * max(1, n_dev)))
        ),
    ).start()

    def _recompiles() -> float:
        m = metrics.get("bls_device_recompiles_total")
        return sum(c.value for c in m.children().values()) if m else 0.0

    def run_flush() -> float:
        futs = [None] * n_sets

        def feed(i):
            futs[i] = sched.submit([sets[i]], "unaggregated")

        threads = [
            threading.Thread(target=feed, args=(i,)) for i in range(n_sets)
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if not all(f.result(timeout=1800) for f in futs):
            raise RuntimeError("dp leg batch must verify")
        return time.perf_counter() - t0

    try:
        run_flush()  # warm-up: the per-shard rung compiles land here
        rec0 = _recompiles()
        samples = [run_flush() for _ in range(reps)]
        steady = _recompiles() - rec0
        st = sched.status()
    finally:
        sched.stop()
        mesh_mod.clear_mesh(mesh)
    med, spread = _median_spread(samples)
    mstat = mesh.status()
    print(json.dumps({
        "n_devices": n_dev,
        "sets_per_sec": round(n_sets / med, 2),
        "rep_spread": round(spread, 3),
        "steady_recompiles": steady,
        "plan": st["planner"]["last_plan"],
        "per_chip": {
            str(c["shard"]): {
                "sets_total": c["sets_total"],
                "dispatches": c["dispatches"],
                "healthy": c["healthy"],
            }
            for c in mstat["chips"]
        },
        "healthy_shards": mstat["healthy_shards"],
    }))


def measure_startup_leg(use_cpu: bool, probe_rung: str = "4:1:1") -> dict:
    """Cold-vs-warm node startup (ISSUE 5): the 120.7 s warmup problem
    (BENCH_r05) measured as a trajectory metric. Two ``tools/warmup.py``
    subprocesses share one fresh persistent-cache dir: the COLD leg pays
    real XLA compiles for the probe rung's three staged programs, the
    WARM leg restarts against the prebaked cache — the wall-clock a
    restarted node pays before its first staged verify. Subprocesses so
    a cache-load crash (the known XLA:CPU AOT SIGSEGV on some host
    families, tests/conftest.py) costs a marker, never the bench line."""
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="lighthouse_tpu_warmup_cache_")
    env = dict(os.environ)
    if use_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    warmup = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools", "warmup.py")

    def run_leg():
        # per-leg budget, RE-checked here: a slow cold leg must shrink
        # (or cancel) the warm leg's allowance, not stack on top of it
        leg_timeout = min(900.0, _budget_left() - 120)
        if leg_timeout <= 0:
            raise subprocess.TimeoutExpired(warmup, 0)
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, warmup, "--cache-dir", cache_dir,
             "--rungs", probe_rung, "--json"],
            capture_output=True, text=True, timeout=leg_timeout, env=env,
        )
        elapsed = time.perf_counter() - t0
        if r.returncode != 0:
            # negative returncode = signal (the known cache-load SIGSEGV
            # lands here as -11); keep it visible in the record
            return elapsed, {"error": f"rc={r.returncode}: {r.stderr[-200:]}"}
        try:
            return elapsed, json.loads(r.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return elapsed, {"error": f"unparseable output: {r.stdout[-200:]}"}

    try:
        try:
            cold_s, cold = run_leg()
        except subprocess.TimeoutExpired:
            return {"probe_rung": probe_rung, "skipped": "cold leg timeout/budget"}
        if "error" in cold:
            return {"probe_rung": probe_rung, "error": cold["error"]}
        rec = {
            "probe_rung": probe_rung,
            "cold_warmup_s": round(cold_s, 1),
            "cache_enabled": bool(cold.get("cache", {}).get("enabled")),
        }
        try:
            warm_s, warm = run_leg()
        except subprocess.TimeoutExpired:
            # keep the cold measurement — it is the 120 s problem itself
            rec["warm_error"] = "timeout/budget"
            return rec
        if "error" in warm:
            rec["warm_error"] = warm["error"]
        else:
            rec["warm_warmup_s"] = round(warm_s, 1)
            rec["warm_manifest_prebaked"] = bool(
                warm["rungs"] and warm["rungs"][0].get("manifest_prebaked")
            )
            rec["warm_vs_cold"] = round(warm_s / cold_s, 4) if cold_s else None
        return rec
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def _data_movement_block(before, after, n_sets, n_packs, step_s) -> dict:
    """The headline bucket's data-movement attribution (ISSUE 8), from
    the transfer-ledger summary DELTA across the measured reps: bytes/set
    by operand, effective H2D bandwidth over the device_put phase, host
    pack share of one verify step (mean pack over the MEDIAN measured
    step, so the one-time warm-up compile cannot dilute the share and
    cold vs cache-warm runs stay comparable), and the repeat-pubkey
    window (the same sets re-pack every rep — the gossip steady-state
    shape where the device-resident pubkey table wins, ROADMAP item 2)."""
    ops = {}
    for op, v in after.get("h2d_bytes_by_operand", {}).items():
        d = v - before.get("h2d_bytes_by_operand", {}).get(op, 0)
        if d:
            ops[op] = d
    total = sum(ops.values())
    denom = max(1, n_packs * n_sets)

    def _phase_sum(doc, phase):
        return doc.get("pack_seconds", {}).get(phase, {}).get("sum_s", 0.0)

    pack_s = _phase_sum(after, "total") - _phase_sum(before, "total")
    dput_s = _phase_sum(after, "device_put") - _phase_sum(before, "device_put")
    reup = after.get("pubkey_reupload", {})
    # zero counted bytes = the ledger was disabled (the pack-phase
    # histogram is always-on, so dput_s alone proves nothing): byte
    # facts become null, never a confident 0.0 — the same unmeasured-
    # vs-zero guard transfer_ledger.summary() applies
    measured = total > 0
    return {
        "n_packs": n_packs,
        "ledger_enabled": measured,
        "h2d_bytes_total": total if measured else None,
        "h2d_bytes_per_set": round(total / denom, 1) if measured else None,
        "h2d_bytes_per_set_by_operand": (
            {op: round(v / denom, 1) for op, v in sorted(ops.items())}
            if measured else None
        ),
        "d2h_bytes_total": (
            after.get("d2h_bytes_total", 0)
            - before.get("d2h_bytes_total", 0)
        ) if measured else None,
        "effective_h2d_bandwidth_bytes_per_s": (
            round(total / dput_s, 1) if dput_s > 0 and measured else None
        ),
        "pack_seconds_total": round(pack_s, 4),
        "pack_share_of_verify_wall": (
            round((pack_s / n_packs) / step_s, 4) if step_s > 0 else None
        ),
        # the acceptance metric of the device key table (ISSUE 10): live
        # G1 bytes shipped per set — the key_table_leg measures its
        # on-table counterpart
        "pubkeys_bytes_per_set": (
            round(ops.get("pubkeys", 0) / denom, 1) if measured else None
        ),
        "pubkey_reupload_ratio": reup.get("ratio") if measured else None,
        "pubkey_reupload_window": reup.get("records") if measured else None,
        "device_memory": after.get("device_memory"),
    }


def measure_native_baseline(sets, reps: int = REPS):
    """Median-of-reps sets/s of the native C backend on the same workload
    (the reference seam, blst.rs:36-119, measured as BASELINE.md
    requires). None when no C toolchain is available."""
    try:
        from lighthouse_tpu.crypto.native import NativeBackend

        native = NativeBackend()
    except Exception:
        return None, 0.0
    assert native.verify_signature_sets(sets) is True
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        native.verify_signature_sets(sets)
        samples.append(time.perf_counter() - t0)
    med, spread = _median_spread(samples)
    return len(sets) / med, spread


def measure_bucket(pack, verify, sets, B, K, M, reps: int = REPS):
    """Median-of-reps end-to-end (pack + device) throughput for one
    padded bucket shape. Returns a record dict."""
    import jax

    def run_once():
        args = pack(sets, pad_b=B, pad_k=K, pad_m=M)
        out = verify(*args)
        jax.block_until_ready(out)
        return out

    t0 = time.perf_counter()
    ok = run_once()  # warm-up: compile
    warm_s = time.perf_counter() - t0
    assert bool(ok) is True, "benchmark batch must verify"

    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_once()
        samples.append(time.perf_counter() - t0)
    med, spread = _median_spread(samples)
    n_sets = len(sets)
    real_pk_slots = sum(len(pks) for _, pks, _ in sets)
    return {
        "B": B, "K": K, "M": M, "n_sets": n_sets,
        "sets_per_sec": round(n_sets / med, 2),
        "step_s": round(med, 4),
        "rep_spread": round(spread, 3),
        "warmup_s": round(warm_s, 1),
        "padding_waste": round(1.0 - real_pk_slots / (B * K), 4),
    }


def main() -> None:
    use_cpu = not probe_tpu()
    if use_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        _shrink_for_cpu_fallback()

    import jax

    if use_cpu:
        # The env var alone does NOT stop the axon plugin from initializing
        # (and hanging on a dead tunnel); the config knob does.
        jax.config.update("jax_platforms", "cpu")

    _configure_jax_cache(jax)

    from lighthouse_tpu.crypto.device import fp as device_fp
    from lighthouse_tpu.crypto.device.bls import (
        pack_signature_sets_raw,
        stage_latency_summary,
        verify_batch_raw_staged,
    )

    from lighthouse_tpu.utils import transfer_ledger

    sets = build_sets(N_AGG, COMMITTEE, N_MSGS)
    dm_before = transfer_ledger.summary()
    headline = measure_bucket(
        pack_signature_sets_raw, verify_batch_raw_staged, sets,
        B_PAD, K_PAD, M_PAD,
    )
    # Data-movement attribution for the headline bucket (ISSUE 8): the
    # ledger delta over exactly the warm-up + reps packs above.
    data_movement = _data_movement_block(
        dm_before, transfer_ledger.summary(),
        n_sets=headline["n_sets"], n_packs=REPS + 1,
        step_s=headline["step_s"],
    )
    # Per-stage attribution from the new telemetry histograms, read
    # BEFORE the extra buckets run so the quantiles describe the headline
    # geometry (the family keeps accumulating across buckets).
    headline["stage_latency"] = stage_latency_summary(device_fp.get_impl())

    buckets = [headline]
    for spec in EXTRA_BUCKETS:
        if _budget_left() < 600:
            buckets.append({"K": spec["K"], "skipped": "budget"})
            continue
        try:
            bsets = build_sets(spec["n_agg"], spec["K"], spec["M"])
            buckets.append(
                measure_bucket(
                    pack_signature_sets_raw, verify_batch_raw_staged,
                    bsets, spec["B"], spec["K"], spec["M"],
                )
            )
        except Exception as e:  # a failed bucket must not kill the line
            buckets.append({"K": spec["K"], "error": str(e)[:200]})

    # Fused-scheduler vs fragmented per-caller dispatch at the headline
    # shape (same compiled program both legs, see measure_scheduler_leg).
    if _budget_left() < 300:
        scheduler_leg = {"skipped": "budget"}
    else:
        try:
            scheduler_leg = measure_scheduler_leg(sets, B_PAD, K_PAD, M_PAD)
        except Exception as e:  # the leg must not kill the line
            scheduler_leg = {"error": str(e)[:200]}

    # Planned multi-rung flush vs legacy single-rung flush (ISSUE 6):
    # the padding-waste fix measured at the headline mix. The planned
    # leg pays its sub-batch rung compiles inside its warm-up flush, so
    # it needs real budget; skipped-with-marker beats silent truncation.
    if _budget_left() < 900:
        planner_leg = {"skipped": "budget"}
    else:
        try:
            planner_leg = measure_planner_leg(sets, B_PAD, K_PAD, M_PAD)
        except Exception as e:  # the leg must not kill the line
            planner_leg = {"error": str(e)[:200]}

    # Pipeline-occupancy profile at the headline rung (ISSUE 12):
    # bubble ratio + cause split, flush-thread saturation, and the
    # overlap-potential projection (ROADMAP item 5's go/no-go number).
    # Cheap: the headline rung is already warm, zero new compiles.
    if _budget_left() < 240:
        pipeline_leg = {"skipped": "budget"}
    else:
        try:
            pipeline_leg = measure_pipeline_leg(sets, B_PAD, K_PAD, M_PAD)
        except Exception as e:  # the leg must not kill the line
            pipeline_leg = {"error": str(e)[:200]}

    # Device key table on/off at the headline bucket (ISSUE 10): the
    # pubkey-plane bytes/set drop and pack-time delta under the same
    # repeat-validator traffic. The staged rung is already warm; the ON
    # leg adds only the sub-second gather compile (warm-up rep).
    if _budget_left() < 240:
        key_table_leg = {"skipped": "budget"}
    else:
        try:
            key_table_leg = measure_key_table_leg(sets, B_PAD, K_PAD, M_PAD)
        except Exception as e:  # the leg must not kill the line
            key_table_leg = {"error": str(e)[:200]}

    # Mainnet-shaped replay (ISSUE 7): per-class p50/p99 verdict latency
    # under the epoch-boundary flood — the arrival model the SLO layer
    # certifies, folded into the trajectory. Subprocess, budget-guarded.
    if _budget_left() < 180:
        replay_leg = {"skipped": "budget"}
    else:
        try:
            replay_leg = measure_replay_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            replay_leg = {"error": str(e)[:200]}

    # Capacity leg (ISSUE 14): the headroom estimator lockstep-replayed
    # over a saturation_ramp at this run's measured headline cost —
    # jax-free subprocess, seconds. Records the saturation point and
    # the predictive lead before the modeled miss onset.
    try:
        capacity_leg = measure_capacity_leg(headline["sets_per_sec"])
    except Exception as e:  # the leg must not kill the line
        capacity_leg = {"error": str(e)[:200]}

    # Chaos leg (ISSUE 13): injected shard loss + in-replay recovery on
    # a 2-shard mesh — SLO miss ratio during degradation,
    # time-to-recover (gated by tools/bench_diff.py) and post-recovery
    # sets/s. Subprocess, budget-guarded, stub backend (seconds).
    if _budget_left() < 120:
        chaos_leg = {"skipped": "budget"}
    else:
        try:
            chaos_leg = measure_chaos_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            chaos_leg = {"error": str(e)[:200]}

    # Bulk-QoS isolation leg (ISSUE 15): gossip SLO under saturating
    # backfill vs the gossip-only baseline + bulk sets/s — stub-backend
    # subprocesses, seconds. gossip_p99_under_bulk_ms is GATED.
    if _budget_left() < 120:
        bulk_leg = {"skipped": "budget"}
    else:
        try:
            bulk_leg = measure_bulk_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            bulk_leg = {"error": str(e)[:200]}

    # Slot-aligned epoch-flood leg (ISSUE 17): per-slot p99 spread
    # between flood and quiet slots + the committee first-sighting hit
    # ratio on the canonical flood trace — stub-backend subprocess,
    # seconds. Both headline numbers are learned by bench_diff.
    if _budget_left() < 90:
        epoch_flood_leg = {"skipped": "budget"}
    else:
        try:
            epoch_flood_leg = measure_epoch_flood_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            epoch_flood_leg = {"error": str(e)[:200]}

    # Duty-lookahead leg (ISSUE 19): the canonical flood replayed
    # reactive-only vs --lookahead — the first-sighting hit-ratio pair
    # (~0.8 -> 1.0 with zero firsts), flood p99 on each side, verdict
    # identity. Two stub subprocesses, seconds; learned by bench_diff.
    if _budget_left() < 120:
        lookahead_leg = {"skipped": "budget"}
    else:
        try:
            lookahead_leg = measure_lookahead_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            lookahead_leg = {"error": str(e)[:200]}

    # Watchtower leg (ISSUE 18): the acceptance saturation ramp with
    # the anomaly evaluator off vs on — evaluator overhead (flagged
    # against the <1% budget) and the measured detection lead of the
    # headroom page over the first miss burst. Stub-backend
    # subprocesses, seconds. Both numbers learned by bench_diff.
    if _budget_left() < 120:
        watchtower_leg = {"skipped": "budget"}
    else:
        try:
            watchtower_leg = measure_watchtower_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            watchtower_leg = {"error": str(e)[:200]}

    # Served multi-chip dp verify, 1 vs 2 virtual devices (ISSUE 11):
    # per-chip + aggregate sets/s through the real scheduler/planner/
    # backend stack. Subprocesses (XLA_FLAGS must precede jax init),
    # budget-guarded; the compile cache keeps repeats cheap.
    if _budget_left() < 1000:
        dp_leg = {"skipped": "budget"}
    else:
        try:
            dp_leg = measure_dp_leg()
        except Exception as e:  # the leg must not kill the line
            dp_leg = {"error": str(e)[:200]}

    # Cold-vs-warm startup (ISSUE 5): two warmup subprocesses against one
    # persistent-cache dir — the trajectory finally records the 120 s
    # first-compile problem AND whether the cache removes it on restart.
    if _budget_left() < 900:
        startup = {"skipped": "budget"}
    else:
        try:
            startup = measure_startup_leg(use_cpu)
        except Exception as e:  # the leg must not kill the line
            startup = {"error": str(e)[:200]}

    baseline, base_spread = measure_native_baseline(sets)
    sets_per_sec = headline["sets_per_sec"]
    agg_per_sec = sets_per_sec / 3.0

    # Per-implementation leg (VERDICT r5 rec #2): re-run the HEADLINE
    # bucket under the OTHER fp.mul engine (crypto/device/fp.py) — by
    # default matmul_int8, the int8-MXU decomposition. Runs LAST, in a
    # SUBPROCESS with its own deadline: a second giant XLA compile in
    # this process has segfaulted before (see dryrun_multichip), and a
    # wedge there must not cost the already-measured headline line.
    # Skipped-with-marker beats silent truncation.
    headline_impl = device_fp.get_impl()
    alt_impl = (
        device_fp.IMPL_MATMUL_INT8
        if headline_impl != device_fp.IMPL_MATMUL_INT8
        else device_fp.IMPL_TOEPLITZ_INT32
    )
    impl_legs = {headline_impl: headline}
    leg_timeout = min(900.0, _budget_left() - 60)
    if leg_timeout < 300:
        impl_legs[alt_impl] = {"skipped": "budget"}
    else:
        env = dict(os.environ)
        env["LIGHTHOUSE_TPU_FP_IMPL"] = alt_impl
        if use_cpu:
            env["JAX_PLATFORMS"] = "cpu"
        try:
            r = subprocess.run(
                [sys.executable, __file__, "--impl-leg",
                 str(N_AGG), str(COMMITTEE), str(N_MSGS),
                 str(B_PAD), str(K_PAD), str(M_PAD)],
                capture_output=True, text=True, timeout=leg_timeout,
                env=env,
            )
            if r.returncode == 0:
                impl_legs[alt_impl] = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
            elif r.returncode == 3:
                impl_legs[alt_impl] = {
                    "error": f"backend init exceeded {INIT_TIMEOUT_S}s"
                }
            else:
                impl_legs[alt_impl] = {"error": r.stderr[-200:]}
        except subprocess.TimeoutExpired:
            impl_legs[alt_impl] = {"skipped": f"timeout>{leg_timeout:.0f}s"}
        except Exception as e:  # the alt leg must not kill the line
            impl_legs[alt_impl] = {"error": str(e)[:200]}

    print(
        json.dumps(
            {
                "metric": "bls_sigset_verifications_per_sec_per_chip",
                "value": sets_per_sec,
                "unit": "sets/s",
                "vs_baseline": (
                    round(sets_per_sec / baseline, 4) if baseline else 0.0
                ),
                "vs_target": round(agg_per_sec / TARGET_AGG_PER_SEC, 4),
                "backend": "cpu-fallback" if use_cpu else "tpu",
                "baseline_backend": "cpu-native" if baseline else "unavailable",
                "baseline_sets_per_sec": round(baseline, 2) if baseline else None,
                "baseline_rep_spread": round(base_spread, 3),
                "reps": REPS,
                "shapes": {"B": B_PAD, "K": K_PAD, "M": M_PAD,
                           "n_sets": headline["n_sets"]},
                "fp_impl": headline_impl,
                "fp_impl_legs": impl_legs,
                "stage_latency": headline.get("stage_latency", {}),
                "data_movement": data_movement,
                "scheduler_leg": scheduler_leg,
                "planner_leg": planner_leg,
                "pipeline_leg": pipeline_leg,
                "key_table_leg": key_table_leg,
                "replay_leg": replay_leg,
                "capacity_leg": capacity_leg,
                "chaos_leg": chaos_leg,
                "bulk_leg": bulk_leg,
                "epoch_flood_leg": epoch_flood_leg,
                "lookahead_leg": lookahead_leg,
                "watchtower_leg": watchtower_leg,
                "dp_leg": dp_leg,
                "startup": startup,
                "buckets": buckets,
            }
        )
    )


def _impl_leg_main(argv) -> None:
    """Subprocess body for the per-impl leg: measure ONE bucket under the
    fp engine selected by LIGHTHOUSE_TPU_FP_IMPL (set by the parent) and
    print its record as one JSON line. Isolated so its XLA compile cannot
    wedge or crash the parent's already-measured headline."""
    import threading

    n_agg, committee, n_msgs, b, k, m = (int(v) for v in argv)

    # Backend-init watchdog (mirrors the parent probe's INIT_TIMEOUT_S):
    # on the real-TPU path the parent still holds its device client, and a
    # dead/contended tunnel would otherwise hang this child for the whole
    # leg timeout. Fail FAST with a distinct exit code instead.
    watchdog = threading.Timer(INIT_TIMEOUT_S, lambda: os._exit(3))
    watchdog.daemon = True
    watchdog.start()
    import jax

    jax.devices()
    watchdog.cancel()

    _configure_jax_cache(jax)

    from lighthouse_tpu.crypto.device import fp as device_fp
    from lighthouse_tpu.crypto.device.bls import (
        pack_signature_sets_raw,
        stage_latency_summary,
        verify_batch_raw_staged,
    )

    sets = build_sets(n_agg, committee, n_msgs)
    rec = measure_bucket(
        pack_signature_sets_raw, verify_batch_raw_staged, sets, b, k, m
    )
    rec["fp_impl"] = device_fp.get_impl()
    rec["stage_latency"] = stage_latency_summary(device_fp.get_impl())
    print(json.dumps(rec))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--dp-leg":
        _dp_leg_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "--impl-leg":
        # The parent already resolved the platform; honour JAX_PLATFORMS.
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        _impl_leg_main(sys.argv[2:])
    else:
        main()
