"""North-star benchmark: BLS signature-set verifications/sec on one chip.

Workload shape follows BASELINE.md config #3 (gossip aggregate batch): each
aggregate attestation costs three signature sets (selection proof,
aggregator signature, aggregate attestation signature over the committee —
reference: ``beacon_node/beacon_chain/src/attestation_verification/batch.rs:77-107``).
Here: B sets per device batch with a mix of single-pubkey and
committee-aggregation (multi-pubkey) sets, pre-hashed messages (message
de-dup mirrors the 64-committees-per-slot structure).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
``vs_baseline`` is measured against the 50k aggregate-verifications/sec
target from BASELINE.json (an aggregate = 3 sets).
"""

from __future__ import annotations

import json
import time

import jax

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.device.bls import pack_signature_sets, verify_batch

# Batch geometry: 64 aggregates -> 192 sets (2/3 single-pubkey, 1/3
# committee sets with COMMITTEE pubkeys), padded to the (256, 16) bucket.
N_AGG = 64
COMMITTEE = 16
B_PAD = 256
K_PAD = 16
TARGET_AGG_PER_SEC = 50_000.0


def build_batch():
    sets = []
    n_msgs = 8  # distinct AttestationData roots in flight
    sks = [bls.SecretKey(1_000 + i) for i in range(COMMITTEE)]
    pks = [sk.public_key().point for sk in sks]
    msgs = [bytes([m + 1]) * 32 for m in range(n_msgs)]
    sigs = [[sk.sign(m) for sk in sks] for m in msgs]
    for i in range(N_AGG):
        m = i % n_msgs
        # selection proof + aggregator signature (single-pubkey sets)
        sets.append((sigs[m][0].point, [pks[0]], msgs[m]))
        sets.append((sigs[m][1].point, [pks[1]], msgs[m]))
        # aggregate attestation signature (committee set)
        agg = bls.AggregateSignature.infinity()
        for s in sigs[m]:
            agg.add_assign(s)
        sets.append((agg.point, pks, msgs[m]))
    return pack_signature_sets(sets, pad_b=B_PAD, pad_k=K_PAD), len(sets)


def main() -> None:
    args, n_sets = build_batch()
    # Warm-up: compile (first TPU compile is slow; cached afterwards).
    ok = verify_batch(*args)
    assert bool(ok) is True, "benchmark batch must verify"

    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = verify_batch(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps

    sets_per_sec = n_sets / dt
    agg_per_sec = N_AGG / dt
    print(
        json.dumps(
            {
                "metric": "bls_sigset_verifications_per_sec_per_chip",
                "value": round(sets_per_sec, 2),
                "unit": "sets/s",
                "vs_baseline": round(agg_per_sec / TARGET_AGG_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
