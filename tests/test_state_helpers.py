"""Shuffling + state accessor tests (reference strategy: the shuffle is a
pure function certified by structural properties + the single-index spec
loop; committees partition the active set)."""

import numpy as np
import pytest

from lighthouse_tpu.types import MINIMAL, FAR_FUTURE_EPOCH, types_for
from lighthouse_tpu.state_transition import (
    CommitteeCache,
    compute_epoch_at_slot,
    compute_proposer_index,
    compute_shuffled_index,
    get_active_validator_indices,
    get_attesting_indices,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
    get_seed,
    get_total_active_balance,
    integer_squareroot,
    shuffle_list,
    unshuffle_list,
)


def test_integer_squareroot():
    for n, want in [(0, 0), (1, 1), (3, 1), (4, 2), (26, 5), (2**64 - 1, 4294967295)]:
        assert integer_squareroot(n) == want


def test_shuffle_list_matches_single_index(rng):
    seed = bytes(rng.randrange(256) for _ in range(32))
    n, rounds = 100, 10
    perm = shuffle_list(n, seed, rounds)
    for i in [0, 1, 50, 99]:
        assert perm[i] == compute_shuffled_index(i, n, seed, rounds)
    # permutation property
    assert sorted(perm.tolist()) == list(range(n))


def test_unshuffle_is_inverse(rng):
    seed = bytes(rng.randrange(256) for _ in range(32))
    n, rounds = 321, 10
    perm = shuffle_list(n, seed, rounds)
    inv = unshuffle_list(n, seed, rounds)
    assert np.array_equal(perm[inv], np.arange(n))
    assert np.array_equal(inv[perm], np.arange(n))


def _make_state(n_validators=64):
    t = types_for(MINIMAL)
    st = t.state["phase0"]()
    st.slot = 16
    st.validators = [
        t.Validator(
            pubkey=bytes([i % 256, i // 256]) + bytes(46),
            effective_balance=32 * 10**9,
            activation_epoch=0,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        for i in range(n_validators)
    ]
    st.balances = [32 * 10**9] * n_validators
    st.randao_mixes = [bytes([i % 256]) * 32 for i in range(64)]
    return t, st


def test_committees_partition_active_set():
    t, st = _make_state(64)
    epoch = compute_epoch_at_slot(MINIMAL, st.slot)
    cache = CommitteeCache(MINIMAL, st, epoch)
    seen = []
    for slot in range(
        epoch * MINIMAL.SLOTS_PER_EPOCH, (epoch + 1) * MINIMAL.SLOTS_PER_EPOCH
    ):
        for idx in range(cache.committees_per_slot):
            seen.extend(cache.committee(slot, idx).tolist())
    assert sorted(seen) == get_active_validator_indices(st, epoch)


def test_committee_count_scales():
    t, st = _make_state(64)
    assert get_committee_count_per_slot(MINIMAL, st, 2) == 2  # 64/8/4 = 2
    t2, st2 = _make_state(8)
    assert get_committee_count_per_slot(MINIMAL, st2, 2) == 1


def test_proposer_index_deterministic_and_active():
    t, st = _make_state(64)
    p1 = get_beacon_proposer_index(MINIMAL, st)
    p2 = get_beacon_proposer_index(MINIMAL, st)
    assert p1 == p2
    assert 0 <= p1 < 64
    st.slot += 1
    # overwhelmingly likely to differ across slots eventually; just check range
    assert 0 <= get_beacon_proposer_index(MINIMAL, st) < 64


def test_proposer_sampling_prefers_effective_balance():
    t, st = _make_state(64)
    # zero out everyone's balance except validator 7: sampling must pick 7
    for i, v in enumerate(st.validators):
        if i != 7:
            v.effective_balance = 0
    seed = b"\x07" * 32
    idx = compute_proposer_index(
        MINIMAL, st, get_active_validator_indices(st, 2), seed
    )
    assert idx == 7


def test_attesting_indices_roundtrip():
    t, st = _make_state(64)
    epoch = compute_epoch_at_slot(MINIMAL, st.slot)
    cache = CommitteeCache(MINIMAL, st, epoch)
    committee = cache.committee(st.slot, 0)
    bits = [i % 2 == 0 for i in range(len(committee))]
    data = t.AttestationData(slot=st.slot, index=0)
    got = get_attesting_indices(MINIMAL, st, data, bits)
    assert got == sorted(int(v) for v, b in zip(committee, bits) if b)
    with pytest.raises(ValueError):
        get_attesting_indices(MINIMAL, st, data, bits + [True])


def test_total_active_balance():
    t, st = _make_state(10)
    assert get_total_active_balance(MINIMAL, st) == 10 * 32 * 10**9
