"""Fee-recipient preparation service + MEV builder client.

Reference analogues: ``validator_client/src/preparation_service.rs`` and
``beacon_node/builder_client/src/lib.rs`` (+ its mock builder test rig).
VERDICT r2 missing #7.
"""

import copy

import pytest

from lighthouse_tpu.beacon_chain import BeaconChain
from lighthouse_tpu.crypto import backend
from lighthouse_tpu.eth2_client import BeaconNodeClient
from lighthouse_tpu.execution_layer.builder_client import (
    BuilderError,
    BuilderHttpClient,
    MockBuilder,
)
from lighthouse_tpu.http_api import BeaconApiServer
from lighthouse_tpu.operation_pool import OperationPool
from lighthouse_tpu.state_transition import interop_secret_key, store_replayer
from lighthouse_tpu.store import HotColdDB, MemoryStore
from lighthouse_tpu.testing.harness import StateHarness
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.preset import MINIMAL
from lighthouse_tpu.utils.slot_clock import ManualSlotClock
from lighthouse_tpu.validator_client import (
    BeaconNodeFallback,
    ValidatorClient,
    ValidatorStore,
)


@pytest.fixture(autouse=True)
def fake_backend():
    backend.set_backend("fake")
    yield
    backend.set_backend("cpu")


def _api_chain():
    h = StateHarness(
        MINIMAL, minimal_spec(), validator_count=4, fork_name="phase0",
        fake_sign=True,
    )
    genesis = copy.deepcopy(h.state)
    db = HotColdDB(MemoryStore(), h.t, h.spec, store_replayer(h.preset, h.spec))
    clock = ManualSlotClock(genesis.genesis_time, h.spec.seconds_per_slot)
    chain = BeaconChain(h.preset, h.spec, h.t, db, genesis, slot_clock=clock)
    chain.op_pool = OperationPool(h.preset, h.spec, h.t)
    return h, chain, clock, genesis


def test_preparation_service_sends_fee_recipients():
    h, chain, clock, genesis = _api_chain()
    api = BeaconApiServer(chain, port=0).start()
    try:
        c = BeaconNodeClient(f"http://127.0.0.1:{api.port}", h.t)
        store = ValidatorStore(
            h.spec, h.preset, h.t,
            genesis_validators_root=bytes(genesis.genesis_validators_root),
        )
        for i in range(4):
            store.add_secret_key(interop_secret_key(i))
        vc = ValidatorClient(store, BeaconNodeFallback([c]), h.t, h.preset, clock)
        vc.preparation.fee_recipient = b"\xaa" * 20
        clock.set_slot(1)
        vc.on_slot(1)  # polls duties (resolves indices) then prepares
        prep = getattr(chain, "proposer_preparations", {})
        assert len(prep) == 4
        assert set(prep.values()) == {"0x" + "aa" * 20}
        # idempotent within the epoch
        assert vc.preparation.prepare_proposers(0) == 0
    finally:
        api.stop()


def test_builder_registration_via_bn_route():
    h, chain, clock, genesis = _api_chain()
    api = BeaconApiServer(chain, port=0).start()
    try:
        c = BeaconNodeClient(f"http://127.0.0.1:{api.port}", h.t)
        store = ValidatorStore(
            h.spec, h.preset, h.t,
            genesis_validators_root=bytes(genesis.genesis_validators_root),
        )
        for i in range(4):
            store.add_secret_key(interop_secret_key(i))
        vc = ValidatorClient(store, BeaconNodeFallback([c]), h.t, h.preset, clock)
        n = vc.preparation.register_validators()
        assert n == 4
        regs = getattr(chain, "validator_registrations", {})
        assert len(regs) == 4
        for pk_hex, msg in regs.items():
            assert msg["pubkey"] == pk_hex
            assert msg["gas_limit"] == "30000000"
    finally:
        api.stop()


def test_builder_client_against_mock():
    mock = MockBuilder(port=0).start()
    try:
        client = BuilderHttpClient(mock.url)
        assert client.status() is True
        regs = [
            {
                "message": {
                    "fee_recipient": "0x" + "bb" * 20,
                    "gas_limit": "30000000",
                    "timestamp": "1",
                    "pubkey": "0x" + "cc" * 48,
                },
                "signature": "0x" + "00" * 96,
            }
        ]
        client.register_validators(regs)
        assert "0x" + "cc" * 48 in mock.registrations

        bid = client.get_header(7, b"\x11" * 32, b"\xcc" * 48)
        assert bid["message"]["value"] == str(10**18)
        assert mock.headers_served[0][0] == 7

        out = client.submit_blinded_block({"signed": "blinded"})
        assert out == {"unblinded": True}
        assert mock.submitted == [{"signed": "blinded"}]

        with pytest.raises(BuilderError):
            client._req("GET", "/eth/v1/builder/nope")
    finally:
        mock.stop()
