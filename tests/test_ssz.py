"""SSZ encode/decode/hash-tree-root: round-trips, spec edge rules, and
known-answer roots computed with an independent in-test merkleizer."""

import hashlib

import pytest

from lighthouse_tpu import ssz
from lighthouse_tpu.ssz import hash_tree_root
from lighthouse_tpu.ssz.core import SSZError


class Checkpoint(ssz.Container):
    fields = [("epoch", ssz.Uint64), ("root", ssz.Bytes32)]


class Mixed(ssz.Container):
    fields = [
        ("a", ssz.Uint16),
        ("bits", ssz.Bitlist(10)),
        ("fixed", ssz.Vector(ssz.Uint8, 3)),
        ("items", ssz.List(ssz.Uint64, 100)),
        ("flag", ssz.Boolean),
    ]


class Outer(ssz.Container):
    fields = [
        ("inner", Mixed),
        ("cp", Checkpoint),
        ("blob", ssz.ByteList(50)),
    ]


def _h(a, b):
    return hashlib.sha256(a + b).digest()


def test_uint_roundtrip_and_encoding():
    assert ssz.Uint64.encode(0x0102030405060708) == bytes(
        [8, 7, 6, 5, 4, 3, 2, 1]
    )
    for v in (0, 1, 2**64 - 1):
        assert ssz.Uint64.decode(ssz.Uint64.encode(v)) == v
    with pytest.raises(SSZError):
        ssz.Uint8.encode(256)
    with pytest.raises(SSZError):
        ssz.Boolean.decode(b"\x02")


def test_container_roundtrip_fixed():
    cp = Checkpoint(epoch=5, root=b"\xAA" * 32)
    enc = Checkpoint.encode(cp)
    assert len(enc) == 40
    assert Checkpoint.decode(enc) == cp


def test_container_roundtrip_variable():
    m = Mixed(a=7, bits=[True, False, True], fixed=[1, 2, 3], items=[10, 20], flag=True)
    out = Outer(inner=m, cp=Checkpoint(epoch=9, root=bytes(32)), blob=b"hello")
    enc = Outer.encode(out)
    assert Outer.decode(enc) == out


def test_bitlist_delimiter_rules():
    bl = ssz.Bitlist(8)
    assert bl.encode([]) == b"\x01"
    assert bl.encode([True]) == b"\x03"
    assert bl.decode(b"\x03") == [True]
    assert bl.decode(b"\x01") == []
    with pytest.raises(SSZError):
        bl.decode(b"\x00")  # no delimiter
    with pytest.raises(SSZError):
        bl.decode(b"")
    with pytest.raises(SSZError):
        bl.encode([True] * 9)  # over limit


def test_bitvector_padding_rules():
    bv = ssz.Bitvector(3)
    assert bv.encode([True, False, True]) == b"\x05"
    assert bv.decode(b"\x05") == [True, False, True]
    with pytest.raises(SSZError):
        bv.decode(b"\x0D")  # padding bit set (bit 3)


def test_malformed_container_rejected():
    cp = Checkpoint(epoch=1, root=bytes(32))
    enc = Checkpoint.encode(cp)
    with pytest.raises(SSZError):
        Checkpoint.decode(enc[:-1])
    with pytest.raises(SSZError):
        Checkpoint.decode(enc + b"\x00")
    m = Mixed(a=1, bits=[], fixed=[0, 0, 0], items=[], flag=False)
    enc2 = Mixed.encode(m)
    # corrupt the first offset
    bad = bytearray(enc2)
    bad[2] = 0xFF
    with pytest.raises(SSZError):
        Mixed.decode(bytes(bad))


def test_htr_basic_known_answers():
    assert hash_tree_root(ssz.Uint64, 5) == (5).to_bytes(8, "little") + bytes(24)
    assert hash_tree_root(ssz.Boolean, True) == b"\x01" + bytes(31)
    assert hash_tree_root(ssz.Bytes32, b"\x42" * 32) == b"\x42" * 32


def test_htr_vector_of_uints_manual():
    # Vector(Uint64, 8) -> two chunks -> one hash
    vals = list(range(8))
    packed = b"".join(v.to_bytes(8, "little") for v in vals)
    expect = _h(packed[:32], packed[32:])
    assert hash_tree_root(ssz.Vector(ssz.Uint64, 8), vals) == expect


def test_htr_list_mixes_length_and_pads_to_limit():
    # List(Uint64, 16): limit 16 uints -> 4 chunks -> depth-2 tree
    vals = [1, 2]
    packed = (b"".join(v.to_bytes(8, "little") for v in vals)).ljust(32, b"\x00")
    z = bytes(32)
    root = _h(_h(packed, z), _h(z, z))
    expect = _h(root, (2).to_bytes(32, "little"))
    assert hash_tree_root(ssz.List(ssz.Uint64, 16), vals) == expect


def test_htr_huge_limit_is_cheap():
    # List(Uint64, 2**40) with 1 element: virtual zero subtrees must make
    # this instant (the reference merkleizes the validator registry the
    # same way).
    t = ssz.List(ssz.Uint64, 2**40)
    root = hash_tree_root(t, [7])
    chunk = (7).to_bytes(8, "little").ljust(32, b"\x00")
    # depth = log2(2**40 * 8 / 32) = 38
    from lighthouse_tpu.ssz.sha256 import ZERO_HASHES

    acc = chunk
    for d in range(38):
        acc = _h(acc, ZERO_HASHES[d])
    assert root == _h(acc, (1).to_bytes(32, "little"))


def test_htr_container_matches_manual():
    cp = Checkpoint(epoch=3, root=b"\x11" * 32)
    leaf0 = (3).to_bytes(8, "little") + bytes(24)
    assert hash_tree_root(cp) == _h(leaf0, b"\x11" * 32)


def test_htr_bitlist_known():
    # Bitlist(5) value [T,T,F,T]: data bits 1101 -> byte 0x0B, limit 1 chunk
    t = ssz.Bitlist(5)
    chunk = b"\x0b" + bytes(31)
    assert hash_tree_root(t, [True, True, False, True]) == _h(
        chunk, (4).to_bytes(32, "little")
    )


def test_union_roundtrip_and_htr():
    t = ssz.Union([None, ssz.Uint64, ssz.Bytes32])
    for v in [(0, None), (1, 77), (2, b"\x09" * 32)]:
        assert t.decode(t.encode(v)) == v
    got = hash_tree_root(t, (1, 77))
    assert got == _h((77).to_bytes(8, "little") + bytes(24), (1).to_bytes(32, "little"))
