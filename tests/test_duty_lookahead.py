"""Duty-lookahead precompute (ISSUE 19): the trigger policy + worker
lifecycle (backoff probation, clean stop, fault-injection drive), the
key table's epoch-tagged aggregate region (``insert_precomputed``
outcome matrix, two-epoch retention, eviction-before-wholesale-reset),
the end-to-end warm → first-sighting-ships-K=1 path, the health block,
and the replay acceptance gates: ``epoch_boundary_flood`` with
lookahead on reaches first-sighting hit-ratio 1.0 (vs ~0.82 off) with
ZERO host EC sums inside any verify span and verdict identity
preserved; the retuned ``first_sighting_hit_regression`` floor
detector opens an incident whose bundle carries the ``duty_lookahead``
health block."""

from __future__ import annotations

import json
import time
import types

import pytest

from lighthouse_tpu import duty_lookahead as dl
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.device import key_table as kt
from lighthouse_tpu.utils import fault_injection
from lighthouse_tpu.utils import flight_recorder as fr
from lighthouse_tpu.utils import slot_clock, slot_ledger


@pytest.fixture
def manual_clock():
    """A process-global ManualSlotClock (epoch 0, slot 0), restored."""
    clock = slot_clock.ManualSlotClock(seconds_per_slot=12, slots_per_epoch=32)
    prev = slot_clock.set_clock(clock)
    try:
        yield clock
    finally:
        slot_clock.set_clock(prev)


@pytest.fixture
def journal(tmp_path):
    prev = fr.configure(
        capacity=4096, enabled=True, dump=False, dump_dir=str(tmp_path)
    )
    fr.clear()
    try:
        yield fr
    finally:
        fr.configure(**prev)
        fr.clear()


def _store_cache(n, seed=4000):
    """A REAL ValidatorPubkeyCache admitted from a fake state — the
    lookahead resolves committee indices through ``.get(i).point``."""
    from lighthouse_tpu.beacon_chain.pubkey_cache import ValidatorPubkeyCache

    sks = [bls.SecretKey(seed + i) for i in range(n)]
    state = types.SimpleNamespace(
        validators=[
            types.SimpleNamespace(pubkey=sk.public_key().serialize())
            for sk in sks
        ]
    )
    cache = ValidatorPubkeyCache()
    cache.import_new_pubkeys(state)
    return sks, cache


def _committee_sets(sks, cache, committee, msg=b"\x19" * 32):
    """One aggregate (sig, [points], msg) triple over ``committee``."""
    from lighthouse_tpu.crypto.params import R

    sk_sum = sum(sks[i].k for i in committee) % R
    agg = bls.Signature.deserialize(bls.SecretKey(sk_sum).sign(msg).serialize())
    return [(agg, [cache.pubkeys[i].point for i in committee], msg)]


def _host_sum(cache, committee):
    pts = [cache.pubkeys[i].point for i in committee]
    agg = pts[0]
    for p in pts[1:]:
        agg = agg + p
    return agg


# ---------------------------------------------------------------------------
# Trigger policy + worker lifecycle
# ---------------------------------------------------------------------------


def test_trigger_policy_waits_for_epoch_fraction(manual_clock):
    warmed = []
    w = dl.DutyLookahead(
        lambda e: [(1, 2, 3)], trigger_frac=0.5,
        on_warmed=lambda e, cs: warmed.append(e),
    )
    # early in epoch 0: before the trigger point, no warm
    manual_clock.set_slot(3)
    assert w.tick() is None
    assert warmed == []
    # past the midpoint: the NEXT epoch warms exactly once
    manual_clock.set_slot(17)
    out = w.tick()
    assert out is not None and out["epoch"] == 1
    assert warmed == [1]
    assert w.tick() is None  # idempotent per target epoch
    # the epoch rolls: the new next epoch warms (again past midpoint)
    manual_clock.set_slot(32 + 20)
    out = w.tick()
    assert out is not None and out["epoch"] == 2
    assert warmed == [1, 2]
    st = w.status()
    assert st["warmed_epoch"] == 2
    assert st["epochs"]["warmed"] == 2
    assert st["committees"]["virtual"] == 2  # no key table: virtual mode


def test_worker_thread_warms_and_stops_cleanly(manual_clock):
    manual_clock.set_slot(20)  # past the epoch-0 midpoint
    warmed = []
    w = dl.DutyLookahead(
        lambda e: [(7, 8)], poll_s=0.02,
        on_warmed=lambda e, cs: warmed.append(e),
    ).start()
    try:
        deadline = time.monotonic() + 5.0
        while not warmed and time.monotonic() < deadline:
            time.sleep(0.01)
        assert warmed == [1], "background worker must warm the next epoch"
        assert w.status()["running"] is True
    finally:
        w.stop()
    assert w.status()["running"] is False
    # stop() is idempotent and start/stop leave no stuck thread
    w.stop()


def test_warm_failure_backs_off_then_probes(manual_clock, journal):
    """PR 13's probation shape: a failing warm arms capped-exponential
    backoff (ticks inside the pause do nothing), the failure journals
    ``lookahead_insert_failed``, and the first post-pause retry IS the
    probe — success resets the failure counter."""
    manual_clock.set_slot(20)
    fault_injection.arm("duty_lookahead", nth=1)
    try:
        w = dl.DutyLookahead(
            lambda e: [(1, 2)], backoff_base_s=30.0, backoff_max_s=60.0
        )
        assert w.tick() is None  # injected failure
        st = w.status()
        assert st["failures"] == 1
        assert st["backoff_s"] > 0
        assert st["epochs"]["error"] == 1
        assert "InjectedFault" in st["last_error"]
        evs = journal.events(["lookahead_insert_failed"])
        assert evs and evs[-1]["fields"]["reason"] == "warm_error"
        # inside the pause: the trigger condition holds but nothing runs
        assert w.tick() is None
        assert w.status()["epochs"]["error"] == 1
        # pause expiry (forced): the retry probes and recovers
        with w._lock:
            w._backoff_until = 0.0
        out = w.tick()
        assert out is not None and out["epoch"] == 1
        st = w.status()
        assert st["failures"] == 0
        assert st["backoff_s"] == 0.0
        assert st["epochs"]["warmed"] == 1
        evs = journal.events(["lookahead_epoch_warmed"])
        assert evs and evs[-1]["fields"]["epoch"] == 1
    finally:
        fault_injection.clear()


# ---------------------------------------------------------------------------
# Key table: insert_precomputed outcome matrix + epoch retention
# ---------------------------------------------------------------------------


def test_insert_precomputed_outcomes_and_first_sighting_k1(manual_clock):
    sks, cache = _store_cache(8)
    t = kt.DeviceKeyTable(cache, agg_min_repeats=2)
    assert t.sync(reason="startup") == 8
    committee = [0, 1, 2, 3]

    # before any sighting: the lookahead pre-inserts, bypassing
    # agg_min_repeats
    assert t.insert_precomputed(committee, _host_sum(cache, committee)) \
        == "inserted"
    st = t.status()
    assert st["aggregates_resident"] == 1
    assert st["aggregate_precomputed"] == 1
    assert st["aggregate_inserts"] == 0  # reactive counter untouched
    # the NEXT epoch is the default retention tag (warmed ahead)
    assert st["aggregate_epochs"] == [1]

    # FIRST sighting ships K=1 (collapsed), zero host EC adds in-span
    resolved, _dev, agg_dev, collapsed = t.resolve_sets(
        _committee_sets(sks, cache, committee)
    )
    assert collapsed == 1
    assert len(resolved[0]) == 1, "first sighting must ship K=1"
    assert agg_dev is not None
    assert st["aggregate_hits"] == 0  # status() snapshot from before
    assert t.status()["aggregate_hits"] == 1

    # duplicate pre-insert: exists, retention extended through epoch 5
    assert t.insert_precomputed(
        committee, _host_sum(cache, committee), epoch=5
    ) == "exists"
    assert t.status()["aggregate_epochs"] == [5]

    # singleton and disabled-region guards
    assert t.insert_precomputed([3], _host_sum(cache, [0, 1])) == "disabled"
    t0 = kt.DeviceKeyTable(cache, max_aggregates=0)
    assert t0.insert_precomputed([0, 1], None) == "disabled"
    # infinity sums are never cached; the mark is remembered
    assert t.insert_precomputed([4, 5], None) == "infinity"
    assert t.insert_precomputed(
        [4, 5], _host_sum(cache, [4, 5])
    ) == "never_cache"
    # an unsynced table has no aggregate region to write
    t2 = kt.DeviceKeyTable(cache)
    assert t2.insert_precomputed(
        committee, _host_sum(cache, committee)
    ) == "unsynced"


def test_two_epoch_retention_evicts_instead_of_wholesale_reset(
    manual_clock, journal
):
    """Epoch-tagged aggregate region: entries older than two epochs
    move to the free-list at the epoch roll (per-epoch eviction), the
    freed slots are reused by later inserts, and the wholesale
    reset-when-full counter stays ZERO throughout."""
    sks, cache = _store_cache(8)
    t = kt.DeviceKeyTable(cache, agg_min_repeats=1)
    t.sync(reason="startup")
    a, b, c = [0, 1], [2, 3], [4, 5]

    # epoch 0: A pre-inserted for epoch 0 (explicit tag), B for epoch 1
    assert t.insert_precomputed(a, _host_sum(cache, a), epoch=0) == "inserted"
    assert t.insert_precomputed(b, _host_sum(cache, b), epoch=1) == "inserted"
    assert t.status()["aggregates_resident"] == 2

    # epoch 1: both inside the two-epoch window — nothing evicts
    manual_clock.set_slot(32)
    t.resolve_sets(_committee_sets(sks, cache, a))
    st = t.status()
    assert st["aggregates_resident"] == 2
    assert st["aggregate_evictions"] == 0

    # epoch 2: A's tag (0) is two epochs behind — evicted; B (1) stays
    manual_clock.set_slot(64)
    resolved, _, _, collapsed = t.resolve_sets(
        _committee_sets(sks, cache, b)
    )
    assert collapsed == 1, "retained entry must still serve K=1"
    st = t.status()
    assert st["aggregates_resident"] == 1
    assert st["aggregate_evictions"] == 1
    assert st["aggregate_free_slots"] == 1
    assert st["aggregate_epochs"] == [1]
    assert st["aggregate_resets"] == 0, "eviction must replace the reset"
    evs = journal.events(["key_table_reset"])
    assert evs and evs[-1]["fields"]["mode"] == "evict_epochs"
    assert evs[-1]["fields"]["dropped"] == 1

    # the freed slot is REUSED (free-list before high-water growth)
    assert t.insert_precomputed(c, _host_sum(cache, c), epoch=3) == "inserted"
    st = t.status()
    assert st["aggregates_resident"] == 2
    assert st["aggregate_free_slots"] == 0
    # evicted A re-inserts REACTIVELY on its next sighting (seen counts
    # survive eviction, same contract as the wholesale reset): the
    # sighting is a `first` (it pays the host sum), the re-insert
    # commits in the same batch's second phase, so the position still
    # ships collapsed — and the next one is a plain hit
    inserts0 = t.status()["aggregate_inserts"]
    hits0 = t.status()["aggregate_hits"]
    _r1, _, _, c1 = t.resolve_sets(_committee_sets(sks, cache, a))
    assert c1 == 1
    assert t.status()["aggregate_inserts"] == inserts0 + 1
    assert t.status()["aggregate_hits"] == hits0
    _r2, _, _, c2 = t.resolve_sets(_committee_sets(sks, cache, a))
    assert c2 == 1
    assert t.status()["aggregate_hits"] == hits0 + 1
    assert t.status()["aggregate_resets"] == 0


def test_full_region_declines_precompute_without_reset(manual_clock):
    """A full region with nothing stale declines the pre-insert
    (``full``) — the lookahead must never force the wholesale reset the
    reactive path owns."""
    _sks, cache = _store_cache(8)
    t = kt.DeviceKeyTable(cache, max_aggregates=1, agg_min_repeats=1)
    t.sync(reason="startup")
    assert t.insert_precomputed(
        [0, 1], _host_sum(cache, [0, 1]), epoch=0
    ) == "inserted"
    assert t.insert_precomputed(
        [2, 3], _host_sum(cache, [2, 3]), epoch=0
    ) == "full"
    st = t.status()
    assert st["aggregate_resets"] == 0
    assert st["aggregates_resident"] == 1
    # two epochs later the stale entry is evictable: the same insert
    # lands on the recycled slot
    manual_clock.set_slot(64)
    assert t.insert_precomputed(
        [2, 3], _host_sum(cache, [2, 3]), epoch=2
    ) == "inserted"
    assert t.status()["aggregate_resets"] == 0


# ---------------------------------------------------------------------------
# End-to-end: worker warm → key table → first sighting ships K=1
# ---------------------------------------------------------------------------


def test_warm_epoch_preinserts_into_key_table(manual_clock, journal):
    sks, cache = _store_cache(8)
    t = kt.DeviceKeyTable(cache)
    t.sync(reason="startup")
    committees = {1: [(0, 1, 2, 3), (4, 5, 6, 7)]}
    prev_ledger = slot_ledger.configure(enabled=True)
    slot_ledger.reset()
    try:
        w = dl.DutyLookahead(
            lambda e: committees.get(e, []),
            key_table=t, pubkey_cache=cache,
            device_sum=False,  # host fold: deterministic, no MSM compile
        )
        manual_clock.set_slot(20)
        out = w.tick()
        assert out is not None and out["epoch"] == 1
        assert out["counts"]["host"] == 2
        assert out["inserts"] == {"inserted": 2}
        st = t.status()
        assert st["aggregate_precomputed"] == 2
        assert st["aggregates_resident"] == 2
        assert st["aggregate_epochs"] == [1]
        # chain-time attribution landed OUTSIDE any verify span
        led = slot_ledger.summary()["lifetime"]
        assert led["lookahead_committees"] == 2
        assert led["lookahead_host_sums"] == 2
        assert led["lookahead_device_sums"] == 0
        ev = journal.events(["lookahead_epoch_warmed"])[-1]["fields"]
        assert ev["epoch"] == 1 and ev["host_sums"] == 2

        # the acceptance shape: epoch 1 arrives, the FIRST sighting of
        # each warmed committee ships K=1 with zero in-span host sums
        manual_clock.set_slot(32)
        for c in committees[1]:
            resolved, _, _, collapsed = t.resolve_sets(
                _committee_sets(sks, cache, list(c))
            )
            assert collapsed == 1 and len(resolved[0]) == 1
        assert t.status()["aggregate_hits"] == 2
    finally:
        slot_ledger.configure(**prev_ledger)
        slot_ledger.reset()


def test_unresolvable_committee_counts_failed_and_journals(
    manual_clock, journal
):
    _sks, cache = _store_cache(4)
    t = kt.DeviceKeyTable(cache)
    t.sync(reason="startup")
    w = dl.DutyLookahead(
        lambda e: [(0, 99)],  # index 99 beyond the cache
        key_table=t, pubkey_cache=cache, device_sum=False,
    )
    out = w.warm_epoch(1)
    assert out["counts"]["failed"] == 1
    evs = journal.events(["lookahead_insert_failed"])
    assert evs and evs[-1]["fields"]["reason"] == "unresolved"
    assert t.status()["aggregates_resident"] == 0


# ---------------------------------------------------------------------------
# Health block
# ---------------------------------------------------------------------------


def test_health_doc_carries_duty_lookahead_block():
    from lighthouse_tpu.http_api import server

    doc = server.build_health_doc(types.SimpleNamespace())
    assert doc["duty_lookahead"] is None  # node without the worker
    w = dl.DutyLookahead(lambda e: [])
    chain = types.SimpleNamespace(duty_lookahead=w)
    doc = server.build_health_doc(chain)
    block = doc["duty_lookahead"]
    assert block is not None
    assert block["running"] is False
    assert set(block) >= {
        "warmed_epoch", "epochs", "committees", "inserts", "failures",
        "backoff_s", "trigger_frac",
    }
    json.dumps(doc)  # the document must stay JSON-serializable


# ---------------------------------------------------------------------------
# Replay acceptance (satellite): epoch_boundary_flood, lookahead off/on
# ---------------------------------------------------------------------------


def test_lockstep_flood_lookahead_reaches_unity_hit_ratio():
    from lighthouse_tpu.verification_service import traffic

    events = traffic.epoch_boundary_flood(duration_s=12, seed=7)
    off = traffic.lockstep_replay(events)
    on = traffic.lockstep_replay(events, lookahead=True)

    # baseline: the reactive cache pays first sightings on the flood's
    # stable committee recurrence
    assert off["chain_time"]["first_sightings"] > 0
    assert off["chain_time"]["first_sighting_hit_ratio"] < 0.9
    assert "lookahead" not in off["chain_time"]

    # lookahead: EVERY sighting is a hit — zero host-EC-sum territory
    assert on["chain_time"]["first_sightings"] == 0
    assert on["chain_time"]["first_sighting_hit_ratio"] == 1.0
    la = on["chain_time"]["lookahead"]
    assert la["enabled"] is True
    assert la["committees"] == 16  # the flood's stable 16 committees
    assert la["committees"] == sum(n for _e, n in la["epochs"])

    # verdict identity: the precompute must not change WHAT was
    # verified or how it flushed — only who paid the EC sums
    for k in ("submissions", "bypasses", "flushes", "set_totals", "bulk"):
        assert on[k] == off[k], f"lookahead changed replay surface {k!r}"
    assert on["chain_time"]["committee_sightings"] \
        == off["chain_time"]["committee_sightings"]

    # determinism: the lookahead-off digest is byte-stable vs a rerun
    again = traffic.lockstep_replay(events)
    assert again["digest"] == off["digest"]


# ---------------------------------------------------------------------------
# Watchtower detector path (satellite): floor breach → incident whose
# bundle carries the duty_lookahead health block
# ---------------------------------------------------------------------------


def test_hit_ratio_floor_incident_bundle_has_lookahead_block(tmp_path):
    from lighthouse_tpu.utils import timeseries, watchtower

    prev_fr = fr.configure(
        capacity=4096, enabled=True, dump=False, dump_dir=str(tmp_path)
    )
    fr.clear()
    timeseries.reset()
    prev_ts = timeseries.configure(enabled=True)
    watchtower.reset()
    prev = watchtower.configure(
        enabled=True, bundle=True,
        bundle_dir=str(tmp_path / "incidents"), bundle_retain=8,
    )
    worker = dl.DutyLookahead(lambda e: [(1, 2)])
    worker.warm_epoch(3)
    watchtower.set_health_provider(
        lambda: {"duty_lookahead": worker.status()}
    )
    try:
        store = timeseries.get_store()
        t0 = time.time()
        # the lookahead steady state: ratio pinned at 1.0 — armed, quiet
        store.record("slot_first_sighting_hit_ratio", 1.0, t=t0, label="4")
        r = watchtower.evaluate(now=t0)
        assert not [
            t for t in r["transitions"]
            if t["detector"] == "first_sighting_hit_regression"
        ]
        # warms failing: firsts pay host sums again, ratio under the
        # 0.9 floor for the sustain pair → exactly one incident opens
        store.record(
            "slot_first_sighting_hit_ratio", 0.5, t=t0 + 1, label="5"
        )
        watchtower.evaluate(now=t0 + 1)
        store.record(
            "slot_first_sighting_hit_ratio", 0.4, t=t0 + 2, label="5"
        )
        r = watchtower.evaluate(now=t0 + 2)
        opened = [
            t for t in r["transitions"]
            if t["detector"] == "first_sighting_hit_regression"
        ]
        assert [t["action"] for t in opened] == ["open"]
        (inc,) = [
            i for i in watchtower.incidents()
            if i["detector"] == "first_sighting_hit_regression"
        ]
        assert inc["severity"] == "warn"
        # the forensic bundle's health snapshot carries the block the
        # operator needs to attribute the drop to the worker
        with open(inc["bundle_path"]) as f:
            bundle = json.load(f)
        block = bundle["health"]["duty_lookahead"]
        assert block["warmed_epoch"] == 3
        assert block["epochs"]["warmed"] == 1
        # hysteresis: inside the band (0.9..0.97) the incident latches
        store.record(
            "slot_first_sighting_hit_ratio", 0.93, t=t0 + 3, label="5"
        )
        assert watchtower.evaluate(now=t0 + 3)["transitions"] == []
        assert watchtower.incidents(open_only=True)
        # back at the lookahead steady state: resolves above 0.97
        # (same label — the floor detector's state is per label)
        store.record(
            "slot_first_sighting_hit_ratio", 1.0, t=t0 + 4, label="5"
        )
        r = watchtower.evaluate(now=t0 + 4)
        assert [
            t["action"] for t in r["transitions"]
            if t["detector"] == "first_sighting_hit_regression"
        ] == ["resolve"]
    finally:
        watchtower.set_health_provider(None)
        watchtower.configure(**prev)
        watchtower.reset()
        timeseries.configure(**prev_ts)
        timeseries.reset()
        fr.configure(**prev_fr)
        fr.clear()
