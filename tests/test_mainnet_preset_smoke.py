"""Mainnet-preset smoke: the rest of the suite runs the minimal preset;
this catches preset-dependent bugs (shape parameters, epoch geometry,
committee math) on the mainnet shapes with a small validator set.

Reference analogue: the per-fork `make test-beacon-chain-%` matrix runs
mainnet-preset suites too.
"""

import copy

import pytest

from lighthouse_tpu.beacon_chain import BeaconChain
from lighthouse_tpu.crypto import backend
from lighthouse_tpu.ssz import hash_tree_root
from lighthouse_tpu.state_transition import state_transition, store_replayer
from lighthouse_tpu.store import HotColdDB, MemoryStore
from lighthouse_tpu.testing.harness import StateHarness
from lighthouse_tpu.types.chain_spec import mainnet_spec
from lighthouse_tpu.types.preset import MAINNET
from lighthouse_tpu.utils.slot_clock import ManualSlotClock


@pytest.fixture(autouse=True)
def fake_backend():
    backend.set_backend("fake")
    yield
    backend.set_backend("cpu")


def test_mainnet_chain_with_attestations():
    spec = mainnet_spec()
    h = StateHarness(MAINNET, spec, validator_count=64, fork_name="phase0",
                     fake_sign=True)
    genesis = copy.deepcopy(h.state)
    db = HotColdDB(MemoryStore(), h.t, spec, store_replayer(MAINNET, spec))
    clock = ManualSlotClock(genesis.genesis_time, spec.seconds_per_slot)
    chain = BeaconChain(MAINNET, spec, h.t, db, genesis, slot_clock=clock)

    for _ in range(3):
        slot = h.state.slot + 1
        clock.set_slot(slot)
        atts = []
        if slot >= 2:
            atts = h.attestations_for_slot(h.state, slot - 1)[
                : MAINNET.MAX_ATTESTATIONS
            ]
        sb = h.produce_block(slot, attestations=atts)
        h.process_block(sb, strategy="none")
        root = chain.process_block(chain.verify_block_for_gossip(sb))
        assert chain.head_block_root == root
    assert chain.head_state.slot == 3
    # attestations actually landed
    assert len(chain.head_state.previous_epoch_attestations) + len(
        chain.head_state.current_epoch_attestations
    ) >= 2
    # storage round-trip at mainnet shapes
    sr = hash_tree_root(chain.head_state)
    assert hash_tree_root(db.get_state(sr)) == sr


@pytest.mark.slow  # second mainnet genesis (~80s of big-vector hashing)
def test_mainnet_state_transition_wrapper():
    spec = mainnet_spec()
    h = StateHarness(MAINNET, spec, validator_count=64, fork_name="altair",
                     fake_sign=True)
    sb = h.produce_block(1)
    st = state_transition(
        MAINNET, spec, copy.deepcopy(h.state), sb, signature_strategy="none"
    )
    assert st.slot == 1
    assert hash_tree_root(st) == bytes(sb.message.state_root)
