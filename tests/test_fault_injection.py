"""Deterministic fault-injection layer (ISSUE 13,
utils/fault_injection.py): trigger shapes, sticky vs one-shot modes,
hang actions, spec parsing, the journal/metric surface, the
sub-microsecond disarmed path, and — the property the chaos gates
lean on — schedule determinism (same seed ⇒ same injected-failure
schedule), pinned in a jax-free subprocess."""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from lighthouse_tpu.utils import fault_injection as fi
from lighthouse_tpu.utils import flight_recorder, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    fi.clear()
    yield
    fi.clear()


def _fire_n(point: str, n: int) -> list:
    out = []
    for _ in range(n):
        try:
            fi.fire(point)
            out.append(False)
        except fi.InjectedFault:
            out.append(True)
    return out


def test_nth_is_one_shot():
    fi.arm("staged_dispatch", nth=3)
    assert _fire_n("staged_dispatch", 6) == [
        False, False, True, False, False, False,
    ]


def test_every_k_and_sticky():
    fi.arm("compile", every=3)
    assert _fire_n("compile", 7) == [
        False, False, True, False, False, True, False,
    ]
    fi.arm("compile", every=3, sticky=True)  # re-arm resets counters
    assert _fire_n("compile", 7) == [
        False, False, True, True, True, True, True,
    ]


def test_after_warmin_and_count_cap():
    fi.arm("device_put", every=2, after=3, count=2)
    # calls 1-3 are warm-in; schedule indices restart after them
    assert _fire_n("device_put", 12) == [
        False, False, False,          # warm-in
        False, True, False, True,     # every=2 on post-warm-in indices
        False, False, False, False, False,  # count cap reached
    ]


def test_hang_action_sleeps_instead_of_raising():
    fi.arm("staged_dispatch", nth=1, hang_s=0.15)
    t0 = time.perf_counter()
    fi.fire("staged_dispatch")  # must NOT raise
    assert time.perf_counter() - t0 >= 0.14
    st = fi.status()
    assert st["points"]["staged_dispatch"]["injected"] == 1


def test_seeded_schedule_is_deterministic_and_seed_sensitive():
    a = fi.schedule(64, p=0.3, seed=7)
    b = fi.schedule(64, p=0.3, seed=7)
    c = fi.schedule(64, p=0.3, seed=8)
    assert a == b
    assert a != c
    assert any(a), "p=0.3 over 64 calls must fire at least once"
    # the live fire() path follows the same pure schedule
    fi.arm("compile", p=0.3, seed=7)
    assert _fire_n("compile", 64) == a


def test_spec_parse_roundtrip_and_malformed_rejected():
    plan = fi.parse_spec(
        "staged_dispatch:nth=2;compile:every=3,mode=sticky;"
        "key_table_sync:hang=0.5,count=1"
    )
    assert plan["staged_dispatch"] == {"nth": 2}
    assert plan["compile"] == {"every": 3, "sticky": True}
    assert plan["key_table_sync"] == {"hang_s": 0.5, "count": 1}
    with pytest.raises(ValueError):
        fi.parse_spec("not_a_point:nth=1")
    with pytest.raises(ValueError):
        fi.parse_spec("compile:bogus_key=1")
    with pytest.raises(ValueError):
        fi.parse_spec("compile:mode=chaotic")
    fi.configure("staged_dispatch:nth=1")
    assert fi.armed()
    assert _fire_n("staged_dispatch", 2) == [True, False]


def test_journal_and_metrics_on_injection():
    fam = metrics.get("fault_injections_total")
    before = fam.with_labels("staged_dispatch", "raise").value
    fi.arm("staged_dispatch", nth=1)
    assert _fire_n("staged_dispatch", 1) == [True]
    assert fam.with_labels("staged_dispatch", "raise").value == before + 1
    if flight_recorder.enabled():
        evs = flight_recorder.events(["fault_injected"])
        assert evs and evs[-1]["fields"]["point"] == "staged_dispatch"
        assert evs[-1]["fields"]["action"] == "raise"


def test_clear_restores_disarmed_and_unknown_points_rejected():
    fi.arm("compile", nth=1)
    fi.clear("compile")
    assert not fi.armed()
    fi.fire("compile")  # disarmed: free no-op, never raises
    with pytest.raises(ValueError):
        fi.arm("bogus_point", nth=1)


def test_disarmed_fire_costs_under_one_microsecond():
    assert not fi.armed()
    n = 20_000
    fire = fi.fire
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            fire("staged_dispatch")
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, (
        f"disarmed fire() costs {best * 1e9:.0f} ns — too expensive to "
        f"leave compiled into the staged dispatch hot path"
    )


def test_schedule_determinism_subprocess_jax_free():
    """The chaos-run reproducibility contract: the same seed produces
    the same injected-failure schedule in a FRESH process (no shared
    state), and the module never pulls jax in."""
    code = (
        "import sys\n"
        "from lighthouse_tpu.utils import fault_injection as fi\n"
        "sched = fi.schedule(48, p=0.25, seed=11)\n"
        "fi.arm('staged_dispatch', p=0.25, seed=11)\n"
        "live = []\n"
        "for _ in range(48):\n"
        "    try:\n"
        "        fi.fire('staged_dispatch')\n"
        "        live.append(0)\n"
        "    except fi.InjectedFault:\n"
        "        live.append(1)\n"
        "assert live == [int(x) for x in sched]\n"
        "assert 'jax' not in sys.modules, 'fault layer must stay jax-free'\n"
        "print(''.join(str(x) for x in live))\n"
    )
    runs = [
        subprocess.run(
            [sys.executable, "-c", code], cwd=REPO,
            capture_output=True, text=True, timeout=120,
        )
        for _ in range(2)
    ]
    for r in runs:
        assert r.returncode == 0, r.stderr
    assert runs[0].stdout == runs[1].stdout, (
        "same seed must reproduce the same schedule across processes"
    )
