"""Checkpoint sync (bootstrap from a remote finalized state) + backfill
+ fork_revert.

Reference analogues: ``client/src/builder.rs:128-350`` checkpoint-sync
bootstrap, ``network/src/sync/backfill_sync``, ``fork_revert.rs``.
"""

import time

import pytest

from lighthouse_tpu.beacon_chain import revert_to_fork_boundary
from lighthouse_tpu.client import ClientBuilder, ClientConfig
from lighthouse_tpu.crypto import backend
from lighthouse_tpu.testing.simulator import LocalNetwork


@pytest.fixture(autouse=True)
def fake_backend():
    backend.set_backend("fake")
    yield
    backend.set_backend("cpu")


def test_checkpoint_sync_bootstrap_and_backfill():
    """A source node finalizes; a fresh node bootstraps from its
    finalized state over HTTP and backfills history over RPC."""
    from lighthouse_tpu.http_api import BeaconApiServer

    net = LocalNetwork(1, validator_count=8)
    api = BeaconApiServer(net.nodes[0].chain, port=0).start()
    try:
        P = net.h.preset
        for _ in range(4 * P.SLOTS_PER_EPOCH):
            net.tick_slot(attest=True)
        src = net.nodes[0]
        fin_epoch = src.chain.fork_choice.store.finalized_checkpoint[0]
        assert fin_epoch >= 1

        cfg = ClientConfig(preset_base="minimal", http_enabled=False, bls_backend="fake")
        from lighthouse_tpu.types.chain_spec import minimal_spec

        builder = ClientBuilder(cfg, minimal_spec()).with_checkpoint_sync(
            f"http://127.0.0.1:{api.port}"
        )
        client = builder.build()
        try:
            anchor_slot = client.chain.head_state.slot
            assert anchor_slot >= fin_epoch * P.SLOTS_PER_EPOCH
            # the anchor is NOT genesis: the chain starts mid-history
            assert client.chain.head_state.slot > 0

            # backfill history below the anchor over RPC
            from lighthouse_tpu.network import NetworkService

            net_svc = NetworkService(client.chain, client.processor)
            try:
                peer = net_svc.connect("127.0.0.1", src.net.port)
                assert peer is not None
                stored = net_svc.backfill.run(peer)
                assert net_svc.backfill.complete
                assert stored > 0
                # the full ancestor chain is now stored down to slot 0/1
                from lighthouse_tpu.store.iter import block_roots_iter

                slots = [
                    s
                    for s, _ in block_roots_iter(
                        client.chain.store, client.chain.head_block_root
                    )
                ]
                assert min(slots) <= 1
            finally:
                net_svc.close()
        finally:
            client.processor.shutdown()
    finally:
        api.stop()
        net.close()


def test_fork_revert():
    net = LocalNetwork(1, validator_count=8)
    try:
        P = net.h.preset
        for _ in range(2 * P.SLOTS_PER_EPOCH):
            net.tick_slot(attest=False)
        chain = net.nodes[0].chain
        head_before = chain.head_state.slot
        assert head_before == 2 * P.SLOTS_PER_EPOCH
        # pretend epoch 1 was a missed fork: revert to the last block
        # before it
        root = revert_to_fork_boundary(chain, fork_epoch=1)
        assert chain.head_state.slot < P.SLOTS_PER_EPOCH
        assert chain.head_block_root == root
        assert chain.fork_choice.proto.contains(root)
    finally:
        net.close()
