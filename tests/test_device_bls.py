"""Device (tpu-backend) batch verification vs the CPU oracle backend.

Mirrors the contract the reference certifies for a new BLS backend: same
results as the incumbent on valid batches, tampered batches, and the edge
cases of ``verify_signature_sets``
(``/root/reference/crypto/bls/src/impls/blst.rs:36-119``).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax.numpy as jnp

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.backend import set_backend
from lighthouse_tpu.crypto.cpu.curve import G2Point, g2_generator
from lighthouse_tpu.crypto.cpu.fields import Fq2
from lighthouse_tpu.crypto.params import P, R
from lighthouse_tpu.crypto.device import bls as device_bls
from lighthouse_tpu.crypto.device import curve, fp2


@pytest.fixture
def tpu_backend():
    set_backend("tpu")
    yield
    set_backend("cpu")


def _keypairs(n, base=1000):
    sks = [bls.SecretKey(base + i) for i in range(n)]
    return sks, [sk.public_key() for sk in sks]


def _make_sets(rng, n_sets, max_pks=3):
    """Realistic mixed sets: single- and multi-pubkey over varied messages."""
    sets = []
    for i in range(n_sets):
        k = rng.randrange(1, max_pks + 1)
        sks, pks = _keypairs(k, base=100 * i + 7)
        msg = bytes([i]) * 32
        agg = bls.AggregateSignature.infinity()
        for sk in sks:
            agg.add_assign(sk.sign(msg))
        sets.append(bls.SignatureSet(agg, pks, msg))
    return sets


def test_valid_batch_verifies(rng, tpu_backend):
    sets = _make_sets(rng, 5)
    assert bls.verify_signature_sets(sets) is True


def test_tampered_batch_fails(rng, tpu_backend):
    sets = _make_sets(rng, 4)
    # swap one set's message
    bad = bls.SignatureSet(sets[0].signature, sets[0].signing_keys, b"\xFF" * 32)
    assert bls.verify_signature_sets([bad] + sets[1:]) is False
    # wrong signer
    sk_evil = bls.SecretKey(0xE71)
    bad2 = bls.SignatureSet(
        sk_evil.sign(sets[1].message), sets[1].signing_keys, sets[1].message
    )
    assert bls.verify_signature_sets(sets[:1] + [bad2] + sets[2:]) is False


def test_edge_semantics_match_reference(tpu_backend):
    sks, pks = _keypairs(2)
    msg = b"\x22" * 32
    sig = sks[0].sign(msg)
    # empty batch => False
    assert bls.verify_signature_sets([]) is False
    # empty signing keys => False
    s = bls.SignatureSet(sig, [], msg)
    assert bls.verify_signature_sets([s]) is False
    # infinity signature => False
    s2 = bls.SignatureSet(bls.Signature.infinity(), [pks[0]], msg)
    assert bls.verify_signature_sets([s2]) is False


def test_single_verify_and_aggregate_paths(tpu_backend):
    sks, pks = _keypairs(3)
    msg = b"\x33" * 32
    sig = sks[0].sign(msg)
    assert sig.verify(pks[0], msg) is True
    assert sig.verify(pks[1], msg) is False

    agg = bls.AggregateSignature.infinity()
    for sk in sks:
        agg.add_assign(sk.sign(msg))
    assert agg.fast_aggregate_verify(msg, pks) is True
    assert agg.fast_aggregate_verify(b"\x00" * 32, pks) is False

    msgs = [bytes([i]) * 32 for i in range(3)]
    agg2 = bls.AggregateSignature.infinity()
    for sk, m in zip(sks, msgs):
        agg2.add_assign(sk.sign(m))
    assert agg2.aggregate_verify(msgs, pks) is True
    assert agg2.aggregate_verify(list(reversed(msgs)), pks) is False


def test_matches_cpu_backend_on_same_batches(rng):
    """Differential: tpu and cpu backends agree set-for-set."""
    sets = _make_sets(rng, 3)
    tampered = [
        bls.SignatureSet(sets[0].signature, sets[0].signing_keys, b"\x01" * 32)
    ] + sets[1:]
    for batch in (sets, tampered):
        set_backend("cpu")
        cpu_out = bls.verify_signature_sets(batch)
        set_backend("tpu")
        tpu_out = bls.verify_signature_sets(batch)
        set_backend("cpu")
        assert cpu_out == tpu_out


def test_staged_verify_b64_matmul_int8(rng, tmp_path):
    """Acceptance pin for the int8 limb-split fp.mul (VERDICT r5 rec #2):
    the FULL staged flagship — decompression, hash-to-curve, aggregation,
    subgroup scans, multi-pairing — at the bench fallback geometry B=64
    under FP_IMPL=matmul_int8, valid batch True / tampered batch False.
    The jit caches are dropped around the switch (trace-time dispatch).

    ISSUE 3 acceptance rides on the same (expensive) compile: the
    tampered run is an induced staged-verify FAILURE at B=64, which must
    journal a ``bls_stage_verify`` event and dump a forensics artifact
    that ``tools/forensics_report.py`` renders with per-stage latency
    attribution."""
    import tools.forensics_report as forensics
    from lighthouse_tpu.crypto import device
    from lighthouse_tpu.crypto.device import fp as device_fp
    from lighthouse_tpu.utils import flight_recorder as fr

    def triples(valid: bool):
        out = []
        for i in range(4):
            sks, pks = _keypairs(2, base=900 + 50 * i)
            msg = bytes([i + 1]) * 32
            signer = sks[0] if (valid or i != 2) else sks[1]
            agg = bls.AggregateSignature.infinity()
            agg.add_assign(signer.sign(msg))
            agg.add_assign(sks[1].sign(msg))
            out.append(
                (
                    bls.Signature.deserialize(agg.serialize()),
                    [pk.point for pk in pks],
                    msg,
                )
            )
        return out

    prev = fr.configure(
        enabled=True, dump=True, dump_dir=str(tmp_path),
        min_dump_interval_s=0.0,
    )
    with device_fp.impl(device_fp.IMPL_MATMUL_INT8):
        device.reset_compiled_state()
        try:
            ok = device_bls.verify_batch_raw_staged(
                *device_bls.pack_signature_sets_raw(
                    triples(True), pad_b=64, pad_k=8, pad_m=4
                )
            )
            bad = device_bls.verify_batch_raw_staged(
                *device_bls.pack_signature_sets_raw(
                    triples(False), pad_b=64, pad_k=8, pad_m=4
                )
            )
        finally:
            device.reset_compiled_state()  # never leak int8-traced kernels
            fr.configure(**prev)
    assert bool(ok) is True
    assert bool(bad) is False

    # both staged runs journaled one event each, with geometry + verdict
    evs = [
        e for e in fr.events(kinds=("bls_stage_verify",))
        if e["fields"]["b"] == 64 and e["fields"]["fp_impl"] == "matmul_int8"
    ]
    assert len(evs) >= 2
    assert evs[-2]["fields"]["verdict"] is True
    assert evs[-1]["fields"]["verdict"] is False
    assert evs[-1]["fields"]["recompiled"] is False  # same shape as the ok run
    assert all(evs[-1]["fields"][f"stage{i}_s"] > 0.0 for i in (1, 2, 3))

    # ISSUE 8 rides along: both staged verifies committed a
    # transfer_ledger row with the measured byte attribution, and the
    # second pack's pubkeys (same keypairs) hit the re-upload window
    from lighthouse_tpu.utils import transfer_ledger as tl

    tevs = [
        e for e in fr.events(kinds=("transfer_ledger",))
        if e["fields"]["b"] == 64
    ]
    assert len(tevs) >= 2
    model_total = tl.operand_bytes_model(64, 8, 4)["total"]
    for e in tevs[-2:]:
        f = e["fields"]
        assert f["h2d_bytes_total"] == model_total
        assert f["pubkeys_bytes"] + f["signatures_bytes"] \
            + f["messages_bytes"] + f["aux_bytes"] \
            + f["padding_bytes"] == model_total
        assert f["d2h_bytes"] >= 1 and f["pack_s"] > 0.0
    assert tevs[-1]["fields"]["pubkeys_reuploaded_bytes"] > 0
    assert tevs[-1]["fields"]["verdict"] is False

    # the induced failure dumped an artifact the forensics tool renders
    # with per-stage latency attribution
    dumps = sorted(tmp_path.glob(fr.DUMP_PREFIX + "*stage_verify_failure.json"))
    assert dumps, "failed staged verify must dump a forensics artifact"
    doc = forensics.load(str(dumps[-1]))
    assert doc["context"] == {
        "b": 64, "k": 8, "m": 4, "fp_impl": "matmul_int8"
    }
    text = forensics.render(doc)
    assert "stage latency attribution" in text
    assert "verdict=False" in text
    for stage in ("stage1", "stage2", "stage3"):
        assert stage in text


def test_staged_verify_populates_stage_telemetry(tpu_backend):
    """ISSUE 2: a staged verify must land per-stage timings in the
    ``{stage, fp_impl}`` family and tick the recompile counter exactly
    once per fresh argument-shape signature (the second identical-shape
    run reuses the jitted program: timings accrue, recompiles don't)."""
    from lighthouse_tpu.crypto.device import fp as device_fp
    from lighthouse_tpu.utils import metrics

    stage_vec = metrics.get("bls_device_stage_seconds")
    recompiles = metrics.get("bls_device_recompiles_total")
    impl = device_fp.get_impl()
    stages = ("stage1", "stage2", "stage3")

    sks, pks = _keypairs(1, base=4242)
    msg = b"\x77" * 32
    sig = bls.Signature.deserialize(sks[0].sign(msg).serialize())
    # pad_b=2/k=1/m=1 is a shape no other test uses: fresh to this process
    args = device_bls.pack_signature_sets_raw(
        [(sig, [pks[0].point], msg)], pad_b=2, pad_k=1, pad_m=1
    )

    counts0 = {s: stage_vec.with_labels(s, impl).total for s in stages}
    rec0 = {s: recompiles.with_labels(s).value for s in stages}
    assert bool(device_bls.verify_batch_raw_staged(*args)) is True
    rec1 = {s: recompiles.with_labels(s).value for s in stages}
    assert bool(device_bls.verify_batch_raw_staged(*args)) is True
    counts2 = {s: stage_vec.with_labels(s, impl).total for s in stages}
    rec2 = {s: recompiles.with_labels(s).value for s in stages}

    for s in stages:
        assert counts2[s] - counts0[s] == 2, (s, counts0, counts2)
        assert rec1[s] - rec0[s] == 1, (s, rec0, rec1)
        assert rec2[s] == rec1[s], (s, "second same-shape run recompiled")
        assert stage_vec.with_labels(s, impl).sum > 0.0

    # the backend path records batch geometry + verdict families and the
    # whole surface still scrapes cleanly
    assert bls.verify_signature_sets(
        [bls.SignatureSet(sig, [pks[0]], msg)]
    ) is True
    out = metrics.gather()
    assert 'bls_device_stage_seconds_bucket{stage="stage1"' in out
    assert 'bls_device_batch_lanes_total{dim="b",kind="padded"}' in out
    assert "bls_device_padding_waste_ratio" in out
    assert 'bls_device_verify_outcomes_total{outcome="ok"}' in out


def _non_subgroup_g2() -> G2Point:
    """A point on E'(Fp2) but outside G2 (cofactor > 1 makes this dense)."""
    x0 = 1
    while True:
        x = Fq2.from_ints(x0, 1)
        rhs = x.square() * x + Fq2.from_ints(4, 4)
        y = rhs.sqrt()
        if y is not None:
            pt = G2Point(x, y)
            if not pt.in_subgroup():
                return pt
        x0 += 1


def test_device_subgroup_check_equals_full_order_check(rng):
    good = [g2_generator().mul(rng.randrange(1, R)) for _ in range(2)]
    bad = [_non_subgroup_g2()]
    pts = good + bad + [G2Point.infinity()]
    xy, inf = curve.pack_g2(pts)
    dev = curve.from_affine(fp2, jnp.asarray(xy[:, 0]), jnp.asarray(xy[:, 1]), jnp.asarray(inf))
    got = list(np.asarray(device_bls.g2_in_subgroup(dev)))
    expect = [p.in_subgroup() or p.is_infinity() for p in pts]
    assert got == expect


def test_non_subgroup_signature_rejected_by_batch(rng, tpu_backend):
    sets = _make_sets(rng, 2)
    evil = bls.Signature(_non_subgroup_g2())
    bad = bls.SignatureSet(evil, sets[0].signing_keys, sets[0].message)
    assert bls.verify_signature_sets([bad] + sets[1:]) is False


def test_raw_compressed_batch_path(rng, tpu_backend):
    """The fully-raw flagship: compressed signatures decompressed on
    device; valid batch passes, tampered message fails, off-curve x is
    invalid (never an exception)."""
    sets = _make_sets(rng, 2)
    lazy_sets = [
        bls.SignatureSet(
            bls.Signature.deserialize(s.signature.serialize()),
            s.signing_keys,
            s.message,
        )
        for s in sets
    ]
    assert bls.verify_signature_sets(lazy_sets) is True
    bad = [
        bls.SignatureSet(lazy_sets[0].signature, lazy_sets[0].signing_keys, b"\x55" * 32)
    ] + lazy_sets[1:]
    assert bls.verify_signature_sets(bad) is False
    raw = bytearray(lazy_sets[0].signature.serialize())
    raw[50] ^= 0x01  # off-curve x
    evil = bls.Signature.deserialize(bytes(raw))
    assert (
        bls.verify_signature_sets(
            [bls.SignatureSet(evil, lazy_sets[0].signing_keys, lazy_sets[0].message)]
        )
        is False
    )
