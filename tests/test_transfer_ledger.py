"""Data-movement ledger (ISSUE 8): byte attribution, pack phases, the
repeat-pubkey sketch, bisection exactly-once labeling, and the
disabled-path cost gate.

The real device pack is exercised directly (pack only — no XLA compile,
so this file stays cheap enough for the tier-1 window); the scheduler
labeling tests run against a stub backend that mimics the device
packer's ledger calls, so the batcher's attribution contract is pinned
without a single jitted program.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from lighthouse_tpu.utils import flight_recorder, metrics, transfer_ledger as tl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def ledger_on():
    prev = tl.configure(enabled=True)
    yield
    tl.configure(**prev)


def _counter_delta(fam_name: str, snap: dict) -> dict:
    fam = metrics.get(fam_name)
    out = {}
    for labels, child in fam.children().items():
        d = child.value - snap.get(labels, 0)
        if d:
            out[labels] = d
    return out


def _counter_snap(fam_name: str) -> dict:
    fam = metrics.get(fam_name)
    if fam is None:
        return {}
    return {labels: c.value for labels, c in fam.children().items()}


# ---------------------------------------------------------------------------
# Byte model vs the real packer (ground truth = ndarray.nbytes)
# ---------------------------------------------------------------------------


def _real_triples(n, k=2, n_msgs=2, base=4000):
    from lighthouse_tpu.crypto import bls

    out = []
    for i in range(n):
        sks = [bls.SecretKey(base + 50 * i + j) for j in range(k)]
        pks = [sk.public_key().point for sk in sks]
        msg = bytes([i % n_msgs + 1]) * 32
        agg = bls.AggregateSignature.infinity()
        for sk in sks:
            agg.add_assign(sk.sign(msg))
        out.append(
            (bls.Signature.deserialize(agg.serialize()), pks, msg)
        )
    return out


@pytest.mark.parametrize("pad_b", (48, 64))
def test_packer_bytes_match_nbytes_and_model(ledger_on, pad_b):
    """ISSUE 8 satellite: at B=48/64 the ledger's per-operand byte split
    (incl. the padding share) sums to the EXACT ndarray.nbytes of the
    device_put operands, and equals the shared analytic model the
    planner and the report tool price plans with."""
    from lighthouse_tpu.crypto.device import bls as device_bls

    sets = _real_triples(4, k=2, n_msgs=2)
    snap = _counter_snap("bls_device_h2d_bytes_total")
    with tl.context("zledger_test", "direct"):
        args = device_bls.pack_signature_sets_raw(
            sets, pad_b=pad_b, pad_k=8, pad_m=4
        )
    row = tl.pending_pack()
    assert row is not None
    assert (row["b"], row["k"], row["m"]) == (pad_b, 8, 4)
    actual = sum(int(a.nbytes) for a in args)
    model = tl.operand_bytes_model(pad_b, 8, 4)
    assert row["h2d_bytes_total"] == actual == model["total"]
    ops = row["h2d_bytes"]
    assert set(ops) == set(tl.OPERANDS)
    assert sum(ops.values()) == actual
    # padding share: 4 live sets of 2 keys over 2 messages at this rung
    live = tl.live_operand_bytes(4, 8, 2)
    assert ops["pubkeys"] == live["pubkeys"]
    assert ops["padding"] == actual - (live["total"])
    # the counter family saw exactly these bytes, attributed to the
    # context kind
    delta = _counter_delta("bls_device_h2d_bytes_total", snap)
    assert sum(delta.values()) == actual
    assert all(kind == "zledger_test" for (_op, kind) in delta)


def test_pack_phase_sum_close_to_total(ledger_on):
    """Ledger phases cover the pack: decode + limb_split + pad + hash +
    device_put ≈ the packer's own total wall time."""
    from lighthouse_tpu.crypto.device import bls as device_bls

    sets = _real_triples(3, k=2, base=7000)
    with tl.context("zledger_phase", "direct"):
        device_bls.pack_signature_sets_raw(sets, pad_b=8, pad_k=4, pad_m=4)
    row = tl.pending_pack()
    assert set(row["phases"]) == set(tl.PACK_PHASES)
    phase_sum = sum(row["phases"].values())
    assert phase_sum <= row["pack_s"] + 1e-6
    # un-phased residue (digesting, dict assembly) must stay small
    assert row["pack_s"] - phase_sum < max(0.005, 0.15 * row["pack_s"])
    # and the family carries every phase + total
    fam = metrics.get("bls_device_pack_seconds")
    have = {labels[0] for labels in fam.children()}
    assert set(tl.PACK_PHASES) | {"total"} <= have


def test_commit_verify_journals_one_row(ledger_on):
    """commit_verify pops the staged row into ONE transfer_ledger
    journal event with the d2h verdict bytes; a second commit without a
    fresh pack journals nothing (exactly-once per pack)."""
    prev = flight_recorder.configure(enabled=True)
    try:
        with tl.context("zledger_commit", "fused"):
            tl.note_pack(
                n_sets=2, b=4, k=2, m=2, pk_slots=3, m_req=2,
                phases={"decode": 0.001}, total_s=0.002,
                operand_nbytes={
                    "pubkeys": 2056, "signatures": 1028,
                    "messages": 1040, "aux": 36,
                },
                pubkey_blobs=[b"a" * 256, b"b" * 256, b"a" * 256],
            )
            tl.commit_verify(True, d2h_bytes=1)
            n_before = len(flight_recorder.events(kinds=("transfer_ledger",)))
            tl.commit_verify(True, d2h_bytes=1)  # no staged row -> no event
        evs = flight_recorder.events(kinds=("transfer_ledger",))
        assert len(evs) == n_before
        f = evs[-1]["fields"]
        assert f["kind"] == "zledger_commit" and f["path"] == "fused"
        assert f["n_sets"] == 2 and f["d2h_bytes"] == 1
        assert f["pubkeys_uploaded_bytes"] == 768
        assert f["pubkeys_reuploaded_bytes"] >= 256  # b"a"*256 repeated
        assert f["verdict"] is True
    finally:
        flight_recorder.configure(**prev)


# ---------------------------------------------------------------------------
# Repeat-pubkey sketch
# ---------------------------------------------------------------------------


def test_reupload_window_wraparound():
    t = tl.ReuploadTracker(window=2)
    d = tl.pubkey_digest
    assert t.observe("a", [(d(b"k1"), 100)]) == (0, 100)
    assert t.observe("a", [(d(b"k1"), 100)]) == (100, 100)
    s = t.summary()
    assert s["uploaded_bytes"] == 200 and s["reuploaded_bytes"] == 100
    assert s["ratio"] == 0.5
    # third record evicts the first: totals shrink exactly
    t.observe("a", [(d(b"k2"), 100)])
    s = t.summary()
    assert s["records"] == 2
    assert s["uploaded_bytes"] == 200
    # the re-upload mark is insert-time sticky (documented)
    assert s["reuploaded_bytes"] == 100
    # evict everything a-kind: kind vanishes from the summary
    t.observe("b", [(d(b"k3"), 1)])
    t.observe("b", [(d(b"k3"), 1)])
    assert "a" not in t.summary()["kinds"]
    # both k3 records in window: 2 uploaded, second one a re-upload
    assert t.summary()["kinds"]["b"]["ratio"] == 0.5
    assert t.ratio() == t.summary()["ratio"]


def test_reupload_zero_byte_record_eviction():
    """Regression: a zero-upload observation keeps a ring entry alive
    after its kind's totals hit 0 and were popped — evicting that
    record must not raise (the packer hot path runs inside this
    lock)."""
    t = tl.ReuploadTracker(window=2)
    d = tl.pubkey_digest
    t.observe("a", [(d(b"k1"), 100)])
    t.observe("a", [])                  # zero-byte record, same kind
    t.observe("b", [(d(b"k2"), 50)])    # evicts the 100B 'a' -> 'a' popped
    t.observe("b", [(d(b"k2"), 50)])    # evicts the zero-byte 'a' record
    s = t.summary()
    assert s["records"] == 2
    assert "a" not in s["kinds"]
    assert s["kinds"]["b"]["uploaded_bytes"] == 100


def test_reupload_concurrent_submitters():
    """Byte conservation under concurrent observers: whatever the
    interleaving, window totals equal the sum of surviving records and
    the digest index never goes negative."""
    t = tl.ReuploadTracker(window=64)
    d = tl.pubkey_digest
    n_threads, per_thread = 8, 200

    def worker(tid):
        for i in range(per_thread):
            t.observe(
                f"kind{tid % 2}",
                [(d(f"{tid}:{i % 10}".encode()), 256)],
            )

    threads = [
        threading.Thread(target=worker, args=(tid,))
        for tid in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    s = t.summary()
    assert s["records"] == 64
    assert s["uploaded_bytes"] == 64 * 256
    assert 0 <= s["reuploaded_bytes"] <= s["uploaded_bytes"]
    assert s["uploaded_bytes"] == sum(
        k["uploaded_bytes"] for k in s["kinds"].values()
    )
    with t._lock:
        assert all(c > 0 for c in t._counts.values())


# ---------------------------------------------------------------------------
# Scheduler attribution: exactly-once under bisection
# ---------------------------------------------------------------------------


class _Poison:
    pass


def _stub_device_verify(sets) -> bool:
    """Mimics the device backend's ledger behavior: one note_pack +
    commit_verify per call, bytes proportional to the batch, verdict
    False when any poison set is present."""
    n = len(sets)
    tl.note_pack(
        n_sets=n, b=n, k=1, m=1, pk_slots=n, m_req=1,
        phases={"decode": 0.0}, total_s=0.0,
        operand_nbytes={
            "pubkeys": 257 * n, "signatures": 129 * n,
            "messages": 132 * n, "aux": 9 * n,
        },
        pubkey_blobs=[b"stub" * 64] * n,
    )
    ok = not any(isinstance(s, _Poison) for s in sets)
    tl.commit_verify(ok, d2h_bytes=1)
    return ok


def test_bisection_packs_labeled_exactly_once(ledger_on):
    """ISSUE 8 satellite (poison pin): a split-and-retry resolution
    re-packs sub-batches — those packs are REAL bytes but must land
    under path=bisection, never inflate the original flush's
    attribution, and every pack appears in the journal exactly once."""
    from lighthouse_tpu.verification_service import VerificationScheduler

    prev = flight_recorder.configure(enabled=True)
    snap = _counter_snap("bls_device_h2d_bytes_total")
    seq_before = len(flight_recorder.events(kinds=("transfer_ledger",)))
    sched = VerificationScheduler(
        verify_fn=_stub_device_verify, deadline_ms=5.0,
        plan_flushes=False,
    ).start()
    try:
        futs = [
            sched.submit([object()], "kind_a"),
            sched.submit([object()], "kind_b"),
            sched.submit([_Poison()], "kind_poison"),
            sched.submit([object()], "kind_c"),
        ]
        sched.flush()
        results = [f.result(timeout=30) for f in futs]
    finally:
        sched.stop()
        flight_recorder.configure(**prev)
    assert results == [True, True, False, True]

    evs = flight_recorder.events(kinds=("transfer_ledger",))[seq_before:]
    fused = [e for e in evs if e["fields"]["path"] == "fused"]
    bisection = [e for e in evs if e["fields"]["path"] == "bisection"]
    # the original flush packed ONCE; every re-pack is a bisection leaf
    # or group retry — no other paths, no double counting
    assert len(fused) == 1
    assert fused[0]["fields"]["n_sets"] == 4
    assert len(bisection) >= 2
    assert len(fused) + len(bisection) == len(evs)
    # bisection rows carry the kind mix of THEIR group, not the flush's
    assert any(
        e["fields"]["kind"] == "kind_poison" for e in bisection
    )
    # byte conservation: the counter saw each pack exactly once
    delta = _counter_delta("bls_device_h2d_bytes_total", snap)
    journal_bytes = sum(e["fields"]["h2d_bytes_total"] for e in evs)
    assert sum(delta.values()) == journal_bytes
    # and the original flush's kind-mix attribution is exactly one
    # batch's worth of bytes (4 sets at 527 B/set in the stub model)
    fused_kind = fused[0]["fields"]["kind"]
    fused_bytes = sum(
        v for (op, kind), v in delta.items() if kind == fused_kind
    )
    assert fused_bytes == fused[0]["fields"]["h2d_bytes_total"]


def test_commit_pops_pending_even_when_disabled(ledger_on):
    """Regression: a row staged while enabled must not survive a
    disable/enable cycle and be journaled against a later, unrelated
    verify — commit pops the thread-local row unconditionally."""
    tl.note_pack(
        n_sets=1, b=1, k=1, m=1, pk_slots=1, m_req=1,
        phases={}, total_s=0.0,
        operand_nbytes={"pubkeys": 257}, pubkey_blobs=[b"x" * 256],
    )
    assert tl.pending_pack() is not None
    inner = tl.configure(enabled=False)
    try:
        tl.commit_verify(True)  # disabled — but the stale row must go
    finally:
        tl.configure(**inner)
    assert tl.pending_pack() is None


def test_raising_staged_verify_still_journals_row(ledger_on, monkeypatch):
    """Regression: a staged verify that raises already shipped (and
    counted) its pack's bytes — the ledger row must land with a null
    verdict and the staged row must not leak to a later verify."""
    from lighthouse_tpu.crypto.device import bls as device_bls

    def boom(*a, **k):
        raise RuntimeError("stage exploded")

    monkeypatch.setattr(device_bls, "_stage1", boom)
    prev = flight_recorder.configure(enabled=True)
    try:
        sets = _real_triples(2, k=1, base=11000)
        with tl.context("zledger_raise", "fused"):
            args = device_bls.pack_signature_sets_raw(
                sets, pad_b=2, pad_k=1, pad_m=2
            )
            with pytest.raises(RuntimeError):
                device_bls.verify_batch_raw_staged(*args)
        ev = flight_recorder.events(kinds=("transfer_ledger",))[-1]
    finally:
        flight_recorder.configure(**prev)
    f = ev["fields"]
    assert f["kind"] == "zledger_raise"
    assert f["verdict"] is None and f["d2h_bytes"] == 0
    assert f["h2d_bytes_total"] > 0
    assert tl.pending_pack() is None


def test_record_cpu_zero_row(ledger_on):
    """CPU resolutions journal explicit zero-device-byte rows under the
    attribution context (the compile-service fallback's contract)."""
    prev = flight_recorder.configure(enabled=True)
    try:
        with tl.context("zledger_cpu", "fallback"):
            tl.record_cpu(7)
        ev = flight_recorder.events(kinds=("transfer_ledger",))[-1]
    finally:
        flight_recorder.configure(**prev)
    f = ev["fields"]
    assert f["kind"] == "zledger_cpu" and f["path"] == "fallback"
    assert f["n_sets"] == 7
    assert f["h2d_bytes_total"] == 0 and f["d2h_bytes"] == 0
    assert f["verdict"] is None


# ---------------------------------------------------------------------------
# Cost gates
# ---------------------------------------------------------------------------


def test_disabled_ledger_under_one_microsecond():
    """Disabled recording entry points cost < 1 µs (pinned like
    disabled spans): the ledger stays always-on in the packer."""
    prev = tl.configure(enabled=False)
    try:
        calls = (
            lambda: tl.note_pack(
                n_sets=1, b=1, k=1, m=1, pk_slots=1, m_req=1,
                phases={}, total_s=0.0, operand_nbytes={},
                pubkey_blobs=(),
            ),
            lambda: tl.commit_verify(True),
            lambda: tl.record_cpu(1),
        )
        for call in calls:
            n = 20_000
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(n):
                    call()
                best = min(best, (time.perf_counter() - t0) / n)
            assert best < 1e-6, (
                f"disabled ledger call costs {best * 1e9:.0f} ns"
            )
    finally:
        tl.configure(**prev)


def test_disabled_packer_skips_collection():
    """With the ledger off, the raw packer stages no row (and per-pubkey
    blob collection is gated off — the disabled path must not pay for
    instrumentation it will drop)."""
    from lighthouse_tpu.crypto.device import bls as device_bls

    prev = tl.configure(enabled=False)
    try:
        tl._tls.pending = None
        sets = _real_triples(2, k=1, base=9000)
        device_bls.pack_signature_sets_raw(sets, pad_b=2, pad_k=1, pad_m=2)
        assert tl.pending_pack() is None
    finally:
        tl.configure(**prev)


def test_enabled_ledger_cost_headline_shape(ledger_on):
    """Acceptance: the enabled ledger's own work at the headline pack
    shape (48 sets x 8 keys = 384 pubkey digests + counters + journal)
    stays far under 1% of a staged verify's wall (≈9 s at the headline
    bucket on this box; we pin < 10 ms, i.e. <1% of even a 1 s
    verify)."""
    blobs = [os.urandom(256) for _ in range(384)]
    nbytes = {
        "pubkeys": 48 * 8 * 257, "signatures": 48 * 257,
        "messages": 4 * 512 + 48 * 4, "aux": 48 * 9,
    }
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        tl.note_pack(
            n_sets=48, b=48, k=8, m=4, pk_slots=384, m_req=4,
            phases={p: 0.001 for p in tl.PACK_PHASES}, total_s=0.005,
            operand_nbytes=nbytes, pubkey_blobs=blobs,
        )
        tl.commit_verify(True, d2h_bytes=1)
    per_verify = (time.perf_counter() - t0) / reps
    assert per_verify < 0.010, (
        f"enabled ledger costs {per_verify * 1e3:.2f} ms per headline "
        f"verify — too expensive to leave always-on"
    )


# ---------------------------------------------------------------------------
# Jax-freedom + device-memory null-safety + report tool
# ---------------------------------------------------------------------------


def test_ledger_and_tools_are_jax_free():
    """The ledger, the planner's byte accounting and both new tools
    import without jax (subprocess-pinned, the flush_plan_report
    discipline)."""
    code = (
        "import sys; "
        "import lighthouse_tpu.utils.transfer_ledger; "
        "import lighthouse_tpu.verification_service.planner; "
        "import tools.transfer_report; "
        "import tools.bench_diff; "
        "assert 'jax' not in sys.modules, 'jax leaked into the ledger path'"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr


def test_update_device_memory_null_safe():
    """No jax imported -> None (never an import); with jax loaded the
    probe reports live_buffers and never raises."""
    out = tl.update_device_memory(force=True)
    if "jax" not in sys.modules:
        assert out is None
    else:
        assert out is None or "live_buffers" in out


def test_transfer_report_replay_model_gossip_steady():
    """ISSUE 8 acceptance (modeled half): under gossip-steady traffic
    spanning several epochs, the modeled pubkey re-upload ratio is
    > 0.5 (same validators re-sign every epoch) and pubkeys dominate
    the per-operand byte attribution — the sized evidence for ROADMAP
    item 2."""
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "transfer_report.py"),
         "--generate", "gossip_steady", "--duration", "24",
         "--seed", "7", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["mode"] == "replay_model"
    assert rep["reupload_model"]["ratio"] > 0.5
    ops = rep["h2d_bytes_by_operand"]
    assert ops["pubkeys"] == max(ops.values())
    assert rep["dedup_opportunity_bytes"] > 0
    assert 0 < rep["pubkey_bytes_share"] <= 1
    # per-kind rows cover every generator kind
    assert any("aggregate" in k for k in rep["per_kind"])
    assert any("unaggregated" in k for k in rep["per_kind"])


def test_planner_plan_carries_byte_accounting():
    """Plan elements price their padded rung with the shared byte model
    (scheduler journal + lockstep replay read these fields)."""
    from lighthouse_tpu.verification_service import traffic
    from lighthouse_tpu.verification_service.planner import FlushPlanner

    subs = [
        traffic.ReplaySubmission(
            "aggregate", traffic.synthetic_sets("aggregate", 8, 8, 1)
        ),
        traffic.ReplaySubmission(
            "unaggregated", traffic.synthetic_sets("unaggregated", 24, 1, 1)
        ),
    ]
    plan = FlushPlanner(enabled=True).plan(subs)
    assert plan.est_h2d_bytes == sum(
        sb.est_h2d_bytes for sb in plan.sub_batches
    )
    for sb in plan.sub_batches:
        assert sb.est_h2d_bytes == tl.operand_bytes_model(*sb.rung)["total"]
        assert sb.est_live_h2d_bytes <= sb.est_h2d_bytes
    # lockstep flushes expose the same accounting
    events = traffic.gossip_steady(duration_s=3.0, seed=3)
    rep = traffic.lockstep_replay(events)
    assert rep["flushes"]
    for fl in rep["flushes"]:
        assert fl["sub_batches"]
        for sb in fl["sub_batches"]:
            b, k, m = sb["rung"]
            assert sb["est_h2d_bytes"] == tl.operand_bytes_model(
                b, k, m
            )["total"]
