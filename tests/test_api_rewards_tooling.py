"""Round-4 operator-surface additions (VERDICT r3 #8): blinded-block
production/submission, block + attestation rewards, liveness, peer_count
routes; am validator-deposits/validator-exit; db version/migrate/prune;
lcli new-testnet."""

import copy
import json
import urllib.request

import pytest

from lighthouse_tpu.beacon_chain import BeaconChain
from lighthouse_tpu.crypto import backend
from lighthouse_tpu.http_api import BeaconApiServer
from lighthouse_tpu.operation_pool import OperationPool
from lighthouse_tpu.state_transition import store_replayer
from lighthouse_tpu.store import HotColdDB, MemoryStore
from lighthouse_tpu.testing.harness import StateHarness
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.preset import MINIMAL
from lighthouse_tpu.utils.slot_clock import ManualSlotClock


@pytest.fixture(autouse=True)
def fake_backend():
    backend.set_backend("fake")
    yield
    backend.set_backend("cpu")


def _mk_node(fork="altair", n=8):
    spec = minimal_spec(
        altair_fork_epoch=0 if fork != "phase0" else None,
        bellatrix_fork_epoch=0 if fork == "bellatrix" else None,
    )
    h = StateHarness(MINIMAL, spec, validator_count=n, fork_name=fork, fake_sign=True)
    genesis = copy.deepcopy(h.state)
    db = HotColdDB(MemoryStore(), h.t, h.spec, store_replayer(h.preset, h.spec))
    clock = ManualSlotClock(genesis.genesis_time, h.spec.seconds_per_slot)
    chain = BeaconChain(h.preset, h.spec, h.t, db, genesis, slot_clock=clock)
    chain.op_pool = OperationPool(h.preset, h.spec, h.t)
    return h, chain, clock


def _get(server, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}{path}", timeout=10
    ) as r:
        return json.loads(r.read())


def _post(server, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        raw = r.read()
        return json.loads(raw) if raw else None


def _grow(h, chain, clock, n_slots):
    for _ in range(n_slots):
        slot = h.state.slot + 1
        clock.set_slot(slot)
        atts = (
            h.attestations_for_slot(h.state, h.state.slot)[: MINIMAL.MAX_ATTESTATIONS]
            if slot >= 2
            else []
        )
        sb = h.produce_block(slot, attestations=atts)
        h.process_block(sb, strategy="none")
        chain.process_block(chain.verify_block_for_gossip(sb))


def test_block_rewards_route():
    h, chain, clock = _mk_node("altair")
    server = BeaconApiServer(chain, port=0).start()
    try:
        _grow(h, chain, clock, 4)
        out = _get(server, "/eth/v1/beacon/rewards/blocks/head")
        data = out["data"]
        assert int(data["proposer_index"]) < 8
        total = int(data["total"])
        assert total == (
            int(data["attestations"]) + int(data["sync_aggregate"])
            + int(data["proposer_slashings"]) + int(data["attester_slashings"])
        )
        assert int(data["attestations"]) > 0  # block carried attestations
    finally:
        server.stop()


def test_attestation_rewards_route():
    h, chain, clock = _mk_node("altair")
    server = BeaconApiServer(chain, port=0).start()
    try:
        _grow(h, chain, clock, MINIMAL.SLOTS_PER_EPOCH + 2)
        out = _post(server, "/eth/v1/beacon/rewards/attestations/0", [])
        data = out["data"]
        assert data["ideal_rewards"], "no ideal rewards tiers"
        assert data["total_rewards"], "no per-validator rewards"
        row = data["total_rewards"][0]
        for key in ("validator_index", "head", "target", "source", "inactivity"):
            assert key in row
        # fully-participating minimal chain: positive target rewards
        assert any(int(r["target"]) > 0 for r in data["total_rewards"])
    finally:
        server.stop()


def test_config_routes():
    h, chain, clock = _mk_node("altair")
    server = BeaconApiServer(chain, port=0).start()
    try:
        dc = _get(server, "/eth/v1/config/deposit_contract")["data"]
        assert dc["address"].startswith("0x") and len(dc["address"]) == 42
        assert dc["chain_id"].isdigit()
        fs = _get(server, "/eth/v1/config/fork_schedule")["data"]
        assert fs[0]["epoch"] == "0"
        # altair active at 0 in this spec: two entries (phase0 + altair)
        assert len(fs) >= 2
        # versions chain: each previous_version == prior current_version
        for a, b in zip(fs, fs[1:]):
            assert b["previous_version"] == a["current_version"]
    finally:
        server.stop()


def test_balances_sync_committees_and_pool_dumps():
    h, chain, clock = _mk_node("altair")
    server = BeaconApiServer(chain, port=0).start()
    try:
        _grow(h, chain, clock, 3)
        out = _get(server, "/eth/v1/beacon/states/head/validator_balances?id=0,3")
        assert {r["index"] for r in out["data"]} == {"0", "3"}
        assert all(int(r["balance"]) > 0 for r in out["data"])
        sc = _get(server, "/eth/v1/beacon/states/head/sync_committees")["data"]
        assert len(sc["validators"]) == MINIMAL.SYNC_COMMITTEE_SIZE
        assert sc["validator_aggregates"]
        # pool dumps round-trip an inserted exit
        ex = h.t.SignedVoluntaryExit(
            message=h.t.VoluntaryExit(epoch=0, validator_index=2),
            signature=b"\x00" * 96,
        )
        chain.op_pool.insert_voluntary_exit(ex)
        dump = _get(server, "/eth/v1/beacon/pool/voluntary_exits")["data"]
        assert dump and dump[0]["message"]["validator_index"] == "2"
        assert _get(server, "/eth/v1/beacon/pool/attester_slashings")["data"] == []
    finally:
        server.stop()


def test_liveness_and_peer_count_routes():
    h, chain, clock = _mk_node("altair")
    server = BeaconApiServer(chain, port=0).start()
    try:
        _grow(h, chain, clock, 3)
        epoch = 0
        chain.observed_attesters.observe(3, epoch)
        out = _post(server, f"/eth/v1/validator/liveness/{epoch}", ["3", "5"])
        by_idx = {r["index"]: r["is_live"] for r in out["data"]}
        assert by_idx == {"3": True, "5": False}
        pc = _get(server, "/eth/v1/node/peer_count")
        assert pc["data"]["connected"] == "0"  # no network attached here
    finally:
        server.stop()


def test_blinded_block_roundtrip_bellatrix():
    h, chain, clock = _mk_node("bellatrix")
    server = BeaconApiServer(chain, port=0).start()
    try:
        _grow(h, chain, clock, 2)
        slot = int(h.state.slot) + 1
        clock.set_slot(slot)
        randao = h.randao_reveal(h.state, slot, 0)
        out = _get(
            server,
            f"/eth/v1/validator/blinded_blocks/{slot}?randao_reveal=0x{randao.hex()}",
        )
        assert out["version"] == "bellatrix"
        blinded = out["data"]
        assert "execution_payload_header" in blinded["body"]
        # sign the blinded message (its root == the full block's root,
        # since the payload header commits to the payload) and submit;
        # the server must reconstruct the payload from its cache and the
        # block must pass the full state transition
        t = h.t
        from lighthouse_tpu.ssz.json import from_json

        msg = from_json(t.BlindedBeaconBlockBellatrix, blinded)
        signed = h.sign_block(msg, int(blinded["proposer_index"]))
        sbb = {
            "message": blinded,
            "signature": "0x" + bytes(signed.signature).hex(),
        }
        _post(server, "/eth/v1/beacon/blinded_blocks", sbb)
        # the chain imported it: head advanced to the submitted slot
        head_block = chain.store.get_block(chain.head_block_root)
        assert int(head_block.message.slot) == slot
    finally:
        server.stop()


# -- CLI tooling ------------------------------------------------------------


def test_am_deposits_and_exit(tmp_path):
    # the account-manager keystore paths (scrypt/AES) need the optional
    # cryptography dependency — skip cleanly where the box lacks it, like
    # the network/keys test modules already do at collection
    pytest.importorskip("cryptography")
    from lighthouse_tpu.cli import main

    wallet = tmp_path / "wallet.json"
    vdir = tmp_path / "validators"
    import unittest.mock as mock

    with mock.patch("getpass.getpass", return_value="pw"):
        assert main(["am", "wallet-create", "--name", "w", "--out", str(wallet), "--kdf-work", "1024"]) == 0
        assert (
            main([
                "am", "validator-create", "--wallet", str(wallet),
                "--out-dir", str(vdir), "--count", "2", "--kdf-work", "1024",
            ])
            == 0
        )
    deposits = tmp_path / "deposit_data.json"
    assert (
        main([
            "am", "validator-deposits", "--validator-dir", str(vdir),
            "--out", str(deposits), "--password", "pw", "--spec", "minimal",
        ])
        == 0
    )
    docs = json.loads(deposits.read_text())
    assert len(docs) == 2
    for d in docs:
        assert len(bytes.fromhex(d["pubkey"])) == 48
        assert bytes.fromhex(d["withdrawal_credentials"])[0] == 0
        assert len(bytes.fromhex(d["signature"])) == 96
        assert d["amount"] == 32 * 10**9

    ks = sorted(vdir.glob("keystore-*.json"))[0]
    exit_out = tmp_path / "exit.json"
    assert (
        main([
            "am", "validator-exit", "--keystore", str(ks),
            "--validator-index", "7", "--epoch", "3",
            "--genesis-validators-root", "0x" + "11" * 32,
            "--out", str(exit_out), "--password", "pw", "--spec", "minimal",
        ])
        == 0
    )
    doc = json.loads(exit_out.read_text())
    assert doc["message"]["validator_index"] == "7"
    assert doc["message"]["epoch"] == "3"
    assert len(bytes.fromhex(doc["signature"][2:])) == 96


def test_db_version_migrate_prune(tmp_path):
    from lighthouse_tpu.cli import main
    from lighthouse_tpu.store import Column, SqliteStore

    # build a tiny datadir with pre-split snapshots
    h, chain, clock = _mk_node("phase0")
    _grow(h, chain, clock, 3)
    kv = SqliteStore(f"{tmp_path}/chain.sqlite")
    for root, state in [
        (b"\x01" * 32, h.state),
    ]:
        data = bytes([1]) + type(state).encode(state)
        kv.put(Column.STATE, root, data)
    import struct

    kv.put(Column.METADATA, b"split", struct.pack("<Q", int(h.state.slot) + 10))
    kv.close()

    assert main(["db", "version", "--datadir", str(tmp_path)]) == 0
    assert main(["db", "migrate", "--datadir", str(tmp_path)]) == 0
    assert main(["db", "prune", "--datadir", str(tmp_path)]) == 0
    kv = SqliteStore(f"{tmp_path}/chain.sqlite")
    assert kv.get(Column.STATE, b"\x01" * 32) is None, "pre-split snapshot kept"


def test_lcli_new_testnet(tmp_path):
    import yaml

    from lighthouse_tpu.cli import main

    out = tmp_path / "testnet"
    assert (
        main([
            "lcli", "new-testnet", "--preset", "minimal", "--validators", "8",
            "--genesis-time", "12345", "--out-dir", str(out),
        ])
        == 0
    )
    cfg = yaml.safe_load((out / "config.yaml").read_text())
    assert cfg["PRESET_BASE"] == "minimal"
    assert cfg["MIN_GENESIS_TIME"] == 12345
    raw = (out / "genesis.ssz").read_bytes()
    from lighthouse_tpu.types.containers import types_for

    t = types_for(MINIMAL)
    st = t.state["phase0"].decode(raw[1:])
    assert len(st.validators) == 8
    assert st.genesis_time == 12345
