"""Remote monitoring push loop (ISSUE 3 satellite): retry with bounded
exponential backoff + jitter, and a scrapeable per-outcome counter —
against a local HTTP stub, no network deps."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from lighthouse_tpu.utils import metrics
from lighthouse_tpu.utils.monitoring import MonitoringService, collect


def _stub_chain():
    """The minimal chain surface collect() reads."""
    return SimpleNamespace(
        head_state=SimpleNamespace(slot=17),
        fork_choice=SimpleNamespace(
            store=SimpleNamespace(finalized_checkpoint=(2, b"\x00" * 32))
        ),
        network=None,
    )


class _Collector:
    """HTTP stub: fails the first ``fail_first`` POSTs with 500, then
    accepts; records every received document."""

    def __init__(self, fail_first: int):
        self.docs = []
        self.requests = 0
        self._fail_first = fail_first
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                outer.requests += 1
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                if outer.requests <= outer._fail_first:
                    self.send_response(500)
                    self.end_headers()
                    return
                outer.docs.append(json.loads(body))
                self.send_response(200)
                self.end_headers()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}/"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_push_outcomes_counted_and_document_shape():
    stub = _Collector(fail_first=1)
    push_total = metrics.get("monitoring_push_total")
    ok0 = push_total.with_labels("ok").value
    err0 = push_total.with_labels("error").value
    try:
        svc = MonitoringService(_stub_chain(), stub.url, interval_s=60.0)
        assert svc.push_once() is False      # stubbed 500
        assert svc.push_once() is True       # accepted
        assert svc.sent == 1 and svc.errors == 1
        assert push_total.with_labels("ok").value == ok0 + 1
        assert push_total.with_labels("error").value == err0 + 1
        (doc,) = stub.docs
        assert doc["beacon_node"]["head_slot"] == 17
        assert doc["beacon_node"]["finalized_epoch"] == 2
        assert doc["process"]["pid"] > 0
    finally:
        stub.close()


def test_backoff_is_bounded_exponential_with_jitter():
    svc = MonitoringService(
        _stub_chain(), "http://127.0.0.1:9/", interval_s=60.0,
        base_backoff_s=1.0, max_backoff_s=8.0,
    )
    # no failures: the regular cadence
    assert svc.next_wait(0) == 60.0
    # failures: ceiling doubles 1, 2, 4, 8, 8, ... with jitter in
    # [0.5, 1.0] x ceiling — never above the cap, never near-zero
    for fails, ceiling in ((1, 1.0), (2, 2.0), (3, 4.0), (4, 8.0), (9, 8.0)):
        waits = [svc.next_wait(fails) for _ in range(50)]
        assert all(0.5 * ceiling <= w <= ceiling for w in waits), (fails, waits[:5])
    # jitter actually jitters (50 draws cannot all collide)
    assert len({round(w, 9) for w in [svc.next_wait(3) for _ in range(50)]}) > 1
    # the cap never exceeds the push interval itself
    svc2 = MonitoringService(
        _stub_chain(), "http://127.0.0.1:9/", interval_s=5.0,
        base_backoff_s=1.0, max_backoff_s=300.0,
    )
    assert svc2.max_backoff_s == 5.0


def test_push_loop_retries_through_failures():
    """End-to-end: the loop retries with backoff past 2 stubbed failures
    and lands a document well before the 60 s interval would allow."""
    import time

    stub = _Collector(fail_first=2)
    try:
        svc = MonitoringService(
            _stub_chain(), stub.url, interval_s=0.05,
            base_backoff_s=0.01, max_backoff_s=0.05,
        ).start()
        deadline = time.monotonic() + 5.0
        while not stub.docs and time.monotonic() < deadline:
            time.sleep(0.01)
        svc.stop()
        assert stub.docs, "loop never recovered past the stubbed failures"
        assert svc.errors >= 2 and svc.sent >= 1
    finally:
        stub.close()
