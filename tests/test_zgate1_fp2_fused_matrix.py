"""The fp2 differential suite, re-collected under the FUSED Pallas
tower engine (``FP2_IMPL=fused_pallas``), plus the dedicated fused
line-evaluation differential (ISSUE 16).

Every test function of ``test_device_fp2.py`` is imported and re-run
here with the autouse fixture switching the tower engine — the
acceptance bar for the fused kernels is "verdict-identical to the
composed engine across every existing differential test", and
re-collection keeps that true BY CONSTRUCTION as the base suite grows.
The composed engine runs the same tests natively (default impl), so a
divergence between engines fails exactly one of the two collections and
names the culprit.

Named ``test_zgate1_*`` for the same tail-sorting reason as the fp.mul
impl matrix (see that module's docstring): the doubled runtime collects
AFTER the functional suite but BEFORE the compile-heavy zgate2/zgate3
gates. Off-TPU the fused kernels run in Pallas interpreter mode — exact
same arithmetic, no Mosaic lowering — so this matrix is a semantics
gate everywhere and a performance path only on TPU.
"""

import numpy as np
import pytest

from lighthouse_tpu.crypto.params import P
from lighthouse_tpu.crypto.device import fp, fp2, pairing

from test_device_fp2 import *     # noqa: F401,F403
from test_device_fp2 import EDGES, _pack, _rand_pairs, _val


@pytest.fixture(autouse=True)
def _fp2_impl():
    with fp2.impl(fp2.IMPL_FUSED_PALLAS):
        yield


def test_fused_matches_composed_including_relaxed(rng):
    """Byte-level agreement between the two tower engines on the same
    inputs, including the worst-case relaxed operand (every limb at
    LIMB_MAX, legal input to mul by the reduced-before-split contract)
    and a non-tile-multiple batch size (padding path)."""
    xs = _rand_pairs(rng, 5) + EDGES
    ys = EDGES + _rand_pairs(rng, 5)
    X, Y = _pack(xs), _pack(ys)
    with fp2.impl(fp2.IMPL_COMPOSED):
        ref_mul = np.asarray(fp2.mul(X, Y))
        ref_sq = np.asarray(fp2.sq(X))
    with fp2.impl(fp2.IMPL_FUSED_PALLAS):
        got_mul = np.asarray(fp2.mul(X, Y))
        got_sq = np.asarray(fp2.sq(X))
    assert _val(got_mul) == _val(ref_mul)
    assert _val(got_sq) == _val(ref_sq)
    # relaxed limbs: both engines must reduce before the int8 split
    relaxed = np.full((1, 2, fp.NL), fp.LIMB_MAX, np.int32)
    with fp2.impl(fp2.IMPL_FUSED_PALLAS):
        out = np.asarray(fp2.mul(relaxed, relaxed))
    assert out.min() >= 0 and out.max() <= fp.LIMB_MAX
    # (a + a*u)^2 = 2*a^2*u since u^2 = -1
    a = fp.limbs_to_int(relaxed[0, 0])
    assert _val(out)[0] == (0, (2 * a * a) % P)


def test_fused_line_eval_differential(rng):
    """The fused Miller-loop doubling/addition line steps agree with the
    composed spelling VALUE-FOR-VALUE on random lanes plus the infinity
    lane (which must yield one under either engine)."""
    import jax.numpy as jnp

    from lighthouse_tpu.crypto.cpu.curve import (
        G1Point, G2Point, g1_generator, g2_generator,
    )
    from lighthouse_tpu.crypto.device import curve, tower

    g1s = [g1_generator().mul(rng.randrange(2, 1 << 48)) for _ in range(2)]
    g2s = [g2_generator().mul(rng.randrange(2, 1 << 48)) for _ in range(2)]
    g1s.append(G1Point.infinity())
    g2s.append(G2Point.infinity())
    pxy, pinf = curve.pack_g1(g1s)
    qxy, qinf = curve.pack_g2(g2s)
    g1_aff = (jnp.asarray(pxy[:, 0]), jnp.asarray(pxy[:, 1]), jnp.asarray(pinf))
    g2_aff = (jnp.asarray(qxy[:, 0]), jnp.asarray(qxy[:, 1]), jnp.asarray(qinf))

    outs = {}
    for name in (pairing.IMPL_LINE_COMPOSED, pairing.IMPL_LINE_FUSED):
        with pairing.line_impl(name):
            outs[name] = tower.unpack_f12(pairing.miller_loop(g1_aff, g2_aff))
    assert outs[pairing.IMPL_LINE_COMPOSED] == outs[pairing.IMPL_LINE_FUSED]
    from lighthouse_tpu.crypto.cpu.fields import Fq12

    assert outs[pairing.IMPL_LINE_FUSED][2] == Fq12.one()
