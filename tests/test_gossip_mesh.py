"""Gossipsub-style mesh control (reference gossipsub behaviour +
``gossipsub_scoring_parameters.rs`` degree params): mesh formation on
real topics, GRAFT refusal for unknown topics, PRUNE + backoff,
relay-through-mesh delivery, and flood fallback below D_low."""

import time

import pytest

from lighthouse_tpu.crypto import backend
from lighthouse_tpu.network.mesh import GRAFT, MeshRouter, PRUNE
from lighthouse_tpu.testing.simulator import LocalNetwork


@pytest.fixture(autouse=True)
def fake_backend():
    backend.set_backend("fake")
    yield
    backend.set_backend("cpu")


def _settle_mesh(net, topic, timeout=6.0):
    """Heartbeat all nodes until every mesh for ``topic`` is >= D_LOW
    (bidirectional grafting needs a couple of rounds)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for n in net.nodes:
            n.net.mesh_router.track(topic)
            n.net.mesh_router.heartbeat()
        time.sleep(0.1)  # control frames propagate via reader threads
        if all(
            len([p for p in n.net.mesh_router.mesh.get(topic, ()) if not p.closed])
            >= MeshRouter.D_LOW
            for n in net.nodes
        ):
            return
    raise AssertionError("meshes failed to fill")


def test_mesh_forms_bidirectionally_on_block_topic():
    net = LocalNetwork(4, validator_count=8)
    for _ in range(2):
        net.tick_slot(attest=True)
    topic = net.nodes[0].net.topics.block()
    _settle_mesh(net, topic)
    # every node ended with a non-trivial mesh — reciprocity happened
    for n in net.nodes:
        assert len(n.net.mesh_router.mesh[topic]) >= MeshRouter.D_LOW


def test_graft_for_unknown_topic_is_refused():
    net = LocalNetwork(2, validator_count=8)
    b = net.nodes[1].net
    peer_at_b = b.transport.peers[0]
    b.mesh_router.on_control(peer_at_b, GRAFT + b"/junk/topic")
    # no mesh state may be created by a remote control frame
    assert "/junk/topic" not in b.mesh_router.mesh


def test_prune_removes_member_and_backs_off():
    net = LocalNetwork(2, validator_count=8)
    a = net.nodes[0].net
    topic = "/test/topic2"
    a.mesh_router.track(topic)
    peer = a.transport.peers[0]
    a.mesh_router.on_control(peer, GRAFT + topic.encode())
    assert peer in a.mesh_router.mesh[topic]
    a.mesh_router.on_control(peer, PRUNE + topic.encode())
    assert peer not in a.mesh_router.mesh[topic]
    # backoff: the next heartbeat must NOT re-graft the pruning peer
    a.mesh_router.heartbeat()
    assert peer not in a.mesh_router.mesh[topic], "prune backoff ignored"


def test_relay_through_mesh_reaches_everyone():
    """With filled meshes, a block published by one node reaches every
    node (relay goes mesh-only once >= D_LOW members past the sender)."""
    net = LocalNetwork(4, validator_count=8)
    for _ in range(2):
        net.tick_slot(attest=True)
    topic = net.nodes[0].net.topics.block()
    _settle_mesh(net, topic)
    net.tick_slot(attest=True)  # flood at origin + mesh relay
    net.check_all_heads_equal()


def test_ihave_iwant_repairs_missed_gossip():
    """A peer outside every mesh (e.g. all its GRAFTs refused) must still
    obtain relayed messages via the heartbeat IHAVE digest + IWANT pull
    (advisor r4: relay-only delivery starves non-mesh peers)."""
    net = LocalNetwork(2, validator_count=8)
    a, b = net.nodes[0].net, net.nodes[1].net
    topic = "/test/repair"
    payload = b"\x01" * 40
    mid = a._msg_id(topic, payload)

    # a relayed/cached message that B never received
    a.mesh_router.track(topic)
    a.mesh_router.remember(topic, mid, payload)
    assert not b.has_seen(mid)

    # A's heartbeat advertises to non-mesh peers; B pulls via IWANT and
    # receives the full frame, marking it seen
    deadline = time.time() + 5.0
    while time.time() < deadline and not b.has_seen(mid):
        a.mesh_router.heartbeat()
        time.sleep(0.1)
    assert b.has_seen(mid), "IHAVE/IWANT pull failed to deliver"


def test_iwant_serves_only_cached_ids():
    net = LocalNetwork(2, validator_count=8)
    a = net.nodes[0].net

    class RecordingPeer:
        def __init__(self):
            self.sent = []
            self.closed = False

        def send(self, kind, name, payload, req_id=0):
            self.sent.append((kind, name, payload))
            return True

    peer = RecordingPeer()
    from lighthouse_tpu.network.mesh import IWANT

    # unknown ids must produce NO frames; a cached id exactly one
    a.mesh_router.on_control(peer, IWANT + b"\x00" * 20)
    assert peer.sent == []
    mid = a._msg_id("/t/x", b"payload")
    a.mesh_router.remember("/t/x", mid, b"payload")
    a.mesh_router.on_control(peer, IWANT + b"\x00" * 20 + mid)
    assert [(n, p) for _, n, p in peer.sent] == [(b"/t/x", b"payload")]


def test_remember_refuses_oversized_topics_and_bounds_bytes():
    """A >255-byte topic must not poison heartbeat digests (1-byte topic
    length on the wire), and the mcache byte budget must hold."""
    net = LocalNetwork(2, validator_count=8)
    r = net.nodes[0].net.mesh_router
    long_topic = "/t/" + "x" * 300
    r.remember(long_topic, b"\x01" * 20, b"p")
    assert long_topic not in r._recent
    r.heartbeat()  # must not raise

    big = b"\x00" * (1 << 20)
    for i in range(12):  # 12 MiB > MCACHE_MAX_BYTES (8 MiB)
        r.remember("/t/big", bytes([i]) * 20, big)
    assert r._mcache_bytes <= r.MCACHE_MAX_BYTES
    assert len(r._mcache) <= 8


def test_flood_fallback_below_dlow():
    net = LocalNetwork(2, validator_count=8)
    r = net.nodes[0].net.mesh_router
    assert r.relay_peers("/never/seen") is None  # empty mesh -> flood
    # sender does not count toward the threshold
    r.track("/t")
    peer = net.nodes[0].net.transport.peers[0]
    r.mesh["/t"].add(peer)
    assert r.relay_peers("/t", exclude=peer) is None
