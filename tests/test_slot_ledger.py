"""Chain-time observability (ISSUE 17): slot-clock epoch math and the
process-global clock seam, slot-ledger exactness (per-slot sums
reconcile with lifetime counters — conservation pinned), first + hits
== committee sightings (the honest denominator behind
``key_table_first_sighting_hit_ratio``), 8-thread writer conservation
under a hammering reader, the bounded-memory retention pin, the
disabled-path <1µs pin, the ``/lighthouse/slots`` endpoint round-trip
(no ``cryptography`` on the path), flood stable-committee determinism,
the ``op_pool_device_agg`` journal kind, and the jax-free subprocess
pin for the ledger + ``tools/slot_report.py``."""

import os
import subprocess
import sys
import threading
import time

import pytest

from lighthouse_tpu.utils import flight_recorder as fr
from lighthouse_tpu.utils import metrics, slot_clock, slot_ledger
from lighthouse_tpu.verification_service import traffic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def ledger():
    """Enabled ledger with a deterministic manual clock installed on
    the global seam; everything restored afterwards."""
    prev = slot_ledger.configure(enabled=True, max_slots=64, max_epochs=64)
    slot_ledger.reset()
    prev_clock = slot_clock.set_clock(
        slot_clock.ManualSlotClock(
            genesis_time=0, seconds_per_slot=12, slots_per_epoch=32
        )
    )
    try:
        yield
    finally:
        slot_clock.set_clock(prev_clock)
        slot_ledger.configure(**prev)
        slot_ledger.reset()


@pytest.fixture
def recorder(tmp_path):
    prev = fr.configure(
        capacity=4096, enabled=True, dump=False, dump_dir=str(tmp_path),
    )
    fr.clear()
    try:
        yield
    finally:
        fr.configure(**prev)
        fr.clear()


# ---------------------------------------------------------------------------
# Slot clock: epoch math + the global seam
# ---------------------------------------------------------------------------


def test_slot_clock_epoch_math_and_global_seam():
    """Genesis-anchored slot/epoch resolution, the manual test clock,
    and the settable process-global clock with restore discipline."""
    c = slot_clock.SlotClock(
        genesis_time=100.0, seconds_per_slot=12, slots_per_epoch=32
    )
    assert c.slot_at(99.0) == 0  # pre-genesis clamps to 0
    assert c.slot_at(100.0) == 0
    assert c.slot_at(111.999) == 0
    assert c.slot_at(112.0) == 1
    assert c.epoch_of(31) == 0 and c.epoch_of(32) == 1
    assert c.first_slot_of_epoch(3) == 96
    assert c.start_of(2) == pytest.approx(124.0)
    # fractional seconds-per-slot (the replay's scaled clock)
    f = slot_clock.SlotClock(genesis_time=0.0, seconds_per_slot=0.5)
    assert f.slot_at(1.74) == 3

    m = slot_clock.ManualSlotClock(
        genesis_time=100.0, seconds_per_slot=12, slots_per_epoch=32
    )
    m.set_slot(65)
    assert m.now() == 65 and m.current_epoch() == 2
    assert m.seconds_into_slot() == pytest.approx(0.0)
    m.advance_seconds(13.0)
    assert m.now() == 66
    assert m.seconds_into_slot() == pytest.approx(1.0)
    assert m.duration_to_next_slot() == pytest.approx(11.0)
    m.advance_slots(2)
    assert m.now() == 68

    prev = slot_clock.set_clock(m)
    try:
        assert slot_clock.get_clock() is m
    finally:
        restored = slot_clock.set_clock(prev)
        assert restored is m
    assert slot_clock.get_clock() is not m


# ---------------------------------------------------------------------------
# Producer exactness + lifetime conservation
# ---------------------------------------------------------------------------


def test_producer_exactness_and_lifetime_conservation(ledger):
    """Every note_* family lands on exactly the right card with exactly
    the right arithmetic, and sum(retained cards) + evicted == lifetime
    for every conserved counter."""
    note = slot_ledger.note_resolution
    note("aggregate", "fused", 8, 0.010, slot=10)
    note("aggregate", "fused", 4, 0.050, missed=True, slot=10)
    note("unaggregated", "bypass", 1, 0.002, slot=10)
    note("aggregate", "shed", 2, 0.030, slot=11)
    slot_ledger.note_rejection("block_rejected", slot=10)
    slot_ledger.note_rejection("block_rejected", slot=10)
    slot_ledger.note_rejection("sync_rejected", slot=11)
    slot_ledger.note_h2d_bytes(1000, slot=10)
    slot_ledger.note_h2d_bytes(24, slot=11)
    slot_ledger.note_bubble(0.5, slot=10)
    slot_ledger.note_headroom(0.7, slot=10)
    slot_ledger.note_headroom(0.3, slot=10)
    slot_ledger.note_headroom(0.9, slot=11)
    slot_ledger.note_fresh_compile(stage="msm", slot=11)
    slot_ledger.note_bulk(admitted_sets=5, parked_sets=3, slot=10)
    for _ in range(2):
        slot_ledger.note_committee_sighting("first", slot=10)
    for _ in range(3):
        slot_ledger.note_committee_sighting("hit", slot=10)
    slot_ledger.note_committee_sighting("first", slot=320)  # epoch 10
    # no explicit slot -> the global clock resolves it
    clock = slot_clock.get_clock()
    clock.set_slot(7)
    note("sync_message", "fused", 6, 0.004)

    cards = {c["slot"]: c for c in slot_ledger.slot_cards()}
    assert sorted(cards) == [7, 10, 11, 320]
    c10 = cards[10]
    assert c10["epoch"] == 0
    assert c10["sets"] == 13 and c10["verdicts"] == 3 and c10["misses"] == 1
    assert c10["kinds"]["aggregate"] == {
        "sets": 12, "verdicts": 2, "misses": 1
    }
    assert c10["kinds"]["unaggregated"]["sets"] == 1
    assert c10["p50_ms"] == pytest.approx(10.0)
    assert c10["p99_ms"] == pytest.approx(50.0)
    assert c10["lat_samples"] == 3 and c10["lat_sampled"] == 3
    assert c10["rejected"] == {"block_rejected": 2}
    assert c10["rejections"] == 2
    assert c10["h2d_bytes"] == 1000
    assert c10["bubble_s"] == pytest.approx(0.5)
    assert c10["headroom_min"] == pytest.approx(0.3)  # slot MIN, not mean
    assert c10["headroom_samples"] == 2
    assert c10["bulk_admitted_sets"] == 5 and c10["bulk_parked_sets"] == 3
    assert c10["sightings_first"] == 2 and c10["sightings_hit"] == 3
    c11 = cards[11]
    assert c11["sets"] == 2 and c11["fresh_compiles"] == 1
    assert c11["rejected"] == {"sync_rejected": 1}
    assert cards[7]["sets"] == 6  # clock-resolved attribution
    assert cards[320]["epoch"] == 10

    # conservation: retained + evicted == lifetime, nothing evicted yet
    lifetime = slot_ledger.lifetime_totals()
    evicted = slot_ledger.evicted_totals()
    for key in lifetime:
        retained = sum(c[key] for c in cards.values())
        assert retained + evicted[key] == pytest.approx(lifetime[key]), key
        assert evicted[key] == 0
    assert lifetime["sets"] == 21 and lifetime["verdicts"] == 5
    assert lifetime["sightings_first"] == 3
    assert lifetime["sightings_hit"] == 3

    # epoch rollup: honest denominator, first + hits == sightings
    epochs = {e["epoch"]: e for e in slot_ledger.epoch_cards()}
    assert epochs[0]["first_sightings"] == 2 and epochs[0]["hits"] == 3
    assert epochs[0]["sightings"] == 5
    assert epochs[0]["hit_ratio"] == pytest.approx(0.6)
    assert epochs[10] == {
        "epoch": 10, "first_sightings": 1, "hits": 0, "sightings": 1,
        "hit_ratio": 0.0,
    }
    ratio = metrics.gauge_vec(
        "key_table_first_sighting_hit_ratio", labelnames=("epoch",)
    )
    assert ratio.with_labels("0").value == pytest.approx(0.6)

    summary = slot_ledger.summary()
    assert summary["enabled"] is True
    assert summary["slots_retained"] == 4 and summary["cards_evicted"] == 0
    assert summary["lifetime"] == lifetime
    assert summary["latest_epoch"]["epoch"] == 10


def test_committee_sighting_model_conservation(ledger):
    """The jax-free mirror of the key table's admission policy: with
    ``min_repeats=2``, sightings 1-2 of a tuple are firsts (miss, then
    miss+insert), 3+ are collapsed hits — and first + hits == sightings
    both in the model and in the ledger it feeds."""
    model = slot_ledger.CommitteeSightingModel(min_repeats=2)
    outcomes = [model.observe((1, 2, 3), slot=4) for _ in range(5)]
    assert outcomes == ["first", "first", "hit", "hit", "hit"]
    assert model.first == 2 and model.hits == 3
    assert model.first + model.hits == 5
    assert model.hit_ratio() == pytest.approx(0.6)
    # a different tuple starts its own admission course
    assert model.observe((7, 8), slot=4) == "first"
    # min_repeats=1: second consult already collapses
    eager = slot_ledger.CommitteeSightingModel(min_repeats=1)
    assert eager.observe((9, 10), slot=4) == "first"
    assert eager.observe((9, 10), slot=4) == "hit"

    lifetime = slot_ledger.lifetime_totals()
    assert lifetime["sightings_first"] == 4 and lifetime["sightings_hit"] == 4
    (card,) = slot_ledger.slot_cards()
    assert card["sightings_first"] + card["sightings_hit"] == 8

    with pytest.raises(ValueError):
        slot_ledger.note_committee_sighting("maybe")


# ---------------------------------------------------------------------------
# Threads, retention, disabled cost
# ---------------------------------------------------------------------------


def test_conservation_under_writer_threads(ledger):
    """8 writer threads, one reader hammering every view: every event
    lands exactly once (lifetime == writes), cards stay internally
    consistent mid-flight, conservation holds after the join."""
    THREADS, N, SLOTS = 8, 500, 32
    torn = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            for card in slot_ledger.slot_cards():
                # each resolution carries exactly 3 sets; a torn card
                # would break the invariant
                if card["sets"] != 3 * card["verdicts"]:
                    torn.append((card["slot"], card["sets"],
                                 card["verdicts"]))
            slot_ledger.summary()
            slot_ledger.epoch_cards()

    def writer(i):
        for j in range(N):
            slot_ledger.note_resolution(
                f"kind{i}", "fused", 3, 0.001 * (j % 7), slot=j % SLOTS
            )
            slot_ledger.note_h2d_bytes(10, slot=j % SLOTS)

    rd = threading.Thread(target=reader, daemon=True)
    rd.start()
    ws = [threading.Thread(target=writer, args=(i,)) for i in range(THREADS)]
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    stop.set()
    rd.join(timeout=5)
    assert not torn, torn[:3]

    lifetime = slot_ledger.lifetime_totals()
    assert lifetime["verdicts"] == THREADS * N
    assert lifetime["sets"] == THREADS * N * 3
    assert lifetime["h2d_bytes"] == THREADS * N * 10
    cards = slot_ledger.slot_cards()
    assert len(cards) == SLOTS  # within max_slots: nothing evicted
    evicted = slot_ledger.evicted_totals()
    for key in ("sets", "verdicts", "h2d_bytes"):
        assert sum(c[key] for c in cards) + evicted[key] == lifetime[key]


def test_retention_eviction_keeps_conservation(ledger):
    """The bounded-memory pin: retention evicts oldest-first down to
    ``max_slots``, evicted cards fold into eviction totals so lifetime
    conservation survives, and the eviction counter ticks."""
    evicted0 = metrics.get("slot_ledger_evicted_total").value
    slot_ledger.configure(max_slots=8)
    for s in range(40):
        slot_ledger.note_resolution("aggregate", "fused", 2, 0.001, slot=s)
    cards = slot_ledger.slot_cards()
    assert len(cards) == 8
    assert [c["slot"] for c in cards] == list(range(32, 40))  # newest kept
    lifetime = slot_ledger.lifetime_totals()
    evicted = slot_ledger.evicted_totals()
    assert lifetime["sets"] == 80 and lifetime["verdicts"] == 40
    assert evicted["sets"] == 64 and evicted["verdicts"] == 32
    for key in lifetime:
        assert sum(c[key] for c in cards) + evicted[key] == pytest.approx(
            lifetime[key]
        ), key
    assert metrics.get("slot_ledger_evicted_total").value == evicted0 + 32
    assert metrics.get("slot_ledger_slots").value == 8
    summary = slot_ledger.summary()
    assert summary["slots_retained"] == 8 and summary["cards_evicted"] == 32

    # shrinking applies retention immediately, conservation intact
    slot_ledger.configure(max_slots=3)
    cards = slot_ledger.slot_cards()
    assert [c["slot"] for c in cards] == [37, 38, 39]
    evicted = slot_ledger.evicted_totals()
    assert sum(c["sets"] for c in cards) + evicted["sets"] == 80

    # last=N keeps the newest N; last=0 is empty, not an error
    assert [c["slot"] for c in slot_ledger.slot_cards(last=1)] == [39]
    assert slot_ledger.slot_cards(last=0) == []

    # epoch rows have their own bound
    slot_ledger.configure(max_epochs=2)
    for e in range(5):
        slot_ledger.note_committee_sighting("first", slot=e * 32)
    rows = slot_ledger.epoch_cards()
    assert len(rows) == 2
    assert [r["epoch"] for r in rows] == [3, 4]


def test_disabled_note_costs_under_one_microsecond():
    """The ISSUE 17 pin: with the ledger disabled, a note_* is one
    global check — cheap enough to leave in every producer, always."""
    prev = slot_ledger.configure(enabled=False)
    try:
        n = 20_000
        note = slot_ledger.note_h2d_bytes
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                note(1)
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 1e-6, (
            f"disabled note_h2d_bytes costs {best * 1e9:.0f} ns — too "
            f"expensive for an always-on attribution seam"
        )
    finally:
        slot_ledger.configure(**prev)


# ---------------------------------------------------------------------------
# Journal rejections feed the ledger
# ---------------------------------------------------------------------------


def test_rejected_journal_kinds_land_on_the_slot_card(ledger, recorder):
    """Every ``*_rejected`` flight-recorder event is chain-time
    attributed — the journal hook is the single rejection funnel."""
    slot_clock.get_clock().set_slot(5)
    fr.record("block_rejected", reason="zgate_bad_signature")
    fr.record("attestation_rejected", reason="zgate_unknown_head")
    fr.record("slo_burn", window="fast")  # non-rejection: not attributed
    (card,) = slot_ledger.slot_cards()
    assert card["slot"] == 5
    assert card["rejected"] == {
        "attestation_rejected": 1, "block_rejected": 1
    }
    assert slot_ledger.lifetime_totals()["rejections"] == 2


# ---------------------------------------------------------------------------
# /lighthouse/slots endpoint (no `cryptography` on the path)
# ---------------------------------------------------------------------------


def test_slots_endpoint_round_trip_and_health_chain_time(ledger):
    """/lighthouse/slots round-trips both views with the documented
    grammar (400 on bad view/last), and /lighthouse/health carries the
    chain_time block — no ``cryptography`` dependency anywhere."""
    import copy
    import json as _json
    import urllib.error
    import urllib.request

    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.http_api import BeaconApiServer
    from lighthouse_tpu.state_transition import store_replayer
    from lighthouse_tpu.store import HotColdDB, MemoryStore
    from lighthouse_tpu.testing.harness import StateHarness
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.preset import MINIMAL
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    slot_ledger.note_resolution("aggregate", "fused", 8, 0.010, slot=3)
    slot_ledger.note_resolution(
        "aggregate", "fused", 4, 0.060, missed=True, slot=4
    )
    slot_ledger.note_committee_sighting("first", slot=3)
    slot_ledger.note_committee_sighting("hit", slot=4)

    h = StateHarness(
        MINIMAL, minimal_spec(), validator_count=8, fork_name="phase0",
        fake_sign=True,
    )
    genesis = copy.deepcopy(h.state)
    db = HotColdDB(
        MemoryStore(), h.t, h.spec, store_replayer(h.preset, h.spec)
    )
    clock = ManualSlotClock(genesis.genesis_time, h.spec.seconds_per_slot)
    chain = BeaconChain(h.preset, h.spec, h.t, db, genesis, slot_clock=clock)
    server = BeaconApiServer(chain, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(
            base + "/lighthouse/slots", timeout=5
        ) as r:
            doc = _json.load(r)["data"]
        assert doc["schema"] == slot_ledger.SCHEMA
        assert doc["view"] == "slots"
        assert [row["slot"] for row in doc["rows"]] == [3, 4]
        assert doc["rows"][0]["sets"] == 8
        assert doc["rows"][1]["misses"] == 1
        assert doc["lifetime"]["sets"] == 12
        assert doc["chain_time"]["enabled"] is True
        # conservation is checkable straight off the wire
        retained = sum(row["sets"] for row in doc["rows"])
        assert retained + doc["evicted"]["sets"] == doc["lifetime"]["sets"]

        with urllib.request.urlopen(
            base + "/lighthouse/slots?view=epochs", timeout=5
        ) as r:
            doc = _json.load(r)["data"]
        assert doc["view"] == "epochs"
        (row,) = doc["rows"]
        assert row["first_sightings"] + row["hits"] == row["sightings"] == 2
        assert row["hit_ratio"] == pytest.approx(0.5)

        with urllib.request.urlopen(
            base + "/lighthouse/slots?last=1", timeout=5
        ) as r:
            doc = _json.load(r)["data"]
        assert [row["slot"] for row in doc["rows"]] == [4]

        for bad in ("view=minutes", "last=abc", "last=-1"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    base + "/lighthouse/slots?" + bad, timeout=5
                )
            assert ei.value.code == 400, bad

        with urllib.request.urlopen(
            base + "/lighthouse/health", timeout=5
        ) as r:
            health = _json.load(r)["data"]
        ct = health["chain_time"]
        assert ct["enabled"] is True
        assert ct["lifetime"]["sets"] == 12
        assert ct["latest_epoch"]["first_sightings"] == 1
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Flood realism: stable committees, deterministically
# ---------------------------------------------------------------------------


def test_flood_stable_committees_deterministic():
    """The epoch's committee shuffle is FIXED: flood aggregates draw
    their ``validators`` tuple from the same ``n_committees`` disjoint
    tuples on every run of a seed — the recurrence the aggregate-cache
    collapse keys on."""
    kw = dict(duration_s=12.0, seed=9, committee=8, n_committees=16)
    evs1 = traffic.epoch_boundary_flood(**kw)
    evs2 = traffic.epoch_boundary_flood(**kw)
    assert evs1 == evs2  # full-trace determinism, validators included
    expected = {
        tuple(range(c * 8, (c + 1) * 8)) for c in range(16)
    }
    seen = [tuple(e["validators"]) for e in evs1 if "validators" in e]
    assert seen, "flood trace carries no committee identities"
    assert set(seen) <= expected
    assert len(set(seen)) > 1  # more than one committee recurs
    # recurrence is the point: strictly fewer tuples than sightings
    assert len(set(seen)) < len(seen)
    for i, ev in enumerate(evs1):
        traffic._validate_event(ev, i + 2)


def test_lockstep_flood_slots_visible_and_sighting_conservation():
    """The acceptance shape, jax-free: on an epoch_boundary_flood
    lockstep replay the flood slots are individually visible (demand
    > 2x the median slot) and first + hits == sightings."""
    evs = traffic.epoch_boundary_flood(duration_s=12.0, seed=7)
    doc = traffic.lockstep_replay(evs, slot_s=2.0, slots_per_epoch=32)
    rows = doc["slots"]
    assert rows and doc["chain_time"]["n_slots"] == len(rows)
    per_slot = sorted(r["sets"] for r in rows)
    median = per_slot[len(per_slot) // 2]
    flood = [r for r in rows if r["sets"] > 2 * median]
    assert flood, "flood slots not visible above the quiet median"
    ct = doc["chain_time"]
    assert ct["first_sightings"] + ct["sighting_hits"] == (
        ct["committee_sightings"]
    )
    assert ct["committee_sightings"] > 0
    assert ct["first_sighting_hit_ratio"] == pytest.approx(
        ct["sighting_hits"] / ct["committee_sightings"], abs=1e-4
    )
    # per-slot rollup reconciles with the chain_time totals
    assert sum(r["sightings_first"] for r in rows) == ct["first_sightings"]
    assert sum(r["sightings_hit"] for r in rows) == ct["sighting_hits"]


# ---------------------------------------------------------------------------
# op_pool_device_agg journal (ISSUE 16 surface wired in ISSUE 17)
# ---------------------------------------------------------------------------


def test_device_agg_journals_ok_and_fallback(recorder, monkeypatch):
    """Every device G2-sum merge journals an ``op_pool_device_agg``
    event — outcome, batch size, pad rung, wall time, and the error on
    the fallback path."""
    from lighthouse_tpu.compile_service.service import MSM_RUNGS
    from lighthouse_tpu.crypto.device import bls as dbls
    from lighthouse_tpu.operation_pool import DeviceAggregator

    assert "op_pool_device_agg" in fr.EVENT_KINDS

    class _FakeSig:
        def point_or_infinity(self):
            return object()

    class _FakeInfinity:
        def is_infinity(self):
            return True

    agg = DeviceAggregator(min_batch=2)
    pad = min(r for r in sorted(MSM_RUNGS) if r >= 3)

    monkeypatch.setattr(
        dbls, "device_sum_g2", lambda pts, pad_n=None: _FakeInfinity()
    )
    out = agg.aggregate([_FakeSig() for _ in range(3)])
    assert out is not None
    (ev,) = fr.events(kinds=["op_pool_device_agg"])
    assert ev["fields"]["outcome"] == "ok"
    assert ev["fields"]["n_points"] == 3
    assert ev["fields"]["pad_n"] == pad
    assert ev["fields"]["wall_s"] >= 0

    def boom(pts, pad_n=None):
        raise RuntimeError("zgate device down")

    monkeypatch.setattr(dbls, "device_sum_g2", boom)
    assert agg.aggregate([_FakeSig() for _ in range(3)]) is None
    evs = fr.events(kinds=["op_pool_device_agg"])
    assert len(evs) == 2
    assert evs[-1]["fields"]["outcome"] == "fallback"
    assert "zgate device down" in evs[-1]["fields"]["error"]


# ---------------------------------------------------------------------------
# jax-freedom, subprocess-pinned
# ---------------------------------------------------------------------------


def test_slot_ledger_and_slot_report_jax_free_subprocess():
    """The hard repo rule: utils/slot_ledger.py, utils/slot_clock.py
    and tools/slot_report.py import and run (ledger round-trip, sighting
    model, lockstep scoreboard) without pulling jax."""
    code = (
        "import sys\n"
        "from lighthouse_tpu.utils import slot_clock, slot_ledger\n"
        "slot_ledger.configure(enabled=True)\n"
        "slot_ledger.reset()\n"
        "slot_clock.set_clock(slot_clock.ManualSlotClock(0, 2.0))\n"
        "slot_ledger.note_resolution('aggregate', 'fused', 4, 0.01, slot=3)\n"
        "m = slot_ledger.CommitteeSightingModel()\n"
        "outcomes = [m.observe((1, 2, 3), slot=3) for _ in range(5)]\n"
        "assert m.first + m.hits == 5\n"
        "cards = slot_ledger.slot_cards()\n"
        "assert cards and cards[0]['sets'] == 4\n"
        "assert slot_ledger.summary()['lifetime']['sets'] == 4\n"
        "import tools.slot_report as sr\n"
        "rep = {'schema': sr.REPORT_SCHEMA, **sr.normalize(\n"
        "    {'view': 'slots', 'rows': cards,\n"
        "     'chain_time': slot_ledger.summary()})}\n"
        "assert sr.render(rep)\n"
        "from lighthouse_tpu.verification_service import traffic\n"
        "evs = traffic.epoch_boundary_flood(duration_s=6.0, seed=1)\n"
        "doc = traffic.lockstep_replay(evs, slot_s=2.0)\n"
        "rep2 = sr.normalize(doc)\n"
        "assert rep2['source'] == 'lockstep' and rep2['slots']\n"
        "for e in rep2['epochs']:\n"
        "    assert e['first_sightings'] + e['hits'] == e['sightings']\n"
        "assert 'jax' not in sys.modules, 'slot ledger must stay jax-free'\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
