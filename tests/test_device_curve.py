"""Device Jacobian curve ops vs the pure-Python affine oracle."""

import numpy as np
import pytest

# Each test compiles a multi-second XLA program; gated like the pairing
# suites so the default run stays under the 5-minute budget.
pytestmark = pytest.mark.slow

from lighthouse_tpu.crypto.cpu.curve import (
    G1Point,
    G2Point,
    g1_generator,
    g2_generator,
)
from lighthouse_tpu.crypto.device import curve, fp, fp2


@pytest.fixture(
    autouse=True,
    params=[fp.IMPL_TOEPLITZ_INT32, fp.IMPL_MATMUL_INT8],
)
def _fp_impl(request):
    """Curve-level differential coverage for both fp.mul engines."""
    with fp.impl(request.param):
        yield request.param


def _g1_points(rng, n):
    g = g1_generator()
    return [g.mul(rng.randrange(1, 1 << 64)) for _ in range(n)]


def _g2_points(rng, n):
    g = g2_generator()
    return [g.mul(rng.randrange(1, 1 << 64)) for _ in range(n)]


def _dev_g1(points):
    xy, inf = curve.pack_g1(points)
    return curve.from_affine(fp, xy[:, 0], xy[:, 1], inf)


def _dev_g2(points):
    xy, inf = curve.pack_g2(points)
    return curve.from_affine(fp2, xy[:, 0], xy[:, 1], inf)


def _host_g1(pt):
    x, y, inf = curve.to_affine(fp, pt)
    return curve.unpack_g1(np.stack([np.asarray(x), np.asarray(y)], 1), inf)


def _host_g2(pt):
    x, y, inf = curve.to_affine(fp2, pt)
    return curve.unpack_g2(np.stack([np.asarray(x), np.asarray(y)], 1), inf)


@pytest.mark.parametrize("group", ["g1", "g2"])
def test_dbl_add_roundtrip(rng, group):
    if group == "g1":
        pts = _g1_points(rng, 3) + [G1Point.infinity()]
        F, dev, host = fp, _dev_g1, _host_g1
    else:
        pts = _g2_points(rng, 3) + [G2Point.infinity()]
        F, dev, host = fp2, _dev_g2, _host_g2
    P = dev(pts)
    assert host(curve.dbl(F, P)) == [p.double() for p in pts]
    # pairwise add against a rotation (includes x + inf)
    rot = pts[1:] + pts[:1]
    Q = dev(rot)
    assert host(curve.add(F, P, Q)) == [a + b for a, b in zip(pts, rot)]


@pytest.mark.parametrize("group", ["g1", "g2"])
def test_add_edge_cases(rng, group):
    """P+P (doubling lane), P + (-P) (infinity lane), inf + inf."""
    if group == "g1":
        p = _g1_points(rng, 1)[0]
        F, dev, host, inf = fp, _dev_g1, _host_g1, G1Point.infinity()
    else:
        p = _g2_points(rng, 1)[0]
        F, dev, host, inf = fp2, _dev_g2, _host_g2, G2Point.infinity()
    lhs = dev([p, p, inf, p])
    rhs = dev([p, -p, inf, inf])
    got = host(curve.add(F, lhs, rhs))
    assert got == [p.double(), inf, inf, p]


@pytest.mark.parametrize("group", ["g1", "g2"])
def test_scalar_mul_bits(rng, group):
    if group == "g1":
        pts = _g1_points(rng, 4)
        F, dev, host = fp, _dev_g1, _host_g1
    else:
        pts = _g2_points(rng, 4)
        F, dev, host = fp2, _dev_g2, _host_g2
    ks = [rng.randrange(0, 1 << 64) for _ in pts]
    bits = np.stack(
        [np.array([(k >> (63 - i)) & 1 for i in range(64)], np.int32) for k in ks]
    )
    got = host(curve.scalar_mul_bits(F, dev(pts), bits))
    assert got == [p.mul(k) for p, k in zip(pts, ks)]


def test_scalar_mul_const(rng):
    pts = _g1_points(rng, 3)
    k = rng.randrange(1 << 63, 1 << 64)
    got = _host_g1(curve.scalar_mul_const(fp, _dev_g1(pts), k))
    assert got == [p.mul(k) for p in pts]
    # k = 0 -> infinity
    got0 = _host_g1(curve.scalar_mul_const(fp, _dev_g1(pts), 0))
    assert all(p.is_infinity() for p in got0)


@pytest.mark.parametrize("group", ["g1", "g2"])
def test_sum_points(rng, group):
    if group == "g1":
        pts = _g1_points(rng, 5) + [G1Point.infinity()]
        # include a duplicate to force a doubling lane inside the tree
        pts.append(pts[0])
        F, dev, host, acc0 = fp, _dev_g1, _host_g1, G1Point.infinity()
    else:
        pts = _g2_points(rng, 5) + [G2Point.infinity()]
        pts.append(pts[0])
        F, dev, host, acc0 = fp2, _dev_g2, _host_g2, G2Point.infinity()
    s = curve.sum_points(F, dev(pts))
    expect = acc0
    for p in pts:
        expect = expect + p
    x, y, inf = curve.to_affine(F, s)
    unpack = curve.unpack_g1 if group == "g1" else curve.unpack_g2
    got = unpack(np.stack([np.asarray(x), np.asarray(y)])[None], np.asarray(inf)[None])
    assert got == [expect]


def test_eq_projective(rng):
    pts = _g1_points(rng, 2)
    P = _dev_g1(pts)
    # 2P computed two ways: dbl vs add(P, P) -> different Z, same point
    a = curve.dbl(fp, P)
    b = curve.add(fp, P, P)
    assert list(np.asarray(curve.eq(fp, a, b))) == [True, True]
    assert list(np.asarray(curve.eq(fp, a, P))) == [False, False]
