"""BeaconProcessor: coalescing, priorities, shedding, delayed requeue.

Reference analogue: ``network/src/beacon_processor/tests.rs`` (876 LoC)
— batch assembly and queue behaviour over a harness chain.
"""

import threading
import time

from lighthouse_tpu.beacon_processor import BeaconProcessor, Work, WorkKind


def _collect(results, lock):
    def cb(r):
        with lock:
            results.append(r)
    return cb


def test_batches_coalesce_under_load():
    """A busy pool accumulates attestations into one batched call."""
    seen_batches = []
    release = threading.Event()

    def att_handler(items):
        if not seen_batches:
            release.wait(timeout=5)  # first batch blocks the only worker
        seen_batches.append(len(items))
        return [None] * len(items)

    bp = BeaconProcessor(
        {WorkKind.GOSSIP_ATTESTATION: att_handler}, n_workers=1,
        batch_ceilings={WorkKind.GOSSIP_ATTESTATION: 64},
    )
    try:
        bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, 0))
        time.sleep(0.15)  # worker picks up item 0 and blocks
        for i in range(1, 101):
            assert bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, i))
        release.set()
        deadline = time.time() + 5
        while sum(seen_batches) < 101 and time.time() < deadline:
            time.sleep(0.01)
        assert sum(seen_batches) == 101
        # everything after the blocker coalesced into ceiling-bound batches
        assert max(seen_batches) > 1
        assert max(seen_batches) <= 64
    finally:
        bp.shutdown()


def test_priority_blocks_before_attestations():
    order = []
    release = threading.Event()
    lock = threading.Lock()

    def block_handler(item):
        with lock:
            order.append(("block", item))

    def att_handler(items):
        if not order:
            release.wait(timeout=5)
        with lock:
            order.extend(("att", i) for i in items)
        return [None] * len(items)

    bp = BeaconProcessor(
        {WorkKind.GOSSIP_BLOCK: block_handler, WorkKind.GOSSIP_ATTESTATION: att_handler},
        n_workers=1,
    )
    try:
        # jam the worker with an attestation, then queue atts + a block
        bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, "jam"))
        time.sleep(0.15)
        bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, "a1"))
        bp.submit(Work(WorkKind.GOSSIP_BLOCK, "b1"))
        release.set()
        deadline = time.time() + 5
        while len(order) < 3 and time.time() < deadline:
            time.sleep(0.01)
        # the block must be drained before the queued attestation
        kinds = [k for k, _ in order if _ != "jam"]
        assert kinds.index("block") < kinds.index("att")
    finally:
        bp.shutdown()


def test_full_queue_sheds():
    ev = threading.Event()

    def handler(items):
        ev.wait(timeout=5)
        return [None] * len(items)

    bp = BeaconProcessor(
        {WorkKind.GOSSIP_ATTESTATION: handler}, n_workers=1,
        queue_bounds={**{k: 4 for k in WorkKind}},
    )
    try:
        bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, "jam"))
        time.sleep(0.15)
        oks = [bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, i)) for i in range(8)]
        assert oks.count(True) == 4 and oks.count(False) == 4
        ev.set()
    finally:
        bp.shutdown()


def test_delayed_requeue():
    got = []
    lock = threading.Lock()

    bp = BeaconProcessor(
        {WorkKind.GOSSIP_BLOCK: lambda item: got.append(item)}, n_workers=1
    )
    try:
        bp.submit_later(Work(WorkKind.GOSSIP_BLOCK, "later"), delay_s=0.2)
        time.sleep(0.1)
        assert not got
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.02)
        assert got == ["later"]
    finally:
        bp.shutdown()


def test_results_delivered_and_latency_recorded():
    results = []
    lock = threading.Lock()
    bp = BeaconProcessor(
        {WorkKind.GOSSIP_ATTESTATION: lambda items: [i * 2 for i in items]},
        n_workers=2,
    )
    try:
        for i in range(10):
            bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, i, done=_collect(results, lock)))
        deadline = time.time() + 5
        while len(results) < 10 and time.time() < deadline:
            time.sleep(0.01)
        assert sorted(results) == [i * 2 for i in range(10)]
    finally:
        bp.shutdown()


def test_dual_latency_throughput_lanes():
    """SURVEY hard-part #4 (dual small/large batch lanes): an idle queue
    must hand a LONE attestation to the handler immediately (batch of 1 →
    small padded device shape → low latency), while a burst coalesces to
    the large ceiling (throughput lane). The lanes are emergent: greedy
    drain + _round_up shape bucketing in the backend."""
    import threading
    import time

    from lighthouse_tpu.beacon_processor import (
        BeaconProcessor, Work, WorkKind,
    )

    batches = []
    gate = threading.Event()

    def handler(items):
        batches.append(len(items))
        gate.set()
        return [None] * len(items)

    bp = BeaconProcessor(
        {WorkKind.GOSSIP_ATTESTATION: handler}, n_workers=1
    )
    try:
        # latency lane: one item, no waiting for fill
        t0 = time.monotonic()
        bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, "solo"))
        assert gate.wait(2.0)
        assert batches[0] == 1
        assert time.monotonic() - t0 < 1.0

        # throughput lane: a burst coalesces toward the 256 ceiling.
        # Stall the single worker with a sentinel so the burst queues up
        # behind it instead of racing the submission loop.
        gate.clear()
        release = threading.Event()
        stall = threading.Event()

        def slow_handler(items):
            if items == ["stall"]:
                stall.set()
                release.wait(5)
            batches.append(len(items))
            return [None] * len(items)

        bp.handlers[WorkKind.GOSSIP_ATTESTATION] = slow_handler
        bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, "stall"))
        assert stall.wait(2.0)
        for i in range(512):
            bp.submit(Work(WorkKind.GOSSIP_ATTESTATION, i))
        release.set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and sum(batches) < 1 + 1 + 512:
            time.sleep(0.02)
        assert max(batches) == 256, batches  # ceiling reached
    finally:
        bp.shutdown()
